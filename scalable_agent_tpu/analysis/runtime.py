"""Runtime half of the invariant analyzer (round 18): lock-order
detection and the `guarded_by` annotation the static pass reads.

The threaded control plane grown by PRs 6-17 (fleet, inference,
controller, slo, remote, ring_buffer, dynamic_batching) holds ~40
locks coordinated by comments ("Lock order where nested: _slot_lock ->
_arena_lock ..."). A silent lock-order inversion there is a
fleet-wide deadlock at Podracer scale (arXiv 2104.06272), not a unit
flake — and nothing verified those comments until this module.

Two pieces:

1. `guarded_by('<lock_attr>')` — a class-body annotation convention::

       class InferenceServer:
         _free: guarded_by('_slot_lock')

   declares that `self._free` may only be read or written while
   `self._slot_lock` is held. The declaration is an ordinary variable
   annotation (no attribute is created, no runtime cost beyond the
   `__annotations__` entry); `analysis/concurrency.py` is the AST
   pass that enforces it at lint time.

2. `OrderedLock` / `make_lock(name)` — a drop-in
   `threading.Lock`/`RLock` wrapper that records the process-wide
   lock acquisition-order graph per thread and reports a
   `lock_order_inversion` the moment any thread ATTEMPTS an
   acquisition that closes a cycle — the inversion is caught on the
   ordering violation itself, deterministically, without needing the
   actual interleaving that deadlocks. Edges are recorded BEFORE a
   blocking acquire parks, so even the half of an inversion that
   would have deadlocked still lands in the graph.

   `make_lock` is the adoption seam: unarmed (the production
   default) it returns a plain `threading.Lock`/`RLock` — zero
   overhead, byte-identical behavior; armed (tests and chaos storms:
   the LOCK_ORDER_CHECK env var, or `--lock_order_check` through
   `driver.train`) it returns an `OrderedLock` so every existing
   chaos storm doubles as a race hunt. Detections increment the
   `analysis/lock_cycles` registry counter and (when a sink is
   wired — driver.train wires its EventLog) emit a durable
   `lock_order_inversion` incident.

stdlib-only on the import path (telemetry is imported lazily at first
detection/arm): `scripts/lint.py` pulls `guarded_by` without jax.
"""

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

log = logging.getLogger('scalable_agent_tpu')


class GuardedBy:
  """Sentinel produced by `guarded_by` — carries the lock attribute
  names for anyone introspecting `__annotations__` at runtime; the
  static checker reads the annotation call itself."""

  __slots__ = ('locks',)

  def __init__(self, locks: Tuple[str, ...]):
    self.locks = locks

  def __repr__(self):
    return f'guarded_by({", ".join(map(repr, self.locks))})'


def guarded_by(*lock_attrs: str) -> GuardedBy:
  """Annotation for attributes that must only be touched under a lock.

  Usage (class body)::

      class Fleet:
        _slots_rehabilitated: guarded_by('_lock')

  Multiple lock names mean ANY of them protects the attribute (the
  Condition-sharing case where several conditions wrap one mutex is
  instead auto-detected by the checker via
  `self.cond = threading.Condition(self.lock)` aliasing).
  """
  if not lock_attrs or not all(
      isinstance(a, str) and a for a in lock_attrs):
    raise ValueError('guarded_by needs at least one lock attribute '
                     f'name, got {lock_attrs!r}')
  return GuardedBy(tuple(lock_attrs))


class LockOrderInversion(RuntimeError):
  """Raised (raise mode only) when an acquisition closes a cycle in
  the process-wide lock-order graph."""


class _LockGraph:
  """Process-wide acquired-before graph over lock NAMES.

  An edge a -> b means some thread held `a` while acquiring (or
  attempting to acquire) `b`. A cycle means two threads disagree
  about the order — the classic ABBA deadlock shape — whether or not
  the deadlocking interleaving ever happened.
  """

  def __init__(self):
    self._mutex = threading.Lock()
    self._edges: Dict[str, Set[str]] = {}
    self._cycles: List[dict] = []

  def _path(self, src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst over current edges (called with _mutex)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
      node, path = stack.pop()
      if node == dst:
        return path
      for nxt in self._edges.get(node, ()):
        if nxt not in seen:
          seen.add(nxt)
          stack.append((nxt, path + [nxt]))
    return None

  def record(self, target: str, held: List[str]) -> List[dict]:
    """Record held -> target edges; returns a report per NEW edge
    that closes a cycle (one acquisition while holding several locks
    can close several — each must be recorded, because the edge is
    inserted either way and the fast path below would suppress an
    unreported one forever). Fast path: every edge already known ->
    one set lookup per held lock, no mutex."""
    reports = []
    for h in held:
      if h == target:        # re-entry (RLock) — never an ordering edge
        continue
      known = self._edges.get(h)
      if known is not None and target in known:
        continue
      with self._mutex:
        edges = self._edges.setdefault(h, set())
        if target in edges:
          continue
        # Adding h -> target closes a cycle iff target already
        # reaches h. Find the path BEFORE inserting the edge so the
        # report shows the pre-existing opposite ordering.
        path = self._path(target, h)
        edges.add(target)
        if path is not None:
          report = {
              'holding': h,
              'acquiring': target,
              'cycle': path + [target],
              'thread': threading.current_thread().name,
          }
          self._cycles.append(report)
          reports.append(report)
    return reports

  def cycles(self) -> List[dict]:
    with self._mutex:
      return list(self._cycles)

  def reset(self):
    with self._mutex:
      self._edges.clear()
      self._cycles.clear()


_graph = _LockGraph()
_tls = threading.local()

_armed = os.environ.get('LOCK_ORDER_CHECK', '').lower() in (
    '1', 'true', 'yes')
_raise_on_cycle = False
_incident_sink: Optional[Callable] = None
_cycle_counter = None  # telemetry.Counter once armed


def _held_names() -> List[str]:
  return getattr(_tls, 'held', [])


def _ensure_counter():
  global _cycle_counter
  if _cycle_counter is None:
    try:
      from scalable_agent_tpu import telemetry
      _cycle_counter = telemetry.counter('analysis/lock_cycles')
    except Exception:  # lint/CLI contexts without numpy etc.
      pass


def _on_cycle(report: dict):
  _ensure_counter()
  log.error(
      'LOCK ORDER INVERSION: thread %s acquiring %r while holding %r '
      'but the opposite order is already recorded (cycle: %s) — two '
      'threads disagree about lock order; this is a latent deadlock',
      report['thread'], report['acquiring'], report['holding'],
      ' -> '.join(report['cycle']))
  if _cycle_counter is not None:
    _cycle_counter.inc()
  sink = _incident_sink
  if sink is not None:
    try:
      sink('lock_order_inversion', holding=report['holding'],
           acquiring=report['acquiring'],
           cycle=' -> '.join(report['cycle']),
           thread=report['thread'])
    except Exception:
      log.exception('lock_order_inversion incident sink failed')
  if _raise_on_cycle:
    raise LockOrderInversion(
        f"lock order inversion: acquiring {report['acquiring']!r} "
        f"while holding {report['holding']!r} (cycle "
        f"{' -> '.join(report['cycle'])})")


class OrderedLock:
  """Drop-in `threading.Lock`/`RLock` that records acquisition order.

  Works as a context manager, with `acquire(blocking, timeout)` /
  `release()` / `locked()`, and as the lock behind a
  `threading.Condition` (`_is_owned` answers from the per-thread held
  list, so `Condition.wait/notify` ownership asserts are exact, not
  the try-acquire probe the default fallback uses).

  Ordering edges are recorded at acquisition ATTEMPT time for
  blocking acquires (a thread parked forever in the deadlock still
  contributed its half of the cycle) and at SUCCESS time for
  non-blocking ones (a failed try-acquire — Condition's ownership
  probe shape — must not invent an edge that was never an ordering
  commitment).
  """

  __slots__ = ('name', '_lock', '_recursive')

  def __init__(self, name: str, recursive: bool = False):
    self.name = name
    self._recursive = recursive
    self._lock = threading.RLock() if recursive else threading.Lock()

  # -- ordering bookkeeping ------------------------------------------

  def _record_edges(self, held):
    for report in _graph.record(self.name, held):
      _on_cycle(report)  # raise mode: the first cycle raises; the
      # rest are already in the graph's report list either way

  # -- the lock API ---------------------------------------------------

  def acquire(self, blocking: bool = True, timeout: float = -1):
    held = getattr(_tls, 'held', None)
    if held is None:
      held = _tls.held = []
    # Fast path: nothing held -> no edge can exist; skip the graph.
    if blocking and held:
      self._record_edges(held)
    ok = self._lock.acquire(blocking, timeout)
    if ok:
      if not blocking and held:
        try:
          self._record_edges(held)
        except BaseException:
          # Raise mode: the cycle raises out of acquire() — the
          # just-acquired lock must be released first or it leaks
          # held-forever (the caller never saw a successful acquire).
          self._lock.release()
          raise
      held.append(self.name)
    return ok

  def release(self):
    held = _held_names()
    # Remove the most recent entry for this lock (re-entrant locks
    # stack duplicates).
    for i in range(len(held) - 1, -1, -1):
      if held[i] == self.name:
        del held[i]
        break
    self._lock.release()

  def __enter__(self):
    self.acquire()
    return self

  def __exit__(self, *exc):
    self.release()
    return False

  def locked(self) -> bool:
    probe = getattr(self._lock, 'locked', None)
    if probe is not None:
      return probe()
    # RLock pre-3.12 has no locked(); owned-by-someone approximation.
    if self._lock.acquire(False):
      self._lock.release()
      return False
    return True

  def _is_owned(self) -> bool:
    """threading.Condition ownership probe."""
    return self.name in _held_names()

  def __repr__(self):
    return f'OrderedLock({self.name!r})'


def make_lock(name: str, recursive: bool = False):
  """The adoption seam: an `OrderedLock` when detection is armed,
  else the plain stdlib lock (zero overhead, byte-identical). Armed
  state is read at CONSTRUCTION — arm before building components
  (driver.train does; tests arm via the LOCK_ORDER_CHECK env var in
  conftest before anything imports)."""
  if _armed:
    return OrderedLock(name, recursive=recursive)
  return threading.RLock() if recursive else threading.Lock()


def arm(enabled: bool = True, raise_on_cycle: Optional[bool] = None):
  """Turn detection on/off for locks constructed from here on. Lazily
  registers the `analysis/lock_cycles` counter on first arm (the
  telemetry import stays off the lint path)."""
  global _armed, _raise_on_cycle, _cycle_counter
  _armed = enabled
  if raise_on_cycle is not None:
    _raise_on_cycle = raise_on_cycle
  if enabled:
    _ensure_counter()


def is_armed() -> bool:
  return _armed


def set_incident_sink(sink: Optional[Callable]):
  """`sink(kind, **fields)` — driver.train wires its EventLog.event so
  a detection lands as a durable `lock_order_inversion` incident."""
  global _incident_sink
  _incident_sink = sink


def cycles_detected() -> int:
  return len(_graph.cycles())


def cycle_reports() -> List[dict]:
  return _graph.cycles()


def reset():
  """Clear the graph and the held-lock bookkeeping (tests)."""
  _graph.reset()
  if hasattr(_tls, 'held'):
    _tls.held = []
