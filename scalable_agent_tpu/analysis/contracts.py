"""Contract-lint checkers: the literal-string contracts that hold the
fleet together, machine-checked both directions.

Ported from the scripts/ci.sh inline heredoc (metric names, SLO
objectives, controller rules — rounds 14/15) and extended to every
contract nothing verified before round 18: config fields <->
experiment.py flags, validate_* coverage in driver.train AND
driver.evaluate, durable incident markers <-> emitted kinds <-> docs,
protocol-version literals <-> the docs/TRANSPORT.md version table,
and the driver's summary-scalar tags <-> the docs/OBSERVABILITY.md
inventory.

Every checker is pure stdlib `ast` + regex over docs — greppable
LITERAL registration/emission is the repo-wide convention that makes
these static checks possible (telemetry.py's docstring states it for
metric names; this module extends the same rule to every contract it
checks). Non-literal names are invisible to the lint and therefore
forbidden on these surfaces.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from scalable_agent_tpu.analysis import CheckContext, Finding, checker

# Per-check suppressions: {check: {symbol: reason}}. Etiquette: every
# entry carries the reason it exists; the runner flags STALE entries
# (suppressing nothing) as findings, so suppressions die with the
# violations they covered. Prefer fixing over allowlisting — this
# table being empty on a clean tree is the goal state.
ALLOWLISTS: Dict[str, Dict[str, str]] = {}


# --- shared AST helpers ----------------------------------------------


def _str_const(node) -> Optional[str]:
  if isinstance(node, ast.Constant) and isinstance(node.value, str):
    return node.value
  return None


def _str_tuple(node) -> Optional[List[str]]:
  """Literal tuple/list of strings -> list, else None."""
  if isinstance(node, (ast.Tuple, ast.List)):
    out = []
    for elt in node.elts:
      s = _str_const(elt)
      if s is None:
        return None
      out.append(s)
    return out
  return None


def _int_tuple(node) -> Optional[List[int]]:
  if isinstance(node, (ast.Tuple, ast.List)):
    out = []
    for elt in node.elts:
      if not (isinstance(elt, ast.Constant)
              and isinstance(elt.value, int)):
        return None
      out.append(elt.value)
    return out
  return None


def _module_assign(tree: ast.AST, name: str) -> Optional[ast.AST]:
  """The value node of a module-level `name = ...` assignment."""
  for node in tree.body:  # type: ignore[attr-defined]
    if isinstance(node, ast.Assign):
      for tgt in node.targets:
        if isinstance(tgt, ast.Name) and tgt.id == name:
          return node.value
    elif isinstance(node, ast.AnnAssign):
      if (isinstance(node.target, ast.Name) and node.target.id == name
          and node.value is not None):
        return node.value
  return None


def _class_assign(tree: ast.AST, cls: str, name: str
                  ) -> Optional[ast.AST]:
  for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef) and node.name == cls:
      for st in node.body:
        if isinstance(st, ast.Assign):
          for tgt in st.targets:
            if isinstance(tgt, ast.Name) and tgt.id == name:
              return st.value
  return None


_METRIC_NAME = re.compile(r'[a-z0-9_]+(?:/[a-z0-9_]+)+')


def registered_metric_names(ctx: CheckContext
                            ) -> Dict[str, Tuple[str, int]]:
  """Every literal-string telemetry registration in the package:
  {metric_name: (path, line)}. A registration is a call to
  `counter`/`gauge`/`histogram` either bare (telemetry.py itself) or
  as an attribute of `telemetry`/`_telemetry` — `writer.histogram`
  (the summary stream API) is a different surface and excluded, same
  as the ci.sh heredoc this replaces."""
  out: Dict[str, Tuple[str, int]] = {}
  for rel in ctx.package_sources():
    for node in ast.walk(ctx.tree(rel)):
      if not isinstance(node, ast.Call) or not node.args:
        continue
      fn = node.func
      if isinstance(fn, ast.Name):
        if fn.id not in ('counter', 'gauge', 'histogram'):
          continue
      elif isinstance(fn, ast.Attribute):
        if fn.attr not in ('counter', 'gauge', 'histogram'):
          continue
        if not (isinstance(fn.value, ast.Name)
                and fn.value.id in ('telemetry', '_telemetry')):
          continue
      else:
        continue
      name = _str_const(node.args[0])
      if name and _METRIC_NAME.fullmatch(name):
        out.setdefault(name, (rel, node.lineno))
  return out


def _documented_metric_names(ctx: CheckContext) -> Set[str]:
  doc = ctx.text('docs/OBSERVABILITY.md')
  return set(re.findall(r'`([a-z0-9_]+(?:/[a-z0-9_]+)+)`', doc))


# --- 1. metric names <-> docs inventory ------------------------------


@checker('metric-names',
         'every telemetry counter/gauge/histogram registration in '
         'scalable_agent_tpu/ appears in the docs/OBSERVABILITY.md '
         'inventory, and no documented name is orphaned')
def check_metric_names(ctx: CheckContext) -> List[Finding]:
  registered = registered_metric_names(ctx)
  documented = _documented_metric_names(ctx)
  findings = []
  for name in sorted(set(registered) - documented):
    path, line = registered[name]
    findings.append(Finding(
        'metric-names', path, line, name,
        f'registered metric {name!r} is missing from the '
        'docs/OBSERVABILITY.md inventory'))
  for name in sorted(documented - set(registered)):
    findings.append(Finding(
        'metric-names', 'docs/OBSERVABILITY.md', 1, name,
        f'documented metric {name!r} is no longer registered '
        'anywhere in scalable_agent_tpu/'))
  return findings


# --- 2. SLO objectives <-> registry + docs table ---------------------


def _slo_defaults(ctx: CheckContext) -> List[Tuple[str, str, int]]:
  """[(objective_name, metric, line)] from slo.DEFAULT_OBJECTIVES."""
  tree = ctx.tree('scalable_agent_tpu/slo.py')
  value = _module_assign(tree, 'DEFAULT_OBJECTIVES')
  out = []
  if value is None:
    return out
  for node in ast.walk(value):
    if isinstance(node, ast.Call):
      name = metric = None
      for kw in node.keywords:
        if kw.arg == 'name':
          name = _str_const(kw.value)
        elif kw.arg == 'metric':
          metric = _str_const(kw.value)
      if name and metric:
        out.append((name, metric, node.lineno))
  return out


@checker('slo-objectives',
         "every slo.DEFAULT_OBJECTIVES metric is a registered "
         "telemetry name, and the docs/OBSERVABILITY.md SLO "
         "inventory table matches the default set by name, both "
         "directions")
def check_slo_objectives(ctx: CheckContext) -> List[Finding]:
  registered = set(registered_metric_names(ctx))
  defaults = _slo_defaults(ctx)
  doc = ctx.text('docs/OBSERVABILITY.md')
  doc_names = set(re.findall(
      r'^\|\s*`([a-z0-9_]+)`\s*\|\s*`[a-z0-9_]+(?:/[a-z0-9_]+)+`',
      doc, re.MULTILINE))
  findings = []
  for name, metric, line in defaults:
    if metric not in registered:
      findings.append(Finding(
          'slo-objectives', 'scalable_agent_tpu/slo.py', line, name,
          f'objective {name!r} judges unregistered metric '
          f'{metric!r}: it would evaluate no_data forever'))
  names = {n for n, _, _ in defaults}
  for name in sorted(names - doc_names):
    findings.append(Finding(
        'slo-objectives', 'scalable_agent_tpu/slo.py', 1, name,
        f'default objective {name!r} missing from the '
        'docs/OBSERVABILITY.md SLO inventory table'))
  for name in sorted(doc_names - names):
    findings.append(Finding(
        'slo-objectives', 'docs/OBSERVABILITY.md', 1, name,
        f'documented SLO objective {name!r} is not in '
        'slo.DEFAULT_OBJECTIVES'))
  return findings


# --- 3. controller rules <-> objectives + actuators ------------------


@checker('controller-rules',
         'every controller.DEFAULT_RULES objective is a shipped SLO '
         'default and every actuator a KNOWN_ACTUATORS name')
def check_controller_rules(ctx: CheckContext) -> List[Finding]:
  tree = ctx.tree('scalable_agent_tpu/controller.py')
  slo_names = {n for n, _, _ in _slo_defaults(ctx)}
  known_node = _module_assign(tree, 'KNOWN_ACTUATORS')
  known = set(_str_tuple(known_node) or [])
  rules = _module_assign(tree, 'DEFAULT_RULES')
  findings = []
  if rules is None:
    return [Finding('controller-rules',
                    'scalable_agent_tpu/controller.py', 1,
                    'DEFAULT_RULES',
                    'DEFAULT_RULES not found as a module literal')]
  for node in ast.walk(rules):
    if not isinstance(node, ast.Call):
      continue
    for kw in node.keywords:
      val = _str_const(kw.value)
      if val is None:
        continue
      if kw.arg == 'objective' and val not in slo_names:
        findings.append(Finding(
            'controller-rules', 'scalable_agent_tpu/controller.py',
            node.lineno, val,
            f'rule watches objective {val!r} which is not in '
            'slo.DEFAULT_OBJECTIVES — it can never fire'))
      if kw.arg == 'actuator' and val not in known:
        findings.append(Finding(
            'controller-rules', 'scalable_agent_tpu/controller.py',
            node.lineno, val,
            f'rule drives unknown actuator {val!r} (not in '
            'KNOWN_ACTUATORS)'))
  return findings


# --- 4. config fields <-> experiment.py flags ------------------------


def _config_fields(ctx: CheckContext) -> Dict[str, int]:
  tree = ctx.tree('scalable_agent_tpu/config.py')
  fields: Dict[str, int] = {}
  for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef) and node.name == 'Config':
      for st in node.body:
        if (isinstance(st, ast.AnnAssign)
            and isinstance(st.target, ast.Name)):
          fields[st.target.id] = st.lineno
  return fields


@checker('config-flags',
         'every Config field is exposed as an experiment.py flag or '
         'named in config.INTERNAL_FIELDS; no flag without a field, '
         'no stale INTERNAL_FIELDS entry')
def check_config_flags(ctx: CheckContext) -> List[Finding]:
  fields = _config_fields(ctx)
  cfg_tree = ctx.tree('scalable_agent_tpu/config.py')
  internal_node = _module_assign(cfg_tree, 'INTERNAL_FIELDS')
  findings = []
  if internal_node is None:
    findings.append(Finding(
        'config-flags', 'scalable_agent_tpu/config.py', 1,
        'INTERNAL_FIELDS',
        'config.py must define the INTERNAL_FIELDS literal tuple '
        '(the explicit allowlist for fields deliberately not '
        'exposed as flags)'))
    internal = []
  else:
    internal = _str_tuple(internal_node) or []
  flags: Dict[str, int] = {}
  for node in ast.walk(ctx.tree('experiment.py')):
    if (isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr.startswith('DEFINE_')
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == 'flags' and node.args):
      name = _str_const(node.args[0])
      if name:
        flags[name] = node.lineno
  for name in sorted(set(fields) - set(flags) - set(internal)):
    findings.append(Finding(
        'config-flags', 'scalable_agent_tpu/config.py',
        fields[name], name,
        f'Config.{name} has no experiment.py flag and no '
        'INTERNAL_FIELDS entry — operators cannot set it, and '
        'nothing records that as deliberate'))
  for name in sorted(set(flags) - set(fields)):
    findings.append(Finding(
        'config-flags', 'experiment.py', flags[name], name,
        f'flag --{name} has no Config field: config_from_flags '
        'silently drops it'))
  for name in sorted(internal):
    if name not in fields:
      findings.append(Finding(
          'config-flags', 'scalable_agent_tpu/config.py', 1, name,
          f'INTERNAL_FIELDS entry {name!r} is not a Config field — '
          'stale allowlist entry'))
    elif name in flags:
      findings.append(Finding(
          'config-flags', 'scalable_agent_tpu/config.py', 1, name,
          f'INTERNAL_FIELDS entry {name!r} HAS a flag '
          '(experiment.py:%d) — the allowlist entry is stale'
          % flags[name]))
  return findings


# --- 5. validate_* coverage in driver.train AND driver.evaluate ------


@checker('validate-coverage',
         'every config.validate_* knob group is called from both '
         'driver.train and driver.evaluate')
def check_validate_coverage(ctx: CheckContext) -> List[Finding]:
  cfg_tree = ctx.tree('scalable_agent_tpu/config.py')
  groups: Dict[str, int] = {}
  for node in cfg_tree.body:  # type: ignore[attr-defined]
    if (isinstance(node, ast.FunctionDef)
        and node.name.startswith('validate_')):
      groups[node.name] = node.lineno
  drv = ctx.tree('scalable_agent_tpu/driver.py')
  findings = []
  for entry in ('train', 'evaluate'):
    fn = next((n for n in drv.body  # type: ignore[attr-defined]
               if isinstance(n, ast.FunctionDef) and n.name == entry),
              None)
    if fn is None:
      findings.append(Finding(
          'validate-coverage', 'scalable_agent_tpu/driver.py', 1,
          entry, f'driver.{entry} not found'))
      continue
    called = set()
    for node in ast.walk(fn):
      if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name):
          called.add(f.id)
        elif isinstance(f, ast.Attribute):
          called.add(f.attr)
    for group in sorted(set(groups) - called):
      findings.append(Finding(
          'validate-coverage', 'scalable_agent_tpu/driver.py',
          fn.lineno, f'{entry}:{group}',
          f'driver.{entry} never calls config.{group} — a bad knob '
          'in that group passes spin-up silently on this path'))
  return findings


# --- 6. durable incident markers <-> emitters <-> docs ---------------


def _emitted_incident_kinds(ctx: CheckContext
                            ) -> Dict[str, Tuple[str, int]]:
  """Literal incident kinds: first args of `<x>.event('kind', ...)`
  calls anywhere in the package or scripts/, plus literal kinds
  handed to an incident `sink(...)` (the analysis runtime's seam)."""
  kinds: Dict[str, Tuple[str, int]] = {}
  sources = ctx.package_sources() + ctx.package_sources('scripts')
  for rel in sources:
    try:
      tree = ctx.tree(rel)
    except SyntaxError:
      continue
    for node in ast.walk(tree):
      if not isinstance(node, ast.Call) or not node.args:
        continue
      f = node.func
      is_event = (isinstance(f, ast.Attribute) and f.attr == 'event')
      is_sink = isinstance(f, ast.Name) and f.id == 'sink'
      if not (is_event or is_sink):
        continue
      kind = _str_const(node.args[0])
      if kind:
        kinds.setdefault(kind, (rel, node.lineno))
  return kinds


def _doc_durable_markers(ctx: CheckContext) -> Set[str]:
  doc = ctx.text('docs/OBSERVABILITY.md')
  m = re.search(
      r'### Durable incident markers\n(.*?)(?:\n#|\Z)', doc, re.S)
  if not m:
    return set()
  return set(re.findall(r'`([a-z0-9_]+)`', m.group(1)))


@checker('durable-markers',
         'every EventLog._DURABLE_MARKERS marker matches an incident '
         'kind some module actually emits, and the '
         'docs/OBSERVABILITY.md durable-marker list matches the code '
         'both directions')
def check_durable_markers(ctx: CheckContext) -> List[Finding]:
  tree = ctx.tree('scalable_agent_tpu/observability.py')
  node = _class_assign(tree, 'EventLog', '_DURABLE_MARKERS')
  markers = _str_tuple(node) if node is not None else None
  findings = []
  if markers is None:
    return [Finding('durable-markers',
                    'scalable_agent_tpu/observability.py', 1,
                    '_DURABLE_MARKERS',
                    'EventLog._DURABLE_MARKERS literal tuple not '
                    'found')]
  kinds = _emitted_incident_kinds(ctx)
  for marker in sorted(markers):
    if not any(marker in kind for kind in kinds):
      findings.append(Finding(
          'durable-markers', 'scalable_agent_tpu/observability.py',
          node.lineno, marker,
          f'durable marker {marker!r} matches no emitted incident '
          'kind anywhere in scalable_agent_tpu/ or scripts/ — '
          'orphaned fsync rule'))
  documented = _doc_durable_markers(ctx)
  if not documented:
    findings.append(Finding(
        'durable-markers', 'docs/OBSERVABILITY.md', 1,
        'durable-markers-section',
        'docs/OBSERVABILITY.md has no "### Durable incident '
        'markers" section listing the fsync markers'))
    return findings
  for marker in sorted(set(markers) - documented):
    findings.append(Finding(
        'durable-markers', 'docs/OBSERVABILITY.md', 1, marker,
        f'durable marker {marker!r} (code) missing from the '
        'docs/OBSERVABILITY.md durable-marker list'))
  for marker in sorted(documented - set(markers)):
    findings.append(Finding(
        'durable-markers', 'docs/OBSERVABILITY.md', 1, marker,
        f'documented durable marker {marker!r} is not in '
        'EventLog._DURABLE_MARKERS'))
  return findings


# --- 7. protocol versions <-> docs/TRANSPORT.md table ----------------


@checker('protocol-versions',
         "remote.py's _COMPATIBLE_PROTOCOLS matches the "
         'docs/TRANSPORT.md version table both directions, and '
         'PROTOCOL_VERSION is the newest compatible version')
def check_protocol_versions(ctx: CheckContext) -> List[Finding]:
  tree = ctx.tree('scalable_agent_tpu/runtime/remote.py')
  compat_node = _module_assign(tree, '_COMPATIBLE_PROTOCOLS')
  compat = _int_tuple(compat_node) if compat_node is not None else None
  current_node = _module_assign(tree, 'PROTOCOL_VERSION')
  findings = []
  if compat is None or not isinstance(current_node, ast.Constant):
    return [Finding('protocol-versions',
                    'scalable_agent_tpu/runtime/remote.py', 1,
                    '_COMPATIBLE_PROTOCOLS',
                    '_COMPATIBLE_PROTOCOLS / PROTOCOL_VERSION '
                    'literals not found')]
  current = current_node.value
  doc = ctx.text('docs/TRANSPORT.md')
  doc_versions = {int(v) for v in
                  re.findall(r'^\|\s*v(\d+)\s*\|', doc, re.M)}
  if not doc_versions:
    return [Finding('protocol-versions', 'docs/TRANSPORT.md', 1,
                    'version-table',
                    'docs/TRANSPORT.md has no protocol version table '
                    '(rows starting `| vN |`)')]
  for v in sorted(set(compat) - doc_versions):
    findings.append(Finding(
        'protocol-versions', 'scalable_agent_tpu/runtime/remote.py',
        compat_node.lineno, f'v{v}',
        f'protocol v{v} is in _COMPATIBLE_PROTOCOLS but missing '
        'from the docs/TRANSPORT.md version table'))
  for v in sorted(doc_versions - set(compat)):
    findings.append(Finding(
        'protocol-versions', 'docs/TRANSPORT.md', 1, f'v{v}',
        f'docs/TRANSPORT.md documents protocol v{v} which is not in '
        '_COMPATIBLE_PROTOCOLS'))
  if current != max(compat):
    findings.append(Finding(
        'protocol-versions', 'scalable_agent_tpu/runtime/remote.py',
        compat_node.lineno, f'v{current}',
        f'PROTOCOL_VERSION ({current}) is not the newest compatible '
        f'version ({max(compat)})'))
  return findings


# --- 8. driver summary scalars <-> docs inventory --------------------

SUMMARY_BLOCK_BEGIN = '<!-- lint:summary-scalars:begin -->'
SUMMARY_BLOCK_END = '<!-- lint:summary-scalars:end -->'


def driver_summary_tags(ctx: CheckContext) -> Dict[str, int]:
  """Literal summary-scalar tags the driver writes: first args of
  `.scalar(tag, value, step)` calls in driver.py — direct literals
  plus names bound by a `for tag in (<literal tuple>)` loop (the
  replay-stats export shape). Fully dynamic tags (per-level episode
  tags, tracer percentile dicts, stacked step metrics) are outside
  the static contract and documented in prose instead."""
  tree = ctx.tree('scalable_agent_tpu/driver.py')
  loop_names: Dict[str, List[str]] = {}
  for node in ast.walk(tree):
    if (isinstance(node, ast.For) and isinstance(node.target, ast.Name)):
      vals = _str_tuple(node.iter)
      if vals:
        loop_names.setdefault(node.target.id, []).extend(vals)
  tags: Dict[str, int] = {}
  for node in ast.walk(tree):
    if (isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == 'scalar' and node.args):
      arg = node.args[0]
      lit = _str_const(arg)
      if lit is not None:
        tags.setdefault(lit, node.lineno)
      elif isinstance(arg, ast.Name) and arg.id in loop_names:
        for val in loop_names[arg.id]:
          tags.setdefault(val, node.lineno)
  return tags


def documented_summary_tags(ctx: CheckContext) -> Set[str]:
  doc = ctx.text('docs/OBSERVABILITY.md')
  start = doc.find(SUMMARY_BLOCK_BEGIN)
  end = doc.find(SUMMARY_BLOCK_END)
  if start < 0 or end < 0:
    return set()
  # Tags may be namespaced with '/' (e.g. population/best_return).
  return set(re.findall(r'`([a-z0-9_/]+)`', doc[start:end]))


@checker('summary-scalars',
         'every literal summary-scalar tag driver.py writes appears '
         'in the generated docs/OBSERVABILITY.md inventory block '
         '(scripts/lint.py --fix-docs regenerates it), and no '
         'documented tag is orphaned')
def check_summary_scalars(ctx: CheckContext) -> List[Finding]:
  tags = driver_summary_tags(ctx)
  documented = documented_summary_tags(ctx)
  findings = []
  if not documented:
    return [Finding(
        'summary-scalars', 'docs/OBSERVABILITY.md', 1,
        'summary-scalar-block',
        'docs/OBSERVABILITY.md has no generated summary-scalar '
        f'inventory block ({SUMMARY_BLOCK_BEGIN} ... '
        f'{SUMMARY_BLOCK_END}) — run scripts/lint.py --fix-docs')]
  for tag in sorted(set(tags) - documented):
    findings.append(Finding(
        'summary-scalars', 'scalable_agent_tpu/driver.py',
        tags[tag], tag,
        f'driver writes summary scalar {tag!r} which is missing '
        'from the docs/OBSERVABILITY.md inventory block (run '
        'scripts/lint.py --fix-docs)'))
  for tag in sorted(documented - set(tags)):
    findings.append(Finding(
        'summary-scalars', 'docs/OBSERVABILITY.md', 1, tag,
        f'documented summary scalar {tag!r} is no longer written by '
        'driver.py (run scripts/lint.py --fix-docs)'))
  return findings


def fix_summary_scalar_docs(ctx: CheckContext) -> bool:
  """Regenerate the summary-scalar block in docs/OBSERVABILITY.md
  from the live driver.py tags. Returns True when the file changed."""
  tags = sorted(driver_summary_tags(ctx))
  body = '\n'.join(
      [SUMMARY_BLOCK_BEGIN] + [f'- `{t}`' for t in tags]
      + [SUMMARY_BLOCK_END])
  path = ctx.root / 'docs/OBSERVABILITY.md'
  doc = path.read_text()
  start = doc.find(SUMMARY_BLOCK_BEGIN)
  end = doc.find(SUMMARY_BLOCK_END)
  if start < 0 or end < 0:
    raise SystemExit(
        'docs/OBSERVABILITY.md has no summary-scalar block markers; '
        'add the section first (see docs/STATIC_ANALYSIS.md)')
  new = doc[:start] + body + doc[end + len(SUMMARY_BLOCK_END):]
  if new != doc:
    path.write_text(new)
    return True
  return False


# --- 9. checker inventory <-> docs/STATIC_ANALYSIS.md ----------------


@checker('checker-inventory',
         'the docs/STATIC_ANALYSIS.md checker table matches '
         'scripts/lint.py --list both directions (the self-applied '
         'contract lint)')
def check_checker_inventory(ctx: CheckContext) -> List[Finding]:
  from scalable_agent_tpu import analysis
  names = {n for n, _, _ in analysis.all_checkers()}
  try:
    doc = ctx.text('docs/STATIC_ANALYSIS.md')
  except FileNotFoundError:
    return [Finding('checker-inventory', 'docs/STATIC_ANALYSIS.md', 1,
                    'docs', 'docs/STATIC_ANALYSIS.md does not exist')]
  doc_names = set(re.findall(r'^\|\s*`([a-z0-9-]+)`\s*\|', doc, re.M))
  findings = []
  for name in sorted(names - doc_names):
    findings.append(Finding(
        'checker-inventory', 'docs/STATIC_ANALYSIS.md', 1, name,
        f'checker {name!r} is missing from the '
        'docs/STATIC_ANALYSIS.md inventory table'))
  for name in sorted(doc_names - names):
    findings.append(Finding(
        'checker-inventory', 'docs/STATIC_ANALYSIS.md', 1, name,
        f'documented checker {name!r} is not registered in the '
        'analysis framework'))
  return findings


# --- 10. ci.sh wiring -------------------------------------------------


@checker('ci-wiring',
         'scripts/ci.sh runs scripts/lint.py and carries no inline '
         'lint heredoc')
def check_ci_wiring(ctx: CheckContext) -> List[Finding]:
  ci = ctx.text('scripts/ci.sh')
  findings = []
  if 'scripts/lint.py' not in ci:
    findings.append(Finding(
        'ci-wiring', 'scripts/ci.sh', 1, 'lint-call',
        'scripts/ci.sh never invokes scripts/lint.py'))
  if 'LINT_EOF' in ci:
    line = ci[:ci.index('LINT_EOF')].count('\n') + 1
    findings.append(Finding(
        'ci-wiring', 'scripts/ci.sh', line, 'inline-heredoc',
        'scripts/ci.sh still contains the inline LINT_EOF lint '
        'heredoc — the checks live in scripts/lint.py now'))
  return findings


# --- 11. sharding registry (round 19) ---------------------------------


@checker('sharding-registry',
         'no inline PartitionSpec(...)/NamedSharding(...) '
         'construction outside parallel/sharding.py — every sharding '
         'decision resolves through the registry')
def check_sharding_registry(ctx: CheckContext) -> List[Finding]:
  """parallel/sharding.py is the ONE source of sharding truth: a
  `PartitionSpec(...)` — or, round 20, a `NamedSharding(...)` binding
  a spec to a mesh — constructed anywhere else in the package (or
  its entry points) is a private sharding decision the registry
  cannot see — exactly the hand-copied-consumer drift this round
  deleted, and exactly what the elastic cross-topology restore would
  silently miss when respecifying for a new mesh. Tests are
  deliberately out of scope (they construct expected specs to assert
  the registry against)."""
  sources = ctx.package_sources()
  for extra in ('experiment.py', 'bench.py'):
    try:
      ctx.text(extra)
      sources.append(extra)
    except (FileNotFoundError, OSError):
      pass
  try:
    sources.extend(ctx.package_sources('scripts'))
  except (FileNotFoundError, OSError):
    pass
  findings = []
  for rel in sources:
    if rel.replace('\\', '/') == 'scalable_agent_tpu/parallel/sharding.py':
      continue
    tree = ctx.tree(rel)
    # PartitionSpec names this module can construct with: `from
    # jax.sharding import PartitionSpec [as P]` aliases...
    aliases: Set[str] = set()
    for node in ast.walk(tree):
      if isinstance(node, ast.ImportFrom) and node.module and (
          node.module == 'jax.sharding'
          or node.module.endswith('.sharding')):
        for a in node.names:
          if a.name in ('PartitionSpec', 'NamedSharding'):
            aliases.add(a.asname or a.name)
    func_of: Dict[int, str] = {}
    for node in ast.walk(tree):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for sub in ast.walk(node):
          if hasattr(sub, 'lineno'):
            func_of.setdefault(sub.lineno, node.name)
    for node in ast.walk(tree):
      if not isinstance(node, ast.Call):
        continue
      inline = (
          # P(...) / PartitionSpec(...) / NamedSharding(...) via a
          # from-import alias
          (isinstance(node.func, ast.Name) and node.func.id in aliases)
          # ...or any attribute spelling:
          # jax.sharding.PartitionSpec(...) / .NamedSharding(...)
          or (isinstance(node.func, ast.Attribute)
              and node.func.attr in ('PartitionSpec',
                                     'NamedSharding')))
      if inline:
        where = func_of.get(node.lineno, '<module>')
        findings.append(Finding(
            'sharding-registry', rel, node.lineno,
            f'{rel}:{where}',
            'inline PartitionSpec/NamedSharding construction outside '
            'parallel/sharding.py — resolve the spec through the '
            'sharding registry (spec helpers or ShardingRegistry '
            'methods) so every consumer sees the same decision'))
  return findings
