"""Invariant analyzer (round 18): a pluggable, stdlib-`ast`-only
contract-lint framework for the literal-string contracts and the
threaded control plane PRs 6-17 grew.

Seven modules spin threads and hold ~40 locks, coordinated by
literal-string contracts: metric names <-> the docs/OBSERVABILITY.md
inventory, SLO objectives <-> controller rules, config fields <->
experiment.py flags, incident kinds <-> durable-fsync markers,
protocol versions <-> docs/TRANSPORT.md. Until this round the only
guard was an inline regex heredoc in scripts/ci.sh plus hand-written
torn-read tests. This package makes those contracts (and the lock
discipline itself) machine-checked:

- `analysis.contracts` — the contract checkers (ported from the ci.sh
  heredoc, then extended to the contracts nothing verified).
- `analysis.concurrency` — the `guarded_by` AST pass: reads/writes of
  annotated attributes outside a `with self.<lock>` block.
- `analysis.runtime` — the runtime half: `OrderedLock` lock-order
  detection and the `guarded_by` annotation helper itself.
- `scripts/lint.py` — the CLI (`--check/--json/--fix-docs/--list`,
  nonzero exit on findings).

The framework is import-light by design: no jax, no numpy — the
build host is air-gapped and CI runs the full suite in seconds.

Extending: write `def check_<x>(ctx) -> List[Finding]`, register it
with `@checker('name', 'description')`, add a row to
docs/STATIC_ANALYSIS.md's inventory table (the `checker-inventory`
check enforces that the docs and `scripts/lint.py --list` cannot
drift), and seed one violation in tests/test_analysis.py proving the
checker can fire. Suppressions go in `ALLOWLISTS` (contracts.py) with
a reason — stale entries are themselves findings.
"""

import ast
import dataclasses
import pathlib
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    'Finding', 'CheckContext', 'checker', 'all_checkers',
    'run_checks',
]


@dataclasses.dataclass(frozen=True)
class Finding:
  """One violation: where, what, and the symbol an allowlist entry
  would name to suppress it."""
  check: str
  path: str
  line: int
  symbol: str
  message: str

  def render(self) -> str:
    return f'{self.path}:{self.line}: [{self.check}] {self.message}'


class CheckContext:
  """Repo handle shared by every checker: rooted paths, a parsed-AST
  cache (each source file is parsed once per run), and text access."""

  def __init__(self, root):
    self.root = pathlib.Path(root)
    self._trees: Dict[pathlib.Path, ast.AST] = {}
    self._texts: Dict[pathlib.Path, str] = {}

  def text(self, rel: str) -> str:
    path = self.root / rel
    if path not in self._texts:
      self._texts[path] = path.read_text()
    return self._texts[path]

  def tree(self, rel: str) -> ast.AST:
    path = self.root / rel
    if path not in self._trees:
      self._trees[path] = ast.parse(self.text(rel), filename=str(path))
    return self._trees[path]

  def package_sources(self, subdir: str = 'scalable_agent_tpu'
                      ) -> List[str]:
    """Repo-relative paths of every .py under `subdir`, sorted."""
    base = self.root / subdir
    return sorted(
        str(p.relative_to(self.root))
        for p in base.rglob('*.py'))


# --- checker registry -------------------------------------------------

_REGISTRY: List[Tuple[str, str, Callable]] = []


def checker(name: str, description: str):
  """Register a checker. The function takes a CheckContext and
  returns a list of Findings."""
  def wrap(fn):
    _REGISTRY.append((name, description, fn))
    return fn
  return wrap


def all_checkers() -> List[Tuple[str, str, Callable]]:
  """(name, description, fn) in registration order — the inventory
  `scripts/lint.py --list` prints and docs/STATIC_ANALYSIS.md must
  mirror."""
  _load()
  return list(_REGISTRY)


_loaded = False


def _load():
  """Import the checker modules exactly once (registration is an
  import side effect, kept out of package import so `analysis.runtime`
  users never pay for it)."""
  global _loaded
  if not _loaded:
    from scalable_agent_tpu.analysis import concurrency  # noqa: F401
    from scalable_agent_tpu.analysis import contracts  # noqa: F401
    _loaded = True


def run_checks(root, only: Optional[List[str]] = None
               ) -> List[Finding]:
  """Run the (selected) checker suite over the repo at `root`.

  Allowlist semantics: a finding whose (check, symbol) appears in
  `contracts.ALLOWLISTS` is suppressed; an allowlist entry that
  suppressed NOTHING is stale and becomes a finding itself (check
  `allowlist`) — suppressions must die with the violations they
  covered.
  """
  _load()
  from scalable_agent_tpu.analysis import contracts
  ctx = CheckContext(root)
  names = {n for n, _, _ in _REGISTRY}
  if only:
    unknown = sorted(set(only) - names)
    if unknown:
      raise ValueError(
          f'unknown checker(s) {unknown}; known: {sorted(names)}')
  findings: List[Finding] = []
  used: Dict[Tuple[str, str], bool] = {
      (check, sym): False
      for check, entries in contracts.ALLOWLISTS.items()
      for sym in entries}
  selected = [e for e in _REGISTRY if not only or e[0] in only]
  for name, _, fn in selected:
    allow = contracts.ALLOWLISTS.get(name, {})
    for f in fn(ctx):
      if f.symbol in allow:
        used[(name, f.symbol)] = True
        continue
      findings.append(f)
  # Stale allowlist entries — only judged when the owning checker ran
  # (a --check run must not misread "didn't look" as "nothing found").
  ran = {e[0] for e in selected}
  for (check, sym), hit in sorted(used.items()):
    if check in ran and not hit:
      findings.append(Finding(
          check='allowlist', path='scalable_agent_tpu/analysis/contracts.py',
          line=1, symbol=f'{check}:{sym}',
          message=f'stale allowlist entry {sym!r} for check '
                  f'{check!r}: it no longer suppresses any finding — '
                  'remove it (allowlist etiquette: suppressions die '
                  'with the violations they covered)'))
  return findings
