"""The lock-discipline AST pass: `guarded_by` annotations enforced.

Convention (see `analysis.runtime.guarded_by` and
docs/STATIC_ANALYSIS.md): a class declares, in its body,

    class InferenceServer:
      _free: guarded_by('_slot_lock')

and this pass flags every `self._free` read/write/delete in that
class's methods that is not lexically inside a
`with self._slot_lock:` block. What the checker understands:

- **Condition aliasing** — `self._not_empty =
  threading.Condition(self._lock)` makes `with self._not_empty:`
  count as holding `_lock` (the ring-buffer shape).
- **`*_locked` methods** — a method whose name ends in `_locked` is,
  by the repo's existing naming convention (`_grow_arena_locked`),
  called with ONE lock already held. The checker grants it exactly
  one assumed-held lock — the one that explains the most otherwise-
  bare accesses — so a `*_locked` helper that also touches state
  guarded by a SECOND lock without taking it is still flagged (a
  blanket exemption would blind-spot the torn-counter class the
  checker exists for). Call SITES of such methods are still checked
  through whatever guarded attributes they touch around the call.
- **`__init__` exemption** — construction happens-before publication
  to other threads; the constructor writes freely.
- **closures** — a nested function inherits the lexical held-set of
  its definition site. (A closure *stored* and called later from
  outside the lock is invisible to a lexical pass — don't do that
  with guarded state.)

Escapes: per-finding allowlist entries in
`contracts.ALLOWLISTS['guarded-by']` keyed by
`Class.method.attribute`, each with a reason.
"""

import ast
from typing import Dict, FrozenSet, List, Set, Tuple

from scalable_agent_tpu.analysis import CheckContext, Finding, checker


def _self_attr(node) -> str:
  """'attr' when node is `self.attr`, else ''."""
  if (isinstance(node, ast.Attribute)
      and isinstance(node.value, ast.Name) and node.value.id == 'self'):
    return node.attr
  return ''


def _guard_decls(cls: ast.ClassDef) -> Dict[str, Tuple[str, ...]]:
  """{attr: (lock_attr, ...)} from `attr: guarded_by('lock')`
  class-body annotations."""
  guards: Dict[str, Tuple[str, ...]] = {}
  for st in cls.body:
    if not (isinstance(st, ast.AnnAssign)
            and isinstance(st.target, ast.Name)):
      continue
    ann = st.annotation
    if not isinstance(ann, ast.Call):
      continue
    fn = ann.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else '')
    if name != 'guarded_by':
      continue
    locks = tuple(a.value for a in ann.args
                  if isinstance(a, ast.Constant)
                  and isinstance(a.value, str))
    if locks:
      guards[st.target.id] = locks
  return guards


def _condition_aliases(cls: ast.ClassDef) -> Dict[str, str]:
  """{condition_attr: lock_attr} from
  `self.cond = threading.Condition(self.lock)` assignments anywhere
  in the class."""
  aliases: Dict[str, str] = {}
  for node in ast.walk(cls):
    if not isinstance(node, ast.Assign) or len(node.targets) != 1:
      continue
    tgt = _self_attr(node.targets[0])
    if not tgt or not isinstance(node.value, ast.Call):
      continue
    fn = node.value.func
    ctor = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else '')
    if ctor != 'Condition' or not node.value.args:
      continue
    src = _self_attr(node.value.args[0])
    if src:
      aliases[tgt] = src
  return aliases


class _MethodChecker:
  """Walks one method body tracking the lexical held-lock set."""

  def __init__(self, rel: str, cls: str, method: str,
               guards: Dict[str, Tuple[str, ...]],
               aliases: Dict[str, str]):
    self.rel = rel
    self.cls = cls
    self.method = method
    self.guards = guards
    self.aliases = aliases
    # (finding, acceptable-locks) pairs — the lock tuple rides along
    # so the *_locked post-pass can grant one assumed-held lock.
    self.findings: List[Tuple[Finding, Tuple[str, ...]]] = []

  def run(self, fn: ast.AST):
    self._visit_body(getattr(fn, 'body', []), frozenset())

  def _expand(self, lock: str) -> Set[str]:
    """A with on `lock` holds `lock` itself plus, for a Condition,
    the mutex it wraps."""
    held = {lock}
    if lock in self.aliases:
      held.add(self.aliases[lock])
    return held

  def _visit_body(self, body, held: FrozenSet[str]):
    for node in body:
      self._visit(node, held)

  def _visit(self, node, held: FrozenSet[str]):
    if isinstance(node, ast.With):
      inner = set(held)
      for item in node.items:
        lock = _self_attr(item.context_expr)
        if lock:
          inner |= self._expand(lock)
        else:
          self._visit(item.context_expr, held)
      self._visit_body(node.body, frozenset(inner))
      return
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
      # Closure: inherits the definition site's held set lexically.
      body = node.body if isinstance(node.body, list) else [node.body]
      self._visit_body(body, held)
      return
    if isinstance(node, ast.Attribute):
      attr = _self_attr(node)
      if attr and attr in self.guards:
        locks = self.guards[attr]
        satisfied = any(lock in held for lock in locks)
        if not satisfied:
          want = ' or '.join(f'self.{lock}' for lock in locks)
          self.findings.append((Finding(
              'guarded-by', self.rel, node.lineno,
              f'{self.cls}.{self.method}.{attr}',
              f'{self.cls}.{self.method} touches self.{attr} '
              f'(guarded_by {locks}) outside `with {want}`'), locks))
      # still visit node.value for chained attributes
      self._visit(node.value, held)
      return
    for child in ast.iter_child_nodes(node):
      self._visit(child, held)


@checker('guarded-by',
         'reads/writes of guarded_by-annotated attributes outside a '
         '`with self.<lock>` block in the owning class')
def check_guarded_by(ctx: CheckContext) -> List[Finding]:
  findings: List[Finding] = []
  for rel in ctx.package_sources():
    tree = ctx.tree(rel)
    for cls in ast.walk(tree):
      if not isinstance(cls, ast.ClassDef):
        continue
      guards = _guard_decls(cls)
      if not guards:
        continue
      aliases = _condition_aliases(cls)
      for st in cls.body:
        if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
          continue
        if st.name == '__init__':
          continue
        mc = _MethodChecker(rel, cls.name, st.name, guards, aliases)
        mc.run(st)
        raw = mc.findings
        if st.name.endswith('_locked') and raw:
          # The naming convention promises the CALLER holds one lock.
          # Grant exactly one: the candidate explaining the most
          # otherwise-bare accesses; anything it does not cover is
          # state under a DIFFERENT lock the helper must take itself.
          candidates = sorted({lock for _, locks in raw
                               for lock in locks})
          best = max(candidates,
                     key=lambda c: sum(1 for _, locks in raw
                                       if c in locks))
          raw = [(f, locks) for f, locks in raw if best not in locks]
        findings.extend(f for f, _ in raw)
  return findings
