"""Self-healing control plane: SLO verdicts wired to the fleet's
actuators (round 15).

PR 11 (slo.py) built the sensor-to-verdict half of ROADMAP item 5:
declarative objectives over the metrics registry, burn-rate
evaluation, SLO_VERDICT.json. This module is the verdict-to-actuation
half — the piece that makes a load surge or a dying plane a counted,
reverted control action instead of a page for a human (PAL's
resource-aware actor/learner scaling, arXiv 2110.01101; IMPACT's
staleness-tolerant reuse, arXiv 1912.00167, is why raising `replay_k`
is a legal move at all).

Design:

1. **Declarative policy table** (`Rule`): objective name → actuator
   name, with a bounded step size, a cool-down between moves, and a
   hysteresis band — a rule TRIGGERS when its objective is burning OR
   its margin has thinned to `trigger_margin` (the controller acts on
   the leading edge, before a page-severity objective ever burns and
   fails the verdict), and REVERTS one step per cool-down only once
   the margin has recovered past `clear_margin` (> trigger_margin by
   validation), so a metric hovering at the threshold cannot flap the
   knob. `DEFAULT_RULES` ships the mapping the ROADMAP names: raise
   `replay_k` when the env plane is the bound, flip admission
   block→shed under overload burn, stretch the remote publish cadence
   under transport pressure, grow/shrink the actor fleet elastically.
   `--controller_policy` loads a JSON rule list instead; a typo'd rule
   fails at spin-up (the --slo_spec rule).

2. **Actuators** (`Actuator`): named, bounded, thread-safe set_* seams
   the driver registers — `replay_k` (BatchPrefetcher.set_replay_k),
   `admission` (InferenceServer.set_admission), `publish_secs` (the
   driver's remote-publish cadence cell), `fleet_size`
   (ActorFleet.set_target_size, whose grow path unparks parked slots
   and REHABILITATES quarantined ones through the probation ladder).
   Rules whose actuator this topology doesn't expose (no ingest → no
   publish cadence) are dropped at construction with a log line, not
   an error.

3. **The loop** (`Controller`): its own thread reads the SloEngine's
   locked `control_snapshot()` (burning set + per-objective margins —
   the round-14 design's intended control inputs) on a cadence and
   applies at most one bounded move per rule per cool-down. Every
   action — applied or dry-run — is an fsync'd `controller_action`
   incident, a `controller/actions` / `controller/reverts` registry
   count, a `health.note_external('controller_<actuator>')` ledger
   entry (applied moves only — so drain manifests and halt bundles
   name what the controller did, like slo_violation incidents), and a
   row in `CONTROLLER_LOG.json`.

4. **Dry-run** (`--controller=observe`, the default): the controller
   evaluates the full policy, logs every move it WOULD make
   (`applied: false`, tracked against a virtual actuator value so the
   simulated sequence is faithful), and touches nothing — the
   zero-risk mode an operator reads before opting into `act`.
   `--controller=off` removes the thread and the log entirely.

The acceptance drill is `scripts/chaos.py run_controller_storm`:
offered load doubles mid-run, the actuated run's SLO_VERDICT.json
stays green with the escalation and the later revert in the action
log, and the same storm under `observe` records the violation the
actuated run avoided. Cost: bench.py's `controller` stage prices the
tick.

No jax imports here (the slo.py rule): the controller must be
importable by scripts and tests without accelerator initialization.
"""

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from scalable_agent_tpu import slo as slo_lib
from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock

log = logging.getLogger('scalable_agent_tpu')

MODES = ('off', 'observe', 'act')

# The actuator names a policy table may reference — the static half of
# the contract scripts/ci.sh lints (a rule over an actuator nobody
# registers is a typo, not a topology gap; topology gaps are the
# KNOWN names the driver legitimately skipped, logged at spin-up).
KNOWN_ACTUATORS = ('replay_k', 'admission', 'publish_secs',
                   'fleet_size', 'pod_size')

ACTUATOR_KINDS = ('int', 'float', 'enum')


class Actuator:
  """One bounded, thread-safe knob the controller may move.

  Args:
    name: registry name (one of KNOWN_ACTUATORS for the shipped
      rules; tests may register others).
    kind: 'int' | 'float' (numeric, stepped within [minimum, maximum])
      or 'enum' (moved to a rule's `to` value, one of `values`).
    get_fn / set_fn: the owner's thread-safe read/write seam. set_fn
      is only called in act mode; a raise is caught and recorded as an
      unapplied action, never propagated into the controller thread.
    minimum / maximum: hard clamp for numeric kinds (the bounded-move
      guarantee — the controller can NEVER push a knob outside the
      range the driver registered).
    values: legal states for enum kinds.
  """

  def __init__(self, name: str, kind: str, get_fn: Callable,
               set_fn: Callable, minimum: Optional[float] = None,
               maximum: Optional[float] = None,
               values: Optional[tuple] = None):
    if kind not in ACTUATOR_KINDS:
      raise ValueError(f'actuator {name!r}: kind must be one of '
                       f'{ACTUATOR_KINDS}, got {kind!r}')
    if kind == 'enum':
      if not values:
        raise ValueError(f'enum actuator {name!r} needs values')
    elif minimum is None or maximum is None or minimum > maximum:
      raise ValueError(f'numeric actuator {name!r} needs '
                       f'minimum <= maximum, got [{minimum}, '
                       f'{maximum}]')
    self.name = name
    self.kind = kind
    self.get_fn = get_fn
    self.set_fn = set_fn
    self.minimum = minimum
    self.maximum = maximum
    self.values = tuple(values) if values else ()

  def clamp(self, value):
    if self.kind == 'enum':
      return value
    value = min(max(value, self.minimum), self.maximum)
    return int(round(value)) if self.kind == 'int' else float(value)


@dataclasses.dataclass(frozen=True)
class Rule:
  """One policy-table row: objective → bounded actuator move.

  Args:
    objective: the SLO objective name watched (must exist in the
      engine's loaded set; unknown names are dropped with a warning —
      a custom --slo_spec legitimately renames objectives).
    actuator: the actuator moved (must be a KNOWN_ACTUATORS name).
    direction: 'up' | 'down' — which bound a numeric escalation steps
      toward. Ignored for enum actuators.
    step: numeric escalation step size (and the revert step back
      toward the baseline).
    to: enum escalation target (enum actuators only).
    revert_to: enum revert target; None = the value at first move.
    trigger_margin: escalate when the objective's margin (signed
      headroom; positive = inside the objective) is <= this, even
      before it burns — the leading-edge trigger that lets the
      controller keep a page objective from ever failing the verdict.
      None = escalate on burning only.
    clear_margin: revert only once state is OK and margin >= this.
      The [trigger_margin, clear_margin] gap IS the hysteresis band.
    cooldown_secs: minimum seconds between this rule's moves.
    description: one line for the log/docs.
  """
  objective: str
  actuator: str
  direction: str = 'up'
  step: float = 1.0
  to: Optional[str] = None
  revert_to: Optional[str] = None
  trigger_margin: Optional[float] = None
  clear_margin: float = 0.0
  cooldown_secs: float = 30.0
  description: str = ''

  def validate(self):
    if self.actuator not in KNOWN_ACTUATORS:
      raise ValueError(
          f'rule for {self.objective!r}: unknown actuator '
          f'{self.actuator!r} (known: {KNOWN_ACTUATORS})')
    if self.direction not in ('up', 'down'):
      raise ValueError(f'rule for {self.objective!r}: direction must '
                       f'be up|down, got {self.direction!r}')
    if self.step <= 0:
      raise ValueError(f'rule for {self.objective!r}: step must be '
                       f'> 0, got {self.step}')
    if self.cooldown_secs < 0:
      raise ValueError(f'rule for {self.objective!r}: cooldown_secs '
                       f'must be >= 0, got {self.cooldown_secs}')
    if (self.trigger_margin is not None
        and self.clear_margin < self.trigger_margin):
      raise ValueError(
          f'rule for {self.objective!r}: clear_margin '
          f'({self.clear_margin}) must be >= trigger_margin '
          f'({self.trigger_margin}) — the gap is the hysteresis band '
          'that keeps a hovering metric from flapping the knob')
    return self


# The shipped mapping — the ROADMAP item 5 playbook as literals (the
# ci.sh lint checks every objective= here against
# slo.DEFAULT_OBJECTIVES by name, and every actuator= against
# KNOWN_ACTUATORS). Cool-downs are deliberately long: production
# planes move in minutes; chaos/tests pass their own table.
DEFAULT_RULES = (
    # Env plane is the bound (the learner mostly parked on the feed):
    # IMPACT says staleness tolerance rises under the clipped-target
    # surrogate — re-serve staged batches instead of idling
    # (arXiv 1912.00167; the replay_k bench rows priced this).
    Rule(objective='learner_plane_utilization', actuator='replay_k',
         direction='up', step=1, cooldown_secs=120.0,
         clear_margin=0.2,
         description='learner starved by the env plane: raise '
                     'replay_k (IMPACT sample reuse)'),
    # Overload burn: unroll end-to-end latency past its objective
    # means admissions parked behind a saturated serving plane —
    # blocking converts overload into latency; shedding converts it
    # into counted, bounded rejections (PR 6's intended response).
    Rule(objective='unroll_e2e_p99_ms', actuator='admission',
         to='shed', revert_to='block', cooldown_secs=120.0,
         clear_margin=10000.0,
         description='overload burn: flip admission block->shed'),
    # Transport pressure: ack service time climbing means the ingest/
    # publish path is contended — stretch the remote publish cadence
    # (each publish is a whole-tree device_get + fleet fan-out).
    Rule(objective='ingest_ack_p99_ms', actuator='publish_secs',
         direction='up', step=2.0, cooldown_secs=120.0,
         clear_margin=2000.0,
         description='transport pressure: stretch the remote publish '
                     'cadence'),
    # Thinning quorum: grow the fleet — unpark parked slots, then
    # rehabilitate quarantined ones through the probation ladder (the
    # PR 8 respawn/re-attach machinery as the add primitive). The
    # trigger margin acts BEFORE the page objective burns.
    Rule(objective='fleet_healthy_fraction', actuator='fleet_size',
         direction='up', step=1, trigger_margin=0.25,
         clear_margin=0.5, cooldown_secs=60.0,
         description='thinning quorum: grow the fleet '
                     '(unpark/rehabilitate slots)'),
    # Dead env plane (producers parked on backpressure the whole
    # window): the learner is the bound and the offered load is pure
    # queueing — shed it by parking slots (PAL's shrink direction).
    Rule(objective='env_plane_utilization', actuator='fleet_size',
         direction='down', step=1, cooldown_secs=180.0,
         clear_margin=0.05,
         description='producers fully parked: shrink the fleet'),
    # Elastic pod membership (round 20): the pod-level analogues of
    # the two fleet_size rules. pod_size is DECLARATIVE — the
    # actuator publishes the desired host count to POD_TARGET.json
    # (process 0 owns it, per-actuator-ownership) and the cluster
    # supervisor reconciles actual hosts toward it; the learner
    # never spawns or kills hosts itself. Registered only when
    # --pod_max_hosts > 0, so these rules drop with a spin-up log
    # line on fixed-topology runs (the KNOWN-name topology-gap path).
    Rule(objective='fleet_healthy_fraction', actuator='pod_size',
         direction='up', step=1, trigger_margin=0.25,
         clear_margin=0.5, cooldown_secs=120.0,
         description='thinning pod: request a replacement actor host '
                     '(POD_TARGET.json; supervisor reconciles)'),
    Rule(objective='env_plane_utilization', actuator='pod_size',
         direction='down', step=1, cooldown_secs=300.0,
         clear_margin=0.05,
         description='producers fully parked: request a smaller pod '
                     '(PAL shrink direction, arXiv 2110.01101)'),
    # Serving-plane overload (round 21): the multi-tenant serving
    # latency objective burning means the shared inference step is
    # saturated — by local batcher traffic, routed v10 batches, or
    # both. Same response as the unroll-latency rule and through the
    # SAME actuator (per-actuator ownership keeps the two rules from
    # fighting: whichever burns first holds the cooldown): shed
    # admissions instead of queueing them.
    Rule(objective='serving_latency_p99_ms', actuator='admission',
         to='shed', revert_to='block', cooldown_secs=120.0,
         clear_margin=10000.0,
         description='serving-plane overload: flip admission '
                     'block->shed'),
)


def load_rules(spec_path: str = '') -> List[Rule]:
  """The policy table: `spec_path` (a JSON list of Rule field dicts)
  when given, else DEFAULT_RULES. Raises on an unreadable/invalid
  spec — a typo'd policy must fail the run at spin-up, not silently
  control nothing (the --slo_spec rule)."""
  if spec_path:
    with open(spec_path) as f:
      raw = json.load(f)
    if not isinstance(raw, list) or not raw:
      raise ValueError(f'controller policy {spec_path!r} must be a '
                       'non-empty JSON list of rule dicts')
    rules = []
    for entry in raw:
      try:
        rules.append(Rule(**entry))
      except TypeError as e:
        raise ValueError(f'controller policy {spec_path!r}: bad rule '
                         f'entry {entry!r}: {e}') from e
  else:
    rules = list(DEFAULT_RULES)
  for rule in rules:
    rule.validate()
  return rules


class _RuleState:
  """Per-rule mutable controller state."""

  def __init__(self):
    self.engaged = False
    self.baseline = None        # actuator value at the first move
    self.virtual = None         # observe-mode simulated value
    self.last_action_time = float('-inf')
    self.escalations = 0
    self.reverts = 0


class Controller:
  """The verdict-to-actuation loop (module docstring).

  Args:
    engine: the SloEngine whose `control_snapshot()` supplies the
      burning set + margins (the locked round-15 API).
    rules: the policy table (load_rules()).
    actuators: the Actuator seams this run exposes; rules over
      actuators not in the list are dropped with a log line.
    logdir: where CONTROLLER_LOG.json lands.
    mode: 'observe' (dry-run; every move logged, nothing touched) or
      'act'.
    interval_secs: tick cadence of the controller thread; tick() is
      also directly callable (tests drive it with an injected clock —
      the loop is deterministic: no randomness, no hidden wall-clock
      reads beyond `now`).
    incidents / health: the EventLog + HealthMonitor emission seams
      (both optional; a missing seam just skips that emission).
    log_name: the action-log filename (multi-host runs suffix it).
  """

  # Lock discipline (round 18, guarded-by lint): the action log, the
  # per-actuator ownership table, and the drop counter mutate only
  # under _lock (tick/finalize hold it; the *_locked helpers run
  # inside). `_applied`/`_apply_errors` stay unannotated: counts()
  # documents its deliberate lock-free GIL-atomic reads.
  _actions: guarded_by('_lock')
  _owner: guarded_by('_lock')
  _dropped_actions: guarded_by('_lock')

  def __init__(self, engine, rules: List[Rule],
               actuators: List[Actuator], logdir: str,
               mode: str = 'observe', interval_secs: float = 5.0,
               incidents=None, health=None,
               log_name: str = 'CONTROLLER_LOG.json',
               max_log_actions: int = 2000):
    if mode not in ('observe', 'act'):
      raise ValueError(f"controller mode must be observe|act, got "
                       f'{mode!r} (off means: do not construct one)')
    self._engine = engine
    self._mode = mode
    self._logdir = logdir
    self._log_path = os.path.join(logdir, log_name)
    self._interval = max(float(interval_secs), 0.05)
    self._incidents = incidents
    self._health = health
    self._max_log_actions = int(max_log_actions)
    self._actuators: Dict[str, Actuator] = {a.name: a
                                            for a in actuators}
    objective_names = set(engine.control_snapshot())
    self._rules: List[Rule] = []
    for rule in rules:
      rule.validate()
      act = self._actuators.get(rule.actuator)
      if act is None:
        log.info('controller: dropping rule %s->%s (actuator not '
                 'exposed by this topology)', rule.objective,
                 rule.actuator)
        continue
      # Enum rules fail at SPIN-UP like every other policy typo: a
      # rule with no `to` would silently never fire, and an invalid
      # `to`/`revert_to` would burn an apply error on every cool-down.
      if act.kind == 'enum':
        if rule.to is None:
          raise ValueError(
              f'rule {rule.objective}->{rule.actuator}: enum '
              f'actuator needs a `to` target (one of {act.values})')
        for label, value in (('to', rule.to),
                             ('revert_to', rule.revert_to)):
          if value is not None and value not in act.values:
            raise ValueError(
                f'rule {rule.objective}->{rule.actuator}: {label}='
                f'{value!r} is not a legal state (one of '
                f'{act.values})')
      if rule.objective not in objective_names:
        log.warning('controller: dropping rule %s->%s (objective not '
                    'in the loaded SLO set)', rule.objective,
                    rule.actuator)
        continue
      self._rules.append(rule)
    self._state = [_RuleState() for _ in self._rules]
    # Per-actuator arbitration: at most ONE engaged rule owns a knob
    # at a time (first engaged wins, in table order) — two rules over
    # the same actuator (the shipped grow/shrink fleet_size pair)
    # must not see-saw it, each revert undoing the other's move.
    self._owner: Dict[str, _RuleState] = {}
    self._lock = make_lock('controller._lock')
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._actions: List[Dict] = []
    self._dropped_actions = 0
    self._applied = 0
    self._apply_errors = 0
    # Registry view (literal names — the ci.sh lint contract). The
    # counters stay registered (cumulative, like slo/violations); the
    # fn-gauge closes over this per-run instance and is unregistered
    # at stop().
    self._m_actions = telemetry.counter('controller/actions')
    self._m_reverts = telemetry.counter('controller/reverts')
    self._g_engaged = telemetry.gauge(
        'controller/engaged', fn=lambda: self.engaged_rules())

  # --- lifecycle ---

  @property
  def mode(self) -> str:
    return self._mode

  def start(self):
    self._thread = threading.Thread(target=self._loop,
                                    name='controller', daemon=True)
    self._thread.start()

  def _loop(self):
    while not self._stop.wait(self._interval):
      try:
        self.tick()
      except Exception:  # pragma: no cover - must never kill the run
        log.exception('controller tick failed')

  def stop(self):
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None
    telemetry.registry().unregister(self._g_engaged.name,
                                    self._g_engaged)

  # --- the loop body ---

  def _current(self, rule: Rule, rs: _RuleState, act: Actuator):
    """The decision-time actuator value: the real knob in act mode;
    the simulated one in observe mode (so a dry run logs the faithful
    escalate→bound→revert sequence instead of re-proposing the same
    first step forever)."""
    if self._mode == 'observe' and rs.virtual is not None:
      return rs.virtual
    try:
      return act.get_fn()
    except Exception:
      log.exception('controller: actuator %r get failed', act.name)
      return None

  def _escalated(self, rule: Rule, act: Actuator, cur):
    if act.kind == 'enum':
      return rule.to if cur != rule.to else None
    delta = rule.step if rule.direction == 'up' else -rule.step
    desired = act.clamp(cur + delta)
    return desired if desired != cur else None

  def _reverted(self, rule: Rule, act: Actuator, cur, baseline):
    if act.kind == 'enum':
      target = rule.revert_to if rule.revert_to is not None \
          else baseline
      return (target, True) if cur != target else (None, True)
    target = baseline if baseline is not None else cur
    if cur == target:
      return None, True
    step = rule.step if cur < target else -rule.step
    desired = act.clamp(cur + step)
    # Never overshoot the baseline on the way back.
    if (cur < target and desired > target) or \
       (cur > target and desired < target):
      desired = act.clamp(target)
    return desired, desired == act.clamp(target)

  def tick(self, now: Optional[float] = None) -> List[Dict]:
    """One control pass; returns the actions taken (tests drive this
    directly with an injected `now` — the pass is deterministic)."""
    now = time.time() if now is None else float(now)
    snapshot = self._engine.control_snapshot()
    taken: List[Dict] = []
    with self._lock:
      for rule, rs in zip(self._rules, self._state):
        entry = snapshot.get(rule.objective)
        if entry is None:
          continue
        state = entry.get('state')
        margin = entry.get('margin')
        if state in (slo_lib.NO_DATA, slo_lib.NO_BASELINE):
          continue  # blind is not a reason to move a knob
        act = self._actuators[rule.actuator]
        burning = state == slo_lib.BURNING
        pressured = (rule.trigger_margin is not None
                     and margin is not None
                     and margin <= rule.trigger_margin)
        if burning or pressured:
          owner = self._owner.get(rule.actuator)
          if owner is not None and owner is not rs:
            continue  # another rule holds this knob: hold, don't fight
          if now - rs.last_action_time < rule.cooldown_secs:
            continue  # hold: the last move gets its cool-down
          cur = self._current(rule, rs, act)
          if cur is None:
            continue
          desired = self._escalated(rule, act, cur)
          if desired is None:
            continue  # at the bound: holding is the action
          if not rs.engaged:
            rs.engaged = True
            rs.baseline = cur
            self._owner[rule.actuator] = rs
          rs.escalations += 1
          taken.append(self._do_action_locked(now, 'escalate', rule, rs,
                                       act, cur, desired, entry))
        elif rs.engaged:
          clear = (state == slo_lib.OK
                   and (margin is None
                        or margin >= rule.clear_margin))
          if not clear:
            continue  # hysteresis: recovered-but-thin holds the knob
          if now - rs.last_action_time < rule.cooldown_secs:
            continue
          cur = self._current(rule, rs, act)
          if cur is None:
            continue
          desired, done = self._reverted(rule, act, cur, rs.baseline)
          if desired is None:
            self._disengage_locked(rule, rs)
            continue
          rs.reverts += 1
          if done:
            self._disengage_locked(rule, rs)
          taken.append(self._do_action_locked(now, 'revert', rule, rs, act,
                                       cur, desired, entry))
    return taken

  def _disengage_locked(self, rule: Rule, rs: _RuleState):
    rs.engaged = False
    if self._owner.get(rule.actuator) is rs:
      del self._owner[rule.actuator]

  def _do_action_locked(self, now, kind, rule: Rule, rs: _RuleState,
                 act: Actuator, cur, desired, entry) -> Dict:
    """Apply (act mode) + record one move. Called with the lock held;
    the actuator set and the emissions are exception-guarded — a
    failing knob or a sick disk costs the action, never the thread."""
    applied = False
    error = None
    if self._mode == 'act':
      try:
        act.set_fn(desired)
        applied = True
        self._applied += 1
      except Exception as e:
        self._apply_errors += 1
        error = f'{type(e).__name__}: {e}'
        log.exception('controller: actuator %r set(%r) failed',
                      act.name, desired)
    rs.virtual = desired
    rs.last_action_time = now
    action = {
        'wall_time': round(now, 3),
        'kind': kind,
        'mode': self._mode,
        'objective': rule.objective,
        'actuator': act.name,
        'from': cur,
        'to': desired,
        'applied': applied,
        'state': entry.get('state'),
        'value': entry.get('value'),
        'margin': entry.get('margin'),
    }
    if error is not None:
      action['error'] = error
    if len(self._actions) < self._max_log_actions:
      self._actions.append(action)
    else:
      self._dropped_actions += 1  # no silent caps: counted + logged
    self._m_actions.inc()
    if kind == 'revert':
      self._m_reverts.inc()
    (log.warning if self._mode == 'act' else log.info)(
        'controller %s [%s]: %s %s: %s -> %s (objective %s state=%s '
        'margin=%s)', kind, self._mode,
        'APPLIED' if applied else 'dry-run', act.name, cur, desired,
        rule.objective, entry.get('state'), entry.get('margin'))
    try:
      if self._incidents is not None:
        # 'kind' is the EventLog's own field — the move's own kind
        # rides as 'action'.
        self._incidents.event('controller_action', **{
            ('action' if k == 'kind' else k): v
            for k, v in action.items() if k != 'wall_time'})
      if applied and self._health is not None:
        # The external-incident ledger: controller moves ride drain
        # manifests and halt bundles exactly like slo_<name> burns.
        self._health.note_external(f'controller_{act.name}')
      self._write_log_locked()
    except Exception:
      log.exception('controller action emission failed')
    return action

  # --- the log + counters surface ---

  def _write_log_locked(self):
    """Atomic CONTROLLER_LOG.json rewrite (tmp + rename, the verdict
    pattern): the log is either complete or the previous complete
    version — a postmortem never reads a half-written row."""
    payload = {
        'mode': self._mode,
        'rules': [dataclasses.asdict(r) for r in self._rules],
        'actions': self._actions,
        'dropped_actions': self._dropped_actions,
        'counts': self.counts(),
        'wall_time': round(time.time(), 3),
    }
    tmp = self._log_path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(payload, f, indent=2, default=str)
    os.replace(tmp, self._log_path)

  def engaged_rules(self) -> int:
    with self._lock:
      return sum(1 for rs in self._state if rs.engaged)

  def counts(self) -> Dict[str, int]:
    # Lock-free: every field is a GIL-atomic read of ints the locked
    # sections maintain; callers (summary block, log writer under the
    # lock) tolerate one-action staleness.
    escalations = sum(rs.escalations for rs in self._state)
    reverts = sum(rs.reverts for rs in self._state)
    return {
        'actions': escalations + reverts,
        'escalations': escalations,
        'reverts': reverts,
        'applied': self._applied,
        'apply_errors': self._apply_errors,
    }

  def actions(self) -> List[Dict]:
    with self._lock:
      return [dict(a) for a in self._actions]

  def finalize(self) -> Dict:
    """Final CONTROLLER_LOG.json write; returns the counts summary
    (driver's finally — written on every exit path, like the SLO
    verdict)."""
    with self._lock:
      try:
        self._write_log_locked()
      except Exception:
        log.exception('controller log finalize failed')
      return self.counts()


def read_log(logdir: str) -> Optional[Dict]:
  """The run's CONTROLLER_LOG.json, or None (chaos/soak consume)."""
  try:
    with open(os.path.join(logdir, 'CONTROLLER_LOG.json')) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None
