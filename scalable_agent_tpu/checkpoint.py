"""Checkpoint / resume (Orbax-backed).

Reference semantics (reference: experiment.py ≈L570
`MonitoredTrainingSession(checkpoint_dir=logdir, save_checkpoint_secs=600)`;
SURVEY §5.4): periodically save ALL global state — network params,
optimizer slots, and the environment-frame counter — and restore the
latest on startup. Actor-local state (LSTM carries, env state) is
intentionally NOT checkpointed: unrolls straddling a restart are lost,
exactly as upstream.

The TPU build checkpoints the whole `learner.TrainState` pytree
(params, opt_state, update_steps) via Orbax. `update_steps` × frames
per step reproduces the reference's `num_environment_frames` global
step. Sharded (multi-chip) states round-trip: Orbax records shardings
and restores to the same placements when given the live state as the
abstract target.
"""

import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

import jax

import orbax.checkpoint as ocp

from scalable_agent_tpu import integrity
from scalable_agent_tpu.learner import TrainState
from scalable_agent_tpu.runtime import faults as faults_lib

log = logging.getLogger('scalable_agent_tpu')


class CheckpointStructureError(ValueError):
  """The latest checkpoint's tree structure does not match the state
  built from the current config (see the message for likely flags)."""


class CheckpointCorruption(RuntimeError):
  """A retained step's on-disk CONTENT does not match the digests its
  verified save recorded (round 12): bit rot after commit. Orbax's own
  restore only catches partial/structural damage — a flipped byte
  inside an array file restores 'successfully' as garbage params. The
  restore ladder classifies this as per-step corruption (falls back
  to the previous retained step), never as a config mismatch.

  The message deliberately avoids every _STRUCTURE_MARKERS phrase so
  `_looks_structural` routes it down the corruption arm."""


# Markers Orbax puts in tree-STRUCTURE mismatch messages (vs corrupt/
# partial files, missing arrays, I/O errors): only these earn the
# config-flag guidance — flag advice on a genuinely corrupt checkpoint
# sends operators down the wrong path (ADVICE r3). Deliberately
# NARROW: generic words like 'missing'/'key'/'mismatch' also appear in
# partial-save messages ('missing commit file', 'checksum mismatch'),
# which must get the corruption wording. 'dict key mismatch' is the
# newer-Orbax spelling of the restore-target/on-disk tree diff
# (jax tree_util raises it before any file is read).
_STRUCTURE_MARKERS = (
    'structure', 'tree', 'pytree', 'not found in checkpoint',
    'do not match', 'dict key mismatch')


def _looks_structural(e) -> bool:
  """Whether a restore failure looks like a tree-STRUCTURE mismatch
  (config-flag guidance, no fallback — older steps share the config)
  rather than corrupt/partial files (corruption guidance, and the
  restore ladder retries the previous retained step). KeyError is
  structural by TYPE (its str is just the missing key, which need not
  contain any marker) — EXCEPT Orbax's missing-ITEM KeyError ('Item
  "default" was not found ... Available items: []'), which means the
  step directory lost its payload (partial save/eviction): that is
  per-step damage the ladder must fall back past, not a config
  mismatch."""
  msg = str(e).lower()
  if isinstance(e, KeyError):
    return 'available items' not in msg
  return any(marker in msg for marker in _STRUCTURE_MARKERS)


def _wrap_structure_error(e, directory, step):
  """Re-raise a restore failure with the likely config-flag causes.

  The agent's param-tree STRUCTURE is a function of the config
  (VERDICT r2 W7): the raw Orbax mismatch error names neither the flag
  nor the fix, so operators hitting the documented migration footgun
  (`config.use_instruction` None-auto) got a dead end. The message is
  sniffed first so non-structural failures (corrupt/partial files)
  don't get misleading flag advice."""
  base = (f'could not restore checkpoint step {step} from {directory}: '
          f'{e}\n')
  if _looks_structural(e):
    guidance = (
        'This looks like a tree-structure mismatch: the param tree is '
        'a function of the config. Usual cause: --use_instruction '
        '(default None = auto by level name — a checkpoint trained '
        'with the instruction encoder needs an explicit '
        '--use_instruction=true when resumed/evaluated on a '
        'non-language level, and vice versa). Also structure-changing: '
        '--torso, --use_popart, --pixel_control_cost. Compare your '
        "flags against the run's config.json saved next to the "
        'checkpoints.')
  else:
    guidance = (
        'This does not look like a tree-structure mismatch — the '
        'checkpoint files may be corrupt or partially written (e.g. a '
        'save interrupted mid-write). Try the previous retained step, '
        'or if the config might have changed, compare your flags '
        "against the run's config.json saved next to the checkpoints.")
  raise CheckpointStructureError(base + guidance) from e


class Checkpointer:
  """Thin lifecycle wrapper over an Orbax CheckpointManager.

  Args:
    directory: checkpoint root (the reference's --logdir).
    max_to_keep: retained checkpoints (oldest pruned).
    save_interval_secs: wall-clock throttle — `maybe_save` is a no-op
      until this many seconds passed since the last save (reference
      save_checkpoint_secs=600).
  """

  def __init__(self, directory: str, max_to_keep: int = 3,
               save_interval_secs: float = 600.0,
               verify_digests: bool = True,
               registry=None, mesh=None):
    # Sharding registry + mesh (round 19, parallel/sharding.py): when
    # provided, every verified save also records the REGISTRY's view
    # of the param placements (SHARDING_{step}.json — rule set, the
    # {path: spec} manifest, its content digest), and restores warn
    # when the on-disk manifest disagrees with what this run would
    # resolve — the checkpoint plane's sharding truth is the same
    # single source as the learner's, and the manifest is the on-disk
    # half of cross-topology resharding (ROADMAP item 3; see
    # `registry_restore_targets`).
    self._registry = registry
    self._mesh = mesh
    self._directory = os.path.abspath(directory)
    os.makedirs(self._directory, exist_ok=True)
    self._manager = ocp.CheckpointManager(
        self._directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True))
    self._save_interval_secs = save_interval_secs
    self._last_save_time: Optional[float] = None
    self._last_good_path = os.path.join(self._directory, 'LAST_GOOD')
    # Content-digest ledger (round 12; config.ckpt_digests): verified
    # saves record a per-file CRC of the committed step; the restore
    # ladder re-verifies before trusting a step, extending the PR 2
    # fallback ladder from partial/structural damage to BIT ROT —
    # orbax restores a flipped byte inside an array file
    # 'successfully', as garbage params.
    self._verify_digests = bool(verify_digests)
    # Integrity-ladder observability (driver summaries + tests).
    self.save_errors = 0
    self.last_save_error: Optional[BaseException] = None
    self.restore_fallbacks = 0
    # Steps the ladder refused specifically for digest (bit-rot)
    # mismatches — counted separately from structural/partial
    # fallbacks so summaries can alarm on silent disk corruption.
    self.digest_fallbacks = 0
    # Unified-registry view (round 13, telemetry.py): lazy gauges over
    # the ladder counters — same numbers as the driver summaries, read
    # by the drain manifest / flight recorder / remote 'stats' from
    # one source of truth.
    from scalable_agent_tpu import telemetry
    self._gauges = [
        telemetry.gauge('checkpoint/save_errors',
                        fn=lambda: self.save_errors),
        telemetry.gauge('checkpoint/restore_fallbacks',
                        fn=lambda: self.restore_fallbacks),
        telemetry.gauge('checkpoint/digest_fallbacks',
                        fn=lambda: self.digest_fallbacks),
    ]

  def save(self, state: TrainState, step: Optional[int] = None,
           force: bool = False) -> bool:
    """Save now and VERIFY completion. `step` defaults to the state's
    own update counter.

    Returns whether a checkpoint was written and finalized. A step
    that already exists is skipped (returns False, even with
    force=True — Orbax raises StepAlreadyExistsError rather than
    overwriting); the throttle clock only resets on a real write so
    `maybe_save` stays truthful.

    This blocks on `wait_until_finished` so save-side errors surface
    HERE (logged + recorded on `save_errors`/`last_save_error`)
    instead of getting lost until close(); a failed save does not
    raise — older retained steps still cover a restore, which is the
    integrity ladder's whole point. Only a save that completed without
    error advances the LAST_GOOD marker, so 'restorable' and 'newest'
    stay distinguishable (restore_last_good reads the marker)."""
    if step is None:
      step = int(jax.device_get(state.update_steps))
    if step in self._manager.all_steps():
      return False  # force=True raises StepAlreadyExistsError otherwise
    saved = bool(self._manager.save(
        step, args=ocp.args.StandardSave(state), force=force))
    if not saved:
      return False
    # Fault-injection site (runtime/faults.py 'checkpoint_save'): a
    # fired fault simulates the process dying mid-write — the step's
    # files are damaged on disk and the marker does NOT advance.
    fault = faults_lib.fire('checkpoint_save')
    try:
      self._manager.wait_until_finished()
    except Exception as e:
      # Throttle clock deliberately NOT reset on this path: the next
      # maybe_save retries immediately instead of training another
      # full save_interval_secs with no checkpoint after a transient
      # storage blip.
      self.save_errors += 1
      self.last_save_error = e
      log.exception(
          'checkpoint save at step %d FAILED to finalize (marker not '
          'advanced; older retained steps remain restorable)', step)
      return False
    self._last_save_time = time.monotonic()
    if fault is not None:
      damaged = faults_lib.corrupt_checkpoint_step(self._directory,
                                                   step)
      self.save_errors += 1
      self.last_save_error = faults_lib.InjectedFault(
          f'checkpoint_save interrupted at step {step}')
      log.warning('injected checkpoint-save interrupt at step %d '
                  '(%d files damaged, LAST_GOOD not advanced)', step,
                  len(damaged))
      return True
    digests = self._record_digests(step)
    self._record_sharding_manifest(step, state)
    self._mark_last_good(step, digests)
    # Fault site 'ckpt_bitrot' (round 12): flip one byte in a file of
    # the step JUST committed — AFTER its digests were recorded and
    # LAST_GOOD advanced. Every marker now calls this step good; only
    # the restore ladder's digest verification can catch it.
    rot = faults_lib.fire('ckpt_bitrot')
    if rot is not None:
      plan = faults_lib.active()
      faults_lib.bitrot_checkpoint_step(
          self._directory, step, seed=plan.seed if plan else 0)
    return True

  # --- content-digest ledger (round 12) ---

  def _digest_path(self, step: int) -> str:
    return os.path.join(self._directory, f'DIGEST_{int(step)}.json')

  def _step_dir(self, step: int) -> Optional[str]:
    """The on-disk directory of a retained step (orbax lays steps out
    as '<step>' or '<prefix>.<step>' depending on version)."""
    for name in os.listdir(self._directory):
      path = os.path.join(self._directory, name)
      if os.path.isdir(path) and (name == str(step)
                                  or name.split('.')[-1] == str(step)):
        return path
    return None

  def _record_digests(self, step: int) -> Optional[Dict]:
    """Digest every file of a just-verified step and persist the
    ledger (atomic, process 0). Returns the digest dict (also embedded
    in the LAST_GOOD manifest). Best-effort: a digest failure must
    not fail the save — it only costs bit-rot coverage for this
    step."""
    if not self._verify_digests:
      return None
    if jax.process_index() != 0:
      # Only process 0 writes the ledger (and the LAST_GOOD manifest
      # that embeds it) — the other hosts must not re-read and
      # checksum the whole multi-GB step from shared storage for a
      # result nothing consumes.
      return None
    try:
      step_dir = self._step_dir(step)
      if step_dir is None:
        return None
      digests = {}
      for root, _, files in os.walk(step_dir):
        for fname in files:
          fpath = os.path.join(root, fname)
          rel = os.path.relpath(fpath, step_dir)
          digests[rel] = integrity.digest_record(
              integrity.file_digest(fpath))
      if jax.process_index() == 0:
        tmp = self._digest_path(step) + '.tmp'
        with open(tmp, 'w') as f:
          json.dump({'step': int(step), 'algo': integrity.CRC_ALGO,
                     'files': digests}, f)
        os.replace(tmp, self._digest_path(step))
        self._prune_digests()
      return digests
    except OSError:
      log.exception('could not record content digests for step %d '
                    '(bit-rot coverage lost for this step)', step)
      return None

  def _prune_digests(self) -> None:
    """Drop digest/sharding ledgers of steps no longer retained."""
    retained = {str(int(s)) for s in self._manager.all_steps()}
    for name in os.listdir(self._directory):
      for prefix in ('DIGEST_', 'SHARDING_'):
        if not (name.startswith(prefix) and name.endswith('.json')):
          continue
        if name[len(prefix):-len('.json')] not in retained:
          try:
            os.remove(os.path.join(self._directory, name))
          except OSError:
            pass

  # --- sharding manifest (round 19, parallel/sharding.py) ---

  def _sharding_path(self, step: int) -> str:
    return os.path.join(self._directory, f'SHARDING_{int(step)}.json')

  def _record_sharding_manifest(self, step: int, state) -> None:
    """Record the registry's {param_path: spec} view of this save
    (process 0, atomic). Best-effort like the digest ledger: a
    manifest failure must not fail the save — it only costs drift
    detection for this step."""
    if self._registry is None or jax.process_index() != 0:
      return
    try:
      specs = self._registry.describe(state.params, self._mesh)
      mesh_shape = (dict(self._mesh.shape)
                    if self._mesh is not None else None)
      payload = {
          'step': int(step),
          'rule_set': self._registry.rule_set,
          'mesh': mesh_shape,
          'specs': specs,
          'digest': integrity.digest_record(
              integrity.spec_table_digest(specs)),
      }
      tmp = self._sharding_path(step) + '.tmp'
      with open(tmp, 'w') as f:
        json.dump(payload, f, indent=1)
      os.replace(tmp, self._sharding_path(step))
    except (OSError, TypeError, ValueError):
      log.exception('could not record sharding manifest for step %d '
                    '(resharding drift detection lost for this step)',
                    step)

  def read_sharding_manifest(self, step: int) -> Optional[Dict]:
    """The recorded sharding manifest of a retained step, or None."""
    try:
      with open(self._sharding_path(step)) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  def _warn_sharding_drift(self, step: int, restored) -> None:
    """Compare the restored step's recorded manifest against what THIS
    run's registry resolves; a mismatch means the checkpoint was laid
    out under different rules/topology. The restore itself is still
    correct — Orbax resharded into the pinned targets — so this warns
    rather than raises; it is the observability half of cross-topology
    resharding."""
    if self._registry is None or restored is None:
      return
    manifest = self.read_sharding_manifest(step)
    if manifest is None:
      return
    try:
      current = self._registry.describe(restored.params, self._mesh)
    except Exception:
      log.exception('sharding drift check failed for step %d', step)
      return
    recorded = manifest.get('specs', {})
    if recorded == current:
      return
    changed = sorted(
        set(recorded.items()) ^ set(current.items()))
    log.warning(
        'checkpoint step %d was saved under sharding rule set %r '
        '(mesh %s) but this run resolves %r — %d spec(s) differ '
        '(first: %s); Orbax resharded into the live placements, '
        'training continues on the new layout',
        step, manifest.get('rule_set'), manifest.get('mesh'),
        self._registry.rule_set, len(changed) // 2 + len(changed) % 2,
        changed[0] if changed else '?')

  def verify_step_digests(self, step: int) -> Optional[bool]:
    """Re-digest a retained step against its recorded ledger.

    Returns True (verified), None (no ledger / foreign algorithm —
    verification SKIPPED, logged), or raises CheckpointCorruption
    naming the first rotted file. A recorded file that has gone
    MISSING is corruption too (partial eviction under the marker)."""
    if not self._verify_digests:
      return None
    try:
      with open(self._digest_path(step)) as f:
        ledger = json.load(f)
    except (OSError, ValueError):
      return None  # pre-round-12 step (or foreign writer): no ledger
    files = ledger.get('files')
    if not isinstance(files, dict):
      return None
    step_dir = self._step_dir(step)
    if step_dir is None:
      raise CheckpointCorruption(
          f'checkpoint step {step} has a digest ledger but no step '
          'directory on disk')
    for rel, record in sorted(files.items()):
      fpath = os.path.join(step_dir, rel)
      try:
        value = integrity.file_digest(fpath)
      except OSError as e:
        raise CheckpointCorruption(
            f'checkpoint step {step}: recorded file {rel!r} is '
            f'unreadable ({e}) — content verification failed')
      verdict = integrity.verify_record(record, value)
      if verdict is None:
        log.warning(
            'checkpoint step %d: digest for %r recorded with a '
            'different algorithm (%r vs local %s) — content '
            'verification skipped', step, rel, record,
            integrity.CRC_ALGO)
        return None
      if not verdict:
        raise CheckpointCorruption(
            f'checkpoint step {step}: content digest verification '
            f'failed for {rel!r} (crc {value:08x} differs from the '
            f'recorded {int(record["crc"]):08x}) — bit rot after '
            'commit; this step cannot be trusted')
    return True

  def _mark_last_good(self, step: int,
                      digests: Optional[Dict] = None) -> None:
    """Atomically advance the LAST_GOOD marker (tmp + rename): only a
    save that verifiably finished earns it. Multi-host: process 0
    writes (shared checkpoint dirs must have one writer — same
    convention as the driver's config.json). The verified save's
    content digests ride the manifest (round 12), so the marker names
    not just WHICH step is good but what its bytes looked like when
    it earned the name."""
    if jax.process_index() != 0:
      return
    tmp = self._last_good_path + '.tmp'
    try:
      manifest = {'step': int(step),
                  'wall_time': round(time.time(), 3)}
      if digests is not None:
        manifest['digest_algo'] = integrity.CRC_ALGO
        manifest['digests'] = digests
      with open(tmp, 'w') as f:
        json.dump(manifest, f)
      os.replace(tmp, self._last_good_path)
    except OSError:
      log.exception('could not write LAST_GOOD marker for step %d',
                    step)

  def last_good_step(self) -> Optional[int]:
    """The step the LAST_GOOD marker names, if it is still retained
    (pruning can outrun the marker on long runs); None otherwise."""
    try:
      with open(self._last_good_path) as f:
        step = int(json.load(f)['step'])
    except (OSError, ValueError, KeyError, TypeError):
      return None
    return step if step in self._manager.all_steps() else None

  def should_save(self) -> bool:
    """Whether the save interval has elapsed (host-local wall clock).

    Multi-host callers MUST NOT act on this independently: clocks
    differ per host, Orbax saves are collective, and disagreeing hosts
    deadlock in the barrier sync. Broadcast process 0's decision
    (driver.train does) and pass it to `maybe_save(decision=...)`.
    The first call after construction starts the clock."""
    now = time.monotonic()
    if self._last_save_time is None:
      self._last_save_time = now
      return False
    return now - self._last_save_time >= self._save_interval_secs

  def maybe_save(self, state: TrainState, step: Optional[int] = None,
                 decision: Optional[bool] = None) -> bool:
    """Save iff the save interval elapsed (call freely from the learner
    loop), matching the reference's every-N-seconds hook. `decision`
    overrides the local clock (multi-host: broadcast from process 0)."""
    if decision is None:
      decision = self.should_save()
    if not decision:
      return False
    return self.save(state, step)

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def _restore_ladder(self, steps: List[int], restore_fn
                      ) -> Tuple[Optional[object], Optional[int]]:
    """Try `restore_fn(step)` down the given step list (newest first).

    The integrity ladder: a corrupt/partial step is logged and the
    previous retained step is tried (the dead-end `restore_latest`
    used to hit on a save interrupted mid-write); a STRUCTURE mismatch
    raises immediately with the config-flag guidance — older steps
    were written by the same config, so falling back cannot help and
    would only bury the real cause. Exhausting every step raises with
    the corruption guidance for the newest failure.

    Round 12: each rung first re-verifies the step's recorded content
    digests (`verify_step_digests`) — BIT ROT on a committed step
    restores 'successfully' through orbax as garbage params, so the
    ladder must refuse it before orbax ever reads it. Digest refusals
    are counted separately (`digest_fallbacks`)."""
    last_err: Optional[Tuple[int, BaseException]] = None
    for tried, step in enumerate(steps):
      try:
        self.verify_step_digests(step)
        restored = restore_fn(step)
      except Exception as e:
        if isinstance(e, CheckpointCorruption):
          self.digest_fallbacks += 1
        elif _looks_structural(e):
          _wrap_structure_error(e, self._directory, step)
        log.warning(
            'checkpoint step %d failed to restore (%s: %s); falling '
            'back to the previous retained step', step,
            type(e).__name__, e)
        if last_err is None:
          last_err = (step, e)
        continue
      if tried:
        self.restore_fallbacks += tried
        log.warning('restored checkpoint step %d after %d newer '
                    'corrupt/partial step(s)', step, tried)
      return restored, step
    _wrap_structure_error(last_err[1], self._directory, last_err[0])

  def restore_latest(self, target: TrainState) -> Optional[TrainState]:
    """Restore the most recent RESTORABLE checkpoint, or None if none
    exists. A corrupt/partial newest step falls back through older
    retained steps (see `_restore_ladder`).

    `target` is a concrete (or abstract shape/dtype/sharding) TrainState
    matching the saved structure — build it with `make_train_state` on
    the right mesh first; restored arrays land on the same placements.
    """
    steps = sorted(self._manager.all_steps(), reverse=True)
    if not steps:
      return None
    restored, step = self._restore_ladder(
        steps, self._make_full_restore_fn(target))
    self._warn_sharding_drift(step, restored)
    return restored

  def restore_last_good(self, target: TrainState
                        ) -> Optional[TrainState]:
    """Rollback restore (health.py's escalation ladder): the step the
    LAST_GOOD marker names first — 'known restorable', not merely
    'newest' — then every other retained step, newest first. None when
    nothing is restorable at all (the driver then halts)."""
    steps = sorted(self._manager.all_steps(), reverse=True)
    good = self.last_good_step()
    if good is not None:
      steps = [good] + [s for s in steps if s != good]
    if not steps:
      return None
    try:
      restored, step = self._restore_ladder(
          steps, self._make_full_restore_fn(target))
    except CheckpointStructureError:
      log.exception('rollback restore failed on every retained step')
      return None
    log.info('rolled back to checkpoint step %d', step)
    self._warn_sharding_drift(step, restored)
    return restored

  def rollback_step_choice(self) -> int:
    """The step a rollback SHOULD restore: last-known-good, else the
    newest retained, else -1 (nothing restorable). Multi-host rollback
    coordination: process 0's choice is broadcast and every host
    restores exactly that step via `restore_step` — the per-host
    ladder could diverge on host-local I/O errors, and a sharded
    restore is a cross-process collective that deadlocks if hosts
    enter it with different steps."""
    good = self.last_good_step()
    if good is not None:
      return good
    steps = self._manager.all_steps()
    return max(steps) if steps else -1

  def restore_step(self, step: int, target: TrainState) -> TrainState:
    """Single-step restore, NO ladder (the multi-host rollback path:
    every host must attempt the SAME step; a failure raises on all
    hosts together — the same exposure as the startup restore).
    Content digests still verify first: a bit-rotted rollback target
    must fail loudly on every host, not restore as garbage."""
    try:
      self.verify_step_digests(step)
      return self._make_full_restore_fn(target)(step)
    except Exception as e:
      _wrap_structure_error(e, self._directory, step)

  def _make_full_restore_fn(self, target: TrainState):
    def to_abstract(x):
      # Pin the TARGET's sharding so restored leaves land exactly on
      # its placements (mesh-sharded or single-device alike). An
      # already-abstract leaf carrying a sharding passes through
      # unchanged (registry_restore_targets builds those).
      if isinstance(x, jax.ShapeDtypeStruct):
        return x
      if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=x.sharding)
      return ocp.utils.to_shape_dtype_struct(x)

    abstract = jax.tree_util.tree_map(to_abstract, target)
    return lambda step: self._manager.restore(
        step, args=ocp.args.StandardRestore(abstract))

  def restore_latest_params(self, params, make_state):
    """Restore ONLY params (+ the update_steps counter) from the latest
    checkpoint; returns (params, update_steps) or None.

    Eval needs the policy weights, not the optimizer moments (≈2×
    params of dead HBM if restored). The full-state target is built
    only abstractly (`jax.eval_shape` over `make_state`) so the
    moments are never materialized, and every leaf outside
    params/update_steps restores as `ocp.PLACEHOLDER` — Orbax never
    reads it. Restored leaves land on `params`' own placements (Orbax
    requires explicit shardings when process_count > 1).

    Args:
      params: CONCRETE param pytree of jax.Arrays (init_params output);
        supplies both the tree structure and the target placements.
      make_state: params → TrainState (e.g. a make_train_state
        closure); evaluated under eval_shape only.
    """
    steps = sorted(self._manager.all_steps(), reverse=True)
    if not steps:
      return None

    abstract = jax.eval_shape(make_state, params)
    as_abstract = lambda c: jax.ShapeDtypeStruct(  # noqa: E731
        c.shape, c.dtype, sharding=c.sharding)
    dev_sharding = jax.tree_util.tree_leaves(params)[0].sharding
    if hasattr(ocp, 'PLACEHOLDER'):
      placeholder = lambda t: jax.tree_util.tree_map(  # noqa: E731
          lambda _: ocp.PLACEHOLDER, t)
      target = abstract._replace(
          params=jax.tree_util.tree_map(as_abstract, params),
          update_steps=jax.ShapeDtypeStruct(
              abstract.update_steps.shape, abstract.update_steps.dtype,
              sharding=dev_sharding),
          opt_state=placeholder(abstract.opt_state),
          popart=placeholder(abstract.popart))
    else:
      # Orbax builds without PLACEHOLDER (< 0.9): restore the FULL
      # abstract state and drop everything but params/update_steps.
      # The optimizer moments materialize for the duration of the call
      # (≈2× params of transient HBM) — a documented availability-
      # over-optimization fallback: a dead eval path is a failure
      # domain too. Non-params leaves land on the params' placements.
      target = jax.tree_util.tree_map(
          lambda c: jax.ShapeDtypeStruct(c.shape, c.dtype,
                                         sharding=dev_sharding),
          abstract)
      target = target._replace(
          params=jax.tree_util.tree_map(as_abstract, params))
    # PLACEHOLDER is a PyTreeRestore feature (StandardRestore rejects
    # it), and a manager that already did a StandardSave has its item
    # handler pinned — restore through a FRESH manager so the step
    # layout stays Orbax's concern, not ours. Same integrity ladder as
    # restore_latest: eval must survive a corrupt newest step too.
    manager = ocp.CheckpointManager(self._directory)
    try:
      restored, _ = self._restore_ladder(
          steps, lambda step: manager.restore(
              step, args=ocp.args.PyTreeRestore(target)))
    finally:
      manager.close()
    return restored.params, int(jax.device_get(restored.update_steps))

  def wait_until_finished(self):
    self._manager.wait_until_finished()

  def saved_mesh_shape(self) -> Optional[Dict[str, int]]:
    """The mesh shape dict the NEWEST retained step's sharding
    manifest recorded, or None (no steps / no manifest / pre-manifest
    writer). The driver's elastic-restore gate compares this against
    the live mesh to decide whether a restore is cross-topology."""
    steps = self._manager.all_steps()
    if not steps:
      return None
    manifest = self.read_sharding_manifest(max(steps))
    if not manifest or not isinstance(manifest.get('mesh'), dict):
      return None
    return {str(k): int(v) for k, v in manifest['mesh'].items()}

  def restore_resharded(self, abstract_state, registry, mesh,
                        strict: bool = True):
    """Restore the latest restorable step directly onto REGISTRY-
    resolved placements for `mesh` — the cross-topology resharding
    path (ROADMAP item 3): a checkpoint saved on any topology restores
    here with Orbax moving each leaf's bytes into the specs this
    registry resolves for THIS mesh, no concrete donor state needed.
    `abstract_state` is the eval_shape of the target TrainState.

    strict (the default, round 20): refuse with `ShardingLayoutError`
    when the registry resolves a cut this mesh cannot honor for a leaf
    the save had NOT already recorded as replicated (the manifest's
    spec table is the exemption list) — a topology change must never
    silently rewrite a layout the checkpoint still holds. strict=False
    accepts the divisibility guard's replicated degradation, exactly
    like a fresh spin-up on the new mesh."""
    if strict:
      steps = self._manager.all_steps()
      manifest = (self.read_sharding_manifest(max(steps))
                  if steps else None)
      saved = manifest.get('specs') if manifest else None
      registry.check_layout(abstract_state.params, mesh, what='param',
                            saved_specs=saved)
    return self.restore_latest(
        registry_restore_targets(abstract_state, registry, mesh))

  def close(self):
    self._manager.wait_until_finished()
    self._manager.close()
    # Drop the registry's fn-gauge hold on this instance (identity-
    # checked — a newer checkpointer's registration survives).
    from scalable_agent_tpu import telemetry
    for gauge in self._gauges:
      telemetry.registry().unregister(gauge.name, gauge)


def registry_restore_targets(abstract_state, registry, mesh):
  """Abstract restore targets whose placements the sharding REGISTRY
  resolves (parallel/sharding.py) — not copied from any live state.

  This is the primitive under cross-topology resharding (ROADMAP
  item 3): restore_latest pins each leaf to its target's sharding, so
  feeding it targets resolved by the registry FOR THE NEW MESH makes
  Orbax reshard a checkpoint saved under any topology into exactly the
  placements the current rules declare. The save-side half is the
  SHARDING_{step}.json manifest (`Checkpointer._record_sharding_
  manifest`), which records what the bytes on disk were laid out as.
  """
  shardings = registry.state_shardings(abstract_state, mesh)
  return jax.tree_util.tree_map(
      lambda leaf, sh: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=sh),
      abstract_state, shardings)
