"""Checkpoint / resume (Orbax-backed).

Reference semantics (reference: experiment.py ≈L570
`MonitoredTrainingSession(checkpoint_dir=logdir, save_checkpoint_secs=600)`;
SURVEY §5.4): periodically save ALL global state — network params,
optimizer slots, and the environment-frame counter — and restore the
latest on startup. Actor-local state (LSTM carries, env state) is
intentionally NOT checkpointed: unrolls straddling a restart are lost,
exactly as upstream.

The TPU build checkpoints the whole `learner.TrainState` pytree
(params, opt_state, update_steps) via Orbax. `update_steps` × frames
per step reproduces the reference's `num_environment_frames` global
step. Sharded (multi-chip) states round-trip: Orbax records shardings
and restores to the same placements when given the live state as the
abstract target.
"""

import os
import time
from typing import Optional

import jax

import orbax.checkpoint as ocp

from scalable_agent_tpu.learner import TrainState


class CheckpointStructureError(ValueError):
  """The latest checkpoint's tree structure does not match the state
  built from the current config (see the message for likely flags)."""


# Markers Orbax puts in tree-STRUCTURE mismatch messages (vs corrupt/
# partial files, missing arrays, I/O errors): only these earn the
# config-flag guidance — flag advice on a genuinely corrupt checkpoint
# sends operators down the wrong path (ADVICE r3). Deliberately
# NARROW: generic words like 'missing'/'key' also appear in
# partial-save messages ('missing commit file'), which must get the
# corruption wording.
_STRUCTURE_MARKERS = (
    'structure', 'tree', 'pytree', 'not found in checkpoint',
    'do not match')


def _wrap_structure_error(e, directory, step):
  """Re-raise a restore failure with the likely config-flag causes.

  The agent's param-tree STRUCTURE is a function of the config
  (VERDICT r2 W7): the raw Orbax mismatch error names neither the flag
  nor the fix, so operators hitting the documented migration footgun
  (`config.use_instruction` None-auto) got a dead end. The message is
  sniffed first so non-structural failures (corrupt/partial files)
  don't get misleading flag advice."""
  base = (f'could not restore checkpoint step {step} from {directory}: '
          f'{e}\n')
  msg = str(e).lower()
  # KeyError is structural by TYPE (its str is just the missing key,
  # which need not contain any marker).
  if isinstance(e, KeyError) or any(
      marker in msg for marker in _STRUCTURE_MARKERS):
    guidance = (
        'This looks like a tree-structure mismatch: the param tree is '
        'a function of the config. Usual cause: --use_instruction '
        '(default None = auto by level name — a checkpoint trained '
        'with the instruction encoder needs an explicit '
        '--use_instruction=true when resumed/evaluated on a '
        'non-language level, and vice versa). Also structure-changing: '
        '--torso, --use_popart, --pixel_control_cost. Compare your '
        "flags against the run's config.json saved next to the "
        'checkpoints.')
  else:
    guidance = (
        'This does not look like a tree-structure mismatch — the '
        'checkpoint files may be corrupt or partially written (e.g. a '
        'save interrupted mid-write). Try the previous retained step, '
        'or if the config might have changed, compare your flags '
        "against the run's config.json saved next to the checkpoints.")
  raise CheckpointStructureError(base + guidance) from e


class Checkpointer:
  """Thin lifecycle wrapper over an Orbax CheckpointManager.

  Args:
    directory: checkpoint root (the reference's --logdir).
    max_to_keep: retained checkpoints (oldest pruned).
    save_interval_secs: wall-clock throttle — `maybe_save` is a no-op
      until this many seconds passed since the last save (reference
      save_checkpoint_secs=600).
  """

  def __init__(self, directory: str, max_to_keep: int = 3,
               save_interval_secs: float = 600.0):
    self._directory = os.path.abspath(directory)
    os.makedirs(self._directory, exist_ok=True)
    self._manager = ocp.CheckpointManager(
        self._directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True))
    self._save_interval_secs = save_interval_secs
    self._last_save_time: Optional[float] = None

  def save(self, state: TrainState, step: Optional[int] = None,
           force: bool = False) -> bool:
    """Save now. `step` defaults to the state's own update counter.

    Returns whether a checkpoint was actually written. A step that
    already exists is skipped (returns False, even with force=True —
    Orbax raises StepAlreadyExistsError rather than overwriting); the
    throttle clock only resets on a real write so `maybe_save` stays
    truthful."""
    if step is None:
      step = int(jax.device_get(state.update_steps))
    if step in self._manager.all_steps():
      return False  # force=True raises StepAlreadyExistsError otherwise
    saved = bool(self._manager.save(
        step, args=ocp.args.StandardSave(state), force=force))
    if saved:
      self._last_save_time = time.monotonic()
    return saved

  def should_save(self) -> bool:
    """Whether the save interval has elapsed (host-local wall clock).

    Multi-host callers MUST NOT act on this independently: clocks
    differ per host, Orbax saves are collective, and disagreeing hosts
    deadlock in the barrier sync. Broadcast process 0's decision
    (driver.train does) and pass it to `maybe_save(decision=...)`.
    The first call after construction starts the clock."""
    now = time.monotonic()
    if self._last_save_time is None:
      self._last_save_time = now
      return False
    return now - self._last_save_time >= self._save_interval_secs

  def maybe_save(self, state: TrainState, step: Optional[int] = None,
                 decision: Optional[bool] = None) -> bool:
    """Save iff the save interval elapsed (call freely from the learner
    loop), matching the reference's every-N-seconds hook. `decision`
    overrides the local clock (multi-host: broadcast from process 0)."""
    if decision is None:
      decision = self.should_save()
    if not decision:
      return False
    return self.save(state, step)

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def restore_latest(self, target: TrainState) -> Optional[TrainState]:
    """Restore the most recent checkpoint, or None if none exists.

    `target` is a concrete (or abstract shape/dtype/sharding) TrainState
    matching the saved structure — build it with `make_train_state` on
    the right mesh first; restored arrays land on the same placements.
    """
    step = self._manager.latest_step()
    if step is None:
      return None

    def to_abstract(x):
      # Pin the TARGET's sharding so restored leaves land exactly on
      # its placements (mesh-sharded or single-device alike).
      if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=x.sharding)
      return ocp.utils.to_shape_dtype_struct(x)

    abstract = jax.tree_util.tree_map(to_abstract, target)
    try:
      return self._manager.restore(
          step, args=ocp.args.StandardRestore(abstract))
    except (ValueError, KeyError, TypeError) as e:
      _wrap_structure_error(e, self._directory, step)

  def restore_latest_params(self, params, make_state):
    """Restore ONLY params (+ the update_steps counter) from the latest
    checkpoint; returns (params, update_steps) or None.

    Eval needs the policy weights, not the optimizer moments (≈2×
    params of dead HBM if restored). The full-state target is built
    only abstractly (`jax.eval_shape` over `make_state`) so the
    moments are never materialized, and every leaf outside
    params/update_steps restores as `ocp.PLACEHOLDER` — Orbax never
    reads it. Restored leaves land on `params`' own placements (Orbax
    requires explicit shardings when process_count > 1).

    Args:
      params: CONCRETE param pytree of jax.Arrays (init_params output);
        supplies both the tree structure and the target placements.
      make_state: params → TrainState (e.g. a make_train_state
        closure); evaluated under eval_shape only.
    """
    step = self._manager.latest_step()
    if step is None:
      return None

    abstract = jax.eval_shape(make_state, params)
    as_abstract = lambda c: jax.ShapeDtypeStruct(  # noqa: E731
        c.shape, c.dtype, sharding=c.sharding)
    dev_sharding = jax.tree_util.tree_leaves(params)[0].sharding
    placeholder = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda _: ocp.PLACEHOLDER, t)
    target = abstract._replace(
        params=jax.tree_util.tree_map(as_abstract, params),
        update_steps=jax.ShapeDtypeStruct(
            abstract.update_steps.shape, abstract.update_steps.dtype,
            sharding=dev_sharding),
        opt_state=placeholder(abstract.opt_state),
        popart=placeholder(abstract.popart))
    # PLACEHOLDER is a PyTreeRestore feature (StandardRestore rejects
    # it), and a manager that already did a StandardSave has its item
    # handler pinned — restore through a FRESH manager so the step
    # layout stays Orbax's concern, not ours.
    manager = ocp.CheckpointManager(self._directory)
    try:
      try:
        restored = manager.restore(step,
                                   args=ocp.args.PyTreeRestore(target))
      except (ValueError, KeyError, TypeError) as e:
        _wrap_structure_error(e, self._directory, step)
    finally:
      manager.close()
    return restored.params, int(jax.device_get(restored.update_steps))

  def wait_until_finished(self):
    self._manager.wait_until_finished()

  def close(self):
    self._manager.wait_until_finished()
    self._manager.close()
