"""Observability: throughput meter, episode stats, summaries.

The reference has three channels (SURVEY §5.5): tf.summary scalars from
build_learner, manual per-episode tf.Summary protos from the learner
Python loop, and tf.logging text. Episode statistics travel THROUGH the
graph as `StepOutputInfo` — no side channel (reference: environments.py
≈L165–190; experiment.py ≈L590–620). This module keeps that design: the
learner loop hands each dequeued batch to `EpisodeStats.extract`, which
reads finished episodes straight out of the trajectory pytree.

What the reference lacks and BASELINE demands is a first-class
frames/sec meter (SURVEY §5.1) — `FpsMeter` here is the north-star
metric source.

Summaries are JSONL events (one object per line: wall_time, step, tag,
value) — greppable, plotter-friendly, no TensorBoard dependency.
"""

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from scalable_agent_tpu import telemetry
from scalable_agent_tpu.envs import suites


class _JsonlAppender(telemetry.JsonlAppender):
  """Shared line-buffered append-only JSONL plumbing for the scalar
  summaries and the incident stream. THE implementation (open/lock/
  write-line/silent-counted-drop-after-close/fsync-durable) lives in
  telemetry.JsonlAppender — one copy behind this module's streams AND
  the tracer's traces.jsonl, so the round-13 crash-safety contract
  cannot drift between them."""


class SummaryWriter(_JsonlAppender):
  """Append-only JSONL scalar writer (thread-safe)."""

  def __init__(self, logdir: str, filename: str = 'summaries.jsonl'):
    super().__init__(logdir, filename)

  def scalar(self, tag: str, value, step: int):
    self.write({'wall_time': round(time.time(), 3),
                 'step': int(step), 'tag': tag, 'value': float(value)})

  def scalars(self, values: Dict[str, float], step: int):
    for tag, value in values.items():
      self.scalar(tag, value, step)

  def histogram(self, tag: str, counts, step: int, edges=None):
    """Fixed-bin histogram event (the reference's
    tf.summary.histogram channel, experiment.py ≈L395 — its one use is
    the per-update action histogram, the main policy-collapse signal).

    `counts[i]` is the count of bin i — for discrete data (actions)
    the bin IS the value; for continuous data pass `edges` (len
    = len(counts)+1, np.histogram convention)."""
    event = {'wall_time': round(time.time(), 3), 'step': int(step),
             'tag': tag, 'kind': 'histogram',
             'counts': [int(c) for c in np.asarray(counts).ravel()]}
    if edges is not None:
      event['edges'] = [float(e) for e in np.asarray(edges).ravel()]
    self.write(event)


class EventLog(_JsonlAppender):
  """Append-only JSONL of structured INCIDENT events (thread-safe).

  Scalar summaries answer 'how much'; during a failure the operator
  (and scripts/chaos.py's SLO asserts) need 'what happened when':
  bad-step bursts, checkpoint rollbacks, watchdog halts, fault
  injections. One object per line — {wall_time, kind, step, ...} —
  in `incidents.jsonl` next to the summaries. Quiet runs produce an
  empty (or absent) file; the log is written on incident, not on a
  cadence.
  """

  # Incident kinds that must survive a kill -9 landing right after
  # the event (fsync'd): the halt/rollback/SDC records ARE the
  # postmortem — a line-buffered write that dies in the page cache
  # with the process defeats the whole stream. Substring match so the
  # driver's spellings (health_halt, sdc_replica_mismatch,
  # fault_replica_divergence, actor_slots_quarantined) all qualify
  # without a fragile exact list.
  # 'slo' (round 14): an SLO violation/capture record is the page an
  # operator will be reading — it must survive the crash it may be
  # narrating.
  # 'controller' (round 15): a controller_action record is the
  # self-healing audit trail — a knob the run moved on its own must
  # survive whatever crash follows it.
  # 'lock_order' (round 18): a lock_order_inversion detection IS the
  # latent-deadlock postmortem — it must survive the deadlock/crash
  # it predicts.
  # 'host_' (round 20): host_left/host_joined membership records are
  # how an operator reconstructs the pod's shape over time — a
  # departure record that dies with the crash that caused the
  # departure defeats the audit.
  # 'reshard' (round 20): a topology_resharded record marks a restore
  # whose layout was respecified for a NEW mesh — the provenance line
  # every later numerical question starts from.
  # 'pbt' (round 22): a pbt_exploit record is the provenance of a
  # member's weights (which donor it copied, at which round, with
  # which explored hypers) — without it a population run's winner is
  # unexplainable after the fact (RUNBOOK "which replica won and
  # why").
  # The canonical marker list is contract-linted
  # (scripts/lint.py durable-markers) against the docs/OBSERVABILITY
  # .md "Durable incident markers" section AND against the kinds the
  # modules actually emit, both directions.
  _DURABLE_MARKERS = ('halt', 'rollback', 'sdc', 'quarantin', 'slo',
                      'controller', 'lock_order', 'host_', 'reshard',
                      'pbt')

  def __init__(self, logdir: str, filename: str = 'incidents.jsonl'):
    super().__init__(logdir, filename)

  def event(self, kind: str, step: Optional[int] = None, **fields):
    record = {'wall_time': round(time.time(), 3), 'kind': str(kind)}
    if step is not None:
      record['step'] = int(step)
    record.update(fields)
    durable = any(m in kind for m in self._DURABLE_MARKERS)
    self.write(record, durable=durable, default=str)


class FpsMeter:
  """Environment-frames/sec over a sliding window of learner steps.

  Frames unit matches the reference's global step: env frames AFTER
  action repeat (experiment.py ≈L390; SURVEY §6 measurement definition).
  """

  def __init__(self, window_secs: float = 30.0):
    self._window_secs = window_secs
    self._events = collections.deque()  # (t, frame_delta)
    self._total_frames = 0
    self._start = time.monotonic()

  def update(self, frames: int):
    now = time.monotonic()
    self._total_frames += frames
    self._events.append((now, frames))
    self._prune(now)

  def _prune(self, now: float):
    cutoff = now - self._window_secs
    while self._events and self._events[0][0] < cutoff:
      self._events.popleft()

  @property
  def total_frames(self) -> int:
    return self._total_frames

  def fps(self) -> float:
    """Rate over the trailing window, anchored at NOW — a stalled
    learner reads as decaying-to-zero fps, not the last healthy rate."""
    now = time.monotonic()
    self._prune(now)
    span = min(now - self._start, self._window_secs)
    if span <= 0:
      return 0.0
    return sum(delta for _, delta in self._events) / span


class ThreadWatchdog:
  """Liveness ledger for long-running service threads (round 11).

  A wedged thread — an ingest reader stuck mid-recv against a
  half-open peer, a param-lane selector loop that died, a worker
  parked forever in a send — used to leak SILENTLY: the socket stayed
  open, the thread stayed alive, and the only symptom was a slowly
  starving pipeline. Each service thread `beat()`s once per loop
  iteration (including idle poll timeouts, so an idle thread is not a
  wedged thread); `wedged(stall_secs)` names the threads that have
  made no progress past the deadline. The owner (the ingest server's
  `stats()`) surfaces the count so the driver can write the
  `ingest_threads_wedged` summary + incident instead of the operator
  discovering the leak hours later.

  Thread-safe; registration is idempotent (a beat registers)."""

  def __init__(self):
    self._beats: Dict[str, float] = {}
    self._lock = threading.Lock()

  def beat(self, name: str):
    with self._lock:
      self._beats[name] = time.monotonic()

  def unregister(self, name: str):
    with self._lock:
      self._beats.pop(name, None)

  def names(self) -> List[str]:
    with self._lock:
      return sorted(self._beats)

  def wedged(self, stall_secs: float) -> List[str]:
    """Registered threads with no beat for `stall_secs` (sorted)."""
    cutoff = time.monotonic() - stall_secs
    with self._lock:
      return sorted(n for n, t in self._beats.items() if t < cutoff)


class LatencyReservoir:
  """Bounded recent-sample reservoir for latency percentiles
  (thread-safe) — the per-lane transport counters' backing store
  (round 6): seconds in, p50/p99 out, for consumers that want the
  seconds-native API without a registry name (inference admission
  waits).

  Since round 13 this is a thin veneer over `telemetry.Histogram`
  (which IS this design promoted to a registry citizen) — ONE
  implementation of the bounded-window/nearest-rank/NaN-on-empty
  contract, so the registry's numbers and this surface can never
  drift. NaN on empty: 'no traffic yet' renders as '-' in
  bench/telemetry rows instead of masquerading as a perfect 0 ms
  latency."""

  def __init__(self, maxlen: int = 4096):
    self._hist = telemetry.Histogram('latency_reservoir',
                                     maxlen=maxlen)

  def record(self, seconds: float):
    self._hist.observe(float(seconds))

  @property
  def count(self) -> int:
    return self._hist.count

  def percentiles(self, *qs: float) -> Tuple[float, ...]:
    return self._hist.percentiles(*qs)

  def percentile_ms(self, *qs: float) -> Tuple[float, ...]:
    """`percentiles`, in rounded milliseconds — the stats()-surface
    form every reservoir consumer was hand-rolling with its own
    `round(x * 1e3, 3)`."""
    return tuple(round(v * 1e3, 3) for v in self.percentiles(*qs))


def stack_metrics(metrics: Dict) -> Tuple[Tuple[str, ...], object]:
  """Stack a step's scalar metrics into ONE device array.

  The deferred-readback half of the learner's metrics path (round 8):
  `driver.train` used to `device_get` the whole per-step metrics dict
  leaf-by-leaf at summary time — one host sync per key, against
  values the step had JUST produced, so the first sync stalled on the
  entire step. Stacking costs one tiny fused dispatch per step; the
  handle is read ONE STEP LATER (`read_stacked_metrics`), by which
  time the values are long computed and the single transfer returns
  without syncing the dispatch pipeline — the same pattern
  health.stack_sentinels proved for the watchdog scalars."""
  import jax.numpy as jnp
  keys = tuple(sorted(metrics))
  return keys, jnp.stack([jnp.asarray(metrics[k], jnp.float32)
                          for k in keys])


def read_stacked_metrics(handle) -> Dict[str, float]:
  """One transfer: (keys, stacked device array) → host float dict."""
  import jax
  keys, stacked = handle
  values = np.asarray(jax.device_get(stacked))
  return {k: float(v) for k, v in zip(keys, values)}


def extract_episodes(batch) -> List[Tuple[int, float, int]]:
  """Finished episodes in a dequeued [T+1, B] batch.

  Returns [(level_id, episode_return, episode_frames)]. A done at
  timestep t>0 marks an episode end whose final stats ride in the
  OUTPUT info at that step (the FlowEnvironment contract). Timestep 0
  is the overlap frame — already counted in the previous batch, so
  skipped exactly like the reference's `done[1:]` (test() ≈L399 and
  the train loop ≈L590).
  """
  done = np.asarray(batch.env_outputs.done)[1:]          # [T, B]
  returns = np.asarray(batch.env_outputs.info.episode_return)[1:]
  steps = np.asarray(batch.env_outputs.info.episode_step)[1:]
  levels = np.asarray(batch.level_name)                  # [B]
  t_idx, b_idx = np.nonzero(done)
  return [(int(levels[b]), float(returns[t, b]), int(steps[t, b]))
          for t, b in zip(t_idx, b_idx)]


class EpisodeStats:
  """Accumulates per-level episode returns and periodic DMLab-30 scores.

  Mirrors the reference learner loop (experiment.py ≈L590–620): every
  finished episode logs `<level>/episode_return` and
  `<level>/episode_frames`; in benchmark mode, once EVERY level has at
  least one finished episode, emit the suite's human-normalized
  training scores over the per-level means (`dmlab30/training_no_cap`
  + `dmlab30/training_cap_100`, or `atari57/training_median` +
  `atari57/training_mean`), then reset the accumulator.

  Args:
    level_names: id → name mapping (actors carry int level ids;
      strings never enter trajectories).
    multi_task: legacy alias for benchmark='dmlab30'.
    benchmark: None | 'dmlab30' | 'atari57' — enables the suite
      scoring path (level_names must then be that suite's levels).
  """

  def __init__(self, level_names: List[str], multi_task: bool = False,
               writer: Optional[SummaryWriter] = None,
               benchmark: Optional[str] = None):
    self._level_names = list(level_names)
    if benchmark is None and multi_task:
      benchmark = 'dmlab30'
    if benchmark is not None and benchmark not in suites.SUITES:
      raise ValueError(f'unknown benchmark {benchmark!r} '
                       f'(suites: {sorted(suites.SUITES)})')
    self._multi_task = benchmark is not None
    self._suite = suites.SUITES[benchmark] if benchmark else None
    self._writer = writer
    self._level_returns: Dict[str, List[float]] = {
        name: [] for name in self._level_names}
    self.last_scores: Optional[Dict[str, float]] = None

  def record_batch(self, batch, step: int) -> List[Tuple[str, float, int]]:
    """Extract finished episodes, write summaries, maybe score.

    Returns [(level_name, episode_return, episode_frames)] for logging.
    """
    episodes = []
    for level_id, ep_return, ep_frames in extract_episodes(batch):
      name = self._level_names[level_id]
      episodes.append((name, ep_return, ep_frames))
      if self._multi_task:  # accumulator is only read by _maybe_score
        self._level_returns.setdefault(name, []).append(ep_return)
      if self._writer is not None:
        self._writer.scalar(f'{name}/episode_return', ep_return, step)
        self._writer.scalar(f'{name}/episode_frames', ep_frames, step)
    if self._multi_task:
      self._maybe_score(step)
    return episodes

  def _maybe_score(self, step: int):
    if not all(self._level_returns.get(name)
               for name in self._level_names):
      return
    self.last_scores = self._suite.training_scores(self._level_returns)
    if self._writer is not None:
      self._writer.scalars(self.last_scores, step)
    self._level_returns = {name: [] for name in self._level_names}
