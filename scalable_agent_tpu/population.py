"""Population engine (round 22, ROADMAP item 4): the pure functions
behind in-graph auto-curriculum, heterogeneous fleet composition, and
minimal PBT across learner replicas.

Three concerns, one module, zero heavy imports — everything here is
either jit-traceable (the curriculum math rides INSIDE the fused
Anakin step, parallel/anakin.py) or a tiny host-side planner the
driver calls between rounds:

1. CURRICULUM (in-graph): `ProcgenCore`'s finite level-id space
   (envs/jittable.py) becomes a driven distribution. Per-level
   regret/TD-error EMAs accumulate inside the fused step
   (`score_signal` + `update_scores`, segment-sum over the unroll's
   transition-level ids) and the next episode's level id is drawn from
   an epsilon-smoothed softmax over those scores (`level_probs` +
   `sample_levels` — a `jax.random.categorical`, i.e. Gumbel-argmax,
   so the prioritized draw is one fused op with zero host round
   trips). Staleness is handled by DECAY: a level the batch never
   visited has its score multiplied by `decay < 1`, so a stale "hard"
   level drifts back toward the smoothed floor instead of starving
   forever. 'regret' scores positive value loss (the PLR positive
   value-loss proxy, arXiv 2010.03934: levels where returns EXCEED
   the baseline — learnable, not yet learned); 'td' scores |delta|
   (symmetric surprise).

2. FLEET COMPOSITION (host-side): `parse_fleet_tasks` /
   `plan_actor_assignment` turn a `--fleet_tasks='bandit:2,gridworld:2'`
   spec into a per-actor task plan (largest-remainder apportionment —
   the per-task frame budget IS the actor share, since every actor
   contributes frames at the same cadence), and `padding_report`
   quantifies what obs-spec FAMILY bucketing buys: merges that never
   cross families pad zero bytes beyond the family's own frame shape,
   vs naive max-shape padding across the whole fleet.

3. PBT (host-side, process-0-owned per the round-12 per-actuator
   ownership rule): `pbt_decide` ranks members WITHIN comparable
   groups (same suite — cross-suite returns are not commensurable),
   bottom-quantile members exploit a top-quantile donor's weights
   (inheritance travels through the round-2 checkpoint ladder:
   the donor's VERIFIED save is the transfer medium, and the
   inheritor's next restore re-verifies digests), and `pbt_explore`
   perturbs (lr, entropy_cost) multiplicatively — the minimal PBT of
   arXiv 1711.09846. Deterministic under a seeded generator: the
   driver derives one per round, so a re-run replays the decisions.

The driver wires these into `train_anakin` (curriculum telemetry +
CURRICULUM_LEVELS.json), `train_population` (the one-invocation
population run), and `make_fleet` (mixed-suite actor assignment);
bench.py's population stage carries the fps-parity and padding-waste
measurements; docs/PARALLELISM.md carries the operator story.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The config axis (config.curriculum; experiment.py --curriculum).
CURRICULUM_MODES = ('uniform', 'regret', 'td')

# The two (hyper)parameters minimal PBT explores over — matching the
# IMPALA paper's own PBT axes (learning rate, entropy cost).
PBT_HYPERS = ('learning_rate', 'entropy_cost')


# --------------------------------------------------------------------
# In-graph curriculum (all jit-traceable; no host round trips).
# --------------------------------------------------------------------


def level_probs(scores, temperature: float, eps: float):
  """Sampling distribution over levels: epsilon-smoothed softmax.

  `(1-eps) * softmax(normalize(scores) / temperature) + eps / n` —
  the eps floor guarantees every level keeps nonzero visitation
  probability (the staleness escape hatch: decayed scores PLUS
  guaranteed revisits mean no level's score can silently fossilize).

  normalize() divides by the max score (clipped away from zero), so
  prioritization is SCALE-FREE: TD/regret magnitudes depend on the
  env's reward scale and the training phase (early procgen deltas
  are ~1e-2), and an un-normalized softmax at temperature 1.0 would
  stay indistinguishable from uniform no matter how skewed the
  scores. After normalization the hottest level sits at 1.0 by
  construction and `temperature` has a fixed meaning: max-to-min
  odds of e^(1/temperature) before the eps floor, whatever the
  reward units. All-zero scores normalize to all-zero → uniform."""
  scores = jnp.asarray(scores, jnp.float32)
  n = scores.shape[0]
  norm = scores / jnp.maximum(jnp.max(scores), 1e-8)
  soft = jax.nn.softmax(norm / jnp.maximum(temperature, 1e-6))
  return (1.0 - eps) * soft + eps / n


def sample_levels(rng, scores, batch: int, temperature: float,
                  eps: float):
  """Draw `batch` level ids from `level_probs` — one
  `jax.random.categorical` (Gumbel-argmax over log-probs), so the
  prioritized sampler is a single fused op inside the device step."""
  logits = jnp.log(level_probs(scores, temperature, eps))
  return jax.random.categorical(rng, logits, shape=(batch,))


def score_signal(delta, mode: str):
  """Per-transition priority signal from the TD error `delta`.

  'regret': relu(delta) — the PLR positive-value-loss proxy (returns
  exceeded the baseline: the level is learnable and not yet learned;
  a level the policy has mastered OR cannot score on goes to zero).
  'td': |delta| — symmetric surprise."""
  if mode == 'regret':
    return jax.nn.relu(delta)
  if mode == 'td':
    return jnp.abs(delta)
  raise ValueError(f'unknown curriculum mode {mode!r} '
                   f'(signal modes: regret, td)')


def update_scores(scores, visits, level_ids, signals, alpha: float,
                  decay: float):
  """EMA the per-level scores from one unroll's transition signals.

  `level_ids`/`signals`: [T-1, B] (or any matching shape) transition
  level ids and priority signals. Levels visited this step move
  `(1-alpha)*s + alpha*mean(signal)`; unvisited levels DECAY
  (`decay*s` — staleness handling: an unvisited level's stale score
  loses authority over time). Returns (scores, visits) with visits
  incremented by per-level transition counts. Pure and traceable —
  under a sharded batch the segment sums reduce across devices via
  the partitioner's inserted psum."""
  scores = jnp.asarray(scores, jnp.float32)
  n = scores.shape[0]
  ids = jnp.reshape(level_ids, (-1,))
  sig = jnp.reshape(jnp.asarray(signals, jnp.float32), (-1,))
  sums = jax.ops.segment_sum(sig, ids, num_segments=n)
  counts = jax.ops.segment_sum(jnp.ones_like(sig), ids,
                               num_segments=n)
  visited = counts > 0
  means = sums / jnp.maximum(counts, 1.0)
  new_scores = jnp.where(visited, (1.0 - alpha) * scores + alpha * means,
                         decay * scores)
  return new_scores, visits + counts


def curriculum_metrics(scores, visits, temperature: float,
                       eps: float) -> Dict[str, Any]:
  """Scalar telemetry for the summary stream (traceable; the fused
  step folds these into its metrics dict): sampling-distribution
  entropy (uniform = log n; collapse → 0), score spread, and how many
  levels have ever been visited."""
  p = level_probs(scores, temperature, eps)
  entropy = -jnp.sum(p * jnp.log(jnp.maximum(p, 1e-12)))
  return {
      'curriculum_entropy': entropy,
      'curriculum_score_mean': jnp.mean(scores),
      'curriculum_score_max': jnp.max(scores),
      'curriculum_levels_visited': jnp.sum(
          (visits > 0).astype(jnp.float32)),
  }


# --------------------------------------------------------------------
# Heterogeneous fleet composition (host-side planning).
# --------------------------------------------------------------------


def parse_fleet_tasks(spec: str) -> List[Tuple[str, float]]:
  """Parse `--fleet_tasks='bandit:2,gridworld:1'` into
  [(backend, weight)] — weights are RELATIVE actor (and therefore
  frame-budget) shares. A bare name means weight 1."""
  tasks = []
  for part in spec.split(','):
    part = part.strip()
    if not part:
      continue
    if ':' in part:
      name, _, weight = part.partition(':')
      try:
        w = float(weight)
      except ValueError:
        raise ValueError(f'fleet_tasks weight {weight!r} for task '
                         f'{name!r} is not a number')
    else:
      name, w = part, 1.0
    name = name.strip()
    if not name:
      raise ValueError(f'fleet_tasks entry {part!r} has no task name')
    if w <= 0:
      raise ValueError(f'fleet_tasks weight for {name!r} must be > 0, '
                       f'got {w}')
    if any(existing == name for existing, _ in tasks):
      raise ValueError(f'fleet_tasks names {name!r} twice')
    tasks.append((name, w))
  return tasks


def plan_actor_assignment(tasks: Sequence[Tuple[str, float]],
                          num_actors: int) -> List[int]:
  """Apportion `num_actors` across weighted tasks (largest-remainder,
  every task guaranteed >= 1 actor) and return the per-actor task
  index, interleaved round-robin so partial fleets (or a drained
  host's survivors) still sample every task.

  The per-task FRAME BUDGET falls out of this plan: actors produce
  frames at the same cadence, so a task's actor share IS its share of
  the fresh-frame budget (driver.train logs both)."""
  if not tasks:
    raise ValueError('plan_actor_assignment needs at least one task')
  if num_actors < len(tasks):
    raise ValueError(f'{num_actors} actor(s) cannot cover '
                     f'{len(tasks)} task(s) at >= 1 actor each')
  weights = np.asarray([w for _, w in tasks], np.float64)
  quotas = num_actors * weights / weights.sum()
  counts = np.maximum(np.floor(quotas).astype(int), 1)
  # Largest remainder for the leftover seats (ties break by index —
  # deterministic for a given spec).
  while counts.sum() < num_actors:
    frac = quotas - counts  # remainders recompute against bumped counts
    counts[int(np.argmax(frac))] += 1
  while counts.sum() > num_actors:
    # The >=1 floor can overshoot tiny fleets; shave the largest
    # overage but never below 1.
    over = counts - quotas
    over[counts <= 1] = -np.inf
    counts[int(np.argmax(over))] -= 1
  # Round-robin interleave: cycle tasks, emitting each until its count
  # is spent.
  remaining = counts.copy()
  plan: List[int] = []
  while len(plan) < num_actors:
    for i in range(len(tasks)):
      if remaining[i] > 0:
        plan.append(i)
        remaining[i] -= 1
        if len(plan) == num_actors:
          break
  return plan


def frame_bytes(frame_shape: Sequence[int], dtype_bytes: int = 1
                ) -> int:
  """Bytes of one observation frame (uint8 frames by default)."""
  n = dtype_bytes
  for d in frame_shape:
    n *= int(d)
  return n


def padding_report(family_counts: Dict[Tuple[int, ...], int]
                   ) -> Dict[str, float]:
  """What obs-spec FAMILY bucketing buys over naive max-shape padding.

  `family_counts`: {frame_shape: frames_served}. Family-bucketed
  merges never cross obs specs, so each frame costs exactly its own
  family's bytes; a naive single-queue batcher must pad every frame to
  the fleet-wide max shape. Returns padded-bytes-per-useful-frame for
  both policies plus the waste ratio — the bench's mixed-suite row."""
  if not family_counts:
    return {'useful_bytes': 0.0, 'bucketed_bytes': 0.0,
            'max_shape_bytes': 0.0, 'bucketed_bytes_per_frame': 0.0,
            'max_shape_bytes_per_frame': 0.0, 'waste_ratio': 0.0}
  max_frame = max(frame_bytes(s) for s in family_counts)
  frames = sum(family_counts.values())
  useful = float(sum(frame_bytes(s) * c
                     for s, c in family_counts.items()))
  naive = float(max_frame * frames)
  return {
      'useful_bytes': useful,
      'bucketed_bytes': useful,  # family merges pad zero extra bytes
      'max_shape_bytes': naive,
      'bucketed_bytes_per_frame': useful / frames,
      'max_shape_bytes_per_frame': naive / frames,
      'waste_ratio': (naive - useful) / naive if naive else 0.0,
  }


# --------------------------------------------------------------------
# Minimal PBT (host-side; the driver's process-0 decision loop).
# --------------------------------------------------------------------


def pbt_explore(hypers: Dict[str, float], rng: np.random.Generator,
                perturb: float) -> Dict[str, float]:
  """Perturb each hyper multiplicatively by `perturb` or `1/perturb`
  (independent fair coins — arXiv 1711.09846's explore step).
  Iteration order is sorted for determinism under a seeded rng."""
  out = dict(hypers)
  for name in sorted(hypers):
    factor = perturb if rng.random() < 0.5 else 1.0 / perturb
    out[name] = float(hypers[name] * factor)
  return out


def pbt_decide(returns: Sequence[float], groups: Sequence[Any],
               rng: np.random.Generator, quantile: float = 0.25,
               perturb: float = 1.2,
               hypers: Optional[Sequence[Dict[str, float]]] = None
               ) -> List[Optional[Dict[str, Any]]]:
  """One PBT round's exploit/explore decisions.

  `returns[i]` is member i's recent mean episode return; `groups[i]`
  its comparability group (the SUITE — cross-suite returns are not on
  one scale, so ranking stays within-group). In each group with >= 2
  members, the bottom `quantile` members exploit a donor drawn
  uniformly from the top `quantile` (weights via the checkpoint
  ladder, hypers via `pbt_explore`). Returns a per-member decision:
  None (keep training) or {'donor': j, 'hypers': {...}} (only when
  the donor strictly outperforms — equal-return pairs keep)."""
  n = len(returns)
  if hypers is not None and len(hypers) != n:
    raise ValueError(f'{len(hypers)} hyper sets for {n} members')
  decisions: List[Optional[Dict[str, Any]]] = [None] * n
  for g in sorted(set(groups), key=repr):
    idx = [i for i in range(n) if groups[i] == g]
    if len(idx) < 2:
      continue
    ranked = sorted(idx, key=lambda i: (returns[i], i))
    k = max(1, int(round(quantile * len(idx))))
    k = min(k, len(idx) // 2)  # bottom and top never overlap
    bottom, top = ranked[:k], ranked[-k:]
    for i in bottom:
      donor = top[int(rng.integers(len(top)))]
      if returns[donor] <= returns[i]:
        continue
      donor_hypers = dict(hypers[donor]) if hypers is not None else {}
      decisions[i] = {
          'donor': donor,
          'hypers': pbt_explore(donor_hypers, rng, perturb),
      }
  return decisions
