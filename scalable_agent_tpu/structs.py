"""Core data structures shared across the framework.

Mirrors the reference's namedtuple contracts (reference: environments.py
≈L120 `StepOutput`/`StepOutputInfo`; experiment.py ≈L52 `ActorOutput`,
≈L55 `AgentOutput`) so that a user of the reference finds the same shapes
in the same places. All are plain pytrees — they cross the host/device
boundary and jit untouched.
"""

from typing import NamedTuple, Any

import jax.numpy as jnp


class StepOutputInfo(NamedTuple):
  """Episode statistics that flow *through* the trajectory (no side channel).

  On `done`, the emitted output carries the final episode stats while the
  carried state resets them to zero — the reference's FlowEnvironment design
  (environments.py ≈L165–190), kept here as part of the trajectory pytree.
  """
  episode_return: Any  # f32 []
  episode_step: Any    # i32 []


class StepOutput(NamedTuple):
  """One environment step (reference: environments.py ≈L120)."""
  reward: Any       # f32 []
  info: Any         # StepOutputInfo
  done: Any         # bool []
  observation: Any  # (frame uint8 [H, W, 3], instruction ids int32 [L])


class AgentOutput(NamedTuple):
  """One agent step (reference: experiment.py ≈L55)."""
  action: Any         # i32 [] — sampled (actor) or argmax (learner unroll)
  policy_logits: Any  # f32 [num_actions]
  baseline: Any       # f32 []


class ActorOutput(NamedTuple):
  """One actor unroll as enqueued for the learner (experiment.py ≈L52).

  Time-major with the 1-frame overlap: T+1 timesteps where timestep 0 is
  the previous unroll's last frame (load-bearing for learner alignment —
  see losses.py).
  """
  level_name: Any    # bytes/str or int level id
  agent_state: Any   # LSTM state at the *start* of the unroll
  env_outputs: Any   # StepOutput of [T+1] tensors
  agent_outputs: Any # AgentOutput of [T+1] tensors


def zeros_like_spec(spec):
  """Build a zeroed pytree from a (shape, dtype) spec pytree."""
  import jax
  return jax.tree_util.tree_map(
      lambda s: jnp.zeros(s.shape, s.dtype), spec)
