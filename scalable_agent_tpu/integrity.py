"""Data-plane integrity primitives: CRC32C + pytree content digests.

PRs 2, 6, and 8 hardened the pipeline against components that FAIL;
nothing defended against data that is WRONG: a bit-flipped unroll
frame that still parses trains the learner on garbage, a corrupted
bf16 param publish silently poisons the whole inference fleet, and
disk bit-rot inside a committed orbax step defeats the LAST_GOOD
ladder (restore verifies structure, not content). This module is the
one place that knows how to checksum bytes and trees; the consumers
are:

  runtime/remote.py    protocol v7 per-frame CRC32C trailers + the
                       per-publish params content digest
  checkpoint.py        per-array-file digests recorded by verified
                       saves, re-verified by the restore ladder
  runtime/ring_buffer  replay-tier entries keep their insert-time CRC
                       so sample reuse can't serve host-memory rot

CRC32C (Castagnoli) via the `google_crc32c` C extension when present
(~GB/s — the jax stack already ships it as a dependency); zlib.crc32
(IEEE polynomial, also C speed) as the fallback so the module never
fails to import. The ALGORITHM NAME is part of every negotiation/
record (`CRC_ALGO`): two hosts — or a checkpoint written on another
host — only compare checksums produced by the same algorithm; a
mismatch in algorithm negotiates the check off (wire) or skips the
verification (disk) instead of reporting phantom corruption.

The device-side counterpart (the in-graph SDC param fingerprint) lives
in learner.param_fingerprint / parallel/train_parallel.py — it must
run inside the compiled step, not on host bytes.
"""

import logging
from typing import Dict, Optional, Tuple

import numpy as np

log = logging.getLogger('scalable_agent_tpu')

try:  # pragma: no cover - exercised implicitly by every consumer
  import google_crc32c as _crc32c_lib

  def _crc_update(crc: int, data) -> int:
    # The C extension accepts ONLY `bytes` (bytearray/memoryview are
    # refused) — the copy costs ~0.1 ms/MB against the extension's
    # ~20 GB/s CRC, still ~6x faster end to end than zlib.crc32's
    # copy-free ~1 GB/s on the 2 MB flagship unroll.
    if not isinstance(data, bytes):
      data = bytes(data)
    return _crc32c_lib.extend(crc, data)

  CRC_ALGO = 'crc32c'
except ImportError:  # pragma: no cover - container always has it
  import zlib as _zlib

  def _crc_update(crc: int, data) -> int:
    return _zlib.crc32(data, crc) & 0xFFFFFFFF

  CRC_ALGO = 'zlib-crc32'


def crc_bytes(data, crc: int = 0) -> int:
  """CRC of one bytes-like object (optionally extending `crc`)."""
  return _crc_update(crc, data)


class Crc:
  """Incremental CRC accumulator (the wire receivers feed each frame
  piece as it lands; the senders feed each segment as it ships)."""

  __slots__ = ('value',)

  def __init__(self, value: int = 0):
    self.value = int(value)

  def update(self, data) -> 'Crc':
    self.value = _crc_update(self.value, data)
    return self


# Unified-registry telemetry (round 13): how much content hashing the
# integrity plane actually performs, and whether this host runs the
# slow zlib fallback — both feed the registry snapshot the bench's
# CRC-cost rows and the fleet 'stats' request read.
from scalable_agent_tpu import telemetry as _telemetry
_TREE_DIGESTS = _telemetry.counter('integrity/tree_digests')
_FILE_DIGESTS = _telemetry.counter('integrity/file_digests')
_telemetry.gauge('integrity/crc_algo_is_fallback',
                 fn=lambda: 0 if CRC_ALGO == 'crc32c' else 1)


def tree_digest(tree) -> int:
  """Content CRC of a pytree of host arrays, in deterministic
  flatten order. Dtype/shape changes ARE content changes: each leaf
  contributes its dtype name and shape to the stream, so a reshaped
  or recast tree never collides with the original."""
  import jax
  _TREE_DIGESTS.inc()
  crc = Crc()
  for leaf in jax.tree_util.tree_leaves(tree):
    arr = np.asarray(leaf)
    crc.update(f'{arr.dtype.name}:{arr.shape};'.encode())
    if not arr.flags['C_CONTIGUOUS']:
      arr = np.ascontiguousarray(arr)
    crc.update(arr.reshape(-1).view(np.uint8))
  return crc.value


def file_digest(path: str, chunk_bytes: int = 1 << 20) -> int:
  """Content CRC of one file (checkpoint bit-rot ledger)."""
  _FILE_DIGESTS.inc()
  crc = Crc()
  with open(path, 'rb') as f:
    while True:
      chunk = f.read(chunk_bytes)
      if not chunk:
        return crc.value
      crc.update(chunk)


def digest_record(value: int) -> Dict:
  """The on-disk/wire spelling of a digest: value + algorithm, so a
  reader produced by a different build refuses to compare instead of
  reporting phantom corruption."""
  return {'crc': int(value), 'algo': CRC_ALGO}


def spec_table_digest(specs: Dict[str, str]) -> int:
  """Content CRC of a sharding-spec manifest ({param_path: spec
  string}, parallel/sharding.ShardingRegistry.describe) in sorted-path
  order. The checkpoint plane records it next to each save
  (SHARDING_{step}.json) so a restore onto a different topology or a
  drifted rule set is DETECTED — a spec change is a layout change even
  when every array byte is identical, which the file digests above
  cannot see."""
  crc = Crc()
  for path in sorted(specs):
    crc.update(f'{path}={specs[path]};'.encode())
  return crc.value


def verify_record(record, value: int) -> Optional[bool]:
  """Compare `value` against a `digest_record`. None = not comparable
  (missing/malformed record or foreign algorithm — the caller should
  SKIP verification, loudly); True/False = verified/corrupt."""
  if not isinstance(record, dict):
    return None
  if record.get('algo') != CRC_ALGO:
    return None
  try:
    return int(record['crc']) == int(value)
  except (KeyError, TypeError, ValueError):
    return None


def flip_bit(buf: bytearray, bit_index: int) -> Tuple[int, int]:
  """Flip one bit in-place; returns (byte_offset, bit). The chaos
  sites (wire_bitflip / publish_corrupt / ckpt_bitrot) share this so
  'a single bit flip' means the same thing at every layer."""
  byte = (bit_index // 8) % max(len(buf), 1)
  bit = bit_index % 8
  buf[byte] ^= 1 << bit
  return byte, bit
