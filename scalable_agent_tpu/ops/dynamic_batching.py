"""Dynamic batching of concurrent inference calls (Python API).

Reference parity: `dynamic_batching.py` (reference ≈130 LoC — `batch_fn`,
`batch_fn_with_options(minimum_batch_size, maximum_batch_size,
timeout_ms)` over the C++ Batcher op, loaded via
`tf.load_op_library('batcher.so')` ≈L25). Here the native piece is a
plain C++ shared library (`ops/batcher/batcher.cc`) driven through
ctypes, and the batched function is any Python callable over numpy
arrays — in production a jitted JAX policy on TPU.

Threading model (same as the reference): N caller threads block in
`compute`; ONE computation thread (spawned lazily per decorated fn)
loops get_batch → f(concatenated inputs) → set_outputs. The reference's
documented caveat applies unchanged: with dynamic batching, actions
within one unroll may be computed with different weight versions
(reference: experiment.py ≈L472 comment).
"""

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
_BATCHER_DIR = os.path.join(_THIS_DIR, 'batcher')
_LIB_PATH = os.path.join(_BATCHER_DIR, 'libbatcher.so')

# Return codes mirroring batcher.cc's enum Rc.
RC_OK, RC_ERROR, RC_CANCELLED, RC_SHAPE, RC_TOO_BIG, RC_CLOSED, \
    RC_BAD_ID, RC_SIZE = range(8)

_lib = None
_lib_lock = threading.Lock()


class BatcherError(RuntimeError):
  """Computation error propagated from the batched function."""


class BatcherCancelled(RuntimeError):
  """The batcher was closed while this call was in flight."""


def _ensure_lib():
  """Load (building if necessary) libbatcher.so."""
  global _lib
  with _lib_lock:
    if _lib is not None:
      return _lib
    # Always invoke make: its batcher.cc dependency makes a fresh build
    # a no-op and a stale .so (edited source) gets rebuilt.
    subprocess.run(['make', '-C', _BATCHER_DIR], check=True,
                   capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    i64 = ctypes.c_longlong
    p = ctypes.c_void_p
    lib.batcher_create.restype = p
    lib.batcher_create.argtypes = [i64, i64, i64, i64]
    lib.batcher_compute_begin.restype = i64
    lib.batcher_compute_begin.argtypes = [
        p, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(i64), i64,
        ctypes.POINTER(i64)]
    lib.batcher_compute_wait.restype = i64
    lib.batcher_compute_wait.argtypes = [p, i64, ctypes.c_char_p, i64]
    lib.batcher_result_count.restype = i64
    lib.batcher_result_count.argtypes = [p, i64]
    lib.batcher_result_size.restype = i64
    lib.batcher_result_size.argtypes = [p, i64, i64]
    lib.batcher_result_copy.restype = i64
    lib.batcher_result_copy.argtypes = [p, i64, i64, ctypes.c_void_p, i64]
    lib.batcher_request_free.restype = None
    lib.batcher_request_free.argtypes = [p, i64]
    lib.batcher_get_batch.restype = i64
    lib.batcher_get_batch.argtypes = [p, ctypes.POINTER(i64),
                                      ctypes.POINTER(i64)]
    lib.batcher_batch_input_copy.restype = i64
    lib.batcher_batch_input_copy.argtypes = [p, i64, i64,
                                             ctypes.c_void_p]
    lib.batcher_set_outputs.restype = i64
    lib.batcher_set_outputs.argtypes = [
        p, i64, i64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(i64), i64]
    lib.batcher_set_error.restype = i64
    lib.batcher_set_error.argtypes = [p, i64, ctypes.c_char_p]
    lib.batcher_close.restype = None
    lib.batcher_close.argtypes = [p]
    lib.batcher_destroy.restype = None
    lib.batcher_destroy.argtypes = [p]
    _lib = lib
    return lib


def _as_contiguous(arrays) -> List[np.ndarray]:
  out = []
  for a in arrays:
    a = np.asarray(a)
    # Check BEFORE ascontiguousarray, which silently promotes 0-d to 1-d.
    if a.ndim < 1:
      raise ValueError('batched tensors need a leading batch dim; got '
                       f'scalar of dtype {a.dtype}')
    out.append(np.ascontiguousarray(a))
  return out


class Batcher:
  """Low-level handle over the C++ batcher (one input-tensor family).

  Most users want `batch_fn` / `batch_fn_with_options`; this class is
  the substrate (and what tests drive for out-of-order completion)."""

  # Lock discipline (round 18, guarded-by lint): the dtype/shape
  # metadata is published under _meta_lock (the C++ mutex orders the
  # actual batch handoff).
  _in_meta: guarded_by('_meta_lock')
  _out_meta: guarded_by('_meta_lock')

  def __init__(self, num_tensors: int, minimum_batch_size: int = 1,
               maximum_batch_size: int = 1024, timeout_ms: int = 100):
    self._lib = _ensure_lib()
    self._h = self._lib.batcher_create(
        minimum_batch_size, maximum_batch_size, timeout_ms, num_tensors)
    self._num_tensors = num_tensors
    self._meta_lock = make_lock('dynamic_batching.Batcher._meta_lock')
    # dtype/trailing-shape per input tensor, fixed by the first call
    # (published under the lock before compute_begin; the computation
    # thread reads after get_batch — the C++ mutex orders the two).
    self._in_meta: Optional[List] = None
    self._out_meta: Optional[List] = None
    self._closed = False

  # -- caller side --

  def compute(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Submit rows, block until the computation thread answers."""
    arrays = _as_contiguous(arrays)
    if len(arrays) != self._num_tensors:
      raise ValueError(
          f'expected {self._num_tensors} tensors, got {len(arrays)}')
    rows = arrays[0].shape[0]
    for a in arrays:
      if a.shape[0] != rows:
        raise ValueError('inconsistent leading (batch) dims: '
                         f'{[x.shape for x in arrays]}')
    with self._meta_lock:
      if self._in_meta is None:
        self._in_meta = [(a.dtype, a.shape[1:]) for a in arrays]
      else:
        for a, (dtype, trail) in zip(arrays, self._in_meta):
          if a.dtype != dtype or a.shape[1:] != trail:
            raise ValueError(
                f'tensor mismatch: got {a.dtype}{a.shape[1:]}, '
                f'expected {dtype}{trail}')

    i64 = ctypes.c_longlong
    n = self._num_tensors
    data = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    row_bytes = (i64 * n)(
        *[int(np.prod(a.shape[1:], dtype=np.int64)) * a.itemsize
          for a in arrays])
    req_id = i64(0)
    rc = self._lib.batcher_compute_begin(
        self._h, data, row_bytes, rows, ctypes.byref(req_id))
    if rc == RC_CLOSED:
      raise BatcherCancelled('batcher is closed')
    if rc == RC_TOO_BIG:
      raise ValueError(f'rows={rows} exceeds maximum_batch_size')
    if rc == RC_SHAPE:
      raise ValueError('row byte-size mismatch vs. earlier calls')
    assert rc == RC_OK, rc

    err = ctypes.create_string_buffer(4096)
    rc = self._lib.batcher_compute_wait(self._h, req_id, err, 4096)
    try:
      if rc == RC_ERROR:
        raise BatcherError(err.value.decode('utf-8', errors='replace'))
      if rc == RC_CANCELLED:
        raise BatcherCancelled('batcher closed while waiting')
      assert rc == RC_OK, rc
      with self._meta_lock:
        out_meta = list(self._out_meta)
      outs = []
      for i, (dtype, trail) in enumerate(out_meta):
        nbytes = self._lib.batcher_result_size(self._h, req_id, i)
        row_nb = int(np.prod(trail, dtype=np.int64)) * dtype.itemsize
        # out_meta can lag the stored output if the batched function's
        # trailing shape varies across batches; a partial row means the
        # snapshot is stale — fail loudly rather than mis-slice.
        if nbytes and (row_nb == 0 or nbytes % row_nb):
          raise BatcherError(
              f'output {i}: stored {nbytes} bytes is not a whole number '
              f'of rows of shape {tuple(trail)} dtype {dtype} '
              f'({row_nb} bytes/row) — batched fn output shape varied')
        out_rows = nbytes // row_nb if row_nb else 0
        buf = np.empty((out_rows,) + tuple(trail), dtype)
        if nbytes:
          rc = self._lib.batcher_result_copy(
              self._h, req_id, i, buf.ctypes.data_as(ctypes.c_void_p),
              buf.nbytes)
          assert rc == RC_OK, rc
        outs.append(buf)
      return outs
    finally:
      self._lib.batcher_request_free(self._h, req_id)

  # -- computation-thread side --

  def input_meta(self):
    """[(dtype, trailing_shape)] per input tensor, or None before the
    first compute() call fixed it."""
    with self._meta_lock:
      return list(self._in_meta) if self._in_meta is not None else None

  def get_batch_into(self, make_buffers):
    """Zero-copy variant of `get_batch`: the C++ merge-copy lands in
    caller-provided storage instead of freshly allocated arrays (the
    inference server hands its preallocated padded staging buffers, so
    the merged batch materializes already padded — no second
    concatenate/pad pass).

    Args:
      make_buffers: callable `(total_rows) -> [np.ndarray]` returning
        one C-contiguous array per input tensor, dtype/trailing shape
        matching `input_meta()` and leading capacity >= total_rows
        (only the first total_rows rows are written).

    Returns:
      (batch_id, total_rows, buffers) — or None when the batcher is
      closed and drained.
    """
    i64 = ctypes.c_longlong
    batch_id, total_rows = i64(0), i64(0)
    rc = self._lib.batcher_get_batch(
        self._h, ctypes.byref(batch_id), ctypes.byref(total_rows))
    if rc == RC_CLOSED:
      return None
    assert rc == RC_OK, rc
    try:
      buffers = make_buffers(total_rows.value)
      for i, buf in enumerate(buffers):
        rc = self._lib.batcher_batch_input_copy(
            self._h, batch_id, i, buf.ctypes.data_as(ctypes.c_void_p))
        if rc != RC_OK:
          # close() raced us and erased the batch — don't hand the
          # caller uninitialized memory; treat as shutdown.
          return None
      return batch_id.value, total_rows.value, buffers
    except Exception as e:
      # The batch was already dequeued: a make_buffers failure (e.g.
      # allocation under memory pressure) must not strand its parked
      # callers in compute_wait — answer them with the error, then
      # let the caller decide whether its loop survives.
      self.set_error(batch_id.value, f'{type(e).__name__}: {e}')
      raise

  def get_batch(self):
    """Block for the next merged batch → (batch_id, [np arrays]) or
    None when the batcher is closed and drained."""

    def alloc(total_rows):
      with self._meta_lock:
        in_meta = list(self._in_meta)
      return [np.empty((total_rows,) + tuple(trail), dtype)
              for dtype, trail in in_meta]

    item = self.get_batch_into(alloc)
    if item is None:
      return None
    batch_id, _, arrays = item
    return batch_id, arrays

  def set_outputs(self, batch_id: int, arrays: Sequence[np.ndarray]):
    arrays = _as_contiguous([np.asarray(a) for a in arrays])
    rows = arrays[0].shape[0]
    for a in arrays:
      if a.shape[0] != rows:
        raise ValueError('inconsistent output batch dims: '
                         f'{[x.shape for x in arrays]}')
    with self._meta_lock:
      self._out_meta = [(a.dtype, a.shape[1:]) for a in arrays]
    i64 = ctypes.c_longlong
    n = len(arrays)
    data = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrays])
    row_bytes = (i64 * n)(
        *[int(np.prod(a.shape[1:], dtype=np.int64)) * a.itemsize
          for a in arrays])
    rc = self._lib.batcher_set_outputs(
        self._h, batch_id, n, data, row_bytes, rows)
    if rc == RC_SIZE:
      raise ValueError('output rows do not match the batch rows')
    if rc not in (RC_OK, RC_BAD_ID):  # BAD_ID: batch cancelled by close
      raise RuntimeError(f'set_outputs rc={rc}')

  def set_error(self, batch_id: int, message: str):
    self._lib.batcher_set_error(self._h, batch_id,
                                message.encode('utf-8'))

  def close(self):
    if not self._closed:
      self._closed = True
      self._lib.batcher_close(self._h)

  def __del__(self):
    try:
      if getattr(self, '_h', None):
        self.close()
        self._lib.batcher_destroy(self._h)
        self._h = None
    except Exception:
      pass


class _BatchedFunction:
  """A callable wrapping `f` behind a Batcher + computation thread."""

  def __init__(self, f, minimum_batch_size, maximum_batch_size,
               timeout_ms):
    self._f = f
    self._opts = (minimum_batch_size, maximum_batch_size, timeout_ms)
    self._batcher: Optional[Batcher] = None
    self._thread: Optional[threading.Thread] = None
    self._start_lock = make_lock(
        'dynamic_batching._BatchedFunction._start_lock')
    self.__name__ = getattr(f, '__name__', 'batched_fn')

  def _loop(self):
    while True:
      item = self._batcher.get_batch()
      if item is None:
        return
      batch_id, arrays = item
      try:
        outs = self._f(*arrays)
        if isinstance(outs, np.ndarray):
          outs = (outs,)
        self._batcher.set_outputs(
            batch_id, [np.asarray(o) for o in outs])
      except Exception as e:  # propagate to the blocked callers
        self._batcher.set_error(batch_id, f'{type(e).__name__}: {e}')

  def _ensure_started(self, num_tensors):
    with self._start_lock:
      if self._batcher is None:
        mn, mx, to = self._opts
        self._batcher = Batcher(num_tensors, mn, mx, to)
        self._thread = threading.Thread(
            target=self._loop, name=f'batcher-{self.__name__}',
            daemon=True)
        self._thread.start()

  def __call__(self, *arrays):
    self._ensure_started(len(arrays))
    outs = self._batcher.compute([np.asarray(a) for a in arrays])
    return outs[0] if len(outs) == 1 else tuple(outs)

  def close(self):
    with self._start_lock:
      if self._batcher is not None:
        self._batcher.close()
        self._thread.join(timeout=5)


def batch_fn_with_options(minimum_batch_size: int = 1,
                          maximum_batch_size: int = 1024,
                          timeout_ms: int = 100):
  """Decorator: merge concurrent calls to `f` into batched calls
  (reference: dynamic_batching.batch_fn_with_options)."""

  def decorator(f):
    return _BatchedFunction(f, minimum_batch_size, maximum_batch_size,
                            timeout_ms)

  return decorator


def batch_fn(f):
  """Decorator with default options (reference: dynamic_batching.batch_fn)."""
  return _BatchedFunction(f, 1, 1024, 100)


def family_key(arrays: Sequence[np.ndarray]):
  """The obs-spec FAMILY of a request: dtype + trailing shape per
  tensor (the leading batch dim is what merging is free to vary).
  Hashable — the FamilyBatcher's routing key."""
  return tuple((np.asarray(a).dtype.str, np.asarray(a).shape[1:])
               for a in arrays)


class FamilyBatcher:
  """Obs-spec FAMILY bucketing over the C++ batcher (round 22): one
  logical batched function whose concurrent callers may carry
  DIFFERENT tensor specs — e.g. a heterogeneous fleet mixing 16x16
  cue_memory frames with 24x32 gridworld frames.

  The single-queue Batcher fixes one tensor family at the first call
  (a later 16x16 caller would either error or, in a pad-to-max
  design, ship every frame at the fleet-wide max shape). Here each
  family gets its OWN Batcher + computation thread, lazily on first
  sight, so merges never cross families and a frame never pads beyond
  its family's exact shape — the generalization of bucketed padding
  from batch-dim buckets to obs-spec buckets. The cost is one
  computation thread per family and merge opportunities that don't
  cross families (mixed fleets want per-family minimum_batch_size
  floors sized to the family's actor share, not the fleet).

  `make_fn(key)` builds the per-family handler (called once per new
  family; the key is `family_key` of the first request) — typically a
  jitted policy step specialized to that family's shapes.

  `padding_stats()` carries the measured perf claim: useful bytes
  served per family vs the counterfactual naive max-shape cost over
  the SAME request stream (every row padded to the widest family seen)
  — the bench.py population stage's mixed-suite row."""

  _families: guarded_by('_lock')
  _rows: guarded_by('_lock')

  def __init__(self, make_fn, minimum_batch_size: int = 1,
               maximum_batch_size: int = 1024, timeout_ms: int = 100):
    self._make_fn = make_fn
    self._opts = (minimum_batch_size, maximum_batch_size, timeout_ms)
    self._lock = make_lock('dynamic_batching.FamilyBatcher._lock')
    self._families = {}  # family key -> _BatchedFunction
    self._rows = {}      # family key -> rows served
    self._closed = False

  def _family(self, key):
    with self._lock:
      if self._closed:
        raise BatcherCancelled('family batcher is closed')
      fn = self._families.get(key)
      if fn is None:
        mn, mx, to = self._opts
        fn = _BatchedFunction(self._make_fn(key), mn, mx, to)
        fn.__name__ = f'family{len(self._families)}'
        self._families[key] = fn
        self._rows[key] = 0
      return fn

  def __call__(self, *arrays):
    arrays = [np.asarray(a) for a in arrays]
    key = family_key(arrays)
    fn = self._family(key)
    out = fn(*arrays)
    with self._lock:
      self._rows[key] += arrays[0].shape[0]
    return out

  @staticmethod
  def _row_bytes(key) -> int:
    total = 0
    for dtype_str, trail in key:
      total += int(np.prod(trail, dtype=np.int64)) * \
          np.dtype(dtype_str).itemsize
    return total

  def padding_stats(self):
    """Measured padded-bytes accounting over everything served so far:
    {families, rows, useful_bytes, max_shape_bytes, waste_ratio, ...}
    (population.padding_report's keys — bucketed == useful because
    family merges pad zero extra bytes; max_shape_bytes is what the
    same stream costs under naive pad-to-fleet-max)."""
    from scalable_agent_tpu import population
    with self._lock:
      counts = {(self._row_bytes(key),): rows
                for key, rows in self._rows.items() if rows}
      families = len(self._families)
      total_rows = float(sum(self._rows.values()))
    report = population.padding_report(counts)
    report['families'] = families
    report['rows'] = total_rows
    return report

  def close(self):
    with self._lock:
      self._closed = True
      families = list(self._families.values())
    for fn in families:
      fn.close()
