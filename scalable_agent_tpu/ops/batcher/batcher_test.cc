// Native concurrency stress test for batcher.cc — built plain and with
// -fsanitize=thread (make tsan-test). Exercises the full lifecycle
// under real thread contention: many callers, one computation thread,
// timeout flushes, max-size splits, an error batch, then close() with
// callers still parked. Exits 0 on success; TSAN reports fail the run.

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

using i64 = long long;

extern "C" {
void* batcher_create(i64, i64, i64, i64);
i64 batcher_compute_begin(void*, const void**, const i64*, i64, i64*);
i64 batcher_compute_wait(void*, i64, char*, i64);
i64 batcher_result_size(void*, i64, i64);
i64 batcher_result_copy(void*, i64, i64, void*, i64);
void batcher_request_free(void*, i64);
i64 batcher_get_batch(void*, i64*, i64*);
i64 batcher_batch_input_copy(void*, i64, i64, void*);
i64 batcher_set_outputs(void*, i64, i64, const void**, const i64*, i64);
i64 batcher_set_error(void*, i64, const char*);
void batcher_close(void*);
void batcher_destroy(void*);
}

namespace {

constexpr int kCallers = 32;
constexpr int kCallsPerCaller = 50;
std::atomic<int> ok_count{0};
std::atomic<int> err_count{0};
std::atomic<int> cancelled_count{0};

void caller(void* h, int tid) {
  for (int i = 0; i < kCallsPerCaller; ++i) {
    double v = tid * 1000 + i;
    const void* data[1] = {&v};
    i64 row_bytes[1] = {sizeof(double)};
    i64 req = 0;
    i64 rc = batcher_compute_begin(h, data, row_bytes, 1, &req);
    if (rc == 5 /*RC_CLOSED*/) {
      cancelled_count++;
      return;
    }
    assert(rc == 0);
    char err[256];
    rc = batcher_compute_wait(h, req, err, sizeof(err));
    if (rc == 0) {
      double out = 0;
      assert(batcher_result_size(h, req, 0) == (i64)sizeof(double));
      assert(batcher_result_copy(h, req, 0, &out, sizeof(double)) == 0);
      assert(out == v * 2);
      ok_count++;
    } else if (rc == 1) {
      assert(std::strcmp(err, "test error") == 0);
      err_count++;
    } else {
      assert(rc == 2);
      cancelled_count++;
      batcher_request_free(h, req);
      return;
    }
    batcher_request_free(h, req);
  }
}

void computation_loop(void* h) {
  int batch_no = 0;
  for (;;) {
    i64 batch_id = 0, rows = 0;
    i64 rc = batcher_get_batch(h, &batch_id, &rows);
    if (rc == 5 /*RC_CLOSED*/) return;
    assert(rc == 0 && rows >= 1);
    std::vector<double> in(rows);
    batcher_batch_input_copy(h, batch_id, 0, in.data());
    if (++batch_no % 97 == 0) {  // occasionally fail a whole batch
      batcher_set_error(h, batch_id, "test error");
      continue;
    }
    std::vector<double> out(rows);
    for (i64 i = 0; i < rows; ++i) out[i] = in[i] * 2;
    const void* data[1] = {out.data()};
    i64 row_bytes[1] = {sizeof(double)};
    rc = batcher_set_outputs(h, batch_id, 1, data, row_bytes, rows);
    assert(rc == 0 || rc == 6 /*batch cancelled by close*/);
  }
}

}  // namespace

int main() {
  // Phase 1: full run to completion.
  {
    void* h = batcher_create(8, 16, 2, 1);
    std::thread comp(computation_loop, h);
    std::vector<std::thread> callers;
    for (int t = 0; t < kCallers; ++t) callers.emplace_back(caller, h, t);
    for (auto& t : callers) t.join();
    batcher_close(h);
    comp.join();
    batcher_destroy(h);
    std::printf("phase1: ok=%d err=%d cancelled=%d\n", ok_count.load(),
                err_count.load(), cancelled_count.load());
    assert(ok_count + err_count == kCallers * kCallsPerCaller);
  }

  // Phase 2: close() while callers are parked (min never reached).
  {
    ok_count = err_count = cancelled_count = 0;
    void* h = batcher_create(1000, 0, 60000, 1);
    std::vector<std::thread> callers;
    for (int t = 0; t < 8; ++t) callers.emplace_back(caller, h, t);
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    batcher_close(h);
    for (auto& t : callers) t.join();
    batcher_destroy(h);
    std::printf("phase2: cancelled=%d\n", cancelled_count.load());
    assert(cancelled_count == 8);
  }
  std::printf("batcher_test: PASS\n");
  return 0;
}
