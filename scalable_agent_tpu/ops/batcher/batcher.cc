// Host-side dynamic request batcher (C ABI, consumed via ctypes).
//
// TPU-native re-design of the reference's TensorFlow custom op
// (reference: batcher.cc — REGISTER_OP("Batcher"), BatcherCompute /
// BatcherGetInputs / BatcherSetOutputs / BatcherClose, ≈500 LoC): same
// contract — many caller threads each submit a small batch of rows and
// block; a single computation thread receives merged batches
// (concatenated along dim 0 when >= minimum size or after timeout_ms,
// capped at maximum), runs the (jitted, batched) function, and returns
// per-caller slices. Errors propagate to exactly the affected batch's
// callers; close() cancels all waiters. Unlike the reference this is
// not a TF graph op: it is a plain shared library with a blocking C
// API, so the "function" can be a jitted JAX callable on TPU.
//
// Synchronization: one mutex + two condition_variables (caller-side and
// batcher-side). Tensors are opaque byte rows — dtype/shape handling
// stays in Python; C++ owns buffering, merging, splitting and wakeups.
//
// Build: make (g++ -O2 -fPIC -shared, plus a -fsanitize=thread target;
// SURVEY §5.2).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

using i64 = long long;
using Clock = std::chrono::steady_clock;

enum ReqState { PENDING, IN_BATCH, DONE, ERROR, CANCELLED };

// Return codes (mirrored in the Python wrapper).
enum Rc {
  RC_OK = 0,
  RC_ERROR = 1,      // computation failed; message available
  RC_CANCELLED = 2,  // batcher closed while waiting
  RC_SHAPE = 3,      // row size mismatch vs. first request
  RC_TOO_BIG = 4,    // rows > maximum_batch_size
  RC_CLOSED = 5,     // submitted/polled after close
  RC_BAD_ID = 6,     // unknown request/batch id
  RC_SIZE = 7,       // set_outputs rows != batch rows
};

struct Request {
  i64 id = 0;
  i64 rows = 0;
  ReqState state = PENDING;
  Clock::time_point enqueue_time;
  std::vector<std::vector<char>> inputs;   // one buffer per tensor
  std::vector<std::vector<char>> outputs;  // filled by set_outputs split
  std::string error;
};

struct Batch {
  i64 id = 0;
  i64 total_rows = 0;
  std::vector<i64> req_ids;
  std::vector<i64> req_rows;
  bool delivered = false;  // handed to the computation thread
};

struct Batcher {
  std::mutex mu;
  std::condition_variable caller_cv;   // requests: DONE/ERROR/CANCELLED
  std::condition_variable batcher_cv;  // computation thread: work ready

  i64 min_rows, max_rows, timeout_ms, num_tensors;
  bool closed = false;

  i64 next_req_id = 1;
  i64 next_batch_id = 1;

  std::vector<i64> input_row_bytes;  // fixed by the first request
  std::deque<i64> pending;           // FIFO of request ids
  i64 pending_rows = 0;
  std::map<i64, Request> requests;
  std::map<i64, Batch> batches;
};

Batcher* H(void* h) { return static_cast<Batcher*>(h); }

void cancel_request_locked(Request& r) {
  if (r.state == PENDING || r.state == IN_BATCH) {
    r.state = CANCELLED;
  }
}

}  // namespace

extern "C" {

void* batcher_create(i64 min_rows, i64 max_rows, i64 timeout_ms,
                     i64 num_tensors) {
  auto* b = new Batcher();
  b->min_rows = min_rows < 1 ? 1 : min_rows;
  b->max_rows = max_rows;
  b->timeout_ms = timeout_ms;
  b->num_tensors = num_tensors;
  b->input_row_bytes.assign(num_tensors, -1);
  return b;
}

// Caller side ---------------------------------------------------------

// Enqueue `rows` rows of `num_tensors` tensors. data[i] points at
// rows*row_bytes[i] bytes. On success *req_id_out identifies the
// request for wait/result/free.
i64 batcher_compute_begin(void* h, const void** data,
                          const i64* row_bytes, i64 rows,
                          i64* req_id_out) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  if (b->closed) return RC_CLOSED;
  if (rows < 1 || (b->max_rows > 0 && rows > b->max_rows))
    return RC_TOO_BIG;
  for (i64 i = 0; i < b->num_tensors; ++i) {
    if (b->input_row_bytes[i] < 0) {
      b->input_row_bytes[i] = row_bytes[i];
    } else if (b->input_row_bytes[i] != row_bytes[i]) {
      return RC_SHAPE;
    }
  }
  i64 id = b->next_req_id++;
  Request& r = b->requests[id];
  r.id = id;
  r.rows = rows;
  r.enqueue_time = Clock::now();
  r.inputs.resize(b->num_tensors);
  for (i64 i = 0; i < b->num_tensors; ++i) {
    const char* src = static_cast<const char*>(data[i]);
    r.inputs[i].assign(src, src + rows * row_bytes[i]);
  }
  b->pending.push_back(id);
  b->pending_rows += rows;
  *req_id_out = id;
  b->batcher_cv.notify_all();
  return RC_OK;
}

// Block until the request resolves. RC_OK: results readable.
// RC_ERROR: message copied into err_buf. RC_CANCELLED: batcher closed.
i64 batcher_compute_wait(void* h, i64 req_id, char* err_buf,
                         i64 err_buf_len) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->requests.find(req_id);
  if (it == b->requests.end()) return RC_BAD_ID;
  Request& r = it->second;
  b->caller_cv.wait(lock, [&] {
    return r.state == DONE || r.state == ERROR || r.state == CANCELLED;
  });
  if (r.state == DONE) return RC_OK;
  if (r.state == ERROR) {
    if (err_buf && err_buf_len > 0) {
      std::snprintf(err_buf, err_buf_len, "%s", r.error.c_str());
    }
    return RC_ERROR;
  }
  return RC_CANCELLED;
}

i64 batcher_result_count(void* h, i64 req_id) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->requests.find(req_id);
  if (it == b->requests.end()) return -1;
  return static_cast<i64>(it->second.outputs.size());
}

i64 batcher_result_size(void* h, i64 req_id, i64 tensor_idx) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->requests.find(req_id);
  if (it == b->requests.end()) return -1;
  auto& outs = it->second.outputs;
  if (tensor_idx < 0 || tensor_idx >= (i64)outs.size()) return -1;
  return static_cast<i64>(outs[tensor_idx].size());
}

// Copies at most `capacity` bytes — the caller sizes dst from its own
// metadata, which can lag the stored output if the batched function's
// trailing shape varies across batches; never overrun the caller.
i64 batcher_result_copy(void* h, i64 req_id, i64 tensor_idx, void* dst,
                        i64 capacity) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->requests.find(req_id);
  if (it == b->requests.end()) return RC_BAD_ID;
  auto& outs = it->second.outputs;
  if (tensor_idx < 0 || tensor_idx >= (i64)outs.size()) return RC_BAD_ID;
  i64 size = static_cast<i64>(outs[tensor_idx].size());
  if (capacity < size) return RC_SIZE;
  std::memcpy(dst, outs[tensor_idx].data(), size);
  return RC_OK;
}

void batcher_request_free(void* h, i64 req_id) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  b->requests.erase(req_id);
}

// Computation-thread side --------------------------------------------

// Block until a batch is ready (>= min rows, or timeout_ms after the
// oldest pending request, or close). RC_OK: *batch_id/*total_rows set.
// RC_CLOSED: batcher closed and nothing pending.
i64 batcher_get_batch(void* h, i64* batch_id, i64* total_rows) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  for (;;) {
    if (b->pending_rows > 0) {
      bool full = b->pending_rows >= b->min_rows;
      auto& oldest = b->requests[b->pending.front()];
      auto deadline =
          oldest.enqueue_time + std::chrono::milliseconds(b->timeout_ms);
      if (full || Clock::now() >= deadline) {
        // Pop FIFO up to max_rows (never splitting one request).
        Batch batch;
        batch.id = b->next_batch_id++;
        while (!b->pending.empty()) {
          i64 rid = b->pending.front();
          Request& r = b->requests[rid];
          if (b->max_rows > 0 &&
              batch.total_rows + r.rows > b->max_rows &&
              batch.total_rows > 0)
            break;
          b->pending.pop_front();
          b->pending_rows -= r.rows;
          r.state = IN_BATCH;
          batch.req_ids.push_back(rid);
          batch.req_rows.push_back(r.rows);
          batch.total_rows += r.rows;
        }
        *batch_id = batch.id;
        *total_rows = batch.total_rows;
        b->batches[batch.id] = std::move(batch);
        return RC_OK;
      }
      b->batcher_cv.wait_until(lock, deadline);
      continue;
    }
    if (b->closed) return RC_CLOSED;
    b->batcher_cv.wait(lock);
  }
}

// Concatenate the batch's rows for one input tensor into dst
// (total_rows * row_bytes bytes).
i64 batcher_batch_input_copy(void* h, i64 batch_id, i64 tensor_idx,
                             void* dst) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->batches.find(batch_id);
  if (it == b->batches.end()) return RC_BAD_ID;
  if (tensor_idx < 0 || tensor_idx >= b->num_tensors) return RC_BAD_ID;
  char* out = static_cast<char*>(dst);
  for (i64 rid : it->second.req_ids) {
    auto& buf = b->requests[rid].inputs[tensor_idx];
    std::memcpy(out, buf.data(), buf.size());
    out += buf.size();
  }
  return RC_OK;
}

// Split `num_outputs` tensors of total_rows rows back to the batch's
// requests (row_bytes[i] bytes per row of output i) and wake them.
// Requests cancelled in the meantime are skipped.
i64 batcher_set_outputs(void* h, i64 batch_id, i64 num_outputs,
                        const void** data, const i64* row_bytes,
                        i64 total_rows) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->batches.find(batch_id);
  if (it == b->batches.end()) return RC_BAD_ID;
  Batch& batch = it->second;
  if (total_rows != batch.total_rows) return RC_SIZE;
  i64 offset_rows = 0;
  for (size_t k = 0; k < batch.req_ids.size(); ++k) {
    i64 rid = batch.req_ids[k];
    i64 rows = batch.req_rows[k];
    auto rit = b->requests.find(rid);
    if (rit != b->requests.end() && rit->second.state == IN_BATCH) {
      Request& r = rit->second;
      r.outputs.resize(num_outputs);
      for (i64 i = 0; i < num_outputs; ++i) {
        const char* src = static_cast<const char*>(data[i]) +
                          offset_rows * row_bytes[i];
        r.outputs[i].assign(src, src + rows * row_bytes[i]);
      }
      r.state = DONE;
    }
    offset_rows += rows;
  }
  b->batches.erase(it);
  b->caller_cv.notify_all();
  return RC_OK;
}

// Fail every request in the batch with `msg`.
i64 batcher_set_error(void* h, i64 batch_id, const char* msg) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  auto it = b->batches.find(batch_id);
  if (it == b->batches.end()) return RC_BAD_ID;
  for (i64 rid : it->second.req_ids) {
    auto rit = b->requests.find(rid);
    if (rit != b->requests.end() && rit->second.state == IN_BATCH) {
      rit->second.state = ERROR;
      rit->second.error = msg ? msg : "unknown error";
    }
  }
  b->batches.erase(it);
  b->caller_cv.notify_all();
  return RC_OK;
}

// Cancel all pending/in-flight requests; wake everyone. get_batch
// returns RC_CLOSED once the queue drains.
void batcher_close(void* h) {
  Batcher* b = H(h);
  std::unique_lock<std::mutex> lock(b->mu);
  b->closed = true;
  for (auto& kv : b->requests) cancel_request_locked(kv.second);
  b->pending.clear();
  b->pending_rows = 0;
  b->batches.clear();
  b->caller_cv.notify_all();
  b->batcher_cv.notify_all();
}

void batcher_destroy(void* h) { delete H(h); }

}  // extern "C"
