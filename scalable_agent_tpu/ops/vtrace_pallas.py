"""Fused V-trace as a single Pallas TPU kernel.

The SURVEY (§7) names "fused vtrace+loss" as the one Pallas candidate
in this model family; this implements the V-trace half: everything
`vtrace.from_importance_weights` does — exp/clip of the importance
weights, the temporal-difference deltas, the backward linear recursion
and the policy-gradient advantages — in ONE kernel, so no intermediate
([T, B] rhos/cs/deltas/vs) ever round-trips through HBM, and the
recursion runs as ceil(log2 T) fully-vectorized VMEM-resident
pointer-doubling passes instead of an XLA while-loop with per-step
buffer plumbing.

Contrast with the reference, which not only materializes every
intermediate but pins the scan to the *CPU* with a comment that XLA
could do better (reference: experiment.py ≈L355, vtrace.py ≈L170–195).

Layout: time-major [T, B]; the grid runs over 128-lane batch blocks
(lanes = batch members — each lane owns an independent recursion; the
time loop walks sublane rows). B is padded to the lane width; T is
whatever the unroll is (T=100 → ~50 KB per [T, 128] f32 operand, far
under VMEM).

Numerics match vtrace.from_importance_weights to float32
reassociation tolerance (the doubling recursion reorders the
accumulation; ~1e-5 absolute at T=100) — vtrace_test.py's ground-truth
applies.

Measured on TPU v5e (1 chip, T=100, B=32, async-dispatch chain,
round 2): XLA scan 851 µs, associative_scan 807 µs, **this kernel
604 µs** per call — the pointer-doubling recursion (see
`_vtrace_kernel`) keeps all operands VMEM-resident across the whole
computation and uses the full 8-sublane VPU, beating both XLA forms.
(Round 1's row-at-a-time `fori_loop` version measured 1490 µs; the
fix was vectorizing the recursion, not more blocking.)
`pallas_call` has no SPMD partitioning rule, so the kernel cannot be
left to GSPMD under a sharded step — but V-trace is per-batch-column
INDEPENDENT, so `sharded_from_importance_weights` (round 8) wraps the
call in `shard_map` over the mesh's data axis: each device runs the
kernel on its own [T, B/D] shard, no collectives, numerics identical
to the single-device kernel on the concatenated batch. The round-3
"single-device only" driver restriction is lifted; the sharded
flagship step can take the fused kernel (`use_pallas_vtrace` under
any pure-shardable mesh — parity-gated vs the lax.scan form on the
8-virtual-device mesh, tests/test_parallel.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.shard_map import shard_map

LANE = 128  # TPU lane width: batch block size


def _vtrace_kernel(clips_ref, log_rhos_ref, discounts_ref, rewards_ref,
                   values_ref, bootstrap_ref, vs_ref, pg_ref):
  """One batch block: full V-trace in VMEM, recursion by doubling.

  clips_ref: SMEM f32 [2] = (rho-bar, pg-rho-bar); +inf encodes "no
  clipping" (min(inf, x) == x), so thresholds may be traced values.

  The backward recursion acc_r = delta_r + dc_r · acc_{r+1} is a
  composition of affine maps f_r(x) = B_r + A_r·x. Pointer-doubling
  composes each row with the row `offset` below it (identity padding
  past the end), doubling coverage per pass: after ceil(log2 T) fully
  vectorized [T, LANE] passes, B_r holds the whole suffix — i.e.
  vs_r − v_r. A first version looped `fori_loop` row-at-a-time
  instead (1/8 sublane utilization + per-iteration overhead) and LOST
  to the XLA scan; this form is what makes the kernel win (timings in
  the module docstring).
  """
  t = log_rhos_ref.shape[0]
  rhos = jnp.exp(log_rhos_ref[:])                       # [T, LANE]
  clipped_rhos = jnp.minimum(clips_ref[0], rhos)
  cs = jnp.minimum(1.0, rhos)
  discounts = discounts_ref[:]
  rewards = rewards_ref[:]
  values = values_ref[:]
  bootstrap = bootstrap_ref[:]                          # [1, LANE]

  values_t_plus_1 = jnp.concatenate([values[1:], bootstrap], axis=0)
  b_acc = clipped_rhos * (rewards +
                          discounts * values_t_plus_1 - values)
  a_acc = discounts * cs

  offset = 1
  while offset < t:  # static python loop: ceil(log2 T) passes
    ident_a = jnp.ones((offset, LANE), a_acc.dtype)
    ident_b = jnp.zeros((offset, LANE), b_acc.dtype)
    a_shift = jnp.concatenate([a_acc[offset:], ident_a], axis=0)
    b_shift = jnp.concatenate([b_acc[offset:], ident_b], axis=0)
    b_acc = b_acc + a_acc * b_shift
    a_acc = a_acc * a_shift
    offset *= 2

  vs = b_acc + values
  vs_ref[:] = vs
  vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap], axis=0)
  clipped_pg_rhos = jnp.minimum(clips_ref[1], rhos)
  pg_ref[:] = clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 -
                                 values)


def from_importance_weights(log_rhos, discounts, rewards, values,
                            bootstrap_value, clip_rho_threshold=1.0,
                            clip_pg_rho_threshold=1.0, interpret=None):
  """Pallas-fused V-trace; drop-in for the math of
  `vtrace.from_importance_weights` (returns plain (vs, pg_advantages)
  arrays — the caller wraps/stop-gradients).

  Rank-generic like the reference: trailing dims beyond [T, B] are
  flattened into the lane axis (each lane is an independent recursion,
  so this is exact). `interpret=None` auto-selects interpreter mode off
  TPU (CI runs the same kernel code path).
  """
  if interpret is None:
    interpret = jax.default_backend() != 'tpu'

  log_rhos = jnp.asarray(log_rhos, jnp.float32)
  discounts = jnp.asarray(discounts, jnp.float32)
  rewards = jnp.asarray(rewards, jnp.float32)
  values = jnp.asarray(values, jnp.float32)
  bootstrap_value = jnp.asarray(bootstrap_value, jnp.float32)

  orig_shape = log_rhos.shape
  t = orig_shape[0]
  # Flatten [T, B, ...] → [T, N]; pad N up to the lane width.
  n = 1
  for d in orig_shape[1:]:
    n *= d
  flat = lambda x: x.reshape(t, n)  # noqa: E731
  log_rhos_f, discounts_f, rewards_f, values_f = map(
      flat, (log_rhos, discounts, rewards, values))
  bootstrap_f = bootstrap_value.reshape(1, n)

  n_pad = max(LANE, ((n + LANE - 1) // LANE) * LANE)
  pad = n_pad - n
  if pad:
    padt = lambda x: jnp.pad(x, ((0, 0), (0, pad)))  # noqa: E731
    log_rhos_f, discounts_f, rewards_f, values_f, bootstrap_f = (
        padt(log_rhos_f), padt(discounts_f), padt(rewards_f),
        padt(values_f), padt(bootstrap_f))

  inf = jnp.float32(jnp.inf)
  clips = jnp.stack([
      inf if clip_rho_threshold is None
      else jnp.asarray(clip_rho_threshold, jnp.float32),
      inf if clip_pg_rho_threshold is None
      else jnp.asarray(clip_pg_rho_threshold, jnp.float32)])

  grid = (n_pad // LANE,)
  time_block = lambda j: (0, j)  # noqa: E731
  specs = pl.BlockSpec((t, LANE), time_block,
                       memory_space=pltpu.VMEM)
  boot_spec = pl.BlockSpec((1, LANE), time_block,
                           memory_space=pltpu.VMEM)
  clip_spec = pl.BlockSpec((2,), lambda j: (0,),
                           memory_space=pltpu.SMEM)
  vs, pg = pl.pallas_call(
      _vtrace_kernel,
      grid=grid,
      in_specs=[clip_spec, specs, specs, specs, specs, boot_spec],
      out_specs=[specs, specs],
      out_shape=[jax.ShapeDtypeStruct((t, n_pad), jnp.float32),
                 jax.ShapeDtypeStruct((t, n_pad), jnp.float32)],
      interpret=interpret,
  )(clips, log_rhos_f, discounts_f, rewards_f, values_f, bootstrap_f)

  vs = vs[:, :n].reshape(orig_shape)
  pg = pg[:, :n].reshape(orig_shape)
  return vs, pg


def sharded_from_importance_weights(mesh, log_rhos, discounts, rewards,
                                    values, bootstrap_value,
                                    clip_rho_threshold=1.0,
                                    clip_pg_rho_threshold=1.0,
                                    batch_axis='data',
                                    interpret=None):
  """The fused kernel under a mesh: `shard_map` over the batch axis.

  Each batch column is an independent recursion, so mapping the
  kernel over the data axis is exact — every device runs the
  single-device kernel on its own [T, B/D] shard with zero
  collectives, and GSPMD reshards the (possibly differently-placed)
  intermediates to `P(None, batch_axis)` at the shard_map boundary.
  Mesh axes beyond `batch_axis` (a TP model axis) are left unmentioned
  → the shard replicates across them, matching how the [T, B]
  V-trace operands already live under TP.

  B must divide the `batch_axis` width — the same divisibility the
  driver's mesh choice already guarantees for the learner batch.
  `check_rep=False`: outputs are replicated over the unmentioned axes
  by construction (pure per-shard math), but shard_map's replication
  checker cannot see through `pallas_call` to prove it.
  """
  from scalable_agent_tpu.parallel import sharding as sharding_lib
  ndim = jnp.ndim(log_rhos)
  spec_t = sharding_lib.spec_time_major(ndim, axis=batch_axis)
  spec_b = sharding_lib.spec_batch_lead(ndim - 1, axis=batch_axis)
  fn = functools.partial(
      from_importance_weights,
      clip_rho_threshold=clip_rho_threshold,
      clip_pg_rho_threshold=clip_pg_rho_threshold,
      interpret=interpret)
  return shard_map(
      fn, mesh=mesh,
      in_specs=(spec_t, spec_t, spec_t, spec_t, spec_b),
      out_specs=(spec_t, spec_t),
      check_rep=False)(log_rhos, discounts, rewards, values,
                       bootstrap_value)
