"""Fused V-trace as a single Pallas TPU kernel.

The SURVEY (§7) names "fused vtrace+loss" as the one Pallas candidate
in this model family; this implements the V-trace half: everything
`vtrace.from_importance_weights` does — exp/clip of the importance
weights, the temporal-difference deltas, the backward linear recursion
and the policy-gradient advantages — in ONE kernel, so no intermediate
([T, B] rhos/cs/deltas/vs) ever round-trips through HBM and the
sequential recursion runs as a VMEM-resident loop instead of an XLA
while-loop with per-step buffer plumbing.

Contrast with the reference, which not only materializes every
intermediate but pins the scan to the *CPU* with a comment that XLA
could do better (reference: experiment.py ≈L355, vtrace.py ≈L170–195).

Layout: time-major [T, B]; the grid runs over 128-lane batch blocks
(lanes = batch members — each lane owns an independent recursion; the
time loop walks sublane rows). B is padded to the lane width; T is
whatever the unroll is (T=100 → ~50 KB per [T, 128] f32 operand, far
under VMEM).

Numerics match vtrace.from_importance_weights bit-for-bit in f32 (same
op order per element); vtrace_test.py's ground-truth applies.

Measured on TPU v5e (1 chip, T=100, B=32, async-dispatch chain):
scan 885 us, associative_scan 723 us, this kernel 1490 us per call —
the row-at-a-time VMEM loop underuses the 8-sublane VPU, so XLA's
fused scan wins at IMPALA sizes and `use_pallas_vtrace` defaults to
False. The kernel remains the door to a blocked/sequence-parallel
formulation at much larger T, and the in-repo example of the Pallas
playbook (grid/BlockSpec/SMEM scalars/VMEM scratch/`pl.ds` loops).
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128  # TPU lane width: batch block size


def _vtrace_kernel(clips_ref, log_rhos_ref, discounts_ref, rewards_ref,
                   values_ref, bootstrap_ref, vs_ref, pg_ref,
                   deltas_ref, dcs_ref):
  """One batch block: full V-trace, recursion over time in VMEM.

  clips_ref: SMEM f32 [2] = (rho-bar, pg-rho-bar); +inf encodes "no
  clipping" (min(inf, x) == x), so thresholds may be traced values.
  deltas_ref/dcs_ref: VMEM scratch — the vectorized precompute lands
  there so the sequential loop can read rows via `pl.ds` (Mosaic has
  dynamic ref indexing but no dynamic_slice on materialized values).
  """
  t = log_rhos_ref.shape[0]
  rhos = jnp.exp(log_rhos_ref[:])                       # [T, LANE]
  clipped_rhos = jnp.minimum(clips_ref[0], rhos)
  cs = jnp.minimum(1.0, rhos)
  discounts = discounts_ref[:]
  rewards = rewards_ref[:]
  values = values_ref[:]
  bootstrap = bootstrap_ref[:]                          # [1, LANE]

  values_t_plus_1 = jnp.concatenate([values[1:], bootstrap], axis=0)
  deltas_ref[:] = clipped_rhos * (rewards +
                                  discounts * values_t_plus_1 - values)
  dcs_ref[:] = discounts * cs

  def body(i, acc):
    # Backward over time: row = T-1-i; acc is vs_minus_v at row+1.
    row = t - 1 - i
    acc = (deltas_ref[pl.ds(row, 1), :] +
           dcs_ref[pl.ds(row, 1), :] * acc)
    vs_ref[pl.ds(row, 1), :] = acc + values_ref[pl.ds(row, 1), :]
    return acc

  jax.lax.fori_loop(0, t, body, jnp.zeros_like(bootstrap))

  vs = vs_ref[:]
  vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap], axis=0)
  clipped_pg_rhos = jnp.minimum(clips_ref[1], rhos)
  pg_ref[:] = clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 -
                                 values)


def from_importance_weights(log_rhos, discounts, rewards, values,
                            bootstrap_value, clip_rho_threshold=1.0,
                            clip_pg_rho_threshold=1.0, interpret=None):
  """Pallas-fused V-trace; drop-in for the math of
  `vtrace.from_importance_weights` (returns plain (vs, pg_advantages)
  arrays — the caller wraps/stop-gradients).

  Rank-generic like the reference: trailing dims beyond [T, B] are
  flattened into the lane axis (each lane is an independent recursion,
  so this is exact). `interpret=None` auto-selects interpreter mode off
  TPU (CI runs the same kernel code path).
  """
  if interpret is None:
    interpret = jax.default_backend() != 'tpu'

  log_rhos = jnp.asarray(log_rhos, jnp.float32)
  discounts = jnp.asarray(discounts, jnp.float32)
  rewards = jnp.asarray(rewards, jnp.float32)
  values = jnp.asarray(values, jnp.float32)
  bootstrap_value = jnp.asarray(bootstrap_value, jnp.float32)

  orig_shape = log_rhos.shape
  t = orig_shape[0]
  # Flatten [T, B, ...] → [T, N]; pad N up to the lane width.
  n = 1
  for d in orig_shape[1:]:
    n *= d
  flat = lambda x: x.reshape(t, n)  # noqa: E731
  log_rhos_f, discounts_f, rewards_f, values_f = map(
      flat, (log_rhos, discounts, rewards, values))
  bootstrap_f = bootstrap_value.reshape(1, n)

  n_pad = max(LANE, ((n + LANE - 1) // LANE) * LANE)
  pad = n_pad - n
  if pad:
    padt = lambda x: jnp.pad(x, ((0, 0), (0, pad)))  # noqa: E731
    log_rhos_f, discounts_f, rewards_f, values_f, bootstrap_f = (
        padt(log_rhos_f), padt(discounts_f), padt(rewards_f),
        padt(values_f), padt(bootstrap_f))

  inf = jnp.float32(jnp.inf)
  clips = jnp.stack([
      inf if clip_rho_threshold is None
      else jnp.asarray(clip_rho_threshold, jnp.float32),
      inf if clip_pg_rho_threshold is None
      else jnp.asarray(clip_pg_rho_threshold, jnp.float32)])

  grid = (n_pad // LANE,)
  time_block = lambda j: (0, j)  # noqa: E731
  specs = pl.BlockSpec((t, LANE), time_block,
                       memory_space=pltpu.VMEM)
  boot_spec = pl.BlockSpec((1, LANE), time_block,
                           memory_space=pltpu.VMEM)
  clip_spec = pl.BlockSpec((2,), lambda j: (0,),
                           memory_space=pltpu.SMEM)
  vs, pg = pl.pallas_call(
      _vtrace_kernel,
      grid=grid,
      in_specs=[clip_spec, specs, specs, specs, specs, boot_spec],
      out_specs=[specs, specs],
      out_shape=[jax.ShapeDtypeStruct((t, n_pad), jnp.float32),
                 jax.ShapeDtypeStruct((t, n_pad), jnp.float32)],
      scratch_shapes=[pltpu.VMEM((t, LANE), jnp.float32),
                      pltpu.VMEM((t, LANE), jnp.float32)],
      interpret=interpret,
  )(clips, log_rhos_f, discounts_f, rewards_f, values_f, bootstrap_f)

  vs = vs[:, :n].reshape(orig_shape)
  pg = pg[:, :n].reshape(orig_shape)
  return vs, pg
