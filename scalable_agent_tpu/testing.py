"""Shared test/bench fixtures: synthetic trajectory batches.

One canonical constructor for a random learner batch so tests, the
driver entry points, and bench.py can't drift apart when the trajectory
structs change.
"""

import numpy as np

import jax.numpy as jnp

from scalable_agent_tpu.structs import (
    ActorOutput, AgentOutput, StepOutput, StepOutputInfo)


def make_example_unroll(t1, h, w, num_actions, instr_len, seed=0,
                        hidden_size=256):
  """One random host-side ActorOutput unroll ([T+1] numpy, batch dim 1
  on the core state) — what a single actor ships over the wire."""
  rng = np.random.RandomState(seed)
  return ActorOutput(
      level_name=np.int32(0),
      agent_state=(np.zeros((1, hidden_size), np.float32),
                   np.zeros((1, hidden_size), np.float32)),
      env_outputs=StepOutput(
          reward=rng.randn(t1).astype(np.float32),
          info=StepOutputInfo(np.zeros(t1, np.float32),
                              np.zeros(t1, np.int32)),
          done=np.zeros(t1, bool),
          observation=(
              rng.randint(0, 255, (t1, h, w, 3)).astype(np.uint8),
              np.zeros((t1, instr_len), np.int32))),
      agent_outputs=AgentOutput(
          action=rng.randint(0, num_actions, t1).astype(np.int32),
          policy_logits=rng.randn(t1, num_actions).astype(np.float32),
          baseline=rng.randn(t1).astype(np.float32)))


def make_example_batch(t1, b, h, w, num_actions, instr_len, seed=0,
                       done_prob=0.05, hidden_size=256):
  """Random ActorOutput batch: [T+1=t1, B=b] time-major trajectory."""
  rng = np.random.RandomState(seed)
  return ActorOutput(
      level_name=jnp.zeros((b,), jnp.int32),
      agent_state=(jnp.zeros((b, hidden_size), jnp.float32),
                   jnp.zeros((b, hidden_size), jnp.float32)),
      env_outputs=StepOutput(
          reward=jnp.asarray(rng.randn(t1, b), jnp.float32),
          info=StepOutputInfo(jnp.zeros((t1, b), jnp.float32),
                              jnp.zeros((t1, b), jnp.int32)),
          done=jnp.asarray(rng.rand(t1, b) < done_prob),
          observation=(
              jnp.asarray(rng.randint(0, 255, (t1, b, h, w, 3)),
                          jnp.uint8),
              jnp.asarray(rng.randint(0, 1000, (t1, b, instr_len)),
                          jnp.int32))),
      agent_outputs=AgentOutput(
          action=jnp.asarray(rng.randint(0, num_actions, (t1, b)),
                             jnp.int32),
          policy_logits=jnp.asarray(rng.randn(t1, b, num_actions),
                                    jnp.float32),
          baseline=jnp.asarray(rng.randn(t1, b), jnp.float32)))
