"""Visual torsos: shallow CNN and deep ResNet (flax.linen).

Re-expresses the reference's `Agent._torso` (reference: experiment.py
≈L120): frames are uint8, scaled by 1/255 on device, run through either

- **deep**: 3 sections [(16, 2), (32, 2), (32, 2)] of Conv3x3 →
  3x3/2 max-pool → 2 residual blocks (relu-conv-relu-conv + skip),
  then relu → flatten → Linear(256) → relu. This is the IMPALA deep
  ResNet, the only torso the reference ships.
- **shallow**: Conv 8x8/4 (16) → Conv 4x4/2 (32) → flatten →
  Linear(256), relu between layers. The paper's shallow model, offered
  as a config (BASELINE.json config 1) though absent from the reference
  repo.

TPU notes: convs are NHWC (XLA's native TPU layout); `dtype` selects the
compute dtype (bfloat16 recommended on TPU — params stay float32).
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class ResidualBlock(nn.Module):
  channels: int
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, x):
    y = nn.relu(x)
    y = nn.Conv(self.channels, (3, 3), padding='SAME', dtype=self.dtype)(y)
    y = nn.relu(y)
    y = nn.Conv(self.channels, (3, 3), padding='SAME', dtype=self.dtype)(y)
    return x + y


class DeepResNetTorso(nn.Module):
  """IMPALA deep torso (reference: experiment.py ≈L120)."""
  sections: Sequence[Tuple[int, int]] = ((16, 2), (32, 2), (32, 2))
  output_size: int = 256
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, frame):
    x = frame.astype(self.dtype) / 255.0
    for channels, num_blocks in self.sections:
      x = nn.Conv(channels, (3, 3), padding='SAME', dtype=self.dtype)(x)
      x = nn.max_pool(x, (3, 3), strides=(2, 2), padding='SAME')
      for _ in range(num_blocks):
        x = ResidualBlock(channels, dtype=self.dtype)(x)
    x = nn.relu(x)
    x = x.reshape((x.shape[0], -1))
    x = nn.Dense(self.output_size, dtype=self.dtype)(x)
    return nn.relu(x)


class DeepFastTorso(nn.Module):
  """`deep_fast`: the deep ResNet with each section's conv3x3 +
  maxpool3x3/2 replaced by a single stride-2 conv3x3.

  HBM-bandwidth variant (docs/PERF.md round 5): the flagship step is
  memory-bound and the per-section PRE-POOL activation (section 1:
  [3232, 72, 96, 16] bf16 = 715 MB at flagship shapes) dominates the
  backward's byte traffic; producing the downsampled activation
  directly removes that tensor and the pool's select-and-scatter
  backward entirely. Same parameter count/shapes as `deep` (conv
  kernels are 3x3 either way), NOT weight-compatible in function: a
  smaller receptive field per section (3 vs 5) and no max nonlinearity
  — an opt-in operating point, not the parity model."""
  sections: Sequence[Tuple[int, int]] = ((16, 2), (32, 2), (32, 2))
  output_size: int = 256
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, frame):
    x = frame.astype(self.dtype) / 255.0
    for channels, num_blocks in self.sections:
      x = nn.Conv(channels, (3, 3), strides=(2, 2), padding='SAME',
                  dtype=self.dtype)(x)
      for _ in range(num_blocks):
        x = ResidualBlock(channels, dtype=self.dtype)(x)
    x = nn.relu(x)
    x = x.reshape((x.shape[0], -1))
    x = nn.Dense(self.output_size, dtype=self.dtype)(x)
    return nn.relu(x)


class ShallowTorso(nn.Module):
  """Paper's shallow 2-conv torso (not in the reference repo; see module
  docstring)."""
  output_size: int = 256
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, frame):
    h, w = frame.shape[1], frame.shape[2]
    if h < 20 or w < 20:
      # VALID 8x8/4 then 4x4/2 needs >= 20 px per dim; smaller frames
      # reach a zero-size activation and die in flax initializers with
      # an inscrutable ZeroDivisionError.
      raise ValueError(
          f'shallow torso needs frames >= 20x20, got {h}x{w} '
          '(--height/--width)')
    x = frame.astype(self.dtype) / 255.0
    x = nn.relu(nn.Conv(16, (8, 8), strides=(4, 4), padding='VALID',
                        dtype=self.dtype)(x))
    x = nn.relu(nn.Conv(32, (4, 4), strides=(2, 2), padding='VALID',
                        dtype=self.dtype)(x))
    x = x.reshape((x.shape[0], -1))
    x = nn.Dense(self.output_size, dtype=self.dtype)(x)
    return nn.relu(x)


TORSOS = {
    'deep': DeepResNetTorso,
    'deep_fast': DeepFastTorso,
    'shallow': ShallowTorso,
}
