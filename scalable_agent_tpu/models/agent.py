"""The IMPALA agent network (flax.linen), TPU-first.

Re-designs the reference's `class Agent(snt.RNNCore)` (reference:
experiment.py ≈L85–210) for XLA:

- The torso (conv net) is applied to the WHOLE [T, B] unroll at once by
  merging time into the batch dimension — one big MXU-friendly conv batch
  instead of per-step calls (the reference gets this via
  `snt.BatchApply`).
- The recurrent core is a `nn.scan` (lax.scan under jit) over time with
  the per-step done-reset expressed as `jnp.where(done, 0, state)` on the
  carry — the reference does this with a *Python* loop over `tf.unstack`
  + `tf.where` (experiment.py ≈L195–205), which it comments precludes
  fused RNN kernels; the scan form compiles to a single fused XLA loop.
- Heads (policy logits, baseline) again run over the merged [T*B] batch.

Inputs each step, matching the reference contract: `(last_action,
StepOutput(reward, info, done, (frame, instruction_ids)))`. Rewards are
clipped to [-1, 1] and concatenated with the one-hot last action and the
instruction encoding before the core (reference `_torso` ≈L120).
"""

import functools
from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from scalable_agent_tpu.structs import AgentOutput
from scalable_agent_tpu.models.torsos import TORSOS
from scalable_agent_tpu.models.instruction import InstructionEncoder
from scalable_agent_tpu.unreal import PixelControlHead


class _ResetCore(nn.Module):
  """LSTM core whose carry is zeroed wherever `done` is set (before the
  step — `done[t]` marks the first observation of a new episode)."""
  hidden_size: int
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, carry, inputs):
    x, done = inputs
    carry = jax.tree_util.tree_map(
        lambda s: jnp.where(done[:, None], jnp.zeros_like(s), s), carry)
    cell = nn.OptimizedLSTMCell(self.hidden_size, dtype=self.dtype)
    carry, out = cell(carry, x)
    return carry, out


class ImpalaAgent(nn.Module):
  """IMPALA agent: torso → LSTM core → policy/baseline heads."""
  num_actions: int
  torso: str = 'deep'        # 'deep' (reference) | 'shallow' (paper)
  hidden_size: int = 256
  use_instruction: bool = True
  # PopArt (popart.py): >0 ⇒ the value head emits one NORMALIZED value
  # column per task and `level_ids` selects each trajectory's column.
  num_popart_tasks: int = 0
  # UNREAL pixel control (unreal.py): adds the auxiliary deconv Q-head.
  use_pixel_control: bool = False
  pixel_control_cell_size: int = 4
  # Q-head deconv implementation ('deconv' | 'd2s') and output dtype —
  # the round-6 fast-path knobs (config.pixel_control_head_impl /
  # pixel_control_q_f32; parity-gated in tests/test_unreal.py). Both
  # impls share one param tree, so checkpoints are interchangeable.
  pixel_control_head_impl: str = 'deconv'
  pixel_control_q_f32: bool = True
  # Partial unrolling of the LSTM time scan (XLA loop unroll factor):
  # amortizes per-iteration loop overhead on TPU; must divide nothing
  # (lax.scan handles remainders). 1 = plain scan.
  scan_unroll: int = 1
  dtype: jnp.dtype = jnp.float32

  def initial_state(self, batch_size):
    """Zeroed LSTM carry (c, h), each [B, hidden] (reference ≈L90)."""
    shape = (batch_size, self.hidden_size)
    return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))

  @nn.compact
  def __call__(self, prev_actions, env_outputs, core_state,
               sample_rng=None, level_ids=None,
               compute_pixel_control=False):
    """Unroll over a [T, B] trajectory.

    Args:
      prev_actions: i32 [T, B] — action taken *before* each timestep.
      env_outputs: StepOutput of [T, B, ...] tensors; observation is
        (frame uint8 [T, B, H, W, C], instruction ids i32 [T, B, L]).
      core_state: LSTM carry (c, h) each [B, hidden] at unroll start.
      sample_rng: PRNG key → actions are sampled from the policy
        (actor/eval path, reference `tf.multinomial` ≈L165); None →
        argmax (learner path, where the action output is unused).
      level_ids: i32 [B] task ids (PopArt only) — selects each
        trajectory's value column. None → task 0 (the act-time path,
        where the recorded baseline is unused by the learner).
      compute_pixel_control: run the auxiliary pixel-control Q-head
        and sow its output as intermediates['pixel_control_q']
        ([T, B, Hc, Wc, A]) — learner path only; actors skip the
        deconv cost. Params exist either way (created at init).

    Returns:
      (AgentOutput([T, B, ...]), final core_state).
    """
    reward, _, done, (frame, instr_ids) = env_outputs
    t, b = reward.shape[0], reward.shape[1]

    # --- Torso over merged time+batch (one big MXU batch). ---
    # (Torso rematerialization was tried and REJECTED: +20% step time
    # at [T=100, B=32] — XLA's remat re-reads more bytes than it
    # saves here. Measurements in docs/PERF.md.)
    flat_frame = frame.reshape((t * b,) + frame.shape[2:])
    torso_out = TORSOS[self.torso](dtype=self.dtype)(flat_frame)

    clipped_reward = jnp.clip(reward, -1.0, 1.0).reshape(t * b, 1)
    one_hot_action = jax.nn.one_hot(
        prev_actions.reshape(t * b), self.num_actions, dtype=torso_out.dtype)
    parts = [torso_out, clipped_reward.astype(torso_out.dtype),
             one_hot_action]
    if self.use_instruction:
      flat_ids = instr_ids.reshape((t * b,) + instr_ids.shape[2:])
      parts.append(InstructionEncoder(dtype=self.dtype)(flat_ids))
    core_input = jnp.concatenate(parts, axis=-1).reshape(t, b, -1)

    # --- Recurrent core: scan over time with done-reset on the carry. ---
    scan = nn.scan(
        lambda core, carry, x: core(carry, x),
        variable_broadcast='params', split_rngs={'params': False},
        in_axes=0, out_axes=0, unroll=self.scan_unroll)
    core = _ResetCore(self.hidden_size, dtype=self.dtype)
    core_state = jax.tree_util.tree_map(
        lambda s: s.astype(self.dtype), core_state)
    new_state, core_out = scan(core, core_state, (core_input, done))
    new_state = jax.tree_util.tree_map(
        lambda s: s.astype(jnp.float32), new_state)

    # --- Heads over merged time+batch. ---
    flat_core = core_out.reshape(t * b, -1)
    if self.use_pixel_control and (compute_pixel_control or
                                   self.is_initializing()):
      cell = self.pixel_control_cell_size
      hc, wc = frame.shape[2] // cell, frame.shape[3] // cell
      pc_q = PixelControlHead(self.num_actions, (hc, wc),
                              dtype=self.dtype,
                              head_impl=self.pixel_control_head_impl,
                              out_f32=self.pixel_control_q_f32,
                              name='pixel_control')(flat_core)
      self.sow('intermediates', 'pixel_control_q',
               pc_q.reshape(t, b, hc, wc, self.num_actions))
    policy_logits = nn.Dense(self.num_actions, dtype=self.dtype,
                             name='policy_logits')(flat_core)
    num_values = max(self.num_popart_tasks, 1)
    baseline = nn.Dense(num_values, dtype=self.dtype,
                        name='baseline')(flat_core)
    policy_logits = policy_logits.astype(jnp.float32).reshape(
        t, b, self.num_actions)
    baseline = baseline.astype(jnp.float32).reshape(t, b, num_values)
    if self.num_popart_tasks:
      if level_ids is None:
        level_ids = jnp.zeros((b,), jnp.int32)
      baseline = jnp.take_along_axis(
          baseline, level_ids[None, :, None].astype(jnp.int32),
          axis=2)
    baseline = baseline[..., 0]

    if sample_rng is not None:
      action = jax.random.categorical(sample_rng, policy_logits, axis=-1)
    else:
      action = jnp.argmax(policy_logits, axis=-1)
    action = action.astype(jnp.int32)

    return AgentOutput(action, policy_logits, baseline), new_state


def make_step_fn(agent: ImpalaAgent):
  """Single-step (T=1) policy for actors: batch-shaped, no time axis.

  Returns f(params, rng, prev_action [B], env_output of [B, ...],
  core_state) → (AgentOutput of [B, ...], new_state). Jit this and serve
  it behind the dynamic batcher.
  """

  @functools.partial(jax.jit, static_argnums=())
  def step(params, rng, prev_action, env_output, core_state):
    env_output_t = jax.tree_util.tree_map(lambda x: x[None], env_output)
    out, new_state = agent.apply(
        params, prev_action[None], env_output_t, core_state,
        sample_rng=rng)
    return jax.tree_util.tree_map(lambda x: x[0], out), new_state

  return step


def init_params(agent: ImpalaAgent, rng, obs_spec, batch_size=1):
  """Initialize parameters from an observation spec pytree.

  obs_spec: dict with 'frame' (H, W, C) uint8 and 'instr_len' L.
  """
  h, w, c = obs_spec['frame']
  l = obs_spec['instr_len']
  t, b = 2, batch_size
  from scalable_agent_tpu.structs import StepOutput, StepOutputInfo
  dummy = StepOutput(
      reward=jnp.zeros((t, b), jnp.float32),
      info=StepOutputInfo(jnp.zeros((t, b), jnp.float32),
                          jnp.zeros((t, b), jnp.int32)),
      done=jnp.zeros((t, b), bool),
      observation=(jnp.zeros((t, b, h, w, c), jnp.uint8),
                   jnp.zeros((t, b, l), jnp.int32)))
  prev_actions = jnp.zeros((t, b), jnp.int32)
  return agent.init(rng, prev_actions, dummy,
                    agent.initial_state(b))
