from scalable_agent_tpu.models.agent import (  # noqa: F401
    ImpalaAgent, init_params, make_step_fn)
from scalable_agent_tpu.models.torsos import (  # noqa: F401
    DeepResNetTorso, ShallowTorso, TORSOS)
from scalable_agent_tpu.models.instruction import (  # noqa: F401
    InstructionEncoder, hash_instruction, MAX_INSTRUCTION_LEN, VOCAB_SIZE)
