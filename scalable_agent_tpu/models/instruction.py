"""Language-instruction pathway.

The reference feeds DMLab's INSTR string through `tf.string_split` →
hash-to-1000-buckets → Embed(20) → dynamic LSTM(64), taking the last
output (reference: experiment.py `_instruction` ≈L95). Strings cannot
reach a TPU, so the device dtype contract here is:

- **host side**: `hash_instruction(text, ...)` tokenizes on whitespace and
  hashes each token into [1, vocab] (0 is reserved for padding), padding /
  truncating to a fixed `max_len`. This happens in the env adapter, so the
  trajectory pytree carries int32 ids only.
- **device side**: `InstructionEncoder` embeds the ids, runs an LSTM over
  the fixed-length padded sequence, and gathers the output at the last
  non-pad position (positions beyond the length cannot influence it).
"""

import zlib

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

VOCAB_SIZE = 1000  # hash buckets, matching the reference
MAX_INSTRUCTION_LEN = 16
EMBED_SIZE = 20
LSTM_SIZE = 64


def hash_instruction(text, vocab_size=VOCAB_SIZE,
                     max_len=MAX_INSTRUCTION_LEN):
  """Host-side: whitespace-split + stable hash → int32 [max_len] ids.

  Uses crc32 (stable across processes/runs, unlike Python's `hash`) in
  place of the reference's FarmHash bucketing — the exact hash family is
  not load-bearing, only its stability and range.
  """
  if isinstance(text, bytes):
    text = text.decode('utf-8', errors='replace')
  ids = np.zeros((max_len,), dtype=np.int32)
  for i, token in enumerate(text.split()[:max_len]):
    ids[i] = (zlib.crc32(token.encode('utf-8')) % vocab_size) + 1
  return ids


def empty_instruction(max_len=MAX_INSTRUCTION_LEN):
  """All-pad ids for env families with no language channel (Atari)."""
  return np.zeros((max_len,), dtype=np.int32)


class InstructionEncoder(nn.Module):
  """Device-side: ids [B, L] → f32 [B, LSTM_SIZE]."""
  vocab_size: int = VOCAB_SIZE
  embed_size: int = EMBED_SIZE
  lstm_size: int = LSTM_SIZE
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, ids):
    batch = ids.shape[0]
    # 0 is the pad id; ids are 1-based.
    emb = nn.Embed(self.vocab_size + 1, self.embed_size,
                   dtype=self.dtype)(ids)  # [B, L, E]
    cell = nn.OptimizedLSTMCell(self.lstm_size, dtype=self.dtype)
    # Fully unrolled: L=16 steps — unrolling removes the XLA loop
    # overhead entirely (measured win on v5e; see models/agent.py
    # scan_unroll for the time-scan analog).
    scan = nn.scan(
        lambda c, carry, x: c(carry, x),
        variable_broadcast='params', split_rngs={'params': False},
        in_axes=1, out_axes=1, unroll=True)
    import jax
    carry = cell.initialize_carry(
        jax.random.PRNGKey(0), (batch, self.embed_size))
    _, outputs = scan(cell, carry, emb)  # [B, L, H]
    lengths = jnp.sum((ids != 0).astype(jnp.int32), axis=1)  # [B]
    last = jnp.clip(lengths - 1, 0, ids.shape[1] - 1)
    gathered = jnp.take_along_axis(
        outputs, last[:, None, None].astype(jnp.int32), axis=1
    ).squeeze(1)  # [B, H]
    # Empty instruction → zeros (matches "no signal", avoids garbage state).
    return jnp.where(lengths[:, None] > 0, gathered,
                     jnp.zeros_like(gathered))
