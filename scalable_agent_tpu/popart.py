"""PopArt value normalization (multi-task IMPALA extension).

NOT in the reference — listed there as a planned extension (SURVEY
§2.12 / BASELINE.json config ladder). Implements Pop-Art ("Preserving
Outputs Precisely while Adaptively Rescaling Targets", van Hasselt et
al. 2016) as used by multi-task PopArt-IMPALA (Hessel et al. 2018):

- the value head emits NORMALIZED per-task values n_i(x) (one output
  column per task; the agent selects the column for each trajectory's
  task id);
- per-task first/second moments (μ_i, ν_i) track the V-trace targets
  with an EMA; σ_i = sqrt(ν_i − μ_i²), clipped;
- V-trace runs on UNNORMALIZED values σ·n + μ; the baseline loss runs
  in normalized space (targets (vs − μ)/σ);
- whenever the statistics move, the head's weights are rewritten so
  its unnormalized outputs are preserved exactly:
      w'_i = w_i·σ_i/σ'_i,   b'_i = (σ_i·b_i + μ_i − μ'_i)/σ'_i.

Everything is a pure function over `PopArtState` — it lives in the
TrainState pytree, is checkpointed with it, and runs inside the one
jitted learner step.

Mixed heterogeneous fleets (round 22): with `--fleet_tasks` the task
axis is the parsed suite order from
`population.parse_fleet_tasks(config.fleet_tasks)` — the fleet
builder stamps each actor slot's `level_name_id` with its suite
index, so PopArt column i is suite i's running target scale. Nothing
here changes: per-task normalization was already the contract; the
fleet wiring just widened what "task" can mean from level-within-one-
suite to suite-within-one-fleet.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

# Paper defaults (Hessel et al. 2018 §3 / appendix).
DEFAULT_BETA = 3e-4
DEFAULT_SIGMA_MIN = 1e-4
DEFAULT_SIGMA_MAX = 1e6


class PopArtState(NamedTuple):
  mu: Any   # f32 [num_tasks] — first moment of value targets
  nu: Any   # f32 [num_tasks] — second moment
  sigma_min: Any = DEFAULT_SIGMA_MIN
  sigma_max: Any = DEFAULT_SIGMA_MAX


def init(num_tasks: int, sigma_min: float = DEFAULT_SIGMA_MIN,
         sigma_max: float = DEFAULT_SIGMA_MAX) -> PopArtState:
  """μ=0, ν=1 ⇒ σ=1: normalization starts as the identity."""
  return PopArtState(
      mu=jnp.zeros((num_tasks,), jnp.float32),
      nu=jnp.ones((num_tasks,), jnp.float32),
      sigma_min=jnp.float32(sigma_min),
      sigma_max=jnp.float32(sigma_max))


def sigma(state: PopArtState):
  # Clip the VARIANCE before the sqrt: float rounding can push
  # nu - mu² slightly negative for a near-constant-target task, and
  # sqrt(negative) = NaN would poison the head permanently.
  variance = jnp.clip(state.nu - jnp.square(state.mu),
                      jnp.square(state.sigma_min),
                      jnp.square(state.sigma_max))
  return jnp.sqrt(variance)


def unnormalize(state: PopArtState, normalized_values, task_ids):
  """σ[task]·n + μ[task]. task_ids broadcasts against the trailing
  batch dim of [T, B] values (ids are per-trajectory, [B])."""
  return (sigma(state)[task_ids] * normalized_values +
          state.mu[task_ids])


def normalize(state: PopArtState, values, task_ids):
  return (values - state.mu[task_ids]) / sigma(state)[task_ids]


def update_stats(state: PopArtState, targets, task_ids,
                 beta: float = DEFAULT_BETA) -> PopArtState:
  """EMA the per-task moments toward this batch's value targets.

  Args:
    state: current statistics.
    targets: f32 [T, B] unnormalized value targets (V-trace vs).
    task_ids: i32 [B] task id per trajectory.
    beta: EMA step size. Tasks absent from the batch keep their stats
      (their effective beta is 0 — no decay toward unseen data).
  """
  num_tasks = state.mu.shape[0]
  onehot = jax.nn.one_hot(task_ids, num_tasks, dtype=jnp.float32)  # [B,K]
  count = jnp.einsum('tb,bk->k', jnp.ones_like(targets), onehot)
  total = jnp.einsum('tb,bk->k', targets, onehot)
  total_sq = jnp.einsum('tb,bk->k', jnp.square(targets), onehot)
  present = count > 0
  safe = jnp.maximum(count, 1.0)
  batch_mu = total / safe
  batch_nu = total_sq / safe
  new_mu = jnp.where(present, (1 - beta) * state.mu + beta * batch_mu,
                     state.mu)
  new_nu = jnp.where(present, (1 - beta) * state.nu + beta * batch_nu,
                     state.nu)
  return state._replace(mu=new_mu, nu=new_nu)


def stats_summary(state: PopArtState, task_names=None):
  """Per-task normalization stats as plain Python (artifacts/logs).

  Returns {'mu': [...], 'sigma': [...]} (floats, task order), plus
  'tasks' when `task_names` is given. Round 22: in a `--fleet_tasks`
  run, task order is the parse_fleet_tasks suite order, so this is a
  free per-suite target-scale readout — a suite whose σ never moved
  off 1.0 never contributed a batch.
  """
  mu = [float(x) for x in jax.device_get(state.mu)]
  sig = [float(x) for x in jax.device_get(sigma(state))]
  out = {'mu': mu, 'sigma': sig}
  if task_names is not None:
    out['tasks'] = list(task_names)
  return out


def preserve_outputs(kernel, bias, old: PopArtState, new: PopArtState):
  """Rewrite the value head so unnormalized outputs are unchanged.

  kernel: f32 [hidden, num_tasks]; bias: f32 [num_tasks]. Returns the
  rewritten (kernel, bias). Exact per task: for every input x,
  σ'·(w'x + b') + μ' == σ·(wx + b) + μ.
  """
  old_sigma, new_sigma = sigma(old), sigma(new)
  new_kernel = kernel * (old_sigma / new_sigma)[None, :]
  new_bias = (old_sigma * bias + old.mu - new.mu) / new_sigma
  return new_kernel, new_bias


def apply_preservation(params, old: PopArtState, new: PopArtState,
                       head_name: str = 'baseline'):
  """preserve_outputs applied inside the agent param pytree (flax
  layout: params['params'][head_name]{'kernel','bias'})."""
  tree = params['params'] if 'params' in params else params
  head = tree[head_name]
  new_kernel, new_bias = preserve_outputs(head['kernel'], head['bias'],
                                          old, new)
  new_head = dict(head, kernel=new_kernel, bias=new_bias)
  new_tree = dict(tree)
  new_tree[head_name] = new_head
  if 'params' in params:
    return dict(params, params=new_tree)
  return new_tree
