"""Training-health watchdog: sentinels, escalation ladder, diagnostics.

The learner is the single point of failure of the decoupled IMPALA
topology: actors respawn (runtime/fleet.py) and reconnect
(runtime/remote.py), but one NaN step, one diverging PopArt scale, or
one corrupt checkpoint used to kill — or silently poison — the whole
run. This module is the learner-side failure domain:

1. **Device-side sentinel + skip** (learner.make_train_step_fn, gated
   by config.health_watchdog): the step computes
   `step_ok = isfinite(total_loss) & isfinite(grad_norm)` and applies
   the parameter/optimizer/PopArt update ONLY when ok — a non-finite
   step is skipped in-graph (params carry over unchanged) at the cost
   of one `where` per leaf, no host sync. `metrics['step_ok']` reports
   it.

2. **Host-side monitor** (`HealthMonitor`): one tiny device_get per
   check (the sentinel scalars stacked into a single array) feeds a
   sliding window with three detectors — non-finite (the device
   already skipped it), loss explosion against the window median, and
   PopArt-σ divergence against its own window. Bad steps escalate:

     skip-and-count  →  ROLLBACK after K consecutive bad steps
                     →  HALT after max_rollbacks rollbacks

   driver.train acts on the verdicts: rollback restores the
   last-known-good checkpoint (checkpoint.Checkpointer.restore_last_
   good) keeping the monotone step/frame counter; halt writes a
   diagnostic bundle (last metrics window + config + versions) and
   raises `TrainingDivergence` instead of training through divergence.

The reference has none of this: its learner trains through NaNs until
the job dies (SURVEY §5.3/5.4 — recovery is a runbook entry, not a
code path).
"""

import collections
import dataclasses
import json
import os
import threading
import time
from typing import Dict, NamedTuple, Optional

import numpy as np

# Verdicts (strings, not enum: they go straight into logs/JSONL).
OK = 'ok'
BAD = 'bad'
ROLLBACK = 'rollback'
HALT = 'halt'

# Sentinel keys read from the step metrics, in wire order. Missing
# keys (no PopArt) read as NaN and their detectors stay off.
# 'sdc_replica_mismatch' is NOT in this list: it is merged host-side
# by the driver from the per-replica fingerprint readback (a [D]
# uint32 array — it cannot ride the f32 sentinel stack exactly).
_SENTINEL_KEYS = ('step_ok', 'total_loss', 'grad_norm',
                  'popart_sigma_min', 'popart_sigma_max')


class TrainingDivergence(RuntimeError):
  """Training health escalated past its rollback budget; the run was
  halted with a diagnostic bundle instead of training through
  divergence. `.bundle_path` names the bundle when one was written."""

  def __init__(self, message: str, bundle_path: Optional[str] = None):
    super().__init__(message)
    self.bundle_path = bundle_path


class SentinelHandle(NamedTuple):
  """Device-side stacked sentinels, not yet transferred. The driver
  stashes the handle for one step and reads it AFTER the next step
  was dispatched — by then the values are computed, so the device_get
  returns without stalling the dispatch pipeline (per-step health at
  zero sync cost, at the price of one step of detection latency; the
  in-graph skip protects params at zero latency regardless)."""
  keys: tuple
  array: object  # [len(keys)] f32 device array


def stack_sentinels(metrics: Dict) -> SentinelHandle:
  """Stack the tiny health scalars into ONE device array (a single
  transfer per check instead of one sync per key). Keys a config
  doesn't produce (PopArt off) are simply absent from the handle."""
  import jax.numpy as jnp
  present = tuple(k for k in _SENTINEL_KEYS if k in metrics)
  stacked = jnp.stack([jnp.asarray(metrics[k], jnp.float32)
                       for k in present])
  return SentinelHandle(keys=present, array=stacked)


def read_handle(handle: SentinelHandle) -> Dict[str, float]:
  """Transfer a handle's values to host. Missing keys come back None
  — distinct from NaN, which means 'produced and non-finite'."""
  import jax
  values = np.asarray(jax.device_get(handle.array))
  out = {k: None for k in _SENTINEL_KEYS}
  out.update({k: float(v) for k, v in zip(handle.keys, values)})
  return out


def read_sentinels(metrics: Dict) -> Dict[str, float]:
  """Immediate (blocking) sentinel read: stack + transfer now."""
  return read_handle(stack_sentinels(metrics))


@dataclasses.dataclass
class _WindowEntry:
  step: int
  wall_time: float
  values: Dict[str, float]
  verdict: str
  reason: str


class HealthMonitor:
  """Sliding-window divergence detection + the escalation ladder.

  Args:
    window: retained recent checks (also the diagnostic bundle's
      metrics tail).
    min_window: good samples required before the relative detectors
      (loss explosion, σ divergence) arm — cold-start losses are not a
      baseline.
    rollback_after: K consecutive bad steps before a ROLLBACK verdict.
    max_rollbacks: rollbacks granted before the ladder escalates to
      HALT (the (max_rollbacks+1)-th request halts).
    loss_explosion_factor: |loss| beyond this multiple of the window
      median |loss| flags the step bad even when finite.
    sigma_divergence_factor: PopArt σ_max beyond this multiple of its
      window median flags the step bad (a diverging value scale shows
      up here long before NaNs — soak.py's observation, now acted on).
  """

  def __init__(self, window: int = 64, min_window: int = 16,
               rollback_after: int = 5, max_rollbacks: int = 3,
               loss_explosion_factor: float = 100.0,
               sigma_divergence_factor: float = 10.0):
    if rollback_after < 1:
      raise ValueError('rollback_after must be >= 1')
    self._window = collections.deque(maxlen=max(window, 8))
    self._good_losses = collections.deque(maxlen=max(window, 8))
    self._good_sigmas = collections.deque(maxlen=max(window, 8))
    self._good_sigma_mins = collections.deque(maxlen=max(window, 8))
    self._min_window = min_window
    self._rollback_after = rollback_after
    self._max_rollbacks = max_rollbacks
    self._loss_factor = loss_explosion_factor
    self._sigma_factor = sigma_divergence_factor
    self._consecutive_bad = 0
    self.skipped_steps = 0    # device-side skipped (non-finite)
    self.flagged_steps = 0    # all bad verdicts (incl. host-detected)
    self.rollbacks = 0
    self.halts = 0
    # SDC sentinel (round 12): steps whose per-replica param
    # fingerprints DISAGREED — deterministic compute violated on some
    # chip. Counted separately from non-finite skips: a NaN burst is
    # (usually) the math diverging; a fingerprint mismatch is the
    # HARDWARE lying, and the operator response differs
    # (docs/RUNBOOK.md §9 — drain the suspect host vs tune the run).
    self.sdc_mismatches = 0
    self.last_reason = ''     # why the most recent bad step was bad
    # External (non-learner-step) incidents other planes report into
    # the health surface (round 11: the transport watchdog's wedged
    # ingest threads, reaped half-open connections; round 14: SLO
    # burns from the evaluator thread) — counted per kind so the
    # drain manifest / postmortem carries them next to the
    # step-health counters instead of only in summaries.jsonl. Lock:
    # since round 14 note_external is called from the SLO engine's
    # thread as well as the driver thread.
    self._external: Dict[str, int] = {}
    self._external_lock = threading.Lock()
    # Unified-registry view (round 13, telemetry.py): lazy gauges over
    # this monitor's ladder counters — the drain manifest, flight
    # recorder, and the remote 'stats' request read the SAME numbers
    # the driver's summaries carry, from one source of truth.
    from scalable_agent_tpu import telemetry
    telemetry.gauge('health/skipped_steps',
                    fn=lambda: self.skipped_steps)
    telemetry.gauge('health/flagged_steps',
                    fn=lambda: self.flagged_steps)
    telemetry.gauge('health/rollbacks', fn=lambda: self.rollbacks)
    telemetry.gauge('health/halts', fn=lambda: self.halts)
    telemetry.gauge('health/sdc_mismatches',
                    fn=lambda: self.sdc_mismatches)

  # --- detectors ---

  def _classify(self, values: Dict[str, float]):
    """(is_bad, reason) for one step's sentinel values. A value of
    None means 'not produced by this config' (detector stays off);
    NaN/inf means 'produced and non-finite' (bad)."""
    sdc = values.get('sdc_replica_mismatch')
    if sdc is not None and sdc > 0.5:
      # Checked FIRST: a replica whose params copy silently diverged
      # invalidates every other sentinel this step produced (they
      # were computed against corrupt state on that replica). The
      # rollback restore re-replicates params from the checkpoint —
      # exactly the repair SDC needs.
      return True, ('SDC: per-replica param fingerprints disagree — '
                    'deterministic compute violated (suspect chip/'
                    'HBM; see docs/RUNBOOK.md §9)')
    step_ok = values.get('step_ok')
    if step_ok is not None and step_ok < 0.5:
      return True, 'non-finite loss/grad (update skipped on device)'
    loss = values.get('total_loss')
    if loss is not None and not np.isfinite(loss):
      return True, f'non-finite total_loss ({loss})'
    grad = values.get('grad_norm')
    if grad is not None and not np.isfinite(grad):
      return True, f'non-finite grad_norm ({grad})'
    if loss is not None and len(self._good_losses) >= self._min_window:
      # Absolute floor 1.0 on the baseline: the detector targets
      # CATASTROPHIC divergence (orders of magnitude), and a healthy
      # converged run's median |loss| approaches 0 — without the
      # floor, ordinary O(1) fluctuations around a near-zero median
      # would flag (measured: soak's bandit run converges to median
      # ~0.003 with benign |loss|≈5 spikes).
      baseline = float(np.median(np.abs(self._good_losses)))
      if abs(loss) > self._loss_factor * max(baseline, 1.0):
        return True, (f'loss explosion: |{loss:.4g}| > '
                      f'{self._loss_factor:g} x window median '
                      f'{baseline:.4g}')
    sigma = values.get('popart_sigma_max')
    if (sigma is not None and np.isfinite(sigma)
        and len(self._good_sigmas) >= self._min_window):
      baseline = float(np.median(self._good_sigmas))
      if sigma > self._sigma_factor * max(baseline, 1e-6):
        return True, (f'PopArt sigma divergence: {sigma:.4g} > '
                      f'{self._sigma_factor:g} x window median '
                      f'{baseline:.4g}')
    # The symmetric failure: sigma COLLAPSING (toward the clip floor)
    # flattens the normalized value targets — same factor, inverted.
    sigma_min = values.get('popart_sigma_min')
    if (sigma_min is not None and np.isfinite(sigma_min)
        and len(self._good_sigma_mins) >= self._min_window):
      baseline = float(np.median(self._good_sigma_mins))
      if sigma_min * self._sigma_factor < baseline:
        return True, (f'PopArt sigma collapse: {sigma_min:.4g} < '
                      f'window median {baseline:.4g} / '
                      f'{self._sigma_factor:g}')
    return False, ''

  # --- the ladder ---

  def observe(self, step: int, metrics: Dict) -> str:
    """Feed one step's metrics; returns a verdict (OK/BAD/ROLLBACK/
    HALT). Exactly one device transfer. The caller acts on
    ROLLBACK/HALT; BAD means 'skipped and counted, keep going'."""
    return self.observe_values(step, read_sentinels(metrics))

  def observe_values(self, step: int, values: Dict[str, float]) -> str:
    """`observe` on already-host values (unit tests, replays)."""
    bad, reason = self._classify(values)
    verdict = OK
    if bad:
      self.last_reason = reason
      self.flagged_steps += 1
      if reason.startswith('SDC:'):
        self.sdc_mismatches += 1
      step_ok = values.get('step_ok')
      if step_ok is not None and step_ok < 0.5:
        self.skipped_steps += 1
      self._consecutive_bad += 1
      verdict = BAD
      if self._consecutive_bad >= self._rollback_after:
        self._consecutive_bad = 0
        # `rollbacks` counts rollbacks GRANTED; the request past the
        # budget halts without being counted as one (the bundle and
        # the halt message must report performed rollbacks, not
        # requests).
        if self.rollbacks >= self._max_rollbacks:
          self.halts += 1
          verdict = HALT
        else:
          self.rollbacks += 1
          verdict = ROLLBACK
    else:
      self._consecutive_bad = 0
      loss = values.get('total_loss')
      if loss is not None and np.isfinite(loss):
        self._good_losses.append(loss)
      sigma = values.get('popart_sigma_max')
      if sigma is not None and np.isfinite(sigma):
        self._good_sigmas.append(sigma)
      sigma_min = values.get('popart_sigma_min')
      if sigma_min is not None and np.isfinite(sigma_min):
        self._good_sigma_mins.append(sigma_min)
    self._window.append(_WindowEntry(
        step=int(step), wall_time=round(time.time(), 3), values=values,
        verdict=verdict, reason=reason))
    return verdict

  @property
  def consecutive_bad(self) -> int:
    return self._consecutive_bad

  def note_external(self, kind: str, count: int = 1):
    """Record an incident another plane detected (transport wedge,
    connection reap burst). Does NOT feed the escalation ladder —
    these are not learner-step verdicts — but the counts ride
    `stats()`/`drain_report()` so the drain manifest and the halt
    bundle name what the transport plane absorbed."""
    with self._external_lock:
      self._external[kind] = self._external.get(kind, 0) + int(count)

  @property
  def external_incidents(self) -> Dict[str, int]:
    with self._external_lock:
      return dict(self._external)

  def stats(self) -> Dict[str, float]:
    """Counters the driver writes to summaries every interval."""
    return {'skipped_steps': self.skipped_steps,
            'flagged_steps': self.flagged_steps,
            'rollbacks': self.rollbacks,
            'halts': self.halts,
            'sdc_mismatches': self.sdc_mismatches,
            'consecutive_bad': self._consecutive_bad}

  def drain_report(self) -> Dict:
    """Training-health state at preemption, for the drain's
    resume_manifest.json: the counters plus WHY the last bad step was
    bad. A resume that finds `consecutive_bad > 0` here knows the
    drain checkpoint was withheld mid-burst (driver.train's drain
    finalize) and that the retained last-good step is the real resume
    point — the postmortem reads the reason from the manifest instead
    of re-deriving it from summaries.jsonl."""
    report = dict(self.stats())
    report['last_reason'] = self.last_reason
    # Locked copy: the SLO engine's thread may note_external a burn
    # while the drain builds the manifest (round 14).
    external = self.external_incidents
    if external:
      report['external_incidents'] = external
    return report

  # --- diagnostics ---

  def write_halt_bundle(self, logdir: str, config, step: int,
                        reason: str, flight=None) -> str:
    """The halt diagnostic bundle: last metrics window + counters +
    config + versions, as one JSON under <logdir>/diagnostics/. The
    operator gets the divergence trajectory, not just a dead job.

    `flight` (round 13): the telemetry flight recorder's dump — the
    last N trace records (batches with policy-lag vectors, publishes,
    installs) plus recent registry snapshots — so the halt ships the
    preceding PIPELINE history, not only the learner-step window."""
    import jax
    try:
      import jaxlib
      jaxlib_version = jaxlib.__version__
    except Exception:
      jaxlib_version = 'unknown'
    try:
      import orbax.checkpoint as ocp
      orbax_version = getattr(ocp, '__version__', 'unknown')
    except Exception:
      orbax_version = 'unknown'
    bundle = {
        'reason': reason,
        'step': int(step),
        'wall_time': round(time.time(), 3),
        'counters': self.stats(),
        'window': [dataclasses.asdict(e) for e in self._window],
        'config': dataclasses.asdict(config)
        if dataclasses.is_dataclass(config) else dict(config or {}),
        'versions': {
            'jax': jax.__version__,
            'jaxlib': jaxlib_version,
            'numpy': np.__version__,
            'orbax': orbax_version,
        },
    }
    if flight is not None:
      bundle['flight'] = flight
    out_dir = os.path.join(logdir, 'diagnostics')
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f'health_halt_step{int(step)}.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(bundle, f, indent=2, default=str)
    os.replace(tmp, path)
    return path


def monitor_from_config(config) -> HealthMonitor:
  return HealthMonitor(
      window=config.health_window,
      min_window=config.health_min_window,
      rollback_after=config.health_rollback_after,
      max_rollbacks=config.health_max_rollbacks,
      loss_explosion_factor=config.health_loss_explosion_factor,
      sigma_divergence_factor=config.health_sigma_divergence_factor)
