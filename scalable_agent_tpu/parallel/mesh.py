"""Device mesh construction (+ delegating sharding wrappers).

The reference scales out with TF1 gRPC: variables pinned to the learner,
actors enqueueing to a learner-hosted FIFOQueue (reference: experiment.py
`train()` ≈L435–460, SURVEY §5.8). The TPU-native design replaces all of
that with an explicit `jax.sharding.Mesh` and XLA collectives:

- **data axis (DP)**: the learner batch dim is sharded across chips;
  gradient reduction is an XLA `psum` over ICI inserted automatically by
  `jit` — this is the BASELINE.json north star (multi-learner sync
  without parameter servers).
- **model axis (TP)**: wide Dense/LSTM kernels can shard their output
  dim; at IMPALA's model sizes this is optional headroom, wired so the
  mechanism is real and tested (SURVEY §2.b).
- **Pipeline / expert parallelism**: not applicable to this model family
  (no layer pipeline depth worth cutting, no MoE — SURVEY §2.b marks
  both "explicitly absent" in the reference too).
- **Sequence parallelism**: the V-trace recursion is a linear scan and
  the LSTM is sequential; long-T scaling rides the associative-scan
  V-trace form (vtrace.py) rather than ring attention (no attention in
  the model family — SURVEY §5.7).

Multi-host: `jax.distributed.initialize()` + the same mesh spanning all
processes; trajectory transport stays host-local per learner shard while
gradients ride ICI/DCN via the same psum.

Round 19: the partition-rule table and every sharding decision moved to
`parallel/sharding.py` (the declarative registry — ONE source of
sharding truth). This module keeps mesh construction plus thin
delegating wrappers so existing `mesh_lib.param_shardings(...)` callers
keep working; the wrappers resolve through the registry, never
privately.
"""

import jax
import numpy as np
from jax.sharding import Mesh

from scalable_agent_tpu.parallel import sharding as sharding_lib

# Canonical axis names live in the registry; re-exported for callers.
DATA_AXIS = sharding_lib.DATA_AXIS
MODEL_AXIS = sharding_lib.MODEL_AXIS

# Re-exported predicate (single authority: parallel/sharding.py).
shard_batch_over_model = sharding_lib.shard_batch_over_model


def make_mesh(devices=None, model_parallelism: int = 1) -> Mesh:
  """Build a (data, model) mesh over the given (default: all) devices."""
  devices = devices if devices is not None else jax.devices()
  n = len(devices)
  if n % model_parallelism != 0:
    raise ValueError(
        f'{n} devices not divisible by model_parallelism='
        f'{model_parallelism}')
  grid = np.asarray(devices).reshape(n // model_parallelism,
                                     model_parallelism)
  return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def param_shardings(params, mesh: Mesh, enable_tp: bool = False):
  """NamedShardings for a param pytree — resolved via the registry."""
  registry = sharding_lib.ShardingRegistry(
      sharding_lib.RULE_SETS['megatron' if enable_tp else 'replicated'],
      rule_set='megatron' if enable_tp else 'replicated')
  return registry.param_shardings(params, mesh)


def batch_shardings(batch_pytree, mesh: Mesh,
                    shard_over_model: bool = False):
  """Learner-batch NamedShardings — resolved via the registry."""
  registry = sharding_lib.ShardingRegistry(
      sharding_lib.RULE_SETS['replicated'], rule_set='replicated')
  return registry.batch_shardings(batch_pytree, mesh,
                                  shard_over_model=shard_over_model)
