"""Device mesh construction and sharding rules.

The reference scales out with TF1 gRPC: variables pinned to the learner,
actors enqueueing to a learner-hosted FIFOQueue (reference: experiment.py
`train()` ≈L435–460, SURVEY §5.8). The TPU-native design replaces all of
that with an explicit `jax.sharding.Mesh` and XLA collectives:

- **data axis (DP)**: the learner batch dim is sharded across chips;
  gradient reduction is an XLA `psum` over ICI inserted automatically by
  `jit` — this is the BASELINE.json north star (multi-learner sync
  without parameter servers).
- **model axis (TP)**: wide Dense/LSTM kernels can shard their output
  dim; at IMPALA's model sizes this is optional headroom, wired here so
  the mechanism is real and tested (SURVEY §2.b).
- **Pipeline / expert parallelism**: not applicable to this model family
  (no layer pipeline depth worth cutting, no MoE — SURVEY §2.b marks
  both "explicitly absent" in the reference too).
- **Sequence parallelism**: the V-trace recursion is a linear scan and
  the LSTM is sequential; long-T scaling rides the associative-scan
  V-trace form (vtrace.py) rather than ring attention (no attention in
  the model family — SURVEY §5.7).

Multi-host: `jax.distributed.initialize()` + the same mesh spanning all
processes; trajectory transport stays host-local per learner shard while
gradients ride ICI/DCN via the same psum.
"""

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def shard_batch_over_model(config) -> bool:
  """Whether the learner batch must shard over the model axis too.

  True exactly when TP spans hosts: trajectory transport is host-local
  (each process supplies only its own fleet's rows), so model-axis
  batch replication would demand bit-identical batches from different
  hosts. The ONE predicate both the batch-divisibility check
  (driver.choose_mesh) and the actual sharding choice
  (train_parallel.make_sharded_train_step) consult — they must never
  drift."""
  return config.model_parallelism > 1 and jax.process_count() > 1


def make_mesh(devices=None, model_parallelism: int = 1) -> Mesh:
  """Build a (data, model) mesh over the given (default: all) devices."""
  devices = devices if devices is not None else jax.devices()
  n = len(devices)
  if n % model_parallelism != 0:
    raise ValueError(
        f'{n} devices not divisible by model_parallelism='
        f'{model_parallelism}')
  grid = np.asarray(devices).reshape(n // model_parallelism,
                                     model_parallelism)
  return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


# Parameter sharding rules: regex on the flattened param path → spec.
# The bulk of the params shard their OUTPUT-feature dim over the model
# axis:
# - anonymous Dense kernels (torso projections),
# - every OptimizedLSTMCell gate kernel (i{i,f,g,o} input-to-gate and
#   h{i,f,g,o} hidden-to-gate) — the recurrent carry then propagates
#   model-sharded through the time scan, the Megatron-style LSTM cut,
# - Conv kernels ([kh, kw, in, out]) on their out-channel dim.
# The named heads (policy_logits, baseline) stay replicated — they are
# tiny and their outputs feed cross-replica math. Leaves whose sharded
# dim does not divide the model width drop to replicated
# (param_shardings guard). At IMPALA scale TP is headroom, not a
# necessity; the mechanism is real and tested (tests/test_parallel.py
# asserts both the placements and TP-vs-single-device numerics).
_PARAM_RULES = (
    (re.compile(r'.*Dense_\d+/kernel$'), P(None, MODEL_AXIS)),
    (re.compile(r'.*Dense_\d+/bias$'), P(MODEL_AXIS)),
    (re.compile(r'.*OptimizedLSTMCell_\d+/[ih][ifgo]/kernel$'),
     P(None, MODEL_AXIS)),
    (re.compile(r'.*OptimizedLSTMCell_\d+/[ih][ifgo]/bias$'),
     P(MODEL_AXIS)),
    (re.compile(r'.*Conv_\d+/kernel$'), P(None, None, None, MODEL_AXIS)),
    (re.compile(r'.*Conv_\d+/bias$'), P(MODEL_AXIS)),
)


def param_spec(path: str, enable_tp: bool) -> P:
  if enable_tp:
    for pattern, spec in _PARAM_RULES:
      if pattern.match(path):
        return spec
  return P()


def param_shardings(params, mesh: Mesh, enable_tp: bool = False):
  """NamedShardings for a param pytree (TP on Dense kernels if asked)."""

  def path_str(kp):
    return '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                    for k in kp)

  def to_sharding(kp, leaf):
    spec = param_spec(path_str(kp), enable_tp)
    # Drop axes that don't divide the leaf (e.g. odd feature sizes).
    if any(s is not None for s in spec):
      for dim, name in enumerate(spec):
        if name is not None and (dim >= leaf.ndim or
                                 leaf.shape[dim] %
                                 mesh.shape[MODEL_AXIS] != 0):
          return NamedSharding(mesh, P())
    return NamedSharding(mesh, spec)

  return jax.tree_util.tree_map_with_path(to_sharding, params)


def batch_shardings(batch_pytree, mesh: Mesh,
                    shard_over_model: bool = False):
  """Shard the learner batch over the data axis.

  Trajectory tensors are time-major [T+1, B, ...] → shard dim 1;
  level_name/agent_state are [B, ...] → shard dim 0. We key on rank
  via the structural position: ActorOutput(level_name, agent_state,
  env_outputs, agent_outputs).

  shard_over_model: shard the batch dim over BOTH axes instead of
  replicating it across the model axis. Required when TP spans hosts:
  trajectory transport is host-local (each process supplies only its
  own fleet's rows to `make_array_from_process_local_data`), and
  model-axis replication would demand bit-identical batches from
  different hosts. With the batch fully sharded, every host feeds
  distinct rows and GSPMD inserts the model-axis all-gather where the
  TP matmuls need the full data shard — the collective rides
  ICI/DCN, placed by the compiler (SURVEY §5.8)."""
  from scalable_agent_tpu.structs import ActorOutput

  batch_axes = ((DATA_AXIS, MODEL_AXIS) if shard_over_model
                else DATA_AXIS)

  def traj(x):
    return NamedSharding(mesh, P(None, batch_axes))

  def lead(x):
    return NamedSharding(mesh, P(batch_axes))

  return ActorOutput(
      level_name=lead(None),
      agent_state=jax.tree_util.tree_map(
          lambda _: lead(None), batch_pytree.agent_state),
      env_outputs=jax.tree_util.tree_map(
          lambda _: traj(None), batch_pytree.env_outputs),
      agent_outputs=jax.tree_util.tree_map(
          lambda _: traj(None), batch_pytree.agent_outputs))
