"""Anakin mode: acting + learning fused into ONE jitted device step.

The production path (driver.py) is Sebulba-shaped (Podracer
architectures, arXiv:2104.06272): C++/CPU simulators on the host feed
a TPU learner through the batcher/buffer pipeline, because DMLab/ALE
can only ever be host processes (reference: environments.py ≈L60
PyProcessDmLab). But the framework's CI tasks (envs/fake.py bandit /
cue-memory) are pure state machines — for these, the TPU-idiomatic
architecture is Podracer's *Anakin*: put the environment INSIDE the
jitted step, `lax.scan` the act→env→act rollout on device, and feed
the trajectory straight into the same learner update, with zero host
transport, zero inference servers, zero Python in the loop.

What this buys:
- research-mode throughput on the CI tasks (no host round trips; the
  whole unroll+update is one XLA program), and
- a one-file demonstration that acting and learning are the SAME
  functional pieces everywhere: this module reuses `ImpalaAgent`
  unchanged (T=1 apply for acting, [T+1, B] apply inside the update)
  and `learner.make_train_step_fn` unchanged — there is exactly one
  IMPALA loss/update in the codebase.

Semantics mirror the host actor loop (runtime/actor.py) exactly:
T+1 overlap frame (timestep 0 of an unroll = last timestep of the
previous one), `agent_state` = LSTM carry at unroll start, flow-style
episode stats (the emitted StepOutputInfo carries final stats at done;
the carried state resets), initial env_output has done=True with a
zero/priming agent_output. Because acting uses the pre-update params
of the same step, behaviour == target at loss time and V-trace's rhos
are 1 for the T timesteps acted THIS step — the on-policy special
case (the correction machinery still runs; tests pin this). The one
exception is the t=0 overlap timestep: its behaviour logits came from
the PREVIOUS fused step's pre-update params, so it carries exactly
one update of policy lag (same as the host pipeline's overlap frame).

Scale-out: `init_carry(..., mesh=...)` / `run(..., mesh=...)` shard
every batch-leading leaf over the mesh's data axis — each device steps
its slice of the environments and the learner locally, params
replicate, and jit inserts the gradient psum over ICI (same placement
discipline as train_parallel.py; `test_anakin_shards_over_the_mesh`).

Round 16 promoted this module to a FIRST-CLASS RUNTIME
(`--runtime=anakin` → driver.train_anakin: the fused loop under the
full production lifecycle — checkpoint ladder, health ladder, SLO
verdict, summaries/incidents), widened the jittable env family
(envs/jittable.py gridworld + procgen cores, registered in ENV_CORES
below AND as host envs so the same task runs under both runtimes),
and added the HYBRID FILLER (`HybridFiller` at the bottom: Anakin
self-play on the fleet runtime's idle learner slices, bounded to one
step per feed probe, with every fleet clock left on the fresh-frame
count). docs/PARALLELISM.md and RUNBOOK §13 carry the operator story.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from scalable_agent_tpu import learner
from scalable_agent_tpu import population
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.structs import (ActorOutput, AgentOutput,
                                        StepOutput, StepOutputInfo)


class EnvCoreState(NamedTuple):
  """Batched functional env state (all [B] unless noted)."""
  rng: Any            # PRNG key []
  context: Any        # i32 [B] — bandit target / memory cue
  step_in_episode: Any  # i32 [B]
  episode_return: Any   # f32 [B] — flow-style carried stats
  episode_frames: Any   # i32 [B]


def _frame_from_channel(channel, batch, height, width, visible=None):
  """uint8 [B, H, W, 3] with `channel`'s plane at 255 (optionally
  masked per-env by `visible`)."""
  plane = jax.nn.one_hot(channel, 3, dtype=jnp.float32) * 255.0
  if visible is not None:
    plane = plane * visible[:, None].astype(jnp.float32)
  plane = plane.astype(jnp.uint8)  # [B, 3]
  return jnp.broadcast_to(plane[:, None, None, :],
                          (batch, height, width, 3))


def _zero_instr(batch):
  return jnp.zeros((batch, MAX_INSTRUCTION_LEN), jnp.int32)


class BanditCore:
  """Jittable ContextualBanditEnv (envs/fake.py): the frame's dominant
  color channel is the rewarded action; `episode_length` steps per
  context. Same rewards, episode shape, and stats semantics as the
  host version — property-tested side by side.

  `num_actions` widens the policy head exactly like the host env does
  (the target stays `randint(num_actions) % 3`, the host's own draw):
  the hybrid filler (HybridFiller) runs this core under the MAIN
  task's action space, so a dmlab fleet's idle learner slices can
  self-play without a second policy head."""

  def __init__(self, height=24, width=32, episode_length=5,
               num_action_repeats=1, num_actions=3):
    if num_actions < 1:
      raise ValueError(f'num_actions must be >= 1, got {num_actions}')
    self.height, self.width = height, width
    self.episode_length = episode_length
    self.num_action_repeats = num_action_repeats
    self.num_actions = num_actions

  def _observation(self, state, visible=None):
    frame = _frame_from_channel(state.context, state.context.shape[0],
                                self.height, self.width, visible)
    return (frame, _zero_instr(state.context.shape[0]))

  def _sample_context(self, rng, shape):
    # Mirrors the host env exactly: randint(num_actions) % 3 — the
    # rewarded channel is always 0..2 regardless of head width.
    return jax.random.randint(rng, shape, 0, self.num_actions) % 3

  def init(self, rng, batch) -> Tuple[EnvCoreState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = EnvCoreState(
        rng=rng,
        context=self._sample_context(sub, (batch,)),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32))
    # Mirrors runtime/actor.py's priming output: done=True (first obs
    # starts an episode), zero reward/stats.
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def step(self, state: EnvCoreState, action
           ) -> Tuple[EnvCoreState, StepOutput]:
    reward = (action == state.context).astype(jnp.float32)
    step_count = state.step_in_episode + 1
    done = step_count >= self.episode_length

    ep_return = state.episode_return + reward
    ep_frames = state.episode_frames + self.num_action_repeats
    info = StepOutputInfo(ep_return, ep_frames)  # emitted: incl. done
    zero_f = jnp.zeros_like(ep_return)
    zero_i = jnp.zeros_like(ep_frames)

    rng, sub = jax.random.split(state.rng)
    fresh = self._sample_context(sub, action.shape)
    new_state = EnvCoreState(
        rng=rng,
        context=jnp.where(done, fresh, state.context),
        step_in_episode=jnp.where(done, 0, step_count),
        episode_return=jnp.where(done, zero_f, ep_return),
        episode_frames=jnp.where(done, zero_i, ep_frames))
    output = StepOutput(reward=reward, info=info, done=done,
                        observation=self._observation(new_state))
    return new_state, output


class CueMemoryCore:
  """Jittable CueMemoryEnv (envs/fake.py): two-step episodes, cue
  visible only on the first frame, fixed-action-0 bonus on the first
  step (relay-proof), match-the-cue reward on the second."""

  def __init__(self, height=16, width=16, episode_length=2,
               num_action_repeats=1, num_actions=3):
    del episode_length  # fixed two-step episodes, like the host env
    if num_actions != 3:
      # Mirrors the host CueMemoryEnv: one action per RGB cue channel.
      raise ValueError('CueMemoryCore is a 3-action task (one action '
                       'per RGB cue channel); got num_actions='
                       f'{num_actions}')
    self.height, self.width = height, width
    self.num_action_repeats = num_action_repeats
    self.num_actions = 3

  def _observation(self, state):
    visible = state.step_in_episode == 0  # cue only pre-first-action
    frame = _frame_from_channel(state.context, state.context.shape[0],
                                self.height, self.width, visible)
    return (frame, _zero_instr(state.context.shape[0]))

  def init(self, rng, batch) -> Tuple[EnvCoreState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = EnvCoreState(
        rng=rng,
        context=jax.random.randint(sub, (batch,), 0, 3),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32))
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def step(self, state: EnvCoreState, action
           ) -> Tuple[EnvCoreState, StepOutput]:
    first = state.step_in_episode == 0
    reward = jnp.where(
        first,
        jnp.where(action == 0, 2.0, 0.0),              # info-free bonus
        (action == state.context).astype(jnp.float32))  # recall
    done = ~first

    ep_return = state.episode_return + reward
    ep_frames = state.episode_frames + self.num_action_repeats
    info = StepOutputInfo(ep_return, ep_frames)

    rng, sub = jax.random.split(state.rng)
    fresh = jax.random.randint(sub, action.shape, 0, 3)
    new_state = EnvCoreState(
        rng=rng,
        context=jnp.where(done, fresh, state.context),
        step_in_episode=jnp.where(done, 0, 1),
        episode_return=jnp.where(done, jnp.zeros_like(ep_return),
                                 ep_return),
        episode_frames=jnp.where(done, jnp.zeros_like(ep_frames),
                                 ep_frames))
    output = StepOutput(reward=reward, info=info, done=done,
                        observation=self._observation(new_state))
    return new_state, output


# The jittable env registry: the two CI cores above plus the round-16
# pure-JAX family (gridworld + the procgen-style parameterized
# generator — envs/jittable.py, which also registers the SAME cores as
# host environments through envs/factory.py: the dual registration the
# runtime-axis parity gate rides on). config.JITTABLE_BACKENDS mirrors
# these keys as literals (config.py cannot import this module);
# tests/test_anakin.py pins the two in sync.
from scalable_agent_tpu.envs import jittable as _jittable  # noqa: E402

ENV_CORES = {'bandit': BanditCore, 'cue_memory': CueMemoryCore,
             **_jittable.JITTABLE_CORES}


def make_env_core(config: Config, num_actions: Optional[int] = None):
  """Construct the jittable core a config names. `num_actions`
  overrides the head width (the hybrid filler passes the MAIN task's);
  falls back to config.num_actions, then the core's default. A core
  that cannot honor the width raises (CueMemoryCore is fixed at 3)."""
  if config.env_backend not in ENV_CORES:
    raise ValueError(
        f'anakin needs a jittable env core, got '
        f'{config.env_backend!r} (available: {sorted(ENV_CORES)}); '
        'real simulators use the host pipeline (driver.train)')
  core_cls = ENV_CORES[config.env_backend]
  kwargs = dict(height=config.height, width=config.width,
                episode_length=config.episode_length,
                num_action_repeats=config.num_action_repeats)
  if config.env_backend == 'procgen':
    # The level-set + curriculum knobs (round 22) are procgen-only:
    # the finite level-id space is what the prioritized sampler
    # drives. The hybrid filler reaches here through its own config
    # copy, so a procgen filler runs the same curriculum.
    kwargs.update(
        num_levels=config.procgen_num_levels,
        wall_density=config.procgen_wall_density,
        curriculum=config.curriculum,
        curriculum_temperature=config.curriculum_temperature,
        curriculum_eps=config.curriculum_eps)
  width = num_actions if num_actions is not None else config.num_actions
  if width is not None:
    kwargs['num_actions'] = width
  return core_cls(**kwargs)


class AnakinCarry(NamedTuple):
  """Everything that persists across fused steps (all device-side)."""
  train_state: Any   # learner.TrainState
  env_state: Any     # EnvCoreState
  env_output: Any    # StepOutput [B] — the pending overlap timestep
  agent_output: Any  # AgentOutput [B] — ditto
  core_state: Any    # LSTM carry (c, h) [B, hidden]
  rng: Any


class EnvCarry(NamedTuple):
  """The non-learner half of AnakinCarry: everything the fused loop
  threads BESIDES the train state. Split out (round 16) so the hybrid
  filler can persist its env-side state across fill slices while
  borrowing the LIVE fleet TrainState at each slice."""
  env_state: Any
  env_output: Any
  agent_output: Any
  core_state: Any
  rng: Any


def init_env_carry(agent, env_core, config: Config, rng,
                   mesh=None) -> EnvCarry:
  """Initial env/agent-side carry for `make_anakin_step` (no params —
  see `init_carry` for the composed whole).

  With `mesh`, every [B]-leading leaf (env state, pending outputs,
  LSTM carry) shards over the data axis. Core states are NamedTuples
  whose `rng` field is the one replicated-by-name leaf ([2]u32 —
  shape-sniffing would misplace it at b=2); every other leaf is
  [B]-leading by the ENV_CORES protocol."""
  b = config.batch_size
  if mesh is not None:
    from scalable_agent_tpu.parallel import mesh as mesh_lib
    if b % mesh.shape[mesh_lib.DATA_AXIS] != 0:
      # Before any init work — a full env init would be wasted.
      raise ValueError(
          f'batch_size={b} not divisible by the data axis '
          f'({mesh.shape[mesh_lib.DATA_AXIS]} devices)')
  rng, env_rng = jax.random.split(rng)
  env_state, env_output = env_core.init(env_rng, b)
  agent_output = AgentOutput(  # actor.py's priming output
      action=jnp.zeros((b,), jnp.int32),
      policy_logits=jnp.zeros((b, env_core.num_actions), jnp.float32),
      baseline=jnp.zeros((b,), jnp.float32))
  core_state = agent.initial_state(b)
  if mesh is None:
    return EnvCarry(env_state, env_output, agent_output, core_state,
                    rng)

  from scalable_agent_tpu.parallel import sharding as sharding_lib
  data = sharding_lib.data_sharding(mesh)
  replicated = sharding_lib.replicated(mesh)

  def place(x):
    x = jnp.asarray(x)
    batch_leading = x.ndim >= 1 and x.shape[0] == b
    return jax.device_put(x, data if batch_leading else replicated)

  # The core's PRNG key is pinned replicated BY NAME (the ENV_CORES
  # state protocol — every jittable core's state is a NamedTuple with
  # an `rng` field; gridworld/procgen ride the same rule). Captured
  # BEFORE the shape-sniffing placement, which would mis-shard the
  # [2]u32 key whenever b == 2. The procgen curriculum accumulators
  # ([num_levels] leaves, round 22) are replicated by name for the
  # same reason: num_levels == b would shape-sniff them onto the data
  # axis, splitting the one global score table the sampler reads.
  by_name = {'rng': env_state.rng}
  for field in ('level_scores', 'level_visits'):
    if hasattr(env_state, field):
      by_name[field] = getattr(env_state, field)
  env_state = jax.tree_util.tree_map(place, env_state)
  env_state = env_state._replace(
      **{k: jax.device_put(v, replicated) for k, v in by_name.items()})
  env_output, agent_output, core_state = jax.tree_util.tree_map(
      place, (env_output, agent_output, core_state))
  return EnvCarry(env_state, env_output, agent_output, core_state,
                  jax.device_put(rng, replicated))


def init_carry(agent, env_core, config: Config, rng,
               mesh=None) -> AnakinCarry:
  """Initial params/opt/env/agent state for `make_anakin_step`.

  With `mesh`, this IS Anakin's scale-out story: every [B]-leading
  leaf (env state, pending outputs, LSTM carry) shards over the data
  axis — each device runs its slice of the environments AND the
  learner locally; params/opt replicate and only the gradient psum
  crosses ICI (inserted by jit from these placements, exactly like
  parallel/train_parallel.py)."""
  from scalable_agent_tpu.models import init_params
  rng, params_rng = jax.random.split(rng)
  env = init_env_carry(agent, env_core, config, rng, mesh=mesh)
  obs_spec = {'frame': (env_core.height, env_core.width, 3),
              'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, params_rng, obs_spec)
  if mesh is None:
    train_state = learner.make_train_state(params, config)
  else:
    from scalable_agent_tpu.parallel import train_parallel
    train_state = train_parallel.make_sharded_train_state(
        params, config, mesh)
  return AnakinCarry(train_state, *env)


def make_anakin_step(agent, env_core, config: Config,
                     return_batch: bool = False,
                     train_step_fn=None,
                     advance_steps: bool = True,
                     mesh=None,
                     traced_hypers: bool = False,
                     jit: bool = True):
  """One fused device step: scan T acting steps, then the SGD update.

  Returns jitted `f(carry) -> (carry, metrics)` (donating the carry);
  with `return_batch` the assembled [T+1, B] ActorOutput is added to
  the metrics dict under 'batch' (alignment tests).

  `train_step_fn` (round 16, the hybrid filler): an externally built
  raw train step — the filler passes the FLEET config's, so the loss
  hyperparameters, the in-graph health guard, and the LR schedule all
  stay exactly the fleet's while this `config` only shapes the
  on-device rollout (filler backend / batch / unroll).

  `advance_steps=False` pins `update_steps` across the fused step (the
  filler contract: filler updates must not advance the frame budget,
  the LR clock, or the checkpoint step numbering — every clock the
  run exposes stays on the fleet's fresh-frame count; IMPACT's
  staleness tolerance, arXiv 1912.00167, is why an off-cadence update
  against the frozen clock is a legal move).

  `mesh` (round 22): only consulted by the curriculum block — the
  updated [num_levels] score table is constrained back to REPLICATED
  so the carry's placement is a fixed point (without the constraint
  the partitioner shards the segment-sum output over data, and the
  sharding flip forces a second compile at step 2).

  `traced_hypers` / `jit` (round 23, the vectorized population): with
  traced_hypers the step becomes f(carry, hypers) — hypers a dict of
  traced {'learning_rate', 'entropy_cost'} scalars threaded into the
  learner's traced-hypers train step. jit=False returns the RAW
  function instead of jitting it, so make_vectorized_anakin_step can
  jax.vmap it over a leading member axis before the one jit."""
  if train_step_fn is None:
    train_step_fn = learner.make_train_step_fn(
        agent, config, traced_hypers=traced_hypers)
  t = config.unroll_length
  # Python-level gate (round 22): the curriculum block only traces for
  # cores with a finite level-id space (procgen). The sampler itself
  # lives in the core's _fresh_episode; THIS side accumulates the
  # per-level priority EMAs from the unroll's own TD errors — acting
  # baselines are already in the batch (AgentOutput.baseline), so the
  # whole loop (score → sample → act → score) is one XLA program with
  # zero host round trips per level decision.
  use_curriculum = (config.curriculum != 'uniform'
                    and hasattr(env_core, 'num_levels'))

  def anakin_step(carry: AnakinCarry, hypers=None):
    initial_core_state = carry.core_state
    params = carry.train_state.params  # pre-update: behaviour == target

    def acting_step(acting_carry, _):
      env_state, env_output, agent_output, core_state, rng = (
          acting_carry)
      rng, sample_rng = jax.random.split(rng)
      # T=1 apply of the SAME agent the learner unrolls — one model.
      out_t, new_core = agent.apply(
          params, agent_output.action[None],
          jax.tree_util.tree_map(lambda x: x[None], env_output),
          core_state, sample_rng=sample_rng)
      new_agent_output = jax.tree_util.tree_map(lambda x: x[0], out_t)
      new_env_state, new_env_output = env_core.step(
          env_state, new_agent_output.action)
      # Pre-step level ids: the level each transition was PLAYED in
      # (step resamples at done, so the post-step id may already be
      # next episode's).
      ys = (new_env_output, new_agent_output)
      if use_curriculum:
        ys = ys + (env_state.level_id,)
      return ((new_env_state, new_env_output, new_agent_output,
               new_core, rng), ys)

    (env_state, env_output, agent_output, core_state, rng), tail = (
        jax.lax.scan(
            acting_step,
            (carry.env_state, carry.env_output, carry.agent_output,
             carry.core_state, carry.rng),
            None, length=t))
    # T+1 assembly with the overlap frame (actor.py unroll()).
    batch = ActorOutput(
        level_name=jnp.zeros((config.batch_size,), jnp.int32),
        agent_state=initial_core_state,
        env_outputs=jax.tree_util.tree_map(
            lambda first, rest: jnp.concatenate([first[None], rest]),
            carry.env_output, tail[0]),
        agent_outputs=jax.tree_util.tree_map(
            lambda first, rest: jnp.concatenate([first[None], rest]),
            carry.agent_output, tail[1]))
    if traced_hypers:
      new_train_state, metrics = train_step_fn(carry.train_state,
                                               batch, hypers)
    else:
      new_train_state, metrics = train_step_fn(carry.train_state,
                                               batch)
    if not advance_steps:
      new_train_state = new_train_state._replace(
          update_steps=carry.train_state.update_steps)
    metrics['mean_reward'] = jnp.mean(batch.env_outputs.reward[1:])
    if use_curriculum:
      # In-graph per-level score update from this unroll's own TD
      # errors. Alignment (learner.py): baseline[i] = V(o_{i-1}),
      # reward[i]/done[i] describe the o_{i-1} -> o_i transition, so
      # delta_i = r[i] + gamma*(1-d[i])*V(o_i) - V(o_{i-1}) needs
      # baseline[i+1] — the T-1 transitions i in [1, T). tail[2][j]
      # is the PRE-step level of the transition that produced
      # env_output j+1, so transition i maps to tail[2][i-1].
      # unroll_length=1 yields an empty update (pure decay) —
      # validate_population warns at spin-up.
      v = batch.agent_outputs.baseline                  # [T+1, B]
      r = batch.env_outputs.reward
      d = batch.env_outputs.done.astype(jnp.float32)
      delta = (r[1:t] + config.discounting * (1.0 - d[1:t]) * v[2:]
               - v[1:t])                                # [T-1, B]
      signal = population.score_signal(delta, config.curriculum)
      scores, visits = population.update_scores(
          env_state.level_scores, env_state.level_visits,
          tail[2][:t - 1], signal, config.curriculum_alpha,
          config.curriculum_decay)
      if mesh is not None:
        # Pin the table back to replicated (see the docstring): the
        # carry's placement must be a fixed point of the step.
        from scalable_agent_tpu.parallel import sharding as \
            sharding_lib
        rep = sharding_lib.replicated(mesh)
        scores = jax.lax.with_sharding_constraint(scores, rep)
        visits = jax.lax.with_sharding_constraint(visits, rep)
      env_state = env_state._replace(
          level_scores=scores, level_visits=visits)
      metrics.update(population.curriculum_metrics(
          scores, visits, config.curriculum_temperature,
          config.curriculum_eps))
    if return_batch:
      metrics['batch'] = batch
    return (AnakinCarry(new_train_state, env_state, env_output,
                        agent_output, core_state, rng),
            metrics)

  if not jit:
    return anakin_step
  return jax.jit(anakin_step, donate_argnums=(0,))


def make_vectorized_anakin_step(agent, env_core, config: Config):
  """One compiled program that advances N PBT members in lockstep.

  vmaps the *raw* (unjitted) fused act+learn step over a leading
  member axis of both the carry and the per-member hyper dict, then
  jits the vmapped function once with the stacked carry donated.
  Member programs must be structurally identical (same suite, same
  shapes) — only (learning_rate, entropy_cost) vary, and those enter
  as traced scalars so PBT explore never retriggers compilation.

  Returns a function `step(stacked_carry, hypers) -> (stacked_carry,
  stacked_metrics)` where `hypers` is a dict of f32[N] arrays with
  keys 'learning_rate' and 'entropy_cost', and every metric leaf
  gains a leading member axis.
  """
  raw_step = make_anakin_step(agent, env_core, config,
                              traced_hypers=True, jit=False)
  return jax.jit(jax.vmap(raw_step), donate_argnums=(0,))


def init_stacked_carry(agent, env_core, config: Config, seeds):
  """Stacks per-member initial carries along a leading member axis.

  Each member gets its own PRNG stream (and therefore its own env
  reset and weight init) from its entry in `seeds`; the results are
  tree-stacked so a single vmapped step advances all members.
  """
  carries = [init_carry(agent, env_core, config, jax.random.PRNGKey(s))
             for s in seeds]
  return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *carries)


def build_run(config: Config, mesh=None,
              rng_seed: Optional[int] = None):
  """Shared construction for run()/train()/driver.train_anakin():
  validated env core, agent, jitted fused step, initial carry."""
  from scalable_agent_tpu import driver
  # The core honors config.num_actions the way the host factory does
  # (wider heads are legal where the host env accepts them: bandit,
  # gridworld, procgen); a core that cannot (CueMemoryCore is a fixed
  # 3-action task) raises here — silently building a differently-
  # shaped policy head than driver.train would for the same Config
  # would make params/checkpoints incompatible between the runtimes.
  env_core = make_env_core(config)
  agent = driver.build_agent(config, env_core.num_actions)
  step = make_anakin_step(agent, env_core, config, mesh=mesh)
  seed = config.seed if rng_seed is None else rng_seed
  carry = init_carry(agent, env_core, config, jax.random.PRNGKey(seed),
                     mesh=mesh)
  return env_core, agent, step, carry


def _cpu_mesh_sync_every(mesh) -> Optional[int]:
  """CPU-emulated meshes (xla_force_host_platform_device_count) run one
  thread per virtual device; on an oversubscribed host a long async
  chain can starve one device >40 s behind its peers at a collective,
  tripping XLA's rendezvous watchdog (observed at ~60 queued sharded
  steps on the 1-core CI host). Periodic syncs bound the queue there;
  real chips keep pace and skip them (a sync costs a tunnel readback)."""
  return 8 if (mesh is not None
               and jax.default_backend() == 'cpu') else None


def train(config: Config, max_steps: Optional[int] = None, mesh=None):
  """Operator-facing Anakin training (`experiment.py --mode=anakin`):
  chunked fused steps with the framework's standard run artifacts —
  JSONL summaries (total_loss, mean_reward, env_frames_per_sec,
  learning_rate), checkpoint/resume in the same TrainState layout as
  driver.train, config.json dump, total_environment_frames
  termination. Returns the final AnakinCarry.

  The carry's env/agent state is NOT checkpointed — matching the
  production path, where actor-local state is intentionally excluded
  (reference: local variables are not saved; SURVEY §5.4)."""
  import dataclasses
  import json as json_lib
  import os
  import time
  from scalable_agent_tpu import checkpoint as checkpoint_lib
  from scalable_agent_tpu import observability

  _, _, step, carry = build_run(config, mesh=mesh)
  os.makedirs(config.logdir, exist_ok=True)
  with open(os.path.join(config.logdir, 'config.json'), 'w') as f:
    json_lib.dump(dataclasses.asdict(config), f, indent=2,
                  sort_keys=True)
  checkpointer = checkpoint_lib.Checkpointer(
      os.path.join(config.logdir, 'checkpoints'),
      save_interval_secs=config.checkpoint_secs)
  writer = observability.SummaryWriter(config.logdir)
  fps_meter = observability.FpsMeter()
  sync_every = _cpu_mesh_sync_every(mesh)

  steps_done = 0
  metrics = None

  def flush(step_num):
    m = jax.device_get(metrics)  # readback = pipeline barrier
    writer.scalars(
        {'total_loss': float(m['total_loss']),
         'mean_reward': float(m['mean_reward']),
         'learning_rate': float(m['learning_rate']),
         'env_frames_per_sec': fps_meter.fps()}, step=step_num)

  restore_ok = False
  try:
    # A structure-mismatch raise must not leak the manager/writer
    # (same discipline as driver.train's restore path).
    restored = checkpointer.restore_latest(carry.train_state)
    restore_ok = True
    if restored is not None:
      carry = carry._replace(train_state=restored)
    # Step count tracked host-side: reading the device counter in the
    # loop condition would be a per-step sync (~85 ms over the
    # tunnel), serializing the async dispatch chain.
    base_steps = int(carry.train_state.update_steps)
    last_summary = time.monotonic()
    while True:
      steps = base_steps + steps_done
      frames = steps * config.frames_per_step
      if frames >= config.total_environment_frames:
        break
      if max_steps is not None and steps_done >= max_steps:
        break
      carry, metrics = step(carry)
      steps_done += 1
      fps_meter.update(config.frames_per_step)
      if sync_every is not None and steps_done % sync_every == 0:
        jax.block_until_ready(metrics['total_loss'])
      now = time.monotonic()
      if now - last_summary >= config.summary_secs:
        flush(base_steps + steps_done)
        last_summary = now
      checkpointer.maybe_save(carry.train_state)
    if steps_done:
      # Final flush: a short run can finish inside one summary window
      # and would otherwise end with only the post-compile sample.
      flush(base_steps + steps_done)
  finally:
    try:
      if restore_ok:
        # Tail-save (preemption/interrupt safety); skipped when the
        # restore itself failed — a fresh state must not be written
        # into a logdir holding an incompatible checkpoint.
        checkpointer.save(carry.train_state)
    finally:
      checkpointer.close()
      writer.close()
  return carry


def run(config: Config, num_steps: int, rng_seed: Optional[int] = None,
        env_backend: Optional[str] = None, mesh=None):
  """Convenience runner: build agent + env core, run `num_steps` fused
  steps, return (carry, list-of-metrics, env_frames_per_sec). Pass
  `mesh` to shard the env batch over the data axis (multi-chip).

  rng_seed=None (the default) honors config.seed, matching
  build_run()/driver.train_anakin — it used to pin seed 0, which made
  two configs differing only in `seed` produce identical runs."""
  import dataclasses
  import time
  if num_steps < 1:
    raise ValueError(f'num_steps must be >= 1, got {num_steps}')
  if env_backend is not None and env_backend != config.env_backend:
    config = dataclasses.replace(config, env_backend=env_backend)
  _, _, step, carry = build_run(config, mesh=mesh, rng_seed=rng_seed)

  carry, metrics = step(carry)  # compile + step 1
  history = [metrics]
  float(jax.device_get(metrics['total_loss']))  # compile barrier
  sync_every = _cpu_mesh_sync_every(mesh)
  t0 = time.perf_counter()
  for i in range(num_steps - 1):
    carry, metrics = step(carry)
    history.append(metrics)  # async — no per-step readback
    if sync_every is not None and i % sync_every == sync_every - 1:
      jax.block_until_ready(metrics['total_loss'])
  # ONE value readback as the timing barrier (tunnel-safe: see
  # docs/PERF.md — block_until_ready can return early here).
  float(jax.device_get(history[-1]['total_loss']))
  dt = time.perf_counter() - t0
  # First (compile) step excluded from timing; num_steps=1 has no
  # timed window at all.
  frames = (num_steps - 1) * config.frames_per_step
  fps = frames / dt if num_steps > 1 and dt > 0 else float('nan')
  return carry, [jax.device_get(m) for m in history], fps


def supports_filler(config: Config, mesh=None) -> Tuple[bool, str]:
  """Whether THIS topology can run the hybrid filler: (ok, reason).

  Topology limits degrade to plain parking with a warning (the
  staging-mode fallback pattern — the run is still correct, just
  unfilled); everything else about the knob group (a non-jittable
  backend, a filler core that cannot honor the main task's
  action-space width) is a CONFIG error and fails at spin-up instead:
  the driver only consults this gate, it never swallows construction
  errors."""
  if jax.process_count() > 1:
    # Fill decisions are per-host (each host's prefetcher idles on its
    # own schedule) but a filler step over a multi-process mesh is a
    # COLLECTIVE — unsynchronized invocation deadlocks, synchronized
    # invocation would stall the busy hosts. Park instead.
    return False, ('multi-process topology: filler steps are '
                   'collectives but idle slices are per-host')
  if mesh is None:
    return True, ''
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  if mesh.shape[mesh_lib.MODEL_AXIS] > 1:
    return False, 'the anakin filler is data-parallel only (model-' \
                  'axis mesh in use)'
  data = mesh.shape[mesh_lib.DATA_AXIS]
  if config.resolved_filler_batch_size % data != 0:
    return False, (f'filler batch {config.resolved_filler_batch_size} '
                   f'not divisible by the data axis ({data} devices)')
  return True, ''


class HybridFiller:
  """Anakin self-play as a FILLER workload on the learner chips
  (round 16, ROADMAP item 3's creative step).

  The regime: BENCH r9 measured an env-bound feed at ~150 fps against
  ~300k fps of learner capacity — >99% of the learner plane idles
  whenever the env plane is the bound. The driver's fleet loop
  (driver.train) consults `fill_one` exactly when the prefetcher has
  NO staged batch ready (the ready-without-dequeue probe): one fused
  Anakin self-play step runs on the learner chips, then the feed is
  re-probed — so a staged batch is never delayed by more than one
  filler step (`fill_one` BLOCKS on the step's completion; the bound
  is structural, not statistical). IMPACT's staleness tolerance
  (arXiv 1912.00167) is why interleaving off-cadence updates from a
  different data stream is a legal move — and why
  config.validate_runtime cross-links the knob with
  `--surrogate=impact`.

  Clock discipline (the PR 7 serve-time attribution, extended): the
  filler's train step is built from the FLEET config
  (`make_anakin_step(train_step_fn=...)`) and runs with
  `advance_steps=False`, so the frame budget, the LR schedule, the
  checkpoint step numbering, and the fps meter all stay on the
  fleet's fresh-frame clock; filler work is accounted SEPARATELY
  (`updates`/`frames` here, the `driver/filler_updates` registry
  counter, and the driver's filler_updates/filler_frames summary
  scalars).

  Pure-DP only: the fused step shards the env batch over the data
  axis exactly like init_env_carry; a model-axis mesh raises and the
  driver falls back to plain parking with a warning.
  """

  def __init__(self, agent, config: Config, num_actions: int,
               mesh=None):
    import dataclasses
    from scalable_agent_tpu import telemetry
    backend = config.resolved_filler_backend
    if backend not in ENV_CORES:
      raise ValueError(
          f'filler backend {backend!r} is not a jittable env core '
          f'(available: {sorted(ENV_CORES)})')
    if mesh is not None:
      from scalable_agent_tpu.parallel import mesh as mesh_lib
      if mesh.shape[mesh_lib.MODEL_AXIS] > 1:
        raise ValueError('the anakin filler is data-parallel only '
                         '(model-axis mesh in use)')
    self._config = dataclasses.replace(
        config,
        env_backend=backend,
        batch_size=config.resolved_filler_batch_size,
        unroll_length=config.resolved_filler_unroll_length,
        num_actions=None)
    core = make_env_core(self._config, num_actions=num_actions)
    # The FLEET config's raw train step: loss hyperparameters, the
    # in-graph non-finite guard, and the LR schedule stay the fleet's
    # (the schedule reads update_steps, which advance_steps=False
    # freezes at the fleet's count — filler updates apply at the LR
    # the fleet is currently training at).
    train_fn = learner.make_train_step_fn(agent, config)
    self._step = make_anakin_step(agent, core, self._config,
                                  train_step_fn=train_fn,
                                  advance_steps=False, mesh=mesh)
    self._env = init_env_carry(
        agent, core, self._config,
        jax.random.PRNGKey(config.seed + 7777), mesh=mesh)
    self.backend = backend
    self.updates = 0
    self.skipped = 0
    self.frames_per_update = (self._config.batch_size *
                              self._config.unroll_length *
                              config.num_action_repeats)
    self._counter = telemetry.counter('driver/filler_updates')

  @property
  def frames(self) -> int:
    """Cumulative FILLER env frames — never mixed into the fleet's
    fresh-frame budget/fps; the separate summary curve."""
    return self.updates * self.frames_per_update

  def fill_one(self, train_state):
    """One bounded self-play slice: run a fused Anakin step on the
    live train state and BLOCK until it completes (the one-filler-step
    delay bound a just-staged batch sees). Returns the updated train
    state; env-side carry persists here across slices."""
    carry = AnakinCarry(train_state, *self._env)
    carry, metrics = self._step(carry)
    # The completion barrier IS the yield bound: a staged batch that
    # landed while this step ran is picked up immediately after.
    step_ok = metrics.get('step_ok')
    if step_ok is not None:
      loss_ok = jax.device_get(step_ok)
      if float(loss_ok) < 0.5:
        # The in-graph guard already withheld the non-finite update
        # (params carried over); count it — a filler stream must
        # never be able to poison the fleet's params silently.
        self.skipped += 1
    else:
      jax.block_until_ready(metrics['total_loss'])
    self.updates += 1
    self._counter.inc()
    self._env = EnvCarry(carry.env_state, carry.env_output,
                         carry.agent_output, carry.core_state,
                         carry.rng)
    return carry.train_state

  def stats(self):
    return {'updates': self.updates, 'frames': self.frames,
            'skipped': self.skipped, 'backend': self.backend,
            'batch_size': self._config.batch_size,
            'unroll_length': self._config.unroll_length}

  def close(self):
    """Unregister the per-run counter (the registry teardown contract
    every driver-owned metric follows): a later run in the same
    process must not snapshot a dead run's filler tally. Identity-
    checked, so closing an old filler never evicts a newer one's
    registration."""
    from scalable_agent_tpu import telemetry
    telemetry.registry().unregister(self._counter.name, self._counter)
