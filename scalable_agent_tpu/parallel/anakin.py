"""Anakin mode: acting + learning fused into ONE jitted device step.

The production path (driver.py) is Sebulba-shaped (Podracer
architectures, arXiv:2104.06272): C++/CPU simulators on the host feed
a TPU learner through the batcher/buffer pipeline, because DMLab/ALE
can only ever be host processes (reference: environments.py ≈L60
PyProcessDmLab). But the framework's CI tasks (envs/fake.py bandit /
cue-memory) are pure state machines — for these, the TPU-idiomatic
architecture is Podracer's *Anakin*: put the environment INSIDE the
jitted step, `lax.scan` the act→env→act rollout on device, and feed
the trajectory straight into the same learner update, with zero host
transport, zero inference servers, zero Python in the loop.

What this buys:
- research-mode throughput on the CI tasks (no host round trips; the
  whole unroll+update is one XLA program), and
- a one-file demonstration that acting and learning are the SAME
  functional pieces everywhere: this module reuses `ImpalaAgent`
  unchanged (T=1 apply for acting, [T+1, B] apply inside the update)
  and `learner.make_train_step_fn` unchanged — there is exactly one
  IMPALA loss/update in the codebase.

Semantics mirror the host actor loop (runtime/actor.py) exactly:
T+1 overlap frame (timestep 0 of an unroll = last timestep of the
previous one), `agent_state` = LSTM carry at unroll start, flow-style
episode stats (the emitted StepOutputInfo carries final stats at done;
the carried state resets), initial env_output has done=True with a
zero/priming agent_output. Because acting uses the pre-update params
of the same step, behaviour == target at loss time and V-trace's rhos
are 1 for the T timesteps acted THIS step — the on-policy special
case (the correction machinery still runs; tests pin this). The one
exception is the t=0 overlap timestep: its behaviour logits came from
the PREVIOUS fused step's pre-update params, so it carries exactly
one update of policy lag (same as the host pipeline's overlap frame).

Scale-out: `init_carry(..., mesh=...)` / `run(..., mesh=...)` shard
every batch-leading leaf over the mesh's data axis — each device steps
its slice of the environments and the learner locally, params
replicate, and jit inserts the gradient psum over ICI (same placement
discipline as train_parallel.py; `test_anakin_shards_over_the_mesh`).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from scalable_agent_tpu import learner
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.structs import (ActorOutput, AgentOutput,
                                        StepOutput, StepOutputInfo)


class EnvCoreState(NamedTuple):
  """Batched functional env state (all [B] unless noted)."""
  rng: Any            # PRNG key []
  context: Any        # i32 [B] — bandit target / memory cue
  step_in_episode: Any  # i32 [B]
  episode_return: Any   # f32 [B] — flow-style carried stats
  episode_frames: Any   # i32 [B]


def _frame_from_channel(channel, batch, height, width, visible=None):
  """uint8 [B, H, W, 3] with `channel`'s plane at 255 (optionally
  masked per-env by `visible`)."""
  plane = jax.nn.one_hot(channel, 3, dtype=jnp.float32) * 255.0
  if visible is not None:
    plane = plane * visible[:, None].astype(jnp.float32)
  plane = plane.astype(jnp.uint8)  # [B, 3]
  return jnp.broadcast_to(plane[:, None, None, :],
                          (batch, height, width, 3))


def _zero_instr(batch):
  return jnp.zeros((batch, MAX_INSTRUCTION_LEN), jnp.int32)


class BanditCore:
  """Jittable ContextualBanditEnv (envs/fake.py): the frame's dominant
  color channel is the rewarded action; `episode_length` steps per
  context. Same rewards, episode shape, and stats semantics as the
  host version — property-tested side by side."""

  num_actions = 3

  def __init__(self, height=24, width=32, episode_length=5,
               num_action_repeats=1):
    self.height, self.width = height, width
    self.episode_length = episode_length
    self.num_action_repeats = num_action_repeats

  def _observation(self, state, visible=None):
    frame = _frame_from_channel(state.context, state.context.shape[0],
                                self.height, self.width, visible)
    return (frame, _zero_instr(state.context.shape[0]))

  def init(self, rng, batch) -> Tuple[EnvCoreState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = EnvCoreState(
        rng=rng,
        context=jax.random.randint(sub, (batch,), 0, self.num_actions),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32))
    # Mirrors runtime/actor.py's priming output: done=True (first obs
    # starts an episode), zero reward/stats.
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def step(self, state: EnvCoreState, action
           ) -> Tuple[EnvCoreState, StepOutput]:
    reward = (action == state.context).astype(jnp.float32)
    step_count = state.step_in_episode + 1
    done = step_count >= self.episode_length

    ep_return = state.episode_return + reward
    ep_frames = state.episode_frames + self.num_action_repeats
    info = StepOutputInfo(ep_return, ep_frames)  # emitted: incl. done
    zero_f = jnp.zeros_like(ep_return)
    zero_i = jnp.zeros_like(ep_frames)

    rng, sub = jax.random.split(state.rng)
    fresh = jax.random.randint(sub, action.shape, 0, self.num_actions)
    new_state = EnvCoreState(
        rng=rng,
        context=jnp.where(done, fresh, state.context),
        step_in_episode=jnp.where(done, 0, step_count),
        episode_return=jnp.where(done, zero_f, ep_return),
        episode_frames=jnp.where(done, zero_i, ep_frames))
    output = StepOutput(reward=reward, info=info, done=done,
                        observation=self._observation(new_state))
    return new_state, output


class CueMemoryCore:
  """Jittable CueMemoryEnv (envs/fake.py): two-step episodes, cue
  visible only on the first frame, fixed-action-0 bonus on the first
  step (relay-proof), match-the-cue reward on the second."""

  num_actions = 3

  def __init__(self, height=16, width=16, episode_length=2,
               num_action_repeats=1):
    del episode_length  # fixed two-step episodes, like the host env
    self.height, self.width = height, width
    self.num_action_repeats = num_action_repeats

  def _observation(self, state):
    visible = state.step_in_episode == 0  # cue only pre-first-action
    frame = _frame_from_channel(state.context, state.context.shape[0],
                                self.height, self.width, visible)
    return (frame, _zero_instr(state.context.shape[0]))

  def init(self, rng, batch) -> Tuple[EnvCoreState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = EnvCoreState(
        rng=rng,
        context=jax.random.randint(sub, (batch,), 0, 3),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32))
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def step(self, state: EnvCoreState, action
           ) -> Tuple[EnvCoreState, StepOutput]:
    first = state.step_in_episode == 0
    reward = jnp.where(
        first,
        jnp.where(action == 0, 2.0, 0.0),              # info-free bonus
        (action == state.context).astype(jnp.float32))  # recall
    done = ~first

    ep_return = state.episode_return + reward
    ep_frames = state.episode_frames + self.num_action_repeats
    info = StepOutputInfo(ep_return, ep_frames)

    rng, sub = jax.random.split(state.rng)
    fresh = jax.random.randint(sub, action.shape, 0, 3)
    new_state = EnvCoreState(
        rng=rng,
        context=jnp.where(done, fresh, state.context),
        step_in_episode=jnp.where(done, 0, 1),
        episode_return=jnp.where(done, jnp.zeros_like(ep_return),
                                 ep_return),
        episode_frames=jnp.where(done, jnp.zeros_like(ep_frames),
                                 ep_frames))
    output = StepOutput(reward=reward, info=info, done=done,
                        observation=self._observation(new_state))
    return new_state, output


ENV_CORES = {'bandit': BanditCore, 'cue_memory': CueMemoryCore}


class AnakinCarry(NamedTuple):
  """Everything that persists across fused steps (all device-side)."""
  train_state: Any   # learner.TrainState
  env_state: Any     # EnvCoreState
  env_output: Any    # StepOutput [B] — the pending overlap timestep
  agent_output: Any  # AgentOutput [B] — ditto
  core_state: Any    # LSTM carry (c, h) [B, hidden]
  rng: Any


def init_carry(agent, env_core, config: Config, rng,
               mesh=None) -> AnakinCarry:
  """Initial params/opt/env/agent state for `make_anakin_step`.

  With `mesh`, this IS Anakin's scale-out story: every [B]-leading
  leaf (env state, pending outputs, LSTM carry) shards over the data
  axis — each device runs its slice of the environments AND the
  learner locally; params/opt replicate and only the gradient psum
  crosses ICI (inserted by jit from these placements, exactly like
  parallel/train_parallel.py)."""
  from scalable_agent_tpu.models import init_params
  b = config.batch_size
  if mesh is not None:
    from scalable_agent_tpu.parallel import mesh as mesh_lib
    if b % mesh.shape[mesh_lib.DATA_AXIS] != 0:
      # Before any init work — a full param init would be wasted.
      raise ValueError(
          f'batch_size={b} not divisible by the data axis '
          f'({mesh.shape[mesh_lib.DATA_AXIS]} devices)')
  rng, params_rng, env_rng = jax.random.split(rng, 3)
  obs_spec = {'frame': (env_core.height, env_core.width, 3),
              'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, params_rng, obs_spec)
  env_state, env_output = env_core.init(env_rng, b)
  agent_output = AgentOutput(  # actor.py's priming output
      action=jnp.zeros((b,), jnp.int32),
      policy_logits=jnp.zeros((b, env_core.num_actions), jnp.float32),
      baseline=jnp.zeros((b,), jnp.float32))
  core_state = agent.initial_state(b)

  if mesh is None:
    train_state = learner.make_train_state(params, config)
    return AnakinCarry(train_state, env_state, env_output,
                       agent_output, core_state, rng)

  from jax.sharding import NamedSharding, PartitionSpec as P
  from scalable_agent_tpu.parallel import train_parallel
  train_state = train_parallel.make_sharded_train_state(
      params, config, mesh)
  data = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
  replicated = NamedSharding(mesh, P())

  def place(x):
    x = jnp.asarray(x)
    batch_leading = x.ndim >= 1 and x.shape[0] == b
    return jax.device_put(x, data if batch_leading else replicated)

  # The env core's PRNG key is [2]u32 — shape-sniffing would misplace
  # it at b=2, so it is pinned replicated by name.
  env_state = EnvCoreState(
      rng=jax.device_put(env_state.rng, replicated),
      **{f: place(getattr(env_state, f))
         for f in EnvCoreState._fields if f != 'rng'})
  env_output, agent_output, core_state = jax.tree_util.tree_map(
      place, (env_output, agent_output, core_state))
  return AnakinCarry(train_state, env_state, env_output, agent_output,
                     core_state, jax.device_put(rng, replicated))


def make_anakin_step(agent, env_core, config: Config,
                     return_batch: bool = False):
  """One fused device step: scan T acting steps, then the SGD update.

  Returns jitted `f(carry) -> (carry, metrics)` (donating the carry);
  with `return_batch` the assembled [T+1, B] ActorOutput is added to
  the metrics dict under 'batch' (alignment tests)."""
  train_step_fn = learner.make_train_step_fn(agent, config)
  t = config.unroll_length

  def anakin_step(carry: AnakinCarry):
    initial_core_state = carry.core_state
    params = carry.train_state.params  # pre-update: behaviour == target

    def acting_step(acting_carry, _):
      env_state, env_output, agent_output, core_state, rng = (
          acting_carry)
      rng, sample_rng = jax.random.split(rng)
      # T=1 apply of the SAME agent the learner unrolls — one model.
      out_t, new_core = agent.apply(
          params, agent_output.action[None],
          jax.tree_util.tree_map(lambda x: x[None], env_output),
          core_state, sample_rng=sample_rng)
      new_agent_output = jax.tree_util.tree_map(lambda x: x[0], out_t)
      new_env_state, new_env_output = env_core.step(
          env_state, new_agent_output.action)
      return ((new_env_state, new_env_output, new_agent_output,
               new_core, rng),
              (new_env_output, new_agent_output))

    (env_state, env_output, agent_output, core_state, rng), tail = (
        jax.lax.scan(
            acting_step,
            (carry.env_state, carry.env_output, carry.agent_output,
             carry.core_state, carry.rng),
            None, length=t))
    # T+1 assembly with the overlap frame (actor.py unroll()).
    batch = ActorOutput(
        level_name=jnp.zeros((config.batch_size,), jnp.int32),
        agent_state=initial_core_state,
        env_outputs=jax.tree_util.tree_map(
            lambda first, rest: jnp.concatenate([first[None], rest]),
            carry.env_output, tail[0]),
        agent_outputs=jax.tree_util.tree_map(
            lambda first, rest: jnp.concatenate([first[None], rest]),
            carry.agent_output, tail[1]))
    new_train_state, metrics = train_step_fn(carry.train_state, batch)
    metrics['mean_reward'] = jnp.mean(batch.env_outputs.reward[1:])
    if return_batch:
      metrics['batch'] = batch
    return (AnakinCarry(new_train_state, env_state, env_output,
                        agent_output, core_state, rng),
            metrics)

  return jax.jit(anakin_step, donate_argnums=(0,))


def _build(config: Config, mesh=None, rng_seed: Optional[int] = None):
  """Shared construction for run()/train(): validated env core, agent,
  jitted fused step, initial carry."""
  from scalable_agent_tpu import driver
  if config.env_backend not in ENV_CORES:
    raise ValueError(
        f'anakin needs a jittable env core, got '
        f'{config.env_backend!r} (available: {sorted(ENV_CORES)}); '
        'real simulators use the host pipeline (driver.train)')
  core_cls = ENV_CORES[config.env_backend]
  env_core = core_cls(height=config.height, width=config.width,
                      episode_length=config.episode_length,
                      num_action_repeats=config.num_action_repeats)
  if (config.num_actions is not None
      and config.num_actions != env_core.num_actions):
    # Fail fast: silently building a differently-shaped policy head
    # than driver.train would for the same Config would make params/
    # checkpoints incompatible between the two paths.
    raise ValueError(
        f'config.num_actions={config.num_actions} but the '
        f'{config.env_backend!r} anakin core is a fixed '
        f'{env_core.num_actions}-action task')
  agent = driver.build_agent(config, env_core.num_actions)
  step = make_anakin_step(agent, env_core, config)
  seed = config.seed if rng_seed is None else rng_seed
  carry = init_carry(agent, env_core, config, jax.random.PRNGKey(seed),
                     mesh=mesh)
  return env_core, agent, step, carry


def _cpu_mesh_sync_every(mesh) -> Optional[int]:
  """CPU-emulated meshes (xla_force_host_platform_device_count) run one
  thread per virtual device; on an oversubscribed host a long async
  chain can starve one device >40 s behind its peers at a collective,
  tripping XLA's rendezvous watchdog (observed at ~60 queued sharded
  steps on the 1-core CI host). Periodic syncs bound the queue there;
  real chips keep pace and skip them (a sync costs a tunnel readback)."""
  return 8 if (mesh is not None
               and jax.default_backend() == 'cpu') else None


def train(config: Config, max_steps: Optional[int] = None, mesh=None):
  """Operator-facing Anakin training (`experiment.py --mode=anakin`):
  chunked fused steps with the framework's standard run artifacts —
  JSONL summaries (total_loss, mean_reward, env_frames_per_sec,
  learning_rate), checkpoint/resume in the same TrainState layout as
  driver.train, config.json dump, total_environment_frames
  termination. Returns the final AnakinCarry.

  The carry's env/agent state is NOT checkpointed — matching the
  production path, where actor-local state is intentionally excluded
  (reference: local variables are not saved; SURVEY §5.4)."""
  import dataclasses
  import json as json_lib
  import os
  import time
  from scalable_agent_tpu import checkpoint as checkpoint_lib
  from scalable_agent_tpu import observability

  _, _, step, carry = _build(config, mesh=mesh)
  os.makedirs(config.logdir, exist_ok=True)
  with open(os.path.join(config.logdir, 'config.json'), 'w') as f:
    json_lib.dump(dataclasses.asdict(config), f, indent=2,
                  sort_keys=True)
  checkpointer = checkpoint_lib.Checkpointer(
      os.path.join(config.logdir, 'checkpoints'),
      save_interval_secs=config.checkpoint_secs)
  writer = observability.SummaryWriter(config.logdir)
  fps_meter = observability.FpsMeter()
  sync_every = _cpu_mesh_sync_every(mesh)

  steps_done = 0
  metrics = None

  def flush(step_num):
    m = jax.device_get(metrics)  # readback = pipeline barrier
    writer.scalars(
        {'total_loss': float(m['total_loss']),
         'mean_reward': float(m['mean_reward']),
         'learning_rate': float(m['learning_rate']),
         'env_frames_per_sec': fps_meter.fps()}, step=step_num)

  restore_ok = False
  try:
    # A structure-mismatch raise must not leak the manager/writer
    # (same discipline as driver.train's restore path).
    restored = checkpointer.restore_latest(carry.train_state)
    restore_ok = True
    if restored is not None:
      carry = carry._replace(train_state=restored)
    # Step count tracked host-side: reading the device counter in the
    # loop condition would be a per-step sync (~85 ms over the
    # tunnel), serializing the async dispatch chain.
    base_steps = int(carry.train_state.update_steps)
    last_summary = time.monotonic()
    while True:
      steps = base_steps + steps_done
      frames = steps * config.frames_per_step
      if frames >= config.total_environment_frames:
        break
      if max_steps is not None and steps_done >= max_steps:
        break
      carry, metrics = step(carry)
      steps_done += 1
      fps_meter.update(config.frames_per_step)
      if sync_every is not None and steps_done % sync_every == 0:
        jax.block_until_ready(metrics['total_loss'])
      now = time.monotonic()
      if now - last_summary >= config.summary_secs:
        flush(base_steps + steps_done)
        last_summary = now
      checkpointer.maybe_save(carry.train_state)
    if steps_done:
      # Final flush: a short run can finish inside one summary window
      # and would otherwise end with only the post-compile sample.
      flush(base_steps + steps_done)
  finally:
    try:
      if restore_ok:
        # Tail-save (preemption/interrupt safety); skipped when the
        # restore itself failed — a fresh state must not be written
        # into a logdir holding an incompatible checkpoint.
        checkpointer.save(carry.train_state)
    finally:
      checkpointer.close()
      writer.close()
  return carry


def run(config: Config, num_steps: int, rng_seed: int = 0,
        env_backend: Optional[str] = None, mesh=None):
  """Convenience runner: build agent + env core, run `num_steps` fused
  steps, return (carry, list-of-metrics, env_frames_per_sec). Pass
  `mesh` to shard the env batch over the data axis (multi-chip)."""
  import dataclasses
  import time
  if num_steps < 1:
    raise ValueError(f'num_steps must be >= 1, got {num_steps}')
  if env_backend is not None and env_backend != config.env_backend:
    config = dataclasses.replace(config, env_backend=env_backend)
  _, _, step, carry = _build(config, mesh=mesh, rng_seed=rng_seed)

  carry, metrics = step(carry)  # compile + step 1
  history = [metrics]
  float(jax.device_get(metrics['total_loss']))  # compile barrier
  sync_every = _cpu_mesh_sync_every(mesh)
  t0 = time.perf_counter()
  for i in range(num_steps - 1):
    carry, metrics = step(carry)
    history.append(metrics)  # async — no per-step readback
    if sync_every is not None and i % sync_every == sync_every - 1:
      jax.block_until_ready(metrics['total_loss'])
  # ONE value readback as the timing barrier (tunnel-safe: see
  # docs/PERF.md — block_until_ready can return early here).
  float(jax.device_get(history[-1]['total_loss']))
  dt = time.perf_counter() - t0
  # First (compile) step excluded from timing; num_steps=1 has no
  # timed window at all.
  frames = (num_steps - 1) * config.frames_per_step
  fps = frames / dt if num_steps > 1 and dt > 0 else float('nan')
  return carry, [jax.device_get(m) for m in history], fps
