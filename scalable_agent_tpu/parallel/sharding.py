"""Declarative sharding registry — the ONE source of sharding truth.

Until round 19 the sharding decision was hand-copied across seven
consumers: the learner step and AOT fit carried their own
param/batch/replicated constructions, the mesh builder owned a private
regex rule table, the publisher codec and `target_params` re-derived
"are params cross-host sharded" from config arithmetic, the inference
arena built its own replicated/data shardings, the SDC fingerprint
encoded "params are logically replicated" as a config predicate, the
checkpoint restore specs were whatever the live state happened to
carry, and the multi-host placement arithmetic re-assumed the
contiguous data layout. Every new consumer was a "forgot to shard it"
bug waiting to land (ROADMAP item 1).

This module is the single authority they all query now:

- **Rule sets** (`RULE_SETS`): ordered (regex-over-param-path →
  `PartitionSpec`) tables, first match wins — the fmengine/EasyLM
  partition-rule pattern (SNIPPETS.md [2]). Scalars resolve replicated
  before the rules run; a param NO rule matches is a hard spin-up
  error (rule sets therefore end with an explicit catch-all — silence
  is never a sharding decision).
- **Optimizer-state specs** are cloned leaf-wise from the matched
  param specs (SNIPPETS.md [1]): any subtree of the optimizer state
  whose tree structure equals the params' (moment buffers) inherits
  the param specs; every other leaf (GA/schedule counters, scalars)
  is replicated.
- **Mesh binding** (`ShardingRegistry.param_shardings` /
  `state_shardings` / `batch_shardings`): resolved specs become
  `NamedSharding`s on a concrete mesh, with the divisibility guard —
  a model-axis cut whose dim does not divide the mesh's model width
  drops to replicated (odd feature sizes), applied HERE so every
  consumer sees the identical post-guard placement.

Consumers (each converted in round 19; the `sharding-registry` lint
pins that no new inline `PartitionSpec(...)` creeps in elsewhere):
`parallel/train_parallel.py` (learner step + SDC fingerprint
dispatch), `parallel/fit.py` (AOT fit), `parallel/mesh.py`
(delegating wrappers), `runtime/inference.py` (arena placements),
`driver.py` (publisher localization predicate), `checkpoint.py`
(save-side sharding manifest + registry restore targets),
`integrity.py` (spec-table digest), and the multi-host placement
arithmetic (`train_parallel.make_unroll_assembly`,
`distributed.global_batch_from_local` — both consume
`batch_shardings`).

The registry is deliberately mesh-independent at the resolution layer
(specs are pure data) — respecifying the same rule set against a new
mesh is exactly what checkpoint resharding across topologies needs
(ROADMAP item 3; the manifest `describe()` writes is its on-disk
record).
"""

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


class ShardingRuleError(ValueError):
  """A param path no rule matches — a hard spin-up error: silence is
  never a sharding decision (the registry's core contract)."""


class ShardingLayoutError(ShardingRuleError):
  """A resolved spec the TARGET mesh cannot honor — the axis is not on
  the mesh, the cut dim is out of rank, or the dim does not divide the
  axis width. Where live binding silently degrades such a cut to
  replicated (`_guard`), the strict layout check cross-topology restore
  runs (round 20, elastic membership) refuses with the structural story
  instead: a topology change must never silently rewrite a layout the
  checkpoint still holds."""


def shard_batch_over_model(config) -> bool:
  """Whether the learner batch must shard over the model axis too.

  True exactly when TP spans hosts: trajectory transport is host-local
  (each process supplies only its own fleet's rows), so model-axis
  batch replication would demand bit-identical batches from different
  hosts. The ONE predicate the batch-divisibility check
  (driver.choose_mesh), the sharding choice (batch_shardings callers),
  and the publisher localization (needs_host_local_params) consult —
  they must never drift."""
  return config.model_parallelism > 1 and jax.process_count() > 1


def needs_host_local_params(config, mesh) -> bool:
  """Whether actor-facing param consumers (the publisher codec, the
  inference server, ingest snapshots) must run on a host-LOCAL copy
  (process_allgather) instead of the learner's at-rest placements.

  True exactly when params are model-sharded ACROSS processes: a jit
  over cross-process-sharded params is a collective SPMD program, and
  the batcher invokes inference at unsynchronized times per host —
  which deadlocks in the collective (round 17's measured hang)."""
  return mesh is not None and shard_batch_over_model(config)


# --- rule sets --------------------------------------------------------

# Megatron-style TP cut (moved verbatim from parallel/mesh.py round 19
# — the rules themselves are unchanged, only their home): the bulk of
# the params shard their OUTPUT-feature dim over the model axis:
# - anonymous Dense kernels (torso projections),
# - every OptimizedLSTMCell gate kernel (i{i,f,g,o} input-to-gate and
#   h{i,f,g,o} hidden-to-gate) — the recurrent carry then propagates
#   model-sharded through the time scan, the Megatron-style LSTM cut,
# - Conv kernels ([kh, kw, in, out]) on their out-channel dim.
# The named heads (policy_logits, baseline) stay replicated — they are
# tiny and their outputs feed cross-replica math; no rule names them,
# so they fall to the mandatory catch-all. At IMPALA scale TP is
# headroom, not a necessity; the mechanism is real and parity-gated
# (tests/test_sharding.py, tests/test_parallel.py).
_TP_RULES: Tuple[Tuple[str, P], ...] = (
    (r'.*Dense_\d+/kernel$', P(None, MODEL_AXIS)),
    (r'.*Dense_\d+/bias$', P(MODEL_AXIS)),
    (r'.*OptimizedLSTMCell_\d+/[ih][ifgo]/kernel$', P(None, MODEL_AXIS)),
    (r'.*OptimizedLSTMCell_\d+/[ih][ifgo]/bias$', P(MODEL_AXIS)),
    (r'.*Conv_\d+/kernel$', P(None, None, None, MODEL_AXIS)),
    (r'.*Conv_\d+/bias$', P(MODEL_AXIS)),
    (r'.*', P()),
)

# Named rule sets a config can declare (--sharding_rules). 'auto'
# resolves at registry construction: 'megatron' when the mesh has a
# model axis to cut, 'replicated' (pure DP) otherwise.
RULE_SETS: Dict[str, Tuple[Tuple[str, P], ...]] = {
    'replicated': ((r'.*', P()),),
    'megatron': _TP_RULES,
}


class ShardingRegistry:
  """Ordered partition rules + every derived sharding decision.

  Resolution (`spec_for`, `param_specs`, `opt_specs`, `state_specs`)
  is pure data — specs, no mesh. Binding (`*_shardings`) takes the
  concrete mesh and applies the divisibility guard. Consumers never
  construct a `PartitionSpec` themselves (the `sharding-registry`
  lint enforces it)."""

  def __init__(self, rules: Sequence[Tuple[str, P]],
               rule_set: str = '<custom>'):
    if not rules:
      raise ValueError('a sharding registry needs at least one rule '
                       '(a catch-all (".*", PartitionSpec()) is the '
                       'minimal pure-DP set)')
    self.rule_set = rule_set
    self.rules: Tuple[Tuple[Any, P], ...] = tuple(
        (re.compile(pattern), spec) for pattern, spec in rules)

  # --- resolution (mesh-independent) ---------------------------------

  @property
  def model_sharded(self) -> bool:
    """Whether this rule set cuts ANY param over the model axis — the
    predicate the SDC sentinel gate and the publisher consult ('are
    params logically replicated?')."""
    return any(MODEL_AXIS in (s or ()) for _, s in self.rules)

  def spec_for(self, path: str, leaf) -> P:
    """First matching rule's spec for one param. Scalars (rank 0 or
    one element) are replicated before the rules run (SNIPPETS [2]);
    an unmatched path is a hard error, not a silent replication."""
    shape = tuple(getattr(leaf, 'shape', ()) or ())
    if len(shape) == 0 or int(np.prod(shape)) == 1:
      return P()
    for pattern, spec in self.rules:
      if pattern.search(path):
        return spec
    raise ShardingRuleError(
        f'no partition rule matches param {path!r} (rule set '
        f'{self.rule_set!r}) — every param must resolve; add a rule '
        'or end the set with a catch-all (".*", PartitionSpec())')

  def param_specs(self, params):
    """Pytree of `PartitionSpec` over a param (or abstract
    shape/dtype) tree, keyed on the '/'-joined key path."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: self.spec_for(_path_str(kp), leaf), params)

  def opt_specs(self, opt_state, param_specs):
    """Optimizer-state specs cloned leaf-wise from the matched param
    specs (SNIPPETS [1]): subtrees whose structure equals the params'
    (first/second moment buffers) inherit `param_specs`; every other
    leaf (GA steps, schedule counts, scalars) is replicated."""
    pdef = jax.tree_util.tree_structure(param_specs)

    def is_param_shaped(x):
      try:
        return jax.tree_util.tree_structure(x) == pdef
      except Exception:
        return False

    def per_node(x):
      return param_specs if is_param_shaped(x) else P()

    return jax.tree_util.tree_map(per_node, opt_state,
                                  is_leaf=is_param_shaped)

  def state_specs(self, state):
    """Specs for a whole TrainState-like NamedTuple: `params` by the
    rules, `target_params` cloned from them (the IMPACT anchor shards
    EXACTLY like the params — mixed placements would force a
    resharding copy every step), `opt_state` via `opt_specs`, every
    other field (step counter, PopArt stats) replicated."""
    pspecs = self.param_specs(state.params)
    fields = {}
    for name, value in state._asdict().items():
      if name == 'params':
        fields[name] = pspecs
      elif name == 'target_params' and value is not None:
        fields[name] = pspecs
      elif name == 'opt_state':
        fields[name] = self.opt_specs(value, pspecs)
      else:
        fields[name] = jax.tree_util.tree_map(lambda _: P(), value)
    return type(state)(**fields)

  def describe(self, params, mesh: Optional[Mesh] = None
               ) -> Dict[str, str]:
    """{param_path: spec_string} — the on-disk manifest form
    (checkpoint.py records it per save; integrity.py digests it).
    With a mesh, the divisibility guard is applied first so the
    record names the placements that actually hold."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for kp, leaf in flat:
      path = _path_str(kp)
      spec = self.spec_for(path, leaf)
      if mesh is not None:
        spec = self._guard(spec, leaf, mesh)
      out[path] = str(spec)
    return out

  # --- binding (mesh-dependent) --------------------------------------

  def _guard(self, spec: P, leaf, mesh: Mesh) -> P:
    """Drop cuts that don't divide the leaf (odd feature sizes) —
    applied at binding so every consumer sees the same post-guard
    placement."""
    if not any(ax is not None for ax in spec):
      return spec
    width = int(mesh.shape.get(MODEL_AXIS, 1))
    for dim, ax in enumerate(spec):
      if ax is not None and (dim >= leaf.ndim
                             or leaf.shape[dim] % width != 0):
        return P()
    return spec

  def layout_violations(self, tree, mesh: Mesh):
    """[(path, reason)] for every leaf whose RESOLVED spec this mesh
    cannot honor — the structural half of the divisibility guard.
    Where `_guard` silently degrades such a binding to replicated,
    this names the leaf and the reason; cross-topology restore
    consults it (`check_layout`) so a topology change never silently
    rewrites the declared layout (round 20, elastic membership)."""
    out = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
      path = _path_str(kp)
      spec = self.spec_for(path, leaf)
      shape = tuple(getattr(leaf, 'shape', ()) or ())
      for dim, ax in enumerate(spec):
        if ax is None:
          continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        missing = sorted(set(axes) - set(mesh.shape))
        if missing:
          out.append((path, (
              f'spec {spec} names mesh axis {missing[0]!r} but the '
              f'target mesh only has {dict(mesh.shape)}')))
          continue
        width = 1
        for a in axes:
          width *= int(mesh.shape[a])
        if dim >= len(shape):
          out.append((path, (
              f'spec {spec} cuts dim {dim} but the leaf is rank '
              f'{len(shape)} {shape}')))
        elif shape[dim] % width != 0:
          out.append((path, (
              f'dim {dim} (size {shape[dim]}) does not divide mesh '
              f'axis {"*".join(axes)} width {width} (spec {spec})')))
    return out

  def check_layout(self, tree, mesh: Mesh, what: str = 'state',
                   saved_specs: Optional[Dict[str, str]] = None
                   ) -> None:
    """Raise `ShardingLayoutError` unless every leaf's resolved spec
    can bind on `mesh` exactly as resolved — the refusal gate of
    strict cross-topology restore. A leaf the SAVE already recorded
    as replicated (`saved_specs`: the checkpoint sharding manifest's
    {path: spec} table) is exempt: its cut was degraded before the
    topology changed, so the restore loses nothing the save still
    had."""
    replicated = str(P())
    violations = [
        (path, reason)
        for path, reason in self.layout_violations(tree, mesh)
        if saved_specs is None or saved_specs.get(path) != replicated]
    if not violations:
      return
    shown = '\n'.join(f'  - {p}: {r}' for p, r in violations[:8])
    more = ('' if len(violations) <= 8
            else f'\n  ... and {len(violations) - 8} more')
    raise ShardingLayoutError(
        f'{len(violations)} {what} leaf/leaves cannot be laid out on '
        f'the target mesh {dict(mesh.shape)} under rule set '
        f'{self.rule_set!r}:\n{shown}{more}\n'
        'Fix the target topology (every cut dim must divide its axis '
        'width), pick a rule set the mesh can honor, or restore '
        'non-strict to accept replicated degradation.')

  def param_shardings(self, params, mesh: Mesh):
    """NamedShardings for a param pytree on this mesh."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: NamedSharding(
            mesh,
            self._guard(self.spec_for(_path_str(kp), leaf), leaf, mesh)),
        params)

  def state_shardings(self, state, mesh: Mesh):
    """NamedShardings for a whole TrainState (optimizer moments cloned
    from param placements, everything else replicated)."""
    pshard = self.param_shardings(state.params, mesh)
    pspecs = jax.tree_util.tree_map(lambda s: s.spec, pshard)
    specs = self.state_specs(state)._replace(
        params=pspecs,
        target_params=(pspecs if state.target_params is not None
                       else None),
        opt_state=self.opt_specs(state.opt_state, pspecs))
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                  specs)

  def batch_specs(self, batch_pytree, shard_over_model: bool = False):
    """PartitionSpecs for the learner batch: data axis on the batch
    dim. Trajectory tensors are time-major [T+1, B, ...] → dim 1;
    level_name/agent_state are [B, ...] → dim 0 (keyed on the
    ActorOutput structural position).

    shard_over_model: shard the batch dim over BOTH axes instead of
    replicating it across the model axis — required when TP spans
    hosts (see `shard_batch_over_model`): every host then feeds
    distinct rows and GSPMD inserts the model-axis all-gather where
    the TP matmuls need the full data shard."""
    from scalable_agent_tpu.structs import ActorOutput

    axes = (DATA_AXIS, MODEL_AXIS) if shard_over_model else DATA_AXIS
    traj = lambda _: P(None, axes)  # noqa: E731
    lead = lambda _: P(axes)        # noqa: E731
    return ActorOutput(
        level_name=lead(None),
        agent_state=jax.tree_util.tree_map(lead,
                                           batch_pytree.agent_state),
        env_outputs=jax.tree_util.tree_map(traj,
                                           batch_pytree.env_outputs),
        agent_outputs=jax.tree_util.tree_map(
            traj, batch_pytree.agent_outputs))

  def batch_shardings(self, batch_pytree, mesh: Mesh,
                      shard_over_model: bool = False):
    """NamedShardings for the learner batch on this mesh."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        self.batch_specs(batch_pytree,
                         shard_over_model=shard_over_model))


def _path_str(kp) -> str:
  return '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                  for k in kp)


def from_config(config, enable_tp: Optional[bool] = None
                ) -> ShardingRegistry:
  """The registry a config declares: `config.sharding_rules` names a
  RULE_SETS entry; 'auto' resolves to 'megatron' when a model axis
  exists to cut ('replicated' otherwise). `enable_tp` overrides the
  model_parallelism predicate for callers that arm TP out-of-band
  (tests pass a TP mesh against a default config)."""
  name = getattr(config, 'sharding_rules', 'auto') or 'auto'
  if enable_tp is None:
    enable_tp = config.model_parallelism > 1
  if name == 'auto':
    name = 'megatron' if enable_tp else 'replicated'
  if name not in RULE_SETS:
    raise ValueError(
        f'unknown sharding_rules {name!r}; known: '
        f"auto, {', '.join(sorted(RULE_SETS))}")
  return ShardingRegistry(RULE_SETS[name], rule_set=name)


# --- shared primitive shardings (the non-param placements) ------------
#
# These are sharding decisions too — inference arenas, SDC probe
# vectors, Anakin carries, shard_map specs. One home for them keeps
# the `sharding-registry` lint meaningful: a consumer importing these
# provably made no private layout choice.


def spec_replicated() -> P:
  """The replicated PartitionSpec (shard_map in/out specs)."""
  return P()


def spec_data() -> P:
  """One vector sharded over the data axis (SDC probe lanes,
  per-replica shard_map inputs)."""
  return P(DATA_AXIS)


def spec_time_major(ndim: int, axis=DATA_AXIS) -> P:
  """[T, B, ...] tensors: batch dim 1 over `axis` (the shard_map
  boundary spec of the Pallas V-trace)."""
  return P(*((None, axis) + (None,) * (ndim - 2)))


def spec_batch_lead(ndim: int, axis=DATA_AXIS) -> P:
  """[B, ...] tensors: batch dim 0 over `axis`."""
  return P(*((axis,) + (None,) * (ndim - 1)))


def replicated(mesh: Mesh) -> NamedSharding:
  """Replicated placement on a mesh (params at inference, scalars,
  gathered outputs)."""
  return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh) -> NamedSharding:
  """Leading-dim data-axis placement (inference batch rows, SDC probe
  vectors)."""
  return NamedSharding(mesh, P(DATA_AXIS))


def quantized_specs(quantized_tree, plain_specs):
  """Specs for an int8-quantized param tree (round 21 publish codec),
  cloned from the PLAIN tree's registry specs: each `codec.Int8Leaf`
  keeps the original leaf's spec on `q` (same shape, so the rule that
  matched the f32 leaf is still the right placement) and replicates
  the scalar `scale` — the codec stays inside the registry's
  one-source-of-truth contract instead of inventing placements.

  `quantized_tree` is the encoded tree (Int8Leaf nodes where f32
  leaves were); `plain_specs` is `registry.param_specs(params)` over
  the ORIGINAL tree. Registry rules key on the plain tree's paths, so
  the clone — not a re-match against the deeper quantized paths — is
  what keeps regex rules working unchanged."""
  from scalable_agent_tpu.runtime import codec

  def one(leaf, spec):
    if isinstance(leaf, codec.Int8Leaf):
      return codec.Int8Leaf(spec, P())
    return spec

  return jax.tree_util.tree_map(
      one, quantized_tree, plain_specs,
      is_leaf=lambda x: isinstance(x, codec.Int8Leaf))


def quantized_shardings(quantized_tree, plain_specs, mesh: Mesh):
  """`quantized_specs` resolved to NamedShardings on `mesh` (the
  device_put placement of an int8-resident version-table entry on a
  sharded serving mesh)."""
  return jax.tree_util.tree_map(
      lambda spec: NamedSharding(mesh, spec),
      quantized_specs(quantized_tree, plain_specs))
