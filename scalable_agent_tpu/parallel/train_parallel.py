"""Sharded (multi-chip) training step.

One `jit` over the mesh: batch sharded on the data axis, params
replicated (or TP-sharded), optimizer state following params. XLA
inserts the gradient all-reduce (psum over ICI) — no hand-written
collectives needed for DP, which is the whole point of the design
(SURVEY §5.8: "gradient/metric reduction = jax.lax.psum over the DP
mesh axis" — jit's partitioner emits exactly that from these
shardings).
"""

import logging

import numpy as np

import jax
from jax.sharding import Mesh

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.parallel import sharding as sharding_lib

log = logging.getLogger('scalable_agent_tpu')


def make_sharded_train_state(params, config: Config, mesh: Mesh,
                             enable_tp: bool = False,
                             num_popart_tasks: int = 0,
                             registry=None):
  """Place params on the mesh and build the TrainState there, every
  placement resolved by the sharding registry (round 19): params by the
  partition rules, optimizer moments cloned leaf-wise from the matched
  param specs, `target_params` pinned identically (the IMPACT anchor's
  in-graph refresh is a leafwise select — mixed placements would force
  a resharding copy every step), and every remaining leaf (step/opt
  counters, PopArt stats) explicitly replicated — a single-device
  committed scalar next to mesh-committed params is a mixed-placement
  error under jit (bites after checkpoint restore).

  Params are placed BEFORE the optimizer state is built so the eager
  zeros_like moments materialize already-sharded (never an unsharded
  full copy in HBM); the final registry-wide device_put is then a
  no-op confirmation for them."""
  if registry is None:
    registry = sharding_lib.from_config(
        config, enable_tp=enable_tp or config.model_parallelism > 1)
  p_shard = registry.param_shardings(params, mesh)
  params = jax.tree_util.tree_map(jax.device_put, params, p_shard)
  state = learner_lib.make_train_state(params, config, num_popart_tasks)
  shardings = registry.state_shardings(state, mesh)
  return jax.tree_util.tree_map(jax.device_put, state, shardings)


def resolve_tp_compute(config) -> str:
  """'gathered' | 'sharded' — how TP matmuls actually execute.

  'auto' resolves per backend: CPU takes the gathered workaround (this
  jaxlib's partitioner mis-computes AD graphs over model-sharded
  leaves — see make_sharded_train_step); TPU/GPU keep true sharded
  compute. Explicit values win either way."""
  mode = getattr(config, 'tp_compute', 'auto')
  if mode == 'auto':
    return 'gathered' if jax.default_backend() == 'cpu' else 'sharded'
  return mode


def make_sharded_train_step(agent, config: Config, mesh: Mesh,
                            example_batch, donate: bool = True):
  """Jit the learner step with explicit in/out shardings over the mesh.

  Returns (train_step, place_batch): `place_batch` device_puts a host
  batch with the data-axis sharding — the host→device edge of the
  trajectory transport (the reference's StagingArea role).

  donate: donate the input state for in-place HBM update (the
  production default). False exists for environments whose jaxlib
  mis-sizes donation aliases of TP-sharded leaves ("Expected aliased
  input ... to have the same size" — the pre-existing bug xfail'd in
  tests/test_parallel.py); __graft_entry__'s dryrun falls back to it
  so the parity gate still runs there.

  The mesh rides into the step fn (round 8): the Pallas V-trace has
  no SPMD partitioning rule, so under this jit it runs shard_map'ped
  over the data axis — the fused kernel is no longer single-device
  only (vtrace.py / ops/vtrace_pallas.py).

  TP compute mode (round 17): with model_parallelism > 1 this jaxlib's
  CPU backend has a SECOND defect beyond donation aliasing — the
  partitioned program computes WRONG numerics whenever any leaf is
  model-axis-sharded (measured: annotating a single bias changes the
  loss by ~0.5; GSPMD and the experimental shardy partitioner both
  produce the identical wrong value, and sharding-constraining every
  activation does not repair it — only the differentiated (AD) graph
  is affected, a forward pass with an in-graph all-gather is exact).
  `resolve_tp_compute(config)` therefore selects 'gathered' on CPU:
  params stay TP-SHARDED AT REST (the memory story and the
  cross-process collective placement are real), but each step runs as
  gather → replicated-compute → scatter, three separate compiled
  programs, so the partitioner never differentiates through a
  model-sharded leaf. Parity-gated by the tp4 multihost child and
  tests/test_parallel.py. TPU/GPU keep true sharded TP compute
  ('sharded'); config.tp_compute overrides either way.
  """
  train_step = learner_lib.make_train_step_fn(agent, config, mesh=mesh)
  registry = sharding_lib.from_config(config)
  batch_shard = registry.batch_shardings(
      example_batch, mesh,
      shard_over_model=sharding_lib.shard_batch_over_model(config))
  replicated = sharding_lib.replicated(mesh)
  # None = decide on the first call from the LIVE state: TP can arrive
  # via config.model_parallelism or via a make_sharded_train_state
  # caller passing enable_tp out-of-band (tests do) — any model-
  # sharded leaf in the state means the defect applies.
  gathered_tp = (True if (config.model_parallelism > 1 and
                          resolve_tp_compute(config) == 'gathered')
                 else None)

  def jit_step(donate_now):
    return jax.jit(
        train_step,
        in_shardings=(None, batch_shard),  # state keeps its placement
        out_shardings=(None, replicated),
        donate_argnums=(0,) if donate_now else ())

  # Donation self-heal (round 17, the ring_buffer._insert pattern):
  # this jaxlib mis-pairs donation aliases of TP-sharded leaves
  # ("Expected aliased input ... to have the same size" — the
  # seed-listed defect, xfail'd in tests/test_parallel.py). The first
  # step that trips it rebuilds the jit UN-donated and retries with
  # the same arguments (the alias check fails before any buffer is
  # consumed — proven by the arena insert's identical retry);
  # correctness first, the in-place HBM update is an optimization.
  # The engaged fallback is visible as `step.donation_fallback` —
  # multi-process callers included, which is what turns the
  # tp-across-process tests green on this jaxlib.
  compiled = {'fn': jit_step(donate), 'donate': donate}

  # The two reshard programs of the gathered path (pure layout moves
  # as their OWN compiled programs — exact, verified leaf-identical
  # round trip), built ONCE on the first step: jit caches on function
  # identity, so a fresh jit(lambda ...) per call would retrace the
  # whole state tree twice per step. The scatter captures the at-rest
  # placements from the FIRST live state (a restored checkpoint's
  # placements included) and re-establishes them every step.
  _reshard_fns = {}

  def run_step(state, batch):
    nonlocal gathered_tp
    if gathered_tp is None:
      gathered_tp = (resolve_tp_compute(config) == 'gathered' and any(
          sharding_lib.MODEL_AXIS in str(getattr(x.sharding, 'spec', ''))
          for x in jax.tree_util.tree_leaves(state)
          if isinstance(x, jax.Array)))
      step.tp_gathered = gathered_tp
      if gathered_tp:
        _log_gathered()
    if not gathered_tp:
      return compiled['fn'](state, batch)
    # gather → replicated compute → scatter.
    if 'gather' not in _reshard_fns:
      at_rest = jax.tree_util.tree_map(lambda x: x.sharding, state)
      rep = jax.tree_util.tree_map(lambda _: replicated, state)
      _reshard_fns['gather'] = jax.jit(lambda t: t, out_shardings=rep)
      _reshard_fns['scatter'] = jax.jit(lambda t: t,
                                        out_shardings=at_rest)
    new_state, metrics = compiled['fn'](
        _reshard_fns['gather'](state), batch)
    return _reshard_fns['scatter'](new_state), metrics

  def step(state, batch):
    try:
      return run_step(state, batch)
    except Exception as e:  # jaxlib XlaRuntimeError (INTERNAL)
      if not compiled['donate'] or 'alias' not in str(e):
        raise
      log.warning(
          'sharded train step: donation aliasing defect on this '
          'jaxlib (%s) — rebuilding un-donated and retrying; HBM '
          'holds one extra state copy for the rest of the run', e)
      compiled['fn'] = jit_step(False)
      compiled['donate'] = False
      step.donation_fallback = True
      return run_step(state, batch)

  step.donation_fallback = False
  step.tp_gathered = bool(gathered_tp)

  def _log_gathered():
    log.info(
        'TP compute mode: gathered (params stay model-sharded at '
        'rest; each step gathers, computes replicated, re-scatters) — '
        'the %s backend mis-computes differentiated programs over '
        'model-sharded leaves on this jaxlib (docs/PARALLELISM.md)',
        jax.default_backend())

  if gathered_tp:
    _log_gathered()

  def place_batch(host_batch):
    """Host numpy → globally-sharded device arrays. Each process passes
    its LOCAL shard of the data axis (on a single host, local == global
    and this is an ordinary sharded device_put); across hosts this is
    the whole trajectory transport — data never leaves the host that
    produced it (SURVEY §5.8)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.make_array_from_process_local_data(
            s, np.asarray(x)),
        host_batch, batch_shard)

  return step, place_batch


def supports_sdc_check(config, mesh) -> bool:
  """Whether the cross-replica SDC fingerprint check can run here:
  it compares PER-REPLICA fingerprints of the (logically replicated)
  params, which needs a pure-DP mesh (TP-sharded params give each
  device a different — legitimately different — shard) with at least
  two data replicas to compare. Single device has nothing to
  cross-check; the driver then leaves the sentinel off."""
  if mesh is None:
    return False
  # "Are params logically replicated?" is a registry question now
  # (round 19): any model-axis rule means each device legitimately
  # holds a different shard — nothing to cross-compare.
  if sharding_lib.from_config(config).model_sharded:
    return False
  if sharding_lib.shard_batch_over_model(config):
    return False
  # Multi-process meshes need the in-graph all-gather (round 17): a
  # raw readback device_gets a P('data')-sharded array, which jax
  # refuses when shards live on non-addressable devices. With
  # sdc_allgather the fingerprint vector leaves the graph REPLICATED
  # (every host reads its local copy), so the PR 9 single-controller
  # gate lifts; without it the sentinel stays off here
  # (validate_distributed warns).
  if any(d.process_index != jax.process_index()
         for d in mesh.devices.flat):
    if not getattr(config, 'sdc_allgather', True):
      return False
  return mesh.shape[sharding_lib.DATA_AXIS] >= 2


def make_sdc_fingerprint_fn(mesh: Mesh):
  """Per-replica param fingerprints for the SDC sentinel (round 12).

  Returns (fingerprint_fn, num_replicas): `fingerprint_fn(params,
  probe_host)` dispatches a shard_map over the data axis in which EACH
  replica computes `learner.param_fingerprint` from ITS OWN copy of
  the replicated params — the computation runs on every device against
  the local HBM buffers, so a silently corrupted replica copy yields a
  differing entry of the returned [num_replicas] uint32 array. The
  driver reads it one step delayed (the sentinel pattern) and any
  disagreement is deterministic-compute-violated: incident + the PR 2
  rollback ladder (the restore re-replicates params, which is exactly
  the repair real SDC needs).

  `probe_host` is the chaos lane (runtime/faults.py
  'replica_divergence'): a host uint32 vector, normally zeros, added
  per-replica to the fingerprint INSIDE the graph. A GSPMD program
  cannot make a logically replicated array truly diverge — real SDC
  is a hardware fault below the program — so the drill perturbs the
  detector's per-replica view instead, driving the identical
  detection → incident → rollback path.

  check_rep=False: params enter replicated but the per-replica
  fingerprints are deliberately per-shard — the whole point is that
  'replicated' is an assumption the hardware can break, which is not
  a claim shard_map's replication checker can express.

  The [replicas] vector leaves the graph REPLICATED via an in-graph
  all-gather over the data axis (round 17): each replica computes its
  own fingerprint from local HBM, the all-gather exchanges the one
  uint32 per replica (bytes on the wire — noise against the step's
  gradient psum), and the host read then touches only addressable
  shards — which is what lifts the PR 9 single-controller gate and
  lets the sentinel run on multi-process meshes. The collective is
  dispatched from the lockstep driver path (per health check, every
  host), so it is barrier-safe by the same argument as the step
  itself."""
  from jax.experimental.shard_map import shard_map

  num_replicas = int(mesh.shape[sharding_lib.DATA_AXIS])
  probe_sharding = sharding_lib.data_sharding(mesh)

  def per_replica(params, probe):
    fp = learner_lib.param_fingerprint(params)
    # [1] per replica → all-gathered [replicas] on EVERY device. A
    # corrupted replica's entry differs identically in every copy of
    # the gathered vector, so any host's local read sees it.
    return jax.lax.all_gather(
        (fp + probe.reshape(())).reshape(()), sharding_lib.DATA_AXIS,
        tiled=False)

  sharded = jax.jit(shard_map(
      per_replica, mesh=mesh,
      in_specs=(sharding_lib.spec_replicated(),
                sharding_lib.spec_data()),
      out_specs=sharding_lib.spec_replicated(), check_rep=False))

  def fingerprint_fn(params, probe_host=None):
    if probe_host is None:
      probe_host = np.zeros((num_replicas,), np.uint32)
    probe = jax.device_put(
        np.ascontiguousarray(probe_host, np.uint32), probe_sharding)
    return sharded(params, probe)

  return fingerprint_fn, num_replicas


def supports_unroll_staging(config, mesh) -> bool:
  """Whether staging_mode='unroll' can serve this topology.

  The per-unroll staging plane places each unroll on the device owning
  its batch slot and assembles the global batch zero-copy from the
  per-device arenas — that requires a pure-data batch sharding (no
  model-axis replication of the batch: duplicating every unroll's H2D
  across the TP width would undo the trickle win) and a local batch
  that divides this process's data width. The driver falls back to
  'batch' with a warning otherwise; None mesh (single device) always
  supports it."""
  if mesh is None:
    return True
  if sharding_lib.shard_batch_over_model(config):
    return False
  if mesh.shape[sharding_lib.MODEL_AXIS] != 1:
    return False
  local = [d for d in mesh.devices.flat
           if d.process_index == jax.process_index()]
  local_batch = config.batch_size // jax.process_count()
  return bool(local) and local_batch % len(local) == 0


def unroll_slot_owners(local_devices, local_batch: int):
  """Slot → owning device for this PROCESS's slice of the global batch
  (round 17 pulls the arithmetic out of make_unroll_assembly so the
  placement is unit-testable without spawning processes).

  Slot s of the local batch lives on local_devices[s // per_dev] — the
  contiguous data-axis shard layout batch_shardings assigns, restricted
  to THIS process's addressable devices: unroll staging is the
  host-local half of the trajectory transport, so slot ownership must
  never name another host's device."""
  n_local = len(local_devices)
  if n_local == 0 or local_batch % n_local != 0:
    raise ValueError(
        f'local batch {local_batch} does not divide over '
        f'{n_local} local device(s)')
  per_dev = local_batch // n_local
  return [local_devices[s // per_dev] for s in range(local_batch)]


def make_unroll_assembly(config, mesh, example_batch):
  """Slot placement + zero-copy global assembly for the per-unroll
  staging plane (runtime/ring_buffer.UnrollBatchStager) over a pure-DP
  mesh.

  Returns (slot_devices, assemble_fn): slot s of this process's local
  batch lives on the s·D/B-th local mesh device (the contiguous
  data-axis shard layout batch_shardings assigns), and `assemble_fn`
  stitches the per-device arenas into the globally-sharded batch via
  `jax.make_array_from_single_device_arrays` — no copy, no host
  round trip: the arena rows ARE the step's shards. Single-host this
  is the whole batch; multi-host each process supplies its
  addressable shards, exactly like make_array_from_process_local_data
  does on the batch path."""
  if not supports_unroll_staging(config, mesh):
    raise ValueError('unroll staging unsupported on this topology '
                     '(see supports_unroll_staging)')
  batch_shard = sharding_lib.from_config(config).batch_shardings(
      example_batch, mesh, shard_over_model=False)
  local_devices = [d for d in mesh.devices.flat
                   if d.process_index == jax.process_index()]
  data_width = mesh.shape[sharding_lib.DATA_AXIS]
  local_batch = config.batch_size // jax.process_count()
  slot_devices = unroll_slot_owners(local_devices, local_batch)

  def assemble(sub_arenas):
    """Per-device arenas (device order) → the global sharded batch."""

    def join(sharding, *shards):
      spec = sharding.spec
      bdim = next(i for i, ax in enumerate(spec) if ax is not None)
      # Global batch dim: per-device rows × data-axis width.
      shape = list(shards[0].shape)
      shape[bdim] = shards[0].shape[bdim] * data_width
      return jax.make_array_from_single_device_arrays(
          tuple(shape), sharding, list(shards))

    return jax.tree_util.tree_map(join, batch_shard, *sub_arenas)

  return slot_devices, assemble
