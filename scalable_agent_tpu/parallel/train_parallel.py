"""Sharded (multi-chip) training step.

One `jit` over the mesh: batch sharded on the data axis, params
replicated (or TP-sharded), optimizer state following params. XLA
inserts the gradient all-reduce (psum over ICI) — no hand-written
collectives needed for DP, which is the whole point of the design
(SURVEY §5.8: "gradient/metric reduction = jax.lax.psum over the DP
mesh axis" — jit's partitioner emits exactly that from these
shardings).
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.parallel import mesh as mesh_lib


def make_sharded_train_state(params, config: Config, mesh: Mesh,
                             enable_tp: bool = False,
                             num_popart_tasks: int = 0):
  """Place params on the mesh (replicated, or TP-sharded kernels) and
  build the TrainState there. Optimizer moment trees inherit the param
  placements (eager zeros_like follows its input's sharding); scalar
  leaves (step/opt counters, PopArt stats) are explicitly replicated —
  a single-device committed scalar next to mesh-committed params is a
  mixed-placement error under jit (bites after checkpoint restore)."""
  p_shard = mesh_lib.param_shardings(params, mesh, enable_tp)
  params = jax.tree_util.tree_map(jax.device_put, params, p_shard)
  state = learner_lib.make_train_state(params, config, num_popart_tasks)
  replicated = NamedSharding(mesh, P())
  mesh_devices = set(mesh.devices.flat)

  def ensure_on_mesh(x):
    if (isinstance(x, jax.Array) and
        x.sharding.device_set == mesh_devices):
      return x
    return jax.device_put(x, replicated)

  return jax.tree_util.tree_map(ensure_on_mesh, state)


def make_sharded_train_step(agent, config: Config, mesh: Mesh,
                            example_batch, donate: bool = True):
  """Jit the learner step with explicit in/out shardings over the mesh.

  Returns (train_step, place_batch): `place_batch` device_puts a host
  batch with the data-axis sharding — the host→device edge of the
  trajectory transport (the reference's StagingArea role).

  donate: donate the input state for in-place HBM update (the
  production default). False exists for environments whose jaxlib
  mis-sizes donation aliases of TP-sharded leaves ("Expected aliased
  input ... to have the same size" — the pre-existing bug xfail'd in
  tests/test_parallel.py); __graft_entry__'s dryrun falls back to it
  so the parity gate still runs there.
  """
  train_step = learner_lib.make_train_step_fn(agent, config)
  batch_shard = mesh_lib.batch_shardings(
      example_batch, mesh,
      shard_over_model=mesh_lib.shard_batch_over_model(config))
  replicated = NamedSharding(mesh, P())

  jitted = jax.jit(
      train_step,
      in_shardings=(None, batch_shard),  # state keeps its placement
      out_shardings=(None, replicated),
      donate_argnums=(0,) if donate else ())

  def place_batch(host_batch):
    """Host numpy → globally-sharded device arrays. Each process passes
    its LOCAL shard of the data axis (on a single host, local == global
    and this is an ordinary sharded device_put); across hosts this is
    the whole trajectory transport — data never leaves the host that
    produced it (SURVEY §5.8)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.make_array_from_process_local_data(
            s, np.asarray(x)),
        host_batch, batch_shard)

  return jitted, place_batch
