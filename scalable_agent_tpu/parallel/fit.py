"""Compiled memory-fit check for the v5e-16 north-star topology.

BASELINE.json's ≥200k-fps target runs the FULL-FEATURE flagship step
(deep ResNet, T=100, B=32, DMLab 72×96, bf16, PopArt + pixel control +
instruction) data-parallel over 16 chips. Until round 6 the "fits on a
v5e-16" claim was projection arithmetic (docs/PERF.md collective
terms); this module turns it into a compiled fact: AOT-lower the
sharded train step over a pure-DP ``{'data': N}`` mesh, compile it,
and read per-device buffer sizes out of XLA's ``memory_analysis()``.

Caveat, stated where the numbers are made: when no 16-device TPU
platform exists the compile runs on N *virtual CPU devices*, so the
figure is the CPU backend's buffer assignment for the per-device
shapes — layout padding and fusion choices differ from the TPU
emitter's (CPU also computes bf16 matmuls via f32 temporaries, which
*overstates* temp. vs a real v5e). It bounds the shape arithmetic
with a compiled buffer assignment rather than hand-waving; the gate
uses a conservative budget margin and the artifact records the
backend it compiled for.

Consumed by:
- ``__graft_entry__.dryrun_multichip`` — the MULTICHIP_rN artifact
  records the fit figures for B=32 and B=16;
- ``scripts/aot_fit.py`` — the <60 s CPU CI smoke (scripts/ci.sh);
- ``tests/test_parallel.py`` — mechanics gate on the 8-device mesh.
"""

from typing import Any, Dict, Optional, Sequence

V5E_HBM_BYTES = 16 * 2**30  # 16 GiB HBM per v5e chip.
# Reserve headroom for XLA's runtime allocations the compile-time
# analysis cannot see (infeed buffers, collectives scratch, the
# framework's own arrays). 15% mirrors jax's default
# XLA_PYTHON_CLIENT_MEM_FRACTION margin.
HBM_BUDGET_FRACTION = 0.85


def full_feature_config(batch_size: int = 32, unroll_length: int = 100,
                        height: int = 72, width: int = 96):
  """The flagship full-feature learner config (the BASELINE.json
  DMLab-30 operating point bench.py's `full_feature` row measures)."""
  from scalable_agent_tpu.config import Config
  return Config(batch_size=batch_size, unroll_length=unroll_length,
                num_action_repeats=4, torso='deep',
                compute_dtype='bfloat16', use_popart=True,
                pixel_control_cost=0.01, use_instruction=True,
                height=height, width=width,
                total_environment_frames=int(1e9))


def aot_memory_fit(devices: Optional[Sequence[Any]] = None,
                   batch_size: int = 32, unroll_length: int = 100,
                   height: int = 72, width: int = 96,
                   num_tasks: int = 30,
                   hbm_bytes: int = V5E_HBM_BYTES) -> Dict[str, Any]:
  """AOT-compile the sharded full-feature step; return per-device fit.

  Pure-DP mesh over ``devices`` (default: all). Everything is
  abstract (``jax.eval_shape`` params, ShapeDtypeStruct batch): no
  param or batch buffer is ever materialized — this works at flagship
  shapes on any host.

  Returns a dict with per-device byte figures and ``fits`` — whether
  live bytes (arguments + outputs + temp − donation alias) stay under
  ``HBM_BUDGET_FRACTION`` of ``hbm_bytes``.
  """
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  from scalable_agent_tpu.parallel import sharding as sharding_lib
  from scalable_agent_tpu.testing import make_example_batch

  devices = list(devices) if devices is not None else jax.devices()
  n = len(devices)
  if batch_size % n:
    raise ValueError(f'batch_size={batch_size} must divide the mesh '
                     f'size {n}')
  mesh = mesh_lib.make_mesh(devices, model_parallelism=1)
  cfg = full_feature_config(batch_size, unroll_length, height, width)
  from scalable_agent_tpu import driver
  agent = driver.build_agent(cfg, num_actions=9, num_tasks=num_tasks)
  obs_spec = {'frame': (height, width, 3),
              'instr_len': MAX_INSTRUCTION_LEN}

  params_abs = jax.eval_shape(
      lambda: init_params(agent, jax.random.PRNGKey(0), obs_spec))
  state_abs = jax.eval_shape(
      lambda p: learner_lib.make_train_state(p, cfg,
                                             num_popart_tasks=num_tasks),
      params_abs)
  # Abstract batch: shapes/dtypes only (the real constructor would
  # materialize a ~67 MB frame stack for nothing). Built as an
  # eval_shape over the canonical constructor so the struct layout
  # can never drift from testing.make_example_batch.
  batch = jax.eval_shape(
      lambda: make_example_batch(unroll_length + 1, batch_size,
                                 height, width, 9,
                                 MAX_INSTRUCTION_LEN))

  # Pure-DP registry (round 19): params/state replicated, batch over
  # the data axis — the single sharding authority, not a private copy.
  registry = sharding_lib.ShardingRegistry(
      sharding_lib.RULE_SETS['replicated'], rule_set='replicated')
  batch_shard = registry.batch_shardings(batch, mesh)
  state_sh = registry.state_shardings(state_abs, mesh)
  # mesh rides in so a pallas-vtrace config lowers under shard_map
  # instead of failing the AOT fit (round 8 — the mesh restriction is
  # lifted everywhere, this path included).
  step = learner_lib.make_train_step_fn(agent, cfg, mesh=mesh)
  compiled = jax.jit(
      step, in_shardings=(state_sh, batch_shard),
      donate_argnums=(0,)).lower(state_abs, batch).compile()
  ma = compiled.memory_analysis()
  live = (ma.argument_size_in_bytes + ma.output_size_in_bytes +
          ma.temp_size_in_bytes - ma.alias_size_in_bytes)
  budget = int(hbm_bytes * HBM_BUDGET_FRACTION)
  return {
      'mesh': {'data': n},
      'backend': devices[0].platform,
      'batch_size': batch_size,
      'per_device_batch': batch_size // n,
      'unroll_length': unroll_length,
      'argument_bytes': int(ma.argument_size_in_bytes),
      'output_bytes': int(ma.output_size_in_bytes),
      'temp_bytes': int(ma.temp_size_in_bytes),
      'alias_bytes': int(ma.alias_size_in_bytes),
      'live_bytes': int(live),
      'live_gib': round(live / 2**30, 3),
      'hbm_bytes': int(hbm_bytes),
      'hbm_budget_bytes': budget,
      'fits': bool(live <= budget),
  }


def format_fit(fit: Dict[str, Any]) -> str:
  """One tail-capture-friendly line for the MULTICHIP artifact."""
  gib = 1 / 2**30
  return (
      'aot_fit(v5e16): B=%d (per-device %d) T=%d mesh=%s live=%.3f GiB'
      ' (args %.3f + out %.3f + temp %.3f - alias %.3f) vs budget '
      '%.1f GiB [backend=%s] %s' % (
          fit['batch_size'], fit['per_device_batch'],
          fit['unroll_length'], fit['mesh'],
          fit['live_bytes'] * gib, fit['argument_bytes'] * gib,
          fit['output_bytes'] * gib, fit['temp_bytes'] * gib,
          fit['alias_bytes'] * gib, fit['hbm_budget_bytes'] * gib,
          fit['backend'], 'ok' if fit['fits'] else 'DOES NOT FIT'))
