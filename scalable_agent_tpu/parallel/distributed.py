"""Multi-host initialization (the reference's ClusterSpec/gRPC role).

The reference scales across machines with the TF1 distributed runtime:
`tf.train.ClusterSpec` + `tf.train.Server`, learner-hosted queue,
variables served over gRPC (reference: experiment.py ≈L435–460; SURVEY
§5.8). The TPU-native story has no parameter server and no remote queue:

- every host runs the SAME program; `jax.distributed.initialize` wires
  the processes into one runtime;
- the device mesh (parallel/mesh.py) spans all hosts' chips; gradient
  psum rides ICI within a slice and DCN across slices — XLA picks the
  transport from the mesh topology;
- trajectory transport stays host-local: each host's actor fleet feeds
  the learner shard(s) on that host (data-parallel inputs are per-host
  shards of the global batch via
  `jax.make_array_from_process_local_data`);
- weight snapshots for actors are host-local device_gets — no gRPC.

On a single host this module is a no-op; the driver works unchanged.
"""

import logging
from typing import Optional

import jax

log = logging.getLogger('scalable_agent_tpu')


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_ids: Optional[list] = None) -> None:
  """Join the multi-host runtime (call before any device op).

  Args:
    coordinator_address: 'host:port' of process 0 (the reference's
      learner address role, minus the parameter server).
    num_processes: total host process count.
    process_id: this process's index (the reference's --task).
    local_device_ids: optionally restrict this process's devices.
  """
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
      local_device_ids=local_device_ids)
  log.info('jax.distributed: process %d/%d, %d local / %d global devices',
           process_id, num_processes, jax.local_device_count(),
           jax.device_count())


def global_batch_from_local(mesh, spec, local_batch):
  """Assemble a globally-sharded array from this host's local batch.

  Each host contributes its fleet's unrolls as the process-local part
  of the data-axis-sharded global batch (the reference's remote
  enqueue [NET] becomes: no transport at all — data stays where it
  was produced)."""
  return jax.tree_util.tree_map(
      lambda x, s: jax.make_array_from_process_local_data(s, x),
      local_batch, spec)
