"""Multi-host initialization (the reference's ClusterSpec/gRPC role).

The reference scales across machines with the TF1 distributed runtime:
`tf.train.ClusterSpec` + `tf.train.Server`, learner-hosted queue,
variables served over gRPC (reference: experiment.py ≈L435–460; SURVEY
§5.8). The TPU-native story has no parameter server and no remote queue:

- every host runs the SAME program; `jax.distributed.initialize` wires
  the processes into one runtime;
- the device mesh (parallel/mesh.py) spans all hosts' chips; gradient
  psum rides ICI within a slice and DCN across slices — XLA picks the
  transport from the mesh topology;
- trajectory transport stays host-local: each host's actor fleet feeds
  the learner shard(s) on that host (data-parallel inputs are per-host
  shards of the global batch via
  `jax.make_array_from_process_local_data`);
- weight snapshots for actors are host-local device_gets — no gRPC.

On a single host this module is a no-op; the driver works unchanged.
"""

import logging
import os
from typing import Optional

import jax

log = logging.getLogger('scalable_agent_tpu')


def is_initialized() -> bool:
  """Whether this process already joined a jax.distributed runtime.

  The fallback must be SIDE-EFFECT-FREE: probing jax.process_count()
  here would instantiate the backend, and a backend created before
  initialize() runs is built with collectives=none — the exact
  failure this module exists to prevent. If jax moved the seam we
  answer False; a double-join then fails loudly in
  jax.distributed.initialize instead of silently losing collectives."""
  try:
    from jax._src.distributed import global_state
    return global_state.coordinator_address is not None
  except Exception:
    return False


def _enable_cpu_collectives() -> None:
  """Arm cross-process collectives for the CPU backend (gloo).

  The CPU client is built with collectives=none by default, and every
  cross-process computation then fails with 'Multiprocess computations
  aren't implemented on the CPU backend' — the error the multihost
  tests were red with since seed. The flag is consumed at backend
  CREATION, so this must run before the first device op; once a
  backend exists we can only log. TPU/GPU backends ignore the flag
  (their collectives ride ICI/NCCL regardless)."""
  try:
    if jax.config.read('jax_cpu_collectives_implementation') != 'none':
      return  # operator already chose (gloo or mpi) — respect it.
    jax.config.update('jax_cpu_collectives_implementation', 'gloo')
    log.info('CPU backend: gloo cross-process collectives enabled')
  except Exception:
    # Older jaxlib without the option: multi-host CPU will fail at the
    # first collective with the backend's own error, which names the
    # real problem.
    log.warning('could not enable CPU gloo collectives (jax %s)',
                jax.__version__, exc_info=True)


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_ids: Optional[list] = None,
               heartbeat_interval_secs: Optional[int] = None,
               max_missing_heartbeats: Optional[int] = None) -> None:
  """Join the multi-host runtime (call before any device op).

  Args:
    coordinator_address: 'host:port' of process 0 (the reference's
      learner address role, minus the parameter server).
    num_processes: total host process count.
    process_id: this process's index (the reference's --task).
    local_device_ids: optionally restrict this process's devices.
    heartbeat_interval_secs / max_missing_heartbeats: coordination-
      service failure-detection tuning (both client and service side).
      None keeps jax's defaults (10 s x 10 = ~100 s to declare a host
      dead — right for production pods riding out GC pauses; the test
      harness passes seconds so a SIGKILL drill doesn't park the
      survivors for minutes).
  """
  _enable_cpu_collectives()
  kwargs = {}
  if heartbeat_interval_secs is not None:
    kwargs.update(
        service_heartbeat_interval_seconds=heartbeat_interval_secs,
        client_heartbeat_interval_seconds=heartbeat_interval_secs)
  if max_missing_heartbeats is not None:
    kwargs.update(service_max_missing_heartbeats=max_missing_heartbeats,
                  client_max_missing_heartbeats=max_missing_heartbeats)
  if kwargs:
    # The PUBLIC initialize() does not expose failure-detection tuning
    # (jax 0.4.x) — it forwards to global_state.initialize, which
    # does. Replicate its one guard and call through; fall back to the
    # public surface (default ~100 s detection) if jax moved the seam.
    try:
      from jax._src import distributed as jdist
      from jax._src import xla_bridge
      if xla_bridge.backends_are_initialized():
        raise RuntimeError(
            'distributed.initialize() must be called before any JAX '
            'computation (a backend already exists)')
      jdist.global_state.initialize(
          coordinator_address=coordinator_address,
          num_processes=num_processes,
          process_id=process_id,
          local_device_ids=local_device_ids,
          **kwargs)
      kwargs = None  # joined; skip the public path below
    except (ImportError, TypeError):
      log.warning('jax private distributed seam moved: heartbeat '
                  'tuning ignored, joining with default detection')
      kwargs = {}
  if kwargs is not None:
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
  log.info('jax.distributed: process %d/%d, %d local / %d global devices',
           process_id, num_processes, jax.local_device_count(),
           jax.device_count())


def _cpu_pinned_platform() -> bool:
  """True when this process is explicitly pinned to XLA:CPU.

  Checked WITHOUT touching `jax.devices()` — arming must never be
  what spins up the backend (that would break the
  distributed-init-before-backend ordering above). The config value
  is authoritative (the sandbox's sitecustomize and tests/conftest.py
  both pin through it); the env var covers plain
  `JAX_PLATFORMS=cpu python ...` launches."""
  plats = (getattr(jax.config, 'jax_platforms', None)
           or os.environ.get('JAX_PLATFORMS', '') or '')
  return plats.strip().lower() == 'cpu'


def _arm_compile_cache(config) -> None:
  """Point jax's persistent compilation cache at the config's dir.

  Must run BEFORE backend spin-up so the very first jit lowers
  through the cache — armed after the fact, the cold compile of the
  fused step (the expensive one) is never written. First writer
  wins: if something already armed a cache dir this process (a
  launcher, a test fixture, an earlier member in the same process),
  we leave it — one shared dir is the point, and members of a
  population deliberately converge on the parent logdir's cache.
  Resolved-empty disables cleanly. Failures only cost the warm-start
  optimization, never the run, so everything is best-effort.

  'auto' declines to arm on a CPU-pinned process: jaxlib's XLA:CPU
  executable deserialization is unreliable at driver scale (observed
  SIGSEGV/SIGABRT reloading ~1 MB train-step executables on jaxlib
  0.4.36 — one of two near-identical cache entries loads fine, the
  other kills the process), so a cache that silently turns itself on
  for every CPU test/tool run is a process-crash lottery, not an
  optimization. An EXPLICIT --compile_cache_dir still arms anywhere:
  opting in by hand is the caller saying their programs are small
  enough to reload safely (the anakin/bandit programs are — measured
  in docs/PERF.md)."""
  try:
    d = config.resolved_compile_cache_dir
    if not d:
      return
    if config.compile_cache_dir == 'auto' and _cpu_pinned_platform():
      log.info('persistent compilation cache: auto-arm skipped on '
               'CPU-pinned process (XLA:CPU executable reload is '
               'unreliable; pass --compile_cache_dir explicitly to '
               'override)')
      return
    if getattr(jax.config, 'jax_compilation_cache_dir', None):
      return  # first writer wins — an armed cache stays armed.
    os.makedirs(d, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', d)
    try:
      # Drop any cache backend built against the previous (None)
      # config value so the new dir actually takes effect.
      from jax._src import compilation_cache
      compilation_cache.reset_cache()
    except Exception:
      pass
    log.info('persistent compilation cache armed: %s', d)
  except Exception:
    log.warning('could not arm persistent compilation cache',
                exc_info=True)


def maybe_initialize(config) -> bool:
  """driver.train's spin-up seam (round 17): join the runtime the
  config names, exactly once.

  Returns True when this call initialized. No-ops (False) when the
  config names no coordinator, or when the process already joined —
  the launcher/test-harness path, where jax.distributed was
  initialized before driver.train was called.

  Also arms the persistent compilation cache (round 23) — here
  rather than in train() because the cache config must be set before
  the backend exists, and this is the one seam every entry path
  (train, train_population members, evaluate) crosses first."""
  _arm_compile_cache(config)
  if not config.coordinator_address:
    return False
  if is_initialized():
    log.info('jax.distributed already initialized '
             '(%d processes) — coordinator flags are a no-op',
             jax.process_count())
    return False
  from scalable_agent_tpu.config import resolve_process_id
  initialize(config.coordinator_address,
             num_processes=config.num_processes,
             process_id=resolve_process_id(config))
  return True


def topology_delta(saved_mesh_shape, mesh) -> Optional[dict]:
  """The elastic-restart detector (round 20, elastic membership).

  Compares the mesh-shape dict a checkpoint's sharding manifest
  recorded at save time against the LIVE mesh. None = same topology
  (or nothing recorded — pre-manifest checkpoints restore on the
  unchanged fixed-topology path); else the change record the driver
  logs and writes as the durable `topology_resharded` incident, with
  the live process topology attached so a postmortem can tell a
  2→4 grow from a 4→2 shrink without cross-referencing launch logs."""
  if saved_mesh_shape is None or mesh is None:
    return None
  live = {str(axis): int(n) for axis, n in dict(mesh.shape).items()}
  saved = {str(axis): int(n) for axis, n in saved_mesh_shape.items()}
  if saved == live:
    return None
  return {'saved_mesh': saved, 'live_mesh': live,
          'processes': jax.process_count(),
          'process_index': jax.process_index()}


def global_batch_from_local(mesh, spec, local_batch):
  """Assemble a globally-sharded array from this host's local batch.

  Each host contributes its fleet's unrolls as the process-local part
  of the data-axis-sharded global batch (the reference's remote
  enqueue [NET] becomes: no transport at all — data stays where it
  was produced)."""
  return jax.tree_util.tree_map(
      lambda x, s: jax.make_array_from_process_local_data(s, x),
      local_batch, spec)
