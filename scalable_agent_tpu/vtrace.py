"""V-trace off-policy actor-critic return estimator, TPU-native (pure JAX).

Re-expresses the reference's V-trace library (reference: vtrace.py —
`log_probs_from_logits_and_actions` ≈L60, `from_logits` ≈L80,
`from_importance_weights` ≈L130) with the same namedtuple API, clip
semantics and time-major [T, B, ...] layout, but built for XLA:

- The backward recursion ``acc <- delta_t + gamma_t * c_t * acc`` (the
  reference runs it as a reversed `tf.scan` with `parallel_iterations=1`
  explicitly placed on CPU) is a `jax.lax.scan` here — it compiles into a
  single fused XLA loop living on-device, so there is no host round trip.
- Because the recursion is a first-order *linear* recurrence, we also offer
  a work-parallel `jax.lax.associative_scan` formulation
  (``use_associative_scan=True``) which is O(log T) depth on TPU and is the
  door to sequence-parallel V-trace for long unrolls.

All math is float32; shapes are rank-generic like the reference (tested
with extra trailing dimensions).
"""

import collections

import jax
import jax.numpy as jnp
from jax import lax

VTraceFromLogitsReturns = collections.namedtuple(
    'VTraceFromLogitsReturns',
    ['vs', 'pg_advantages', 'log_rhos',
     'behaviour_action_log_probs', 'target_action_log_probs'])

VTraceReturns = collections.namedtuple('VTraceReturns', 'vs pg_advantages')


def log_probs_from_logits_and_actions(policy_logits, actions):
  """log pi(a|x) for the given actions.

  Mirrors the reference's `-sparse_softmax_cross_entropy` formulation
  (reference: vtrace.py ≈L60) — rank generic: `policy_logits` is
  [T, B, ..., NUM_ACTIONS] and `actions` is [T, B, ...].
  """
  policy_logits = jnp.asarray(policy_logits, jnp.float32)
  log_probs = jax.nn.log_softmax(policy_logits, axis=-1)
  return jnp.take_along_axis(
      log_probs, actions[..., None].astype(jnp.int32), axis=-1).squeeze(-1)


def from_logits(behaviour_policy_logits, target_policy_logits, actions,
                discounts, rewards, values, bootstrap_value,
                clip_rho_threshold=1.0, clip_pg_rho_threshold=1.0,
                use_associative_scan=False, use_pallas=False,
                mesh=None, batch_axis='data'):
  """V-trace for softmax policies (reference: vtrace.py ≈L80).

  Shapes (time-major): logits [T, B, NUM_ACTIONS], actions [T, B],
  discounts/rewards/values [T, B], bootstrap_value [B]. Extra trailing
  dimensions are supported everywhere the reference supports them.

  `mesh` (with `batch_axis`) only matters for the Pallas form: inside
  a sharded step the kernel runs under `shard_map` over the batch
  axis (ops/vtrace_pallas.sharded_from_importance_weights) — V-trace
  is per-batch-column independent, so the mapping is exact. The pure
  JAX forms partition under GSPMD without help and ignore it.

  `target_policy_logits` need not be the differentiated policy: the
  IMPACT surrogate (learner.loss_fn with config.surrogate='impact';
  arXiv 1912.00167) passes the TARGET-NETWORK logits here, so the IS
  ratios become pi_target/mu — clipped at the same rho-bar — and the
  returned `target_action_log_probs` double as the anchor log-probs
  the clipped surrogate's pi_theta/pi_target ratio is built from.
  Nothing differentiates through this function's outputs either way
  (vs/pg_advantages are stop-gradient'ed below).
  """
  behaviour_action_log_probs = log_probs_from_logits_and_actions(
      behaviour_policy_logits, actions)
  target_action_log_probs = log_probs_from_logits_and_actions(
      target_policy_logits, actions)
  log_rhos = target_action_log_probs - behaviour_action_log_probs
  vtrace_returns = from_importance_weights(
      log_rhos=log_rhos,
      discounts=discounts,
      rewards=rewards,
      values=values,
      bootstrap_value=bootstrap_value,
      clip_rho_threshold=clip_rho_threshold,
      clip_pg_rho_threshold=clip_pg_rho_threshold,
      use_associative_scan=use_associative_scan,
      use_pallas=use_pallas,
      mesh=mesh, batch_axis=batch_axis)
  return VTraceFromLogitsReturns(
      log_rhos=log_rhos,
      behaviour_action_log_probs=behaviour_action_log_probs,
      target_action_log_probs=target_action_log_probs,
      **vtrace_returns._asdict())


def _vs_minus_v_xs_scan(deltas, discounts_cs):
  """Sequential backward recursion via lax.scan (single fused XLA loop)."""

  def body(acc, x):
    delta_t, discount_c_t = x
    acc = delta_t + discount_c_t * acc
    return acc, acc

  init = jnp.zeros_like(deltas[0])
  _, out = lax.scan(body, init, (deltas, discounts_cs), reverse=True)
  return out


def _vs_minus_v_xs_associative(deltas, discounts_cs):
  """Same recurrence as `_vs_minus_v_xs_scan` but O(log T) depth.

  y_t = delta_t + (gamma_t c_t) y_{t+1} is a linear first-order recurrence;
  over reversed time it is y_i = a_i y_{i-1} + b_i which composes
  associatively as (a, b) ∘ (a', b') = (a a', a' b + b').
  """

  def combine(x, y):
    a_x, b_x = x
    a_y, b_y = y
    return a_y * a_x, a_y * b_x + b_y

  _, out = lax.associative_scan(combine, (discounts_cs, deltas),
                                reverse=True)
  return out


def from_importance_weights(log_rhos, discounts, rewards, values,
                            bootstrap_value, clip_rho_threshold=1.0,
                            clip_pg_rho_threshold=1.0,
                            use_associative_scan=False,
                            use_pallas=False,
                            mesh=None, batch_axis='data'):
  """V-trace from log importance weights (reference: vtrace.py ≈L130).

  rhos = exp(log_rhos); clipped at `clip_rho_threshold` (rho-bar) for the
  value fixpoint and `clip_pg_rho_threshold` for the policy-gradient
  advantage; cs = min(1, rhos). Outputs are stop-gradient'ed exactly like
  the reference.

  `use_pallas=True` runs the whole computation as one fused Pallas TPU
  kernel (ops/vtrace_pallas.py) — no HBM intermediates; interpreter
  mode off-TPU keeps CI on the same code path. Under a sharded step,
  pass the step's `mesh`: pallas_call has no SPMD partitioning rule,
  so the kernel is shard_map'ped over `batch_axis` instead (exact —
  each batch column is an independent recursion).
  """
  if use_pallas and use_associative_scan:
    raise ValueError('use_pallas and use_associative_scan are mutually '
                     'exclusive — pick one V-trace form')
  if use_pallas:
    from scalable_agent_tpu.ops import vtrace_pallas
    # Stop gradients on the INPUTS: the outputs are stop-gradiented
    # anyway (below and in the reference), and pallas_call has no jvp
    # rule — tangents reaching the kernel under value_and_grad would
    # fail at trace time.
    (log_rhos, discounts, rewards, values,
     bootstrap_value) = jax.tree_util.tree_map(
         lax.stop_gradient,
         (log_rhos, discounts, rewards, values, bootstrap_value))
    if mesh is not None:
      vs, pg_advantages = vtrace_pallas.sharded_from_importance_weights(
          mesh, log_rhos, discounts, rewards, values, bootstrap_value,
          clip_rho_threshold=clip_rho_threshold,
          clip_pg_rho_threshold=clip_pg_rho_threshold,
          batch_axis=batch_axis)
    else:
      vs, pg_advantages = vtrace_pallas.from_importance_weights(
          log_rhos, discounts, rewards, values, bootstrap_value,
          clip_rho_threshold=clip_rho_threshold,
          clip_pg_rho_threshold=clip_pg_rho_threshold)
    return VTraceReturns(vs=lax.stop_gradient(vs),
                         pg_advantages=lax.stop_gradient(pg_advantages))
  log_rhos = jnp.asarray(log_rhos, jnp.float32)
  discounts = jnp.asarray(discounts, jnp.float32)
  rewards = jnp.asarray(rewards, jnp.float32)
  values = jnp.asarray(values, jnp.float32)
  bootstrap_value = jnp.asarray(bootstrap_value, jnp.float32)

  rhos = jnp.exp(log_rhos)
  if clip_rho_threshold is not None:
    clipped_rhos = jnp.minimum(clip_rho_threshold, rhos)
  else:
    clipped_rhos = rhos
  cs = jnp.minimum(1.0, rhos)

  # V(x_{t+1}) with the bootstrap appended.
  values_t_plus_1 = jnp.concatenate(
      [values[1:], bootstrap_value[None]], axis=0)
  deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)

  scan_fn = (_vs_minus_v_xs_associative if use_associative_scan
             else _vs_minus_v_xs_scan)
  vs_minus_v_xs = scan_fn(deltas, discounts * cs)

  vs = vs_minus_v_xs + values

  # Advantage for the policy gradient; vs_{t+1} uses the bootstrap at the end.
  vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
  if clip_pg_rho_threshold is not None:
    clipped_pg_rhos = jnp.minimum(clip_pg_rho_threshold, rhos)
  else:
    clipped_pg_rhos = rhos
  pg_advantages = clipped_pg_rhos * (
      rewards + discounts * vs_t_plus_1 - values)

  return VTraceReturns(
      vs=lax.stop_gradient(vs),
      pg_advantages=lax.stop_gradient(pg_advantages))
