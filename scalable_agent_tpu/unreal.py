"""UNREAL pixel-control auxiliary task.

NOT in the reference — a planned extension (SURVEY §2.12 / BASELINE
config ladder). Implements the pixel-control auxiliary objective of
UNREAL ("Reinforcement Learning with Unsupervised Auxiliary Tasks",
Jaderberg et al. 2017 §3.1):

- pseudo-rewards: the frame is divided into `cell_size`×`cell_size`
  cells; the reward for a cell at step t is the mean absolute pixel
  change within the cell between consecutive observations;
- an auxiliary dueling Q-head (deconv from the LSTM output) predicts,
  per cell and per action, the discounted pseudo-return of maximally
  changing that cell;
- the loss is n-step Q-learning over the unroll, bootstrapped from
  max_a Q at the final frame (the same backward-recursion shape as
  V-trace — `lax.scan` over reversed time).

Everything here is pure JAX over [T, B] time-major tensors; the head
itself lives in models/agent.py (it needs the LSTM features).

Round 6 (the full-feature 20%, docs/PERF.md): the pixel-control path
got the step-cost treatment. Two numerics-preserving fast paths ship
behind config (defaults stay at the reference forms until the chip
rows land — see config.py), each parity-gated in tests/test_unreal.py
and individually measured by bench.py's `pc_levers` stage:

- `pixel_control_rewards` has an INTEGER-DOMAIN form (uint8 frames
  only): |Δ| in int16, per-cell sum in int32, one float32 scale at
  the tiny [T, B, Hc, Wc] output — where the f32 reference form
  leaves it to the backend's fusion whether a full-resolution float
  copy of the [T+1, B, H, W, C] frame stack materializes (a real
  risk in a step that is ~72% HBM-bound). Mathematically identical:
  the integer sum is exact; one correctly-rounded division replaces
  a 48-term float mean.
- the stride-2 4×4 `ConvTranspose` of the Q-head can run as a
  depth-to-space decomposition (`_DeconvD2S`): one dense VALID 2×2
  conv over the zero-padded input producing all four output phases as
  channels, then a pixel-shuffle interleave. Parameter-identical to
  the deconv (same names, shapes, and init — checkpoints are
  interchangeable) and algebraically the same map; it removes the
  zero-stuffed fractionally-strided conv (75% wasted taps at stride
  2) that XLA's TPU emitter otherwise lowers the deconv to.

One numerics-AFFECTING lever is gated OFF by default:
`out_f32=False` keeps the Q-map in the compute dtype (bfloat16 on
TPU) until the loss's gather/max — the [N, Hc, Wc, A] f32
materialization halves — at the cost of bf16-rounding the Q values
the loss sees (config.pixel_control_q_f32).
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

DEFAULT_CELL_SIZE = 4
DEFAULT_DISCOUNT = 0.9

HEAD_IMPLS = ('deconv', 'd2s')


def pixel_control_rewards(frames, cell_size: int = DEFAULT_CELL_SIZE,
                          integer_path: bool = None):
  """Per-cell mean |Δpixel| between consecutive frames.

  Args:
    frames: uint8/float [T+1, B, H, W, C] observations (H, W divisible
      by cell_size).
    integer_path: None (auto) → use the integer-domain form exactly
      when `frames` is uint8; True forces it (uint8 required); False
      forces the f32 reference form. Both forms compute the same
      quantity — the integer form is the byte lever (no full-res
      float temporaries), the f32 form is the golden reference the
      parity test pins it to.
  Returns:
    f32 [T, B, H/cell, W/cell] pseudo-rewards; entry t covers the
    transition from frame t to frame t+1.
  """
  t1, b, h, w, c = frames.shape
  if h % cell_size or w % cell_size:
    raise ValueError(
        f'frame {h}x{w} not divisible by pixel-control cell_size '
        f'{cell_size}')
  hc, wc = h // cell_size, w // cell_size
  is_uint8 = frames.dtype == jnp.uint8
  if integer_path is None:
    integer_path = is_uint8
  if integer_path and not is_uint8:
    raise ValueError(
        f'integer-domain pixel_control_rewards needs uint8 frames, '
        f'got {frames.dtype}')
  if integer_path:
    # |a - b| exactly in int16 (uint8 range fits), per-cell sum in
    # int32 (≤ 255·cell²·C per cell — far inside i32), ONE f32 scale
    # at the [T, B, Hc, Wc] output. No [T, B, H, W, C] float
    # temporary exists at any point.
    d = jnp.abs(frames[1:].astype(jnp.int16) -
                frames[:-1].astype(jnp.int16))
    d = d.reshape(t1 - 1, b, hc, cell_size, wc, cell_size, c)
    cell_sum = d.astype(jnp.int32).sum(axis=(3, 5, 6))
    scale = 1.0 / (255.0 * cell_size * cell_size * c)
    return cell_sum.astype(jnp.float32) * jnp.float32(scale)
  f = frames.astype(jnp.float32) / 255.0
  diff = jnp.abs(f[1:] - f[:-1])  # [T, B, H, W, C]
  diff = diff.reshape(t1 - 1, b, hc, cell_size, wc, cell_size, c)
  return diff.mean(axis=(3, 5, 6))


class _DeconvD2S(nn.Module):
  """Stride-2 4×4 SAME ConvTranspose as conv + depth-to-space.

  Parameter-identical to `nn.ConvTranspose(features, (4, 4),
  strides=(2, 2), padding='SAME')`: a `kernel` [4, 4, in, out] and a
  `bias` [out] under the same names with the same initializers, so
  the two implementations are interchangeable on one checkpoint (the
  golden parity test applies both to shared params).

  Derivation: flax's ConvTranspose lowers to a correlation over the
  stride-dilated input with padding (2, 2). Output row 2i+r only
  meets kernel taps with row index ≡ r (mod 2) — the kernel splits
  into four 2×2 phase kernels w[r::2, c::2]. Computing all four
  phases as output channels of ONE VALID 2×2 conv over the
  (1, 1)-padded input yields every output pixel; phase (r, c) lives
  at window offset (r, c), and a reshape/transpose interleaves them
  back into the [2H, 2W] grid. Same multiply count as the dense view
  of the deconv, but as a standard conv (an [N·H·W, 2·2·in] @
  [2·2·in, 4·out] contraction) with no zero-stuffed rows.
  """
  features: int
  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    n, h, w, cin = x.shape
    f = self.features
    kernel = self.param('kernel', nn.initializers.lecun_normal(),
                        (4, 4, cin, f), jnp.float32)
    bias = self.param('bias', nn.initializers.zeros_init(), (f,),
                      jnp.float32)
    x, kernel, bias = nn.dtypes.promote_dtype(x, kernel, bias,
                                              dtype=self.dtype)
    # Phase kernels stacked on the output-channel dim, order
    # (r, c) ∈ [(0,0), (0,1), (1,0), (1,1)].
    phased = jnp.concatenate(
        [kernel[r::2, c::2] for r in (0, 1) for c in (0, 1)], axis=-1)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    y = jax.lax.conv_general_dilated(
        xp, phased, window_strides=(1, 1), padding='VALID',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))  # [n, h+1, w+1, 4f]
    parts = []
    for i, (r, c) in enumerate(((0, 0), (0, 1), (1, 0), (1, 1))):
      parts.append(y[:, r:r + h, c:c + w, i * f:(i + 1) * f])
    y = jnp.stack(parts, axis=-1)          # [n, h, w, f, (r·2+c)]
    y = y.reshape(n, h, w, f, 2, 2)        # [n, h, w, f, r, c]
    y = y.transpose(0, 1, 4, 2, 5, 3)      # [n, h, r, w, c, f]
    y = y.reshape(n, 2 * h, 2 * w, f)
    return y + bias


class PixelControlHead(nn.Module):
  """Dueling deconv Q-head: LSTM features → [Hc, Wc, A] Q-values.

  UNREAL §3.1 architecture shape: FC → spatial map → deconv ×2 → dueling
  value/advantage maps. `target_cells` = (H/cell, W/cell) of the frame.

  head_impl: 'deconv' (the stride-2 nn.ConvTranspose reference form)
  or 'd2s' (the parameter-identical depth-to-space recast — see
  _DeconvD2S). The stride-1 3×3 value/advantage ConvTransposes are
  already plain convolutions in disguise (SAME, no dilation) and stay
  shared between the impls.

  out_f32: cast the Q-map to float32 at the head (the r5 form). False
  keeps it in `dtype` until the loss gathers/maxes it — the byte
  lever behind config.pixel_control_q_f32.
  """
  num_actions: int
  target_cells: Any  # (hc, wc)
  dtype: Any = jnp.float32
  head_impl: str = 'deconv'
  out_f32: bool = True

  @nn.compact
  def __call__(self, core_out):
    if self.head_impl not in HEAD_IMPLS:
      raise ValueError(f'head_impl must be one of {HEAD_IMPLS}, got '
                       f'{self.head_impl!r}')
    hc, wc = self.target_cells
    # Round the base grid UP so the stride-2 deconv covers the target;
    # crop after (odd cell grids — e.g. 84x84/4 → 21x21 — just work).
    base_h, base_w, ch = (hc + 1) // 2, (wc + 1) // 2, 32
    x = nn.Dense(base_h * base_w * ch, dtype=self.dtype,
                 name='pc_fc')(core_out)
    x = nn.relu(x)
    x = x.reshape(x.shape[0], base_h, base_w, ch)
    if self.head_impl == 'd2s':
      x = _DeconvD2S(ch, dtype=self.dtype, name='pc_deconv')(x)
    else:
      x = nn.ConvTranspose(ch, (4, 4), strides=(2, 2), padding='SAME',
                           dtype=self.dtype, name='pc_deconv')(x)
    x = nn.relu(x)[:, :hc, :wc]
    value = nn.ConvTranspose(1, (3, 3), padding='SAME',
                             dtype=self.dtype, name='pc_value')(x)
    advantage = nn.ConvTranspose(self.num_actions, (3, 3),
                                 padding='SAME', dtype=self.dtype,
                                 name='pc_advantage')(x)
    advantage = advantage - advantage.mean(axis=-1, keepdims=True)
    q = value + advantage  # [N, hc, wc, A]
    return q.astype(jnp.float32) if self.out_f32 else q


def pixel_control_loss(q_values, actions, rewards, done,
                       discount: float = DEFAULT_DISCOUNT):
  """n-step Q loss for the pixel-control head.

  Args:
    q_values: f32 or bf16 [T+1, B, Hc, Wc, A] — Q at every
      observation; the last frame provides the max-Q bootstrap. A
      non-f32 Q-map (config.pixel_control_q_f32=False) is cast to
      f32 only AFTER the gather/max, so the full [T+1, B, Hc, Wc, A]
      float32 tensor never materializes.
    actions: i32 [T, B] — action taken on the t→t+1 transition.
    rewards: f32 [T, B, Hc, Wc] pseudo-rewards (pixel_control_rewards).
    done: bool [T, B] — done[t] True ⇒ the t'th transition crosses an
      episode reset (frame t+1 starts a new episode): no reward flows
      and the return recursion cuts.
  Returns:
    scalar loss: 0.5·Σ_cells (target − Q[a])², meaned over T and B.
  """
  not_done = (~done).astype(jnp.float32)[..., None, None]  # [T,B,1,1]
  rewards = rewards * not_done
  bootstrap = q_values[-1].max(axis=-1).astype(jnp.float32)  # [B,Hc,Wc]

  def step(carry, inputs):
    r, nd = inputs
    ret = r + discount * nd * carry
    return ret, ret

  _, targets = jax.lax.scan(
      step, bootstrap, (jnp.flip(rewards, 0), jnp.flip(not_done, 0)))
  targets = jnp.flip(targets, 0)  # [T, B, Hc, Wc]
  targets = jax.lax.stop_gradient(targets)

  q_taken = jnp.take_along_axis(
      q_values[:-1], actions[:, :, None, None, None], axis=-1
      )[..., 0].astype(jnp.float32)
  per_step = 0.5 * jnp.square(targets - q_taken).sum(axis=(2, 3))
  return per_step.mean()
