"""UNREAL pixel-control auxiliary task.

NOT in the reference — a planned extension (SURVEY §2.12 / BASELINE
config ladder). Implements the pixel-control auxiliary objective of
UNREAL ("Reinforcement Learning with Unsupervised Auxiliary Tasks",
Jaderberg et al. 2017 §3.1):

- pseudo-rewards: the frame is divided into `cell_size`×`cell_size`
  cells; the reward for a cell at step t is the mean absolute pixel
  change within the cell between consecutive observations;
- an auxiliary dueling Q-head (deconv from the LSTM output) predicts,
  per cell and per action, the discounted pseudo-return of maximally
  changing that cell;
- the loss is n-step Q-learning over the unroll, bootstrapped from
  max_a Q at the final frame (the same backward-recursion shape as
  V-trace — `lax.scan` over reversed time).

Everything here is pure JAX over [T, B] time-major tensors; the head
itself lives in models/agent.py (it needs the LSTM features).
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

DEFAULT_CELL_SIZE = 4
DEFAULT_DISCOUNT = 0.9


def pixel_control_rewards(frames, cell_size: int = DEFAULT_CELL_SIZE):
  """Per-cell mean |Δpixel| between consecutive frames.

  Args:
    frames: uint8/float [T+1, B, H, W, C] observations (H, W divisible
      by cell_size).
  Returns:
    f32 [T, B, H/cell, W/cell] pseudo-rewards; entry t covers the
    transition from frame t to frame t+1.
  """
  t1, b, h, w, c = frames.shape
  if h % cell_size or w % cell_size:
    raise ValueError(
        f'frame {h}x{w} not divisible by pixel-control cell_size '
        f'{cell_size}')
  f = frames.astype(jnp.float32) / 255.0
  diff = jnp.abs(f[1:] - f[:-1])  # [T, B, H, W, C]
  hc, wc = h // cell_size, w // cell_size
  diff = diff.reshape(t1 - 1, b, hc, cell_size, wc, cell_size, c)
  return diff.mean(axis=(3, 5, 6))


class PixelControlHead(nn.Module):
  """Dueling deconv Q-head: LSTM features → [Hc, Wc, A] Q-values.

  UNREAL §3.1 architecture shape: FC → spatial map → deconv ×2 → dueling
  value/advantage maps. `target_cells` = (H/cell, W/cell) of the frame.
  """
  num_actions: int
  target_cells: Any  # (hc, wc)
  dtype: jnp.dtype = jnp.float32

  @nn.compact
  def __call__(self, core_out):
    hc, wc = self.target_cells
    # Round the base grid UP so the stride-2 deconv covers the target;
    # crop after (odd cell grids — e.g. 84x84/4 → 21x21 — just work).
    base_h, base_w, ch = (hc + 1) // 2, (wc + 1) // 2, 32
    x = nn.Dense(base_h * base_w * ch, dtype=self.dtype,
                 name='pc_fc')(core_out)
    x = nn.relu(x)
    x = x.reshape(x.shape[0], base_h, base_w, ch)
    x = nn.ConvTranspose(ch, (4, 4), strides=(2, 2), padding='SAME',
                         dtype=self.dtype, name='pc_deconv')(x)
    x = nn.relu(x)[:, :hc, :wc]
    value = nn.ConvTranspose(1, (3, 3), padding='SAME',
                             dtype=self.dtype, name='pc_value')(x)
    advantage = nn.ConvTranspose(self.num_actions, (3, 3),
                                 padding='SAME', dtype=self.dtype,
                                 name='pc_advantage')(x)
    advantage = advantage - advantage.mean(axis=-1, keepdims=True)
    return (value + advantage).astype(jnp.float32)  # [N, hc, wc, A]


def pixel_control_loss(q_values, actions, rewards, done,
                       discount: float = DEFAULT_DISCOUNT):
  """n-step Q loss for the pixel-control head.

  Args:
    q_values: f32 [T+1, B, Hc, Wc, A] — Q at every observation; the
      last frame provides the max-Q bootstrap.
    actions: i32 [T, B] — action taken on the t→t+1 transition.
    rewards: f32 [T, B, Hc, Wc] pseudo-rewards (pixel_control_rewards).
    done: bool [T, B] — done[t] True ⇒ the t'th transition crosses an
      episode reset (frame t+1 starts a new episode): no reward flows
      and the return recursion cuts.
  Returns:
    scalar loss: 0.5·Σ_cells (target − Q[a])², meaned over T and B.
  """
  not_done = (~done).astype(jnp.float32)[..., None, None]  # [T,B,1,1]
  rewards = rewards * not_done
  bootstrap = q_values[-1].max(axis=-1)  # [B, Hc, Wc]

  def step(carry, inputs):
    r, nd = inputs
    ret = r + discount * nd * carry
    return ret, ret

  _, targets = jax.lax.scan(
      step, bootstrap, (jnp.flip(rewards, 0), jnp.flip(not_done, 0)))
  targets = jnp.flip(targets, 0)  # [T, B, Hc, Wc]
  targets = jax.lax.stop_gradient(targets)

  q_taken = jnp.take_along_axis(
      q_values[:-1], actions[:, :, None, None, None], axis=-1)[..., 0]
  per_step = 0.5 * jnp.square(targets - q_taken).sum(axis=(2, 3))
  return per_step.mean()
