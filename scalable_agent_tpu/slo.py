"""SLO engine: declarative objectives over the metrics registry,
burn-rate evaluation, and triggered deep diagnostics (round 14).

PR 10 gave the fleet rich sensors — a 47-name metrics registry, trace
spans, policy-lag attribution, a flight recorder — but nothing in the
system *judges* those numbers: every target lived in a human's head or
a chaos script's asserts. This module is the sensor-to-verdict half of
the control loop (ROADMAP item 5; PAL's resource-aware monitoring,
arXiv 2110.01101, and the per-plane accounting Podracer makes
first-class, arXiv 2104.06272):

1. **Declarative objectives** (`Objective`): named targets over
   registry metric names — `policy_lag_p99 <= N`,
   `env_plane_utilization >= x`, `wire_crc_rejected rate == 0`, an fps
   floor against a per-host baseline file — each with a comparison, a
   target, fast/slow evaluation windows, and a severity
   (info < ticket < page). `DEFAULT_OBJECTIVES` ships a set covering
   every plane PRs 1–10 instrumented; `--slo_spec` loads a custom JSON
   set. Metric names are literal strings on purpose: scripts/ci.sh
   lints every objective's metric against the registered-name
   inventory (an objective over a metric nobody registers is a CI
   failure, both directions).

2. **Burn-rate evaluation** (`SloEvaluator`): registry snapshots
   accumulate into a bounded history; each objective is judged over a
   FAST and a SLOW window (multi-window burn-rate alerting — a blip
   must not page, a sustained burn must). Value objectives burn when
   every fast-window sample violates (≥ `min_samples`) AND at least
   half the slow-window samples do; rate objectives (counters) burn on
   the windowed delta/rate. Missing or NaN metrics evaluate as
   `no_data` (present in the verdict, never a violation — a
   `--telemetry_trace=false` run must not page on its own blindness).

3. **Triggered deep diagnostics** (`SloEngine`): on the FIRST burn of
   a severity≥page objective the engine captures its own explanation —
   a flight-recorder dump and a trace_report hop-delta slice over the
   violation window land in `<logdir>/diagnostics/`, and a bounded
   `jax.profiler` capture of the next K learner steps is requested
   from the driver loop (slo.py itself never imports jax). Rate
   limited: ONE capture per objective per run. An SLO page therefore
   ships with the pipeline history that explains it.

4. **The verdict** (`SLO_VERDICT.json`): one per-run artifact —
   overall pass/fail plus per-objective state, value, target, margin,
   and burn count — consumed by scripts/chaos.py (the storms assert
   the SAME objectives production is judged by), scripts/soak.py, and
   scripts/slo_report.py (the CI/chip go-no-go gate, which also diffs
   bench headline numbers against docs/BENCH_HISTORY.md baselines).

Cost is measured, not assumed: bench.py's `slo` stage times the
evaluator tick and the profiler-capture overhead; the default-ON call
is recorded in docs/PERF.md (r12).

No jax imports here — the engine must be importable by actor hosts,
scripts, and tests without accelerator initialization (the telemetry
module's rule).
"""

import collections
import dataclasses
import json
import math
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock

# Severity ladder. Only `page` triggers deep diagnostics; `info`
# objectives are recorded in the verdict but never fail it (advisory
# floors an operator tunes per deployment).
SEVERITIES = ('info', 'ticket', 'page')

# Objective states in the verdict.
OK = 'ok'
BURNING = 'burning'
NO_DATA = 'no_data'          # metric absent/NaN over the window
NO_BASELINE = 'no_baseline'  # baseline-relative target, no baseline

_COMPARATORS = {
    '<=': lambda v, t: v <= t,
    '>=': lambda v, t: v >= t,
    '==': lambda v, t: v == t,
}


@dataclasses.dataclass(frozen=True)
class Objective:
  """One declarative objective over a registry metric.

  Args:
    name: the objective's name (verdict key, incident label,
      diagnostics filename stem).
    metric: the registry metric name judged (ci.sh lints it against
      the registered inventory).
    comparison: '<=', '>=' or '==' — value `comparison` target holds
      when healthy.
    target: the threshold. With `baseline` set, a FRACTION of the
      per-host baseline value instead (see `fps_floor`).
    kind: 'value' (judge the sampled values in the windows) or 'rate'
      (judge the windowed counter movement: the per-second rate for
      <=/>=; the raw window delta for '==' — `rate == 0` means "this
      counter must not move").
    field: for histogram metrics, which snapshot field to judge
      ('p50' | 'p99' | 'max' | 'count' | 'sum').
    fast_window_secs / slow_window_secs: the two burn windows. None
      defers to the evaluator's configured defaults.
    severity: 'info' | 'ticket' | 'page'.
    baseline: key into the per-host baseline file; the effective
      target is baseline_value * target. No file/entry → NO_BASELINE.
    description: one line for the verdict/docs.
  """
  name: str
  metric: str
  comparison: str
  target: float
  kind: str = 'value'
  field: Optional[str] = None
  fast_window_secs: Optional[float] = None
  slow_window_secs: Optional[float] = None
  severity: str = 'ticket'
  baseline: Optional[str] = None
  description: str = ''

  def validate(self):
    if self.comparison not in _COMPARATORS:
      raise ValueError(f'objective {self.name!r}: comparison must be '
                       f'one of {sorted(_COMPARATORS)}, got '
                       f'{self.comparison!r}')
    if self.kind not in ('value', 'rate'):
      raise ValueError(f'objective {self.name!r}: kind must be '
                       f'value|rate, got {self.kind!r}')
    if self.severity not in SEVERITIES:
      raise ValueError(f'objective {self.name!r}: severity must be '
                       f'one of {SEVERITIES}, got {self.severity!r}')
    if not self.metric or '/' not in self.metric:
      raise ValueError(f'objective {self.name!r}: metric must be a '
                       f'registry name (component/name), got '
                       f'{self.metric!r}')
    return self


# The shipped default set — one named objective per plane PRs 1–10
# instrumented. Names, metrics, targets, windows and severities are
# all literals: docs/OBSERVABILITY.md carries this table verbatim and
# scripts/ci.sh lints BOTH directions (an objective over an
# unregistered metric, and a documented objective nobody ships).
# Targets are deliberately loose "is the system sane" floors — an
# operator tightens them per deployment via --slo_spec; the point of
# the defaults is that every run is judged by SOMETHING machine-read.
DEFAULT_OBJECTIVES = (
    # Policy-lag plane (PR 10): the publish-count delta V-trace
    # corrects for. The healthy bound is the feed pipeline's depth
    # (buffer + staging + in-flight batches — measured p99 ~5-8 on
    # the per-step publish cadence); p99 past 16 published versions
    # means staleness is OFF the V-trace design point — page, with
    # the trace slice as the explanation.
    Objective(name='policy_lag_p99', metric='trace/policy_lag',
              field='p99', comparison='<=', target=16.0,
              severity='page',
              description='behaviour-vs-train publish-count delta p99'),
    # Unroll end-to-end latency (PR 10 spans): done→step p99.
    Objective(name='unroll_e2e_p99_ms', metric='trace/e2e_ms',
              field='p99', comparison='<=', target=30000.0,
              severity='ticket',
              description='per-unroll done->step span p99 (ms)'),
    # Env plane (PR 5/7 utilization split): the floor detects a DEAD
    # env plane (nothing produced all window), not a backpressured
    # one — a pipeline that consumes at all keeps the ratio above it.
    Objective(name='env_plane_utilization',
              metric='driver/env_plane_utilization',
              comparison='>=', target=0.001, severity='ticket',
              description='producers not parked on backpressure'),
    # Actor plane (PR 6): the quorum fraction the fleet feeds with.
    Objective(name='fleet_healthy_fraction',
              metric='driver/fleet_healthy_fraction',
              comparison='>=', target=0.25, severity='page',
              description='healthy actor slots / fleet size'),
    # Throughput floor vs the per-host baseline file (the north-star
    # number, judged against what THIS host has shown it can do).
    Objective(name='fps_floor', metric='driver/env_frames',
              kind='rate', comparison='>=', target=0.5,
              baseline='fps', severity='ticket',
              description='env frames/sec >= 0.5x per-host baseline'),
    # Data-plane integrity (PR 9): any movement is an incident.
    Objective(name='wire_crc_rejected_zero',
              metric='ingest/wire_crc_rejected',
              kind='rate', comparison='==', target=0.0,
              severity='page',
              description='unroll frames refused for CRC mismatch'),
    Objective(name='sdc_mismatch_zero', metric='health/sdc_mismatches',
              kind='rate', comparison='==', target=0.0,
              severity='page',
              description='per-replica param fingerprint disagreements'),
    Objective(name='ckpt_digest_fallbacks_zero',
              metric='checkpoint/digest_fallbacks',
              kind='rate', comparison='==', target=0.0,
              severity='ticket',
              description='restore rungs refused for content digests'),
    # Transport plane (PR 8): quarantines/reaps/stale epochs flat at
    # zero on a healthy fleet.
    Objective(name='ingest_quarantine_zero', metric='ingest/quarantined',
              kind='rate', comparison='==', target=0.0,
              severity='ticket',
              description='connections dropped for unparseable frames'),
    Objective(name='conns_reaped_zero', metric='ingest/conns_reaped',
              kind='rate', comparison='==', target=0.0,
              severity='ticket',
              description='idle/half-open connections reaped'),
    Objective(name='stale_epoch_zero',
              metric='ingest/stale_epoch_rejected',
              kind='rate', comparison='==', target=0.0,
              severity='ticket',
              description='unrolls refused from a dead incarnation'),
    # Learner failure domain (PR 2): a rollback is the ladder working,
    # and still an incident someone should read.
    Objective(name='rollbacks_zero', metric='health/rollbacks',
              kind='rate', comparison='==', target=0.0,
              severity='ticket',
              description='automatic checkpoint rollbacks'),
    # Plane-balance leading indicator (round 15, controller.py): the
    # learner mostly parked on the feed = the env plane is the bound —
    # the controller's raise-replay_k trigger (IMPACT,
    # arXiv 1912.00167). Advisory: env-bound is a CAPACITY shape, not
    # an incident, so burning this must never fail a verdict.
    Objective(name='learner_plane_utilization',
              metric='driver/learner_plane_utilization',
              comparison='>=', target=0.05, severity='info',
              description='learner not starved by the env plane'),
    # Filler-aware variant (round 16, the hybrid filler /
    # --runtime=anakin): with the filler ON — or under the fused
    # anakin runtime — the learner plane is lifted to ~1.0 BY
    # CONSTRUCTION (idle feed slices run Anakin self-play), so this
    # stricter floor burning on such a run means the filler itself is
    # failing to fill. On a plain env-bound fleet run it burns
    # benignly (info can never fail a verdict) — that burn IS the
    # capacity-headroom signal the filler knob exists for. Filler
    # frames must NOT mask a dead env plane: env_plane_utilization
    # above stays the dead-plane signal either way
    # (config.validate_runtime cross-links the knobs).
    Objective(name='learner_plane_utilization_filler',
              metric='driver/learner_plane_utilization',
              comparison='>=', target=0.9, severity='info',
              description='hybrid filler keeps the learner plane '
                          '~fully busy'),
    # Transport-pressure leading indicator (round 15, controller.py):
    # ack service time is the end-to-end backpressure remote pumps
    # feel — the controller's stretch-publish-cadence trigger.
    Objective(name='ingest_ack_p99_ms', metric='ingest/ack_ms',
              field='p99', comparison='<=', target=5000.0,
              severity='info',
              description='ingest ack service time p99 (ms)'),
    # Telemetry self-health (PR 10 satellites): advisory only.
    Objective(name='dropped_writes_zero',
              metric='observability/dropped_writes',
              kind='rate', comparison='==', target=0.0,
              severity='info',
              description='JSONL writes dropped after close'),
    Objective(name='trace_drops_zero', metric='trace/dropped_records',
              kind='rate', comparison='==', target=0.0,
              severity='info',
              description='tracer FIFO overflows'),
    # Serving plane (round 21, multi-tenant serving): end-to-end
    # service latency of the shared inference step — every decoupled-
    # serving client (local C++ batcher callers AND v10 routed
    # cross-host batches) lands in this histogram. Burning past the
    # target is overload the admission actuator can shed (the routed
    # chaos storm asserts this objective stays green through a
    # replica kill).
    Objective(name='serving_latency_p99_ms', metric='serving/latency_ms',
              field='p99', comparison='<=', target=30000.0,
              severity='ticket',
              description='inference serve latency p99 (ms), local '
                          'and routed'),
    # Population plane (round 22, driver.train_population): the WORST
    # suite's best member return — a population whose laggard suite
    # never crosses zero is spending its frame budget on one task.
    # The gauge only exists inside a PBT run (registered after the
    # first scoring round); every other run evaluates no_data, which
    # never violates. Advisory: return scales are task-relative, so a
    # default floor can only be the "learning at all" zero line.
    Objective(name='per_task_return_floor',
              metric='population/task_return_min',
              comparison='>=', target=0.0, severity='info',
              description='worst suite best-member return >= 0'),
)


def load_objectives(spec_path: str = '',
                    fast_window_secs: float = 30.0,
                    slow_window_secs: float = 300.0
                    ) -> List[Objective]:
  """The objective set: `spec_path` (a JSON list of Objective field
  dicts) when given, else the shipped defaults — either way with the
  configured windows filled in wherever an entry didn't pin its own.
  Raises on an unreadable/invalid spec (a typo'd objective must fail
  the run at spin-up, not silently judge nothing)."""
  if spec_path:
    with open(spec_path) as f:
      raw = json.load(f)
    if not isinstance(raw, list) or not raw:
      raise ValueError(f'SLO spec {spec_path!r} must be a non-empty '
                       'JSON list of objective dicts')
    objectives = []
    for entry in raw:
      try:
        objectives.append(Objective(**entry))
      except TypeError as e:
        raise ValueError(f'SLO spec {spec_path!r}: bad objective '
                         f'entry {entry!r}: {e}') from e
  else:
    objectives = list(DEFAULT_OBJECTIVES)
  seen = set()
  resolved = []
  for o in objectives:
    o.validate()
    if o.name in seen:
      raise ValueError(f'duplicate SLO objective name {o.name!r}')
    seen.add(o.name)
    resolved.append(dataclasses.replace(
        o,
        fast_window_secs=(o.fast_window_secs
                          if o.fast_window_secs is not None
                          else fast_window_secs),
        slow_window_secs=(o.slow_window_secs
                          if o.slow_window_secs is not None
                          else slow_window_secs)))
  return resolved


# --------------------------------------------------------------------
# Per-host fps baseline file.
# --------------------------------------------------------------------


def load_baseline(path: str, host: Optional[str] = None) -> Dict:
  """The per-host baseline entry ({'fps': ...}) from a JSON file
  keyed by hostname. An ABSENT file (or entry) is {} — a host that
  never recorded a baseline evaluates its baseline-relative
  objectives as NO_BASELINE, never as a violation. A PRESENT but
  unreadable/corrupt file raises: the operator set a floor and a
  typo must not silently disarm it (the --slo_spec fail-fast rule)."""
  if not path:
    return {}
  host = host or socket.gethostname()
  try:
    with open(path) as f:
      table = json.load(f)
  except FileNotFoundError:
    return {}
  except (OSError, ValueError) as e:
    raise ValueError(
        f'SLO fps baseline file {path!r} exists but is unreadable '
        f'({e}) — fix or remove it; a corrupt baseline must not '
        'silently disarm the fps_floor objective') from e
  entry = table.get(host)
  return dict(entry) if isinstance(entry, dict) else {}


def update_baseline(path: str, values: Dict,
                    host: Optional[str] = None) -> str:
  """Merge `values` (e.g. {'fps': measured}) into the per-host entry
  (atomic tmp+rename). scripts/slo_report.py --update-fps-baseline
  uses this to record a known-good run as the floor future runs are
  judged against."""
  host = host or socket.gethostname()
  try:
    with open(path) as f:
      table = json.load(f)
  except (OSError, ValueError):
    table = {}
  entry = table.setdefault(host, {})
  entry.update(values)
  entry['wall_time'] = round(time.time(), 3)
  tmp = path + '.tmp'
  with open(tmp, 'w') as f:
    json.dump(table, f, indent=2, sort_keys=True)
  os.replace(tmp, path)
  return path


# --------------------------------------------------------------------
# Evaluation.
# --------------------------------------------------------------------


def _metric_value(snapshot: Dict, objective: Objective):
  """The judged scalar from one registry snapshot, or None when the
  metric (or its histogram field) is absent/NaN."""
  raw = snapshot.get(objective.metric)
  if raw is None:
    return None
  if isinstance(raw, dict):
    raw = raw.get(objective.field or 'p99')
  if raw is None:
    return None
  try:
    value = float(raw)
  except (TypeError, ValueError):
    return None
  if math.isnan(value):
    return None
  return value


class SloEvaluator:
  """Windowed burn-rate evaluation of a set of objectives against a
  history of registry snapshots.

  `observe(snapshot, now)` appends one sample and re-judges every
  objective; the per-objective result dicts carry
  {state, value, target, margin, burns, ...}. Burn semantics:

  - value objectives: burning when the fast window holds >=
    `min_samples` valid samples, ALL of them violate, and >= half the
    slow-window samples violate (multi-window: a single bad sample
    cannot page; a sustained burn cannot hide).
  - rate objectives: the counter's movement over each window — the
    per-second rate for <=/>= comparisons, the raw delta for '=='
    (== 0 means "this counter must not move"). Monotone counters make
    the slow window confirmation automatic.

  `burns` counts burn EPISODES (entering the burning state), so the
  verdict distinguishes "violated once, recovered" from "never
  violated"; an objective with burns > 0 fails the verdict at
  ticket/page severity.
  """

  def __init__(self, objectives: List[Objective],
               min_samples: int = 3,
               baseline: Optional[Dict] = None):
    self._objectives = list(objectives)
    self._min_samples = max(int(min_samples), 2)
    self._baseline = dict(baseline or {})
    horizon = max([o.slow_window_secs or 300.0
                   for o in self._objectives] or [300.0])
    self._horizon = horizon * 1.25
    self._samples = collections.deque()   # (t, snapshot)
    self._state: Dict[str, Dict] = {
        o.name: {'name': o.name, 'metric': o.metric,
                 'comparison': o.comparison, 'kind': o.kind,
                 'severity': o.severity, 'state': NO_DATA,
                 'value': None, 'target': o.target, 'margin': None,
                 'burns': 0, 'last_burn_wall_time': None,
                 'description': o.description}
        for o in self._objectives}

  @property
  def objectives(self) -> List[Objective]:
    return list(self._objectives)

  def _resolved_target(self, o: Objective) -> Optional[float]:
    if o.baseline is None:
      return o.target
    base = self._baseline.get(o.baseline)
    if base is None:
      return None
    return float(base) * o.target

  def _window(self, now: float, secs: float):
    cutoff = now - secs
    return [(t, snap) for t, snap in self._samples if t >= cutoff]

  def _judge_value(self, o: Objective, now: float, target: float):
    holds = _COMPARATORS[o.comparison]
    fast = [(t, v) for t, snap in self._window(now, o.fast_window_secs)
            if (v := _metric_value(snap, o)) is not None]
    if not fast:
      return NO_DATA, None
    value = fast[-1][1]
    if len(fast) < self._min_samples:
      return OK, value
    if any(holds(v, target) for _, v in fast):
      # At least one fast-window sample is healthy: not burning.
      return OK, value
    slow = [v for t, snap in self._window(now, o.slow_window_secs)
            if (v := _metric_value(snap, o)) is not None]
    bad = sum(1 for v in slow if not holds(v, target))
    if slow and bad >= max(len(slow) / 2.0, 1):
      return BURNING, value
    return OK, value

  def _rate_over(self, o: Objective, now: float, secs: float):
    """(window delta, per-second rate) of a counter metric over the
    trailing `secs`, or (None, None) below two valid samples."""
    samples = [(t, v) for t, snap in self._window(now, secs)
               if (v := _metric_value(snap, o)) is not None]
    if len(samples) < 2:
      return None, None
    (t0, v0), (t1, v1) = samples[0], samples[-1]
    dt = t1 - t0
    if dt <= 0:
      return None, None
    return v1 - v0, (v1 - v0) / dt

  def _judge_rate(self, o: Objective, now: float, target: float):
    delta, rate = self._rate_over(o, now, o.fast_window_secs)
    if delta is None:
      return NO_DATA, None
    if o.comparison == '==':
      # "rate == 0": the counter must not move inside the fast window.
      # Monotone counters need no slow-window confirmation — a
      # fast-window bump IS a slow-window bump.
      return (OK if delta == target else BURNING), delta
    if _COMPARATORS[o.comparison](rate, target):
      return OK, rate
    # Multi-window confirmation for <=/>= rate objectives (the fps
    # floor shape): one fast-window stall — a checkpoint save, a
    # transient ingest hiccup — must not fail the run; the SLOW
    # window's rate must agree the bound is broken.
    _, slow_rate = self._rate_over(o, now, o.slow_window_secs)
    if slow_rate is None or _COMPARATORS[o.comparison](slow_rate,
                                                      target):
      return OK, rate
    return BURNING, rate

  def observe(self, snapshot: Dict,
              now: Optional[float] = None) -> List[str]:
    """Append one snapshot; re-judge everything. Returns the names of
    objectives that ENTERED the burning state on this observation."""
    now = time.time() if now is None else float(now)
    self._samples.append((now, snapshot))
    while self._samples and self._samples[0][0] < now - self._horizon:
      self._samples.popleft()
    newly = []
    for o in self._objectives:
      entry = self._state[o.name]
      target = self._resolved_target(o)
      if target is None:
        entry.update(state=NO_BASELINE, value=None, margin=None)
        continue
      entry['target'] = target
      if o.kind == 'rate':
        state, value = self._judge_rate(o, now, target)
      else:
        state, value = self._judge_value(o, now, target)
      margin = None
      if value is not None:
        # Signed headroom: positive = inside the objective.
        if o.comparison == '<=':
          margin = target - value
        elif o.comparison == '>=':
          margin = value - target
        else:
          margin = -abs(value - target)
      was_burning = entry['state'] == BURNING
      entry.update(state=state, value=value, margin=margin)
      if state == BURNING and not was_burning:
        entry['burns'] += 1
        entry['last_burn_wall_time'] = round(now, 3)
        newly.append(o.name)
    return newly

  def burning(self) -> List[str]:
    return [n for n, e in self._state.items()
            if e['state'] == BURNING]

  def states(self) -> Dict[str, Dict]:
    """Deep-copied per-objective judged state ({name: {state, value,
    target, margin, severity, burns, ...}}). Each entry's fields were
    written by ONE `entry.update(...)` call, so a copy is internally
    consistent; callers needing consistency ACROSS objectives must
    hold the owning engine's lock (SloEngine.control_snapshot does)."""
    return {n: dict(e) for n, e in self._state.items()}

  def verdict(self) -> Dict:
    """The per-run verdict: overall pass/fail + every objective's
    final state and burn count. `pass` fails on any ticket/page
    objective that EVER burned; info objectives are advisory."""
    violations = sorted(
        n for n, e in self._state.items()
        if e['burns'] > 0 and e['severity'] in ('ticket', 'page'))
    return {
        'pass': not violations,
        'violations': violations,
        'wall_time': round(time.time(), 3),
        'objectives': {n: dict(e) for n, e in self._state.items()},
    }


# --------------------------------------------------------------------
# The engine: thread + emission + triggered deep diagnostics.
# --------------------------------------------------------------------


class SloEngine:
  """The driver-resident judge: snapshots the registry on a cadence
  (its own thread, PLUS `observe()` calls from the driver's summary
  block so detection is step-synchronous when summaries are frequent),
  emits structured violations into summaries.jsonl + incidents.jsonl
  (+ health.note_external — the external-incident ledger carries SLO
  burns into drain manifests and halt bundles), and on the first
  severity-page burn captures the run's own explanation into
  `<logdir>/diagnostics/`:

    slo_flight_<objective>.json   the flight-recorder dump
    slo_trace_<objective>.json    trace_report hop-delta slice over
                                  the violation window
    slo_profile_<objective>/      a bounded jax.profiler capture of
                                  the next K learner steps (requested
                                  via `take_profile_request` — the
                                  driver loop owns the profiler)

  One capture per objective per run; `finalize()` writes
  SLO_VERDICT.json (atomic) and returns the verdict."""

  # Lock discipline (round 18, guarded-by lint): the evaluator state,
  # the capture rate-limit table, and both work queues mutate only
  # under _lock — observe() runs from TWO threads (engine tick + the
  # driver's summary block), so a bare deque append here is exactly
  # the torn-coordination shape the round-15 snapshot-consistency
  # test exists for.
  _captures: guarded_by('_lock')
  _profile_queue: guarded_by('_lock')
  _capture_queue: guarded_by('_lock')

  def __init__(self, objectives: List[Objective], logdir: str,
               registry: Optional[telemetry.MetricsRegistry] = None,
               writer=None, incidents=None, flight=None, health=None,
               capture: bool = True, interval_secs: float = 5.0,
               baseline: Optional[Dict] = None,
               min_samples: int = 3,
               trace_slice_fn: Optional[Callable] = None):
    self._evaluator = SloEvaluator(objectives,
                                   min_samples=min_samples,
                                   baseline=baseline)
    self._logdir = logdir
    self._registry = registry or telemetry.registry()
    self._writer = writer
    self._incidents = incidents
    self._flight = flight
    self._health = health
    self._capture = bool(capture)
    self._interval = max(float(interval_secs), 0.25)
    self._trace_slice_fn = trace_slice_fn or _trace_slice
    self._lock = make_lock('slo.SloEngine._lock')
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None
    self._captures: Dict[str, Dict] = {}
    self._profile_queue: collections.deque = collections.deque()
    # Captures pending their artifact writes: (name, capture, state)
    # queued by whoever's observe() detects the burn, DRAINED on the
    # engine thread (flush_captures) — the driver's summary-block
    # observe must never pay the flight-dump + whole-trace-stream
    # slice inline on the training loop.
    self._capture_queue: collections.deque = collections.deque()
    # Registry view of the judge itself (unregistered at stop — the
    # fn-gauge closes over this per-run engine).
    self._m_violations = telemetry.counter('slo/violations')
    self._g_burning = telemetry.gauge(
        'slo/burning', fn=lambda: len(self._evaluator.burning()))

  # --- lifecycle ---

  def start(self):
    self.observe()  # t0 sample: rate objectives span the whole run
    self._thread = threading.Thread(target=self._loop,
                                    name='slo-engine', daemon=True)
    self._thread.start()

  def _loop(self):
    while not self._stop.wait(self._interval):
      try:
        self.observe()
      except Exception:  # pragma: no cover - must never kill the run
        import logging
        logging.getLogger('scalable_agent_tpu').exception(
            'SLO evaluator tick failed')
      self.flush_captures()

  def stop(self):
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None
    telemetry.registry().unregister(self._g_burning.name,
                                    self._g_burning)

  # --- evaluation + emission ---

  def observe(self, now: Optional[float] = None) -> List[str]:
    """One evaluation pass (thread-safe; the engine thread and the
    driver's summary block both call this). Returns newly-burning
    objective names.

    Only the evaluator-state mutation runs under the lock. The
    emission (incident/summary writes) happens after release, fully
    exception-guarded — a disk-full at the moment of a burn must not
    kill the thread that called observe (which may be the TRAINING
    loop's summary block). The heavy capture artifacts (flight dump +
    a trace_report pass over the whole traces.jsonl — seconds on a
    long run) are only QUEUED here; the engine thread (and finalize)
    drains them via flush_captures. The per-objective rate limit is
    enforced under the lock (the captures entry is reserved before
    release)."""
    snapshot = self._registry.snapshot()
    with self._lock:
      newly = self._evaluator.observe(snapshot, now=now)
      if not newly:
        return newly
      states = {name: dict(self._evaluator._state[name])
                for name in newly}
      for name in newly:
        if (self._capture and states[name]['severity'] == 'page'
            and name not in self._captures):
          capture: Dict = {
              'objective': name, 'wall_time': round(time.time(), 3),
              'flight': None, 'trace_slice': None, 'profile': None}
          self._captures[name] = capture
          self._capture_queue.append((name, capture, states[name]))
    try:
      step = int(snapshot.get('driver/update_steps') or 0)
      for name in newly:
        state = states[name]
        self._m_violations.inc()
        if self._incidents is not None:
          self._incidents.event(
              'slo_violation', step=step, objective=name,
              severity=state['severity'], metric=state['metric'],
              value=state['value'], target=state['target'],
              margin=state['margin'], burns=state['burns'])
        if self._health is not None:
          self._health.note_external(f'slo_{name}')
      if self._writer is not None:
        self._writer.scalar('slo_violations',
                            self._m_violations.value, step)
    except Exception:  # best-effort: judging survives a sick disk
      import logging
      logging.getLogger('scalable_agent_tpu').exception(
          'SLO violation emission failed')
    return newly

  # --- the control surface (round 15, controller.py) ---

  def burning(self) -> List[str]:
    """The currently-burning objective names, read under the engine
    lock (stable against a concurrent observe() — the controller
    thread's read API)."""
    with self._lock:
      return self._evaluator.burning()

  def control_snapshot(self) -> Dict[str, Dict]:
    """A locked, self-consistent copy of every objective's judged
    state ({name: {state, value, target, margin, severity, burns,
    ...}}) — the round-15 controller's control input. The lock
    guarantees the copy describes ONE evaluation pass: two objectives
    over the same metric can never disagree about its value inside a
    single snapshot (regression-pinned by
    tests/test_slo.py::test_control_snapshot_consistent_mid_evaluation).
    """
    with self._lock:
      return self._evaluator.states()

  def flush_captures(self):
    """Write queued capture artifacts (engine thread per tick;
    finalize as the backstop for burns detected after the last tick).
    Each capture is independently best-effort."""
    while True:
      # Round 18 (guarded-by lint): the queue is appended to under
      # the lock by whichever thread's observe() detects the burn —
      # the drain must pop under the same lock, not rely on deque
      # GIL-atomicity.
      with self._lock:
        if not self._capture_queue:
          return
        name, capture, state = self._capture_queue.popleft()
      try:
        self._write_capture_artifacts(name, capture, state)
      except Exception:  # the contract: never take down the run
        import logging
        logging.getLogger('scalable_agent_tpu').exception(
            'SLO capture artifacts for %r failed', name)

  # --- triggered deep diagnostics ---

  def _write_capture_artifacts(self, name: str, capture: Dict,
                               state: Dict):
    """First page-severity burn of `name` (entry already reserved
    under the lock): dump the flight recorder, slice the trace stream
    over the violation window, and queue a profiler capture for the
    driver loop. Runs on the ENGINE thread (flush_captures), outside
    the lock; every artifact is independently best-effort — a sick
    disk at page time must cost artifacts, never the run (and never
    the profiler request, which needs no disk until jax writes)."""
    out_dir = os.path.join(self._logdir, 'diagnostics')
    try:
      os.makedirs(out_dir, exist_ok=True)
    except OSError:
      out_dir = None
    if out_dir is not None and self._flight is not None:
      try:
        capture['flight'] = self._flight.write(
            os.path.join(out_dir, f'slo_flight_{name}.json'))
      except Exception:
        pass
    if out_dir is not None:
      try:
        objective = next(o for o in self._evaluator.objectives
                         if o.name == name)
        window_secs = objective.slow_window_secs or 300.0
        slice_path = os.path.join(out_dir, f'slo_trace_{name}.json')
        if self._trace_slice_fn(self._logdir, window_secs, slice_path,
                                state):
          capture['trace_slice'] = slice_path
      except Exception:
        pass
    # Round 18 (guarded-by lint): the driver loop pops this queue
    # under the lock; the engine-thread append holds it too.
    with self._lock:
      self._profile_queue.append(name)
    if self._incidents is not None:
      try:
        self._incidents.event('slo_capture', objective=name,
                              flight=capture['flight'],
                              trace_slice=capture['trace_slice'])
      except Exception:
        pass

  def take_profile_request(self) -> Optional[str]:
    """Pop the next queued profiler capture (driver loop; None when
    idle). The driver owns jax.profiler — it starts a bounded trace
    into diagnostics/slo_profile_<name>/ and reports back via
    `note_profile`."""
    with self._lock:
      return self._profile_queue.popleft() if self._profile_queue \
          else None

  def note_profile(self, name: str, path: Optional[str]):
    with self._lock:
      if name in self._captures:
        self._captures[name]['profile'] = path

  # --- the verdict ---

  def verdict(self, extra: Optional[Dict] = None) -> Dict:
    with self._lock:
      out = self._evaluator.verdict()
      out['captures'] = {n: dict(c) for n, c in self._captures.items()}
    if extra:
      out.update(extra)
    return out

  def finalize(self, path: Optional[str] = None,
               extra: Optional[Dict] = None) -> Dict:
    """Final observation + atomic SLO_VERDICT.json write. Returns the
    verdict dict (chaos/soak/slo_report read the file). Drains any
    capture still queued (a burn detected after the engine thread's
    last tick must not lose its artifacts)."""
    try:
      self.observe()
    except Exception:
      pass
    self.flush_captures()
    verdict = self.verdict(extra=extra)
    if path is None:
      path = os.path.join(self._logdir, 'SLO_VERDICT.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(verdict, f, indent=2, sort_keys=True, default=str)
    os.replace(tmp, path)
    return verdict


def _trace_slice(logdir: str, window_secs: float, out_path: str,
                 state: Dict) -> bool:
  """The violation-window hop-delta slice: trace_report.summarize over
  the records inside [burn - slow_window, now], written as JSON next
  to the other capture artifacts. Lazy script import (operator installs
  without the scripts/ tree skip the slice, never crash)."""
  try:
    from scripts import trace_report
  except ImportError:
    return False
  now = time.time()
  records = [r for r in trace_report.load_traces(logdir)
             if r.get('t') is None or r['t'] >= now - window_secs]
  summary = trace_report.summarize(records)
  summary['slo_objective'] = dict(state)
  summary['window_secs'] = window_secs
  tmp = out_path + '.tmp'
  with open(tmp, 'w') as f:
    json.dump(summary, f, indent=2, default=str)
  os.replace(tmp, out_path)
  return True


def read_verdict(logdir: str) -> Optional[Dict]:
  """The run's SLO_VERDICT.json, or None (consumed by chaos/soak/
  slo_report)."""
  try:
    with open(os.path.join(logdir, 'SLO_VERDICT.json')) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None
