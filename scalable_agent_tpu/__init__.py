"""scalable_agent_tpu — TPU-native IMPALA framework (JAX/XLA/Pallas).

A ground-up re-design of the capabilities of the reference IMPALA
implementation (`RoganInglis/scalable_agent`, a fork of
deepmind/scalable_agent, arXiv:1802.01561) for TPU:

- `vtrace`            — pure-JAX V-trace (scan + associative-scan forms)
- `models`            — agent networks (shallow CNN / deep ResNet torsos,
                        LSTM core with done-reset, instruction encoder)
- `losses`            — IMPALA losses (policy gradient, baseline, entropy)
- `learner`           — jitted train step, optimizer, frame accounting
- `envs`              — environment adapters behind a process-safe spec
                        protocol (fake env for CI, DMLab/ALE import-guarded)
- `runtime`           — host runtime: process-hosted envs, trajectory ring
                        buffer, C++ dynamic batcher, actors, checkpointing
- `parallel`          — mesh construction and sharded (pjit) training
- `dmlab30`           — DMLab-30 task table + human-normalized scoring
"""

from scalable_agent_tpu import vtrace  # noqa: F401
from scalable_agent_tpu.config import Config  # noqa: F401
from scalable_agent_tpu.structs import (  # noqa: F401
    ActorOutput, AgentOutput, StepOutput, StepOutputInfo)

__version__ = '0.1.0'


def __getattr__(name):
  """Lazy top-level API (heavy deps — flax/orbax — load on demand):
  `scalable_agent_tpu.ImpalaAgent`, `.driver`, `.learner`, etc."""
  import importlib
  if name in ('driver', 'learner', 'losses', 'popart', 'unreal',
              'checkpoint', 'observability', 'models', 'envs',
              'runtime', 'parallel'):
    return importlib.import_module(f'scalable_agent_tpu.{name}')
  if name == 'ImpalaAgent':
    from scalable_agent_tpu.models import ImpalaAgent
    return ImpalaAgent
  raise AttributeError(name)
