"""Experiment configuration.

Flag *names* mirror the reference (experiment.py ≈L30–75) so an operator
of the reference finds the same knobs; defaults are the paper's tuned
DMLab values. A dataclass + absl-flags overlay replaces TF1 app flags
(SURVEY §5.6).
"""

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class Config:
  # Experiment / run control.
  logdir: str = '/tmp/agent'
  mode: str = 'train'                     # train | test
  test_num_episodes: int = 10

  # Distributed topology (reference: --job_name/--task over gRPC;
  # here: jax.distributed process topology + host actor fleets).
  task: int = -1
  job_name: str = 'learner'
  num_actors: int = 4
  # Multi-process spin-up (round 17): driver.train joins the
  # jax.distributed runtime itself when a coordinator is named —
  # 'host:port' of process 0 (the reference's learner-address role,
  # minus the parameter server). Empty = single-host, or the caller
  # already initialized (the launcher / test harness path); both are
  # no-ops here. num_processes is the total host-process count;
  # process_id is this process's index (-1 = defer to max(task, 0),
  # the reference's --task spelling).
  coordinator_address: str = ''
  num_processes: int = 1
  process_id: int = -1

  # Training.
  total_environment_frames: int = int(1e9)
  batch_size: int = 2
  unroll_length: int = 100
  num_action_repeats: int = 4
  seed: int = 1

  # Loss.
  entropy_cost: float = 0.00025
  baseline_cost: float = 0.5
  discounting: float = 0.99
  reward_clipping: str = 'abs_one'        # abs_one | soft_asymmetric | none

  # Environment.
  dataset_path: str = ''
  level_cache_dir: str = ''               # DMLab compiled-map cache
                                          # override ('' = adapter
                                          # default)
  level_name: str = 'explore_goal_locations_small'
  width: int = 96
  height: int = 72

  # Optimizer (RMSProp, poly-decay to 0 over total frames).
  learning_rate: float = 0.00048
  decay: float = 0.99
  momentum: float = 0.0
  epsilon: float = 0.1

  # TPU-build additions (not in the reference).
  env_backend: str = 'dmlab'              # dmlab | atari | fake |
                                          # bandit | cue_memory
  num_actions: Optional[int] = None       # backend default when None
  sticky_action_prob: float = 0.0         # Atari: per-frame previous-
                                          # action repeat prob (0.25 =
                                          # Machado et al. eval
                                          # protocol; 0 = reference-era
                                          # deterministic)
  episode_length: int = 100               # fake/bandit only (cue_memory
                                          # is fixed two-step episodes)
  use_py_process: bool = True             # host each env in its own process
  publish_params_every: int = 1           # actor weight-snapshot cadence
  model_parallelism: int = 1              # TP width of the mesh
  # How TP matmuls execute (round 17): 'auto' = true sharded compute
  # on TPU/GPU, the 'gathered' workaround on CPU (this jaxlib's
  # partitioner mis-computes DIFFERENTIATED programs over model-
  # sharded leaves — params stay TP-sharded at rest, each step runs
  # gather -> replicated compute -> scatter; parity-gated in
  # tests/test_parallel.py and the tp4 multihost child).
  # 'sharded' | 'gathered' force either path.
  tp_compute: str = 'auto'
  # Which partition-rule set the sharding registry resolves from
  # (round 19, parallel/sharding.py — the ONE source of sharding
  # truth). 'auto' = 'megatron' when model_parallelism > 1 (TP cuts on
  # Dense/LSTM/Conv output features), 'replicated' (pure DP) otherwise
  # — i.e. defaults are unchanged. Naming a set explicitly pins it
  # regardless of the mesh shape.
  sharding_rules: str = 'auto'
  torso: str = 'deep'                     # deep | deep_fast | shallow
  scan_unroll: int = 10                   # LSTM time-scan unroll factor
                                          # (v5e sweep at T=100, B=32:
                                          # 1→40.8ms 5→40.5 10→39.3
                                          # 25→39.1; 10 balances the
                                          # win against compile time)
  # Language/instruction channel. None = auto by task: ON for
  # multi-task dmlab30 and language_*/psychlab_* levels, OFF otherwise
  # — the encoder costs ~6% step time (docs/PERF.md) and single-task
  # levels emit constant/empty instructions. The reference always runs
  # its language net; set True to match it exactly. MIGRATION: the
  # encoder's params are part of the checkpoint structure — resuming a
  # run trained when the default was True (pre-auto) on a non-language
  # level needs an explicit --use_instruction=true.
  use_instruction: Optional[bool] = None
  compute_dtype: str = 'float32'          # float32 | bfloat16
  use_associative_scan: bool = False      # parallel V-trace recursion
  use_pallas_vtrace: bool = False         # fused Pallas V-trace kernel
  use_popart: bool = False                # PopArt value normalization
  popart_beta: float = 3e-4               # PopArt stats EMA step size
  pixel_control_cost: float = 0.0         # >0 enables UNREAL aux task
  pixel_control_discount: float = 0.9
  pixel_control_cell_size: int = 4
  # --- Pixel-control fast path (round 6, docs/PERF.md itemization).
  # Three candidate levers, each parity-gated (tests/test_unreal.py)
  # and measured head-to-head by bench.py's `pc_levers` stage every
  # round. DEFAULTS STAY AT THE r5 REFERENCE FORMS: per the repo's
  # measured accept/reject discipline a default only flips on CHIP
  # numbers, and the round-6 build host had no chip — the CPU-backend
  # compile evidence (scripts/attribute_bytes.py) actually favors the
  # reference forms there (the CPU emitter single-pass-fuses the f32
  # reward reduce and materializes the d2s interleave), which is
  # precisely why these were not flipped blind. BENCH_rN's pc_levers
  # rows carry the on-chip call.
  #
  # Integer-domain pseudo-rewards: uint8 |Δ| + int32 cell sums, f32
  # only at the tiny [T, B, Hc, Wc] output — no full-resolution float
  # frame temporary can exist, where the f32 form leaves that choice
  # to the backend's fusion. Mathematically identical (exact integer
  # sum + one correctly-rounded scale); auto-falls back to the f32
  # form for non-uint8 frames.
  pixel_control_integer_rewards: bool = False
  # Q-head deconv implementation: 'deconv' (the r5 nn.ConvTranspose
  # reference form) | 'd2s' (the stride-2 4x4 deconv re-expressed as
  # one dense 2x2 conv + depth-to-space interleave — parameter-
  # identical, checkpoint-interchangeable, numerics-parity-gated; no
  # zero-stuffed fractionally-strided conv, at the price of an
  # explicit interleave relayout).
  pixel_control_head_impl: str = 'deconv'  # deconv | d2s
  # Cast the pixel-control Q-map to float32 at the head output (the
  # r5 form). False keeps it in the compute dtype until the loss's
  # gather/max — halves the [T+1·B, Hc, Wc, A] head-output bytes at
  # the cost of bf16-rounding the Q-values the loss sees
  # (numerics-AFFECTING: opt-in, measured by pc_levers).
  pixel_control_q_f32: bool = True
  grad_clip_norm: Optional[float] = None
  checkpoint_secs: int = 600              # reference save_checkpoint_secs
  # Learner steps between cross-host checkpoint-cadence broadcasts
  # (multi-host only; the broadcast is a cross-host sync, so it must
  # not run every step).
  checkpoint_check_every_steps: int = 20
  summary_secs: int = 30                  # reference save_summaries_secs
  # jax.profiler trace capture (SURVEY §5.1 — absent upstream):
  # non-empty dir ⇒ capture steps [profile_start, profile_start+steps).
  profile_dir: str = ''
  profile_start_step: int = 20            # past warmup/compile
  profile_num_steps: int = 5
  # Inference batching (reference dynamic_batching ≈2.9). min_batch 0
  # = AUTO: floor the merge at the fleet size so every call carries
  # the whole fleet (r5 sweep: min_batch=4/t60 measured 201.7 e2e fps
  # vs 146.4 at min_batch=1 — docs/PERF.md). Auto is the default
  # since round 6; evaluate() opts out (retiring levels would turn
  # the floor into one batcher-timeout per tail batch). Set an
  # explicit value to pin the floor by hand.
  inference_min_batch: int = 0
  inference_max_batch: int = 1024
  inference_timeout_ms: int = 100
  # --- Actor-plane inference overhaul (round 7; docs/INFERENCE.md).
  # Device-resident recurrent-state cache: each actor owns a slot in
  # an on-device [slots, hidden] arena; the jitted step gathers the
  # carry by slot id and scatters the new one in-graph (Podracer,
  # arXiv:2104.06272), so the per-step wire drops to (action, reward,
  # done, frame, instr, slot_id) and the LSTM carry crosses the host
  # boundary once per UNROLL (the learner's agent_state snapshot)
  # instead of twice per STEP. Numerics-identical to carry-passing
  # (golden parity gate, tests/test_runtime.py — done edges, respawn
  # slot reuse, sharded eval). DEFAULT OFF pending chip rows: per the
  # repo's measured accept/reject discipline a default only flips on
  # chip numbers, and bench.py's inference_plane stage measures
  # cache×depth head-to-head every round so BENCH_rN carries the
  # call (this build host's CPU rows are recorded in docs/PERF.md r7).
  inference_state_cache: bool = False
  # Dispatched-but-uncompleted merged inference batches allowed in
  # flight (the actor-plane mirror of staging_depth): 2 lets merged
  # batch k+1 assemble and land on device while batch k computes —
  # per-call latency absorbs the overlap, calls/s gains. 1 restores
  # the pre-round serialized assemble→dispatch→readback loop.
  inference_pipeline_depth: int = 2
  # State-arena capacity in slots (state-cache mode only). 0 = auto:
  # 2× the fleet size with a small floor — respawn headroom, because
  # a wedged actor's slot frees only when its orphaned thread
  # unwinds (runtime/fleet.py respawn contract).
  inference_state_slots: int = 0
  # --- Actor-plane overload & preemption hardening (round 9;
  # docs/ROBUSTNESS.md actor-plane rows). ---
  # Slot admission policy when the state arena is exhausted (the old
  # behavior — raise RuntimeError into the fleet — is gone):
  #   'block' (default): park on a priority waitlist until a slot
  #     frees or the admission deadline passes (then a clean
  #     SlotUnavailable that fleet respawn treats as pause-and-retry);
  #   'shed': same wait, but the deadline rejection is the intended
  #     overload response — counted in stats()['sheds'] and the
  #     driver's inference_sheds summary;
  #   'grow': never park — double the arena in place (one recompile
  #     per growth, counted as arena_grows).
  inference_admission: str = 'block'      # block | shed | grow
  # Deadline for parked slot acquisitions (block and shed policies).
  inference_admission_timeout_secs: float = 10.0
  # Ingest staleness window, in published param versions: a remote
  # unroll generated with params more than this many versions behind
  # the current snapshot is refused at admission (benign 'stale'
  # reply; the client refetches and keeps feeding). 0 = no window.
  max_unroll_staleness: int = 0
  # Consecutive respawns without one completed unroll before a fleet
  # slot gives up and quarantines (surfaced as slots_quarantined);
  # 0 = retry forever (pre-round-9 semantics, minus the hot loop —
  # respawns are always backoff-paced now).
  fleet_quarantine_after: int = 5
  # Preemption drain budget: on SIGTERM (or the preempt_signal fault)
  # the driver stops admissions, flushes in-flight unrolls through
  # the learner, takes a verified checkpoint and writes
  # resume_manifest.json — all within this many seconds.
  preempt_drain_timeout_secs: float = 30.0
  # Ring buffer capacity in batches (reference FIFOQueue capacity=1 +
  # StagingArea double buffer ⇒ bounded policy lag; keep it small).
  queue_capacity_batches: int = 1
  # Staged device batches in flight (BatchPrefetcher depth — the
  # StagingArea role). 2 double-buffers jax.device_put against the
  # (sharded) step so consecutive H2D transfers overlap each other
  # and the compute (BENCH_r05: h2d_ms 1430.5 dominated the fed-loop
  # gap). Each extra slot extends the policy-lag bound by one batch.
  staging_depth: int = 2
  # --- Learner feed staging mode (round 8; docs/PERF.md r8). ---
  # 'batch': host-stack B unrolls (`batch_unrolls`) then one burst
  #   device_put per step — the r5–r7 reference path (BENCH_r05
  #   itemized it at stack_ms 37.5 / h2d_ms 1430.5 per 67.5 MB batch).
  # 'unroll': each completed unroll is device_put the moment it leaves
  #   the TrajectoryBuffer — placed directly on the device owning its
  #   batch slot — and the [T+1, B] batch assembles ON DEVICE via a
  #   jitted donated dynamic_update_slice arena
  #   (runtime/ring_buffer.UnrollBatchStager), so the step-boundary
  #   burst becomes a trickle overlapped with the previous step's
  #   compute and the host stack leaves the hot path. Golden
  #   parity-gated vs the host-stack path (bit-identical batches);
  #   falls back to 'batch' with a warning on topologies the per-slot
  #   placement cannot serve (model-axis batch sharding, indivisible
  #   local batch — parallel/train_parallel.supports_unroll_staging).
  # DEFAULT STAYS 'batch' per the repo's measured accept/reject
  # discipline: bench.py's `learner_plane` stage measures both modes
  # × staging_depth head-to-head every round (exposed H2D ms/step,
  # stack_ms, step gap), so BENCH_r08's chip rows carry the flip call.
  staging_mode: str = 'batch'            # batch | unroll
  # --- Sample reuse (round 10; IMPACT, arXiv 1912.00167 —
  # docs/PERF.md r9). The e2e bench shows the actor/env plane bounding
  # throughput at ~150 fps while the compiled learner step runs ~300k
  # frames/s synthetic: V-trace consumes each frame exactly once, so
  # >99% of learner capacity idles. These knobs multiply learner
  # updates per env frame by re-serving staged batches and replaying
  # retained unrolls. ---
  # Loss surrogate: 'vtrace' is the reference IMPALA path (default);
  # 'impact' is the IMPACT clipped-target surrogate — a target-network
  # param copy held on device anchors both the V-trace corrections
  # (IS ratios pi_target/mu, clipped exactly like the reference's
  # rho-bar) and a PPO-style clip of the pi_theta/pi_target ratio, so
  # replayed/stale data cannot push an unbounded policy-gradient step.
  # Parity-gated: with replay_k=1, replay_ratio=0 and
  # target_update_interval=1 the impact path is bit-identical to the
  # vtrace path (tests/test_replay.py) — the surrogate only diverges
  # when reuse/staleness makes the anchor differ from the live params.
  surrogate: str = 'vtrace'               # vtrace | impact
  # PPO-style clip width of the impact surrogate's current/target
  # ratio (the paper's epsilon).
  impact_epsilon: float = 0.2
  # Learner steps between target-network refreshes (impact only; the
  # version-gated publish cadence applied to the on-device anchor —
  # the refresh is an in-graph select, no host round trip). 1 pins
  # the target to the live params (the parity-gate operating point).
  target_update_interval: int = 1
  # Times each staged device batch is served to the learner before
  # release (IMPACT's sample-reuse K). The staged arena is re-served
  # AS IS — no re-stage, no additional H2D traffic — so K updates ride
  # one transfer; episode stats/frame counters only count the first
  # serve. DEFAULT 1 (no reuse) per the measured accept/reject
  # discipline: bench.py's `replay` stage measures step_ms and
  # learner-updates/env-frame across replay_k x replay_ratio every
  # round, and the cue_memory return-vs-wallclock artifact carries
  # the flip call.
  replay_k: int = 1
  # Fraction of each batch's unroll slots drawn from the circular
  # replay tier instead of fresh production ([0, 1); 0 = off). Unlike
  # replay_k, replayed unrolls re-stage (one H2D per replayed unroll)
  # but decouple batch composition from the env plane's rate.
  replay_ratio: float = 0.0
  # Circular replay tier capacity in unrolls (0 = auto: 4x batch).
  # Oldest entries are overwritten IMPACT-style when full (counted as
  # evictions-by-age).
  replay_capacity_unrolls: int = 0
  # Replay staleness window, in PUBLISHED PARAM-VERSION deltas — the
  # SAME unit as --max_unroll_staleness (round 10 unified them; the
  # ingest knob gates admission, this one gates re-serving): a
  # retained unroll whose insert-time param version is more than this
  # many published versions behind the current one is evicted instead
  # of replayed (evictions-by-version). 0 = defer to
  # max_unroll_staleness (both windows then agree); both 0 = no bound.
  replay_max_staleness: int = 0
  # Remote actors (reference --job_name=actor gRPC topology, SURVEY
  # §3.4): learner listens on this port for actor-host connections
  # (0 = disabled); actor hosts point learner_address at it.
  remote_actor_port: int = 0
  # Interface the ingest server binds. The wire is pickle (arbitrary
  # code execution for anyone who can reach the port — same trust
  # model as the reference's unauthenticated TF gRPC runtime), so
  # exposure is OPT-IN: the default is loopback-only, and a real
  # multi-host topology must explicitly bind the cluster-internal
  # interface (or '0.0.0.0' inside a trusted network) — ADVICE r3.
  remote_actor_bind_host: str = '127.0.0.1'
  learner_address: str = ''
  # Min seconds between param snapshots published to remote hosts (a
  # publish is a full device_get; remote staleness ~ this value).
  remote_publish_secs: float = 2.0
  # Publish codec for served param snapshots: 'bf16' (default) casts
  # float32 leaves for the wire (the actor host upcasts back) —
  # exactly halves the dominant term of learner egress
  # (hosts x blob_bytes / remote_publish_secs) at a measured ~5 ms
  # cast cost vs zlib-1's 209 ms for a 0.926 ratio (BENCH_r05;
  # docs/TRANSPORT.md). Acting tolerates the ~3 decimal digits of
  # mantissa (inference already runs bfloat16 compute); training
  # state is never touched. 'f32' opts out and ships exact float32.
  publish_codec: str = 'bf16'
  # LEGACY spelling of the same knob (pre-round-6): '' defers to
  # publish_codec; 'bfloat16' forces the cast regardless of codec.
  remote_params_dtype: str = ''
  # Actor-host elasticity: on disconnect, keep retrying the learner
  # for this many seconds (surviving a learner restart-from-
  # checkpoint) instead of exiting. 0 = exit on disconnect.
  # DEFAULT FLIPPED round 11 (0.0 -> 180.0): the hard-crash restart
  # story (docs/RUNBOOK.md §8) needs the fleet to outlive a learner
  # kill -9 + restore + recompile by default — exiting on the first
  # disconnect turned every learner blip into a dead fleet. The
  # window must cover the learner restart budget (validate_transport
  # warns when it doesn't); envs stay alive and paused on buffer
  # backpressure for the duration.
  actor_reconnect_secs: float = 180.0
  # --- Transport-plane liveness (round 11; docs/TRANSPORT.md v6,
  # docs/ROBUSTNESS.md transport rows). ---
  # Application-level heartbeat interval for the ingest/param lanes:
  # a v6 client pings when its trajectory lane is idle this long (the
  # pong carries the current params version, so an idle fleet still
  # learns about publishes), and the server emits 'busy' keepalives
  # at this cadence while an ack is held back by buffer backpressure
  # (a slow learner stays tellable from a dead one). Negotiated per
  # connection at hello — a v5 peer gets neither. 0 = no heartbeats.
  remote_heartbeat_secs: float = 10.0
  # Idle/half-open connection reaping window: a connection (either
  # lane) that has received NO bytes for this long is reaped —
  # half-open peers (silent partition, killed host behind a live NAT
  # entry) used to pin their reader thread and its buffers forever.
  # With heartbeats on, a live-but-idle peer is never silent longer
  # than remote_heartbeat_secs, so the reap only fires on genuinely
  # dead/blackholed peers. Doubles as the client-side I/O deadline
  # (how long an actor waits on a silent learner before entering its
  # reconnect window) and the server's mid-frame recv/send stall
  # deadline. 0 = never reap, no deadlines (pre-round-11 semantics).
  remote_conn_idle_timeout_secs: float = 60.0
  # Validate/commit workers draining the ingest readers' handoff
  # queue (runtime/remote.py — validation, the backpressure put and
  # the ack run here, off the per-connection reader threads).
  # 0 = auto (min(4, cpu count)).
  ingest_workers: int = 0
  # --- Data-plane integrity (round 12; docs/TRANSPORT.md v7,
  # docs/ROBUSTNESS.md integrity rows). PRs 2/6/8 hardened against
  # components that FAIL; these knobs defend against data that is
  # WRONG — a bit-flipped unroll that still parses, a corrupted
  # publish, disk rot under LAST_GOOD, a chip whose replica copy
  # silently diverged. ---
  # Protocol v7 per-frame CRC32C trailers on both remote lanes,
  # negotiated per connection at hello (v5/v6 peers: off). A corrupt
  # unroll is refused BEFORE the buffer put ('corrupt' reply — the
  # client re-sends once, then quarantines itself); param blobs are
  # trailer-checked by the fetching client. Overhead is measured by
  # bench.py's transport stage (CRC on/off rows; <5% frames/s on the
  # build host, docs/PERF.md r10).
  wire_crc: bool = True
  # Verified checkpoint saves record a per-file content digest
  # (DIGEST_<step>.json + the LAST_GOOD manifest); the restore ladder
  # re-verifies before trusting a step, classifying mismatch as
  # corruption (fallback to the previous retained step) — extends the
  # PR 2 ladder from partial/structural damage to BIT ROT.
  ckpt_digests: bool = True
  # In-graph SDC sentinel: per-data-replica param fingerprints
  # (segmented uint32 sum of bit-cast leaves) cross-checked by the
  # one-step-delayed health readback; replica disagreement =
  # deterministic compute violated -> incident + the PR 2 rollback
  # ladder (counted as sdc_replica_mismatches, separate from
  # non-finite skips). Pure-DP meshes with >= 2 data replicas only;
  # a no-op elsewhere.
  sdc_check: bool = True
  # Multi-host SDC (round 17): all-gather the per-replica fingerprints
  # IN-GRAPH so the host readback touches only a fully-replicated
  # [replicas] array — the device_get of a P('data')-sharded array
  # across processes is illegal (non-addressable shards), which is
  # why the PR 9 gate kept the sentinel single-controller. False
  # restores the old gate (the sentinel silently stays off on
  # multi-process meshes — validate_distributed warns).
  sdc_allgather: bool = True
  # Replay-tier entries keep their insert-time content CRC and are
  # re-verified at every serve (reuse must not multiply host-memory
  # rot into K batches); mismatches evict (replay_evictions_crc).
  replay_crc: bool = True
  # --- Telemetry plane (round 13; docs/OBSERVABILITY.md). ---
  # Per-unroll trace spans: each unroll carries a compact trace
  # context (actor id, sequence, session epoch, behaviour params
  # version, hop timestamps) stamped at env-step completion and
  # completed through ingest → staging → serve → train step; the
  # learner emits traces.jsonl (one line per trained batch with the
  # policy-lag vector) and scripts/trace_report.py reconstructs
  # per-hop latency + the lag distribution. Negotiated on the wire
  # (protocol v8) — older peers simply don't stamp. Default ON: the
  # bench.py `telemetry` stage measured the overhead below run-to-run
  # noise (docs/PERF.md r11 records the accept call); False turns off
  # stamping, the tracer, and the traces.jsonl stream.
  telemetry_trace: bool = True
  # Flight-recorder depth: the most recent N trace records (batches /
  # publishes / installs) plus periodic metrics-registry snapshots
  # kept in memory and dumped with the health halt bundle and every
  # rollback incident — the "last N seconds of pipeline history"
  # an incident postmortem starts from.
  telemetry_flight_len: int = 512
  # --- SLO engine (round 14; slo.py, docs/OBSERVABILITY.md). The
  # sensor-to-verdict half of the control loop: declarative objectives
  # over the metrics registry, evaluated continuously on fast/slow
  # burn windows, with the per-run SLO_VERDICT.json go/no-go artifact
  # and triggered deep diagnostics on page-severity burns. Default ON:
  # the bench.py `slo` stage measured the evaluator tick sub-
  # millisecond, paid once per cadence interval off the hot loop
  # (docs/PERF.md r12 records the accept call); False removes the
  # thread, the verdict, and the captures entirely. ---
  slo_engine: bool = True
  # Objective set: '' = the shipped defaults (slo.DEFAULT_OBJECTIVES —
  # one per instrumented plane, the table in docs/OBSERVABILITY.md);
  # a path loads a JSON list of objective dicts instead. A spec naming
  # an unregistered metric is a spin-up error, not a silent no-op.
  slo_spec: str = ''
  # Default burn windows for objectives that don't pin their own:
  # multi-window burn-rate alerting — the fast window must be FULLY
  # violating and at least half the slow window too before an
  # objective burns (a blip must not page; a sustained burn must).
  slo_fast_window_secs: float = 30.0
  slo_slow_window_secs: float = 300.0
  # Evaluator cadence (its own thread; the driver's summary block
  # also evaluates, so detection is step-synchronous whenever
  # summaries are frequent). 0 = derive from summary_secs.
  slo_interval_secs: float = 0.0
  # Triggered deep diagnostics: on the FIRST burn of a severity=page
  # objective, dump the flight recorder + a trace_report slice over
  # the violation window into <logdir>/diagnostics/ and capture a
  # bounded jax.profiler trace of the next slo_capture_steps learner
  # steps (one capture per objective per run).
  slo_capture: bool = True
  slo_capture_steps: int = 5
  # Per-host fps baseline file (JSON {hostname: {'fps': value}}): the
  # fps_floor objective judges throughput against THIS host's
  # recorded capability ('' = no baseline — the objective reads
  # no_baseline, never a violation). scripts/slo_report.py
  # --update-fps-baseline records a known-good run into it.
  slo_fps_baseline: str = ''
  # --- Self-healing controller (round 15; controller.py,
  # docs/RUNBOOK.md §12). The verdict-to-actuation half of the
  # control loop: a controller thread maps the SLO engine's burning
  # set + margins to bounded actuator moves through a declarative
  # policy table. 'observe' (default) is the dry run — every move the
  # policy WOULD make is logged (CONTROLLER_LOG.json, applied:false)
  # and nothing is touched; 'act' applies them (replay_k, admission
  # mode, remote publish cadence, fleet size); 'off' removes the
  # thread and the log. The acceptance drill is
  # CHAOS_STORM=controller; cost is bench.py's `controller` stage. ---
  controller: str = 'observe'             # off | observe | act
  # Policy table: '' = controller.DEFAULT_RULES (the table in
  # docs/OBSERVABILITY.md); a path loads a JSON rule list. A rule
  # over an unknown actuator is a spin-up error.
  controller_policy: str = ''
  # Controller tick cadence (0 = derive from the SLO engine's
  # interval — the judge and the actuator loop then share a clock).
  controller_interval_secs: float = 0.0
  # Hard upper bound the replay_k actuator may escalate to (the
  # bounded-move guarantee; IMPACT's measured-safe reuse range).
  controller_replay_k_max: int = 4
  # Hard upper bound for the publish-cadence actuator, seconds.
  controller_publish_secs_max: float = 30.0
  # Quarantine probation (round 15): how long a quarantined fleet
  # slot (or a self-quarantined remote client) must cool down before
  # a rehabilitation attempt — one probe (re)spawn/unroll, then
  # re-quarantine on repeat failure. The controller's grow-fleet move
  # reclaims slots through this ladder (slots_rehabilitated).
  fleet_probation_secs: float = 30.0
  # Elastic pod membership (round 20): upper bound for the pod_size
  # actuator — the pod-level analogue of fleet_size. The learner does
  # not SPAWN hosts; the actuator publishes the desired host count to
  # <logdir>/POD_TARGET.json (atomic replace) and the cluster
  # supervisor (chaos.py's elastic storm in tests; an operator's
  # orchestration in production) reconciles actual hosts toward it.
  # 0 (default) = actuator not registered; membership accounting
  # (host_joined/host_left incidents, driver/remote_live_hosts) is
  # independent of this knob and always on for v9 peers.
  pod_max_hosts: int = 0
  # --- Runtime axis (round 16; docs/PARALLELISM.md, RUNBOOK §13).
  # 'fleet' is the production Sebulba pipeline (host envs → inference
  # → buffer → learner). 'anakin' fuses act+learn into ONE jitted
  # device step (Podracer arXiv:2104.06272) for jittable env backends
  # (JITTABLE_BACKENDS below) — the r4 bench measured it 4x the fed
  # fleet path on the CI tasks — under the SAME run lifecycle:
  # checkpoint ladder, health watchdog, metrics registry, SLO engine
  # + verdict, summaries/incidents JSONL (driver.train dispatches on
  # this axis; driver.train_anakin is the loop). ---
  runtime: str = 'fleet'                  # fleet | anakin
  # Hybrid filler fleets (fleet runtime only): whenever the
  # prefetcher has NO staged batch ready, the driver runs ONE bounded
  # Anakin self-play step on the learner chips instead of parking on
  # the feed — learner-plane utilization is lifted by construction in
  # env-bound regimes (the BENCH r9 shape: ~150 fps feed vs ~300k fps
  # learner capacity) while a staged batch is never delayed by more
  # than one filler step. Filler updates ride the IMPACT staleness
  # argument (arXiv 1912.00167 — validate_runtime cross-links
  # --surrogate); the frame budget, LR schedule, and fps meter stay
  # on the fleet's fresh-frame clock (filler work is accounted
  # separately: filler_updates/filler_frames summaries + the
  # driver/filler_updates registry counter). DEFAULT OFF per the
  # measured accept/reject discipline: bench.py's `anakin` stage
  # measures the hybrid row every round and docs/PERF.md r13 records
  # the call.
  anakin_filler: bool = False
  # Filler env core: '' = auto (env_backend itself when jittable,
  # else 'bandit' — which accepts the main task's action-space width).
  filler_backend: str = ''
  # Filler rollout shape (0 = auto: the fleet's batch_size, and
  # min(unroll_length, 16) — short slices keep the one-filler-step
  # yield bound tight).
  filler_batch_size: int = 0
  filler_unroll_length: int = 0
  # --- Learner failure domain (health.py, round 7). ---
  # Training-health watchdog: the train step skips non-finite updates
  # on device (params carry over unchanged) and the driver escalates
  # bad steps: skip-and-count → rollback to the last-known-good
  # checkpoint after `health_rollback_after` consecutive bad steps →
  # halt with a diagnostic bundle after `health_max_rollbacks`
  # rollbacks. False removes the in-graph guard and the host monitor
  # entirely (exact pre-round-7 step semantics).
  health_watchdog: bool = True
  # Host-side sentinel read cadence. The read is ONE-STEP DELAYED
  # (the stacked scalars of step N are fetched after step N+1 was
  # dispatched, so the device_get reads completed values instead of
  # syncing the dispatch pipeline); the device-side skip protects
  # params regardless of cadence — this only bounds rollback/halt
  # latency.
  health_check_every_steps: int = 1
  health_window: int = 64                 # retained recent checks
  health_min_window: int = 16             # samples before relative
                                          # detectors arm
  health_rollback_after: int = 5          # K consecutive bad steps
  health_max_rollbacks: int = 3           # then halt
  health_loss_explosion_factor: float = 100.0
  health_sigma_divergence_factor: float = 10.0
  # --- Invariant analyzer (round 18; analysis/, docs/STATIC_ANALYSIS
  # .md). Runtime lock-order detection: the threaded modules build
  # their locks through analysis.runtime.make_lock, which returns a
  # plain threading.Lock unless detection is armed — True arms it for
  # this run (driver.train arms BEFORE constructing components and
  # wires detections into incidents.jsonl as durable
  # lock_order_inversion events). Default OFF in production (the
  # graph bookkeeping is cheap but not free); tests and chaos storms
  # run armed (conftest.py sets LOCK_ORDER_CHECK=1; the fault storm
  # passes this flag and asserts zero cycles), so every storm doubles
  # as a race hunt. ---
  lock_order_check: bool = False
  # --- Multi-tenant serving plane (round 21; docs/INFERENCE.md). ---
  # Policy versions resident concurrently in the InferenceServer's
  # version table. 1 (default) reproduces the single-snapshot
  # behaviour exactly; >1 keeps older publishes resident (LRU
  # eviction of unpinned non-live entries) so a re-publish of a
  # resident version flips live WITHOUT a tree copy — the rollback/
  # A/B substrate.
  serving_resident_versions: int = 1
  # Optional byte budget over resident entries, MB (0 = count cap
  # only). Eviction honours pins and never evicts the live entry.
  serving_hbm_budget_mb: float = 0.0
  # Fraction of merged inference calls served by the A/B candidate
  # (the newest non-live resident, or set_ab's explicit version).
  # Granularity is the MERGED call — the C++ batcher folds many
  # actors into one call, so per-request assignment does not exist at
  # this layer.
  serving_ab_fraction: float = 0.0
  # Fraction of merged calls ALSO replayed against the shadow version
  # through a pure step (no key chain, no arena writes) and scored on
  # greedy action agreement vs live — the serving/shadow_divergence
  # gauge. Costs one extra forward per sampled call.
  serving_shadow_fraction: float = 0.0
  # Pre-compile serving steps per (batch bucket, params structure) at
  # publish/warmup time (the jit lower/compile AOT seam) so a version
  # flip or warmed bucket never pays first-call compile on the serve
  # path. DEFAULT OFF pending chip rows per the docs/PERF.md
  # accept/reject discipline (bench.py serving stage measures the
  # flip-blackout delta every round).
  serving_aot: bool = False
  # Comma-separated learner replica addresses ('host:port,...') an
  # actor host routes inference over (runtime/routing.py: health-
  # weighted round-robin, drain on leave, wire v10). '' = no routed
  # serving (params are fetched and inference stays host-local).
  serving_replicas: str = ''
  # --- Population engine (round 22; population.py,
  # docs/PARALLELISM.md §population). ---
  # In-graph auto-curriculum over the procgen level set (anakin
  # runtime AND the hybrid filler — both reach the core through
  # anakin.make_env_core): 'uniform' keeps the reference draw;
  # 'regret' EMAs positive value loss per level (the PLR proxy,
  # arXiv 2010.03934); 'td' EMAs |TD error|. Sampler and score update
  # both live INSIDE the fused device step — zero host round trips
  # per level decision. DEFAULT stays 'uniform' per the measured
  # accept/reject discipline: bench.py's population stage measures
  # the curriculum fps delta every round, and the regret default flip
  # is parked in ROADMAP housekeeping (b) pending chip rows.
  curriculum: str = 'uniform'             # uniform | regret | td
  curriculum_temperature: float = 1.0     # score-softmax temperature
  curriculum_eps: float = 0.1             # uniform mixing floor — every
                                          # level keeps >0 visitation
                                          # (the staleness escape hatch)
  curriculum_alpha: float = 0.3           # per-level score EMA step
  curriculum_decay: float = 0.995         # unvisited-level score decay
                                          # per fused step (staleness)
  # Procgen level-set size (envs/jittable.ProcgenCore) — the
  # curriculum's support. Both runtimes honor it (the host wrapper
  # receives it through the factory), so the anakin-vs-fleet parity
  # gate holds at any value.
  procgen_num_levels: int = 8
  # Procgen wall density: the Bernoulli rate of the per-level wall
  # mask. 0.25 (the prior hard-coded value) keeps most levels
  # solvable; raising it makes a growing fraction of layouts
  # goal-unreachable — the skewed-difficulty regime where curriculum
  # prioritization structurally beats uniform sampling (unlearnable
  # levels' regret scores decay to zero, so the sampler stops paying
  # for them; uniform keeps wasting 1/n of every batch per dead
  # level).
  procgen_wall_density: float = 0.25
  # Heterogeneous fleet composition (fleet runtime, round 22): '' =
  # single-task (unchanged). 'bandit:2,gridworld:1' runs ONE fleet
  # whose actors split across jittable suites by largest-remainder
  # weight apportionment (population.plan_actor_assignment — the
  # per-task frame budget IS the actor share), with per-task PopArt
  # statistics and per-task return curves riding the existing
  # level-id machinery. All tasks share the model's frame shape
  # (config.height x width); obs-spec FAMILY bucketing in the dynamic
  # batcher keeps mixed shapes merge-local (ops/dynamic_batching.
  # FamilyBatcher).
  fleet_tasks: str = ''
  # Minimal PBT across learner replicas (round 22; population.py,
  # arXiv 1711.09846): 0 = off; >= 2 trains that many independent
  # anakin-runtime members under ONE driver invocation
  # (<logdir>/member_<k>), suites assigned round-robin from
  # pbt_suites. Every pbt_round_frames frames per member, process 0
  # ranks members WITHIN their suite (cross-suite returns are not
  # commensurable) and bottom-quantile members inherit a top-quantile
  # donor's weights through the checkpoint ladder (verified save ->
  # re-verified restore) with (learning_rate, entropy_cost) perturbed
  # by pbt_perturb — each exploit is a durable pbt_exploit incident.
  pbt_population: int = 0
  pbt_round_frames: int = 0               # frames/member/round (0 =
                                          # auto: 1/4 of the budget)
  pbt_suites: str = ''                    # comma-separated jittable
                                          # backends; '' = env_backend
  pbt_quantile: float = 0.25              # exploit bottom/top fraction
  pbt_perturb: float = 1.2                # explore factor (x or /)
  # Fused population (round 23): vmap the N single-device members
  # over a leading member axis so every round trains ONE compiled
  # Anakin program instead of N serial spin-ups — (learning_rate,
  # entropy_cost) become traced per-member scalars, exploit is an
  # on-device stacked-slice copy, PBT decide/explore stays host-side
  # between rounds. Requires a single jittable suite; a model-axis
  # mesh degrades to the serial member loop with a warning.
  pbt_vectorized: bool = False
  # Persistent XLA compilation cache (round 23): armed in
  # distributed.maybe_initialize BEFORE backend spin-up, so repeat
  # spin-ups of identical programs (population rounds, elastic
  # rejoin, serving flips, plain restarts) skip retrace+compile.
  # 'auto' = <logdir>/.jax_cache, armed on accelerator hosts only
  # (CPU-pinned processes skip auto-arming: jaxlib's XLA:CPU
  # executable reload can kill the process at driver scale); ''
  # disables; any other value is the cache dir itself, armed on any
  # backend (shareable across runs/processes — entries are keyed,
  # concurrent writers are safe).
  compile_cache_dir: str = 'auto'

  @property
  def frames_per_step(self):
    return self.batch_size * self.unroll_length * self.num_action_repeats

  @property
  def resolved_wire_dtype(self) -> str:
    """The ingest server's wire_dtype from the codec knobs: the
    legacy `remote_params_dtype` (non-empty) wins, else
    `publish_codec` ('bf16' → 'bfloat16', 'f32' → exact float32).
    Resolved here so the driver, the remote-actor role, and bench.py
    can never disagree on the production default."""
    if self.remote_params_dtype:
      return self.remote_params_dtype
    if self.publish_codec == 'bf16':
      return 'bfloat16'
    if self.publish_codec == 'f32':
      return ''
    if self.publish_codec == 'int8':
      # Round 21: absmax-int8 wire blobs (runtime/codec.py), protocol
      # v10 — v<=9 subscribers are negotiated down to bf16 blobs.
      return 'int8'
    raise ValueError(
        f"publish_codec must be 'bf16', 'f32' or 'int8', got "
        f'{self.publish_codec!r}')

  @property
  def resolved_replay_capacity(self) -> int:
    """Replay-tier capacity with the 0-auto rule applied (4x batch —
    enough history for ratio .75 at replay_k 4 without letting mean
    staleness run away)."""
    if self.replay_capacity_unrolls > 0:
      return self.replay_capacity_unrolls
    return 4 * self.batch_size

  @property
  def resolved_replay_max_staleness(self) -> int:
    """The replay staleness window in published param-version deltas —
    the unit shared with `max_unroll_staleness` (round 10 unified the
    two; they used to be spelled in different units). 0 defers to the
    ingest window so an operator bounding admission staleness bounds
    replay staleness for free; both 0 = unbounded."""
    if self.replay_max_staleness > 0:
      return self.replay_max_staleness
    return self.max_unroll_staleness

  @property
  def resolved_filler_backend(self) -> str:
    """The hybrid filler's env core: the explicit knob, else the run's
    own backend when it is jittable (the filler then self-plays the
    REAL task), else 'bandit' (which accepts any policy-head width —
    the filler must run under the main task's action space)."""
    if self.filler_backend:
      return self.filler_backend
    if self.env_backend in JITTABLE_BACKENDS:
      return self.env_backend
    return 'bandit'

  @property
  def resolved_filler_batch_size(self) -> int:
    return (self.filler_batch_size if self.filler_batch_size > 0
            else self.batch_size)

  @property
  def resolved_filler_unroll_length(self) -> int:
    """Filler rollout length (0-auto: min(T, 16)) — short slices keep
    the one-filler-step yield bound tight at flagship T=100."""
    if self.filler_unroll_length > 0:
      return self.filler_unroll_length
    return min(self.unroll_length, 16)

  @property
  def resolved_use_instruction(self) -> bool:
    """`use_instruction` with the None-auto rule applied (must be
    deterministic in the config alone: train, evaluate, and remote
    actors all resolve independently and the agent param structure —
    hence checkpoints — depends on it)."""
    if self.use_instruction is not None:
      return self.use_instruction
    if self.level_name == 'dmlab30':
      return True
    return self.level_name.startswith(('language_', 'psychlab_'))

  @property
  def resolved_pbt_suites(self) -> List[str]:
    """The population's suite list: the explicit comma list, else the
    run's own backend repeated — members then differ only in hypers
    (classic single-task PBT)."""
    if self.pbt_suites:
      return [s.strip() for s in self.pbt_suites.split(',')
              if s.strip()]
    return [self.env_backend]

  @property
  def resolved_pbt_round_frames(self) -> int:
    """Frames each member trains between PBT decision points (0-auto:
    a quarter of the per-member budget — 4 rounds, enough for one
    exploit to propagate and still show post-exploit learning)."""
    if self.pbt_round_frames > 0:
      return self.pbt_round_frames
    return max(self.total_environment_frames // 4, 1)

  @property
  def resolved_compile_cache_dir(self) -> str:
    """The persistent-compilation-cache dir with the 'auto' rule
    applied ('' = disabled). Resolved here so the driver, bench.py,
    and distributed.maybe_initialize can never disagree on where a
    run's cache lives."""
    if self.compile_cache_dir == 'auto':
      return self.logdir + '/.jax_cache'
    return self.compile_cache_dir


def validate_replay(config: Config) -> List[str]:
  """Validate the sample-reuse knob group (round 10); raises
  ValueError on hard errors, returns human-readable warnings for the
  caller to log (config.py has no logger; driver.train and bench.py
  both call this before spin-up so a bad knob combination fails
  before any env/checkpoint cost).

  The staleness cross-link (the round-10 unit unification): both
  `max_unroll_staleness` (ingest admission) and `replay_max_staleness`
  (replay eviction) are in PUBLISHED PARAM-VERSION deltas. A replay
  window narrower than the admission window means a remote unroll can
  be admitted as fresh enough to train on once, yet already be too
  stale to ever replay — legal (admission is about training at all,
  replay about training again) but worth a warning since the operator
  probably meant one window."""
  warnings = []
  if config.surrogate not in ('vtrace', 'impact'):
    raise ValueError(f'surrogate must be vtrace|impact, got '
                     f'{config.surrogate!r}')
  if config.replay_k < 1:
    raise ValueError(f'replay_k must be >= 1, got {config.replay_k}')
  if not 0.0 <= config.replay_ratio < 1.0:
    raise ValueError(f'replay_ratio must be in [0, 1) (a batch needs '
                     f'at least one fresh slot), got '
                     f'{config.replay_ratio}')
  if config.target_update_interval < 1:
    raise ValueError(f'target_update_interval must be >= 1, got '
                     f'{config.target_update_interval}')
  if config.impact_epsilon <= 0:
    raise ValueError(f'impact_epsilon must be > 0, got '
                     f'{config.impact_epsilon}')
  if config.replay_capacity_unrolls < 0:
    raise ValueError(f'replay_capacity_unrolls must be >= 0, got '
                     f'{config.replay_capacity_unrolls}')
  if config.replay_max_staleness < 0:
    raise ValueError(f'replay_max_staleness must be >= 0, got '
                     f'{config.replay_max_staleness}')
  reuse_on = config.replay_k > 1 or config.replay_ratio > 0
  if reuse_on and config.surrogate == 'vtrace':
    warnings.append(
        'sample reuse (replay_k=%d, replay_ratio=%.2f) with '
        'surrogate=vtrace: plain V-trace has no clipped-target anchor '
        'against reused/stale data (IMPACT, arXiv 1912.00167) — '
        'consider --surrogate=impact' %
        (config.replay_k, config.replay_ratio))
  if (config.replay_max_staleness > 0 and
      config.max_unroll_staleness > 0 and
      config.replay_max_staleness < config.max_unroll_staleness):
    warnings.append(
        'replay_max_staleness=%d is narrower than '
        'max_unroll_staleness=%d (both in published param-version '
        'deltas): unrolls admitted near the ingest window will be '
        'version-evicted from the replay tier without ever being '
        'replayed' %
        (config.replay_max_staleness, config.max_unroll_staleness))
  if config.replay_ratio > 0 and config.resolved_replay_capacity < \
      config.batch_size:
    warnings.append(
        'replay capacity %d is below batch_size %d: replayed slots '
        'will repeat the same few unrolls within adjacent batches' %
        (config.resolved_replay_capacity, config.batch_size))
  return warnings


# What a learner restart-from-checkpoint actually costs before the
# ingest port answers hellos again: process spawn + jax import +
# checkpoint restore + the 20-40 s inference/train compiles. An actor
# reconnect window shorter than this turns every learner hard-crash
# into a dead fleet — validate_transport cross-links the two.
LEARNER_RESTART_BUDGET_SECS = 90.0


def validate_transport(config: Config) -> List[str]:
  """Validate the transport-liveness knob group (round 11); raises
  ValueError on hard errors, returns human-readable warnings for the
  caller to log (same contract as validate_replay — driver.train and
  run_remote_actor both call it before spin-up).

  The reconnect/restart cross-link: `actor_reconnect_secs` is how long
  an actor host survives a dead learner, and a learner hard-crash
  restart (docs/RUNBOOK.md §8) costs LEARNER_RESTART_BUDGET_SECS
  before the new ingest port answers — a window shorter than the
  budget means the fleet gives up mid-restart and the restarted
  learner comes back to nobody."""
  warnings = []
  if config.remote_heartbeat_secs < 0:
    raise ValueError(f'remote_heartbeat_secs must be >= 0, got '
                     f'{config.remote_heartbeat_secs}')
  if config.remote_conn_idle_timeout_secs < 0:
    raise ValueError(f'remote_conn_idle_timeout_secs must be >= 0, '
                     f'got {config.remote_conn_idle_timeout_secs}')
  if config.actor_reconnect_secs < 0:
    raise ValueError(f'actor_reconnect_secs must be >= 0, got '
                     f'{config.actor_reconnect_secs}')
  if 0 < config.actor_reconnect_secs < LEARNER_RESTART_BUDGET_SECS:
    warnings.append(
        'actor_reconnect_secs=%.1f is shorter than the learner '
        'restart budget (~%.0fs: restore + recompile before the '
        'ingest port answers) — the fleet will give up mid-restart '
        'and a hard-crashed learner comes back to nobody '
        '(docs/RUNBOOK.md §8)' %
        (config.actor_reconnect_secs, LEARNER_RESTART_BUDGET_SECS))
  hb = config.remote_heartbeat_secs
  idle = config.remote_conn_idle_timeout_secs
  if hb > 0 and idle > 0 and hb >= idle:
    warnings.append(
        'remote_heartbeat_secs=%.1f >= remote_conn_idle_timeout_secs'
        '=%.1f: heartbeats cannot keep an idle-but-healthy connection '
        'inside the reaping window — every quiet period becomes a '
        'reap + reconnect cycle' % (hb, idle))
  if idle > 0 and hb == 0:
    warnings.append(
        'remote_conn_idle_timeout_secs=%.1f with heartbeats disabled: '
        'idle-but-healthy peers (slow envs, v5 clients) will be '
        'reaped and must reconnect — set remote_heartbeat_secs > 0 '
        'or size the window above the slowest unroll cadence' % idle)
  if hb > 0 and idle == 0:
    warnings.append(
        'remote_heartbeat_secs=%.1f with idle reaping disabled '
        '(remote_conn_idle_timeout_secs=0): mid-frame stalls still '
        'abort, but a BETWEEN-frames half-open connection is never '
        'reaped and heartbeat misses are not counted — set a nonzero '
        'idle window to get the full liveness story' % hb)
  return warnings


def validate_integrity(config: Config) -> List[str]:
  """Validate the data-plane-integrity knob group (round 12); returns
  human-readable warnings (same contract as validate_replay /
  validate_transport — driver.train and run_remote_actor call it
  before spin-up). All knobs are booleans, so there are no hard range
  errors — only cross-links where a half-enabled integrity plane is
  probably a mistake."""
  warnings = []
  if config.sdc_check and not config.health_watchdog:
    warnings.append(
        'sdc_check=True with health_watchdog=False: replica '
        'fingerprint mismatches would be computed but never escalated '
        '(no monitor, no rollback ladder) — enable the watchdog or '
        'disable the SDC sentinel')
  if not config.wire_crc and config.remote_actor_port:
    warnings.append(
        'wire_crc=False with remote ingest enabled: a bit-flipped '
        'unroll frame that still parses will train the learner on '
        'garbage with no detection (the round-12 integrity plane is '
        'off on the wire); param publishes keep their content digest '
        'either way')
  if (config.replay_crc and not config.wire_crc
      and config.replay_ratio > 0):
    warnings.append(
        'replay_crc=True with wire_crc=False: replayed unrolls are '
        'verified against their INSERT-time CRC, but a remote unroll '
        'corrupted on the wire is inserted already-rotten and will '
        're-serve cleanly — the replay check only covers rot AFTER '
        'retention')
  return warnings


def validate_slo(config: Config) -> List[str]:
  """Validate the SLO knob group (round 14); raises ValueError on
  hard errors, returns warnings (same contract as validate_replay /
  validate_transport / validate_integrity — driver.train calls it
  before spin-up). The spec file itself is loaded (and therefore
  validated) by slo.load_objectives at engine construction; here the
  cross-links."""
  warnings = []
  if config.slo_fast_window_secs <= 0:
    raise ValueError(f'slo_fast_window_secs must be > 0, got '
                     f'{config.slo_fast_window_secs}')
  if config.slo_slow_window_secs <= 0:
    raise ValueError(f'slo_slow_window_secs must be > 0, got '
                     f'{config.slo_slow_window_secs}')
  if config.slo_capture_steps < 1:
    raise ValueError(f'slo_capture_steps must be >= 1, got '
                     f'{config.slo_capture_steps}')
  if config.slo_interval_secs < 0:
    raise ValueError(f'slo_interval_secs must be >= 0, got '
                     f'{config.slo_interval_secs}')
  if not config.slo_engine:
    if config.slo_spec:
      warnings.append(
          'slo_spec=%r with slo_engine=False: the objective set is '
          'loaded by the engine — nothing will judge it' %
          config.slo_spec)
    return warnings
  if config.slo_fast_window_secs >= config.slo_slow_window_secs:
    warnings.append(
        'slo_fast_window_secs=%.1f >= slo_slow_window_secs=%.1f: the '
        'slow window no longer confirms a sustained burn — every '
        'fast-window blip escalates straight to a violation' %
        (config.slo_fast_window_secs, config.slo_slow_window_secs))
  if (config.slo_interval_secs > 0 and
      config.slo_interval_secs * 3 > config.slo_fast_window_secs):
    warnings.append(
        'slo_interval_secs=%.1f leaves fewer than the 3 samples the '
        'fast window (%.1fs) needs before a value objective can '
        'burn — the policy-lag/utilization/fleet objectives would be '
        'structurally unable to fire; lower the interval or widen '
        'slo_fast_window_secs' %
        (config.slo_interval_secs, config.slo_fast_window_secs))
  if not config.telemetry_trace:
    warnings.append(
        'slo_engine=True with telemetry_trace=False: the policy-lag '
        'and end-to-end-span objectives will evaluate as no_data '
        '(their histograms never fill), and page captures lose the '
        'flight/trace-slice artifacts — the verdict only judges the '
        'counter planes')
  if config.slo_capture and not config.health_watchdog:
    warnings.append(
        'slo_capture=True with health_watchdog=False: SLO burns '
        'cannot feed the external-incident ledger (no monitor), so '
        'drain manifests and halt bundles will not name them')
  return warnings


def validate_controller(config: Config) -> List[str]:
  """Validate the self-healing-controller knob group (round 15);
  raises ValueError on hard errors, returns warnings (same contract
  as the other validate_* groups — driver.train calls it before
  spin-up). The policy file itself is loaded (and validated) by
  controller.load_rules at construction; here the cross-links."""
  warnings = []
  if config.controller not in ('off', 'observe', 'act'):
    raise ValueError(f'controller must be off|observe|act, got '
                     f'{config.controller!r}')
  if config.controller_interval_secs < 0:
    raise ValueError(f'controller_interval_secs must be >= 0, got '
                     f'{config.controller_interval_secs}')
  if config.controller_replay_k_max < 1:
    raise ValueError(f'controller_replay_k_max must be >= 1, got '
                     f'{config.controller_replay_k_max}')
  if config.controller_publish_secs_max <= 0:
    raise ValueError(f'controller_publish_secs_max must be > 0, got '
                     f'{config.controller_publish_secs_max}')
  if config.fleet_probation_secs < 0:
    raise ValueError(f'fleet_probation_secs must be >= 0, got '
                     f'{config.fleet_probation_secs}')
  if config.pod_max_hosts < 0:
    raise ValueError(f'pod_max_hosts must be >= 0, got '
                     f'{config.pod_max_hosts}')
  if config.pod_max_hosts > 0 and not config.remote_actor_port:
    warnings.append(
        'pod_max_hosts=%d with remote ingest disabled '
        '(remote_actor_port=0): the pod_size actuator reads the '
        'ingest membership ledger — it will not be registered'
        % config.pod_max_hosts)
  if (config.remote_heartbeat_secs == 0
      and config.remote_conn_idle_timeout_secs > 0
      and config.fleet_probation_secs >
      config.remote_conn_idle_timeout_secs):
    warnings.append(
        'fleet_probation_secs=%.1f exceeds the idle-reaping window '
        '(remote_conn_idle_timeout_secs=%.1f) with heartbeats '
        'disabled: a remote client cooling down in CRC probation '
        'cannot ping, so the learner will reap it as half-open '
        'mid-probation — enable heartbeats or shorten the cool-down'
        % (config.fleet_probation_secs,
           config.remote_conn_idle_timeout_secs))
  if config.controller == 'off':
    if config.controller_policy:
      warnings.append(
          'controller_policy=%r with controller=off: the policy '
          'table is loaded by the controller — nothing will read it'
          % config.controller_policy)
    return warnings
  if not config.slo_engine:
    warnings.append(
        'controller=%s with slo_engine=False: the controller\'s only '
        'input is the SLO engine\'s burning set and margins — it '
        'will be disabled for this run' % config.controller)
  if (config.controller == 'act' and config.surrogate == 'vtrace'
      and config.controller_replay_k_max > 1):
    warnings.append(
        'controller=act may raise replay_k up to %d, but '
        'surrogate=vtrace has no clipped-target anchor against '
        'reused data (IMPACT, arXiv 1912.00167) — consider '
        '--surrogate=impact, or cap --controller_replay_k_max=1'
        % config.controller_replay_k_max)
  return warnings


# Fields deliberately NOT exposed as experiment.py flags — the
# explicit allowlist the `config-flags` contract lint
# (scripts/lint.py, round 18) checks: every Config field must either
# have a flag of the same name or be named here with the reason a
# flag would be wrong. Empty today — every field is operator-facing.
# Allowlist etiquette (docs/STATIC_ANALYSIS.md): entries carry a
# trailing comment saying WHY, and a stale entry (field gone, or flag
# added) is itself a lint finding.
INTERNAL_FIELDS = ()


# Env backends whose dynamics exist as jittable device cores
# (parallel/anakin.ENV_CORES) — the backends --runtime=anakin and the
# hybrid filler can run. Literal here because config.py must not
# import jax-importing modules; tests/test_anakin.py pins this tuple
# against the live ENV_CORES registry.
JITTABLE_BACKENDS = ('bandit', 'cue_memory', 'gridworld', 'procgen')


def validate_runtime(config: Config) -> List[str]:
  """Validate the runtime-axis knob group (round 16); raises
  ValueError on hard errors, returns warnings (same contract as the
  other validate_* groups — driver.train calls it before spin-up for
  BOTH runtimes).

  The filler/SLO cross-link: the hybrid filler lifts
  `learner_plane_utilization` to ~1.0 BY CONSTRUCTION, so that curve
  can no longer signal an env-bound (or dead) env plane —
  `env_plane_utilization` stays the dead-plane signal either way
  (docs/OBSERVABILITY.md; the SLO engine's env-plane objective is the
  page path filler must never mask)."""
  warnings = []
  if config.runtime not in ('fleet', 'anakin'):
    raise ValueError(f'runtime must be fleet|anakin, got '
                     f'{config.runtime!r}')
  if config.filler_batch_size < 0:
    raise ValueError(f'filler_batch_size must be >= 0, got '
                     f'{config.filler_batch_size}')
  if config.filler_unroll_length < 0:
    raise ValueError(f'filler_unroll_length must be >= 0, got '
                     f'{config.filler_unroll_length}')
  if config.runtime == 'anakin':
    if config.env_backend not in JITTABLE_BACKENDS:
      raise ValueError(
          f'--runtime=anakin needs a jittable env backend '
          f'({", ".join(JITTABLE_BACKENDS)}), got '
          f'{config.env_backend!r}; real simulators use the fleet '
          'runtime')
    if config.remote_actor_port:
      warnings.append(
          'runtime=anakin with remote_actor_port=%d: the fused '
          'device loop has no ingest plane — the port will not be '
          'bound' % config.remote_actor_port)
    if config.anakin_filler:
      warnings.append(
          'anakin_filler=True under runtime=anakin is a no-op: the '
          'whole run IS the on-device loop (the filler is the fleet '
          "runtime's idle-slice workload)")
    return warnings
  if not config.anakin_filler:
    if config.filler_backend:
      warnings.append(
          'filler_backend=%r with anakin_filler=False: nothing will '
          'run it' % config.filler_backend)
    return warnings
  if config.resolved_filler_backend not in JITTABLE_BACKENDS:
    raise ValueError(
        f'filler_backend must be jittable '
        f'({", ".join(JITTABLE_BACKENDS)}), got '
        f'{config.filler_backend!r}')
  if config.surrogate == 'vtrace':
    warnings.append(
        'anakin_filler=True with surrogate=vtrace: filler updates are '
        'off-cadence relative to the fleet stream and plain V-trace '
        'has no clipped-target anchor against them (IMPACT, '
        'arXiv 1912.00167) — consider --surrogate=impact')
  if not config.slo_engine:
    warnings.append(
        'anakin_filler=True with slo_engine=False: the filler lifts '
        'learner_plane_utilization to ~1.0 by construction, and with '
        'the engine off nothing watches env_plane_utilization — the '
        'dead-env-plane signal the filler could otherwise mask '
        '(docs/OBSERVABILITY.md)')
  return warnings


def resolve_process_id(config: Config) -> int:
  """The ONE resolution of this process's declared index:
  config.process_id when set, else the reference's --task spelling
  (floored at 0). Shared by validate_distributed and
  distributed.maybe_initialize so the id the validator checks is the
  id the join actually uses."""
  return (config.process_id if config.process_id >= 0
          else max(config.task, 0))


def validate_distributed(config: Config,
                         live_process_count: int = 1) -> List[str]:
  """Validate the multi-process knob group (round 17); raises
  ValueError on hard errors, returns warnings (same contract as the
  other validate_* groups — driver.train calls it before spin-up,
  AFTER distributed.maybe_initialize, passing the live
  jax.process_count() so topologies initialized by a launcher rather
  than these fields are cross-linked too).

  Pure-config checks use the DECLARED topology (num_processes /
  coordinator_address) so they are unit-testable without spawning
  processes; the cross-links below use
  max(declared, live_process_count)."""
  warnings = []
  if config.num_processes < 1:
    raise ValueError(f'num_processes must be >= 1, got '
                     f'{config.num_processes}')
  if config.tp_compute not in ('auto', 'sharded', 'gathered'):
    raise ValueError(f'tp_compute must be auto|sharded|gathered, got '
                     f'{config.tp_compute!r}')
  # Registry rule-set name (round 19): resolved against the same table
  # every consumer queries, so a typo dies here instead of as a
  # mysterious replicated run.
  from scalable_agent_tpu.parallel import sharding as _sharding_lib
  if (config.sharding_rules != 'auto'
      and config.sharding_rules not in _sharding_lib.RULE_SETS):
    raise ValueError(
        f'sharding_rules must be auto|'
        f'{"|".join(sorted(_sharding_lib.RULE_SETS))}, got '
        f'{config.sharding_rules!r}')
  if (config.sharding_rules == 'replicated'
      and config.model_parallelism > 1):
    warnings.append(
        'sharding_rules=replicated with model_parallelism=%d: the '
        'model axis exists but no rule cuts over it — every param '
        'replicates across it (TP memory win forfeited); use '
        'sharding_rules=auto or =megatron to shard'
        % config.model_parallelism)
  if config.coordinator_address:
    host, sep, port = config.coordinator_address.rpartition(':')
    if not sep or not host or not port.isdigit():
      raise ValueError(
          f'coordinator_address must be host:port, got '
          f'{config.coordinator_address!r}')
    if config.num_processes == 1:
      warnings.append(
          'coordinator_address=%r with num_processes=1: a one-process '
          'jax.distributed runtime works but coordinates nothing — '
          'drop the flag or raise the count'
          % config.coordinator_address)
    resolved_id = resolve_process_id(config)
    if resolved_id >= config.num_processes:
      raise ValueError(
          f'process_id {resolved_id} out of range for num_processes='
          f'{config.num_processes}')
  elif config.num_processes > 1:
    raise ValueError(
        f'num_processes={config.num_processes} needs '
        'coordinator_address (host:port of process 0)')
  elif config.process_id >= 0:
    warnings.append(
        'process_id=%d without coordinator_address: nothing will '
        'join a distributed runtime' % config.process_id)
  procs = max(config.num_processes, live_process_count)
  if procs <= 1:
    return warnings
  # --- Multi-process cross-links. ---
  if config.runtime == 'anakin':
    # Hard error, same verdict train_anakin reaches later — but here,
    # before any device/env spin-up: each process would train an
    # unsynchronized replica (the fused loop has no cross-host batch
    # transport).
    raise ValueError(
        'runtime=anakin is single-host; multi-process runs use the '
        'fleet runtime (per-host ingest + gradient psum)')
  if config.sdc_check and not config.sdc_allgather:
    warnings.append(
        'sdc_check=True with sdc_allgather=False on a multi-process '
        'topology: the per-replica fingerprint readback needs the '
        'in-graph all-gather (a cross-process P(\'data\') device_get '
        'is illegal), so the SDC sentinel will be silently OFF — '
        'enable sdc_allgather or drop sdc_check')
  if config.model_parallelism > 1:
    # TP across hosts flips the shard_batch_over_model predicate
    # (parallel/mesh.py): the batch shards over BOTH axes, so
    # batch_size must divide the FULL device count, actors run on a
    # localized param copy (a collective allgather per publish), and
    # unroll staging falls back to batch mode. Legal, but the
    # operator should know the shape changed.
    warnings.append(
        'model_parallelism=%d on a multi-process topology: the model '
        'axis crosses hosts, so the batch shards over BOTH mesh axes '
        '(mesh.shard_batch_over_model) — batch_size must divide the '
        'full device count, param publishes localize via a collective '
        'allgather, and staging_mode=unroll falls back to batch'
        % config.model_parallelism)
  if config.anakin_filler:
    warnings.append(
        'anakin_filler=True on a multi-process topology: the filler '
        'mutates params OUTSIDE the collective train step, so hosts '
        'with different idle patterns would diverge — the driver '
        'disables it (supports_filler) and parks idle slices instead')
  return warnings


def validate_serving(config: Config) -> List[str]:
  """Validate the multi-tenant serving knob group (round 21); raises
  ValueError on hard errors, returns warnings (same contract as the
  other validate_* groups — driver.train and run_remote_actor call it
  before spin-up)."""
  warnings = []
  if config.serving_resident_versions < 1:
    raise ValueError(f'serving_resident_versions must be >= 1, got '
                     f'{config.serving_resident_versions}')
  if config.serving_hbm_budget_mb < 0:
    raise ValueError(f'serving_hbm_budget_mb must be >= 0, got '
                     f'{config.serving_hbm_budget_mb}')
  for name in ('serving_ab_fraction', 'serving_shadow_fraction'):
    value = getattr(config, name)
    if not 0.0 <= value <= 1.0:
      raise ValueError(f'{name} must be in [0, 1], got {value}')
  if (config.serving_resident_versions == 1
      and (config.serving_ab_fraction > 0
           or config.serving_shadow_fraction > 0)):
    warnings.append(
        'serving_ab_fraction/serving_shadow_fraction > 0 with '
        'serving_resident_versions=1: there is never a non-live '
        'resident candidate, so A/B and shadow traffic will not fire '
        '— raise serving_resident_versions')
  if config.serving_replicas and not config.learner_address:
    warnings.append(
        'serving_replicas set without learner_address: routed '
        'inference replicas are an ACTOR-host knob — the learner '
        'role ignores it')
  return warnings


def validate_population(config: Config) -> List[str]:
  """Validate the population knob group (round 22); raises ValueError
  on hard errors, returns warnings (same contract as the other
  validate_* groups — driver.train AND driver.evaluate call it before
  spin-up). Covers the three population axes: curriculum, mixed
  fleets, PBT."""
  warnings = []
  # --- Curriculum. ---
  if config.curriculum not in ('uniform', 'regret', 'td'):
    raise ValueError(f'curriculum must be uniform|regret|td, got '
                     f'{config.curriculum!r}')
  if config.curriculum_temperature <= 0:
    raise ValueError(f'curriculum_temperature must be > 0, got '
                     f'{config.curriculum_temperature}')
  if not 0.0 <= config.curriculum_eps <= 1.0:
    raise ValueError(f'curriculum_eps must be in [0, 1], got '
                     f'{config.curriculum_eps}')
  if not 0.0 < config.curriculum_alpha <= 1.0:
    raise ValueError(f'curriculum_alpha must be in (0, 1], got '
                     f'{config.curriculum_alpha}')
  if not 0.0 < config.curriculum_decay <= 1.0:
    raise ValueError(f'curriculum_decay must be in (0, 1], got '
                     f'{config.curriculum_decay}')
  if config.procgen_num_levels < 1:
    raise ValueError(f'procgen_num_levels must be >= 1, got '
                     f'{config.procgen_num_levels}')
  if not 0.0 <= config.procgen_wall_density < 1.0:
    raise ValueError(f'procgen_wall_density must be in [0, 1), got '
                     f'{config.procgen_wall_density}')
  if config.curriculum != 'uniform':
    curriculum_backends = {config.env_backend}
    if config.anakin_filler:
      curriculum_backends.add(config.resolved_filler_backend)
    if 'procgen' not in curriculum_backends:
      warnings.append(
          'curriculum=%s with env_backend=%r: only the procgen core '
          'has a finite level-id space to prioritize — the sampler '
          'is inert for this run' %
          (config.curriculum, config.env_backend))
    if config.unroll_length < 2:
      warnings.append(
          'curriculum=%s with unroll_length=1: a TD error needs two '
          'consecutive value estimates, so no per-level signal can '
          'accumulate (scores only decay) — use unroll_length >= 2' %
          config.curriculum)
    if config.curriculum_eps == 0:
      warnings.append(
          'curriculum_eps=0: no uniform mixing floor — a level whose '
          'score collapses early may never be revisited, so its stale '
          'score cannot recover (the decay then has nothing to rescue)')
  # --- Heterogeneous fleets. ---
  if config.fleet_tasks:
    from scalable_agent_tpu import population as _population
    tasks = _population.parse_fleet_tasks(config.fleet_tasks)
    if not tasks:
      raise ValueError(f'fleet_tasks={config.fleet_tasks!r} names no '
                       'tasks')
    names = [name for name, _ in tasks]
    for name in names:
      if name not in JITTABLE_BACKENDS:
        raise ValueError(
            f'fleet_tasks names {name!r}: mixed fleets compose the '
            f'jittable suites ({", ".join(JITTABLE_BACKENDS)}) — '
            'real simulators keep their own single-task fleets')
    if 'cue_memory' in names and any(n in ('gridworld', 'procgen')
                                     for n in names):
      raise ValueError(
          'fleet_tasks mixes cue_memory (a fixed 3-action task) with '
          'gridworld/procgen (>= 4 movement actions): one shared '
          'policy head cannot satisfy both — drop one side or widen '
          'with bandit (any head width)')
    if config.runtime == 'anakin':
      warnings.append(
          'fleet_tasks is a fleet-runtime feature (per-actor task '
          'assignment); runtime=anakin runs env_backend=%r only — '
          'the spec is ignored' % config.env_backend)
    elif len(tasks) > config.num_actors:
      raise ValueError(
          f'fleet_tasks names {len(tasks)} tasks but num_actors='
          f'{config.num_actors} cannot cover them at >= 1 actor each')
    if not config.use_popart and len(tasks) > 1:
      warnings.append(
          'fleet_tasks mixes %d suites with use_popart=False: reward '
          'scales will compete in one value head — consider '
          '--use_popart' % len(tasks))
  # --- PBT. ---
  if config.pbt_population < 0:
    raise ValueError(f'pbt_population must be >= 0, got '
                     f'{config.pbt_population}')
  if config.pbt_round_frames < 0:
    raise ValueError(f'pbt_round_frames must be >= 0, got '
                     f'{config.pbt_round_frames}')
  if not 0.0 < config.pbt_quantile <= 0.5:
    raise ValueError(f'pbt_quantile must be in (0, 0.5] (bottom and '
                     f'top slices must not overlap), got '
                     f'{config.pbt_quantile}')
  if config.pbt_perturb <= 1.0:
    raise ValueError(f'pbt_perturb must be > 1 (the explore factor '
                     f'multiplies OR divides), got '
                     f'{config.pbt_perturb}')
  if config.pbt_population == 1:
    warnings.append(
        'pbt_population=1: a population of one has no donor to '
        'exploit — PBT is off (use >= 2, ideally >= 2 per suite)')
  if config.pbt_vectorized and config.pbt_population < 2:
    warnings.append(
        'pbt_vectorized without pbt_population >= 2: there is no '
        'population to vectorize — the flag is inert')
  if config.pbt_population >= 2:
    if config.runtime != 'anakin':
      raise ValueError(
          'pbt_population >= 2 needs --runtime=anakin: population '
          'members are fused-loop replicas (the fleet runtime owns '
          'the host devices exclusively — replicas would contend)')
    suites = config.resolved_pbt_suites
    for suite in suites:
      if suite not in JITTABLE_BACKENDS:
        raise ValueError(
            f'pbt_suites names {suite!r}: population members are '
            f'anakin runs and need jittable backends '
            f'({", ".join(JITTABLE_BACKENDS)})')
    if 'cue_memory' in suites and any(s in ('gridworld', 'procgen')
                                      for s in suites):
      raise ValueError(
          'pbt_suites mixes cue_memory (fixed 3-action) with '
          'gridworld/procgen (>= 4 actions): members share one agent '
          'architecture, so their policy heads must be one width')
    if config.pbt_vectorized:
      if len(set(suites)) > 1:
        raise ValueError(
            'pbt_vectorized: one vmapped program trains ONE suite '
            '(member programs must be structurally identical), but '
            f'pbt_suites names {sorted(set(suites))} — drop '
            '--pbt_vectorized or train a single-suite population')
      if config.model_parallelism > 1:
        warnings.append(
            'pbt_vectorized with model_parallelism=%d: vectorized '
            'members are single-device programs — train_population '
            'degrades to the serial member loop' %
            config.model_parallelism)
    if config.pbt_population < len(suites):
      raise ValueError(
          f'pbt_population={config.pbt_population} cannot cover '
          f'{len(suites)} suites at >= 1 member each')
    if config.pbt_population < 2 * len(suites):
      warnings.append(
          'pbt_population=%d over %d suite(s): some suites get a '
          'single member, and exploit/explore only fires WITHIN a '
          'suite — size the population at >= 2 per suite' %
          (config.pbt_population, len(suites)))
  return warnings


def apply_overrides(config: Config, **overrides) -> Config:
  return dataclasses.replace(config, **overrides)
