"""IMPALA losses (reference: experiment.py ≈L300–330).

Sum-reductions over [T, B] exactly like the reference (not means) — the
loss scale interacts with the tuned learning rate, so this is
load-bearing for hyperparameter parity.
"""

import jax
import jax.numpy as jnp


def compute_baseline_loss(advantages):
  """0.5 * sum((vs - V)^2) — reference `compute_baseline_loss`."""
  return 0.5 * jnp.sum(jnp.square(advantages))


def compute_entropy_loss(logits):
  """Negative total entropy (minimizing it maximizes entropy) —
  reference `compute_entropy_loss`."""
  policy = jax.nn.softmax(logits, axis=-1)
  log_policy = jax.nn.log_softmax(logits, axis=-1)
  entropy_per_timestep = -jnp.sum(policy * log_policy, axis=-1)
  return -jnp.sum(entropy_per_timestep)


def compute_policy_gradient_loss(logits, actions, advantages):
  """sum over T,B of -log pi(a|x) * advantage, advantages stopped —
  reference `compute_policy_gradient_loss`."""
  log_probs = jax.nn.log_softmax(logits, axis=-1)
  cross_entropy = -jnp.take_along_axis(
      log_probs, actions[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
  advantages = jax.lax.stop_gradient(advantages)
  return jnp.sum(cross_entropy * advantages)
