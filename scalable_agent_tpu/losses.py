"""IMPALA losses (reference: experiment.py ≈L300–330).

Sum-reductions over [T, B] exactly like the reference (not means) — the
loss scale interacts with the tuned learning rate, so this is
load-bearing for hyperparameter parity.
"""

import jax
import jax.numpy as jnp


def compute_baseline_loss(advantages):
  """0.5 * sum((vs - V)^2) — reference `compute_baseline_loss`."""
  return 0.5 * jnp.sum(jnp.square(advantages))


def compute_entropy_loss(logits):
  """Negative total entropy (minimizing it maximizes entropy) —
  reference `compute_entropy_loss`."""
  policy = jax.nn.softmax(logits, axis=-1)
  log_policy = jax.nn.log_softmax(logits, axis=-1)
  entropy_per_timestep = -jnp.sum(policy * log_policy, axis=-1)
  return -jnp.sum(entropy_per_timestep)


def compute_policy_gradient_loss(logits, actions, advantages):
  """sum over T,B of -log pi(a|x) * advantage, advantages stopped —
  reference `compute_policy_gradient_loss`."""
  log_probs = jax.nn.log_softmax(logits, axis=-1)
  cross_entropy = -jnp.take_along_axis(
      log_probs, actions[..., None].astype(jnp.int32), axis=-1).squeeze(-1)
  advantages = jax.lax.stop_gradient(advantages)
  return jnp.sum(cross_entropy * advantages)


def compute_impact_surrogate_loss(log_ratio, advantages, epsilon):
  """IMPACT clipped-target surrogate (arXiv 1912.00167, round 10).

  `log_ratio` is log pi_theta(a|x) - log pi_target(a|x): the CURRENT
  policy against the on-device target-network anchor (the paper's
  preferred of its three ratio choices — the anchor is what buys
  staleness tolerance under sample reuse). The PPO-style form

      -sum over T,B of min(r * A, clip(r, 1-eps, 1+eps) * A)

  bounds how far one (possibly replayed) batch can push the policy
  away from the anchor. Sum-reduced like every loss in this module
  (load-bearing for hyperparameter parity with the tuned LR).

  At the parity-gate operating point (target == current params, so
  log_ratio == 0 exactly and r == 1), the clip never binds and the
  gradient reduces to A * grad(log pi) — bit-identical to
  `compute_policy_gradient_loss`'s gradient (tests/test_replay.py
  pins this)."""
  advantages = jax.lax.stop_gradient(advantages)
  ratio = jnp.exp(log_ratio)
  unclipped = ratio * advantages
  clipped = jnp.clip(ratio, 1.0 - epsilon, 1.0 + epsilon) * advantages
  return -jnp.sum(jnp.minimum(unclipped, clipped))


def impact_clip_fraction(log_ratio, epsilon):
  """Fraction of (t, b) elements whose current/target ratio left the
  clip band — the reuse-health signal (≈0 fresh, climbing with
  staleness; persistently high means the target cadence or replay
  windows are too loose)."""
  ratio = jnp.exp(jax.lax.stop_gradient(log_ratio))
  outside = jnp.abs(ratio - 1.0) > epsilon
  return jnp.mean(outside.astype(jnp.float32))
