"""Learner: one jitted SGD step over a batch of actor unrolls.

Re-expresses the reference's `build_learner` (reference: experiment.py
≈L330–410) as a pure function over (TrainState, batch):

- the whole step — agent unroll over [T+1, B], V-trace, losses, RMSProp
  update — is ONE jit; V-trace runs on-device (the reference pins it to
  CPU with a comment that XLA could do better; here XLA does).
- the global step counts update steps on device; environment frames are
  `steps * batch * unroll * num_action_repeats` (reference counts frames
  directly, ≈L390) — same unit, computed host-side for reporting and
  in-schedule for the polynomial LR decay.
- the shift/overlap alignment (the 1-frame overlap between consecutive
  unrolls, reference ≈L285 + ≈L340) is factored into `align_batch` so it
  can be unit-tested against hand-indexed expectations.

Trajectory layout reminder (time-major [T+1, B]):
  env_outputs[i]  = o_i  (o_0 is the previous unroll's last frame)
  agent_outputs[i].action = a_{i-1} (action *before* o_i)
so rewards[1:] pair with values[:-1] and the bootstrap is V(o_T).
"""

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from scalable_agent_tpu import losses as losses_lib
from scalable_agent_tpu import popart as popart_lib
from scalable_agent_tpu import telemetry
from scalable_agent_tpu import unreal
from scalable_agent_tpu import vtrace
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.structs import ActorOutput

# Unified-registry telemetry (round 13): registered once at import —
# the registry replaces by name, so a per-call registration would
# reset the cumulative build count.
_STEP_FN_BUILDS = telemetry.counter('learner/step_fn_builds')
_FRAMES_PER_STEP = telemetry.gauge('learner/frames_per_step')


class TrainState(NamedTuple):
  params: Any
  opt_state: Any
  update_steps: Any  # i32 [] — device-side; frames derived host-side.
  popart: Any = None  # PopArtState when config.use_popart
  # IMPACT clipped-target anchor (config.surrogate='impact', round
  # 10): an on-device param copy refreshed every
  # config.target_update_interval steps by an in-graph select. None
  # (the vtrace default) is an empty pytree subtree, so existing
  # checkpoints and the vtrace state structure are unchanged.
  target_params: Any = None
  # PopArt stats snapshot taken WITH target_params at each refresh
  # (impact + use_popart only): apply_preservation rewrites only the
  # LIVE value head as the stats move, so the frozen anchor head must
  # be unnormalized with the stats it was frozen under — current
  # stats would mis-scale the V-trace values by the drift since the
  # last refresh.
  target_popart: Any = None


class VTraceInputs(NamedTuple):
  behaviour_logits: Any  # [T, B, A] — actor's logits at acting time
  target_logits: Any     # [T, B, A] — learner's logits, same steps
  actions: Any           # [T, B]    — actions actually taken
  discounts: Any         # [T, B]
  rewards: Any           # [T, B]    — clipped
  values: Any            # [T, B]    — learner baseline V(o_i)
  bootstrap_value: Any   # [B]       — V(o_T)


def clip_rewards(rewards, mode):
  """Reference reward clipping (experiment.py ≈L345)."""
  if mode == 'abs_one':
    return jnp.clip(rewards, -1.0, 1.0)
  elif mode == 'soft_asymmetric':
    squeezed = jnp.tanh(rewards / 5.0)
    return jnp.where(rewards < 0, 0.3 * squeezed, squeezed) * 5.0
  elif mode == 'none':
    return rewards
  raise ValueError(f'unknown reward clipping: {mode!r}')


def align_batch(env_outputs, agent_outputs, learner_outputs, config):
  """Shift the [T+1] trajectory into aligned [T] V-trace inputs.

  Mirrors reference build_learner ≈L335–355: bootstrap from the last
  learner baseline, actor/env tensors drop the overlap frame ([1:]),
  learner tensors drop the last frame ([:-1])."""
  bootstrap_value = learner_outputs.baseline[-1]
  actor_t = jax.tree_util.tree_map(lambda t: t[1:], agent_outputs)
  rewards = env_outputs.reward[1:]
  done = env_outputs.done[1:]
  learner_t = jax.tree_util.tree_map(lambda t: t[:-1], learner_outputs)

  clipped_rewards = clip_rewards(rewards, config.reward_clipping)
  discounts = (~done).astype(jnp.float32) * config.discounting
  return VTraceInputs(
      behaviour_logits=actor_t.policy_logits,
      target_logits=learner_t.policy_logits,
      actions=actor_t.action,
      discounts=discounts,
      rewards=clipped_rewards,
      values=learner_t.baseline,
      bootstrap_value=bootstrap_value)


def loss_fn(params, agent, batch: ActorOutput, config: Config,
            popart_state=None, mesh=None, target_params=None,
            target_popart=None, entropy_cost=None):
  """Total IMPALA loss for one batch; returns (loss, (metrics, aux)).

  `mesh` is the sharded step's mesh (train_parallel passes it; None on
  the single-device path). It only matters to the Pallas V-trace form,
  which runs under shard_map over the mesh's data axis — pallas_call
  has no SPMD partitioning rule of its own (vtrace.py).

  With PopArt (popart_state not None): the agent's baseline is the
  NORMALIZED per-task value; V-trace runs on the unnormalized σ·n + μ,
  the baseline loss in normalized space with the CURRENT statistics
  (the stats/preservation update happens in train_step, one step
  behind — standard PopArt ordering). aux carries the vs targets for
  that update.

  `target_params` (config.surrogate='impact', round 10 — IMPACT,
  arXiv 1912.00167): the clipped-target anchor. The V-trace IS ratios
  and value estimates then come from a SECOND forward pass through the
  anchor (rho = pi_target/mu, values/bootstrap from the target
  critic — both clipped exactly like the reference's rho-bar), the
  baseline loss regresses the CURRENT critic toward those vs targets,
  and the policy gradient is the PPO-style clipped
  pi_theta/pi_target surrogate (losses.compute_impact_surrogate_loss)
  instead of -log pi * A. Behavior-vs-target staleness is therefore
  handled per the paper: mu may lag arbitrarily (V-trace corrects it
  against the anchor), and theta may run ahead of the anchor only as
  far as the clip band allows.

  `entropy_cost` (round 23, the vectorized population): an optional
  TRACED override of config.entropy_cost — vmapping PBT members over
  one program needs the per-member hypers as array inputs, not baked
  constants. None (every non-population caller) keeps the config's
  compile-time constant, bit-identical to before."""
  task_ids = jnp.asarray(batch.level_name).astype(jnp.int32)
  use_pc = config.pixel_control_cost > 0
  if use_pc:
    ((learner_outputs, _), mutables) = agent.apply(
        params, batch.agent_outputs.action, batch.env_outputs,
        batch.agent_state, level_ids=task_ids,
        compute_pixel_control=True, mutable=['intermediates'])
    pc_q = mutables['intermediates']['pixel_control_q'][0]
  else:
    learner_outputs, _ = agent.apply(
        params, batch.agent_outputs.action, batch.env_outputs,
        batch.agent_state, level_ids=task_ids)

  if popart_state is not None:
    normalized = learner_outputs.baseline  # [T+1, B]
    unnormalized = popart_lib.unnormalize(popart_state, normalized,
                                          task_ids)
    learner_for_align = learner_outputs._replace(baseline=unnormalized)
  else:
    learner_for_align = learner_outputs
  inputs = align_batch(batch.env_outputs, batch.agent_outputs,
                       learner_for_align, config)

  use_impact = config.surrogate == 'impact' and target_params is not None
  metrics_extra = {}
  if use_impact:
    # Anchor forward pass: the target network's logits and baseline
    # over the same batch. target_params is a constant of this loss
    # (refreshed by train_step's cadence select), so no gradient
    # flows — stop_gradient makes that explicit for readers and for
    # any jvp reaching the anchor subtree.
    target_outputs, _ = agent.apply(
        target_params, batch.agent_outputs.action, batch.env_outputs,
        batch.agent_state, level_ids=task_ids)
    target_outputs = jax.lax.stop_gradient(target_outputs)
    if popart_state is not None:
      # Unnormalize with the stats snapshotted AT the anchor's refresh
      # (target_popart): preservation only rewrites the LIVE head as
      # stats drift, so current stats would mis-scale the frozen head
      # by sigma_now/sigma_refresh between refreshes.
      anchor_stats = (target_popart if target_popart is not None
                      else popart_state)
      target_for_align = target_outputs._replace(
          baseline=popart_lib.unnormalize(
              anchor_stats, target_outputs.baseline, task_ids))
    else:
      target_for_align = target_outputs
    # V-trace anchored on the target network: IS ratios pi_target/mu
    # (clipped at rho-bar like the reference) and the target critic's
    # values/bootstrap. The vs targets train the CURRENT critic below.
    vtrace_src = align_batch(batch.env_outputs, batch.agent_outputs,
                             target_for_align, config)
  else:
    vtrace_src = inputs
  vtrace_returns = vtrace.from_logits(
      behaviour_policy_logits=vtrace_src.behaviour_logits,
      target_policy_logits=vtrace_src.target_logits,
      actions=vtrace_src.actions,
      discounts=vtrace_src.discounts,
      rewards=vtrace_src.rewards,
      values=vtrace_src.values,
      bootstrap_value=vtrace_src.bootstrap_value,
      use_associative_scan=config.use_associative_scan,
      use_pallas=config.use_pallas_vtrace,
      mesh=mesh)
  if use_impact:
    log_ratio = (vtrace.log_probs_from_logits_and_actions(
        inputs.target_logits, inputs.actions) -
        vtrace_returns.target_action_log_probs)
    pg_loss = losses_lib.compute_impact_surrogate_loss(
        log_ratio, vtrace_returns.pg_advantages, config.impact_epsilon)
    metrics_extra['impact_clip_fraction'] = losses_lib.\
        impact_clip_fraction(log_ratio, config.impact_epsilon)
  else:
    pg_loss = losses_lib.compute_policy_gradient_loss(
        inputs.target_logits, inputs.actions,
        vtrace_returns.pg_advantages)
  if popart_state is not None:
    # Regress the normalized head toward normalized targets.
    norm_targets = popart_lib.normalize(
        popart_state, vtrace_returns.vs, task_ids)
    baseline_loss = losses_lib.compute_baseline_loss(
        jax.lax.stop_gradient(norm_targets) -
        learner_outputs.baseline[:-1])
  else:
    baseline_loss = losses_lib.compute_baseline_loss(
        vtrace_returns.vs - inputs.values)
  entropy_loss = losses_lib.compute_entropy_loss(inputs.target_logits)

  ec = config.entropy_cost if entropy_cost is None else entropy_cost
  total_loss = (pg_loss + config.baseline_cost * baseline_loss +
                ec * entropy_loss)
  metrics = {
      'total_loss': total_loss,
      'pg_loss': pg_loss,
      'baseline_loss': baseline_loss,
      'entropy_loss': entropy_loss,
  }
  metrics.update(metrics_extra)
  if use_pc:
    # UNREAL pixel control (unreal.py): pseudo-rewards from frame
    # deltas; action on the t→t+1 transition is agent_outputs[t+1]
    # (the [1:] slice — same alignment as the policy inputs).
    frames = batch.env_outputs.observation[0]
    # The opt-in integer-domain rewards need uint8 frames; any float
    # observation source falls back to the f32 reference form.
    use_int = (config.pixel_control_integer_rewards and
               frames.dtype == jnp.uint8)
    pc_rewards = unreal.pixel_control_rewards(
        frames, config.pixel_control_cell_size, integer_path=use_int)
    pc_loss = unreal.pixel_control_loss(
        pc_q, inputs.actions, pc_rewards,
        jnp.asarray(batch.env_outputs.done)[1:],
        discount=config.pixel_control_discount)
    total_loss = total_loss + config.pixel_control_cost * pc_loss
    metrics['pixel_control_loss'] = pc_loss
    metrics['total_loss'] = total_loss
  aux = {'vs': vtrace_returns.vs, 'task_ids': task_ids}
  return total_loss, (metrics, aux)


def param_fingerprint(params):
  """Cheap in-graph content fingerprint of a param tree: every leaf
  bit-cast to its same-width unsigned integer view and summed with
  uint32 wraparound (round 12 — the device half of the SDC sentinel).

  Properties the cross-replica check rests on:
  - EXACT: integer addition mod 2^32 is associative/commutative, so
    the value is independent of reduction order — two replicas holding
    bit-identical params ALWAYS produce equal fingerprints (a float
    reduction could not promise that).
  - SENSITIVE: any single flipped bit in any leaf changes the sum
    (one term changes by a power of two; collisions need a second
    compensating corruption).
  - CHEAP: one pass over the params, no host sync — it rides the
    step's dispatch stream and is read one step later with the other
    sentinels.

  8-byte leaves bitcast to uint32 PAIRS (trailing dim 2) so the graph
  never needs x64; bool leaves go through uint8."""
  total = jnp.zeros((), jnp.uint32)
  for leaf in jax.tree_util.tree_leaves(params):
    a = jnp.asarray(leaf)
    if a.size == 0:
      continue
    if a.dtype == jnp.bool_:
      bits = a.astype(jnp.uint8)
    else:
      itemsize = a.dtype.itemsize
      target = {1: jnp.uint8, 2: jnp.uint16}.get(itemsize, jnp.uint32)
      bits = jax.lax.bitcast_convert_type(a, target)
    total = total + jnp.sum(bits.astype(jnp.uint32))
  return total


def frames_per_step(config: Config):
  """Env frames consumed per SGD step (reference ≈L390)."""
  return config.frames_per_step


def make_schedule(config: Config):
  """Polynomial (linear) LR decay to 0 over total env frames, driven by
  the update-step count × frames-per-step (reference ≈L380–390). The
  single source of truth for the LR — used by both the optimizer and
  the logged `learning_rate` metric.

  Under sample reuse (round 10) the frame clock is FRESH env frames:
  each update consumes frames_per_step × (1 − replay_ratio)/replay_k
  of them at steady state, and the driver's frame budget counts fresh
  frames too — without this the schedule would hit zero at ~1/reuse
  of the run and train the rest at lr=0. Identical to frames_per_step
  with reuse off (the parity-gate operating point). The (1−ratio)/K
  factor assumes the tier sustains the configured composition: a
  chronically under-filled tier (tight staleness windows, cold start)
  serves extra fresh slots, so such a run exhausts its fresh-frame
  budget with the schedule only partly decayed — watch
  `replay_occupancy` vs capacity in summaries (RUNBOOK §5)."""
  fps = (float(config.frames_per_step) *
         (1.0 - config.replay_ratio) / config.replay_k)

  def schedule(count):
    frames = jnp.asarray(count).astype(jnp.float32) * fps
    frac = jnp.minimum(frames / float(config.total_environment_frames),
                       1.0)
    return config.learning_rate * (1.0 - frac)

  return schedule


def make_optimizer(config: Config):
  """RMSProp (+ optional global-norm clipping) with the frame-driven
  polynomial decay schedule."""
  opt = optax.rmsprop(
      learning_rate=make_schedule(config), decay=config.decay,
      eps=config.epsilon, momentum=config.momentum)
  if config.grad_clip_norm is not None:
    opt = optax.chain(
        optax.clip_by_global_norm(config.grad_clip_norm), opt)
  return opt


def make_train_state(params, config: Config,
                     num_popart_tasks: int = 0) -> TrainState:
  optimizer = make_optimizer(config)
  target = None
  if config.surrogate == 'impact':
    # DISTINCT buffers: the state is donated every step, and a target
    # leaf aliasing its param leaf would be donated twice. The copy
    # preserves the params' placement/sharding (eager copy follows its
    # input); make_sharded_train_state re-pins it explicitly anyway.
    target = jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                                    params)
  popart = (popart_lib.init(max(num_popart_tasks, 1))
            if config.use_popart else None)
  return TrainState(
      params=params,
      opt_state=optimizer.init(params),
      update_steps=jnp.zeros((), jnp.int32),
      popart=popart,
      target_params=target,
      target_popart=(jax.tree_util.tree_map(
          lambda x: jnp.array(x, copy=True), popart)
          if target is not None and popart is not None else None))


def make_train_step_fn(agent, config: Config, mesh=None,
                       traced_hypers: bool = False):
  """The raw (unjitted) train step: (TrainState, batch) → (state,
  metrics). Single source of truth — jitted plain here and with explicit
  shardings in parallel/train_parallel.py (which passes its mesh so the
  Pallas V-trace can shard_map over the data axis).

  `traced_hypers` (round 23, the vectorized population): the step
  becomes (state, batch, hypers) with hypers a dict of traced scalars
  {'learning_rate', 'entropy_cost'} — what lets jax.vmap carry N PBT
  members through ONE compiled program with per-member hypers as
  array inputs. The optimizer is built at unit learning rate (the
  schedule keeps its shape, so opt_state structure — and therefore
  checkpoints — interchange exactly with the baked-constant step) and
  the traced lr post-scales the updates. Exact for the config default
  momentum=0, and for any constant-lr run (optax.trace is linear);
  with momentum AND mid-round decay the lr applies one multiply later
  than the baked form — same first-order update, not bit-identical."""
  # Unified-registry telemetry (round 13): each build corresponds to
  # one XLA (re)compile of the step — a climbing count mid-run means
  # shape churn recompiling the hot path; frames_per_step is the
  # constant trace_report's throughput arithmetic divides by.
  _STEP_FN_BUILDS.inc()
  _FRAMES_PER_STEP.set(frames_per_step(config))
  if traced_hypers:
    # Unit-lr optimizer/schedule: schedule(count) is the pure decay
    # fraction; the member's traced lr multiplies it back in.
    unit_config = dataclasses.replace(config, learning_rate=1.0)
    optimizer = make_optimizer(unit_config)
    schedule = make_schedule(unit_config)
  else:
    optimizer = make_optimizer(config)
    schedule = make_schedule(config)

  def train_step(state: TrainState, batch: ActorOutput, hypers=None):
    if traced_hypers:
      lr = jnp.asarray(hypers['learning_rate'], jnp.float32)
      ec = jnp.asarray(hypers['entropy_cost'], jnp.float32)
    else:
      lr = None
      ec = None
    (total_loss, (metrics, aux)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params, agent, batch, config,
                               state.popart, mesh, state.target_params,
                               state.target_popart, ec)
    # Pre-clip norm: explosions must stay visible even with clipping on.
    metrics['grad_norm'] = optax.global_norm(grads)
    updates, new_opt_state = optimizer.update(
        grads, state.opt_state, state.params)
    if traced_hypers:
      updates = jax.tree_util.tree_map(lambda u: lr * u, updates)
    new_params = optax.apply_updates(state.params, updates)
    new_popart = state.popart
    if state.popart is not None:
      # PopArt: EMA the per-task moments toward this batch's targets,
      # then rewrite the value head so unnormalized outputs are
      # preserved exactly (popart.py).
      new_popart = popart_lib.update_stats(
          state.popart, aux['vs'], aux['task_ids'],
          beta=config.popart_beta)
      new_params = popart_lib.apply_preservation(
          new_params, state.popart, new_popart)
      # Stability observability (the soak asserts these stay bounded):
      # a diverging value scale shows up here long before NaNs.
      sig = popart_lib.sigma(new_popart)
      metrics['popart_sigma_min'] = jnp.min(sig)
      metrics['popart_sigma_max'] = jnp.max(sig)
    if config.health_watchdog:
      # Device-side sentinel + skip (health.py): a non-finite loss or
      # grad norm means this update would poison the params — keep the
      # old state wholesale instead. One `where` per leaf; identity on
      # healthy steps, no host sync. The step counter still advances
      # (the batch's frames were consumed either way), so the
      # step/frame accounting stays monotone through skips.
      step_ok = (jnp.isfinite(total_loss) &
                 jnp.isfinite(metrics['grad_norm']))

      def keep(new, old):
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(step_ok, n, o), new, old)

      new_params = keep(new_params, state.params)
      new_opt_state = keep(new_opt_state, state.opt_state)
      if new_popart is not None:
        new_popart = keep(new_popart, state.popart)
      metrics['step_ok'] = step_ok.astype(jnp.float32)
    new_target = state.target_params
    new_target_popart = state.target_popart
    if state.target_params is not None:
      # Target-network refresh on its own cadence (IMPACT round 10):
      # an in-graph select — the version-gated publish pattern applied
      # to the on-device anchor (a non-refresh step copies nothing; a
      # refresh is one select per leaf, no host round trip). Runs
      # AFTER the watchdog keep() so a skipped step's anchor snapshots
      # the kept (old) params, never a withheld non-finite update.
      # With interval=1 the anchor entering step N+1 IS the params
      # entering step N+1 — the parity-gate operating point.
      refresh = ((state.update_steps + 1) %
                 config.target_update_interval) == 0
      new_target = jax.tree_util.tree_map(
          lambda p, t: jnp.where(refresh, p, t),
          new_params, state.target_params)
      if state.target_popart is not None:
        # The stats snapshot refreshes WITH the anchor head — the pair
        # is what unnormalizes the frozen baseline exactly (loss_fn).
        new_target_popart = jax.tree_util.tree_map(
            lambda p, t: jnp.where(refresh, p, t),
            new_popart, state.target_popart)
    new_state = TrainState(new_params, new_opt_state,
                           state.update_steps + 1, new_popart,
                           new_target, new_target_popart)
    metrics['learning_rate'] = (
        lr * schedule(state.update_steps) if traced_hypers
        else schedule(state.update_steps))
    return new_state, metrics

  return train_step


def make_train_step(agent, config: Config):
  """Jitted single-device train step; donates the state for in-place
  HBM update. `batch` is an ActorOutput pytree of [T+1, B] time-major
  arrays (plus agent_state [B, ...])."""
  return jax.jit(make_train_step_fn(agent, config), donate_argnums=(0,))
