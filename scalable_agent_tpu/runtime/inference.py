"""Batched inference server: many actor threads, one jitted TPU call.

The reference reaches ~3× single-machine throughput by transparently
merging ~48 concurrent batch-1 `Agent._build` calls into one GPU call
via the C++ Batcher op (reference: experiment.py ≈L470–482 monkey-patch
+ dynamic_batching.py). This is the TPU-native equivalent:

- actor threads call `policy(prev_action, env_output, core_state)`
  (the `runtime.actor.Actor` contract) and block;
- the C++ batcher (ops/batcher) merges concurrent calls;
- a dispatch thread runs the jitted single-step agent on the merged
  batch on TPU; a completion thread reads results back and unparks
  the callers.

XLA needs static shapes, so merged batches are padded up to the next
power of two (capped at maximum_batch_size) before the jitted call and
sliced after — a handful of compiled shapes total, no recompiles in
steady state (the reference's TF graph handled dynamic batch dims
natively; bucketing is the XLA-idiomatic trade).

Round 7 overhaul (docs/INFERENCE.md) — three independent levers:

1. Device-resident core-state cache (config.inference_state_cache):
   instead of shipping the LSTM carry host→device and the new carry
   device→host on EVERY env step, each actor owns a slot in an
   on-device `[slots, hidden]` state arena; the jitted step gathers
   carries by slot id, computes, and scatters the new carries back
   in-graph (Podracer, arXiv:2104.06272). The per-step wire drops to
   (action, reward, done, frame, instr, slot_id); the carry crosses
   the host boundary only once per unroll (the learner needs the
   unroll-start state — `_SlotHandle.snapshot()`). Numerics-identical
   to the carry-passing path (golden parity gate in
   tests/test_runtime.py, done edges + respawn slot reuse + the
   sharded-eval mesh included); done-reset stays in-graph via the
   agent's `_ResetCore`.
2. Pipelined dispatch (config.inference_pipeline_depth, default 2):
   dispatch and completion are separate threads with a depth
   semaphore between them, so merged batch k+1 assembles and lands on
   device while batch k computes — the actor-plane mirror of
   `BatchPrefetcher`'s H2D/compute overlap. Depth 1 reproduces the
   old serialized assemble→dispatch→device_get loop.
3. Zero-copy merge staging: the C++ batcher's merge-copy lands
   directly in preallocated per-bucket padded staging buffers
   (`Batcher.get_batch_into`) — no per-call np.concatenate, no
   per-call allocation — and the PRNG key lives on device, split
   in-graph by the jitted step instead of per-call on the host.

Weights: the server holds a params snapshot updated via
`update_params` (the reference's gRPC weight fetch becomes an on-host
pointer swap; the same "actions within one unroll may span weight
versions" caveat applies — reference ≈L472 comment).

Round 9 (actor-plane overload hardening, docs/ROBUSTNESS.md): slot
ADMISSION CONTROL replaces raise-on-exhaustion. `_acquire_slot` parks
callers on a priority-ordered bounded waitlist instead of raising
`RuntimeError('state arena exhausted')` — exhaustion now DEGRADES per
`config.inference_admission`:

  block  (default) wait (deadline-bounded, capped-jitter re-check via
         runtime.remote.Backoff) for a released slot; raise
         `SlotUnavailable` only at the deadline.
  shed   same parked wait, but the deadline REJECTION is the intended
         steady-state response to overload: counted in
         stats()['sheds'] and the driver's `inference_sheds` summary —
         the serving-plane load-shedding seam (TorchBeast's decoupled
         actor/server split, arXiv:1910.03552).
  grow   never park: the arena doubles in place (one recompile per
         growth, counted in stats()['arena_grows']).

Waiters carry a PRIORITY class (PRIORITY_LIVE < PRIORITY_RESPAWN <
PRIORITY_EVAL): releases hand the freed slot to the best-priority
waiter directly, so eval/respawn churn can never starve live actor
traffic. `close()` answers every parked waiter with `InferenceClosed`
(never leaves them blocked forever) and counts worker threads that
missed their join deadline (stats()['unjoined_threads']).

Round 21 (multi-tenant serving plane, docs/INFERENCE.md): the single
resident params snapshot generalizes to a VERSION TABLE —
`config.serving_resident_versions` policy versions resident
concurrently (LRU eviction of unpinned, non-live entries under the
count cap and the optional `serving_hbm_budget_mb` byte budget), with
per-version serve counters, A/B assignment
(`serving_ab_fraction` of merged calls served by the newest non-live
candidate — assignment is at merged-call granularity because the C++
batcher merges rows from many actors into one call), and SHADOW
traffic: `serving_shadow_fraction` of merged calls are ALSO replayed
against a shadow version through a PURE step (no key chain, no arena
scatter) and scored against live on GREEDY action agreement — the
`serving/shadow_divergence` gauge (sampled actions would differ by
RNG alone, so only argmax isolates the version delta). A version
re-published while still resident flips live WITHOUT a tree copy
(stats()['version_flips']); `publish_codec=int8` stores table entries
quantized (runtime/codec.py — ~4x more resident versions per byte,
dequantized in-graph by the serving step). `serving_aot=True`
pre-compiles serving steps per (batch-bucket, params-structure) at
publish time via the jit lower/compile seam (parallel/fit.py's AOT
pattern), so a version flip to a new dtype structure — or a warmed
bucket under a flipped structure — never pays first-call compile on
the serve path (misses fall back to the jit cache and count
stats()['aot_misses']). `serve_remote` serves carry-passing batches
from the same table for the wire-v10 routed inference service
(runtime/routing.py).
"""

import collections
import logging
import queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock
from scalable_agent_tpu.observability import LatencyReservoir
from scalable_agent_tpu.ops import dynamic_batching
from scalable_agent_tpu.runtime import codec as codec_lib
from scalable_agent_tpu.runtime import faults as faults_lib
from scalable_agent_tpu.runtime.remote import Backoff
from scalable_agent_tpu.structs import AgentOutput, StepOutput

log = logging.getLogger('scalable_agent_tpu')

# Serving-plane telemetry (round 21; docs/OBSERVABILITY.md inventory).
# Merged-call service latency also feeds the serving_latency_p99_ms
# SLO objective — admission is its actuator (controller.DEFAULT_RULES).
_SERVE_LATENCY = telemetry.histogram('serving/latency_ms')
_SHADOW_DIVERGENCE = telemetry.gauge('serving/shadow_divergence')
_SHADOW_CALLS = telemetry.counter('serving/shadow_calls')
_AB_CALLS = telemetry.counter('serving/ab_calls')
_EVICTIONS = telemetry.counter('serving/evictions')
_VERSION_FLIPS = telemetry.counter('serving/version_flips')
_RESIDENT_VERSIONS = telemetry.gauge('serving/resident_versions')
_AOT_MISSES = telemetry.counter('serving/aot_misses')

# Admission priority classes (lower = served first): a released slot
# is handed to the best-priority parked waiter, so background churn
# (respawns, eval fleets sharing a server) cannot starve live actors.
PRIORITY_LIVE = 0
PRIORITY_RESPAWN = 1
PRIORITY_EVAL = 2

ADMISSION_POLICIES = ('block', 'shed', 'grow')

# Padded merge rows scatter/gather with this slot id: ALWAYS out of
# range (gather clamps, scatter mode='drop' discards), and — unlike
# the old `num_slots` stamp — still out of range after a 'grow'
# admission doubles the arena between staging and dispatch.
_PAD_SLOT_ID = np.int32(1 << 30)


class SlotUnavailable(RuntimeError):
  """No state-arena slot could be admitted before the deadline (shed
  policy: the intended overload response; block policy: the bounded-
  wait backstop). Fleet respawn treats this as pause-and-retry, never
  as a learner-loop crash."""


class InferenceClosed(RuntimeError):
  """The server closed while the caller was parked on the admission
  waitlist — a clean shutdown answer, not an overload signal."""


class _Waiter:
  """One parked `_acquire_slot` caller: priority + FIFO tiebreak, an
  event the release path sets on direct slot handoff, and the closed
  flag `close()` answers parked callers with."""

  __slots__ = ('priority', 'seq', 'event', 'slot', 'closed')

  def __init__(self, priority, seq):
    self.priority = priority
    self.seq = seq
    self.event = threading.Event()
    self.slot = None
    self.closed = False


def _next_power_of_two(n):
  p = 1
  while p < n:
    p *= 2
  return p


def _tree_nbytes(tree):
  """Leaf-byte total WITHOUT a device transfer (jax and numpy arrays
  both expose .nbytes) — the version table's HBM-budget accounting
  runs on every publish, so it must not device_get the tree."""
  total = 0
  for leaf in jax.tree_util.tree_leaves(tree):
    nbytes = getattr(leaf, 'nbytes', None)
    if nbytes is None:
      nbytes = np.asarray(leaf).nbytes
    total += int(nbytes)
  return total


def _params_fingerprint(params):
  """Hashable structure key for the AOT executable table: treedef +
  per-leaf dtypes. Two versions with the same fingerprint share
  compiled steps (the common case: every fp32 publish); an int8
  publish (Int8Leaf nodes change the treedef AND the dtypes) maps to
  its own executables."""
  leaves, treedef = jax.tree_util.tree_flatten(params)
  return (treedef,
          tuple(str(getattr(l, 'dtype', type(l).__name__))
                for l in leaves))


def percentile_ms(sorted_secs_or_ms, q, scale=1.0):
  """q-th percentile of an ascending list (nearest-rank, clamped) ×
  scale — the ONE implementation behind stats() and the bench rows, so
  the accept/reject numbers are computed identically everywhere."""
  if not sorted_secs_or_ms:
    return 0.0
  n = len(sorted_secs_or_ms)
  return sorted_secs_or_ms[min(n - 1, int(n * q))] * scale


class _SlotHandle:
  """An actor's claim on one state-arena slot (state-cache mode).

  Opaque under the `runtime.actor.Actor` core-state contract; the
  actor only touches the duck-typed surface:

  - `snapshot()`: the slot's carry as host numpy `(c[1,H], h[1,H])` —
    the once-per-unroll read the learner's `agent_state` needs.
  - `write(carry)`: overwrite the slot (the actor's priming-call
    undo).
  - `release()`: return the slot to the free list (idempotent). The
    slot is zeroed again on the NEXT acquire, so a reclaimed slot can
    never serve a stale carry.
  """

  __slots__ = ('_server', 'slot', 'released')

  def __init__(self, server, slot):
    self._server = server
    self.slot = slot
    self.released = False

  def snapshot(self):
    if self.released:
      # A released slot may already be serving its next owner (the
      # waitlist hands freed slots over directly): a straggler thread
      # must fail here, not read someone else's carry.
      raise RuntimeError('snapshot() on a released state slot')
    return self._server._read_slot(self.slot)

  def write(self, carry):
    if self.released:
      raise RuntimeError('write() on a released state slot')
    self._server._write_slot(self.slot, carry)

  def release(self):
    if not self.released:
      self.released = True
      self._server._release_slot(self.slot)

  def __repr__(self):
    return (f'_SlotHandle(slot={self.slot}, '
            f'released={self.released})')


class _VersionEntry:
  """One resident policy version in the serving table: the (owned,
  possibly int8-quantized) params copy, its publish key, the pin
  flag eviction honours, the per-version serve counter, its leaf
  bytes (the HBM-budget accounting) and the LRU tick."""

  __slots__ = ('key', 'params', 'pinned', 'serves', 'nbytes', 'tick')

  def __init__(self, key, params, nbytes, tick):
    self.key = key
    self.params = params
    self.pinned = False
    self.serves = 0
    self.nbytes = nbytes
    self.tick = tick

  def label(self):
    """Stable stats() key: the numeric publish version, 'anon-N' for
    None-version publishes (the dedup-less always-publish path), or
    '<seed>' for the constructor's by-reference sentinel entry."""
    if isinstance(self.key, int):
      return self.key
    if isinstance(self.key, tuple) and self.key and self.key[0] == 'anon':
      return f'anon-{self.key[1]}'
    return '<seed>'


class InferenceServer:
  """Serves a batched policy for host actor threads.

  Args:
    agent: ImpalaAgent (flax module).
    params: initial parameter pytree (host or device).
    config: Config (uses inference_* knobs).
    seed: PRNG seed for action sampling.
    mesh: optional jax.sharding.Mesh — merged inference batches shard
      over its data axis (params replicated), so concurrent eval of
      many envs uses every chip instead of one (VERDICT r2 W6: the
      reference's test() is batch-1 serial; sharded batched eval is
      TPU headroom it never had). Padded batch sizes round up to a
      multiple of the data width.
    pad_batch_to: optional floor on the padded batch size — every
      merged batch pads up to (at least) this bucket, so the server
      compiles exactly ONE program instead of one per power-of-two
      bucket (VERDICT r3 W5: eval warmed 6 buckets ≈ 2–4 min of
      serial 20–40 s compiles before the first episode). The padding
      FLOPs are noise next to one avoided compile; use where the
      steady-state merged size is known (eval: all levels step
      concurrently), not for training fleets whose merge size is the
      tuning signal.
    fleet_size: number of actor threads this server will serve —
      consulted when config.inference_min_batch == 0 (AUTO merge
      floor; see the constructor comment) and when sizing the state
      arena (config.inference_state_slots == 0).
  """

  # Lock discipline (round 18; enforced by the guarded-by lint and,
  # armed, by OrderedLock's inversion detector). Documented order
  # where nested: _slot_lock -> _arena_lock and _slot_lock ->
  # _stats_lock (the admission path), _key_lock -> _arena_lock
  # (dispatch), _params_lock -> _stats_lock (publish-skip). Nothing
  # takes _slot_lock after any other lock.
  # Round 21: the version table and its A/B + shadow assignment state
  # live under _params_lock (the picker runs where the old single-
  # snapshot read ran); the AOT executable table under _aot_lock; the
  # routed-serving key counter under _remote_lock. None of the new
  # locks nests inside (or outside) another serving lock.
  _versions: guarded_by('_params_lock')
  _live_key: guarded_by('_params_lock')
  _serve_tick: guarded_by('_params_lock')
  _anon_seq: guarded_by('_params_lock')
  _ab_fraction: guarded_by('_params_lock')
  _ab_key: guarded_by('_params_lock')
  _ab_acc: guarded_by('_params_lock')
  _shadow_fraction: guarded_by('_params_lock')
  _shadow_key: guarded_by('_params_lock')
  _shadow_acc: guarded_by('_params_lock')
  _aot: guarded_by('_aot_lock')
  _warm_meta: guarded_by('_aot_lock')
  _warm_buckets: guarded_by('_aot_lock')
  _remote_calls: guarded_by('_remote_lock')
  _key: guarded_by('_key_lock')
  _arena: guarded_by('_arena_lock')
  _free: guarded_by('_slot_lock')
  _waiters: guarded_by('_slot_lock')
  _waiter_seq: guarded_by('_slot_lock')
  _closed: guarded_by('_slot_lock')
  _admission: guarded_by('_slot_lock')
  # The grow path swaps the arena (and its size) holding BOTH
  # _slot_lock and _arena_lock, so readers under either are safe.
  _num_slots: guarded_by('_slot_lock', '_arena_lock')
  _calls: guarded_by('_stats_lock')
  _merged_requests: guarded_by('_stats_lock')
  _params_version: guarded_by('_stats_lock')
  _publishes_skipped: guarded_by('_stats_lock')
  _devices_last_call: guarded_by('_stats_lock')
  _inflight: guarded_by('_stats_lock')
  _inflight_peak: guarded_by('_stats_lock')
  _acquires: guarded_by('_stats_lock')
  _admission_waits: guarded_by('_stats_lock')
  _sheds: guarded_by('_stats_lock')
  _admission_timeouts: guarded_by('_stats_lock')
  _arena_grows: guarded_by('_stats_lock')
  _unjoined_threads: guarded_by('_stats_lock')
  _latencies: guarded_by('_stats_lock')
  _chain_recoveries: guarded_by('_stats_lock')
  _version_flips: guarded_by('_stats_lock')
  _evictions: guarded_by('_stats_lock')
  _ab_calls: guarded_by('_stats_lock')
  _shadow_calls: guarded_by('_stats_lock')
  _shadow_divergence: guarded_by('_stats_lock')
  _aot_misses: guarded_by('_stats_lock')

  def __init__(self, agent, params, config, seed=0, mesh=None,
               pad_batch_to=None, fleet_size=None):
    self._pad_floor = pad_batch_to
    # inference_min_batch == 0 means AUTO: floor the merge at the
    # local fleet size, so every inference call carries the whole
    # fleet and per-call dispatch amortizes fully (measured +53% e2e
    # fps at the bench operating point — docs/PERF.md round-5 batcher
    # sweep). inference_timeout_ms bounds the wait when an actor is
    # mid-unroll-publish or being respawned, so the floor degrades to
    # a latency cap, never a deadlock.
    min_batch = config.inference_min_batch
    if min_batch == 0:
      min_batch = max(fleet_size or 1, 1)
    self._min_batch = min(min_batch, config.inference_max_batch)
    self._agent = agent
    self._core_sizes = (agent.hidden_size, agent.hidden_size)  # (c, h)
    self._mesh = mesh
    self._state_cache = bool(config.inference_state_cache)
    self._depth = max(1, int(config.inference_pipeline_depth))
    # --- Slot admission policy (overload hardening; module docstring).
    self._admission = getattr(config, 'inference_admission', 'block')
    if self._admission not in ADMISSION_POLICIES:
      raise ValueError(
          f'unknown inference_admission {self._admission!r} '
          f'(policies: {ADMISSION_POLICIES})')
    self._admission_timeout = float(
        getattr(config, 'inference_admission_timeout_secs', 10.0))
    if mesh is not None:
      # Arena placements come from the sharding registry's primitive
      # helpers (round 19): params replicated over the acting mesh,
      # batch rows over the data axis — no private layout choice here.
      from scalable_agent_tpu.parallel import sharding as sharding_lib
      self._dp = int(mesh.shape[sharding_lib.DATA_AXIS])
      self._replicated = sharding_lib.replicated(mesh)
      self._batch_sharding = sharding_lib.data_sharding(mesh)
      params = jax.device_put(params, self._replicated)
    else:
      self._dp = 1
    self._params_lock = make_lock('inference._params_lock')
    # --- Serving version table (round 21; module docstring). The
    # constructor's params enter BY REFERENCE under a sentinel key no
    # caller-supplied version can equal, so the FIRST update_params
    # always lands a fresh owned copy (donation safety — see
    # update_params; the sentinel is process memory on purpose and
    # does NOT survive a checkpoint restore, tests/test_serving.py
    # pins why).
    self._resident_cap = max(1, int(
        getattr(config, 'serving_resident_versions', 1)))
    self._hbm_budget_bytes = int(
        float(getattr(config, 'serving_hbm_budget_mb', 0.0)) * 1e6)
    self._quantize_resident = (
        getattr(config, 'publish_codec', 'bf16') == 'int8')
    self._serve_tick = 0
    self._anon_seq = 0
    self._versions = collections.OrderedDict()
    seed_key = object()
    self._live_key = seed_key
    self._versions[seed_key] = _VersionEntry(
        seed_key, params, _tree_nbytes(params), 0)
    # A/B + shadow assignment (merged-call granularity — the batcher
    # merges many actors into one call, so per-request assignment
    # does not exist at this layer).
    self._ab_fraction = float(
        getattr(config, 'serving_ab_fraction', 0.0))
    self._ab_key = None      # None = auto: newest non-live resident
    self._ab_acc = 0.0
    self._shadow_fraction = float(
        getattr(config, 'serving_shadow_fraction', 0.0))
    self._shadow_key = None  # None = auto: newest non-live resident
    self._shadow_acc = 0.0
    # Per-bucket AOT serving executables (round 21): (padded bucket,
    # params-structure fingerprint) -> compiled step. Populated by
    # _precompile_params at publish/warmup time; _dispatch falls back
    # to the jit cache (and counts the miss) when absent.
    self._serving_aot = bool(getattr(config, 'serving_aot', False))
    self._aot_lock = make_lock('inference._aot_lock')
    self._aot = {}
    self._warm_meta = None
    self._warm_buckets = set()
    # Routed-serving (wire v10) RNG: a dedicated per-call fold chain,
    # so cross-host requests never perturb the local fleet's key.
    self._remote_lock = make_lock('inference._remote_lock')
    self._remote_calls = 0
    self._remote_base_key = jax.random.PRNGKey(seed + 424_243)
    self._stats_lock = make_lock('inference._stats_lock')
    self._version_flips = 0
    self._evictions = 0
    self._ab_calls = 0
    self._shadow_calls = 0
    self._shadow_divergence = 0.0
    self._aot_misses = 0
    self._calls = 0
    self._merged_requests = 0
    self._params_version = 0
    self._publishes_skipped = 0
    self._devices_last_call = 0
    self._inflight = 0
    self._inflight_peak = 0
    # Admission counters (stats(); the driver's summary surface).
    self._acquires = 0
    self._admission_waits = 0      # acquires that had to park
    self._sheds = 0                # shed policy: deadline rejections
    self._admission_timeouts = 0   # block policy: deadline rejections
    self._arena_grows = 0
    self._unjoined_threads = 0
    self._admission_wait_reservoir = LatencyReservoir(maxlen=1024)
    # Per-merged-call latency ring (assembly start → callers unparked)
    # for the stats() p50/p99 — bounded so a week-long run's stats
    # reflect RECENT service time, not the cumulative history.
    self._latencies = collections.deque(maxlen=512)
    # _key is a DEVICE array chained through the jitted step (split
    # in-graph); the lock orders warmup (caller thread) against the
    # dispatch thread. Same split sequence as the old host-side
    # jax.random.split — numerics unchanged.
    self._key_lock = make_lock('inference._key_lock')
    self._key = jax.random.PRNGKey(seed)
    self._base_seed = seed
    self._chain_recoveries = 0
    self._max_batch = config.inference_max_batch

    # --- Device-resident state arena (state-cache mode). ---
    # Lock order where nested: _slot_lock -> _arena_lock (the grow
    # path swaps the arena while holding the free list); _key_lock ->
    # _arena_lock (dispatch). Nothing takes _slot_lock after either.
    self._arena_lock = make_lock('inference._arena_lock')
    self._slot_lock = make_lock('inference._slot_lock')
    self._waiters = []          # parked _acquire_slot callers
    self._waiter_seq = 0
    if self._state_cache:
      num_slots = int(config.inference_state_slots)
      if num_slots <= 0:
        # Auto: 2× the fleet (respawn headroom — a wedged actor's slot
        # frees only when its orphaned thread unwinds) with a floor,
        # covering eval servers sized by pad_batch_to instead of
        # fleet_size.
        num_slots = max(2 * max(fleet_size or 0, pad_batch_to or 0), 8)
      self._num_slots = num_slots
      self._free = list(range(num_slots))
      arena = tuple(jnp.zeros((num_slots, s), jnp.float32)
                    for s in self._core_sizes)
      if mesh is not None:
        arena = jax.device_put(arena, self._replicated)
      self._arena = arena
    else:
      self._num_slots = 0
      self._free = []
      self._arena = None
    if mesh is not None:
      self._key = jax.device_put(self._key, self._replicated)

    def _apply(params, sub, prev_action, reward, done, frame, instr,
               core_c, core_h):
      # Int8-resident versions (publish_codec=int8) dequantize HERE,
      # in-graph: XLA fuses the per-leaf multiply into the step, so
      # serving a quantized version costs no host round trip. Identity
      # for plain trees.
      params = codec_lib.dequantize_tree(params)
      env_output = StepOutput(
          reward=reward[None], info=None, done=done[None],
          observation=(frame[None], instr[None]))
      out, (new_c, new_h) = agent.apply(
          params, prev_action[None], env_output, (core_c, core_h),
          sample_rng=sub)
      return (out.action[0], out.policy_logits[0], out.baseline[0],
              new_c, new_h)

    def carry_step(params, key, prev_action, reward, done, frame,
                   instr, core_c, core_h):
      key, sub = jax.random.split(key)
      action, logits, baseline, new_c, new_h = _apply(
          params, sub, prev_action, reward, done, frame, instr,
          core_c, core_h)
      return key, action, logits, baseline, new_c, new_h

    def cache_step(params, key, arena_c, arena_h, slot_ids,
                   prev_action, reward, done, frame, instr):
      key, sub = jax.random.split(key)
      # Gather each row's carry by slot id. Padded rows carry
      # _PAD_SLOT_ID (out of range for any arena size, grown or not):
      # the gather clamps (their compute is sliced away) and the
      # scatter DROPS them — mode='drop' is what keeps a padded row
      # from ever corrupting a live slot.
      core_c = arena_c[slot_ids]
      core_h = arena_h[slot_ids]
      action, logits, baseline, new_c, new_h = _apply(
          params, sub, prev_action, reward, done, frame, instr,
          core_c, core_h)
      arena_c = arena_c.at[slot_ids].set(new_c, mode='drop')
      arena_h = arena_h.at[slot_ids].set(new_h, mode='drop')
      return key, arena_c, arena_h, action, logits, baseline

    step = cache_step if self._state_cache else carry_step
    num_batch_args = 6 if self._state_cache else 7
    if mesh is None:
      self._step = jax.jit(step)
    else:
      # params keep their (replicated) placement; the key (and the
      # state arena) are replicated; batch args shard dim 0 over the
      # data axis.
      if self._state_cache:
        in_shardings = (None, self._replicated, self._replicated,
                        self._replicated) + \
            (self._batch_sharding,) * num_batch_args
        out_shardings = (self._replicated,) * 3 + \
            (self._batch_sharding,) * 3
      else:
        in_shardings = (None, self._replicated) + \
            (self._batch_sharding,) * num_batch_args
        out_shardings = (self._replicated,) + \
            (self._batch_sharding,) * 5
      self._step = jax.jit(step, in_shardings=in_shardings,
                           out_shardings=out_shardings)

    # Shadow step (round 21): PURE — no key split chained back, no
    # arena scatter — so replaying a merged call against a shadow
    # version can never perturb the live fleet's RNG stream or
    # carries. Scored on GREEDY agreement downstream, so the fixed
    # sample key is irrelevant to the gauge.
    def shadow_carry(params, prev_action, reward, done, frame, instr,
                     core_c, core_h):
      sub = jax.random.PRNGKey(0)
      _, logits, _, _, _ = _apply(params, sub, prev_action, reward,
                                  done, frame, instr, core_c, core_h)
      return logits

    def shadow_cache(params, arena_c, arena_h, slot_ids, prev_action,
                     reward, done, frame, instr):
      sub = jax.random.PRNGKey(0)
      core_c = arena_c[slot_ids]
      core_h = arena_h[slot_ids]
      _, logits, _, _, _ = _apply(params, sub, prev_action, reward,
                                  done, frame, instr, core_c, core_h)
      return logits

    self._shadow_step = jax.jit(
        shadow_cache if self._state_cache else shadow_carry)
    # Routed-serving step (serve_remote): always carry-passing — the
    # remote caller owns its carry; a cross-host request must never
    # consume a local arena slot.
    self._remote_step = jax.jit(carry_step)
    # AOT lower/compile inputs (see _precompile_params): the key's
    # spec is fixed at construction; _step is the jit object lowered.
    self._key_spec = jax.ShapeDtypeStruct(
        np.shape(jax.random.PRNGKey(0)),
        np.asarray(jax.random.PRNGKey(0)).dtype)

    # --- Pipelined dispatch plane: the C++ batcher merges concurrent
    # policy() calls; the dispatch thread copies each merged batch
    # into a padded staging buffer (zero-copy via get_batch_into),
    # dispatches the jitted step (async), and moves on to assemble
    # the next batch; the completion thread reads results back in
    # FIFO order and unparks the callers. The semaphore bounds
    # dispatched-but-uncompleted batches at `depth`. ---
    self._staging = {}        # padded size -> ring of buffer lists
    self._staging_calls = {}  # padded size -> calls (ring index)
    self._batcher = dynamic_batching.Batcher(
        num_tensors=num_batch_args,
        minimum_batch_size=self._min_batch,
        maximum_batch_size=config.inference_max_batch,
        timeout_ms=config.inference_timeout_ms)
    self._sem = threading.Semaphore(self._depth)
    self._completion_q = queue.Queue()
    self._closed = False
    self._dispatch_thread = threading.Thread(
        target=self._dispatch_loop, name='inference-dispatch',
        daemon=True)
    self._completion_thread = threading.Thread(
        target=self._completion_loop, name='inference-completion',
        daemon=True)
    self._dispatch_thread.start()
    self._completion_thread.start()

  # -- state arena (state-cache mode) --

  def initial_core_state(self, priority=PRIORITY_LIVE):
    """Per-actor policy-state factory (driver.make_fleet's
    initial_state_fn): zeroed host carry in carry-passing mode, a
    freshly acquired (zeroed) arena slot in state-cache mode. Called
    at actor (re)spawn — a respawned actor starts from a clean slot
    either way. `priority` is the admission class of the acquire
    (PRIORITY_LIVE / PRIORITY_RESPAWN / PRIORITY_EVAL — released
    slots go to the best-priority parked waiter first)."""
    if not self._state_cache:
      return tuple(np.zeros((1, s), np.float32)
                   for s in self._core_sizes)
    return self._acquire_slot(priority=priority)

  @property
  def admission(self) -> str:
    """The live admission policy (the controller's actuator get
    path). Round 18: read under _slot_lock like every other
    _admission access — the bare read was GIL-atomic but violated
    the declared guarded_by discipline (found by the lint)."""
    with self._slot_lock:
      return self._admission

  def set_admission(self, mode: str) -> str:
    """Thread-safe live admission-policy flip (round 15: the
    controller's overload actuator). Takes effect for every acquire
    that has not yet chosen its path; callers already PARKED on the
    waitlist keep their original deadline semantics (block→shed
    mid-park changes only how their deadline rejection is counted;
    →grow lets the next arriving acquire grow the arena, which then
    hands slots to the parked waiters through the normal release
    path). Returns the previous mode."""
    if mode not in ADMISSION_POLICIES:
      raise ValueError(f'unknown inference_admission {mode!r} '
                       f'(policies: {ADMISSION_POLICIES})')
    with self._slot_lock:
      old = self._admission
      self._admission = mode
    if old != mode:
      log.warning('inference admission policy: %s -> %s', old, mode)
    return old

  def _acquire_slot(self, priority=PRIORITY_LIVE):
    """Admit one slot acquisition under the configured policy (module
    docstring): fast-path pop when slots are free and nobody is parked
    ahead of us, else grow (grow policy) or park on the priority
    waitlist (block/shed) with a deadline. Raises SlotUnavailable at
    the deadline, InferenceClosed when the server shuts down — never
    the old bare 'state arena exhausted' RuntimeError."""
    # Fault site 'slot_exhaustion' (runtime/faults.py): a fired fault
    # forces this acquire down the contended path even when slots are
    # free — the parked waiter re-checks the real free list on its
    # next backoff tick, so the forced detour is bounded and the
    # waitlist machinery executes under test.
    forced = faults_lib.fire('slot_exhaustion') is not None
    waiter = None
    with self._slot_lock:
      if self._closed:
        raise InferenceClosed('inference server is closed')
      with self._stats_lock:
        self._acquires += 1
      if not forced and self._free and not self._waiters:
        slot = self._free.pop()
      elif self._admission == 'grow':
        if forced or not self._free:
          self._grow_arena_locked()
        slot = self._free.pop()
      else:
        self._waiter_seq += 1
        waiter = _Waiter(priority, self._waiter_seq)
        self._waiters.append(waiter)
        with self._stats_lock:
          self._admission_waits += 1
    if waiter is not None:
      slot = self._wait_for_slot(waiter)
    self._zero_slot(slot)
    return _SlotHandle(self, slot)

  def _best_waiter_locked(self):
    """Called with _slot_lock held; waitlists are fleet-sized."""
    return min(self._waiters, key=lambda w: (w.priority, w.seq))

  def _wait_for_slot(self, waiter):
    """Park until a released slot is handed over, the server closes,
    or the admission deadline passes. The event wait is capped-jitter
    (runtime.remote.Backoff) so a missed wake — or a fault-forced park
    with slots actually free — re-checks the free list instead of
    blocking until the deadline."""
    t0 = time.monotonic()
    deadline = t0 + self._admission_timeout
    backoff = Backoff(base=0.02, cap=0.5)
    while True:
      remaining = deadline - time.monotonic()
      if remaining > 0:
        waiter.event.wait(timeout=min(backoff.next_delay() + 1e-3,
                                      remaining))
      with self._slot_lock:
        if waiter.slot is not None:
          slot = waiter.slot  # direct handoff from _release_slot
          break
        if waiter.closed or self._closed:
          if waiter in self._waiters:
            self._waiters.remove(waiter)
          raise InferenceClosed(
              'inference server closed while waiting for a state slot')
        if self._free and self._best_waiter_locked() is waiter:
          self._waiters.remove(waiter)
          slot = self._free.pop()
          break
        if time.monotonic() >= deadline:
          self._waiters.remove(waiter)
          shed = self._admission == 'shed'
          with self._stats_lock:
            if shed:
              self._sheds += 1
            else:
              self._admission_timeouts += 1
          raise SlotUnavailable(
              f'{"shed" if shed else "admission timeout"}: no state-'
              f'arena slot free within {self._admission_timeout:.1f}s '
              f'({self._num_slots} slots, {len(self._waiters)} other '
              'waiter(s)) — overload; raise --inference_state_slots, '
              'or pick --inference_admission=grow')
    self._admission_wait_reservoir.record(time.monotonic() - t0)
    return slot

  def _grow_arena_locked(self):
    """Double the state arena in place (grow admission; called with
    _slot_lock held). Existing slot ids and carries are preserved; the
    new rows are zeroed and appended to the free list. One XLA
    recompile per growth (new arena shape) — rare by construction."""
    old = self._num_slots
    new = 2 * old if old else 8
    with self._arena_lock:
      arena = tuple(
          jnp.zeros((new, s), jnp.float32).at[:old].set(a)
          for a, s in zip(self._arena, self._core_sizes))
      if self._mesh is not None:
        arena = jax.device_put(arena, self._replicated)
      self._arena = arena
      self._num_slots = new
    self._free.extend(range(old, new))
    # Cache-mode AOT executables bake the arena shape into their
    # compiled programs — all stale after a grow. Drop them; the next
    # publish/warmup repopulates at the new shape. Lock order:
    # _slot_lock -> _aot_lock (this path only).
    with self._aot_lock:
      self._aot.clear()
    with self._stats_lock:
      self._arena_grows += 1
    log.warning(
        'inference state arena grown %d -> %d slots '
        '(--inference_admission=grow; one recompile per growth)',
        old, new)

  def _release_slot(self, slot):
    with self._slot_lock:
      if self._waiters:
        # Direct handoff to the best-priority waiter: the slot never
        # touches the free list, so a lower-priority waiter (or a
        # fresh fast-path acquire) cannot steal it.
        w = self._best_waiter_locked()
        self._waiters.remove(w)
        w.slot = slot
        w.event.set()
      else:
        self._free.append(slot)

  def _zero_slot(self, slot):
    with self._arena_lock:
      self._arena = tuple(a.at[slot].set(0.0) for a in self._arena)

  def _read_slot(self, slot):
    with self._arena_lock:
      arena = self._arena
    # The old arena array stays valid (never donated) even if the
    # dispatch thread swaps in a successor while we read; only the
    # owning actor writes this slot, and it is parked while reading.
    return tuple(np.asarray(a[slot], np.float32)[None] for a in arena)

  def _write_slot(self, slot, carry):
    vals = [jnp.asarray(np.asarray(c, np.float32)[0]) for c in carry]
    with self._arena_lock:
      self._arena = tuple(a.at[slot].set(v)
                          for a, v in zip(self._arena, vals))

  def slots_free(self):
    with self._slot_lock:
      return len(self._free)

  # -- dispatch plane --

  def _staging_for(self, total_rows):
    """Padded staging buffers for a merged batch of total_rows rows.

    Per padded bucket, a ring of depth+1 preallocated buffer lists:
    with at most `depth` batches dispatched-but-uncompleted (the
    semaphore) and completions released in FIFO order, a ring slot is
    reused only after the batch that last used it has completed — its
    host buffers are free to overwrite."""
    padded = self._padded_size(total_rows)
    meta = self._batcher.input_meta()
    ring = self._staging.get(padded)
    if ring is None:
      ring = [[np.zeros((padded,) + tuple(trail), dtype)
               for dtype, trail in meta]
              for _ in range(self._depth + 1)]
      self._staging[padded] = ring
      self._staging_calls[padded] = 0
    i = self._staging_calls[padded] % len(ring)
    self._staging_calls[padded] += 1
    return ring[i]

  def _aot_lookup(self, params, inputs):
    """The pre-compiled serving executable for this (padded bucket,
    params structure), or None — in which case _dispatch falls back to
    the jit cache and the miss is counted (a miss on the serve path is
    exactly the first-call compile stall the AOT table exists to
    remove)."""
    padded = int(np.shape(inputs[0])[0])
    k = (padded, _params_fingerprint(params))
    with self._aot_lock:
      compiled = self._aot.get(k)
    if compiled is None:
      with self._stats_lock:
        self._aot_misses += 1
      _AOT_MISSES.inc()
    return compiled

  def _dispatch(self, params, inputs, shadow_params=None):
    """Dispatch one padded batch through the jitted step, chaining the
    device-resident key (and arena) — returns the (async) caller-
    visible output arrays plus the shadow version's logits (or None).
    The shadow step runs BEFORE the live step so both read the same
    pre-step arena carries."""
    step = self._step  # read per call: tests monkeypatch it
    compiled = (self._aot_lookup(params, inputs)
                if self._serving_aot else None)
    fn = compiled if compiled is not None else step
    with self._key_lock:
      if self._state_cache:
        with self._arena_lock:
          shadow_out = None
          if shadow_params is not None:
            shadow_out = self._shadow_step(
                shadow_params, *self._arena, *inputs)
          outs = fn(params, self._key, *self._arena, *inputs)
          self._key = outs[0]
          self._arena = (outs[1], outs[2])
          return outs[3:], shadow_out
      shadow_out = None
      if shadow_params is not None:
        shadow_out = self._shadow_step(shadow_params, *inputs)
      outs = fn(params, self._key, *inputs)
      self._key = outs[0]
      return outs[1:], shadow_out

  def _dispatch_loop(self):
    while True:
      try:
        # Late-bound: _staging_for is resolved per batch, after the
        # (long) park in get_batch — not captured at loop entry.
        item = self._batcher.get_batch_into(
            lambda rows: self._staging_for(rows))
      except Exception:
        # Staging-buffer construction failed; get_batch_into answers
        # the batch's callers with the error before re-raising (its
        # rc-assert path cannot, so this stays loud). The dispatch
        # plane must survive — a dead dispatch thread hangs every
        # future policy call — but never silently: a persistent error
        # here would otherwise be an undiagnosable busy-spin.
        log.exception('inference dispatch: merged-batch staging failed')
        continue
      if item is None:
        self._completion_q.put(None)
        return
      batch_id, n, bufs = item
      t0 = time.perf_counter()
      try:
        if self._state_cache:
          # The staging ring reuses buffers: rows [n:] may hold slot
          # ids from an earlier (larger) merge — point them out of
          # range so the in-graph scatter drops them. The sentinel is
          # a constant (not num_slots): a concurrent 'grow' admission
          # must not turn a just-stamped pad id into a live slot.
          bufs[0][n:] = _PAD_SLOT_ID
        with self._stats_lock:
          self._calls += 1
          self._merged_requests += n
        with self._params_lock:
          params, _ = self._pick_live_locked()
          shadow_params = self._pick_shadow_locked()
        inputs = tuple(bufs)
        if self._mesh is not None:
          # Explicit placement: under multi-process JAX, jit refuses
          # numpy args with non-trivial shardings — and the local eval
          # mesh is exactly that. All its devices are process-local,
          # so the transfer itself is ordinary.
          inputs = jax.device_put(inputs, self._batch_sharding)
        self._sem.acquire()
        try:
          payload, shadow_out = self._dispatch(
              params, inputs, shadow_params)
          with self._stats_lock:
            self._inflight += 1
            self._inflight_peak = max(self._inflight_peak,
                                      self._inflight)
        except BaseException:
          self._sem.release()
          raise
        self._completion_q.put((batch_id, n, t0, payload, shadow_out))
      except Exception as e:  # propagate to the parked callers
        self._batcher.set_error(batch_id, f'{type(e).__name__}: {e}')

  def _completion_loop(self):
    while True:
      item = self._completion_q.get()
      if item is None:
        return
      batch_id, n, t0, payload, shadow_out = item
      try:
        # Observability for the sharded-eval contract: how many
        # devices the last merged call actually spanned (read before
        # device_get turns the arrays into host numpy).
        try:
          devices = len(payload[0].sharding.device_set)
        except Exception:
          devices = 1
        # ONE device_get for all outputs: each separate device→host
        # readback is a full round trip (85 ms through this sandbox's
        # remote-TPU tunnel, vs ~µs co-located — either way, batching
        # the transfer is strictly better).
        host = jax.device_get(payload)
        self._batcher.set_outputs(
            batch_id, [np.asarray(o)[:n] for o in host])
        if shadow_out is not None:
          # Shadow scoring AFTER the callers are answered: the gauge
          # must never add device_get latency to the live path. Logits
          # sit at payload index 1 in both step modes.
          try:
            live_logits = np.asarray(host[1])[:n]
            shadow_logits = np.asarray(jax.device_get(shadow_out))[:n]
            divergence = 1.0 - codec_lib.greedy_agreement(
                live_logits, shadow_logits)
            with self._stats_lock:
              self._shadow_calls += 1
              if self._shadow_calls == 1:
                self._shadow_divergence = divergence
              else:
                # EWMA: the gauge tracks RECENT divergence, so a
                # shadow flip mid-run shows up within ~10 samples.
                self._shadow_divergence = (
                    0.9 * self._shadow_divergence + 0.1 * divergence)
              ewma = self._shadow_divergence
            _SHADOW_CALLS.inc()
            _SHADOW_DIVERGENCE.set(ewma)
          except Exception:
            log.exception('inference: shadow scoring failed')
      except Exception as e:
        # A failed execution poisons everything CHAINED from its
        # outputs — the device key, and in cache mode the arena —
        # which _dispatch already swapped in. Re-anchor them BEFORE
        # answering the parked callers: an unparked caller retries
        # immediately, and that retry's dispatch must never inherit
        # the poisoned chain (on a loaded 1-core host the retry used
        # to win the race and fail on the poisoned key). set_error is
        # in the finally so a recovery failure can't strand callers.
        try:
          self._recover_chain()
        finally:
          try:
            self._batcher.set_error(batch_id, f'{type(e).__name__}: {e}')
          except Exception:
            pass
      finally:
        self._sem.release()
      lat_ms = (time.perf_counter() - t0) * 1e3
      _SERVE_LATENCY.observe(lat_ms)
      with self._stats_lock:
        self._inflight -= 1
        self._devices_last_call = devices
        self._latencies.append(lat_ms)

  def _recover_chain(self):
    """Re-anchor the device-chained state after a failed execution.

    The key (and state arena) are outputs of every dispatched step, so
    a failed step leaves poisoned arrays in the chain and every
    later dispatch would inherit the failure (the old host-side split
    survived transient failures — this restores that property). The
    key re-seeds deterministically from (base_seed, recovery count);
    the arena, if poisoned, can only be zeroed — its carry values
    passed through the failed step — which resets the fleet's
    episodes-in-flight, the same degraded class as a respawn's fresh
    episode."""
    recovered = False
    with self._key_lock:
      try:
        jax.block_until_ready(self._key)
      except Exception:
        recovered = True
        # Round 18 (guarded-by lint + review): read the recovery
        # count under _stats_lock NESTED in _key_lock — two racing
        # recoveries serialize on _key_lock, and each must see the
        # previous one's increment (below, same nesting) or both
        # would reseed with the identical (base_seed, count) key and
        # silently replay the same inference RNG stream. Lock order
        # _key_lock -> _stats_lock; nothing takes them inverted.
        with self._stats_lock:
          recoveries = self._chain_recoveries
        key = jax.random.PRNGKey(
            self._base_seed + 100_003 * (recoveries + 1))
        if self._mesh is not None:
          key = jax.device_put(key, self._replicated)
        self._key = key
      if self._state_cache:
        with self._arena_lock:
          try:
            jax.block_until_ready(self._arena)
          except Exception:
            recovered = True
            arena = tuple(jnp.zeros((self._num_slots, s), jnp.float32)
                          for s in self._core_sizes)
            if self._mesh is not None:
              arena = jax.device_put(arena, self._replicated)
            self._arena = arena
      if recovered:
        # Still inside _key_lock: the count advance is part of the
        # recovery's critical section, not an afterthought a second
        # recoverer can sneak past.
        with self._stats_lock:
          self._chain_recoveries += 1

  def _padded_size(self, n):
    """Bucket size for a merged batch of n: next power of two (capped
    at max_batch), rounded up to a multiple of the mesh's data width
    so every shard is non-empty. Note the rounding can EXCEED
    max_batch when the data width doesn't divide it: max_batch caps
    how many real requests merge (the batcher enforces that); the
    padded compute shape must still be shardable."""
    if self._pad_floor is not None:
      n = max(n, self._pad_floor)
    padded = min(_next_power_of_two(n), self._max_batch)
    if self._dp > 1:
      padded = ((padded + self._dp - 1) // self._dp) * self._dp
    return padded

  def warmup(self, obs_spec, sizes=None, max_size=None):
    """Pre-compile the jitted step for the padded bucket sizes.

    XLA compiles one program per padded batch shape (powers of two up
    to max_batch). Without this, each new bucket's first appearance
    stalls EVERY parked actor thread for the 20–40 s TPU compile; the
    reference's TF graph had no such stall (dynamic batch dims). Call
    before starting the fleet.

    Args:
      obs_spec: {'frame': (H, W, C), 'instr_len': L}.
      sizes: iterable of *unpadded* sizes to warm. Default: every
        power-of-two bucket up to `max_size` (capped at
        maximum_batch_size) — pass max_size=fleet size so only
        reachable buckets compile.
      max_size: see `sizes`; None means maximum_batch_size.
    """
    h, w, c = obs_spec['frame']
    l = obs_spec['instr_len']
    if sizes is None:
      cap = self._max_batch if max_size is None else min(
          _next_power_of_two(max_size), self._max_batch)
      sizes, s = [], 1
      while s <= cap:
        sizes.append(s)
        s *= 2
      if sizes[-1] != cap:
        # A non-power-of-two max_batch cap is itself a reachable
        # padded size (merged batches pad to min(pow2, max_batch)).
        sizes.append(cap)
    padded_done = set()
    for size in sizes:
      padded = self._padded_size(size)
      if padded in padded_done:
        continue
      padded_done.add(padded)
      with self._params_lock:
        params = self._versions[self._live_key].params
      inputs = (
          np.zeros((padded,), np.int32),
          np.zeros((padded,), np.float32),
          np.zeros((padded,), bool),
          np.zeros((padded, h, w, c), np.uint8),
          np.zeros((padded, l), np.int32))
      if self._state_cache:
        # Warmup must not touch live carries: out-of-range slot ids
        # make every scatter a drop (same compiled program — shapes
        # and dtypes are what XLA specializes on, not values).
        ids = np.full((padded,), _PAD_SLOT_ID, np.int32)
        inputs = (ids,) + inputs
      else:
        inputs = inputs + tuple(
            np.zeros((padded, s), np.float32) for s in self._core_sizes)
      # Record the input meta + warmed bucket for the AOT table —
      # _precompile_params re-derives argument specs from these when a
      # NEW params structure publishes later (the version-flip-
      # without-compile guarantee needs exactly this memo).
      with self._aot_lock:
        if self._warm_meta is None:
          self._warm_meta = tuple(
              (a.dtype, tuple(a.shape[1:])) for a in inputs)
        self._warm_buckets.add(padded)
      if self._serving_aot:
        # Pre-compile BEFORE dispatching, so warmup itself serves
        # from the AOT table (aot_misses stays 0 end to end).
        self._precompile_params(params)
      if self._mesh is not None:
        inputs = jax.device_put(inputs, self._batch_sharding)
      payload, _ = self._dispatch(params, inputs)
      jax.block_until_ready(payload)

  def stats(self):
    """Merge + service telemetry.

    {'calls', 'requests', 'mean_batch', 'params_version',
     'publishes_skipped', 'devices_last_call', 'latency_p50_ms',
     'latency_p99_ms', 'pipeline_depth', 'state_cache',
     'inflight_peak', 'slots_free'}.

    mean_batch near 1.0 means the batcher is not merging (the
    reference's ~3x single-machine win comes precisely from this
    number being high — paper Table 1); watch it when tuning
    inference_{min_batch,timeout_ms}. The latency percentiles cover
    the last ≤512 merged calls, assembly start → callers unparked
    (the per-call number bench.py's inference_plane stage itemizes).
    """
    with self._stats_lock:
      calls, reqs = self._calls, self._merged_requests
      lat = sorted(self._latencies)
      devices = self._devices_last_call
      version = self._params_version
      skipped = self._publishes_skipped
      peak = self._inflight_peak
      recoveries = self._chain_recoveries
      acquires = self._acquires
      admission_waits = self._admission_waits
      sheds = self._sheds
      admission_timeouts = self._admission_timeouts
      arena_grows = self._arena_grows
      unjoined = self._unjoined_threads
      version_flips = self._version_flips
      evictions = self._evictions
      ab_calls = self._ab_calls
      shadow_calls = self._shadow_calls
      shadow_divergence = self._shadow_divergence
      aot_misses = self._aot_misses
    with self._params_lock:
      resident = len(self._versions)
      live_label = self._versions[self._live_key].label()
      serve_counts = {str(e.label()): e.serves
                      for e in self._versions.values()}
    with self._aot_lock:
      aot_compiled = len(self._aot)
    with self._slot_lock:
      waitlist_depth = len(self._waiters)
      admission = self._admission
    (wait_p99_ms,) = self._admission_wait_reservoir.percentile_ms(0.99)
    p50 = percentile_ms(lat, 0.5)
    p99 = percentile_ms(lat, 0.99)
    return {
        'calls': calls,
        'requests': reqs,
        'mean_batch': (reqs / calls) if calls else 0.0,
        'params_version': version,
        'publishes_skipped': skipped,
        'devices_last_call': devices,
        'latency_p50_ms': round(p50, 3),
        'latency_p99_ms': round(p99, 3),
        'pipeline_depth': self._depth,
        'state_cache': self._state_cache,
        'inflight_peak': peak,
        'chain_recoveries': recoveries,
        'slots_free': self.slots_free() if self._state_cache else None,
        # Admission/overload telemetry (round 9): the shed fraction is
        # sheds / acquires — the serving-plane overload SLO number.
        'admission': admission,
        'acquires': acquires,
        'admission_waits': admission_waits,
        'sheds': sheds,
        'admission_timeouts': admission_timeouts,
        'admission_wait_p99_ms': wait_p99_ms,
        'arena_grows': arena_grows,
        'waitlist_depth': waitlist_depth,
        'unjoined_threads': unjoined,
        # Serving version table (round 21): per-version counters keyed
        # by entry label, plus the A/B + shadow + AOT planes.
        'resident_versions': resident,
        'live_version': live_label,
        'serve_counts': serve_counts,
        'version_flips': version_flips,
        'evictions': evictions,
        'ab_calls': ab_calls,
        'shadow_calls': shadow_calls,
        'shadow_divergence': round(shadow_divergence, 6),
        'aot_misses': aot_misses,
        'aot_compiled': aot_compiled,
    }

  # -- serving version table (round 21) --

  def _newest_nonlive_locked(self):
    """The most recently PUBLISHED non-live resident entry (insertion
    order, not serve recency) — the auto A/B candidate and the auto
    shadow version. Called with _params_lock held."""
    for key in reversed(self._versions):
      if key != self._live_key:
        return self._versions[key]
    return None

  def _entry_for_locked(self, key_or_none):
    if key_or_none is None:
      return self._newest_nonlive_locked()
    return self._versions.get(key_or_none)

  def _pick_live_locked(self):
    """Pick this merged call's serving params under _params_lock: the
    live entry, or — serving_ab_fraction of calls, via a deterministic
    accumulator — the A/B candidate (set_ab's key, else the newest
    non-live resident). Bumps the entry's serve counter + LRU tick.
    Returns (params, entry key)."""
    self._serve_tick += 1
    entry = self._versions[self._live_key]
    if self._ab_fraction > 0.0:
      cand = self._entry_for_locked(self._ab_key)
      if cand is not None and cand.key != self._live_key:
        self._ab_acc += self._ab_fraction
        if self._ab_acc >= 1.0:
          self._ab_acc -= 1.0
          entry = cand
          with self._stats_lock:
            self._ab_calls += 1
          _AB_CALLS.inc()
    entry.serves += 1
    entry.tick = self._serve_tick
    return entry.params, entry.key

  def _pick_shadow_locked(self):
    """The shadow version's params for this merged call, or None —
    sampled at serving_shadow_fraction by the same accumulator
    scheme. The shadow is set_shadow's key, else the newest non-live
    resident; never the live entry (zero divergence by construction
    would only dilute the gauge)."""
    if self._shadow_fraction <= 0.0:
      return None
    entry = self._entry_for_locked(self._shadow_key)
    if entry is None or entry.key == self._live_key:
      return None
    self._shadow_acc += self._shadow_fraction
    if self._shadow_acc < 1.0:
      return None
    self._shadow_acc -= 1.0
    return entry.params

  def _install_locked(self, key, params):
    """Insert an OWNED params copy as the live entry, then evict LRU
    unpinned non-live entries past the count cap / byte budget.
    Called with _params_lock held."""
    self._serve_tick += 1
    self._versions[key] = _VersionEntry(
        key, params, _tree_nbytes(params), self._serve_tick)
    self._versions.move_to_end(key)
    self._live_key = key
    self._evict_locked()
    _RESIDENT_VERSIONS.set(float(len(self._versions)))

  def _evict_locked(self):
    while True:
      over_count = len(self._versions) > self._resident_cap
      over_bytes = (
          self._hbm_budget_bytes > 0 and len(self._versions) > 1
          and sum(e.nbytes for e in self._versions.values())
          > self._hbm_budget_bytes)
      if not (over_count or over_bytes):
        return
      victim = None
      for e in self._versions.values():
        if e.key == self._live_key or e.pinned:
          continue
        if victim is None or e.tick < victim.tick:
          victim = e
      if victim is None:
        # Every resident entry is live or pinned: the budget cannot
        # be honoured without breaking a pin — keep them and say so.
        log.warning(
            'serving version table over budget (%d resident) but '
            'every entry is live/pinned — nothing evictable',
            len(self._versions))
        return
      del self._versions[victim.key]
      with self._stats_lock:
        self._evictions += 1
      _EVICTIONS.inc()
      log.info('serving: evicted resident version %s (LRU; %d left)',
               victim.label(), len(self._versions))

  def _precompile_params(self, params):
    """AOT-compile the serving step for `params`' structure across
    every warmed bucket (the jit .lower(...).compile() seam —
    parallel/fit.py's AOT pattern), so a later flip to this version
    never pays first-call compile on the serve path. Runs on the
    PUBLISHER's thread; a no-op before the first warmup() (no input
    meta recorded yet) and for already-compiled (bucket, structure)
    keys."""
    with self._aot_lock:
      meta = self._warm_meta
      buckets = sorted(self._warm_buckets)
    if meta is None:
      return
    fingerprint = _params_fingerprint(params)
    params_sds = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype), params)
    arena_sds = ()
    if self._state_cache:
      with self._arena_lock:
        arena_sds = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in self._arena)
    for padded in buckets:
      cache_key = (padded, fingerprint)
      with self._aot_lock:
        if cache_key in self._aot:
          continue
      in_sds = tuple(
          jax.ShapeDtypeStruct((padded,) + trail, dtype)
          for dtype, trail in meta)
      try:
        compiled = self._step.lower(
            params_sds, self._key_spec, *arena_sds, *in_sds).compile()
      except Exception:
        log.exception(
            'serving AOT compile failed (bucket %d) — the jit cache '
            'covers it at first-call cost', padded)
        return
      with self._aot_lock:
        self._aot[cache_key] = compiled

  def update_params(self, params, version=None):
    """Publish a weight snapshot into the serving version table.

    Copy semantics: a NEW entry copies each leaf — the learner's train
    step DONATES its state, so the caller's buffers will be
    invalidated by the next update; a zero-copy swap would hand actors
    deleted buffers ("Buffer has been deleted or donated"). The copy
    is dispatched before any subsequent donation, so it's race-free.
    On the mesh path the explicit copy also matters: device_put alone
    is a NO-OP (aliased buffers) when the input already carries the
    target sharding.

    Version semantics (round 21):
      - version == the LIVE entry's key: skipped entirely (counted in
        stats()['publishes_skipped']) — republishing an unchanged
        snapshot must not cost a tree copy.
      - version RESIDENT but not live: flips live to that entry with
        NO copy (stats()['version_flips']) — the rollback/promote
        path the table exists for.
      - otherwise: copy (quantize first when publish_codec=int8),
        AOT-precompile if enabled (BEFORE the flip, off the serve
        path), install as live, evict LRU past the caps.
      - version=None: always a fresh anonymous entry (the safe
        default for callers with no version).

    Restore caveat (round 21 satellite; tests/test_serving.py pins
    it): the table — dedup keys included — is process memory BY
    DESIGN. A server rebuilt after a checkpoint restore re-copies on
    the first publish of any version, including a numeric version it
    published before the restart: the constructor holds its params by
    reference under a sentinel key, and the first publish must land
    an owned copy for the donation safety above. A dedup key that
    survived restore would skip that copy and hand actors the
    learner's donated buffers.
    """
    if version is not None:
      with self._params_lock:
        if version == self._live_key:
          with self._stats_lock:
            self._publishes_skipped += 1
          return
        if version in self._versions:
          self._serve_tick += 1
          entry = self._versions[version]
          entry.tick = self._serve_tick
          self._versions.move_to_end(version)
          self._live_key = version
          with self._stats_lock:
            self._version_flips += 1
            self._params_version += 1
          _VERSION_FLIPS.inc()
          return
    params = jax.tree_util.tree_map(jnp.copy, params)
    if self._quantize_resident:
      params = codec_lib.quantize_device(params)
    if self._mesh is not None:
      params = jax.device_put(params, self._replicated)
    if self._serving_aot:
      # Compile for this structure BEFORE the entry goes live: the
      # publisher's thread eats the compile, never a serving call.
      self._precompile_params(params)
    with self._params_lock:
      key = version
      if key is None:
        self._anon_seq += 1
        key = ('anon', self._anon_seq)
      self._install_locked(key, params)
    with self._stats_lock:
      self._params_version += 1

  def pin_version(self, version, pinned=True):
    """Pin (or unpin) a resident version: pinned entries are exempt
    from LRU eviction — the rollback anchor. Returns True if the
    version was resident."""
    with self._params_lock:
      entry = self._versions.get(version)
      if entry is None:
        return False
      entry.pinned = bool(pinned)
      return True

  def set_live(self, version):
    """Flip serving to an already-resident version without a publish
    (stats()['version_flips']). Raises KeyError if not resident."""
    with self._params_lock:
      if version not in self._versions:
        raise KeyError(f'version {version!r} is not resident')
      if version == self._live_key:
        return
      self._serve_tick += 1
      entry = self._versions[version]
      entry.tick = self._serve_tick
      self._versions.move_to_end(version)
      self._live_key = version
      with self._stats_lock:
        self._version_flips += 1
        self._params_version += 1
      _VERSION_FLIPS.inc()

  def set_ab(self, version, fraction):
    """Route `fraction` of merged calls to `version` (None = the
    newest non-live resident). Fraction 0 disables A/B."""
    fraction = float(fraction)
    if not 0.0 <= fraction <= 1.0:
      raise ValueError(f'ab fraction {fraction} outside [0, 1]')
    with self._params_lock:
      self._ab_key = version
      self._ab_fraction = fraction
      self._ab_acc = 0.0

  def set_shadow(self, version, fraction):
    """Replay `fraction` of merged calls against `version` (None =
    the newest non-live resident) and score greedy agreement vs live
    into the serving/shadow_divergence gauge. Fraction 0 disables."""
    fraction = float(fraction)
    if not 0.0 <= fraction <= 1.0:
      raise ValueError(f'shadow fraction {fraction} outside [0, 1]')
    with self._params_lock:
      self._shadow_key = version
      self._shadow_fraction = fraction
      self._shadow_acc = 0.0

  def resident_versions(self):
    """[(label, serves, pinned, live?)] for every resident entry, in
    publish order — the bench's per-version counter rows."""
    with self._params_lock:
      return [(e.label(), e.serves, e.pinned, e.key == self._live_key)
              for e in self._versions.values()]

  _REMOTE_ORDER = ('prev_action', 'reward', 'done', 'frame', 'instr',
                   'core_c', 'core_h')

  def serve_remote(self, payload):
    """Serve one CARRY-PASSING batch for the wire-v10 routed inference
    service (runtime/remote.py 'infer' requests — the driver attaches
    this as the ingest server's serving seam).

    `payload` is a dict of batch-leading arrays: prev_action [B]
    int32, reward [B] f32, done [B] bool, frame [B,H,W,C] uint8,
    instr [B,L] int32, core_c/core_h [B,H] f32. Returns the result
    dict (action, logits, baseline, core_c, core_h, version label).

    Carry-passing even on a state-cache server: the remote caller
    owns its carry — a cross-host request must never consume a local
    arena slot. RNG is a per-call fold_in of a dedicated base key, so
    routed traffic never perturbs the local fleet's key chain. One
    compiled program per distinct batch size: route fixed-size
    batches, or accept the first-call compile."""
    t0 = time.perf_counter()
    inputs = tuple(np.asarray(payload[k]) for k in self._REMOTE_ORDER)
    with self._params_lock:
      params, key = self._pick_live_locked()
      label = self._versions[key].label()
    with self._remote_lock:
      self._remote_calls += 1
      count = self._remote_calls
    sub = jax.random.fold_in(self._remote_base_key, count)
    if self._mesh is not None:
      inputs = jax.device_put(inputs, self._replicated)
    outs = self._remote_step(params, sub, *inputs)
    action, logits, baseline, new_c, new_h = jax.device_get(outs[1:])
    _SERVE_LATENCY.observe((time.perf_counter() - t0) * 1e3)
    return {
        'action': np.asarray(action),
        'logits': np.asarray(logits),
        'baseline': np.asarray(baseline),
        'core_c': np.asarray(new_c),
        'core_h': np.asarray(new_h),
        'version': label,
    }

  def policy(self, prev_action, env_output, core_state):
    """`runtime.actor.Actor`-contract policy: scalars in, scalars out.

    Carry-passing mode: core_state is the numeric (c, h) carry and the
    new carry rides the wire back. State-cache mode: core_state is a
    `_SlotHandle` and only its slot id rides the wire — the carry
    advances in-graph on the device."""
    frame, instr = env_output.observation
    if self._state_cache:
      if not isinstance(core_state, _SlotHandle):
        raise TypeError(
            'state-cache mode: core_state must be the slot handle '
            'from initial_core_state(), got '
            f'{type(core_state).__name__}')
      if core_state.released:
        # A respawned actor owns this slot's successor; a straggler
        # thread must fail here, not scatter into someone else's slot.
        raise RuntimeError('policy() called with a released state slot')
      action, logits, baseline = self._batcher.compute([
          np.asarray([core_state.slot], np.int32),
          np.asarray([prev_action], np.int32),
          np.asarray([env_output.reward], np.float32),
          np.asarray([env_output.done], bool),
          np.asarray(frame)[None],
          np.asarray(instr)[None]])
      out = AgentOutput(action=action[0], policy_logits=logits[0],
                        baseline=baseline[0])
      return out, core_state
    core_c, core_h = core_state
    action, logits, baseline, new_c, new_h = self._batcher.compute([
        np.asarray([prev_action], np.int32),
        np.asarray([env_output.reward], np.float32),
        np.asarray([env_output.done], bool),
        np.asarray(frame)[None],
        np.asarray(instr)[None],
        np.asarray(core_c, np.float32),
        np.asarray(core_h, np.float32)])
    out = AgentOutput(action=action[0], policy_logits=logits[0],
                      baseline=baseline[0])
    return out, (new_c, new_h)

  def close(self):
    with self._slot_lock:
      if self._closed:
        return
      self._closed = True
      # Parked admission waiters get a CLEAN InferenceClosed answer —
      # a caller waiting out an overload must not block forever on a
      # server that is going away.
      waiters, self._waiters = self._waiters, []
      for w in waiters:
        w.closed = True
        w.event.set()
    # Close wakes the dispatch thread's get_batch (None) and cancels
    # parked callers; the dispatch thread forwards the sentinel so the
    # completion thread drains in-flight batches first.
    self._batcher.close()
    unjoined = []
    for t in (self._dispatch_thread, self._completion_thread):
      if t is not None:
        t.join(timeout=10)
        if t.is_alive():
          unjoined.append(t.name)
    if unjoined:
      # Leaked threads used to vanish silently; a wedged dispatch/
      # completion thread pins device buffers and a staging ring for
      # the rest of the process lifetime — say so, and count it.
      with self._stats_lock:
        self._unjoined_threads = len(unjoined)
      log.warning(
          'InferenceServer.close(): %d thread(s) missed the join '
          'deadline and leak as daemons: %s', len(unjoined),
          ', '.join(unjoined))
