"""Batched inference server: many actor threads, one jitted TPU call.

The reference reaches ~3× single-machine throughput by transparently
merging ~48 concurrent batch-1 `Agent._build` calls into one GPU call
via the C++ Batcher op (reference: experiment.py ≈L470–482 monkey-patch
+ dynamic_batching.py). This is the TPU-native equivalent:

- actor threads call `policy(prev_action, env_output, core_state)`
  (the `runtime.actor.Actor` contract) and block;
- the C++ batcher (ops/batcher) merges concurrent calls;
- ONE computation thread runs the jitted single-step agent on the
  merged batch on TPU.

XLA needs static shapes, so merged batches are padded up to the next
power of two (capped at maximum_batch_size) before the jitted call and
sliced after — a handful of compiled shapes total, no recompiles in
steady state (the reference's TF graph handled dynamic batch dims
natively; bucketing is the XLA-idiomatic trade).

Weights: the server holds a params snapshot updated via
`update_params` (the reference's gRPC weight fetch becomes an on-host
pointer swap; the same "actions within one unroll may span weight
versions" caveat applies — reference ≈L472 comment).
"""

import threading

import numpy as np

import jax
import jax.numpy as jnp

from scalable_agent_tpu.ops import dynamic_batching
from scalable_agent_tpu.structs import AgentOutput, StepOutput


def _next_power_of_two(n):
  p = 1
  while p < n:
    p *= 2
  return p


class InferenceServer:
  """Serves a batched policy for host actor threads.

  Args:
    agent: ImpalaAgent (flax module).
    params: initial parameter pytree (host or device).
    config: Config (uses inference_* knobs).
    seed: PRNG seed for action sampling.
  """

  def __init__(self, agent, params, config, seed=0):
    self._agent = agent
    self._params = params
    self._params_lock = threading.Lock()
    self._key = jax.random.PRNGKey(seed)
    self._max_batch = config.inference_max_batch

    @jax.jit
    def step(params, rng, prev_action, reward, done, frame, instr,
             core_c, core_h):
      env_output = StepOutput(
          reward=reward[None], info=None, done=done[None],
          observation=(frame[None], instr[None]))
      out, (new_c, new_h) = agent.apply(
          params, prev_action[None], env_output, (core_c, core_h),
          sample_rng=rng)
      return (out.action[0], out.policy_logits[0], out.baseline[0],
              new_c, new_h)

    self._step = step

    def batched(prev_action, reward, done, frame, instr, core_c,
                core_h):
      n = prev_action.shape[0]
      padded = min(_next_power_of_two(n), self._max_batch)
      pad = padded - n

      def pad0(x):
        if pad == 0:
          return x
        return np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)

      with self._params_lock:
        params = self._params
      self._key, sub = jax.random.split(self._key)
      outs = self._step(params, sub, *map(
          pad0, (prev_action, reward, done, frame, instr, core_c,
                 core_h)))
      return tuple(np.asarray(o)[:n] for o in outs)

    self._batched = dynamic_batching.batch_fn_with_options(
        minimum_batch_size=config.inference_min_batch,
        maximum_batch_size=config.inference_max_batch,
        timeout_ms=config.inference_timeout_ms)(batched)

  def update_params(self, params):
    """Publish a new weight snapshot.

    Copies each leaf: the learner's train step DONATES its state, so
    the caller's buffers will be invalidated by the next update — a
    zero-copy swap would hand actors deleted buffers ("Buffer has been
    deleted or donated"). The copy is dispatched before any subsequent
    donation, so it's race-free."""
    params = jax.tree_util.tree_map(jnp.copy, params)
    with self._params_lock:
      self._params = params

  def policy(self, prev_action, env_output, core_state):
    """`runtime.actor.Actor`-contract policy: scalars in, scalars out."""
    frame, instr = env_output.observation
    core_c, core_h = core_state
    action, logits, baseline, new_c, new_h = self._batched(
        np.asarray([prev_action], np.int32),
        np.asarray([env_output.reward], np.float32),
        np.asarray([env_output.done], bool),
        np.asarray(frame)[None],
        np.asarray(instr)[None],
        np.asarray(core_c, np.float32),
        np.asarray(core_h, np.float32))
    out = AgentOutput(action=action[0], policy_logits=logits[0],
                      baseline=baseline[0])
    return out, (new_c, new_h)

  def close(self):
    self._batched.close()
