"""Batched inference server: many actor threads, one jitted TPU call.

The reference reaches ~3× single-machine throughput by transparently
merging ~48 concurrent batch-1 `Agent._build` calls into one GPU call
via the C++ Batcher op (reference: experiment.py ≈L470–482 monkey-patch
+ dynamic_batching.py). This is the TPU-native equivalent:

- actor threads call `policy(prev_action, env_output, core_state)`
  (the `runtime.actor.Actor` contract) and block;
- the C++ batcher (ops/batcher) merges concurrent calls;
- ONE computation thread runs the jitted single-step agent on the
  merged batch on TPU.

XLA needs static shapes, so merged batches are padded up to the next
power of two (capped at maximum_batch_size) before the jitted call and
sliced after — a handful of compiled shapes total, no recompiles in
steady state (the reference's TF graph handled dynamic batch dims
natively; bucketing is the XLA-idiomatic trade).

Weights: the server holds a params snapshot updated via
`update_params` (the reference's gRPC weight fetch becomes an on-host
pointer swap; the same "actions within one unroll may span weight
versions" caveat applies — reference ≈L472 comment).
"""

import threading

import numpy as np

import jax
import jax.numpy as jnp

from scalable_agent_tpu.ops import dynamic_batching
from scalable_agent_tpu.structs import AgentOutput, StepOutput


def _next_power_of_two(n):
  p = 1
  while p < n:
    p *= 2
  return p


class InferenceServer:
  """Serves a batched policy for host actor threads.

  Args:
    agent: ImpalaAgent (flax module).
    params: initial parameter pytree (host or device).
    config: Config (uses inference_* knobs).
    seed: PRNG seed for action sampling.
    mesh: optional jax.sharding.Mesh — merged inference batches shard
      over its data axis (params replicated), so concurrent eval of
      many envs uses every chip instead of one (VERDICT r2 W6: the
      reference's test() is batch-1 serial; sharded batched eval is
      TPU headroom it never had). Padded batch sizes round up to a
      multiple of the data width.
    pad_batch_to: optional floor on the padded batch size — every
      merged batch pads up to (at least) this bucket, so the server
      compiles exactly ONE program instead of one per power-of-two
      bucket (VERDICT r3 W5: eval warmed 6 buckets ≈ 2–4 min of
      serial 20–40 s compiles before the first episode). The padding
      FLOPs are noise next to one avoided compile; use where the
      steady-state merged size is known (eval: all levels step
      concurrently), not for training fleets whose merge size is the
      tuning signal.
    fleet_size: number of actor threads this server will serve —
      only consulted when config.inference_min_batch == 0 (AUTO merge
      floor; see the constructor comment).
  """

  def __init__(self, agent, params, config, seed=0, mesh=None,
               pad_batch_to=None, fleet_size=None):
    self._pad_floor = pad_batch_to
    # inference_min_batch == 0 means AUTO: floor the merge at the
    # local fleet size, so every inference call carries the whole
    # fleet and per-call dispatch amortizes fully (measured +53% e2e
    # fps at the bench operating point — docs/PERF.md round-5 batcher
    # sweep). inference_timeout_ms bounds the wait when an actor is
    # mid-unroll-publish or being respawned, so the floor degrades to
    # a latency cap, never a deadlock.
    min_batch = config.inference_min_batch
    if min_batch == 0:
      min_batch = max(fleet_size or 1, 1)
    self._min_batch = min(min_batch, config.inference_max_batch)
    self._agent = agent
    self._core_sizes = (agent.hidden_size, agent.hidden_size)  # (c, h)
    self._mesh = mesh
    self._devices_last_call = 0
    if mesh is not None:
      from jax.sharding import NamedSharding, PartitionSpec
      from scalable_agent_tpu.parallel import mesh as mesh_lib
      self._dp = int(mesh.shape[mesh_lib.DATA_AXIS])
      self._replicated = NamedSharding(mesh, PartitionSpec())
      self._batch_sharding = NamedSharding(
          mesh, PartitionSpec(mesh_lib.DATA_AXIS))
      params = jax.device_put(params, self._replicated)
    else:
      self._dp = 1
    self._params = params
    self._params_lock = threading.Lock()
    self._stats_lock = threading.Lock()
    self._calls = 0
    self._merged_requests = 0
    self._params_version = 0
    # _key is split from both warmup (caller thread) and batched (the
    # batcher's computation thread); the lock makes that safe without
    # relying on warmup-completes-before-serving ordering.
    self._key_lock = threading.Lock()
    self._key = jax.random.PRNGKey(seed)
    self._max_batch = config.inference_max_batch

    def step(params, rng, prev_action, reward, done, frame, instr,
             core_c, core_h):
      env_output = StepOutput(
          reward=reward[None], info=None, done=done[None],
          observation=(frame[None], instr[None]))
      out, (new_c, new_h) = agent.apply(
          params, prev_action[None], env_output, (core_c, core_h),
          sample_rng=rng)
      return (out.action[0], out.policy_logits[0], out.baseline[0],
              new_c, new_h)

    if mesh is None:
      self._step = jax.jit(step)
    else:
      self._step = jax.jit(
          step,
          # params keep their (replicated) placement; batch args shard
          # dim 0 over the data axis; rng is replicated.
          in_shardings=(None, self._replicated) +
          (self._batch_sharding,) * 7,
          out_shardings=(self._batch_sharding,) * 5)

    def batched(prev_action, reward, done, frame, instr, core_c,
                core_h):
      n = prev_action.shape[0]
      with self._stats_lock:
        self._calls += 1
        self._merged_requests += n
      padded = self._padded_size(n)
      pad = padded - n

      def pad0(x):
        if pad == 0:
          return x
        return np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)

      with self._params_lock:
        params = self._params
      with self._key_lock:
        self._key, sub = jax.random.split(self._key)
      inputs = tuple(map(
          pad0, (prev_action, reward, done, frame, instr, core_c,
                 core_h)))
      if self._mesh is not None:
        # Explicit placement: under multi-process JAX, jit refuses
        # numpy args with non-trivial shardings — and the local eval
        # mesh is exactly that. All its devices are process-local, so
        # the transfer itself is ordinary.
        inputs = jax.device_put(inputs, self._batch_sharding)
        sub = jax.device_put(sub, self._replicated)
      outs = self._step(params, sub, *inputs)
      # Observability for the sharded-eval contract: how many devices
      # the last merged call actually spanned.
      self._devices_last_call = len(outs[0].sharding.device_set)
      # ONE device_get for all outputs: each separate device→host
      # readback is a full round trip (85 ms through this sandbox's
      # remote-TPU tunnel, vs ~µs co-located — either way, batching
      # the transfer is strictly better).
      outs = jax.device_get(outs)
      return tuple(o[:n] for o in outs)

    self._batched = dynamic_batching.batch_fn_with_options(
        minimum_batch_size=self._min_batch,
        maximum_batch_size=config.inference_max_batch,
        timeout_ms=config.inference_timeout_ms)(batched)

  def _padded_size(self, n):
    """Bucket size for a merged batch of n: next power of two (capped
    at max_batch), rounded up to a multiple of the mesh's data width
    so every shard is non-empty. Note the rounding can EXCEED
    max_batch when the data width doesn't divide it: max_batch caps
    how many real requests merge (the batcher enforces that); the
    padded compute shape must still be shardable."""
    if self._pad_floor is not None:
      n = max(n, self._pad_floor)
    padded = min(_next_power_of_two(n), self._max_batch)
    if self._dp > 1:
      padded = ((padded + self._dp - 1) // self._dp) * self._dp
    return padded

  def warmup(self, obs_spec, sizes=None, max_size=None):
    """Pre-compile the jitted step for the padded bucket sizes.

    XLA compiles one program per padded batch shape (powers of two up
    to max_batch). Without this, each new bucket's first appearance
    stalls EVERY parked actor thread for the 20–40 s TPU compile; the
    reference's TF graph had no such stall (dynamic batch dims). Call
    before starting the fleet.

    Args:
      obs_spec: {'frame': (H, W, C), 'instr_len': L}.
      sizes: iterable of *unpadded* sizes to warm. Default: every
        power-of-two bucket up to `max_size` (capped at
        maximum_batch_size) — pass max_size=fleet size so only
        reachable buckets compile.
      max_size: see `sizes`; None means maximum_batch_size.
    """
    h, w, c = obs_spec['frame']
    l = obs_spec['instr_len']
    core_c, core_h = (np.zeros((1, s), np.float32)
                      for s in self._core_sizes)
    if sizes is None:
      cap = self._max_batch if max_size is None else min(
          _next_power_of_two(max_size), self._max_batch)
      sizes, s = [], 1
      while s <= cap:
        sizes.append(s)
        s *= 2
      if sizes[-1] != cap:
        # A non-power-of-two max_batch cap is itself a reachable
        # padded size (batched() pads to min(pow2, max_batch)).
        sizes.append(cap)
    padded_done = set()
    for size in sizes:
      padded = self._padded_size(size)
      if padded in padded_done:
        continue
      padded_done.add(padded)
      with self._params_lock:
        params = self._params
      with self._key_lock:
        self._key, sub = jax.random.split(self._key)
      inputs = (
          np.zeros((padded,), np.int32),
          np.zeros((padded,), np.float32),
          np.zeros((padded,), bool),
          np.zeros((padded, h, w, c), np.uint8),
          np.zeros((padded, l), np.int32),
          np.repeat(core_c, padded, 0), np.repeat(core_h, padded, 0))
      if self._mesh is not None:
        inputs = jax.device_put(inputs, self._batch_sharding)
        sub = jax.device_put(sub, self._replicated)
      outs = self._step(params, sub, *inputs)
      jax.block_until_ready(outs)

  def stats(self):
    """Merge telemetry: {'calls', 'requests', 'mean_batch',
    'params_version'}. mean_batch near 1.0 means the batcher is not
    merging (the reference's ~3x single-machine win comes precisely
    from this number being high — paper Table 1); watch it when tuning
    inference_{min_batch,timeout_ms}."""
    with self._stats_lock:
      calls, reqs = self._calls, self._merged_requests
    return {
        'calls': calls,
        'requests': reqs,
        'mean_batch': (reqs / calls) if calls else 0.0,
        'params_version': self._params_version,
        'devices_last_call': self._devices_last_call,
    }

  def update_params(self, params):
    """Publish a new weight snapshot.

    Copies each leaf: the learner's train step DONATES its state, so
    the caller's buffers will be invalidated by the next update — a
    zero-copy swap would hand actors deleted buffers ("Buffer has been
    deleted or donated"). The copy is dispatched before any subsequent
    donation, so it's race-free. On the mesh path the explicit copy
    also matters: device_put alone is a NO-OP (aliased buffers) when
    the input already carries the target sharding."""
    params = jax.tree_util.tree_map(jnp.copy, params)
    if self._mesh is not None:
      params = jax.device_put(params, self._replicated)
    with self._params_lock:
      self._params = params
    with self._stats_lock:
      self._params_version += 1

  def policy(self, prev_action, env_output, core_state):
    """`runtime.actor.Actor`-contract policy: scalars in, scalars out."""
    frame, instr = env_output.observation
    core_c, core_h = core_state
    action, logits, baseline, new_c, new_h = self._batched(
        np.asarray([prev_action], np.int32),
        np.asarray([env_output.reward], np.float32),
        np.asarray([env_output.done], bool),
        np.asarray(frame)[None],
        np.asarray(instr)[None],
        np.asarray(core_c, np.float32),
        np.asarray(core_h, np.float32))
    out = AgentOutput(action=action[0], policy_logits=logits[0],
                      baseline=baseline[0])
    return out, (new_c, new_h)

  def close(self):
    self._batched.close()
