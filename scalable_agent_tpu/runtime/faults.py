"""Deterministic cross-layer fault injection.

The robustness layer (health watchdog, checkpoint integrity ladder,
fleet respawn, transport reconnect) is only trustworthy if its failure
paths EXECUTE — in CI, deterministically, not just in a post-mortem.
This module is the one place that knows how to break the pipeline on
purpose:

- `FaultPlan`: a seedable schedule of `Fault`s keyed by (site, event
  index). Each injection site keeps a monotone event counter; a fault
  fires when the counter hits its index. Same plan + same workload ⇒
  same faults, every run (`scripts/chaos.py` asserts recovery SLOs on
  top of this).
- Injection sites threaded through the real code paths (no mocks — the
  production error handling is what executes):

    env_step          FaultyEnv wrapper (driver.make_fleet wraps when a
                      plan covers the site): 'raise' kills the actor
                      (fleet must respawn), 'hang' wedges it for
                      `param` seconds (stall detection must respawn).
    transport_send    RemoteActorClient._rpc: 'drop' closes the socket,
                      'garbage'/'truncate' first ship a corrupt frame
                      the learner's ingest must survive (and
                      quarantine), then drop. All surface as OSError so
                      the actor's reconnect/backoff path runs.
    checkpoint_save   Checkpointer.save: the just-written newest step
                      is corrupted on disk and the last-known-good
                      marker is NOT advanced — a save interrupted
                      mid-write. `restore_latest` must fall back.
    nan_burst         driver.train: the staged batch's rewards become
                      NaN for the step — the loss/grads go non-finite
                      and the learner's device-side guard + watchdog
                      ladder must skip/roll back.
    slot_exhaustion   InferenceServer._acquire_slot: the acquire is
                      forced down the contended admission path (parked
                      waitlist) even when slots are free — the
                      block/shed/grow degrade machinery must execute,
                      never the old raise-on-exhaustion.
    preempt_signal    driver.train loop (one event per learner step):
                      a fired fault requests the preemption drain —
                      SIGTERM made deterministic for the chaos SLOs
                      (quiesce → flush → verified checkpoint →
                      resume_manifest.json).
    slow_learner      driver.train loop: 'hang' sleeps `param` seconds
                      in the step path, so the trajectory buffer fills
                      and producer-side backpressure (actor put
                      blocking, ingest ack delay, staleness growth)
                      must engage instead of unbounded queueing.
    conn_partition    RemoteActorClient._rpc (round 11): 'blackhole'
                      goes silent for `param` seconds WITHOUT closing
                      the socket — the half-open shape a network
                      partition/dead NAT entry produces. The learner's
                      idle reaper must reap the silent connection
                      within its budget; the client resumes after the
                      partition "heals" and its next send finds the
                      reaped socket (reconnect window runs).
    conn_delay        RemoteActorClient._rpc: 'delay' sleeps exactly
                      `param` seconds before the send; 'jitter'
                      sleeps a seeded U[0, param] — injected transport
                      latency the liveness machinery must tolerate
                      WITHOUT reaping (delay < idle window).
    learner_crash     driver.train loop, one event per consumed batch:
                      'kill' hard-aborts the process with SIGKILL — no
                      finally blocks, no drain, no 'bye' frame.
                      kill -9 / OOM made deterministic; only ever
                      scheduled against a learner running as a CHILD
                      process (scripts/chaos.py run_partition_storm),
                      which then restarts it and asserts the
                      restore-from-LAST_GOOD + fleet re-attach SLOs.
    wire_bitflip      RemoteActorClient._rpc OOB sends (round 12):
                      'flip' flips ONE seeded bit in the largest raw
                      buffer of the outgoing unroll frame AFTER the
                      v7 CRC trailer was computed — a frame that still
                      PARSES (the flip lands in the frame-stack bytes,
                      not the pickle skeleton), which is exactly the
                      silent corruption the CRC exists to catch.
                      Distinct from transport_send 'garbage' (which
                      cannot parse and trips the quarantine path).
                      The sender's own unroll is never touched (the
                      damaged segment is a copy), so the scripted
                      re-send ships clean bytes.
    publish_corrupt   TrajectoryIngestServer._make_blob (round 12):
                      flips one seeded bit in a float leaf of the
                      params snapshot AFTER the content digest was
                      computed but BEFORE serialization — host-memory
                      rot between device_get and the wire. The frame
                      CRC is consistent with the corrupted bytes (it
                      is computed over them), so only the client's
                      digest check before update_params can catch it.
    ckpt_bitrot       Checkpointer.save (round 12): flips one byte in
                      the largest file of the JUST-COMMITTED step
                      AFTER its digests were recorded and LAST_GOOD
                      advanced — disk rot on a step every marker calls
                      good. Only the restore ladder's digest
                      verification can catch it (the save already
                      verified; structure stays intact).
    replica_divergence  driver.train (round 12), one event per step:
                      perturbs ONE data-parallel replica's input to
                      the in-graph SDC param fingerprint (the probe
                      lane of train_parallel.make_sdc_fingerprint_fn).
                      A GSPMD program cannot make a logically
                      replicated array actually diverge — real SDC is
                      a hardware fault below the program — so the
                      injection perturbs the detector's per-replica
                      view instead, driving the IDENTICAL detection →
                      incident → rollback path a truly diverged
                      replica would: fingerprints disagree, health flags the
                      step, the ladder rolls back (re-replicating
                      params from the checkpoint — the real-SDC fix).

The plan is installed process-globally (`install`/`clear`); sites are
consulted via `fire(site)` which is a no-op returning None when no
plan is active (zero overhead on production paths). Multi-process
topologies (remote actor children) ship the plan through the
`SA_FAULT_PLAN` env var as JSON (`to_json`/`from_json`) and install it
themselves at startup.

Determinism note: event counters are global per site. When several
actor threads share a site ('env_step'), WHICH thread draws the firing
index depends on scheduling, but the NUMBER and KIND of faults fired
is exactly the schedule — the property the chaos SLOs assert on.
"""

import dataclasses
import json
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

SITES = ('env_step', 'transport_send', 'checkpoint_save', 'nan_burst',
         'slot_exhaustion', 'preempt_signal', 'slow_learner',
         'conn_partition', 'conn_delay', 'learner_crash',
         'wire_bitflip', 'publish_corrupt', 'ckpt_bitrot',
         'replica_divergence')

_LEN = struct.Struct('>Q')


class InjectedFault(RuntimeError):
  """An exception raised by fault injection (never by real code) —
  recovery paths can tell scripted damage from organic failures."""


@dataclasses.dataclass(frozen=True)
class Fault:
  site: str    # one of SITES
  index: int   # the site's event counter value at which to fire
  kind: str    # site-specific: raise|hang|drop|garbage|truncate|
               # interrupt|nan
  param: float = 0.0  # kind-specific (hang seconds, ...)

  def __post_init__(self):
    if self.site not in SITES:
      raise ValueError(f'unknown fault site {self.site!r} '
                       f'(sites: {SITES})')


class FaultPlan:
  """A deterministic schedule of faults + per-site event counters.

  Thread-safe: `fire` is called from actor threads, the learner loop,
  and checkpoint saves concurrently.
  """

  def __init__(self, faults: List[Fault], seed: int = 0):
    self._seed = int(seed)
    self._table: Dict[str, Dict[int, Fault]] = {}
    for f in faults:
      self._table.setdefault(f.site, {})[int(f.index)] = f
    self._counters: Dict[str, int] = {site: 0 for site in SITES}
    self._fired: Dict[str, int] = {site: 0 for site in SITES}
    self._lock = threading.Lock()

  @property
  def seed(self) -> int:
    return self._seed

  def faults(self) -> List[Fault]:
    return sorted((f for per in self._table.values()
                   for f in per.values()),
                  key=lambda f: (f.site, f.index))

  def covers(self, site: str) -> bool:
    """Whether any fault targets `site` (drives e.g. whether envs get
    wrapped at all — uncovered sites stay zero-cost)."""
    return bool(self._table.get(site))

  def fire(self, site: str) -> Optional[Fault]:
    """Advance `site`'s event counter; return the fault scheduled at
    the pre-advance index, if any."""
    with self._lock:
      idx = self._counters[site]
      self._counters[site] = idx + 1
      fault = self._table.get(site, {}).get(idx)
      if fault is not None:
        self._fired[site] += 1
      return fault

  def stats(self) -> Dict[str, Dict[str, int]]:
    with self._lock:
      return {site: {'events': self._counters[site],
                     'fired': self._fired[site],
                     'scheduled': len(self._table.get(site, {}))}
              for site in SITES}

  # --- serialization (cross-process: SA_FAULT_PLAN env var) ---

  def to_json(self) -> str:
    return json.dumps({'seed': self._seed,
                       'faults': [dataclasses.asdict(f)
                                  for f in self.faults()]})

  @classmethod
  def from_json(cls, payload: str) -> 'FaultPlan':
    obj = json.loads(payload)
    return cls([Fault(**f) for f in obj['faults']],
               seed=obj.get('seed', 0))

  @classmethod
  def storm(cls, seed: int,
            env_raise_at: Optional[int] = None,
            env_hang_at: Optional[int] = None,
            env_hang_secs: float = 3.0,
            transport: Optional[List[str]] = None,
            transport_start: int = 3,
            transport_stride: int = 4,
            nan_burst_at: Optional[int] = None,
            nan_burst_len: int = 0,
            checkpoint_interrupt_at: Optional[int] = None,
            slot_exhaustion_at: Optional[int] = None,
            slot_exhaustion_len: int = 0,
            preempt_at: Optional[int] = None,
            slow_learner_at: Optional[int] = None,
            slow_learner_len: int = 0,
            slow_learner_secs: float = 0.5,
            conn_partition_at: Optional[int] = None,
            conn_partition_secs: float = 3.0,
            conn_delay: Optional[List[int]] = None,
            conn_delay_secs: float = 0.2,
            learner_crash_at: Optional[int] = None,
            wire_bitflip: Optional[List[int]] = None,
            publish_corrupt_at: Optional[int] = None,
            publish_corrupt_len: int = 1,
            ckpt_bitrot_at: Optional[int] = None,
            replica_divergence_at: Optional[int] = None,
            replica_divergence_len: int = 0
            ) -> 'FaultPlan':
    """The scripted multi-fault storm chaos.py runs: one builder so
    the schedule is a pure function of its arguments (+ seed, which
    only perturbs garbage payload content, not the schedule)."""
    faults: List[Fault] = []
    if env_raise_at is not None:
      faults.append(Fault('env_step', env_raise_at, 'raise'))
    if env_hang_at is not None:
      faults.append(Fault('env_step', env_hang_at, 'hang',
                          param=env_hang_secs))
    for i, kind in enumerate(transport or []):
      faults.append(Fault('transport_send',
                          transport_start + i * transport_stride, kind))
    for i in range(nan_burst_len):
      faults.append(Fault('nan_burst', (nan_burst_at or 0) + i, 'nan'))
    if checkpoint_interrupt_at is not None:
      faults.append(Fault('checkpoint_save', checkpoint_interrupt_at,
                          'interrupt'))
    for i in range(slot_exhaustion_len):
      faults.append(Fault('slot_exhaustion',
                          (slot_exhaustion_at or 0) + i, 'force'))
    if preempt_at is not None:
      faults.append(Fault('preempt_signal', preempt_at, 'drain'))
    for i in range(slow_learner_len):
      faults.append(Fault('slow_learner', (slow_learner_at or 0) + i,
                          'hang', param=slow_learner_secs))
    if conn_partition_at is not None:
      faults.append(Fault('conn_partition', conn_partition_at,
                          'blackhole', param=conn_partition_secs))
    for idx in conn_delay or []:
      faults.append(Fault('conn_delay', idx, 'delay',
                          param=conn_delay_secs))
    if learner_crash_at is not None:
      faults.append(Fault('learner_crash', learner_crash_at, 'kill'))
    for idx in wire_bitflip or []:
      faults.append(Fault('wire_bitflip', idx, 'flip'))
    if publish_corrupt_at is not None:
      # A LENGTH, not one shot: publishes are cached per version and
      # replaced on a cadence — a single corrupt blob can be
      # superseded before any client fetches it, so the storm
      # corrupts a RUN of consecutive publishes to guarantee the
      # fleet meets one.
      for i in range(max(publish_corrupt_len, 1)):
        faults.append(Fault('publish_corrupt', publish_corrupt_at + i,
                            'flip'))
    if ckpt_bitrot_at is not None:
      faults.append(Fault('ckpt_bitrot', ckpt_bitrot_at, 'flip'))
    for i in range(replica_divergence_len):
      faults.append(Fault('replica_divergence',
                          (replica_divergence_at or 0) + i, 'perturb'))
    return cls(faults, seed=seed)


# --- process-global registry ---

_active_lock = threading.Lock()
_active: Optional[FaultPlan] = None

PLAN_ENV_VAR = 'SA_FAULT_PLAN'


def install(plan: Optional[FaultPlan]) -> None:
  global _active
  with _active_lock:
    _active = plan


def clear() -> None:
  install(None)


def active() -> Optional[FaultPlan]:
  return _active


def install_from_env() -> Optional[FaultPlan]:
  """Install the plan serialized in SA_FAULT_PLAN, if any (chaos.py's
  remote-actor child calls this before run_remote_actor)."""
  payload = os.environ.get(PLAN_ENV_VAR)
  if not payload:
    return None
  plan = FaultPlan.from_json(payload)
  install(plan)
  return plan


def fire(site: str) -> Optional[Fault]:
  """Consult the active plan; None when no plan is installed (the
  common production case — one global read, no lock)."""
  plan = _active
  if plan is None:
    return None
  return plan.fire(site)


# --- site: env_step ---


class FaultyEnv:
  """Environment wrapper consulting the plan on every step.

  'raise' propagates an InjectedFault out of env.step — exactly the
  shape of an organic env crash (the fleet's respawn path runs).
  'hang' sleeps `param` seconds while the step is in flight — the
  shape of a wedged simulator (heartbeats go stale; stall detection
  must orphan the thread and respawn the slot).
  """

  def __init__(self, env):
    self._env = env

  def initial(self):
    return self._env.initial()

  def step(self, action):
    fault = fire('env_step')
    if fault is not None:
      if fault.kind == 'raise':
        raise InjectedFault('env_step: injected crash')
      if fault.kind == 'hang':
        time.sleep(float(fault.param))
      # unknown kinds fall through: a typo'd schedule should not
      # silently change the no-fault behavior mid-run
    return self._env.step(action)

  def close(self):
    return self._env.close()

  def __getattr__(self, name):
    return getattr(self._env, name)


def maybe_wrap_env(env):
  """Wrap `env` iff the active plan targets env_step (otherwise the
  production object is returned untouched — zero indirection)."""
  plan = _active
  if plan is not None and plan.covers('env_step'):
    return FaultyEnv(env)
  return env


# --- site: transport_send ---


def apply_transport_fault(fault: Fault, sock: socket.socket,
                          seed: int = 0) -> None:
  """Damage `sock` per `fault` and raise the OSError the caller's
  reconnect path expects. 'garbage' ships a well-framed message of
  seeded random bytes (the receiver must fail parsing and quarantine
  the connection, not crash); 'truncate' claims more bytes than it
  sends (the receiver sees EOF mid-message); 'drop' just dies
  mid-conversation."""
  import numpy as np
  try:
    if fault.kind == 'garbage':
      rng = np.random.RandomState((seed + fault.index) % (2 ** 31))
      payload = rng.bytes(256)
      sock.sendall(_LEN.pack(len(payload)) + payload)
    elif fault.kind == 'truncate':
      rng = np.random.RandomState((seed + fault.index) % (2 ** 31))
      payload = rng.bytes(128)
      sock.sendall(_LEN.pack(len(payload) * 4) + payload)
    # 'drop' and unknown kinds: no bytes, just the close below.
  except OSError:
    pass  # the peer may already be gone; the raise below still runs
  try:
    sock.close()
  except OSError:
    pass
  raise ConnectionError(
      f'injected transport fault: {fault.kind} (index {fault.index})')


# --- sites: conn_partition / conn_delay (round 11) ---


def apply_conn_partition(fault: Fault) -> None:
  """Blackhole the connection for `fault.param` seconds: the caller
  goes completely silent — no send, no recv, NO close — exactly the
  half-open shape a network partition produces (the peer's socket
  stays ESTABLISHED with nothing flowing). Returns when the partition
  'heals'; the caller then proceeds normally and discovers whatever
  the other side did meanwhile (idle reap → RST on the next send)."""
  time.sleep(float(fault.param))


def apply_conn_delay(fault: Fault, seed: int = 0) -> None:
  """Injected transport latency: 'delay' sleeps exactly `param`
  seconds (deterministic — tests assert the floor); 'jitter' sleeps a
  seeded U[0, param]."""
  if fault.kind == 'jitter':
    import numpy as np
    rng = np.random.RandomState((seed + fault.index) % (2 ** 31))
    time.sleep(float(rng.uniform(0.0, float(fault.param))))
  else:
    time.sleep(float(fault.param))


# --- site: learner_crash ---


def hard_crash(fault: Fault) -> None:
  """kill -9 the current process: no exception unwind, no finally
  blocks, no drain, no 'bye' frame — the OOM-killer/preempt shape the
  restart story (docs/RUNBOOK.md §8) must survive. Logged first so
  the chaos harness can tell a scheduled crash from an organic one."""
  import logging
  import signal
  logging.getLogger('scalable_agent_tpu').error(
      'learner_crash fault firing (index %d): hard-killing pid %d',
      fault.index, os.getpid())
  os.kill(os.getpid(), signal.SIGKILL)


# --- site: checkpoint_save ---


def corrupt_checkpoint_step(directory: str, step: int) -> List[str]:
  """Simulate a save killed mid-write: truncate every non-trivial file
  of the step's directory to half its bytes (metadata/commit markers
  are left in place, so the step still LISTS as the newest — the
  dead-end `restore_latest` used to hit). Returns the damaged paths.
  Shared by the checkpoint_save site and the checkpoint tests."""
  step_dir = None
  for name in os.listdir(directory):
    path = os.path.join(directory, name)
    if os.path.isdir(path) and name.split('.')[-1] == str(step):
      step_dir = path
      break
    if os.path.isdir(path) and name == str(step):
      step_dir = path
      break
  if step_dir is None:
    raise FileNotFoundError(
        f'no step directory for step {step} under {directory}')
  damaged = []
  for root, _, files in os.walk(step_dir):
    for fname in files:
      fpath = os.path.join(root, fname)
      size = os.path.getsize(fpath)
      if size >= 32:
        with open(fpath, 'r+b') as f:
          f.truncate(size // 2)
        damaged.append(fpath)
  return damaged


# --- site: wire_bitflip ---


def apply_wire_bitflip(fault: Fault, segments, seed: int = 0):
  """One seeded bit flip in the LARGEST raw-buffer segment of an
  outgoing OOB frame — after the CRC trailer was computed, so the
  receiver's v7 check sees exactly the silent-corruption shape: a
  frame that parses (the flip lands in array bytes, not the pickle
  skeleton) with a stale trailer. Returns a NEW segment list; the
  caller's unroll (aliased by the other segments) is never touched,
  so its scripted re-send ships clean bytes."""
  import numpy as np
  from scalable_agent_tpu import integrity
  if len(segments) < 2:
    return segments  # no raw buffers to damage (tiny frame): no-op
  idx = max(range(1, len(segments)),
            key=lambda i: memoryview(segments[i]).nbytes)
  damaged = bytearray(segments[idx])
  rng = np.random.RandomState((seed + fault.index) % (2 ** 31))
  byte, bit = integrity.flip_bit(
      damaged, int(rng.randint(0, max(len(damaged) * 8, 1))))
  import logging
  logging.getLogger('scalable_agent_tpu').warning(
      'wire_bitflip fault firing (index %d): flipped bit %d of byte '
      '%d in a %d-byte frame segment', fault.index, bit, byte,
      len(damaged))
  return segments[:idx] + [memoryview(damaged)] + segments[idx + 1:]


# --- site: publish_corrupt ---


def corrupt_params_tree(fault: Fault, params, seed: int = 0):
  """Return `params` with ONE seeded bit flipped in its largest leaf
  — host-memory rot between the digest computation and the wire
  serialization. The caller computes the content digest BEFORE this
  runs, so the shipped blob's frame CRC is self-consistent and only
  the receiving client's digest check can catch the damage. Leaves
  other than the victim alias the input (no tree copy). Dtype is NOT
  filtered on: the wire form may be ml_dtypes.bfloat16 (numpy kind
  'V'), and rot does not care what it flips."""
  import jax
  import numpy as np
  from scalable_agent_tpu import integrity
  leaves, treedef = jax.tree_util.tree_flatten(params)
  candidates = [i for i, leaf in enumerate(leaves)
                if np.asarray(leaf).size > 0]
  if not candidates:
    return params
  victim = max(candidates, key=lambda i: np.asarray(leaves[i]).nbytes)
  arr = np.array(leaves[victim], copy=True)
  raw = bytearray(arr.tobytes())
  rng = np.random.RandomState((seed + fault.index) % (2 ** 31))
  integrity.flip_bit(raw, int(rng.randint(0, len(raw) * 8)))
  leaves[victim] = np.frombuffer(
      bytes(raw), dtype=arr.dtype).reshape(arr.shape)
  import logging
  logging.getLogger('scalable_agent_tpu').warning(
      'publish_corrupt fault firing (index %d): flipped one bit in a '
      '%d-byte param leaf after digest', fault.index, len(raw))
  return jax.tree_util.tree_unflatten(treedef, leaves)


# --- site: ckpt_bitrot ---


def bitrot_checkpoint_step(directory: str, step: int,
                           seed: int = 0) -> str:
  """Flip ONE byte mid-file in the largest file of a COMMITTED step
  directory — disk rot after the save verified and LAST_GOOD advanced
  (distinct from corrupt_checkpoint_step's half-truncated
  mid-write shape, which the PR 2 ladder already catches without
  digests). Returns the damaged path."""
  import numpy as np
  step_dir = None
  for name in os.listdir(directory):
    path = os.path.join(directory, name)
    if os.path.isdir(path) and (name == str(step)
                                or name.split('.')[-1] == str(step)):
      step_dir = path
      break
  if step_dir is None:
    raise FileNotFoundError(
        f'no step directory for step {step} under {directory}')
  candidates = []
  for root, _, files in os.walk(step_dir):
    for fname in files:
      fpath = os.path.join(root, fname)
      candidates.append((os.path.getsize(fpath), fpath))
  if not candidates:
    raise FileNotFoundError(f'step {step} directory is empty')
  size, target = max(candidates)
  rng = np.random.RandomState((seed + step) % (2 ** 31))
  offset = int(rng.randint(0, max(size, 1)))
  with open(target, 'r+b') as f:
    f.seek(offset)
    byte = f.read(1) or b'\x00'
    f.seek(offset)
    f.write(bytes((byte[0] ^ (1 << int(rng.randint(0, 8))),)))
  import logging
  logging.getLogger('scalable_agent_tpu').warning(
      'ckpt_bitrot fault: flipped one bit at offset %d of %s', offset,
      target)
  return target


# --- site: nan_burst ---


def poison_batch(batch):
  """Return `batch` with its rewards replaced by NaN (device-side op:
  the batch is already staged). Drives a non-finite loss/grad through
  the REAL loss, so the watchdog sees exactly what organic divergence
  produces."""
  import jax.numpy as jnp
  env_outputs = batch.env_outputs._replace(
      reward=jnp.full_like(batch.env_outputs.reward, jnp.nan))
  return batch._replace(env_outputs=env_outputs)


def maybe_poison_batch(batch):
  """Consult the nan_burst site once (one learner step = one event);
  poison when scheduled."""
  fault = fire('nan_burst')
  if fault is not None:
    return poison_batch(batch), True
  return batch, False
