"""Host-side actor: rolls environments into learner-ready unrolls.

Re-expresses the reference's `build_actor` (reference: experiment.py
≈L215–300) outside the graph: on TPU the env loop is host Python while
inference runs on-device (directly jitted, or via the dynamic batcher) —
there is no in-graph `tf.scan` over env steps to port.

Faithfully preserved semantics:
- persistent cross-unroll state (env output, agent output, LSTM state) —
  the reference's local TF variables (≈L235);
- the 1-frame overlap: each `ActorOutput` has T+1 timesteps, timestep 0
  being the previous unroll's last (env_output, agent_output) (≈L285);
- `agent_state` in the output is the LSTM state at the *start* of the
  unroll;
- episode statistics flow *through* the trajectory as `StepOutputInfo`
  (the reference's FlowEnvironment state machine, environments.py
  ≈L165–190): the output at a done step carries the finished episode's
  stats while the carried state resets to zero.
"""

import threading
import time
from typing import Callable, Optional

import numpy as np

from scalable_agent_tpu import telemetry
from scalable_agent_tpu.structs import (
    ActorOutput, AgentOutput, StepOutput, StepOutputInfo)

# run_actor_loop's put is a POLL (not one unbounded block): each
# timeout re-checks the stop event, so a stopping/quiescing fleet can
# join producers parked on a full buffer even when nobody closes it.
_PUT_POLL_SECS = 0.5
# After stop is requested, how long a parked producer keeps trying to
# land its completed unroll before dropping it and exiting (the drain
# path WANTS the unroll — the learner is flushing and room appears;
# this bound only fires when nothing is draining, where the old
# behavior was an unjoinable thread).
_STOP_PUT_GRACE_SECS = 5.0


def _tree_stack(items):
  """Stack a list of identically-structured pytrees of np arrays."""
  import jax
  return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *items)


class Actor:
  """One environment + its rollout state.

  Args:
    env: an `envs.base.Environment`.
    policy: callable `(prev_action i32[], env_output StepOutput of
      scalars, core_state) -> (AgentOutput of scalars, new_core_state)`.
      This is where inference plugs in — a direct jitted call for tests,
      the dynamic-batching client in production.
    initial_core_state: zeroed LSTM state for one env (no batch dim or
      batch dim 1, policy-defined — the actor treats it opaquely).
    unroll_length: T (the output carries T+1 with the overlap frame).
    num_action_repeats: frames per env step, for episode_step accounting
      (frames unit matches the reference's global step).
    level_name_id: int id standing in for the reference's level-name
      string (strings don't cross the device boundary; the mapping lives
      in dmlab30.py / the driver).
  """

  def __init__(self, env, policy: Callable, initial_core_state,
               unroll_length: int, num_action_repeats: int = 1,
               level_name_id: int = 0):
    self._env = env
    self._policy = policy
    self._unroll_length = unroll_length
    self._num_action_repeats = num_action_repeats
    self._level_name_id = np.int32(level_name_id)

    observation = env.initial()
    self._env_output = StepOutput(
        reward=np.float32(0.0),
        info=StepOutputInfo(np.float32(0.0), np.int32(0)),
        done=np.bool_(True),  # first obs starts an episode, like reference
        observation=observation)
    self._core_state = initial_core_state
    self._zero_core_state = initial_core_state
    self._agent_output: Optional[AgentOutput] = None
    self._episode_return = np.float32(0.0)
    self._episode_step = np.int32(0)

  def unroll(self) -> ActorOutput:
    """Produce one ActorOutput of [T+1] time-major numpy arrays."""
    # Device-resident policy state (InferenceServer state-cache mode)
    # is an opaque handle: the learner still needs the NUMERIC carry
    # at the unroll start, so snapshot it here — the once-per-unroll
    # host read that replaces the old once-per-step carry round trip.
    core0 = self._core_state
    if hasattr(core0, 'snapshot'):
      initial_core_state = core0.snapshot()
    else:
      initial_core_state = core0
    env_outputs = [self._env_output]
    if self._agent_output is None:
      # Prime lazily so we know num_actions from the first policy call.
      out, _ = self._policy(np.int32(0), self._env_output,
                            self._core_state)
      if hasattr(core0, 'write'):
        # The carry-passing path DISCARDS the priming call's new state;
        # a device-resident state advanced in-graph must be put back,
        # or the cache path would start the unroll one step ahead
        # (parity gate in tests/test_runtime.py).
        core0.write(initial_core_state)
      self._agent_output = AgentOutput(
          action=np.int32(0),
          policy_logits=np.zeros_like(np.asarray(out.policy_logits)),
          baseline=np.float32(0.0))
    agent_outputs = [self._agent_output]

    for _ in range(self._unroll_length):
      agent_output, core_state = self._policy(
          self._agent_output.action, self._env_output, self._core_state)
      agent_output = AgentOutput(
          *[np.asarray(x) for x in agent_output])
      reward, done, observation = self._env.step(
          int(agent_output.action))

      # Flow-style episode accounting (output carries final stats at
      # done; carried state resets).
      self._episode_return = np.float32(self._episode_return + reward)
      self._episode_step = np.int32(
          self._episode_step + self._num_action_repeats)
      info = StepOutputInfo(self._episode_return, self._episode_step)
      if done:
        self._episode_return = np.float32(0.0)
        self._episode_step = np.int32(0)

      env_output = StepOutput(np.float32(reward), info, np.bool_(done),
                              observation)
      env_outputs.append(env_output)
      agent_outputs.append(agent_output)
      self._env_output = env_output
      self._agent_output = agent_output
      self._core_state = core_state

    return ActorOutput(
        level_name=self._level_name_id,
        agent_state=initial_core_state,
        env_outputs=_tree_stack(env_outputs),
        agent_outputs=_tree_stack(agent_outputs))

  def release_policy_state(self):
    """Return device-resident policy state (a state-arena slot) to its
    server; no-op for plain numeric carries. Idempotent — called from
    close() on every exit path and defensively by the fleet's respawn
    (a thread killed before its finally ran must not leak the slot)."""
    state = self._core_state
    if hasattr(state, 'release'):
      try:
        state.release()
      except Exception:
        pass

  def close(self):
    self.release_policy_state()
    self._env.close()


def run_actor_loop(actor: Actor, buffer, stop_event,
                   on_unroll: Optional[Callable[[], bool]] = None,
                   on_failure: Optional[Callable] = None) -> None:
  """Produce unrolls into `buffer` until stopped (thread target).

  THE actor loop — the fleet (`runtime.fleet.ActorFleet`) and
  standalone threads both run this, so there is exactly one
  shutdown/poison contract:

  - Clean shutdown: a closed buffer or a cancelled inference call
    (batcher closed) while `stop_event` is set is normal termination,
    mirroring the reference's closed-pipe → StopIteration convention
    (reference: py_process.py ≈L72).
  - Real failure (the same exceptions while NOT stopping, or any other
    exception): by default the buffer is poisoned — closed, so the
    learner's next get raises instead of hanging — and the exception
    surfaces on this thread. `on_failure(exc)` overrides this (the
    fleet records the error on its slot and keeps the shared buffer
    open for the other actors).

  Args:
    actor: the Actor to roll (closed on exit, always).
    buffer: TrajectoryBuffer receiving unrolls.
    stop_event: threading.Event signalling shutdown.
    on_unroll: called after each successful put; returning False ends
      the loop (the fleet's orphaned-slot check). None = run forever.
    on_failure: called with the failure exception instead of the
      default poison-and-raise.
  """
  from scalable_agent_tpu.ops.dynamic_batching import BatcherCancelled
  from scalable_agent_tpu.runtime import ring_buffer

  def fail(exc):
    if on_failure is None:
      buffer.close()
      raise exc
    on_failure(exc)

  # Trace-span stamping (round 13, telemetry.py): when tracing is on
  # in this process, each completed unroll gets a fresh trace context
  # — actor id (the fleet's thread name), per-loop sequence, the
  # behaviour params version — stamped HOP_DONE here at env-step
  # completion and carried beside the unroll (identity-keyed sidecar;
  # the pytree itself cannot grow a leaf without breaking the wire
  # contract). Downstream hops stamp at ingest/staging/step; a remote
  # pump pops the tag and ships it on the v8 wire.
  actor_name = threading.current_thread().name
  unroll_seq = 0

  try:
    while not stop_event.is_set():
      unroll = actor.unroll()
      trace = telemetry.begin_unroll_trace(actor_name, unroll_seq)
      if trace is not None:
        telemetry.stamp(trace, telemetry.HOP_DONE)
        telemetry.tag_unroll(unroll, trace)
      unroll_seq += 1
      # Poll-put with a stop-aware grace (round 11): an actor parked
      # on a full buffer used to block UNBOUNDED — quiesce() (which
      # deliberately keeps the buffer open so in-flight unrolls land)
      # could never join it unless the learner drained. Now the park
      # re-checks the stop event every poll; once stopping, the unroll
      # gets a bounded grace to land (the drain path drains, so it
      # normally does) and is then dropped — a joined thread with a
      # named lost unroll beats a wedged one.
      stop_deadline = None
      while True:
        try:
          buffer.put(unroll, timeout=_PUT_POLL_SECS)
          break
        except TimeoutError:
          if not stop_event.is_set():
            continue
          if stop_deadline is None:
            stop_deadline = time.monotonic() + _STOP_PUT_GRACE_SECS
          elif time.monotonic() > stop_deadline:
            return  # stopping and nobody is draining: drop + exit
      if on_unroll is not None and not on_unroll():
        return  # orphaned: a replacement owns this actor's slot
  except (ring_buffer.Closed, BatcherCancelled) as e:
    if not stop_event.is_set():
      fail(e)
  except BaseException as e:
    fail(e)
  finally:
    try:
      actor.close()
    except Exception:
      pass


def batch_unrolls(unrolls):
  """Stack B ActorOutputs into a learner batch: time-major [T+1, B] for
  the trajectory, [B, ...] for level_name/agent_state (no time axis)."""
  import jax
  env_outputs = jax.tree_util.tree_map(
      lambda *xs: np.stack(xs, axis=1), *[u.env_outputs for u in unrolls])
  agent_outputs = jax.tree_util.tree_map(
      lambda *xs: np.stack(xs, axis=1),
      *[u.agent_outputs for u in unrolls])
  level = np.stack([u.level_name for u in unrolls])
  # Per-actor core states carry batch dim 1 ([1, hidden] leaves);
  # concatenating gives the learner's [B, hidden].
  agent_state = jax.tree_util.tree_map(
      lambda *xs: np.concatenate(xs, axis=0),
      *[u.agent_state for u in unrolls])
  return ActorOutput(level, agent_state, env_outputs, agent_outputs)
