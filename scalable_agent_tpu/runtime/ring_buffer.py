"""Trajectory transport: bounded unroll buffer + device prefetch.

Replaces the reference's learner-hosted `tf.FIFOQueue(capacity=1)` +
`StagingArea` double-buffer (reference: experiment.py ≈L470, ≈L540–560;
SURVEY §2.b "async pipeline"):

- `TrajectoryBuffer`: a bounded ring of completed unrolls. Producers
  (actor threads) block when full — capacity IS the backpressure that
  bounds policy lag, exactly the reference's capacity-1 queue semantics
  (lag ≤ capacity + in-flight unroll + staged batch).
- `BatchPrefetcher`: one thread that assembles [T+1, B] batches and
  stages the next `depth` device batches while the learner trains on
  the current one (the StagingArea role, default depth 2 —
  config.staging_depth). `place_fn` is where `jax.device_put` with
  data-axis shardings happens, so staging overlaps host→HBM transfer
  with TPU compute; with depth >= 2 consecutive transfers also
  overlap each other (the r5 fed bench measured H2D as the dominant
  feed-gap term).
- `UnrollBatchStager` (round 8, config.staging_mode='unroll'): the
  device-resident alternative to the host stack + one-burst
  `device_put`. Each completed unroll is `device_put` the moment it
  leaves the buffer — placed directly on the device owning its batch
  slot — and the [T+1, B] batch is assembled ON DEVICE by a jitted,
  donated `dynamic_update_slice` arena, so the step-boundary H2D
  burst (BENCH_r05: h2d_ms 1430.5 on a 67.5 MB batch) becomes a
  per-unroll trickle overlapped with the previous step's compute, and
  the host-side `batch_unrolls` stack (stack_ms 37.5) leaves the hot
  path entirely. Golden parity: `dynamic_update_slice` of the same
  values is bit-identical to the host-stack + transfer path
  (tests/test_learner_plane.py).

Episode stats ride inside the trajectories (StepOutputInfo), so there
is no side channel to drain — consume them from the dequeued batch
like the reference's learner loop does (≈L590–620).

Round 10 adds the sample-reuse tier (IMPACT, arXiv 1912.00167;
docs/PERF.md r9): `ReplayTier` is a circular arena of already-consumed
unrolls sitting BEHIND the TrajectoryBuffer — `get_unrolls` composes
each batch fresh:replayed per the replay ratio — and the
`BatchPrefetcher` re-serves every staged device batch `replay_k` times
before release (the staged arena is handed out AS IS: no re-stage, no
additional H2D), multiplying learner updates per env frame while the
actor/env plane stays the rate limiter it measures as.
"""

import collections
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from scalable_agent_tpu import integrity
from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock
from scalable_agent_tpu.runtime.actor import batch_unrolls
from scalable_agent_tpu.structs import ActorOutput

log = logging.getLogger('scalable_agent_tpu')


class Closed(Exception):
  """The buffer was closed while blocking."""


class ReplayTier:
  """Circular replay arena of completed unrolls (round 10 — IMPACT's
  circular buffer, host tier).

  Consumed unrolls are retained (by reference — they are immutable
  host numpy once the actor enqueued them) with the param version
  current at retention time. `sample(n)` hands out up to n unrolls via
  a circular read cursor (IMPACT reads its buffer sequentially, not
  uniformly — recent data recurs at a bounded cadence), evicting
  entries that aged past the staleness window in passing. Eviction is
  two-fold and separately counted:

  - by AGE: the ring is full and a new unroll overwrites the oldest
    (`evictions_age`) — capacity IS the age bound;
  - by VERSION: an entry's retention-time param version has fallen
    more than `max_staleness` PUBLISHED VERSIONS behind the current
    one (`evictions_version`). The unit is the same param-version
    delta `--max_unroll_staleness` uses for ingest admission (the
    round-10 unification); 0 = no version bound.
  - by CONTENT (round 12, `verify_crc`): each entry keeps the CRC of
    its bytes at INSERT time and is re-verified at every serve — a
    retained unroll sitting in host memory for thousands of serves is
    exactly where silent RAM rot would otherwise be multiplied into
    the batch mix K times over. A mismatch evicts instead of serving
    (`evictions_crc`), the host-tier sibling of the wire CRC and the
    checkpoint digest ladder.

  Thread-safe (own lock; never calls back into the buffer).
  """

  # Lock discipline (round 18, guarded-by lint). The public eviction/
  # reuse counters stay unannotated on purpose: their fn-gauge reads
  # are lock-free by design (torn-read-benign ints, documented below).
  _entries: guarded_by('_lock')
  _cursor: guarded_by('_lock')
  _version: guarded_by('_lock')
  _staleness_sum: guarded_by('_lock')
  _staleness_samples: guarded_by('_lock')
  _last_sample: guarded_by('_lock')

  def __init__(self, capacity_unrolls: int, max_staleness: int = 0,
               verify_crc: bool = True):
    if capacity_unrolls < 1:
      raise ValueError('replay capacity must be >= 1')
    self._capacity = capacity_unrolls
    self._max_staleness = max_staleness
    self._verify_crc = bool(verify_crc)
    self._entries = collections.deque()  # (unroll, version, crc|None)
    self._cursor = 0
    self._lock = make_lock('ring_buffer.ReplayTier._lock')
    self._version = 0
    # Telemetry (summary surface via TrajectoryBuffer.stats()).
    self.evictions_age = 0
    self.evictions_version = 0
    self.evictions_crc = 0
    self.reused_unrolls = 0
    self._staleness_sum = 0
    self._staleness_samples = 0
    self._last_sample = (0, 0)  # (count, staleness_sum) — unsample_last
    # Unified-registry view (round 13): lazy gauges over the counters
    # above — the module-local bookkeeping stays authoritative (and
    # lock-guarded for mutation); the registry reads it. Lock-free
    # reads of ints are torn-read-benign. Handles kept so the owning
    # buffer's close() can unregister them (fn-gauges close over
    # `self` — an unregistered gauge is what lets a finished run's
    # tier be collected).
    self._gauges = [
        telemetry.gauge('replay/occupancy',
                        fn=lambda: len(self._entries)),
        telemetry.gauge('replay/evictions_age',
                        fn=lambda: self.evictions_age),
        telemetry.gauge('replay/evictions_version',
                        fn=lambda: self.evictions_version),
        telemetry.gauge('replay/evictions_crc',
                        fn=lambda: self.evictions_crc),
        telemetry.gauge('replay/reused_unrolls',
                        fn=lambda: self.reused_unrolls),
    ]

  def note_param_version(self, version: int):
    """Advance the current published param version (driver publish
    cadence) — the clock both staleness accounting and version
    eviction read."""
    with self._lock:
      self._version = max(self._version, int(version))

  def add(self, unroll: ActorOutput):
    # Insert-time content CRC, computed OUTSIDE the lock (one pass
    # over the unroll's bytes — ~0.1 ms/MB; the serve-side verify is
    # what catches rot accumulated while retained).
    crc = integrity.tree_digest(unroll) if self._verify_crc else None
    with self._lock:
      if len(self._entries) >= self._capacity:
        self._entries.popleft()
        self.evictions_age += 1
        if self._cursor > 0:
          self._cursor -= 1  # keep the cursor on the same entry
      self._entries.append((unroll, self._version, crc))

  def sample(self, n: int) -> List[ActorOutput]:
    """Up to `n` unrolls from the circular cursor (fewer when the
    tier is short, or when version/CRC eviction thins the pick). Each
    DELIVERED serve counts toward `reused_unrolls` and the
    mean-staleness accumulator.

    The serve-time CRC verification (a full pass over each multi-MB
    unroll) runs OUTSIDE the lock — holding it would stall every
    producer's `add()` behind milliseconds of hashing on the learner
    feed path (the same reason the insert-side CRC sits outside).
    Rotted entries found in the verify phase are evicted by IDENTITY
    on re-acquire (never by ==: tuples of numpy arrays don't
    compare), with the cursor adjusted; a rotted pick shrinks this
    call's batch instead of rescanning — the next call refills."""
    picked: List[Tuple] = []  # (entry, staleness), CRC pending
    with self._lock:
      budget = len(self._entries)  # at most one full lap per call
      while len(picked) < n and self._entries and budget > 0:
        budget -= 1
        if self._cursor >= len(self._entries):
          self._cursor = 0
        entry = self._entries[self._cursor]
        staleness = self._version - entry[1]
        if self._max_staleness and staleness > self._max_staleness:
          del self._entries[self._cursor]
          self.evictions_version += 1
          continue
        picked.append((entry, staleness))
        self._cursor += 1
    verified: List[Tuple] = []
    rotten: List[Tuple] = []
    for entry, staleness in picked:
      unroll, _, crc = entry
      if crc is not None and integrity.tree_digest(unroll) != crc:
        # Host-memory rot since insert: reuse must NEVER serve it
        # (replay would multiply the corruption into K batches).
        rotten.append(entry)
      else:
        verified.append((entry, staleness))
    with self._lock:
      for entry in rotten:
        for idx, cand in enumerate(self._entries):
          if cand is entry:
            del self._entries[idx]
            if idx < self._cursor:
              self._cursor -= 1
            self.evictions_crc += 1
            break
      sample_staleness = 0
      for _, staleness in verified:
        self.reused_unrolls += 1
        self._staleness_sum += staleness
        self._staleness_samples += 1
        sample_staleness += staleness
      self._last_sample = (len(verified), sample_staleness)
    return [entry[0] for entry, _ in verified]

  def unsample_last(self):
    """Undo the ACCOUNTING of the most recent sample() — the caller
    failed to deliver its batch (fresh-side timeout/close push-back in
    get_unrolls): the cursor steps back so the sequential scan
    re-serves the same entries next call, and the reuse/staleness
    counters forget them. Version evictions stand (the entries really
    were too stale). One outstanding sample at a time — the
    single-consumer prefetcher pattern; a repeated call is a no-op."""
    with self._lock:
      n, staleness_sum = self._last_sample
      self._last_sample = (0, 0)
      if n == 0:
        return
      if self._entries:
        self._cursor = (self._cursor - n) % len(self._entries)
      self.reused_unrolls -= n
      self._staleness_sum -= staleness_sum
      self._staleness_samples -= n

  def __len__(self):
    with self._lock:
      return len(self._entries)

  def stats(self):
    with self._lock:
      mean_staleness = (self._staleness_sum / self._staleness_samples
                        if self._staleness_samples else 0.0)
      return {
          'replay_occupancy': len(self._entries),
          'replay_capacity': self._capacity,
          'replay_evictions_age': self.evictions_age,
          'replay_evictions_version': self.evictions_version,
          'replay_evictions_crc': self.evictions_crc,
          'replay_reused_unrolls': self.reused_unrolls,
          'replay_mean_staleness': round(mean_staleness, 3),
      }


def _wait_until(cond: threading.Condition, predicate: Callable[[], bool],
                deadline: Optional[float], what: str):
  """Wait on `cond` (held) until predicate() or deadline; deadline-based
  so spurious wakeups under contention don't restart the clock."""
  while not predicate():
    remaining = None if deadline is None else deadline - time.monotonic()
    if remaining is not None and remaining <= 0:
      raise TimeoutError(f'{what} timed out')
    cond.wait(remaining)


class TrajectoryBuffer:
  """Bounded FIFO of unrolls with blocking put/get and backpressure.

  With a `ReplayTier` attached (round 10), every FRESH unroll dequeued
  is retained into the tier on its way out, and `get_unrolls` composes
  each batch's slots fresh-first:replayed per `replay_ratio`. The
  bounded FIFO semantics of the fresh path — backpressure, FIFO order,
  push-back on timeout/close — are untouched; the tier is pure
  retention behind it.
  """

  # Lock discipline (round 18, guarded-by lint): the deque, close
  # flag, and backpressure counters mutate only under _lock (the
  # conditions wrap the same mutex — the checker understands the
  # aliasing); fn-gauge reads in __init__ are exempt by convention.
  _deque: guarded_by('_lock')
  _closed: guarded_by('_lock')
  _high_water: guarded_by('_lock')
  _put_waits: guarded_by('_lock')
  _put_wait_secs: guarded_by('_lock')
  _fresh_unrolls: guarded_by('_lock')

  def __init__(self, capacity_unrolls: int,
               replay: Optional[ReplayTier] = None,
               replay_ratio: float = 0.0):
    if capacity_unrolls < 1:
      raise ValueError('capacity must be >= 1')
    if not 0.0 <= replay_ratio < 1.0:
      raise ValueError('replay_ratio must be in [0, 1)')
    if replay_ratio > 0 and replay is None:
      raise ValueError('replay_ratio > 0 needs a ReplayTier')
    self._capacity = capacity_unrolls
    self._replay = replay
    self._replay_ratio = replay_ratio
    self._deque = collections.deque()
    self._lock = make_lock('ring_buffer.TrajectoryBuffer._lock')
    self._not_full = threading.Condition(self._lock)
    self._not_empty = threading.Condition(self._lock)
    self._closed = False
    # Occupancy telemetry (round 9 — the bounded-queueing guard made
    # observable): the high-water mark (which also exposes get_batch's
    # transient push-back overshoot), and how often/long producers
    # actually blocked on the full buffer — the producer-side
    # backpressure the capacity bound exists to apply.
    self._high_water = 0
    self._put_waits = 0
    self._put_wait_secs = 0.0
    # Fresh-dequeue counter (round 10): cumulative unrolls that left
    # the FIFO toward the learner (stats()['fresh_unrolls']). NOTE
    # this runs AHEAD of training by the prefetch lookahead — frame
    # budgets and the learner_updates_per_env_frame denominator read
    # the prefetcher's serve-time fresh_slots_served instead.
    self._fresh_unrolls = 0
    # Unified-registry view (round 13): same pattern as the replay
    # tier — lazy gauges over this instance's occupancy/backpressure
    # counters, so the drain manifest / flight recorder / fleet stats
    # request read them without a stats() plumbing path. close()
    # unregisters them (identity-checked, so a newer buffer's
    # registration survives an older one's teardown).
    self._gauges = [
        telemetry.gauge('buffer/occupancy',
                        fn=lambda: len(self._deque)),
        telemetry.gauge('buffer/high_water',
                        fn=lambda: self._high_water),
        telemetry.gauge('buffer/put_waits',
                        fn=lambda: self._put_waits),
        telemetry.gauge('buffer/fresh_unrolls',
                        fn=lambda: self._fresh_unrolls),
    ]
    if replay is not None:
      self._gauges += replay._gauges

  @property
  def replay(self) -> Optional[ReplayTier]:
    return self._replay

  def note_param_version(self, version: int):
    """Driver publish cadence → the replay tier's staleness clock
    (no-op without a tier, so call sites stay unconditional)."""
    if self._replay is not None:
      self._replay.note_param_version(version)

  def put(self, unroll: ActorOutput, timeout: Optional[float] = None):
    """Block while full (backpressure). Raises Closed after close().

    The timeout bounds TOTAL blocking time (deadline-based — spurious
    wakeups under contention don't restart the clock)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._not_full:
      if len(self._deque) >= self._capacity and not self._closed:
        self._put_waits += 1
        t0 = time.monotonic()
        try:
          _wait_until(self._not_full,
                      lambda: (len(self._deque) < self._capacity
                               or self._closed),
                      deadline, 'TrajectoryBuffer.put')
        finally:
          self._put_wait_secs += time.monotonic() - t0
      if self._closed:
        raise Closed()
      self._deque.append(unroll)
      self._high_water = max(self._high_water, len(self._deque))
      self._not_empty.notify()

  def get(self, timeout: Optional[float] = None) -> ActorOutput:
    """Block while empty. Raises Closed after close() drains. Timeout
    bounds total blocking time (deadline-based)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._not_empty:
      _wait_until(self._not_empty,
                  lambda: self._deque or self._closed,
                  deadline, 'TrajectoryBuffer.get')
      if not self._deque:
        raise Closed()
      item = self._deque.popleft()
      self._fresh_unrolls += 1
      self._not_full.notify()
    if self._replay is not None:
      self._replay.add(item)
    return item

  def sample_replay(self, batch_size: int) -> List[ActorOutput]:
    """The replayed slice of one composed batch: up to
    floor(batch_size * replay_ratio) unrolls from the tier (fewer when
    it is short), [] without a tier. Sampled BEFORE the fresh fetch so
    a batch never replays an unroll it is also consuming fresh. Split
    out of get_unrolls so the unroll staging path can plan its slot
    composition while still staging each fresh unroll the moment it
    dequeues (the per-unroll trickle is the mode's whole point)."""
    if self._replay is None or self._replay_ratio == 0:
      return []
    return self._replay.sample(int(batch_size * self._replay_ratio))

  def get_unrolls(self, batch_size: int,
                  timeout: Optional[float] = None
                  ) -> Tuple[List[ActorOutput], int]:
    """Dequeue one batch's unrolls composed fresh:replayed (round 10).

    Returns `(unrolls, n_fresh)` — FRESH unrolls first (slots
    [0, n_fresh)), replayed after, so downstream stats peels can slice
    the env-plane view (episode events, action histograms) without
    double-counting replays. Replayed slots are sampled from the tier
    BEFORE the blocking fresh fetch (so a batch never replays an
    unroll it is also consuming fresh); with no tier or ratio 0 every
    slot is fresh and this is exactly the old `get_batch` dequeue.

    Fresh fetch semantics are unchanged from get_batch: incremental
    accumulation (dequeued unrolls free producer slots immediately),
    deadline-bounded blocking, and push-back to the FRONT on
    timeout/close so no trajectory is dropped (replayed samples need
    no push-back — the tier still holds them). Every completed fresh
    dequeue is retained into the replay tier."""
    replayed = self.sample_replay(batch_size)
    n_fresh = batch_size - len(replayed)
    deadline = None if timeout is None else time.monotonic() + timeout
    items: List[ActorOutput] = []
    with self._not_empty:
      try:
        while len(items) < n_fresh:
          _wait_until(self._not_empty,
                      lambda: self._deque or self._closed,
                      deadline, 'TrajectoryBuffer.get_batch')
          if not self._deque:  # closed and drained: partial batch
            raise Closed()
          while self._deque and len(items) < n_fresh:
            items.append(self._deque.popleft())
          self._not_full.notify_all()
      except (TimeoutError, Closed):
        # Push-back may transiently exceed capacity (up to capacity +
        # batch_size - 1): keeping trajectories beats the strict lag
        # bound on this error path; producers stay blocked until the
        # excess drains. Wake other consumers — the restored items are
        # consumable (lost-wakeup otherwise).
        self._deque.extendleft(reversed(items))
        self._high_water = max(self._high_water, len(self._deque))
        if items:
          self._not_empty.notify_all()
        if replayed:
          # The replayed slice never reached the learner either: give
          # its accounting back so the tier's sequential scan and the
          # reuse/staleness counters only see DELIVERED serves.
          self._replay.unsample_last()
        raise
      self._fresh_unrolls += len(items)
    if self._replay is not None:
      for item in items:
        self._replay.add(item)
    return items + replayed, n_fresh

  def get_batch(self, batch_size: int,
                timeout: Optional[float] = None) -> ActorOutput:
    """Dequeue `batch_size` unrolls and stack to a [T+1, B] batch (the
    reference's `dequeue_many` + time-major transpose). Composes
    fresh:replayed when a replay tier is attached — see get_unrolls,
    which owns the dequeue/push-back semantics."""
    items, _ = self.get_unrolls(batch_size, timeout)
    return batch_unrolls(items)

  def close(self):
    with self._lock:
      self._closed = True
      self._not_full.notify_all()
      self._not_empty.notify_all()
    # Release the registry's hold on this instance (and its replay
    # tier): the fn-gauges close over self, and a closed buffer must
    # be collectable, not pinned by telemetry for the process
    # lifetime. Identity-checked — a newer incarnation's registration
    # under the same names is left alone.
    for gauge in self._gauges:
      telemetry.registry().unregister(gauge.name, gauge)

  def stats(self):
    """Occupancy/backpressure counters (driver summary surface):
    {'occupancy', 'capacity', 'high_water', 'put_waits',
    'put_wait_secs', 'fresh_unrolls'}, plus the replay tier's
    occupancy/eviction/reuse counters when one is attached (round 10).
    high_water at (or briefly above) capacity with growing put_waits
    means producers are throttled by backpressure — the
    bounded-occupancy guarantee working, not a failure."""
    with self._lock:
      out = {
          'occupancy': len(self._deque),
          'capacity': self._capacity,
          'high_water': self._high_water,
          'put_waits': self._put_waits,
          'put_wait_secs': round(self._put_wait_secs, 4),
          'fresh_unrolls': self._fresh_unrolls,
      }
    if self._replay is not None:
      out.update(self._replay.stats())
    return out

  def __len__(self):
    with self._lock:
      return len(self._deque)


def _arena_insert(arena, unroll, slot):
  """One jitted batch-slot write: place unroll `slot`'s rows into the
  [T+1, B(, ...)] arena via `dynamic_update_slice` (bit-identical to
  `np.stack` of the same values — the golden-parity property the
  unroll staging mode rests on). Donated on the arena so the update is
  in-place in HBM."""
  import jax
  import jax.numpy as jnp
  from jax import lax

  def traj(a, x):
    # [T+1, ...] unroll leaf → arena [T+1, B, ...] at batch index slot.
    x = jnp.asarray(x)
    return lax.dynamic_update_slice(
        a, x[:, None].astype(a.dtype), (0, slot) + (0,) * (a.ndim - 2))

  def lead(a, x):
    # Leading-batch leaf: level_name scalar → arena [B]; core-state
    # [1, hidden] → arena [B, hidden].
    x = jnp.asarray(x)
    upd = x if x.ndim == a.ndim else x[None]
    return lax.dynamic_update_slice(a, upd.astype(a.dtype),
                                    (slot,) + (0,) * (a.ndim - 1))

  tree_map = jax.tree_util.tree_map
  return ActorOutput(
      level_name=lead(arena.level_name, unroll.level_name),
      agent_state=tree_map(lead, arena.agent_state, unroll.agent_state),
      env_outputs=tree_map(traj, arena.env_outputs, unroll.env_outputs),
      agent_outputs=tree_map(traj, arena.agent_outputs,
                             unroll.agent_outputs))


class UnrollBatchStager:
  """On-device [T+1, B] batch assembly from per-unroll transfers
  (config.staging_mode='unroll').

  `add(unroll)` runs the moment an unroll leaves the TrajectoryBuffer:
  the optional `host_view_fn` peels its tiny host-side stats view
  first (the batch never comes back to host), then the unroll is
  `jax.device_put` — async, directly to the device owning its batch
  slot (`slot_devices`) — and written into a zeroed per-device arena
  by the jitted, DONATED `_arena_insert`. The step-boundary H2D burst
  becomes a B-transfer trickle that overlaps the previous step's
  compute; the host `batch_unrolls` stack disappears.

  `finish()` emits the [T+1, B] batch: the arena itself on a single
  device, or `assemble_fn` (zero-copy
  `jax.make_array_from_single_device_arrays` over the data-axis
  sharding — parallel/train_parallel.make_unroll_assembly) under a
  pure-DP mesh. Fresh zero arenas back the NEXT batch, so the emitted
  arrays are never written again while the learner reads them.

  Donation-aliasing fallback: some jaxlib builds mis-pair donation
  aliases of mesh-placed leaves (the PR-3 dryrun defect — "Expected
  aliased input ... to have the same size"). The first insert that
  trips it rebuilds the insert un-donated and continues; the engaged
  fallback is visible as `stats()['donation_fallback']`.

  NOT thread-safe: owned and driven by the BatchPrefetcher loop
  thread. `abort()` (partial batch at close/error) is idempotent.
  """

  def __init__(self, batch_size: int, slot_devices=None,
               assemble_fn=None, host_view_fn=None, finalize_fn=None,
               donate: bool = True):
    import jax
    if batch_size < 1:
      raise ValueError('batch_size must be >= 1')
    if slot_devices is not None and len(slot_devices) != batch_size:
      raise ValueError(f'slot_devices must have one entry per batch '
                       f'slot ({len(slot_devices)} != {batch_size})')
    self._batch_size = batch_size
    self._slot_devices = slot_devices
    self._assemble_fn = assemble_fn
    self._host_view_fn = host_view_fn
    self._finalize_fn = finalize_fn
    self._donate = donate
    self._insert_donated = jax.jit(_arena_insert, donate_argnums=(0,))
    self._insert_plain = jax.jit(_arena_insert)
    # Slots grouped by device, in slot order: arena d holds the
    # contiguous run of slots placed on device d (the data-axis shard
    # layout make_unroll_assembly's sharding expects).
    if slot_devices is None:
      self._device_slots = [(None, batch_size)]
    else:
      groups = []
      for dev in slot_devices:
        if groups and groups[-1][0] == dev:
          groups[-1][1] += 1
        else:
          groups.append([dev, 1])
      self._device_slots = [(d, n) for d, n in groups]
    self._arenas = None   # list of per-device arenas (current batch)
    self._views = []
    self._next_slot = 0
    # Telemetry (read via stats(); single-writer, torn reads benign).
    self.unrolls_staged = 0
    self.batches_assembled = 0
    self.aborted_partials = 0
    self.donation_fallback = False

  def _zero_arena(self, unroll, slots, device):
    """Zeroed per-device arena with `slots` batch rows, shaped from a
    real unroll (no spec plumbing — the first unroll of each batch
    defines the shapes, and a shape drift fails loudly in the jit)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def traj(x):
      x = np.asarray(x)
      return jnp.zeros((x.shape[0], slots) + x.shape[1:], x.dtype)

    def lead(x):
      x = np.asarray(x)
      shape = (slots,) + (x.shape[1:] if x.ndim else ())
      return jnp.zeros(shape, x.dtype)

    tree_map = jax.tree_util.tree_map
    arena = ActorOutput(
        level_name=lead(unroll.level_name),
        agent_state=tree_map(lead, unroll.agent_state),
        env_outputs=tree_map(traj, unroll.env_outputs),
        agent_outputs=tree_map(traj, unroll.agent_outputs))
    if device is not None:
      arena = jax.device_put(arena, device)
    return arena

  def _insert(self, arena, unroll_dev, local_slot):
    import numpy as np
    slot = np.int32(local_slot)
    if self._donate:
      try:
        return self._insert_donated(arena, unroll_dev, slot)
      except Exception as e:  # jaxlib XlaRuntimeError (INTERNAL)
        if 'alias' not in str(e):
          raise
        # The PR-3 jaxlib donation-aliasing defect: retry un-donated
        # for the rest of the run (correctness first; the in-place
        # update is an optimization).
        self._donate = False
        self.donation_fallback = True
    return self._insert_plain(arena, unroll_dev, slot)

  def add(self, unroll, peel_view: bool = True):
    """Stage one unroll into the current batch (called with host
    numpy, straight off the TrajectoryBuffer). `peel_view=False` skips
    the host stats peel — REPLAYED unrolls (round 10) already peeled
    their episode view on first consumption; peeling again would
    double-count episodes in the summaries."""
    import jax
    if self._next_slot >= self._batch_size:
      raise RuntimeError('batch already full; call finish()')
    if self._host_view_fn is not None and peel_view:
      self._views.append(self._host_view_fn(unroll))
    if self._arenas is None:
      self._arenas = [self._zero_arena(unroll, n, d)
                      for d, n in self._device_slots]
    # Which per-device arena owns this global slot, and where in it.
    slot = self._next_slot
    arena_idx, local_slot = 0, slot
    for i, (_, n) in enumerate(self._device_slots):
      if local_slot < n:
        arena_idx = i
        break
      local_slot -= n
    device = self._device_slots[arena_idx][0]
    unroll_dev = (jax.device_put(unroll, device) if device is not None
                  else jax.device_put(unroll))
    self._arenas[arena_idx] = self._insert(self._arenas[arena_idx],
                                           unroll_dev, local_slot)
    self._next_slot += 1
    self.unrolls_staged += 1

  def finish(self):
    """Emit the completed [T+1, B] device batch (plus the finalized
    host views when configured); resets for the next batch."""
    if self._next_slot != self._batch_size:
      raise RuntimeError(
          f'finish() with {self._next_slot}/{self._batch_size} slots '
          'staged')
    arenas, views = self._arenas, self._views
    self._arenas, self._views, self._next_slot = None, [], 0
    batch = (self._assemble_fn(arenas) if self._assemble_fn is not None
             else arenas[0])
    self.batches_assembled += 1
    if self._finalize_fn is not None:
      return self._finalize_fn(views, batch)
    return batch

  def abort(self):
    """Drop a partially staged batch (close/error path): releases the
    arena device buffers so nothing leaks past the prefetcher's
    lifetime. Idempotent."""
    if self._arenas is not None or self._next_slot:
      self.aborted_partials += 1
    self._arenas = None
    self._views = []
    self._next_slot = 0

  def stats(self):
    return {
        'unrolls_staged': self.unrolls_staged,
        'batches_assembled': self.batches_assembled,
        'aborted_partials': self.aborted_partials,
        'donation_fallback': self.donation_fallback,
    }


class BatchPrefetcher:
  """Stages upcoming device batches while the learner consumes the
  current one (the StagingArea role, generalized to `depth` slots).

  depth is the number of staged batches that may be in flight at once
  (config.staging_depth; default 2). With depth >= 2 the prefetcher
  keeps TWO `place_fn` dispatches outstanding: `jax.device_put` is
  async, so the transfers of batches N+1 and N+2 overlap each other
  AND the step computing batch N — the r5 fed-learner bench measured
  the host→device copy as the dominant feed-gap term (`h2d_ms` 1430.5
  vs `stack_ms` 37.5, BENCH_r05), and a single staged slot can hide
  at most one transfer behind one step. Raising depth trades policy
  lag (each staged batch extends the lag bound by one batch) for
  transfer overlap; keep it small.

  `stats()` reports the overlap counters the acceptance gate reads:
  `h2d_overlap_fraction` is the fraction of `get()` calls that found
  a batch already staged (the step did NOT block on staging). It
  conflates data starvation with transfer stalls by design — both are
  "the learner waited" — so read it together with `buffer_unrolls`
  (≈0 means starvation upstream of staging).

  Sample reuse (round 10): with `replay_k` > 1 each staged batch is
  SERVED `replay_k` times before its slot frees — the staged device
  arena is handed out AS IS (the same arrays; the train step donates
  only its state, and the unroll stager backs every batch with fresh
  arenas, so re-serves are bit-identical), which is `replay_k` learner
  updates per ONE stage/H2D. Serves after the first pass through
  `reserve_fn` (when given) so the caller can blank the host stats
  view — a re-serve consumes zero new env frames. A batch being
  re-served still occupies its depth slot until the Kth serve, and
  `close()` drops partially-served batches with everything else (no
  staged HBM outlives the prefetcher).

  When the buffer carries a replay tier, `place_fn` is called as
  `place_fn(batch, n_fresh)` — the composed batch's fresh slot count —
  so the driver's stats peel can exclude replayed columns; without a
  tier the one-argument contract is unchanged.
  """

  # Lock discipline (round 18, guarded-by lint): staging state, the
  # overlap telemetry, and the live replay_k knob all mutate under
  # _lock (the _ready/_space conditions wrap the same mutex).
  _out: guarded_by('_lock')
  _closed: guarded_by('_lock')
  _error: guarded_by('_lock')
  _staged: guarded_by('_lock')
  _gets: guarded_by('_lock')
  _blocked_gets: guarded_by('_lock')
  _wait_secs: guarded_by('_lock')
  _serves: guarded_by('_lock')
  _reserves: guarded_by('_lock')
  _fresh_served: guarded_by('_lock')
  _replay_k: guarded_by('_lock')

  def __init__(self, buffer: TrajectoryBuffer, batch_size: int,
               place_fn: Callable = lambda batch, n_fresh=None: batch,
               depth: int = 2,
               stager: Optional[UnrollBatchStager] = None,
               replay_k: int = 1,
               reserve_fn: Optional[Callable] = None):
    if depth < 1:
      raise ValueError('staging depth must be >= 1')
    if replay_k < 1:
      raise ValueError('replay_k must be >= 1')
    self._buffer = buffer
    self._batch_size = batch_size
    self._place_fn = place_fn
    # staging_mode='unroll': per-unroll device staging + on-device
    # assembly replaces get_batch + place_fn (which is then unused).
    self._stager = stager
    self._replay_k = replay_k
    self._reserve_fn = reserve_fn
    self._fresh_aware = buffer.replay is not None
    self._serves = 0
    self._reserves = 0
    self._fresh_served = 0
    self._out = collections.deque()
    self._lock = make_lock('ring_buffer.BatchPrefetcher._lock')
    self._ready = threading.Condition(self._lock)
    self._space = threading.Condition(self._lock)
    self._depth = depth
    self._closed = False
    self._error: Optional[BaseException] = None
    # Overlap telemetry (all under self._lock).
    self._staged = 0
    self._gets = 0
    self._blocked_gets = 0
    self._wait_secs = 0.0
    # Unified-registry view (round 13); unregistered by close().
    self._gauges = [
        telemetry.gauge('staging/staged_batches',
                        fn=lambda: self._staged),
        telemetry.gauge('staging/blocked_gets',
                        fn=lambda: self._blocked_gets),
        telemetry.gauge('staging/serves', fn=lambda: self._serves),
        telemetry.gauge('staging/fresh_slots_served',
                        fn=lambda: self._fresh_served),
    ]
    self._thread = threading.Thread(target=self._loop,
                                    name='batch-prefetcher', daemon=True)
    self._thread.start()

  def _stage_next(self):
    """Assemble + stage one batch; returns (staged, n_fresh). Batch
    mode: host stack via get_unrolls, then one place_fn burst. Unroll
    mode: each unroll is transferred the moment it dequeues and the
    batch assembles on device (UnrollBatchStager) — the transfers
    overlap the step that is computing RIGHT NOW, not just each other.
    Both modes compose fresh:replayed slots through the buffer's
    replay tier (fresh first); replayed unrolls skip the host stats
    peel."""
    tracer = telemetry.get_tracer()
    if self._stager is None:
      items, n_fresh = self._buffer.get_unrolls(self._batch_size)
      if tracer is not None:
        # Trace hop (round 13): this batch's fresh unrolls were
        # picked for staging — completes each sidecar span's STAGED
        # stamp and opens the batch's entry in the tracer's FIFO
        # (serve/step stamps follow in this same FIFO order).
        tracer.on_batch(items, n_fresh)
      batch = batch_unrolls(items)
      if self._fresh_aware:
        return self._place_fn(batch, n_fresh), n_fresh
      return self._place_fn(batch), n_fresh  # async put: overlaps
    # Unroll mode stays INCREMENTAL: each fresh unroll stages (and
    # starts its H2D) the moment it dequeues — batching the dequeue
    # would turn the trickle back into a step-boundary burst. Replayed
    # slots (available instantly) fill the tail of the batch.
    replayed = self._buffer.sample_replay(self._batch_size)
    n_fresh = self._batch_size - len(replayed)
    fresh_items = []
    for _ in range(n_fresh):
      unroll = self._buffer.get()
      fresh_items.append(unroll)
      self._stager.add(unroll)
    for unroll in replayed:
      self._stager.add(unroll, peel_view=False)
    if tracer is not None:
      tracer.on_batch(fresh_items + replayed, n_fresh)
    return self._stager.finish(), n_fresh

  def _loop(self):
    try:
      while True:
        staged, n_fresh = self._stage_next()
        with self._space:
          while len(self._out) >= self._depth and not self._closed:
            self._space.wait()
          if self._closed:
            return
          # [staged, serves_remaining, n_fresh, staged_k]: the entry
          # leaves the deque — freeing its depth slot AND its device
          # arrays — only after the replay_k-th serve. n_fresh is
          # credited to `fresh_slots_served` at FIRST serve, so the
          # fresh-vs-serve accounting is attributed at consumption
          # time (a batch staged ahead by the prefetcher but never
          # served counts nothing — the lookahead-free invariant
          # bench.py's composition rows rely on). staged_k pins the K
          # this entry was staged under: set_replay_k (round 15, the
          # controller's actuator) changes only FUTURE entries, and
          # first-serve detection compares against the entry's own K,
          # never the live knob.
          k = self._replay_k
          self._out.append([staged, k, n_fresh, k])
          self._staged += 1
          self._ready.notify()
    except Closed:
      if self._stager is not None:
        self._stager.abort()  # partial batch: free its arena buffers
      with self._lock:
        self._closed = True
        self._ready.notify_all()
    except BaseException as e:  # surfaced to the consumer
      if self._stager is not None:
        self._stager.abort()
      with self._lock:
        self._error = e
        self._closed = True
        self._ready.notify_all()

  def ready(self) -> bool:
    """Ready-without-dequeue probe (round 16, the hybrid filler's
    yield check): True when a `get()` right now would NOT block — a
    batch is staged, or the prefetcher is closed/errored (then get()
    raises immediately, which is the caller's signal to take its
    normal error path instead of filling forever). Never consumes,
    never counts toward the wait telemetry."""
    with self._lock:
      return bool(self._out) or self._closed

  def get(self, timeout: Optional[float] = None):
    deadline = None if timeout is None else time.monotonic() + timeout
    t0 = time.monotonic()
    with self._ready:
      self._gets += 1
      blocked = not self._out and not self._closed
      if blocked:
        self._blocked_gets += 1
      while not self._out and not self._closed:
        remaining = (None if deadline is None
                     else deadline - time.monotonic())
        if remaining is not None and remaining <= 0:
          self._wait_secs += time.monotonic() - t0
          raise TimeoutError('BatchPrefetcher.get timed out')
        self._ready.wait(remaining)
      if blocked:
        self._wait_secs += time.monotonic() - t0
      if self._error is not None:
        raise self._error
      if not self._out:
        raise Closed()
      entry = self._out[0]
      item = entry[0]
      first_serve = entry[1] == entry[3]
      entry[1] -= 1
      if entry[1] <= 0:  # Kth serve: release the slot + the arrays
        self._out.popleft()
        self._space.notify()
      self._serves += 1
      if first_serve:
        self._fresh_served += entry[2]
        tracer = telemetry.get_tracer()
        if tracer is not None:
          # First serve = the learner picked this staged batch up
          # (re-serves ride the same arena; no new pipeline traversal).
          tracer.on_serve()
      if not first_serve:
        self._reserves += 1
        if self._reserve_fn is not None:
          item = self._reserve_fn(item)
      return item

  @property
  def replay_k(self) -> int:
    """The live re-serve count (the controller's actuator get path).
    Round 18: read under _lock like every other _replay_k access —
    the bare read was GIL-atomic but violated the declared
    guarded_by discipline (found by the lint)."""
    with self._lock:
      return self._replay_k

  def set_replay_k(self, k: int):
    """Thread-safe live replay_k change (round 15: the controller's
    sample-reuse actuator). Applies to batches staged AFTER the call;
    entries already staged finish out the K they were staged under
    (their first-serve accounting compares against that pinned K, so
    fresh-frame attribution can never double- or under-count across a
    change)."""
    k = int(k)
    if k < 1:
      raise ValueError('replay_k must be >= 1')
    with self._lock:
      if k != self._replay_k:
        log.warning('prefetcher replay_k: %d -> %d',
                    self._replay_k, k)
      self._replay_k = k

  def fresh_slots_served(self) -> int:
    """Cumulative fresh unroll slots of FIRST-served batches — the
    serve-time env-frame counter (immune to prefetch lookahead). Split
    from stats() because the driver's frame budget reads it every
    step; building the full stats dict there would add lock hold time
    the staging thread contends on."""
    with self._lock:
      return self._fresh_served

  def stats(self):
    """Staging/overlap counters: staged batches, consumer gets, how
    many blocked, total blocked seconds, and the headline
    `h2d_overlap_fraction` (1.0 = no step ever waited on staging)."""
    with self._lock:
      gets = self._gets
      # Overlap is denominated on FIRST serves: a re-serve (replay_k
      # > 1) hands back the entry already at the deque head, so it can
      # never block — counting it would dilute the fraction by 1/K and
      # mask real staging stalls on reuse configs.
      first_gets = max(gets - self._reserves, 0)
      out = {
          'depth': self._depth,
          'mode': 'unroll' if self._stager is not None else 'batch',
          'staged_batches': self._staged,
          'gets': gets,
          'blocked_gets': self._blocked_gets,
          'wait_secs': round(self._wait_secs, 4),
          'h2d_overlap_fraction': (
              (first_gets - self._blocked_gets) / first_gets
              if first_gets else 0.0),
          # Sample reuse (round 10): serves counts every batch handed
          # to the learner; batch_reserves the serves beyond each
          # batch's first (zero-H2D re-serves of the staged arena);
          # fresh_slots_served the fresh unroll slots of FIRST-served
          # batches (credited at serve time, so composition ratios
          # derived from it are immune to prefetch lookahead).
          'replay_k': self._replay_k,
          'serves': self._serves,
          'batch_reserves': self._reserves,
          'fresh_slots_served': self._fresh_served,
      }
    if self._stager is not None:
      out.update(self._stager.stats())
    return out

  def close(self):
    with self._lock:
      self._closed = True
      self._ready.notify_all()
      self._space.notify_all()
    self._buffer.close()
    self._thread.join(timeout=5)
    # Release staged device batches (and, via the loop thread's abort,
    # any partial arena): a closed prefetcher must not pin batch-sized
    # HBM buffers for the rest of the process lifetime — and neither
    # may the registry pin the prefetcher itself via its fn-gauges.
    with self._lock:
      self._out.clear()
    for gauge in self._gauges:
      telemetry.registry().unregister(gauge.name, gauge)
