"""Trajectory transport: bounded unroll buffer + device prefetch.

Replaces the reference's learner-hosted `tf.FIFOQueue(capacity=1)` +
`StagingArea` double-buffer (reference: experiment.py ≈L470, ≈L540–560;
SURVEY §2.b "async pipeline"):

- `TrajectoryBuffer`: a bounded ring of completed unrolls. Producers
  (actor threads) block when full — capacity IS the backpressure that
  bounds policy lag, exactly the reference's capacity-1 queue semantics
  (lag ≤ capacity + in-flight unroll + staged batch).
- `BatchPrefetcher`: one thread that assembles [T+1, B] batches and
  stages the next `depth` device batches while the learner trains on
  the current one (the StagingArea role, default depth 2 —
  config.staging_depth). `place_fn` is where `jax.device_put` with
  data-axis shardings happens, so staging overlaps host→HBM transfer
  with TPU compute; with depth >= 2 consecutive transfers also
  overlap each other (the r5 fed bench measured H2D as the dominant
  feed-gap term).

Episode stats ride inside the trajectories (StepOutputInfo), so there
is no side channel to drain — consume them from the dequeued batch
like the reference's learner loop does (≈L590–620).
"""

import collections
import threading
import time
from typing import Callable, List, Optional

from scalable_agent_tpu.runtime.actor import batch_unrolls
from scalable_agent_tpu.structs import ActorOutput


class Closed(Exception):
  """The buffer was closed while blocking."""


def _wait_until(cond: threading.Condition, predicate: Callable[[], bool],
                deadline: Optional[float], what: str):
  """Wait on `cond` (held) until predicate() or deadline; deadline-based
  so spurious wakeups under contention don't restart the clock."""
  while not predicate():
    remaining = None if deadline is None else deadline - time.monotonic()
    if remaining is not None and remaining <= 0:
      raise TimeoutError(f'{what} timed out')
    cond.wait(remaining)


class TrajectoryBuffer:
  """Bounded FIFO of unrolls with blocking put/get and backpressure."""

  def __init__(self, capacity_unrolls: int):
    if capacity_unrolls < 1:
      raise ValueError('capacity must be >= 1')
    self._capacity = capacity_unrolls
    self._deque = collections.deque()
    self._lock = threading.Lock()
    self._not_full = threading.Condition(self._lock)
    self._not_empty = threading.Condition(self._lock)
    self._closed = False

  def put(self, unroll: ActorOutput, timeout: Optional[float] = None):
    """Block while full (backpressure). Raises Closed after close().

    The timeout bounds TOTAL blocking time (deadline-based — spurious
    wakeups under contention don't restart the clock)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._not_full:
      _wait_until(self._not_full,
                  lambda: len(self._deque) < self._capacity or self._closed,
                  deadline, 'TrajectoryBuffer.put')
      if self._closed:
        raise Closed()
      self._deque.append(unroll)
      self._not_empty.notify()

  def get(self, timeout: Optional[float] = None) -> ActorOutput:
    """Block while empty. Raises Closed after close() drains. Timeout
    bounds total blocking time (deadline-based)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._not_empty:
      _wait_until(self._not_empty,
                  lambda: self._deque or self._closed,
                  deadline, 'TrajectoryBuffer.get')
      if not self._deque:
        raise Closed()
      item = self._deque.popleft()
      self._not_full.notify()
      return item

  def get_batch(self, batch_size: int,
                timeout: Optional[float] = None) -> ActorOutput:
    """Dequeue `batch_size` unrolls and stack to a [T+1, B] batch (the
    reference's `dequeue_many` + time-major transpose).

    Accumulates incrementally — dequeued unrolls free producer slots
    immediately, so `batch_size > capacity` works exactly like the
    reference's capacity-1 FIFOQueue feeding `dequeue_many(batch)`.
    On timeout or close with a partial batch, the accumulated unrolls
    are pushed back to the FRONT of the queue (FIFO order preserved),
    so no trajectories are ever dropped.
    The timeout bounds total blocking (deadline-based)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    items: List[ActorOutput] = []
    with self._not_empty:
      try:
        while len(items) < batch_size:
          _wait_until(self._not_empty,
                      lambda: self._deque or self._closed,
                      deadline, 'TrajectoryBuffer.get_batch')
          if not self._deque:  # closed and drained: partial batch
            raise Closed()
          while self._deque and len(items) < batch_size:
            items.append(self._deque.popleft())
          self._not_full.notify_all()
      except (TimeoutError, Closed):
        # Push-back may transiently exceed capacity (up to capacity +
        # batch_size - 1): keeping trajectories beats the strict lag
        # bound on this error path; producers stay blocked until the
        # excess drains. Wake other consumers — the restored items are
        # consumable (lost-wakeup otherwise).
        self._deque.extendleft(reversed(items))
        if items:
          self._not_empty.notify_all()
        raise
    return batch_unrolls(items)

  def close(self):
    with self._lock:
      self._closed = True
      self._not_full.notify_all()
      self._not_empty.notify_all()

  def __len__(self):
    with self._lock:
      return len(self._deque)


class BatchPrefetcher:
  """Stages upcoming device batches while the learner consumes the
  current one (the StagingArea role, generalized to `depth` slots).

  depth is the number of staged batches that may be in flight at once
  (config.staging_depth; default 2). With depth >= 2 the prefetcher
  keeps TWO `place_fn` dispatches outstanding: `jax.device_put` is
  async, so the transfers of batches N+1 and N+2 overlap each other
  AND the step computing batch N — the r5 fed-learner bench measured
  the host→device copy as the dominant feed-gap term (`h2d_ms` 1430.5
  vs `stack_ms` 37.5, BENCH_r05), and a single staged slot can hide
  at most one transfer behind one step. Raising depth trades policy
  lag (each staged batch extends the lag bound by one batch) for
  transfer overlap; keep it small.

  `stats()` reports the overlap counters the acceptance gate reads:
  `h2d_overlap_fraction` is the fraction of `get()` calls that found
  a batch already staged (the step did NOT block on staging). It
  conflates data starvation with transfer stalls by design — both are
  "the learner waited" — so read it together with `buffer_unrolls`
  (≈0 means starvation upstream of staging).
  """

  def __init__(self, buffer: TrajectoryBuffer, batch_size: int,
               place_fn: Callable = lambda x: x, depth: int = 2):
    if depth < 1:
      raise ValueError('staging depth must be >= 1')
    self._buffer = buffer
    self._batch_size = batch_size
    self._place_fn = place_fn
    self._out = collections.deque()
    self._lock = threading.Lock()
    self._ready = threading.Condition(self._lock)
    self._space = threading.Condition(self._lock)
    self._depth = depth
    self._closed = False
    self._error: Optional[BaseException] = None
    # Overlap telemetry (all under self._lock).
    self._staged = 0
    self._gets = 0
    self._blocked_gets = 0
    self._wait_secs = 0.0
    self._thread = threading.Thread(target=self._loop,
                                    name='batch-prefetcher', daemon=True)
    self._thread.start()

  def _loop(self):
    try:
      while True:
        batch = self._buffer.get_batch(self._batch_size)
        staged = self._place_fn(batch)  # async device_put: overlaps
        with self._space:
          while len(self._out) >= self._depth and not self._closed:
            self._space.wait()
          if self._closed:
            return
          self._out.append(staged)
          self._staged += 1
          self._ready.notify()
    except Closed:
      with self._lock:
        self._closed = True
        self._ready.notify_all()
    except BaseException as e:  # surfaced to the consumer
      with self._lock:
        self._error = e
        self._closed = True
        self._ready.notify_all()

  def get(self, timeout: Optional[float] = None):
    deadline = None if timeout is None else time.monotonic() + timeout
    t0 = time.monotonic()
    with self._ready:
      self._gets += 1
      blocked = not self._out and not self._closed
      if blocked:
        self._blocked_gets += 1
      while not self._out and not self._closed:
        remaining = (None if deadline is None
                     else deadline - time.monotonic())
        if remaining is not None and remaining <= 0:
          self._wait_secs += time.monotonic() - t0
          raise TimeoutError('BatchPrefetcher.get timed out')
        self._ready.wait(remaining)
      if blocked:
        self._wait_secs += time.monotonic() - t0
      if self._error is not None:
        raise self._error
      if not self._out:
        raise Closed()
      item = self._out.popleft()
      self._space.notify()
      return item

  def stats(self):
    """Staging/overlap counters: staged batches, consumer gets, how
    many blocked, total blocked seconds, and the headline
    `h2d_overlap_fraction` (1.0 = no step ever waited on staging)."""
    with self._lock:
      gets = self._gets
      return {
          'depth': self._depth,
          'staged_batches': self._staged,
          'gets': gets,
          'blocked_gets': self._blocked_gets,
          'wait_secs': round(self._wait_secs, 4),
          'h2d_overlap_fraction': (
              (gets - self._blocked_gets) / gets if gets else 0.0),
      }

  def close(self):
    with self._lock:
      self._closed = True
      self._ready.notify_all()
      self._space.notify_all()
    self._buffer.close()
    self._thread.join(timeout=5)
