"""Actor fleet: owns env processes, actor threads, and their health.

The reference's actor fleet is implicit — QueueRunner threads plus
PyProcessHook-started env processes, with NO failure detection: a dead
actor silently stops contributing (SURVEY §5.3). This module makes the
fleet explicit and adds what upstream lacks:

- per-actor heartbeats (last unroll completion time),
- dead/stalled-actor detection,
- respawn of the env (process) + actor thread without disturbing the
  rest of the fleet or the learner.

Trajectories from a respawned actor restart from a fresh episode —
consistent with the reference's crash story (unrolls straddling a
restart are lost, SURVEY §5.4).
"""

import threading
import time
from typing import Callable, List, Optional

from scalable_agent_tpu.runtime import ring_buffer
from scalable_agent_tpu.runtime.actor import Actor


class _Slot:
  """One actor's mutable runtime state (env, thread, health)."""

  def __init__(self, index):
    self.index = index
    self.env = None
    self.process = None          # PyProcess when process-hosted
    self.actor: Optional[Actor] = None
    self.thread: Optional[threading.Thread] = None
    self.generation: int = 0     # bumped on every (re)spawn
    self.last_heartbeat: float = time.monotonic()
    self.unrolls_done: int = 0
    self.respawns: int = 0
    self.error: Optional[BaseException] = None


class ActorFleet:
  """N actors producing unrolls into a shared TrajectoryBuffer.

  Args:
    make_actor: (slot_index) → (env, process_or_None, Actor). Called at
      start and again on every respawn; must build a FRESH env.
    buffer: the shared TrajectoryBuffer.
    num_actors: fleet size.
  """

  def __init__(self, make_actor: Callable, buffer, num_actors: int):
    self._make_actor = make_actor
    self._buffer = buffer
    self._stop = threading.Event()
    self._lock = threading.Lock()
    self._slots: List[_Slot] = [_Slot(i) for i in range(num_actors)]

  @property
  def stop_event(self):
    return self._stop

  def start(self):
    for slot in self._slots:
      self._spawn(slot)

  def _spawn(self, slot: _Slot):
    env, process, actor = self._make_actor(slot.index)
    with self._lock:
      slot.generation += 1
      generation = slot.generation
      slot.env, slot.process, slot.actor = env, process, actor
      slot.error = None
      slot.last_heartbeat = time.monotonic()
    slot.thread = threading.Thread(
        target=self._run, args=(slot, generation, actor, process),
        name=f'actor-{slot.index}', daemon=True)
    slot.thread.start()

  def _run(self, slot: _Slot, generation: int, actor: Actor, process):
    """Thread body: `actor.run_actor_loop` (the one shutdown/poison
    contract) with fleet bookkeeping hooked in. Touches only ITS OWN
    actor/process objects and writes slot state only while it is still
    the slot's current generation — an orphaned thread (replaced after
    a stall) must not mark the healthy replacement dead or close its
    process. Failures are recorded on the slot (the shared buffer
    stays open for the other actors); the learner surfaces them via
    errors() on its stall path."""
    from scalable_agent_tpu.runtime.actor import run_actor_loop

    def still_current():
      return slot.generation == generation

    def on_unroll():
      with self._lock:
        if not still_current():
          return False  # orphaned: a replacement owns the slot now
        slot.last_heartbeat = time.monotonic()
        slot.unrolls_done += 1
        return True

    def on_failure(exc):
      with self._lock:
        if still_current():
          slot.error = exc

    try:
      run_actor_loop(actor, self._buffer, self._stop,
                     on_unroll=on_unroll, on_failure=on_failure)
    finally:
      if process is not None:
        try:
          process.close(timeout=2.0)
        except Exception:
          pass

  def check_health(self, stall_timeout_secs: Optional[float] = None,
                   respawn: bool = True) -> List[int]:
    """Detect failed/stalled actors; respawn them. Returns the indices
    acted upon. Call periodically from the learner loop (the reference
    has no equivalent — SURVEY §5.3 greenfield)."""
    if self._stop.is_set():
      return []
    now = time.monotonic()
    bad: List[_Slot] = []
    with self._lock:
      for slot in self._slots:
        dead = slot.error is not None or (
            slot.thread is not None and not slot.thread.is_alive())
        stalled = (stall_timeout_secs is not None and
                   now - slot.last_heartbeat > stall_timeout_secs)
        if dead or stalled:
          bad.append(slot)
    for slot in bad:
      if respawn:
        self._respawn(slot)
    return [s.index for s in bad]

  def _respawn(self, slot: _Slot):
    old_thread = slot.thread
    old_actor = slot.actor
    if slot.process is not None:
      try:
        slot.process.close(timeout=1.0)
      except Exception:
        pass
    if old_thread is not None and old_thread.is_alive():
      # A stalled thread blocked in env.step can't be killed; it is
      # orphaned (daemon) and a fresh actor takes over the slot. Its
      # buffer.put may still land one stale unroll — harmless, same
      # policy-lag bound as any in-flight unroll. Its device-resident
      # inference state (a state-arena slot) stays acquired until the
      # thread unwinds through run_actor_loop's finally — the arena's
      # auto headroom (2× fleet) covers the interim; the replacement
      # gets a FRESH zeroed slot from make_actor either way.
      pass
    elif old_actor is not None:
      # Dead thread: run_actor_loop's finally normally released the
      # inference state via actor.close(); this is the idempotent
      # backstop for a thread killed before its finally ran — the
      # respawn must free the old slot, not leak it.
      try:
        old_actor.release_policy_state()
      except Exception:
        pass
    with self._lock:
      slot.respawns += 1
    try:
      self._spawn(slot)
    except Exception as e:
      # A failed respawn (env construction, exhausted inference state
      # arena) must not propagate into the learner loop that called
      # check_health — start()-time spawn failures still raise (setup
      # errors belong to the caller), but a mid-run respawn records
      # the error on the slot: the next health check retries, and the
      # learner surfaces it via errors() only if the pipeline actually
      # stalls (the same containment as any other actor-side failure).
      with self._lock:
        slot.error = e
        slot.thread = None

  def errors(self) -> List[BaseException]:
    with self._lock:
      return [s.error for s in self._slots if s.error is not None]

  def stats(self, healthy_horizon_secs: float = 60.0):
    """Fleet health counters.

    `alive` counts slots whose CURRENT thread is running — but a
    wedged actor (blocked in env.step) or one whose error hasn't been
    collected yet is alive without producing, and a stalled thread
    orphaned by respawn keeps running as a daemon invisibly. `healthy`
    is the honest signal: the slot's current-generation thread is
    alive, has no recorded error, AND heartbeat-fresh within
    `healthy_horizon_secs` (align it with the driver's stall timeout).
    `healthy_fraction` is the quorum the driver logs — the scheduler-
    facing 'how much of my fleet is actually feeding' number.
    """
    now = time.monotonic()
    with self._lock:
      alive = [s for s in self._slots
               if s.thread is not None and s.thread.is_alive()]
      healthy = [s for s in alive
                 if s.error is None and
                 now - s.last_heartbeat <= healthy_horizon_secs]
      return {
          'unrolls': sum(s.unrolls_done for s in self._slots),
          'respawns': sum(s.respawns for s in self._slots),
          'alive': len(alive),
          'healthy': len(healthy),
          'healthy_fraction': (len(healthy) / len(self._slots)
                               if self._slots else 1.0),
      }

  def stop(self, timeout: float = 10.0):
    self._stop.set()
    self._buffer.close()
    deadline = time.monotonic() + timeout
    for slot in self._slots:
      if slot.thread is not None:
        slot.thread.join(max(0.0, deadline - time.monotonic()))
