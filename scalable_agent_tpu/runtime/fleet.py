"""Actor fleet: owns env processes, actor threads, and their health.

The reference's actor fleet is implicit — QueueRunner threads plus
PyProcessHook-started env processes, with NO failure detection: a dead
actor silently stops contributing (SURVEY §5.3). This module makes the
fleet explicit and adds what upstream lacks:

- per-actor heartbeats (last unroll completion time),
- dead/stalled-actor detection,
- respawn of the env (process) + actor thread without disturbing the
  rest of the fleet or the learner,
- capped-exponential respawn backoff with full jitter PER SLOT and a
  give-up-after-N quarantine (round 9): a persistently failing env —
  or a respawn starved by inference-slot admission under overload —
  used to hot-loop respawn attempts through every health check;
  now each failed generation pushes the slot's next attempt out on
  its own jittered backoff, and after `quarantine_after` consecutive
  respawns without ONE completed unroll the slot is quarantined
  (marked dead, surfaced as `slots_quarantined` in stats()/driver
  summaries) instead of burning the learner loop forever.

Trajectories from a respawned actor restart from a fresh episode —
consistent with the reference's crash story (unrolls straddling a
restart are lost, SURVEY §5.4).
"""

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock
from scalable_agent_tpu.runtime import ring_buffer
from scalable_agent_tpu.runtime.actor import Actor
from scalable_agent_tpu.runtime.remote import Backoff

log = logging.getLogger('scalable_agent_tpu')


def _is_admission_error(e: BaseException) -> bool:
  """Whether a spawn failure is inference-slot admission (overload —
  degrade to pause-and-retry) rather than a setup error (raise).
  Lazy import: the fleet must not pull jax at module import."""
  from scalable_agent_tpu.runtime.inference import (InferenceClosed,
                                                    SlotUnavailable)
  return isinstance(e, (SlotUnavailable, InferenceClosed))


class _Slot:
  """One actor's mutable runtime state (env, thread, health)."""

  def __init__(self, index):
    self.index = index
    self.env = None
    self.process = None          # PyProcess when process-hosted
    self.actor: Optional[Actor] = None
    self.thread: Optional[threading.Thread] = None
    self.generation: int = 0     # bumped on every (re)spawn
    self.last_heartbeat: float = time.monotonic()
    self.unrolls_done: int = 0
    self.respawns: int = 0
    self.error: Optional[BaseException] = None
    # Respawn pacing (round 9): consecutive respawns since the last
    # COMPLETED unroll (a spawn that crash-loops before producing is
    # still a failure), the per-slot jittered backoff, the earliest
    # next respawn attempt, and the give-up flag.
    self.respawn_streak: int = 0
    self.backoff = Backoff(base=0.5, cap=30.0)
    self.next_respawn_time: float = 0.0
    self.quarantined: bool = False
    # Elastic fleet (round 15): a PARKED slot is deliberately idle —
    # excluded from spawning, health checks, and the quorum
    # denominator (set_target_size is the controller's shrink/grow
    # seam). `quarantined_at` feeds the probation cool-down;
    # `probation` marks a rehabilitated slot whose NEXT failure
    # re-quarantines immediately (one probe, not a fresh ladder).
    self.parked: bool = False
    self.quarantined_at: float = 0.0
    self.probation: bool = False


class ActorFleet:
  """N actors producing unrolls into a shared TrajectoryBuffer.

  Args:
    make_actor: (slot_index) → (env, process_or_None, Actor). Called at
      start and again on every respawn; must build a FRESH env.
    buffer: the shared TrajectoryBuffer.
    num_actors: fleet size.
    quarantine_after: consecutive respawns without one completed
      unroll before the slot gives up and quarantines (0 = never).
  """

  # Lock discipline (round 18, checked by the guarded-by lint): slot
  # mutation and the rehabilitation counters happen under _lock; the
  # _Slot objects themselves are reached only through _slots.
  _slots_rehabilitated: guarded_by('_lock')
  _rehabilitations: guarded_by('_lock')

  def __init__(self, make_actor: Callable, buffer, num_actors: int,
               quarantine_after: int = 5,
               probation_secs: float = 30.0):
    self._make_actor = make_actor
    self._buffer = buffer
    self._quarantine_after = int(quarantine_after)
    self._probation_secs = float(probation_secs)
    self._stop = threading.Event()
    self._lock = make_lock('fleet._lock')
    self._slots: List[_Slot] = [_Slot(i) for i in range(num_actors)]
    self._slots_rehabilitated = 0  # probation cleared by an unroll
    self._rehabilitations = 0      # probation attempts started

  @property
  def stop_event(self):
    return self._stop

  def start(self):
    for slot in self._slots:
      if slot.parked:
        continue  # parked before start (elastic fleets spin up small)
      try:
        self._spawn(slot)
      except Exception as e:
        # Overload degrade (round 9): a start-time acquire denied by
        # inference-slot admission is NOT a setup error — record it on
        # the slot and let the health loop retry on the slot's backoff
        # instead of crashing the run before it begins. Anything else
        # (env construction, bad config) still raises to the caller.
        if not _is_admission_error(e):
          raise
        with self._lock:
          slot.error = e
          slot.thread = None
          slot.respawn_streak += 1
          slot.next_respawn_time = (time.monotonic()
                                    + slot.backoff.next_delay())
        log.warning(
            'actor %d: start-time slot admission denied (%s) — '
            'degrading to pause-and-retry', slot.index, e)

  def _spawn(self, slot: _Slot):
    env, process, actor = self._make_actor(slot.index)
    with self._lock:
      slot.generation += 1
      generation = slot.generation
      slot.env, slot.process, slot.actor = env, process, actor
      slot.error = None
      slot.last_heartbeat = time.monotonic()
    slot.thread = threading.Thread(
        target=self._run, args=(slot, generation, actor, process),
        name=f'actor-{slot.index}', daemon=True)
    slot.thread.start()

  def _run(self, slot: _Slot, generation: int, actor: Actor, process):
    """Thread body: `actor.run_actor_loop` (the one shutdown/poison
    contract) with fleet bookkeeping hooked in. Touches only ITS OWN
    actor/process objects and writes slot state only while it is still
    the slot's current generation — an orphaned thread (replaced after
    a stall) must not mark the healthy replacement dead or close its
    process. Failures are recorded on the slot (the shared buffer
    stays open for the other actors); the learner surfaces them via
    errors() on its stall path."""
    from scalable_agent_tpu.runtime.actor import run_actor_loop

    def still_current():
      return slot.generation == generation

    def on_unroll():
      with self._lock:
        if not still_current():
          return False  # orphaned: a replacement owns the slot now
        slot.last_heartbeat = time.monotonic()
        slot.unrolls_done += 1
        # A completed unroll is the success signal that resets the
        # respawn ladder: streak, backoff, and pacing all clear — and
        # it is what clears PROBATION: a rehabilitated slot has
        # proven itself only once it lands real data (round 15,
        # counted as slots_rehabilitated).
        slot.respawn_streak = 0
        slot.backoff.reset()
        slot.next_respawn_time = 0.0
        if slot.probation:
          slot.probation = False
          self._slots_rehabilitated += 1
          log.info('actor %d REHABILITATED: probation unroll '
                   'completed; the slot rejoins the fleet',
                   slot.index)
        if slot.parked:
          # The controller shrank the fleet under us: land this
          # unroll (already put), then exit the loop cleanly.
          return False
        return True

    def on_failure(exc):
      with self._lock:
        if still_current():
          slot.error = exc

    try:
      run_actor_loop(actor, self._buffer, self._stop,
                     on_unroll=on_unroll, on_failure=on_failure)
    finally:
      if process is not None:
        try:
          process.close(timeout=2.0)
        except Exception:
          pass

  def check_health(self, stall_timeout_secs: Optional[float] = None,
                   respawn: bool = True) -> List[int]:
    """Detect failed/stalled actors; respawn them. Returns the indices
    acted upon. Call periodically from the learner loop (the reference
    has no equivalent — SURVEY §5.3 greenfield)."""
    if self._stop.is_set():
      return []
    now = time.monotonic()
    bad: List[_Slot] = []
    with self._lock:
      for slot in self._slots:
        if slot.quarantined or slot.parked:
          continue  # gave up / deliberately idle; stats() carries both
        # thread-None counts as dead (round 15): a slot unparked after
        # never spawning (elastic grow) has no thread and no error —
        # it must still be picked up here and spawned.
        dead = (slot.error is not None or slot.thread is None
                or not slot.thread.is_alive())
        stalled = (stall_timeout_secs is not None and
                   now - slot.last_heartbeat > stall_timeout_secs)
        # Respawn pacing: a failing slot is retried only once its
        # jittered backoff elapses — a crash-looping env (or an
        # admission-denied respawn under overload) must not hot-loop
        # the learner thread through every health check.
        if (dead or stalled) and now >= slot.next_respawn_time:
          bad.append(slot)
    for slot in bad:
      if respawn:
        self._respawn(slot)
    return [s.index for s in bad]

  def _respawn(self, slot: _Slot):
    old_thread = slot.thread
    old_actor = slot.actor
    if slot.process is not None:
      try:
        slot.process.close(timeout=1.0)
      except Exception:
        pass
    if old_thread is not None and old_thread.is_alive():
      # A stalled thread blocked in env.step can't be killed; it is
      # orphaned (daemon) and a fresh actor takes over the slot. Its
      # buffer.put may still land one stale unroll — harmless, same
      # policy-lag bound as any in-flight unroll. Its device-resident
      # inference state (a state-arena slot) stays acquired until the
      # thread unwinds through run_actor_loop's finally — the arena's
      # auto headroom (2× fleet) covers the interim; the replacement
      # gets a FRESH zeroed slot from make_actor either way.
      pass
    elif old_actor is not None:
      # Dead thread: run_actor_loop's finally normally released the
      # inference state via actor.close(); this is the idempotent
      # backstop for a thread killed before its finally ran — the
      # respawn must free the old slot, not leak it.
      try:
        old_actor.release_policy_state()
      except Exception:
        pass
    with self._lock:
      slot.respawns += 1
      slot.respawn_streak += 1
      # Pace the NEXT attempt now, so a spawn that fails (or succeeds
      # and immediately crash-loops) waits out the jittered backoff
      # before the health loop touches the slot again.
      slot.next_respawn_time = (time.monotonic()
                                + slot.backoff.next_delay())
      # Probation (round 15): a rehabilitated slot gets ONE probe
      # (re)spawn — streak 1 is the probe itself; a second respawn
      # without a completed unroll re-quarantines immediately instead
      # of re-running the whole give-up ladder.
      give_up = ((self._quarantine_after > 0 and
                  slot.respawn_streak > self._quarantine_after) or
                 (slot.probation and slot.respawn_streak > 1))
      if give_up:
        slot.quarantined = True
        slot.quarantined_at = time.monotonic()
        slot.probation = False
        slot.thread = None
    if give_up:
      log.error(
          'actor %d QUARANTINED after %d consecutive respawns without '
          'a completed unroll (last error: %s) — the slot is marked '
          'dead; the rest of the fleet keeps feeding', slot.index,
          slot.respawn_streak, slot.error)
      return
    try:
      self._spawn(slot)
    except Exception as e:
      # A failed respawn (env construction, denied inference-slot
      # admission) must not propagate into the learner loop that
      # called check_health — start()-time spawn failures still raise
      # for setup errors (admission denials degrade; see start()), but
      # a mid-run respawn records the error on the slot: the next
      # health check retries after the slot's backoff, and the learner
      # surfaces it via errors() only if the pipeline actually stalls
      # (the same containment as any other actor-side failure).
      with self._lock:
        slot.error = e
        slot.thread = None

  # --- elastic fleet size (round 15): the controller's actuator ---

  def target_size(self) -> int:
    """Contributing slots: neither parked nor quarantined — the value
    the fleet-size actuator steps (growing past it first unparks,
    then rehabilitates)."""
    with self._lock:
      return sum(1 for s in self._slots
                 if not s.parked and not s.quarantined)

  def set_target_size(self, n: int) -> Dict[str, List[int]]:
    """Thread-safe elastic resize toward `n` contributing slots.

    Shrink parks the highest-index contributing slots (each actor
    exits cleanly after its current unroll — the on_unroll seam; a
    parked slot leaves the quorum denominator, so shedding load never
    reads as a dying fleet). Grow first UNPARKS parked slots, then
    REHABILITATES quarantined ones whose probation cool-down has
    elapsed: quarantine cleared, probation armed, respawn ladder
    reset — the next check_health runs the probe spawn, and ONE
    completed unroll clears probation (slots_rehabilitated); a repeat
    failure re-quarantines immediately. The fleet never grows past
    its constructed slot count (the bounded-move guarantee — the
    controller's actuator registers that as the hard max).

    Returns {'parked': [...], 'unparked': [...], 'rehabilitated':
    [...]} slot indices. May deliver fewer than requested when every
    remaining quarantined slot is still inside its cool-down — the
    caller (controller) simply retries after its own cool-down."""
    now = time.monotonic()
    report = {'parked': [], 'unparked': [], 'rehabilitated': []}
    with self._lock:
      n = max(0, min(int(n), len(self._slots)))
      contributing = [s for s in self._slots
                      if not s.parked and not s.quarantined]
      if n < len(contributing):
        for slot in reversed(contributing[n:]):
          slot.parked = True
          report['parked'].append(slot.index)
      elif n > len(contributing):
        need = n - len(contributing)
        for slot in self._slots:
          if need == 0:
            break
          if slot.parked and not slot.quarantined:
            slot.parked = False
            # Spawn-eligible immediately: a slot parked since start
            # has no thread; one parked mid-run has a finished one.
            # Any error from before the park is a closed incident —
            # it must not surface through errors() as the cause of
            # whatever stalls the pipeline next.
            slot.error = None
            slot.next_respawn_time = 0.0
            report['unparked'].append(slot.index)
            need -= 1
        if need:
          ready = sorted(
              (s for s in self._slots if s.quarantined and
               now - s.quarantined_at >= self._probation_secs),
              key=lambda s: s.quarantined_at)
          for slot in ready[:need]:
            slot.quarantined = False
            slot.probation = True
            slot.parked = False
            slot.respawn_streak = 0
            slot.backoff.reset()
            slot.next_respawn_time = 0.0
            # The quarantine-era error is a CLOSED incident: leaving
            # it would make errors() surface it as live mid-probation
            # and misdiagnose an unrelated stall (the slot stays
            # respawn-eligible — a thread-less slot counts as dead).
            slot.error = None
            self._rehabilitations += 1
            report['rehabilitated'].append(slot.index)
    for which in ('parked', 'unparked', 'rehabilitated'):
      if report[which]:
        log.warning('fleet resize -> %d contributing: %s slots %s',
                    n, which, report[which])
    return report

  def errors(self) -> List[BaseException]:
    """Errors the learner should act on NOW. A quarantined slot's
    error is a closed incident (logged, counted in stats() — the
    give-up already happened), not the cause of whatever stalls the
    pipeline hours later — surfacing it would misdiagnose the new
    incident; a PARKED slot's stale error is the same (the park was
    deliberate). Exception: when EVERY active slot is quarantined the
    fleet is dead and those errors ARE the cause, so they come back."""
    with self._lock:
      live = [s.error for s in self._slots
              if s.error is not None and not s.quarantined
              and not s.parked]
      if live:
        return live
      active = [s for s in self._slots if not s.parked]
      if active and all(s.quarantined for s in active):
        return [s.error for s in active if s.error is not None]
      return []

  def stats(self, healthy_horizon_secs: float = 60.0):
    """Fleet health counters.

    `alive` counts slots whose CURRENT thread is running — but a
    wedged actor (blocked in env.step) or one whose error hasn't been
    collected yet is alive without producing, and a stalled thread
    orphaned by respawn keeps running as a daemon invisibly. `healthy`
    is the honest signal: the slot's current-generation thread is
    alive, has no recorded error, AND heartbeat-fresh within
    `healthy_horizon_secs` (align it with the driver's stall timeout).
    `healthy_fraction` is the quorum the driver logs — the scheduler-
    facing 'how much of my fleet is actually feeding' number.
    """
    now = time.monotonic()
    with self._lock:
      alive = [s for s in self._slots
               if s.thread is not None and s.thread.is_alive()]
      healthy = [s for s in alive
                 if s.error is None and not s.quarantined and
                 not s.parked and
                 now - s.last_heartbeat <= healthy_horizon_secs]
      # Wedged = alive with NO heartbeat inside the horizon and no
      # recorded error: the thread runs but produces nothing — the
      # blocked-in-env.step / parked-on-backpressure shape the
      # zero-deadlocked-threads chaos SLO counts (an errored slot is
      # 'dead pending respawn', a different bucket; a parked slot is
      # deliberately idle, neither).
      wedged = [s for s in alive
                if s.error is None and not s.quarantined and
                not s.parked and
                now - s.last_heartbeat > healthy_horizon_secs]
      # Quorum denominator = ACTIVE (non-parked) slots (round 15): a
      # controller-shrunk fleet is smaller on purpose — parked slots
      # reading as unhealthy would make every deliberate shed look
      # like a dying plane to the fleet_healthy_fraction objective.
      active = sum(1 for s in self._slots if not s.parked)
      return {
          'unrolls': sum(s.unrolls_done for s in self._slots),
          'respawns': sum(s.respawns for s in self._slots),
          'alive': len(alive),
          'healthy': len(healthy),
          'wedged': len(wedged),
          'healthy_fraction': (len(healthy) / active
                               if active else 1.0),
          # Give-up slots (round 9): respawn exhausted its budget —
          # the honest 'this much of my fleet is permanently gone'
          # number the driver surfaces as `slots_quarantined`.
          'slots_quarantined': sum(1 for s in self._slots
                                   if s.quarantined),
          # Elastic-fleet surface (round 15).
          'parked': len(self._slots) - active,
          'target_size': sum(1 for s in self._slots
                             if not s.parked and not s.quarantined),
          'rehabilitations': self._rehabilitations,
          'slots_rehabilitated': self._slots_rehabilitated,
      }

  def _join_all(self, timeout: float, what: str,
                consequence: str) -> Dict[str, List[int]]:
    """Deadline-join every actor thread; actors that miss it are
    NAMED in the log and the returned report instead of dropped
    silently (round 9 — the shared tail of stop() and quiesce())."""
    deadline = time.monotonic() + timeout
    unjoined: List[int] = []
    for slot in self._slots:
      if slot.thread is not None:
        slot.thread.join(max(0.0, deadline - time.monotonic()))
        if slot.thread.is_alive():
          unjoined.append(slot.index)
    if unjoined:
      log.warning('fleet %s: actors %s did not stop within %.1fs '
                  '(%s)', what, unjoined, timeout, consequence)
    return {'unjoined_actors': unjoined}

  def quiesce(self, timeout: float = 10.0) -> Dict[str, List[int]]:
    """Stop production WITHOUT closing the buffer (the preemption-
    drain path): the stop event ends each actor's loop after its
    current unroll, and the in-flight unrolls land in the trajectory
    buffer for the learner to flush. Joins actor threads up to
    `timeout`; returns {'unjoined_actors': [...]} — the slots whose
    unrolls are lost to the drain (a wedged env can't be joined; its
    unroll follows the reference's crash semantics)."""
    self._stop.set()
    return self._join_all(timeout, 'quiesce',
                          'their in-flight unrolls are lost')

  def stop(self, timeout: float = 10.0) -> Dict[str, List[int]]:
    """Stop the fleet and close the buffer. Returns the same report as
    `quiesce`. After stop() returns the buffer is CLOSED: any
    straggler thread's `put` raises `ring_buffer.Closed` instead of
    landing a stale unroll (regression-tested; the in-RUN orphan
    window documented in `_respawn` is unchanged). The buffer closes
    BEFORE the join: an actor blocked in a full buffer's put must be
    woken (Closed) or it could never exit."""
    self._stop.set()
    self._buffer.close()
    return self._join_all(timeout, 'stop',
                          'orphaned as daemon threads')
