"""Quantized publish codec (round 21): int8 absmax param snapshots.

The PR 1 wire codec stopped at bf16 — a 2x cut of the publish blob
with ~3 decimal digits kept, safe for behaviour policies (the bench's
param_fanout rows priced it). This module is the next rung: INT8 with
a per-leaf absmax scale, for both the in-process publish copy (the
serving plane's version table holds ~4x more resident versions under
the same HBM budget) and the cross-host fan-out (wire kind
'params_int8', protocol v10 — negotiated off for v<=9 peers, which
keep getting the bf16/f32 blob).

Shape of the encoding: each float32 leaf x becomes
`Int8Leaf(q=round(x/scale) in [-127,127], scale=max|x|/127)`. The q
array keeps the ORIGINAL shape, which is what makes the codec
`ShardingRegistry`-aware: a quantized leaf's placement spec is the
original leaf's spec applied to q plus a replicated scalar scale
(`parallel.sharding.quantized_specs`), so registry rules written
against param paths keep matching. Non-f32 leaves (ints, bools,
already-bf16 trees) pass through untouched — the same f32-only rule
the bf16 codec ships.

`Int8Leaf` is a registered jax pytree node: a quantized tree jits,
device_puts, and digests (`integrity.tree_digest` walks q AND scale)
exactly like a plain tree, and `dequantize_tree` runs in-graph — the
serving step traces the dequant into the compiled program, so serving
an int8-resident version costs one fused multiply, not a host round
trip.

Quantization is LOSSY (max per-leaf error = scale/2). It therefore
ships parity-GATED: `greedy_agreement` scores argmax-action agreement
of the quantized policy against fp32 on the same inputs, and the
serving bench (BENCH_ONLY=serving) + the CI serving lane hold the
gate. docs/PERF.md records the wire-bytes/blackout rows per the
accept/reject discipline.
"""

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

# q = clip(round(x / scale), -QMAX, QMAX); scale = absmax / QMAX.
QMAX = 127


class Int8Leaf:
  """One quantized leaf: `q` (int8, the original leaf's shape) and
  `scale` (float32 scalar). Registered as a jax pytree node so
  quantized trees flow through jit / device_put / tree_digest like
  plain trees; `dequantize_tree` maps it back to float32."""

  __slots__ = ('q', 'scale')

  def __init__(self, q, scale):
    self.q = q
    self.scale = scale

  def __repr__(self):
    shape = getattr(self.q, 'shape', None)
    return f'Int8Leaf(shape={shape}, scale={self.scale!r})'

  # __slots__ classes need explicit pickle state (the wire blob is a
  # pickled tree of these; protocol-5 OOB buffers still extract the
  # arrays zero-copy — numpy provides the buffers, not the container).
  def __getstate__(self):
    return (self.q, self.scale)

  def __setstate__(self, state):
    self.q, self.scale = state


jax.tree_util.register_pytree_node(
    Int8Leaf,
    lambda leaf: ((leaf.q, leaf.scale), None),
    lambda _, children: Int8Leaf(*children))


def _is_q(x):
  return isinstance(x, Int8Leaf)


def _is_f32(x):
  return getattr(x, 'dtype', None) in (np.float32, jnp.float32)


def quantize_np(tree):
  """Host-side (wire) absmax int8 quantization: every float32 leaf →
  Int8Leaf(np.int8 q, np.float32 scalar scale); everything else
  passes through. An all-zero leaf gets scale 0 (dequantizes to
  exact zeros)."""

  def one(x):
    if not _is_f32(x):
      return x
    x = np.asarray(x)
    absmax = float(np.max(np.abs(x))) if x.size else 0.0
    scale = np.float32(absmax / QMAX)
    if scale == 0.0:
      return Int8Leaf(np.zeros(x.shape, np.int8), scale)
    q = np.clip(np.rint(x / scale), -QMAX, QMAX).astype(np.int8)
    return Int8Leaf(q, scale)

  return jax.tree_util.tree_map(one, tree)


def quantize_device(tree):
  """Device-side quantization for the in-process publish copy (the
  version table's int8-resident entries): same absmax scheme with
  jnp ops, so the copy stays on device. `jnp.where` keeps the
  all-zero-leaf case graph-safe (no host read of the scale)."""

  def one(x):
    if not _is_f32(x):
      return x
    x = jnp.asarray(x)
    scale = (jnp.max(jnp.abs(x)) / QMAX).astype(jnp.float32)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.rint(x / safe), -QMAX, QMAX).astype(jnp.int8)
    return Int8Leaf(q, scale)

  return jax.tree_util.tree_map(one, tree)


def dequantize_tree(tree):
  """Int8Leaf leaves → float32 (jnp ops — traces in-graph, so a
  serving step over an int8-resident version fuses the dequant into
  the compiled program). Identity for trees with no quantized
  leaves."""

  def one(x):
    if not _is_q(x):
      return x
    return jnp.asarray(x.q, jnp.float32) * x.scale

  return jax.tree_util.tree_map(one, tree, is_leaf=_is_q)


def dequantize_np(tree):
  """Host-side decode (the v10 client's 'params_int8' install path):
  Int8Leaf → np.float32. The actor's agent/contract only ever sees
  f32, exactly like the bf16 upcast path."""

  def one(x):
    if not _is_q(x):
      return x
    return (np.asarray(x.q, np.float32)
            * np.float32(x.scale)).astype(np.float32)

  return jax.tree_util.tree_map(one, tree, is_leaf=_is_q)


def is_quantized(tree) -> bool:
  """True if any leaf of `tree` is an Int8Leaf."""
  found = []
  jax.tree_util.tree_map(
      lambda x: found.append(True) if _is_q(x) else None, tree,
      is_leaf=_is_q)
  return bool(found)


def tree_nbytes(tree) -> int:
  """Total leaf bytes (Int8Leaf counts q + scale) — the version
  table's HBM-budget accounting and the bench's wire-bytes rows."""
  total = 0
  for leaf in jax.tree_util.tree_leaves(tree):
    total += int(np.asarray(leaf).nbytes)
  return total


def max_abs_error(tree) -> float:
  """Upper bound on the per-element absolute quantization error of an
  encoded tree: max over leaves of scale/2 (rounding half-step)."""
  worst = 0.0

  def one(x):
    nonlocal worst
    if _is_q(x):
      worst = max(worst, float(x.scale) / 2.0)

  jax.tree_util.tree_map(one, tree, is_leaf=_is_q)
  return worst


def greedy_agreement(logits_a, logits_b) -> float:
  """Fraction of rows whose greedy (argmax) action agrees — the
  parity gate's score. Greedy, not sampled: sampled actions differ by
  RNG alone, so only the argmax comparison isolates the codec's
  effect on the policy."""
  a = np.argmax(np.asarray(logits_a), axis=-1)
  b = np.argmax(np.asarray(logits_b), axis=-1)
  if a.size == 0:
    return 1.0
  return float(np.mean(a == b))


def wire_sizes(params) -> Tuple[int, int, int]:
  """(f32, bf16, int8) leaf-byte totals for one tree — the bench's
  wire-bytes arithmetic without building three real blobs."""
  f32 = tree_nbytes(params)
  bf16 = 0
  for leaf in jax.tree_util.tree_leaves(params):
    arr = np.asarray(leaf)
    bf16 += arr.nbytes // 2 if arr.dtype == np.float32 else arr.nbytes
  int8 = tree_nbytes(quantize_np(params))
  return f32, bf16, int8
