"""Cross-host serving router (round 21): actor-side request routing.

An actor host that offloads inference (TorchBeast-style decoupled
serving) no longer binds to a single learner-host replica: this module
spreads `remote_infer` batches over every learner host that advertises
the v10 serving capability, so one slow or dead replica costs its
share of the traffic and nothing else.

Design:

- **Smooth weighted round-robin** (the nginx algorithm): every pick
  adds each candidate's weight to its running credit, serves the
  highest credit, then subtracts the total weight from the winner.
  Unlike naive weighted RR this interleaves — a 5:1:1 weight split
  yields A A B A A C A..., not A A A A A B C — so a fast replica's
  extra share never arrives as a burst that re-creates the queueing
  it was meant to absorb.
- **Health-weighted**: each success folds the observed latency into a
  per-replica EWMA, and the weight is the inverse of that EWMA — a
  replica running 3x slower organically receives ~1/3 of the traffic
  without any operator knob.
- **Failover with probation**: a transport or server error marks the
  replica down for `probation_secs` and the request retries on the
  next pick, so a SIGKILLed replica costs at most one in-flight
  request per connection. Probation expiry makes the replica pickable
  again (the next pick redials it); repeated failure just re-arms the
  window — no thundering reconnect loop.
- **Draining**: the v10 infer reply's notice dict carries 'draining'
  once the server has begun shutdown. The router stops NEW picks to a
  draining replica immediately (its in-flight result is still valid —
  drain is an advisory, not an error) and `apply_membership` turns the
  PR 17 ledger's host_left/host_joined events into removals/adds, so
  elastic pod changes reshape the serving plane without a restart.

The router never owns the wire: `connect_fn(address)` returns any
channel exposing `remote_infer(payload) -> (result, notice)`,
`supports_infer() -> bool`, and `close()` — production uses
`connect_serving` below (a RemoteActorClient handshake, which rides
the learner's existing listener and, by never offering a 'host'
identity, stays OUT of the membership ledger), tests use fakes.
"""

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from absl import logging as log

from scalable_agent_tpu import telemetry

_ROUTE_MS = telemetry.histogram('serving/route_ms')
_ROUTE_ERRORS = telemetry.counter('serving/route_errors')
_ROUTE_FAILOVERS = telemetry.counter('serving/route_failovers')
_ROUTE_REPLICAS = telemetry.gauge('serving/route_replicas')


class NoReplicasAvailable(RuntimeError):
  """Every replica is down, draining, or departed — the caller backs
  off and retries (or falls back to local inference); the router never
  blocks waiting for one to recover."""


class _Replica:
  """Routing state for one serving replica.

  All fields except `io_lock` are guarded by the router's `_lock`;
  the channel's REQUEST traffic (request/reply lockstep on one
  socket) is serialized by `io_lock` alone, so a slow infer on one
  replica never holds the pick path for the others.
  """

  __slots__ = ('address', 'channel', 'weight', 'current', 'ewma_ms',
               'serves', 'errors', 'draining', 'down_until', 'left',
               'io_lock')

  def __init__(self, address: str):
    self.address = address
    self.channel = None          # lazy: dialed on first pick
    self.weight = 1.0            # inverse-EWMA health weight
    self.current = 0.0           # smooth-RR running credit
    self.ewma_ms: Optional[float] = None
    self.serves = 0
    self.errors = 0
    self.draining = False
    self.down_until = 0.0        # monotonic deadline; 0 = up
    self.left = False            # departed via membership/note_left
    self.io_lock = threading.Lock()


class ServingRouter:
  """Spread `infer` calls over N serving replicas (see module doc).

  Thread-safe: picks and bookkeeping run under one router lock;
  dials and the infer RPCs themselves run outside it (per-replica
  `io_lock` keeps each channel's request/reply framing intact).
  """

  # EWMA smoothing for per-replica latency; 0.2 ≈ the last ~5 calls
  # dominate, so a recovering replica earns its weight back in a few
  # requests instead of dragging an hour of history.
  _EWMA_ALPHA = 0.2

  def __init__(self, addresses: Sequence[str],
               connect_fn: Callable[[str], object],
               probation_secs: float = 5.0,
               clock: Callable[[], float] = time.monotonic):
    self._connect_fn = connect_fn
    self._probation = float(probation_secs)
    self._clock = clock
    self._lock = threading.Lock()
    # guarded_by _lock: _replicas (and every _Replica field except
    # io_lock), _route_errors, _route_failovers.
    self._replicas: Dict[str, _Replica] = {}
    self._route_errors = 0
    self._route_failovers = 0
    for addr in addresses:
      self._replicas[str(addr)] = _Replica(str(addr))
    _ROUTE_REPLICAS.set(len(self._replicas))

  # -- pick / serve ------------------------------------------------

  def _available_locked(self) -> List[_Replica]:
    now = self._clock()
    return [r for r in self._replicas.values()
            if not r.left and not r.draining
            and (r.down_until == 0.0 or r.down_until <= now)]

  # Weight-spread bound for the pick: no replica's effective share
  # drops below 1/_MAX_SPREAD of the fastest's. Without it a one-off
  # slow reply poisons the EWMA into exile — the measured case is the
  # warm-up request eating a ~470 ms first-call compile (weight 0.002
  # vs 0.36), after which the replica gets ~1/180 of the picks and
  # the EWMA never sees enough traffic to recover. Floored at 1/10 it
  # keeps ~9% share and re-earns its weight in a handful of replies.
  _MAX_SPREAD = 10.0

  def _pick_locked(self) -> Optional[_Replica]:
    """Smooth weighted RR over the currently-available replicas."""
    avail = self._available_locked()
    if not avail:
      return None
    floor = max(r.weight for r in avail) / self._MAX_SPREAD
    total = 0.0
    best = None
    for rep in avail:
      w = max(rep.weight, floor)
      rep.current += w
      total += w
      if best is None or rep.current > best.current:
        best = rep
    best.current -= total
    return best

  def infer(self, payload: dict) -> Tuple[dict, dict]:
    """Route one inference batch; returns (result, notice).

    Tries each available replica at most once (failover on transport/
    server errors counts `serving/route_failovers`); raises
    NoReplicasAvailable when the pool is exhausted. A 'draining'
    notice drains the replica AFTER returning its (valid) result.
    """
    attempts = 0
    last_err: Optional[Exception] = None
    # Upper-bound the failover walk by the pool size at entry; the
    # pick itself re-evaluates availability each round, so replicas
    # marked down mid-walk are not retried.
    with self._lock:
      max_attempts = max(1, len(self._replicas))
    while attempts < max_attempts:
      with self._lock:
        rep = self._pick_locked()
      if rep is None:
        break
      attempts += 1
      try:
        result, notice = self._call(rep, payload)
      except (ConnectionError, OSError, RuntimeError, EOFError) as e:
        last_err = e
        self._mark_down(rep, e)
        if attempts < max_attempts:
          with self._lock:
            self._route_failovers += 1
          _ROUTE_FAILOVERS.inc()
        continue
      if notice.get('draining'):
        self.note_draining(rep.address)
      return result, notice
    raise NoReplicasAvailable(
        f'no serving replica available after {attempts} attempt(s)'
        + (f' (last error: {last_err})' if last_err else ''))

  def _call(self, rep: _Replica, payload: dict) -> Tuple[dict, dict]:
    with rep.io_lock:
      channel = rep.channel
      if channel is None:
        channel = self._connect_fn(rep.address)
        if hasattr(channel, 'supports_infer') and \
            not channel.supports_infer():
          self._close_channel(channel)
          raise RuntimeError(
              f'replica {rep.address} pre-dates wire v10 '
              '(no routed-inference capability)')
        with self._lock:
          rep.channel = channel
      t0 = self._clock()
      result, notice = channel.remote_infer(payload)
      lat_ms = (self._clock() - t0) * 1000.0
    _ROUTE_MS.observe(lat_ms)
    with self._lock:
      rep.serves += 1
      if rep.ewma_ms is None:
        rep.ewma_ms = lat_ms
      else:
        rep.ewma_ms = ((1.0 - self._EWMA_ALPHA) * rep.ewma_ms
                       + self._EWMA_ALPHA * lat_ms)
      # Inverse-latency health weight, normalized so the fastest
      # possible replica (ewma <= 1ms) sits at 1.0 — the SAME weight
      # an unmeasured replica starts with. Unmeasured must tie the
      # fastest, not trail it: otherwise the first replica to answer
      # a sub-millisecond call starves the rest before they are ever
      # probed.
      rep.weight = 1.0 / max(rep.ewma_ms, 1.0)
    return result, notice if isinstance(notice, dict) else {}

  def _mark_down(self, rep: _Replica, err: Exception):
    log.warning('serving replica %s failed (%s): probation %.1fs',
                rep.address, err, self._probation)
    _ROUTE_ERRORS.inc()
    with self._lock:
      rep.errors += 1
      rep.down_until = self._clock() + self._probation
      self._route_errors += 1
      channel, rep.channel = rep.channel, None
    self._close_channel(channel)

  @staticmethod
  def _close_channel(channel):
    if channel is None:
      return
    try:
      channel.close()
    except (OSError, RuntimeError):
      pass

  # -- membership --------------------------------------------------

  def add_replica(self, address: str):
    """Add (or resurrect) a replica; a departed address re-joins with
    fresh health state — its old EWMA belonged to the old process."""
    address = str(address)
    with self._lock:
      rep = self._replicas.get(address)
      if rep is None or rep.left:
        self._replicas[address] = _Replica(address)
      n = len([r for r in self._replicas.values() if not r.left])
    _ROUTE_REPLICAS.set(n)

  def note_draining(self, address: str):
    """Stop NEW picks to `address` (v10 drain notice)."""
    with self._lock:
      rep = self._replicas.get(str(address))
      if rep is not None and not rep.draining:
        rep.draining = True
        log.info('serving replica %s draining: removed from rotation',
                 address)

  def note_left(self, address: str):
    """Remove `address` from the pool (membership host_left)."""
    channel = None
    with self._lock:
      rep = self._replicas.get(str(address))
      if rep is not None and not rep.left:
        rep.left = True
        channel, rep.channel = rep.channel, None
      n = len([r for r in self._replicas.values() if not r.left])
    self._close_channel(channel)
    _ROUTE_REPLICAS.set(n)

  def apply_membership(self, events: Sequence[Dict],
                       address_of: Optional[Callable[[str], Optional[str]]]
                       = None):
    """Fold PR 17 ledger events into the pool: host_joined adds,
    host_left removes. `address_of(host_id)` maps a ledger identity to
    a serving address (None = this host serves no traffic — skipped);
    without it the host identity is assumed to BE the address."""
    for ev in events:
      host = ev.get('host')
      if host is None:
        continue
      addr = address_of(host) if address_of is not None else str(host)
      if addr is None:
        continue
      kind = ev.get('kind')
      if kind == 'host_joined':
        self.add_replica(addr)
      elif kind == 'host_left':
        self.note_left(addr)

  # -- introspection / lifecycle -----------------------------------

  def stats(self) -> Dict:
    with self._lock:
      replicas = [{
          'address': r.address,
          'serves': r.serves,
          'errors': r.errors,
          'weight': round(r.weight, 3),
          'ewma_ms': (round(r.ewma_ms, 3)
                      if r.ewma_ms is not None else None),
          'draining': r.draining,
          'left': r.left,
          'down': bool(r.down_until
                       and r.down_until > self._clock()),
      } for r in self._replicas.values()]
      return {
          'replicas': replicas,
          'available': len(self._available_locked()),
          'route_errors': self._route_errors,
          'route_failovers': self._route_failovers,
      }

  def close(self):
    with self._lock:
      channels = [r.channel for r in self._replicas.values()]
      for r in self._replicas.values():
        r.channel = None
    for channel in channels:
      self._close_channel(channel)


def connect_serving(address: str, contract,
                    connect_timeout_secs: float = 60.0,
                    wire_crc: bool = True):
  """Dial one serving replica: a RemoteActorClient handshake on the
  learner's existing listener. The hello offers NO 'host' identity,
  so this connection never enters the replica's membership ledger —
  routed-inference fan-out must not read as pod growth. Raises
  RuntimeError against a pre-v10 replica (the router treats that as a
  dead pick and moves on)."""
  from scalable_agent_tpu.runtime import remote  # cycle-free at call time
  client = remote.RemoteActorClient(
      address, connect_timeout_secs=connect_timeout_secs,
      wire_crc=wire_crc)
  try:
    client.handshake(contract)
    if not client.supports_infer():
      raise RuntimeError(
          f'replica {address} speaks protocol '
          f"{client.server_info.get('protocol')} < 10: no routed "
          'inference')
  except BaseException:
    client.close()
    raise
  return client
