"""Process-hosted Python objects (the reference's py_process, TPU-build).

Runs an arbitrary Python class (typically an environment) in a separate
OS process — the GIL escape that lets dozens of envs step concurrently —
and exposes its methods to the host runtime as blocking calls over a
pipe. Re-expresses the reference's `py_process.py` (reference:
py_process.py ≈L50–230) without the TF graph: there is no `tf.py_func`
to wrap because on the TPU build env stepping is host Python already
(runtime/actor.py); what survives is the process-hosting contract:

- `PyProcess(type_, constructor_kwargs)` + `.proxy.<method>(*args)` —
  the call is sent over a `multiprocessing.Pipe`, the caller blocks on
  the reply (reference `_TFProxy.__getattr__` ≈L50).
- `_tensor_specs(method_name, kwargs, constructor_kwargs)` protocol —
  classes declare the dtypes/shapes of method results; the parent
  validates replies against the declaration (the reference needed this
  to build graph ops; here it is a runtime contract check that keeps
  fixed-shape numerics the only thing crossing the boundary).
- Exceptions raised in the constructor or in a method are serialized
  back (with the remote traceback) and re-raised at the call site
  (reference ≈L60–80); the worker keeps serving after a method error.
- A broken/closed pipe raises `ProcessClosed` — the clean-shutdown
  signal, the reference's `IOError → StopIteration` convention (≈L72).
- `start_all` / `close_all` start/stop fleets via a thread pool — the
  reference's `PyProcessHook.begin/end` (≈L190–230) without the session.

Start method: `forkserver` by default. The driver builds env processes
AFTER JAX's inference warmup, i.e. from a parent already running JAX
thread pools — a plain `fork` there copies whatever mutexes happen to
be locked (Python 3.12 warns exactly about this), the classic
once-a-week CI hang. With forkserver, children are forked from the
clean single-threaded server process instead; call `warm_forkserver()`
as early as possible (before JAX spins up) so the one-time fork that
creates the server itself happens from a still-quiet parent.
Constructor kwargs and the hosted class must be picklable (module
level). `fork` remains available as an explicit opt-in for
unpicklable fixtures; `spawn` for classes needing a pristine
interpreter.
"""

import multiprocessing
import threading
import traceback
from multiprocessing.pool import ThreadPool

import numpy as np

DEFAULT_START_METHOD = 'forkserver'


def warm_forkserver():
  """Start the forkserver process now (idempotent). Best called before
  any JAX import/initialization — see the module docstring."""
  from multiprocessing import forkserver
  forkserver.ensure_running()


class ProcessClosed(Exception):
  """The hosted process's pipe is closed (clean shutdown or death)."""


class RemoteError(Exception):
  """An exception raised inside the hosted process.

  Carries the remote traceback text; the original exception (when
  picklable) is chained as `__cause__`."""


class SpecMismatchError(Exception):
  """A method reply did not match the class's `_tensor_specs`."""


_CLOSE = '__process_close__'


def _worker(conn, type_, constructor_kwargs):
  """Worker loop: construct, then serve (method, args, kwargs) requests."""
  try:
    obj = type_(**constructor_kwargs)
  except Exception as e:  # ctor failure → reported on first proxy call
    conn.send(('exception', _serialize_error(e)))
    conn.close()
    return
  while True:
    try:
      request = conn.recv()
    except (EOFError, OSError):
      break  # parent died/closed: fall through to close the object
    method, args, kwargs = request
    if method == _CLOSE:
      try:
        if hasattr(obj, 'close'):
          obj.close()
        conn.send(('ok', None))
      except Exception as e:
        conn.send(('exception', _serialize_error(e)))
      break
    try:
      result = getattr(obj, method)(*args, **kwargs)
      conn.send(('ok', result))
    except Exception as e:  # keep serving — reference semantics
      conn.send(('exception', _serialize_error(e)))
  try:
    conn.close()
  except OSError:
    pass


def _serialize_error(e):
  tb = ''.join(traceback.format_exception(type(e), e, e.__traceback__))
  try:
    import pickle
    pickle.dumps(e)
    payload = e
  except Exception:
    payload = None  # unpicklable exception: text only
  return (payload, tb)


def _validate_specs(result, specs, method):
  """Recursively check a reply against an ArraySpec pytree (None=skip)."""
  if specs is None:
    return
  if hasattr(specs, 'shape') and hasattr(specs, 'dtype'):
    arr = np.asarray(result)
    if tuple(arr.shape) != tuple(specs.shape) or arr.dtype != specs.dtype:
      raise SpecMismatchError(
          f'{method}: got shape={arr.shape} dtype={arr.dtype}, '
          f'spec requires shape={tuple(specs.shape)} dtype={specs.dtype}')
    return
  if isinstance(specs, (tuple, list)):
    if not isinstance(result, (tuple, list)) or len(result) != len(specs):
      raise SpecMismatchError(
          f'{method}: reply structure {type(result).__name__}'
          f'/{len(result) if hasattr(result, "__len__") else "?"} does '
          f'not match spec structure of length {len(specs)}')
    for r, s in zip(result, specs):
      _validate_specs(r, s, method)
    return
  raise SpecMismatchError(f'{method}: unsupported spec node {specs!r}')


class _Proxy:
  """Attribute access builds blocking remote calls (reference _TFProxy)."""

  def __init__(self, process):
    self._process = process

  def __getattr__(self, name):
    if name.startswith('_'):
      raise AttributeError(name)

    def call(*args, **kwargs):
      return self._process._call(name, args, kwargs)

    call.__name__ = name
    return call


class PyProcess:
  """Hosts an instance of `type_` in a child OS process.

  Args:
    type_: class to instantiate in the child. If it defines
      `_tensor_specs(method_name, kwargs, constructor_kwargs)` (static),
      replies are validated against the returned spec pytree.
    constructor_kwargs: kwargs for the child-side constructor (must be
      picklable under the default start method).
    context: multiprocessing start method (None = the module default,
      'forkserver'; 'fork'/'spawn' as explicit opt-ins).
    validate_specs: disable to skip reply validation (hot-path opt-out).
  """

  def __init__(self, type_, constructor_kwargs=None, context=None,
               validate_specs=True):
    self._type = type_
    self._constructor_kwargs = dict(constructor_kwargs or {})
    self._ctx = multiprocessing.get_context(
        context or DEFAULT_START_METHOD)
    self._validate = validate_specs and hasattr(type_, '_tensor_specs')
    self._conn = None
    self._process = None
    self._lock = threading.Lock()  # pipes are not thread-safe
    self._closed = False

  @property
  def proxy(self):
    return _Proxy(self)

  def start(self):
    if self._process is not None:
      raise RuntimeError('already started')
    self._conn, child_conn = self._ctx.Pipe(duplex=True)
    self._process = self._ctx.Process(
        target=_worker,
        args=(child_conn, self._type, self._constructor_kwargs),
        daemon=True)
    self._process.start()
    child_conn.close()  # parent keeps one end only
    return self

  def _call(self, method, args, kwargs):
    with self._lock:
      if self._closed or self._conn is None:
        raise ProcessClosed(f'{self._type.__name__} process not running')
      def handle_closed_pipe(e):
        # A child whose ctor failed sends ('exception', ...) and closes
        # its end; if it closed before our send/recv, the buffered ctor
        # error would be lost. Drain it so the documented "ctor failure
        # reported on first proxy call" contract holds regardless of
        # timing.
        buffered = self._drain_buffered_reply()
        if buffered is None:
          raise ProcessClosed(
              f'{self._type.__name__} process pipe closed') from e
        return buffered

      reply = None
      try:
        self._conn.send((method, args, kwargs))
      except (EOFError, OSError, BrokenPipeError) as e:
        reply = handle_closed_pipe(e)
      except Exception as e:
        # send() failed locally (e.g. unpicklable argument) — nothing
        # reached the child; blame the caller, not the remote side.
        raise TypeError(
            f'could not serialize request for '
            f'{self._type.__name__}.{method}: {e!r}') from e
      if reply is None:
        try:
          reply = self._conn.recv()
        except (EOFError, OSError, BrokenPipeError) as e:
          reply = handle_closed_pipe(e)
        except Exception as e:
          # The reply arrived but failed to unpickle (e.g. an exception
          # class whose __reduce__ pickles but can't reconstruct). The
          # message was fully consumed, so the pipe is still in sync —
          # report it as a remote failure instead of leaking a bare
          # unpickling error with no context.
          raise RemoteError(
              f'in hosted {self._type.__name__}.{method}: reply could '
              f'not be deserialized ({e!r})') from e
      status, payload = reply
    if status == 'exception':
      exc, tb = payload
      err = RemoteError(
          f'in hosted {self._type.__name__}.{method}:\n{tb}')
      if exc is not None:
        raise err from exc
      raise err
    if self._validate:
      specs = self._type._tensor_specs(method, kwargs,
                                       self._constructor_kwargs)
      _validate_specs(payload, specs, f'{self._type.__name__}.{method}')
    return payload

  def _drain_buffered_reply(self):
    """Return a reply the child pipelined before dying, if any."""
    try:
      if self._conn is not None and self._conn.poll(0):
        return self._conn.recv()
    except (EOFError, OSError, BrokenPipeError):
      pass
    return None

  def close(self, timeout=5.0):
    """Ask the child to close() its object and exit; reap the process.

    Idempotent; safe on a child that already died. If a proxy call is
    blocked on a hung child (it holds the call lock across recv), the
    graceful path is unreachable — terminate the child instead, which
    breaks the blocked recv with EOF."""
    if not self._lock.acquire(timeout=timeout):
      # A call is in flight against an unresponsive child: kill it.
      self._closed = True
      if self._process is not None:
        self._process.terminate()
        self._process.join(timeout)
      return
    try:
      if self._closed:
        return
      self._closed = True
      conn, process = self._conn, self._process
      self._conn = None
    finally:
      self._lock.release()
    if conn is not None:
      try:
        conn.send((_CLOSE, (), {}))
        if conn.poll(timeout):
          conn.recv()
      except (EOFError, OSError, BrokenPipeError):
        pass
      try:
        conn.close()
      except OSError:
        pass
    if process is not None:
      process.join(timeout)
      if process.is_alive():
        process.terminate()
        process.join(timeout)

  @property
  def running(self):
    return (self._process is not None and self._process.is_alive()
            and not self._closed)


def start_all(processes):
  """Start a fleet of PyProcesses (reference PyProcessHook.begin ≈L200).

  Sequential on purpose: `start()` is non-blocking (the child constructs
  asynchronously), and forking from pool threads is what Python 3.12's
  multi-threaded-fork warning is about."""
  processes = list(processes)
  for p in processes:
    p.start()
  return processes


def close_all(processes, timeout=5.0, pool_size=None):
  """Close a fleet concurrently (reference PyProcessHook.end ≈L220)."""
  processes = list(processes)
  if not processes:
    return
  with ThreadPool(pool_size or len(processes)) as pool:
    pool.map(lambda p: p.close(timeout), processes)


class PyProcessHook:
  """Reference-named lifecycle hook (reference: py_process.py ≈L190
  `PyProcessHook(SessionRunHook)`): `begin()` starts the registered
  fleet, `end()` closes it. There is no TF session to hook into here —
  call begin/end around your run loop, or use `hosted(...)` as a
  context manager (same implementation, exception-safe)."""

  def __init__(self, processes):
    self._processes = list(processes)

  def begin(self):
    return start_all(self._processes)

  def end(self, timeout=5.0):
    close_all(self._processes, timeout=timeout)


class hosted(PyProcessHook):
  """Context manager form: `with hosted([PyProcess(...), ...]) as
  procs:` — begin() on enter, end() on exit (error or not)."""

  def __enter__(self):
    return self.begin()

  def __exit__(self, *exc):
    self.end()
    return False


class ProxyEnv:
  """Adapts a hosted env's proxy to the `envs.base.Environment` surface
  so `runtime.actor.Actor` can drive an out-of-process env unchanged."""

  def __init__(self, process: PyProcess):
    self._process = process
    self._proxy = process.proxy

  def initial(self):
    return self._proxy.initial()

  def step(self, action):
    return self._proxy.step(action)

  def close(self):
    self._process.close()
