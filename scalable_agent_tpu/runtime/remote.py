"""Remote actors: actor-only hosts feeding a learner over TCP.

The reference runs dedicated actor processes/machines against the
learner through the TF1 gRPC runtime: actors hold their own env +
inference graph, fetch the learner-pinned weights per run, and their
`queue.enqueue` is a remote op into the learner-hosted FIFOQueue
(reference: experiment.py ≈L435–460 ClusterSpec/Server wiring, ≈L625
actor loop; SURVEY §3.4 — paper configs used 150–500 actor CPUs per
learner). A TPU host cannot step enough DMLab envs by itself to feed
200k frames/sec, so this scale-out path is load-bearing for the north
star.

TPU-native re-design (SURVEY §5.8 "shared memory / RPC to actor
processes"):

- The learner host runs a `TrajectoryIngestServer` next to its
  `TrajectoryBuffer`: remote unrolls land in the SAME buffer the local
  fleet feeds, so the learner pipeline (batcher → prefetcher → sharded
  step) is oblivious to where trajectories come from.
- Each actor-only host runs `run_remote_actor()`: a normal `ActorFleet`
  + CPU `InferenceServer` (inference on the actor host, exactly like
  the reference's distributed mode — NOT request/response inference
  against the learner), a local buffer, and a pump thread that ships
  unrolls to the learner and pulls fresh params when the learner's
  version advances.
- Weights flow learner → actor piggybacked on the unroll acks: each ack
  carries the learner's current params version; a stale client fetches
  the new snapshot. This is the gRPC variable-read replaced by an
  explicit snapshot protocol, with the same staleness story (actions
  within one unroll may span weight versions).

Wire protocol: length-prefixed pickled messages over TCP, strict
request→reply lockstep per socket (no concurrent writes per socket).
Backpressure is end-to-end: a full learner buffer blocks the ingest
worker's `put`, which delays the ack, which blocks the actor's pump —
the reference's capacity-1 remote enqueue semantics.

Transport planes (round 6 — BENCH_r05 measured all three pathologies):

- **Trajectory lane** (the hot path): one reader thread per connection
  does ONLY recv+parse and hands the unroll to a small validate/commit
  worker pool via a GIL-atomic queue; the worker validates, lands the
  unroll in the shared buffer (backpressure lives here) and sends the
  ack. Readers never touch the buffer lock, so N connections scale by
  overlapping socket copies instead of fighting over one
  recv→validate→put→ack critical path (r5: 4 connections measured
  SLOWER than 1).
- **Param lane** (weight fan-out): subscribers open a SECOND
  connection (`hello_params`) served by one selector thread with
  chunked non-blocking sends. r5 measured 8 polling fetchers
  collapsing the unroll pump 838.6 → 29.9 unrolls/s (ack p99 1.18 →
  95.8 ms): 8 handler threads each mid-sendall of a 6.5 MB blob
  starve the tiny acks. One multiplexing thread writing bounded
  chunks caps the blob plane at one runnable thread regardless of
  subscriber count. Snapshots ship bf16-cast by default
  (config.publish_codec; measured ratio 0.5 for ~5 ms vs zlib-1's
  0.926 for 209 ms).

Transport fault tolerance (round 11 — docs/TRANSPORT.md v6,
docs/ROBUSTNESS.md transport rows): every blocking socket path now
carries a deadline. Server readers poll with short timeouts
(`_ConnLiveness` — a half-open peer stalling MID-frame is reaped
instead of pinning its reader forever), sends are progress-bounded
(`_sendall_bounded` — a non-reading peer can't wedge a worker in
sendall), an idle reaper closes connections silent past
`remote_conn_idle_timeout_secs` on both lanes, v6 clients heartbeat
('ping'/'pong' with the current params version) to stay inside the
window, ingest workers emit ('busy',) keepalives while backpressure
holds an ack (slow learner ≠ dead learner), and a per-run SESSION
EPOCH rides the handshake so a hard-crashed-and-restarted learner
tells reattaching clients from fresh ones, times the fleet re-attach,
and provably accepts zero stale-incarnation unrolls
(`scripts/chaos.py run_partition_storm` asserts the SLOs). A
`ThreadWatchdog` surfaces any service thread that still wedges
(stats()['ingest_threads_wedged'] → driver summaries + incidents).

Data-plane integrity (round 12 — docs/TRANSPORT.md v7,
docs/ROBUSTNESS.md integrity rows): protocol v7 adds end-to-end
payload verification on both lanes. Every frame on a CRC-negotiated
connection carries a CRC32C trailer (integrity.py); the ingest
validate/commit worker verifies it BEFORE the buffer put and answers
`('corrupt', crc)` — the client re-sends once, then quarantines
ITSELF (persistent CRC failures mean a bad NIC/host, docs/RUNBOOK.md
§9). Param publishes additionally carry a CONTENT digest computed
from the snapshot at publish time: the client verifies it before
`update_params` installs anything into the inference arena, so a
publish corrupted between device_get and the wire (where the frame
CRC is self-consistent) is rejected fleet-wide without a version bump
and refetched on backoff — and the rejection is reported back on the
next `get_params`, so the learner's summaries see
`publish_digest_rejected` without a client-side side channel. All of
it negotiates OFF for v5/v6 peers at hello, the same extension
pattern as every protocol bump since round 9.

Trust model: pickle over cluster-internal sockets — identical trust to
the reference's unauthenticated TF gRPC runtime. Never expose the
ingest port outside the job's network. The CRC is an INTEGRITY check
against accidental corruption, not authentication.
"""

import logging
import os
import pickle
import queue
import random
import selectors
import signal
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from scalable_agent_tpu.observability import ThreadWatchdog

import numpy as np

from scalable_agent_tpu import integrity
from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis.runtime import guarded_by, make_lock
from scalable_agent_tpu.runtime import faults as faults_lib
from scalable_agent_tpu.runtime import ring_buffer

log = logging.getLogger('scalable_agent_tpu')

_LEN = struct.Struct('>Q')
_MAX_MSG = 1 << 32  # 4 GiB sanity bound
# v7 per-frame CRC32C trailer: 4 big-endian bytes AFTER the payload on
# connections that negotiated CRC at hello. The length prefix keeps
# counting tag+payload only, so the framing stays v4-compatible — a
# receiver that negotiated CRC simply reads 4 more bytes per frame.
_CRC = struct.Struct('>I')
# Frame kinds (one tag byte after the length prefix). PLAIN frames
# carry one pickled object. OOB frames carry a pickle-protocol-5
# skeleton plus the arrays' raw buffers out of band — pickling a
# 2.11 MB flagship unroll inline costs ~600 µs of pure copying per
# direction on the ingest path, the skeleton+buffers form ~66 µs
# (measured, docs/PERF.md): the frames are the bytes, so don't copy
# them through the pickler.
_FRAME_PLAIN = 0
_FRAME_OOB = 1
_OOB_META = struct.Struct('>II')    # (num buffers, skeleton length)
_OOB_BUFLEN = struct.Struct('>Q')
# Remote-actor seed namespace: far above any learner host's
# process_index * max(num_actors, 1000) base (a 16M+ learner stride
# would need thousands of processes), so cross-role streams never
# collide.
_REMOTE_SEED_SPACE = 1 << 24


def _plain_frame(payload: bytes, crc: bool = False) -> bytes:
  """One complete PLAIN wire frame for pre-pickled payload bytes,
  with the v7 CRC trailer when `crc` (the trailer covers tag+payload
  — everything the length prefix counts)."""
  body = bytes((_FRAME_PLAIN,)) + payload
  frame = _LEN.pack(len(body)) + body
  if crc:
    frame += _CRC.pack(integrity.crc_bytes(body))
  return frame


def _send_msg(sock: socket.socket, obj, crc: bool = False) -> None:
  sock.sendall(_plain_frame(
      pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), crc=crc))


# Buffers at or below this coalesce into one sendall with their
# neighbors: an unroll carries ~11 OOB buffers of which only the
# frame stack is big, and a syscall per 400-byte reward array costs
# more than copying it (round 6 — the per-message syscall count was
# one of the two costs keeping multi-connection ingest from scaling).
_OOB_COALESCE = 128 * 1024


def _oob_frame_segments(obj) -> List:
  """The complete OOB wire frame for `obj`, as segments ready for
  sendall: [head (length prefix + tag + meta + skeleton + buffer
  table), raw buffer memoryview, ...]. The ONE place the OOB frame
  layout is built — `_send_oob` streams these per message, the ingest
  server caches them per published param version."""
  buffers = []
  skeleton = pickle.dumps(obj, protocol=5,
                          buffer_callback=buffers.append)
  raws = [b.raw() for b in buffers]
  lens = b''.join(_OOB_BUFLEN.pack(r.nbytes) for r in raws)
  total = (1 + _OOB_META.size + len(skeleton) + len(lens)
           + sum(r.nbytes for r in raws))
  head = (_LEN.pack(total) + bytes((_FRAME_OOB,))
          + _OOB_META.pack(len(raws), len(skeleton))
          + skeleton + lens)
  return [head] + raws


def _segments_crc(segments) -> int:
  """CRC32C over a complete OOB frame's CONTENT (everything the
  length prefix counts: tag + meta + skeleton + table + raw buffers —
  i.e. segment 0 minus its 8-byte length prefix, then every raw)."""
  acc = integrity.Crc()
  acc.update(memoryview(segments[0])[_LEN.size:])
  for raw in segments[1:]:
    acc.update(raw)
  return acc.value


def _send_segments(sock: socket.socket, segments,
                   trailer: Optional[bytes] = None) -> None:
  """Stream a pre-built frame's segments with small-buffer coalescing
  (`_OOB_COALESCE`); big ones go as bare sendalls on their memoryview
  — no 2 MB join. `trailer` (the v7 CRC bytes) rides the final
  flush."""
  pending = [segments[0]]

  def flush():
    if not pending:
      return
    sock.sendall(pending[0] if len(pending) == 1
                 else b''.join(pending))
    pending.clear()

  for raw in segments[1:]:
    if memoryview(raw).nbytes <= _OOB_COALESCE:
      pending.append(raw)
      if sum(len(p) for p in pending) > _OOB_COALESCE:
        flush()
    else:
      flush()
      sock.sendall(raw)
  if trailer is not None:
    pending.append(trailer)
  flush()


def _send_oob(sock: socket.socket, obj, crc: bool = False) -> None:
  """Ship `obj` with its array buffers OUT of the pickle stream: the
  skeleton + per-buffer lengths go in the frame head, then each raw
  buffer is sent directly (no pickler copy). The receiver
  reconstructs with zero-copy views. With `crc`, the v7 trailer is
  computed over the frame content BEFORE the wire_bitflip fault site
  runs — an injected flip ships with a stale trailer, exactly the
  silent-corruption shape the check exists to catch."""
  segments = _oob_frame_segments(obj)
  trailer = _CRC.pack(_segments_crc(segments)) if crc else None
  plan = faults_lib.active()
  fault = faults_lib.fire('wire_bitflip')
  if fault is not None:
    segments = faults_lib.apply_wire_bitflip(
        fault, segments, seed=plan.seed if plan else 0)
  _send_segments(sock, segments, trailer)


class _CrcContext:
  """Per-frame CRC ledger for a receive on a v7 CRC connection:
  `_recv_msg` accumulates the computed CRC over every frame piece as
  it lands and records the wire trailer; the CALLER compares (the
  ingest worker does it just before the buffer put, so a corrupt
  unroll is refused with the benign ('corrupt', crc) reply instead of
  a connection drop — the reader only hard-fails frames whose very
  parse is untrustworthy)."""

  __slots__ = ('computed', 'wire')

  def __init__(self):
    self.computed = 0
    self.wire: Optional[int] = None

  def reset(self):
    self.computed = 0
    self.wire = None

  def update(self, data):
    self.computed = integrity.crc_bytes(data, self.computed)

  @property
  def ok(self) -> bool:
    return self.wire is not None and self.wire == self.computed


class _FrameStall(OSError):
  """A peer stopped sending MID-frame past the stall deadline (a
  half-open connection trickling to silence) — the reader reaps the
  connection instead of pinning itself on the partial frame forever."""


class _ServerClosing(ConnectionError):
  """The server's close() began while this reader was parked in its
  poll loop. The reader exits WITHOUT closing or unlisting its
  connection: close() already holds the shutdown sequence ('bye' →
  half-close → close) for every listed conn, and a reader racing it
  with its own close() would discard the buffered 'bye' with an RST
  (legacy blocking readers never woke here, so the bye always won)."""


class _SendStall(OSError):
  """A send made no progress past the stall deadline (a blackholed /
  non-reading peer with a full TCP window) — the sender gives up on
  the connection instead of wedging its thread in sendall forever."""


class _ConnLiveness:
  """Per-connection recv liveness for the server's reader threads
  (round 11). The socket runs in timeout mode (short poll); every poll
  expiry lands here:

  - `progress(n)` on received bytes: refreshes the connection's
    last-recv clock (the reaper's idle measure) and beats the server's
    thread watchdog.
  - `idle(got)` on a poll timeout: beats the watchdog (an idle reader
    is NOT a wedged reader), aborts cleanly when the server is
    closing, raises `_FrameStall` when the timeout fired MID-frame
    past the stall deadline (a half-open peer must not pin the reader
    on a partial frame — `in_frame` spans the WHOLE frame, set by
    _recv_msg once the header lands, so the deadline cannot reset at
    sub-frame read boundaries), and emits the ('busy',) backpressure
    keepalive for a conn whose unroll is in flight — the READER owns
    the keepalive, so it flows whether the job is held by a worker or
    still parked in the handoff queue (workers < connections under
    load). Idle BETWEEN frames with nothing in flight is legal here;
    the reaper owns that budget (it closes the socket, which surfaces
    as an OSError in the reader).
  """

  def __init__(self, conn, closed_event, stall_secs, watchdog=None,
               name='', heartbeat_secs: float = 0.0):
    self._conn = conn
    self._closed = closed_event
    self._stall_secs = stall_secs
    self._watchdog = watchdog
    self._name = name
    self._heartbeat_secs = heartbeat_secs
    self._last_busy = time.monotonic()
    self.in_frame = False  # header received, frame body outstanding
    # Bytes of the CURRENT frame received so far (header included):
    # the discard ledger — when a frame is thrown away (quarantine on
    # an unparseable frame, a mid-frame stall reap), the reader
    # reports HOW MUCH was discarded instead of dropping the partial
    # accounting on the floor (round 12 fix).
    self.frame_bytes = 0

  def beat(self):
    if self._watchdog is not None:
      self._watchdog.beat(self._name)

  def progress(self, nbytes):
    if self.in_frame:
      self.frame_bytes += nbytes
    self._conn.last_recv = time.monotonic()
    self._conn.hb_missed = False
    self.beat()

  def idle(self, got):
    self.beat()
    if self._closed.is_set():
      raise _ServerClosing('server closing')
    now = time.monotonic()
    if (got or self.in_frame) and (now - self._conn.last_recv
                                   > self._stall_secs):
      raise _FrameStall(
          f'peer silent mid-frame for more than {self._stall_secs}s')
    if (self._conn.heartbeat and self._conn.is_waiting_on_us()
        and self._heartbeat_secs > 0
        and now - self._last_busy >= self._heartbeat_secs):
      # Backpressure keepalive: the peer is parked lockstep awaiting
      # our reply (worker blocked in put OR job still queued) — tell
      # it we're slow, not dead, at the heartbeat cadence.
      self._last_busy = now
      try:
        self._conn.send(('busy',))
      except OSError:
        pass  # peer gone; the recv path will notice


def _recv_into(sock: socket.socket, view, n: int, liveness=None) -> int:
  """Fill view[:n] from the socket; returns bytes received (< n only
  on EOF). With `liveness`, the socket is expected to be in timeout
  mode: poll expiries route to liveness.idle (which may raise to abort
  a stalled frame) and received bytes to liveness.progress."""
  got = 0
  while got < n:
    try:
      r = sock.recv_into(view[got:n])
    except socket.timeout:
      if liveness is None:
        raise
      liveness.idle(got)
      continue
    if r == 0:
      return got  # EOF
    got += r
    if liveness is not None:
      liveness.progress(r)
  return got


def _recv_exact(sock: socket.socket, n: int, liveness=None):
  """n bytes as a bytearray (writable — OOB array views alias it), or
  None on clean EOF."""
  buf = bytearray(n)
  got = _recv_into(sock, memoryview(buf), n, liveness)
  if got == 0:
    return None  # clean EOF
  if got < n:
    return None  # EOF mid-read; callers map non-header Nones to errors
  return buf


def _sendall_bounded(sock: socket.socket, data, stall_secs: float,
                     beat=None) -> None:
  """sendall with a NO-PROGRESS deadline, for sockets in timeout mode:
  a live-but-slow peer keeps the transfer going chunk by chunk (each
  successful send resets the clock — a big snapshot over a thin pipe
  is fine), while a blackholed/non-reading peer whose TCP window
  filled makes no progress and aborts with `_SendStall` instead of
  wedging the sending thread forever."""
  view = memoryview(data)
  last_progress = time.monotonic()
  while view.nbytes:
    try:
      sent = sock.send(view)
    except socket.timeout:
      if beat is not None:
        beat()
      if time.monotonic() - last_progress > stall_secs:
        raise _SendStall(
            f'send made no progress for more than {stall_secs}s '
            f'({view.nbytes} byte(s) unsent)')
      continue
    view = view[sent:]
    last_progress = time.monotonic()


def _recv_msg(sock: socket.socket, liveness=None, crc_ctx=None):
  """One message (either frame kind), or None on clean EOF.

  OOB frames recv each array buffer straight into its own
  UNINITIALIZED storage (np.empty + recv_into): one 2.11 MB unroll
  used to land in a zero-filled bytearray first — ~95 µs of memset
  holding the GIL per message, one of the two per-message costs that
  kept multi-connection ingest from scaling (round 6).

  `crc_ctx` (v7 CRC-negotiated connections): the computed CRC over
  every frame piece and the 4-byte wire trailer land on the context;
  the CALLER compares (a mismatched unroll earns a benign 'corrupt'
  reply, not a drop). The trailer read happens inside the in_frame
  window — a peer stalling mid-trailer is still a mid-frame stall."""
  header = _recv_exact(sock, _LEN.size, liveness)
  if header is None:
    return None
  # The discard ledger resets the moment a new header lands — BEFORE
  # the length sanity check below can raise, or an oversized-length
  # quarantine would charge the PREVIOUS (successfully committed)
  # frame's byte count to the discard accounting.
  if liveness is not None:
    liveness.frame_bytes = _LEN.size
  (length,) = _LEN.unpack(header)
  if length > _MAX_MSG:
    raise ValueError(f'message length {length} exceeds bound')
  if crc_ctx is not None:
    crc_ctx.reset()
  if liveness is not None:
    # The frame has begun: from here to return, peer silence past the
    # stall window is a half-open MID-frame stall — the flag spans
    # every sub-frame read, so the deadline cannot reset at
    # _recv_exact boundaries.
    liveness.in_frame = True
  try:
    msg = _recv_msg_body(sock, length, liveness, crc_ctx)
    if crc_ctx is not None:
      trailer = _recv_exact(sock, _CRC.size, liveness)
      if trailer is None:
        raise ConnectionError('EOF mid-message (CRC trailer)')
      crc_ctx.wire = _CRC.unpack(trailer)[0]
    return msg
  finally:
    if liveness is not None:
      liveness.in_frame = False


def _recv_msg_body(sock: socket.socket, length: int, liveness,
                   crc_ctx=None):
  def feed(data):
    if crc_ctx is not None:
      crc_ctx.update(data)
    return data

  tag = _recv_exact(sock, 1, liveness)
  if tag is None:
    raise ConnectionError('EOF mid-message')
  feed(tag)
  kind = tag[0]
  if kind == _FRAME_PLAIN:
    payload = _recv_exact(sock, length - 1, liveness)
    if payload is None:
      raise ConnectionError('EOF mid-message')
    return pickle.loads(memoryview(feed(payload)))
  if kind == _FRAME_OOB:
    head_len = _OOB_META.size
    head = _recv_exact(sock, head_len, liveness)
    if head is None:
      raise ConnectionError('EOF mid-message')
    nbufs, skel_len = _OOB_META.unpack(feed(head))
    # Bound the header-derived sizes by the ALREADY-validated frame
    # length BEFORE allocating or recv'ing anything sized by them: a
    # corrupt peer can put 2^32-1 in either meta field independently
    # of `length`, and the consistency check below runs too late to
    # stop a ~38 GB table allocation.
    if 1 + head_len + skel_len + _OOB_BUFLEN.size * nbufs > length:
      raise ValueError(
          f'OOB header inconsistent with frame length {length}: '
          f'{nbufs} buffers, skeleton {skel_len}')
    table = _recv_exact(sock, skel_len + _OOB_BUFLEN.size * nbufs,
                        liveness)
    if table is None:
      raise ConnectionError('EOF mid-message')
    view = memoryview(feed(table))
    skeleton = view[:skel_len]
    sizes = [_OOB_BUFLEN.unpack_from(view,
                                     skel_len + _OOB_BUFLEN.size * i)[0]
             for i in range(nbufs)]
    consumed = (1 + head_len + len(table) + sum(sizes))
    if consumed != length:
      raise ValueError(
          f'OOB frame length mismatch: parsed {consumed} of {length}')
    buffers = []
    for size in sizes:
      buf = memoryview(np.empty(int(size), np.uint8))
      if _recv_into(sock, buf, int(size), liveness) < size:
        raise ConnectionError('EOF mid-message')
      buffers.append(feed(buf))
    return pickle.loads(skeleton, buffers=buffers)
  raise ValueError(f'unknown frame kind {kind}')


class LearnerShutdown(Exception):
  """The learner announced a CLEAN shutdown ('bye' frame): end of
  training, not a crash — actors must exit instead of reconnecting."""


class ContractMismatch(RuntimeError):
  """The learner rejected this actor host's handshake: the config/
  signature the actor offered does not match the learner's."""


class ProtocolError(RuntimeError):
  """The peer sent bytes this protocol version cannot parse — almost
  always a version-skewed peer (e.g. a pre-v4 role whose frames are
  untagged). Terminal: retrying against the same peer cannot succeed,
  so actors surface this instead of burning their reconnect window."""


class SessionEpochMismatch(ConnectionError):
  """The learner refused an unroll stamped with a FOREIGN session
  epoch ('stale_epoch' reply): this client's handshake belongs to a
  learner incarnation that no longer exists. A ConnectionError on
  purpose — the reconnect path (full re-handshake, fresh epoch +
  params) is exactly the right response."""


class UnrollCorrupt(RuntimeError):
  """The learner's v7 CRC check refused this unroll ('corrupt' reply):
  the bytes that arrived are not the bytes that were sent. The
  connection is FINE (the reply proves it) — the pump re-sends the
  same unroll once; a second refusal for the same unroll means the
  corruption is on this host's own path (NIC/RAM) and the host
  quarantines itself instead of feeding the learner garbage."""

  def __init__(self, message: str, crc: Optional[int] = None):
    super().__init__(message)
    self.crc = crc


class CrcProbation:
  """Client-side CRC self-quarantine ladder, with a probation rung
  (round 15). PR 9 made a double CRC refusal of the same unroll
  terminal — the host took itself out of the fleet for good, so the
  controller's grow-fleet move had nothing to reclaim on the remote
  side. The rehabilitation path mirrors the fleet-slot probation:

    refusal #1 of an unroll  -> RESEND (wire noise; at-least-once)
    refusal #2 (same unroll) -> PROBE, once per run: cool down
                                `cooldown_secs`, then re-send the
                                SAME unroll as a single probe
    probe refused (or a later unroll double-refused after the
    probation was spent)     -> QUARANTINE (terminal, as before)
    probe acked              -> recovered; the host keeps feeding

  Pure decision state (no I/O) so the ladder is unit-testable; the
  pump owns the sleep and the sends. Counters feed the
  INTEGRITY_REPORT line chaos.py and operators grep."""

  RESEND = 'resend'
  PROBE = 'probe'
  QUARANTINE = 'quarantine'

  def __init__(self, cooldown_secs: float = 30.0):
    self.cooldown_secs = max(float(cooldown_secs), 0.0)
    self.crc_resends = 0
    self.probations = 0
    self.recoveries = 0
    self._probation_used = False
    self._probe_pending = False
    self._resent = False  # current unroll already re-sent once?

  def next_unroll(self):
    """A new unroll is being sent: the per-unroll resend budget
    resets (the probation budget is per-RUN and does not)."""
    self._resent = False

  def on_refusal(self) -> str:
    """The learner's CRC refused the current unroll — what now?"""
    if not self._resent:
      self._resent = True
      self.crc_resends += 1
      return self.RESEND
    if self._probe_pending or self._probation_used:
      self._probe_pending = False  # the probe chapter is closed
      return self.QUARANTINE
    self._probation_used = True
    self._probe_pending = True
    self.probations += 1
    return self.PROBE

  def on_ack(self) -> bool:
    """An unroll was accepted; True when it was the probation probe
    (the host just recovered instead of quarantining)."""
    if self._probe_pending:
      self._probe_pending = False
      self.recoveries += 1
      return True
    return False


class ParamsCorrupt(RuntimeError):
  """A fetched param snapshot failed its content digest: the blob the
  learner published is not the tree the learner digested at publish
  time (host-memory rot between device_get and serialization — the
  frame CRC is self-consistent, only the digest can see this). The
  snapshot must NOT be installed; the caller keeps its current params
  and refetches on backoff (a corrupt blob stays corrupt until the
  next publish)."""

  def __init__(self, message: str, version: Optional[int] = None):
    super().__init__(message)
    self.version = version


class Backoff:
  """Capped exponential backoff with FULL jitter for retry loops.

  The fixed `time.sleep(0.3)` the connect/reconnect loops used to run
  meant a learner restart got the whole actor fleet back in lockstep:
  every host lost its connection at the same instant, so every host
  retried at the same instant, forever 0.3 s apart — a thundering herd
  against a listener with a finite accept backlog. Full jitter
  (delay ~ U[0, min(cap, base·2^attempt)]) decorrelates the fleet
  while still backing off a learner that stays down.

  The client loops construct a FRESH Backoff per incident (each
  connect/reconnect window starts from the fast end by construction);
  `reset()` exists for callers that hold one instance across
  incidents. `rng` is injectable for deterministic tests.
  """

  def __init__(self, base: float = 0.2, cap: float = 5.0, rng=None):
    if base <= 0 or cap <= 0:
      raise ValueError('base and cap must be > 0')
    self._base = base
    self._cap = cap
    self._rng = rng if rng is not None else random
    self._attempt = 0

  @property
  def attempt(self) -> int:
    return self._attempt

  def next_delay(self) -> float:
    ceiling = min(self._cap, self._base * (2 ** self._attempt))
    # Attempts stop growing once the cap is the binding term (2^n
    # would overflow floats long before a long outage ends).
    if self._base * (2 ** self._attempt) < self._cap:
      self._attempt += 1
    return self._rng.uniform(0.0, ceiling)

  def sleep(self) -> float:
    delay = self.next_delay()
    time.sleep(delay)
    return delay

  def reset(self) -> None:
    self._attempt = 0


# Bumped whenever the wire format or the handshake contract changes.
# v3: fields gained num_levels (level-id range validation) and the
# contract gained signature_tree (server-side fast-path validation).
# v4: tagged frames — unrolls ship as pickle-5 skeleton + out-of-band
# raw buffers instead of one inline pickle (~530 µs/unroll of pure
# copying removed from the hot ingest path).
# v5: the param lane — clients fetch weight snapshots over a SECOND
# connection opened with 'hello_params' (served by the chunked
# non-blocking publisher, isolating blob traffic from unroll acks);
# 'get_params' on the trajectory lane stays answered for the
# handshake and protocol-level tests.
# v5 extension (round 9, no version bump — compatible both ways):
# 'unroll' frames MAY carry a third element, the params version the
# client currently acts with; servers running a staleness window
# (--max_unroll_staleness) answer too-stale unrolls with a benign
# ('stale', current_version) reply instead of an ack. Old servers
# ignore the extra element; old clients read 'stale' as an ack whose
# version triggers exactly the refetch the reply intends.
# v6 (round 11): connection liveness + the hard-crash restart story,
# v5-COMPATIBLE both ways (the handshake accepts any protocol in
# _COMPATIBLE_PROTOCOLS and negotiates the new machinery OFF for v5
# peers — the same extension pattern as the round-9 staleness field):
#   - params replies carry a 4th element, the server-info dict
#     {'protocol', 'session_epoch', 'heartbeat_secs',
#     'idle_timeout_secs'} (old clients index [0..2] and never see
#     it); 'hello' MAY carry a 3rd element, the client-info dict
#     {'epoch': last-known session epoch} — a restarted learner tells
#     REATTACHING clients (prior epoch != current) from fresh ones and
#     records the fleet re-attach latency.
#   - 'ping' on either lane answers ('pong', current_version) — the
#     application-level heartbeat idle clients send so the server's
#     idle reaper can tell live-but-quiet from half-open/dead (and an
#     idle fleet still learns about new publishes from the pong).
#   - ('busy',) keepalives: while an ack is held back by buffer
#     backpressure the server emits 'busy' at the heartbeat cadence to
#     v6 peers — a slow learner stays tellable from a dead one, so the
#     client's I/O deadline can be tight without breaking the
#     backpressure contract. v6 clients skip them; v5 peers never get
#     them.
#   - 'unroll' frames MAY carry a 4th element, the session epoch the
#     client handshook under; a server seeing a FOREIGN epoch refuses
#     with ('stale_epoch', current_epoch) — the client re-handshakes.
#     Structurally unreachable over plain TCP (the connection dies
#     with the learner process), but it makes "zero stale-epoch
#     unrolls accepted across a restart" an asserted invariant instead
#     of an assumption (chaos.py run_partition_storm).
# v7 (round 12): end-to-end payload integrity, v5/v6-COMPATIBLE both
# ways (the same negotiation pattern — every v7 mechanism turns OFF
# per connection for older peers):
#   - the client-info dict MAY carry {'crc': True, 'crc_algo': <name>}
#     in the hello; a v7 server running wire_crc answers with
#     {'crc': True, 'crc_algo': ...} in its server-info — from the
#     NEXT frame on, every frame BOTH ways on that connection carries
#     a 4-byte CRC32C trailer after the payload (the length prefix
#     still counts tag+payload only). Algorithms must MATCH (a host
#     without the crc32c extension falls back to zlib-crc32;
#     cross-algorithm pairs negotiate the check off instead of
#     reporting phantom corruption).
#   - an unroll whose trailer does not match earns ('corrupt',
#     computed_crc) — verified by the ingest worker BEFORE the buffer
#     put, counted in stats()['wire_crc_rejected'], connection kept.
#     The client re-sends the unroll ONCE; a second corrupt reply for
#     the same unroll means the damage is on THIS host's path (NIC/
#     RAM) and the client quarantines itself (docs/RUNBOOK.md §9).
#   - params replies' server-info carries 'params_digest' — a content
#     CRC of the (wire-form) snapshot computed at publish time. The
#     client verifies it BEFORE update_params installs anything; a
#     mismatch (corruption upstream of frame serialization, where the
#     frame CRC is self-consistent) rejects the install without a
#     version bump, and the client's next 'get_params' carries a
#     {'digest_rejected': version} notice so the learner's
#     publish_digest_rejected counter sees the fleet-side refusal.
#   - 'hello_params' MAY carry the same client-info dict; the param
#     lane then appends the cached trailer to its blob replies and
#     verifies trailers on requests.
# v8 (round 13): per-unroll trace spans, v5/v6/v7-COMPATIBLE both
# ways (the same negotiation pattern — everything turns OFF per
# connection for older peers):
#   - the server-info dict carries 'trace' (a server-wide fact: the
#     learner runs a telemetry tracer); a v8 client seeing it stamps
#     each unroll frame with a 5th element — the compact trace
#     context (telemetry.make_trace: actor id, unroll seq, session
#     epoch, behaviour params version, [hop, wall_time] stamps). Old
#     servers never index it; old clients never send it.
#   - the trace context MAY carry 'pi' = [version, wall_time], the
#     client's most recent params-install event — how the
#     publish→installed-at-actor hop reaches the learner's
#     traces.jsonl without a dedicated side channel (the same
#     piggyback pattern as the v7 digest_rejected notice).
#   - 'stats' on the trajectory lane answers ('stats', {...}) — the
#     on-demand fleet telemetry request: the learner's unified
#     metrics-registry snapshot plus its ingest stats, served over
#     the existing control lane so operators (and tests) can read the
#     single source of truth remotely.
# v9 (round 20): elastic pod membership, v5..v8-COMPATIBLE both ways:
#   - the 'hello' client-info dict MAY carry 'host' — a stable host
#     identity string. The server keys its membership ledger on it:
#     a hello for an unknown host records a host_joined event, and
#     the connection's unwind records host_left with the reason
#     (drain/reaped/lost). Old servers ignore the extra key; old
#     clients simply never appear in the ledger (membership events
#     degrade to nothing, exactly like heartbeats on a v5 peer).
#   - 'leave' on the trajectory lane announces a DELIBERATE exit
#     (SIGTERM drain): ('leave', info) → ('bye_ack',). The server
#     marks the connection draining so its unwind records
#     host_left(reason='drain') instead of 'lost'. Old servers answer
#     ('error', unknown kind) — the draining client tolerates that
#     and closes anyway (the exit is best-effort-announced, never
#     gated on the server's vintage).
# v10 (round 21): multi-tenant serving plane, v5..v9-COMPATIBLE both
# ways (the same negotiation pattern — everything turns OFF per
# connection for older peers):
#   - blob kind 'params_int8': with --publish_codec=int8 the param
#     lane serves absmax-quantized snapshots (runtime/codec.py
#     Int8Leaf trees — ~4x smaller than f32 on the wire; the v7
#     params_digest covers the WIRE form, q and scales). Negotiated
#     PER SUBSCRIBER: 'hello_params' client-info now always carries
#     'protocol', and a v<=9 subscriber keeps receiving the cached
#     bf16 blob — both encodings are built once per publish, never
#     per subscriber.
#   - 'infer' on the trajectory lane: ('infer', payload) → ('infer_ok',
#     result, notice) serves one carry-passing inference batch from
#     the learner's resident version table (InferenceServer
#     .serve_remote — the TorchBeast decoupled-serving seam,
#     arXiv:1910.03552) when the learner attached a serving fn;
#     ('error', 'serving not attached') otherwise. The notice dict
#     carries {'draining': bool} so routers (runtime/routing.py)
#     drain a replica's share BEFORE the connection dies. Old servers
#     answer ('error', unknown kind) — the router treats that peer as
#     not routable, exactly like a v<=9 handshake.
PROTOCOL_VERSION = 10

# Handshakes accepted without negotiation failure: v5 peers get the
# round-9 wire exactly (no heartbeats, no busy keepalives, no epoch
# checks), v6 peers the round-11 wire (no CRC trailers, no digest
# checks), v7 peers the round-12 wire (no trace stamps), v8 peers the
# round-13 wire (no membership ledger entries), v9 peers the round-20
# wire (bf16 param blobs, no routed inference); everything else about
# the lanes is unchanged.
_COMPATIBLE_PROTOCOLS = (5, 6, 7, 8, 9, 10)

# Bound on the reader→worker handoff queue. The request→reply
# lockstep already implies at most one in-flight unroll per live
# connection, but that bound is a CLIENT property — a misbehaving
# peer pipelining unrolls without awaiting acks could otherwise grow
# the handoff queue without limit. A blocked reader is the correct
# backpressure: the peer's sendall stalls against the unread socket.
_INGEST_QUEUE_DEPTH = 256


def _is_signature_leaf(x) -> bool:
  """Leaves of a signature tree are (shape-tuple, dtype-name) pairs —
  they must stay leaves under tree_flatten, not flatten as tuples."""
  return (isinstance(x, tuple) and len(x) == 2
          and isinstance(x[1], str))


def trajectory_contract(config, agent, num_actions: int):
  """The wire contract both roles derive from their own config: the
  config fields the trajectory semantics depend on, plus the
  shape/dtype signature of one unroll.

  The reference's transport was graph-typed end to end — the shared
  FIFOQueue declares dtypes/shapes at construction (reference:
  experiment.py ≈L462–470 throwaway-graph spec capture) and py_process
  enforces `_tensor_specs`. This is that role for the TCP wire: the
  server compares the client's offered contract at `hello` and rejects
  mismatches naming the offending fields; each received unroll is then
  validated against the agreed signature before it can reach the
  buffer (VERDICT r2 Missing #2).

  `fields` carries semantic knobs even when they don't change shapes
  (`num_action_repeats` corrupts frame accounting silently; `torso` /
  `compute_dtype` make the served param snapshots unusable), so skew
  fails at connect instead of mid-training.
  """
  import jax
  from scalable_agent_tpu.envs import factory
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.structs import (
      ActorOutput, AgentOutput, StepOutput, StepOutputInfo)

  t1 = config.unroll_length + 1
  h, w = config.height, config.width

  def leaf(shape, dtype):
    return (tuple(int(s) for s in shape), np.dtype(dtype).name)

  # Core-state leaves come from the agent itself (the actor ships
  # `agent.initial_state(1)`-structured carries), heads are f32 by
  # the model contract (models/agent.py casts logits/baseline).
  state_sig = jax.tree_util.tree_map(
      lambda x: leaf(np.shape(x), np.asarray(jax.device_get(x)).dtype),
      agent.initial_state(1))
  example = ActorOutput(
      level_name=leaf((), np.int32),
      agent_state=state_sig,
      env_outputs=StepOutput(
          reward=leaf((t1,), np.float32),
          info=StepOutputInfo(
              episode_return=leaf((t1,), np.float32),
              episode_step=leaf((t1,), np.int32)),
          done=leaf((t1,), np.bool_),
          observation=(leaf((t1, h, w, 3), np.uint8),
                       leaf((t1, MAX_INSTRUCTION_LEN), np.int32))),
      agent_outputs=AgentOutput(
          action=leaf((t1,), np.int32),
          policy_logits=leaf((t1, int(num_actions)), np.float32),
          baseline=leaf((t1,), np.float32)))
  paths = jax.tree_util.tree_flatten_with_path(
      example, is_leaf=_is_signature_leaf)[0]
  signature = {jax.tree_util.keystr(p): v for p, v in paths}
  fields = {
      'env_backend': config.env_backend,
      # Level list must agree: unroll level ids index the learner's
      # list (and PopArt's per-task statistics) by position.
      'level_name': config.level_name,
      # Unroll level ids must index that list: an out-of-range id
      # crashes (or for negative ids silently ALIASES) the learner's
      # per-level episode stats and PopArt per-task statistics, so
      # each received unroll is range-checked against this.
      'num_levels': len(factory.level_names(config)),
      'height': int(config.height),
      'width': int(config.width),
      'unroll_length': int(config.unroll_length),
      'num_actions': int(num_actions),
      'num_action_repeats': int(config.num_action_repeats),
      'use_instruction': bool(config.resolved_use_instruction),
      'torso': config.torso,
      'compute_dtype': config.compute_dtype,
      # Shape-invisible but distribution/structure-changing knobs:
      # skew here silently shifts the data distribution (sticky
      # actions, fake-env episode length) or breaks the actor's use
      # of fetched params far from the cause (popart/pixel-control
      # change the param tree).
      'sticky_action_prob': float(config.sticky_action_prob),
      'episode_length': int(config.episode_length),
      'use_popart': bool(config.use_popart),
      'pixel_control_cost': float(config.pixel_control_cost),
  }
  # signature_tree carries the SAME leaves as `signature` but in pytree
  # form: the server flattens it once per connection into a
  # (treedef, flat leaves) pair so per-unroll validation compares
  # leaf-by-leaf instead of re-deriving a keystr dict per unroll
  # (measured ~12% of ingest throughput, VERDICT r3 W4). The keystr
  # dict stays the wire-compared form (order-insensitive, and its keys
  # name offending leaves in mismatch messages).
  return {'protocol': PROTOCOL_VERSION, 'fields': fields,
          'signature': signature, 'signature_tree': example}


def contract_mismatch_message(expected, offered) -> Optional[str]:
  """Human-readable diff of two contracts, or None when they agree.
  Names every offending field/leaf (the whole point — the raw
  failure used to surface nowhere near the offending host)."""
  if offered is None:
    return ('actor sent a legacy hello with no contract (protocol < '
            f'{PROTOCOL_VERSION}); upgrade the actor host')
  problems = []
  # v6 is v5-compatible: a peer offering any protocol in the
  # compatible set handshakes fine (the v6-only machinery — heartbeat
  # pings, busy keepalives, epoch stamps — negotiates OFF per
  # connection for v5 peers); anything else is a true skew.
  offered_protocol = offered.get('protocol')
  if (offered_protocol != expected['protocol']
      and offered_protocol not in _COMPATIBLE_PROTOCOLS):
    problems.append(f"protocol: learner={expected['protocol']} "
                    f"actor={offered_protocol}")
  for key in sorted(set(expected['fields']) |
                    set(offered.get('fields', {}))):
    e = expected['fields'].get(key, '<missing>')
    o = offered.get('fields', {}).get(key, '<missing>')
    if e != o:
      problems.append(f'config.{key}: learner={e!r} actor={o!r}')
  exp_sig = expected['signature']
  off_sig = offered.get('signature', {})
  for key in sorted(set(exp_sig) | set(off_sig)):
    e, o = exp_sig.get(key), off_sig.get(key)
    if e != o:
      problems.append(f'unroll{key}: learner={e} actor={o}')
  if not problems:
    return None
  return ('config/signature mismatch between learner and actor host: '
          + '; '.join(problems))


def _value_violations(unroll, fields) -> List[str]:
  """Range checks on a structurally valid unroll: values a corrupt
  actor could ship that blow up (actions — driver.py's bincount) or
  silently corrupt (level ids — per-level episode stats and PopArt
  per-task statistics index the learner's level list by position;
  negative ids ALIAS another level's slot) the learner's stats path."""
  problems = []
  num_actions = fields['num_actions']
  actions = np.asarray(unroll.agent_outputs.action)
  if actions.size and (actions.min() < 0 or
                       actions.max() >= num_actions):
    problems.append(
        f'actions out of range [0, {num_actions}): '
        f'min={actions.min()} max={actions.max()}')
  num_levels = fields.get('num_levels')
  if num_levels is not None:
    level = int(np.asarray(unroll.level_name))
    if not 0 <= level < num_levels:
      problems.append(
          f'level_name {level} out of range [0, {num_levels})')
  return problems


def unroll_violations(unroll, contract) -> List[str]:
  """Validate one received unroll's leaves against the agreed
  signature (+ action/level ranges, so a corrupt actor cannot blow up
  or alias the learner's stats path). Returns problems ([] = clean).

  This is the slow, leaf-NAMING path (keystr diff); the server's hot
  loop runs `FastUnrollValidator` and only falls back here to produce
  the error message once something already failed."""
  import jax
  signature = contract['signature']
  try:
    paths = jax.tree_util.tree_flatten_with_path(unroll)[0]
    got = {jax.tree_util.keystr(p): (tuple(np.shape(x)),
                                     np.asarray(x).dtype.name)
           for p, x in paths}
  except Exception as e:  # not even a pytree of arrays
    return [f'unroll is not a valid trajectory pytree: {e!r}']
  problems = []
  for key in sorted(set(signature) | set(got)):
    e, o = signature.get(key), got.get(key)
    if e is None:
      problems.append(f'unexpected leaf unroll{key}={o}')
    elif o is None:
      problems.append(f'missing leaf unroll{key} (expected {e})')
    elif e != o:
      problems.append(f'unroll{key}: expected {e}, got {o}')
  if not problems:
    problems = _value_violations(unroll, contract['fields'])
  return problems


class FastUnrollValidator:
  """Per-connection precompiled validation (VERDICT r3 W4).

  The expected signature is static per connection, so the treedef and
  the flat (shape, dtype-name) list are computed ONCE here; each unroll
  then costs one `tree_flatten` + a leaf-by-leaf compare instead of
  `tree_flatten_with_path` + keystr + dict building per unroll
  (measured ~12% of ingest throughput). Any failure falls back to
  `unroll_violations` for the leaf-naming diff — the slow path only
  runs when an error message is about to be produced anyway.

  Contracts from protocol < 3 peers lack `signature_tree`; the
  validator then just delegates to the slow path (correctness first)."""

  def __init__(self, contract):
    import jax
    self._contract = contract
    self._fast = None
    tree = contract.get('signature_tree')
    if tree is not None:
      leaves, treedef = jax.tree_util.tree_flatten(
          tree, is_leaf=_is_signature_leaf)
      self._fast = (treedef, leaves)

  def __call__(self, unroll) -> List[str]:
    if self._fast is None:
      return unroll_violations(unroll, self._contract)
    import jax
    treedef, expected = self._fast
    try:
      leaves, got_def = jax.tree_util.tree_flatten(unroll)
      if got_def == treedef:
        for (eshape, edtype), x in zip(expected, leaves):
          if (np.shape(x) != eshape
              or np.asarray(x).dtype.name != edtype):
            break
        else:
          return _value_violations(unroll, self._contract['fields'])
    except Exception:
      pass  # fall through: the slow path names the problem
    return unroll_violations(unroll, self._contract)


class _Conn:
  """One actor connection: socket + send lock (worker threads and
  close()'s 'bye' frame must not interleave writes mid-message).

  Liveness fields (round 11): `last_recv` is the reaper's idle clock
  (refreshed on EVERY received byte, so a trickling half-open peer is
  distinguishable from a live slow one); `protocol`/`heartbeat` are
  negotiated at hello (v5 peers get no busy keepalives and no
  heartbeat-miss accounting); `reaped` marks a reaper-initiated close
  so the reader's unwind logs/counts it once. When `send_stall_secs`
  is set (liveness mode — the socket runs short poll timeouts), every
  send path is progress-bounded: a non-reading peer aborts the send
  with `_SendStall` instead of wedging the sending thread."""

  # Lock discipline (round 18, guarded-by lint): the in-flight count
  # is the only _Conn field shared between the reader, the worker
  # pool, and the reaper; send_lock serializes writers on the socket.
  inflight: guarded_by('inflight_lock')

  def __init__(self, sock: socket.socket, addr=None,
               send_stall_secs: Optional[float] = None,
               base_timeout: Optional[float] = None):
    self.sock = sock
    self.addr = addr
    self.send_lock = make_lock('remote._Conn.send_lock')
    self.send_stall_secs = send_stall_secs
    # The socket timeout try_send must RESTORE (None = blocking legacy
    # mode; the reader's poll interval in liveness mode — restoring
    # None there would silently turn the reader's bounded recv
    # back into an unbounded one).
    self.base_timeout = base_timeout
    # Per-connection ingest ledger (observability: the driver reports
    # unrolls/sec per connection from deltas of these; stale
    # rejections are counted per connection so one starved/lagging
    # host is tellable from a uniformly stale fleet).
    self.unrolls = 0
    self.stale_rejected = 0
    # Liveness state.
    self.last_recv = time.monotonic()
    self.protocol = 5          # until a hello says otherwise
    self.heartbeat = False     # negotiated: v6 peer + server heartbeat
    self.hb_missed = False     # current silence window already counted
    self.reaped = False        # reaper-initiated close in progress
    # v7 payload integrity, negotiated at hello: when True, every
    # frame BOTH ways on this connection carries the CRC32C trailer
    # (the hello reply itself is pre-negotiation and ships per the
    # conn's PRIOR state, so a re-handshake stays parseable).
    self.crc = False
    self.crc_rejected = 0      # unrolls refused with ('corrupt', crc)
    # v9 elastic membership: the host identity the hello's client-info
    # carried (None for pre-v9 peers — they never enter the ledger),
    # and whether a 'leave' announced a deliberate drain (the unwind
    # then records host_left(reason='drain') instead of 'lost').
    self.host_id = None
    self.draining = False
    # Unrolls handed to the worker pool whose ack has not gone out
    # yet. A LOCKSTEP client is silent BY PROTOCOL while its unroll is
    # in flight (it may be parked for minutes behind buffer
    # backpressure) — the reaper and the heartbeat-miss counter must
    # exempt such conns or they would reap/flag protocol-obedient
    # peers exactly when the learner is slowest.
    self.inflight = 0
    self.inflight_lock = make_lock('remote._Conn.inflight_lock')

  def job_started(self):
    with self.inflight_lock:
      self.inflight += 1

  def job_finished(self):
    with self.inflight_lock:
      self.inflight -= 1

  def is_waiting_on_us(self) -> bool:
    with self.inflight_lock:
      return self.inflight > 0

  def _write(self, data) -> None:
    """One bounded-or-legacy write; callers hold send_lock."""
    if self.send_stall_secs is not None:
      _sendall_bounded(self.sock, data, self.send_stall_secs)
    else:
      self.sock.sendall(data)

  def send(self, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    with self.send_lock:
      self._write(_plain_frame(payload, crc=self.crc))

  def send_bytes(self, payload: bytes) -> None:
    """Ship pre-serialized bytes (a cached plain frame): handler
    threads must not re-pickle the whole tree per request."""
    with self.send_lock:
      self._write(_plain_frame(payload, crc=self.crc))

  def send_segments(self, segments,
                    trailer: Optional[bytes] = None) -> None:
    """Ship a pre-built wire frame as its segments (the cached param
    snapshot frame: head + raw buffers) without joining them into one
    giant bytes object first. `trailer`: the frame's cached CRC bytes
    — passed ONLY when this send should carry one (the caller knows
    whether the peer expects v7 trailers on this frame)."""
    with self.send_lock:
      for seg in segments:
        self._write(seg)
      if trailer is not None:
        self._write(trailer)

  def send_oob(self, obj) -> None:
    """Ship `obj` as an out-of-band frame (pickle-5 skeleton + raw
    array buffers — arrays never pass through the pickler): the v10
    routed-inference reply path, whose payload is batch arrays. The
    trailer rides only when this conn negotiated v7 CRC, mirroring
    the cached-blob convention (_make_blob)."""
    segments = _oob_frame_segments(obj)
    trailer = (_CRC.pack(_segments_crc(segments))
               if self.crc else None)
    self.send_segments(segments, trailer)

  def try_send(self, obj, timeout: float = 2.0) -> bool:
    """Bounded best-effort send: never blocks shutdown behind a stuck
    peer (a handler mid-sendall of a large snapshot holds send_lock;
    a non-reading client stalls sendall itself)."""
    if not self.send_lock.acquire(timeout=timeout):
      return False
    try:
      self.sock.settimeout(timeout)
      _send_msg(self.sock, obj, crc=self.crc)
      return True
    except OSError:
      return False
    finally:
      try:
        self.sock.settimeout(self.base_timeout)
      except OSError:
        pass
      self.send_lock.release()


class _ParamLane:
  """The weight fan-out plane: every `hello_params` subscriber socket,
  multiplexed by ONE selector thread with chunked non-blocking sends.

  Why not a thread per subscriber (the r5 design): 8 polling fetchers
  measured the unroll pump at 29.9 unrolls/s against 838.6 alone (ack
  p99 1.18 → 95.8 ms) — each fetch handler monopolizes the core in
  blob-sized `sendall` slices and the tiny acks queue behind up to 8
  of them. Here each ready subscriber advances at most `chunk_bytes`
  per poll round, so the blob plane is one runnable thread with
  bounded GIL holds no matter how many hosts subscribe, and the
  trajectory lane's acks never wait behind a blob mid-send.

  Requests are tiny (`get_params` frames); replies are the server's
  cached per-version blob — the lane never pickles, it only slices
  memoryviews of bytes the publisher already built.
  """

  def __init__(self, blob_fn, chunk_bytes: int = 128 * 1024,
               idle_timeout_secs: float = 0.0,
               watchdog: Optional[ThreadWatchdog] = None):
    # (subscriber protocol) -> (cached frame segments, trailer): the
    # v10 codec negotiation — an int8 publisher still hands v<=9
    # subscribers the cached bf16 blob.
    self._blob_fn = blob_fn
    self._chunk = chunk_bytes
    self._idle_timeout = float(idle_timeout_secs)
    self._watchdog = watchdog
    self._selector = selectors.DefaultSelector()
    self._lock = make_lock('remote._ParamLane._lock')  # adopt vs close
    self._closed = False
    self._blobs_served = 0
    self._bytes_sent = 0
    # Fan-out shrinkage ledger (round 11): EVERY dropped subscriber is
    # counted — a param lane that quietly loses hosts used to be
    # invisible until the fleet's params went uniformly stale.
    self._subs_dropped = 0
    self._subs_reaped = 0   # the idle/half-open subset of the drops
    # Integrity ledger (round 12): digest-rejected notices subscribers
    # attach to their retry fetches — the learner-side visibility of
    # "a corrupt publish was refused fleet-wide" — and requests whose
    # own v7 trailer failed (a corrupting subscriber loses its sub).
    self._digest_rejected = 0
    self._req_crc_dropped = 0
    # Self-pipe: adopt()/close() must wake a parked select().
    self._wake_r, self._wake_w = socket.socketpair()
    self._wake_r.setblocking(False)
    self._selector.register(self._wake_r, selectors.EVENT_READ, None)
    self._pending_adopts: List[socket.socket] = []
    self._thread = threading.Thread(target=self._loop,
                                    name='param-lane', daemon=True)
    self._thread.start()

  class _Sub:
    """Per-subscriber state: request parse buffer + outgoing chunks."""

    def __init__(self, sock, crc: bool = False, proto: int = 5):
      self.sock = sock
      self.crc = crc  # v7: trailers on replies, verified on requests
      self.proto = proto  # v10: which cached blob encoding it gets
      self.rbuf = bytearray()
      self.out: List[memoryview] = []  # remaining reply bytes
      self.last_recv = time.monotonic()  # idle-reaping clock

  def adopt(self, sock: socket.socket, crc: bool = False,
            proto: int = 5) -> bool:
    """Hand a connected socket to the lane (called from the accept
    handler once the peer said 'hello_params'). False if closing.
    `crc`: the hello_params negotiation — this subscriber's replies
    carry the blob's cached v7 trailer and its requests are
    trailer-verified. `proto`: the subscriber's offered protocol —
    selects which cached blob encoding it fetches (v10: int8)."""
    with self._lock:
      if self._closed:
        return False
      self._pending_adopts.append((sock, crc, proto))
    try:
      self._wake_w.send(b'x')
    except OSError:
      pass
    return True

  def stats(self):
    with self._lock:
      return {'blobs': self._blobs_served, 'bytes': self._bytes_sent,
              'subs_dropped': self._subs_dropped,
              'subs_reaped': self._subs_reaped,
              'digest_rejected': self._digest_rejected,
              'req_crc_dropped': self._req_crc_dropped}

  def _drop(self, sub, reaped: bool = False):
    with self._lock:
      self._subs_dropped += 1
      if reaped:
        self._subs_reaped += 1
    try:
      self._selector.unregister(sub.sock)
    except (KeyError, ValueError):
      pass
    sub.sock.close()

  def _queue_segments(self, sub, segments):
    """Queue a pre-built wire frame (its segments verbatim)."""
    sub.out.extend(memoryview(s) for s in segments)
    self._selector.modify(sub.sock,
                          selectors.EVENT_READ | selectors.EVENT_WRITE,
                          sub)

  def _queue_reply(self, sub, payload: bytes):
    header = _LEN.pack(len(payload) + 1) + bytes((_FRAME_PLAIN,))
    if sub.crc:
      self._queue_segments(sub, (header, payload, _CRC.pack(
          integrity.crc_bytes(payload, integrity.crc_bytes(
              bytes((_FRAME_PLAIN,)))))))
    else:
      self._queue_segments(sub, (header, payload))

  def _on_readable(self, sub) -> bool:
    """Drain request bytes; False = connection is gone."""
    try:
      data = sub.sock.recv(4096)
    except BlockingIOError:
      return True
    except OSError:
      return False
    if not data:
      return False
    sub.last_recv = time.monotonic()
    sub.rbuf += data
    while True:
      if len(sub.rbuf) < _LEN.size:
        return True
      (length,) = _LEN.unpack_from(sub.rbuf)
      if length > 1 << 20:  # param requests are tiny frames
        log.warning('param lane: oversized request frame (%d bytes); '
                    'dropping subscriber', length)
        return False
      # v7 subscribers append a 4-byte CRC trailer to every request.
      want = _LEN.size + length + (_CRC.size if sub.crc else 0)
      if len(sub.rbuf) < want:
        return True
      frame = bytes(sub.rbuf[_LEN.size:_LEN.size + length])
      if sub.crc:
        (wire_crc,) = _CRC.unpack_from(sub.rbuf, _LEN.size + length)
        if wire_crc != integrity.crc_bytes(frame):
          # A request this tiny failing its CRC means the subscriber's
          # send path corrupts — nothing it asks for can be trusted.
          with self._lock:
            self._req_crc_dropped += 1
          log.warning('param lane: request failed its CRC trailer; '
                      'dropping subscriber')
          return False
      del sub.rbuf[:want]
      try:
        if frame[0] != _FRAME_PLAIN:
          raise ValueError(f'unexpected frame kind {frame[0]}')
        msg = pickle.loads(frame[1:])
        kind = msg[0]
      except Exception as e:  # version-skewed peer: drop just it
        log.warning('param lane: unparseable request (%r); dropping '
                    'subscriber', e)
        return False
      if kind in ('get_params', 'hello_params', 'ping'):
        # hello_params may arrive here when the peer pipelined it with
        # its first fetch; it needs no reply of its own (but a v7 info
        # dict still upgrades the sub's CRC negotiation).
        if kind == 'hello_params' and len(msg) > 1 and \
            isinstance(msg[1], dict):
          sub.crc = bool(msg[1].get('crc')) and \
              msg[1].get('crc_algo') == integrity.CRC_ALGO
          sub.proto = int(msg[1].get('protocol') or sub.proto)
        if kind == 'get_params':
          # v7 retry fetches MAY carry a digest-rejected notice: the
          # subscriber refused to install version N because its
          # content digest failed — the learner-side ledger of a
          # corrupt publish being rejected fleet-wide.
          if len(msg) > 1 and isinstance(msg[1], dict) and \
              msg[1].get('digest_rejected') is not None:
            with self._lock:
              self._digest_rejected += 1
            log.error(
                'param lane: subscriber refused params v%s — content '
                'digest mismatch (corrupt publish); it keeps its '
                'prior snapshot and refetches on backoff',
                msg[1]['digest_rejected'])
          with self._lock:
            self._blobs_served += 1
          segments, trailer = self._blob_fn(sub.proto)
          self._queue_segments(
              sub, tuple(segments) + ((trailer,) if sub.crc else ()))
        elif kind == 'ping':
          # The v6 keepalive: an idle subscriber pings inside the
          # reaping window; the pong keeps the conversation protocol-
          # shaped (and last_recv above already refreshed the clock).
          self._queue_reply(sub, pickle.dumps(
              ('pong',), protocol=pickle.HIGHEST_PROTOCOL))
      else:
        self._queue_reply(sub, pickle.dumps(
            ('error', f'param lane only serves get_params, got '
             f'{kind!r}'), protocol=pickle.HIGHEST_PROTOCOL))
    return True

  def _on_writable(self, sub) -> bool:
    """Send at most one chunk; False = connection is gone."""
    while sub.out:
      view = sub.out[0]
      try:
        sent = sub.sock.send(view[:self._chunk])
      except BlockingIOError:
        return True
      except OSError:
        return False
      with self._lock:
        self._bytes_sent += sent
      if sent < len(view):
        sub.out[0] = view[sent:]
      else:
        sub.out.pop(0)
      # ONE bounded write per poll round: fairness across subscribers
      # and a bounded GIL hold are the whole point of the lane.
      return True
    self._selector.modify(sub.sock, selectors.EVENT_READ, sub)
    return True

  def _loop(self):
    try:
      self._loop_body()
    except Exception:
      # A dead lane must be loud: every subscriber would silently
      # hang on its next fetch otherwise.
      log.exception('param lane died; subscribers will see drops')

  def _loop_body(self):
    while True:
      if self._watchdog is not None:
        self._watchdog.beat('param-lane')
      with self._lock:
        if self._closed:
          return
        adopts, self._pending_adopts = self._pending_adopts, []
      for sock, crc, proto in adopts:
        sock.setblocking(False)
        try:
          self._selector.register(sock, selectors.EVENT_READ,
                                  self._Sub(sock, crc=crc, proto=proto))
        except (KeyError, ValueError, OSError):
          sock.close()
      # Idle/half-open subscriber reaping (round 11): a silent sub
      # past the window is dropped HERE, on the lane thread — selector
      # mutation must never race the select loop. A live v6 client
      # pings inside the window; a sub mid-reply (pending out) is
      # making progress on the write side and is left alone.
      if self._idle_timeout > 0:
        cutoff = time.monotonic() - self._idle_timeout
        stale = [key.data for key in self._selector.get_map().values()
                 if key.data is not None and not key.data.out
                 and key.data.last_recv < cutoff]
        for sub in stale:
          log.warning('param lane: reaping idle subscriber (silent '
                      'for > %.1fs)', self._idle_timeout)
          self._drop(sub, reaped=True)
      for key, events in self._selector.select(timeout=0.5):
        if key.data is None:  # wake pipe
          try:
            self._wake_r.recv(4096)
          except OSError:
            pass
          continue
        sub = key.data
        ok = True
        if events & selectors.EVENT_READ:
          ok = self._on_readable(sub)
        if ok and events & selectors.EVENT_WRITE:
          ok = self._on_writable(sub)
        if not ok:
          self._drop(sub)

  def close(self, graceful: bool = True) -> int:
    """Shut the lane down; returns the join-deadline-missed thread
    count (0 or 1 — the selector thread), which the owning server
    folds into its `unjoined_threads` stat instead of dropping.

    graceful=True answers every live subscriber with a ('bye',) frame
    before the close (best-effort, non-blocking — the sockets are
    already non-blocking): a subscriber parked in recv gets a clean
    LearnerShutdown instead of a raw EOF it must diagnose. Crash-path
    closes (graceful=False) skip it — actors must keep their reconnect
    window."""
    with self._lock:
      if self._closed:
        return 0
      self._closed = True
    try:
      self._wake_w.send(b'x')
    except OSError:
      pass
    self._thread.join(timeout=5.0)
    unjoined = 1 if self._thread.is_alive() else 0
    if unjoined:
      # The leaked thread still OWNS the selector and its sockets: a
      # teardown here would race its select loop (use-after-close on
      # the selector, corrupted mid-chunk replies). Leak the lot with
      # the thread — counted and named; the process is going away.
      log.warning('param lane close: selector thread missed the join '
                  'deadline and leaks as a daemon (selector/sockets '
                  'leaked with it)')
      return unjoined
    if graceful:
      bye = pickle.dumps(('bye',), protocol=pickle.HIGHEST_PROTOCOL)
      frame = (_LEN.pack(len(bye) + 1) + bytes((_FRAME_PLAIN,)) + bye)
      frame_crc = frame + _CRC.pack(
          integrity.crc_bytes(frame[_LEN.size:]))
      for key in list(self._selector.get_map().values()):
        # Only subscribers with NO partially-sent reply: appending the
        # bye where a client expects the rest of a chunked params
        # frame would corrupt the stream mid-message (that sub gets
        # the EOF path instead — indistinguishable from a crash, which
        # its half-fetched state already is).
        if key.data is not None and not key.data.out:
          try:
            # v7 subs expect a trailer on every frame, the bye too.
            key.fileobj.send(frame_crc if key.data.crc else frame)
          except OSError:
            pass
    for key in list(self._selector.get_map().values()):
      if key.data is not None:
        key.fileobj.close()
    self._selector.close()
    self._wake_r.close()
    self._wake_w.close()
    if self._watchdog is not None:
      self._watchdog.unregister('param-lane')
    return unjoined


class TrajectoryIngestServer:
  """Learner-side: accepts remote-actor connections, lands their
  unrolls in the shared TrajectoryBuffer, serves param snapshots.

  Args:
    buffer: the learner's TrajectoryBuffer (shared with the local
      fleet).
    params: initial host (numpy) param pytree; version 1.
    host/port: bind address; port 0 picks a free port (see `.port`).
      Loopback-only by default (the wire is unauthenticated pickle) —
      real actor-host topologies must opt in to a cluster-internal
      interface, mirroring config.remote_actor_bind_host.
    contract: `trajectory_contract(...)` of the learner's config.
      When given, clients must open with a matching `hello` before
      any unroll is accepted, and every received unroll is validated
      against the signature before it can reach the buffer. None
      disables both checks (protocol-level tests).
    wire_dtype: 'bfloat16' casts float32 leaves of each published
      snapshot for the wire (config.publish_codec resolves here — bf16
      is the production default; 'f32' opts out) — the blob kind
      becomes 'params_bf16' and RemoteActorClient upcasts on receipt,
      halving the egress term of the feed arithmetic (docs/PERF.md,
      docs/TRANSPORT.md). ''/None ships exact float32.
    ingest_workers: size of the validate/commit pool that drains the
      reader threads' handoff queue (validation + buffer.put + ack off
      the reader thread). 0 = auto (min(4, cpu count)). The handoff
      queue is bounded (`_INGEST_QUEUE_DEPTH`): well-behaved clients
      are request→reply lockstep (one in-flight unroll per live
      connection), and a misbehaving pipelined peer blocks its own
      reader instead of growing server memory.
    max_unroll_staleness: admit an unroll only when the client's
      params version is within this many published versions of the
      current one (0 = no window). Too-stale unrolls get a benign
      ('stale', current_version) reply — the client drops the unroll
      and refetches — counted per connection and in
      stats()['stale_rejected']. Off-policy V-trace tolerates bounded
      lag; this bounds it at the ADMISSION seam instead of letting a
      lagging host poison the batch mix (IMPACT's staleness window,
      arXiv:1912.00167, applied at ingest).
    heartbeat_secs: v6 connection-liveness cadence (round 11;
      config.remote_heartbeat_secs): v6 clients ping at this interval
      when idle, and ingest workers emit ('busy',) keepalives at this
      cadence to v6 peers while an ack is held back by buffer
      backpressure. 0 disables (v5 wire exactly).
    idle_timeout_secs: idle/half-open reaping window (round 11;
      config.remote_conn_idle_timeout_secs): a connection — either
      lane — that received NO bytes for this long is reaped
      (stats()['conns_reaped']), and it doubles as the mid-frame
      recv stall and send no-progress deadline on every blocking
      socket path. 0 disables reaping AND deadlines (pre-round-11
      behavior: a half-open peer pins its reader forever).
    wire_crc: v7 payload integrity (round 12; config.wire_crc): offer
      per-frame CRC32C trailers to v7 clients at hello. A mismatched
      unroll is refused with ('corrupt', crc) BEFORE the buffer put —
      counted in stats()['wire_crc_rejected'] — and the connection is
      kept (the client re-sends once, then quarantines itself). False
      negotiates every connection down to the v6 wire (the bench's
      CRC-off row, and the escape hatch for CPU-bound ingest hosts).
  """

  # Lock discipline (round 18, guarded-by lint). Three planes, three
  # locks, no nesting between them: the published snapshot + its
  # serialization clock under _params_lock, the connection/reattach
  # counters under _stats_lock, the live conn/thread lists under
  # _conns_lock. The registry counters (ingest/unrolls etc.) carry
  # their own per-counter locks and stay unannotated.
  _version: guarded_by('_params_lock')
  _blob_version: guarded_by('_params_lock')
  _params_frame: guarded_by('_params_lock')
  _params_frame_compat: guarded_by('_params_lock')
  _serving_fn: guarded_by('_params_lock')
  _draining: guarded_by('_params_lock')
  _serializations: guarded_by('_params_lock')
  _connections: guarded_by('_stats_lock')
  _param_subscribers: guarded_by('_stats_lock')
  _reattached: guarded_by('_stats_lock')
  _reconnected: guarded_by('_stats_lock')
  _reattach_latency: guarded_by('_stats_lock')
  _unjoined_threads: guarded_by('_stats_lock')
  _threads: guarded_by('_conns_lock')
  _conns: guarded_by('_conns_lock')
  _members: guarded_by('_conns_lock')
  _member_events: guarded_by('_conns_lock')

  def __init__(self, buffer, params, host: str = '127.0.0.1',
               port: int = 0, contract=None,
               wire_dtype: Optional[str] = None,
               ingest_workers: int = 0,
               max_unroll_staleness: int = 0,
               heartbeat_secs: float = 0.0,
               idle_timeout_secs: float = 0.0,
               wire_crc: bool = True,
               trace: bool = True):
    if wire_dtype not in (None, '', 'bfloat16', 'int8'):
      raise ValueError(f'unsupported wire_dtype {wire_dtype!r}')
    self._wire_bf16 = wire_dtype == 'bfloat16'
    # v10 int8 codec (round 21): the cached blob pair — int8 for v10
    # subscribers, bf16 for v<=9 (which cannot parse Int8Leaf trees
    # reliably across codec revisions and never negotiated the lossy
    # codec). Both built ONCE per publish.
    self._wire_int8 = wire_dtype == 'int8'
    self._wire_crc = bool(wire_crc)
    # v8 trace spans (round 13; config.telemetry_trace): advertised as
    # a server-wide fact in the hello reply's server-info — v8 clients
    # then stamp each unroll frame with its trace context, which the
    # reader/worker complete learner-side (telemetry.PipelineTracer).
    self._trace = bool(trace)
    self._buffer = buffer
    self._contract = contract
    self._max_staleness = int(max_unroll_staleness)
    self._validate = (FastUnrollValidator(contract)
                      if contract is not None else None)
    # --- Connection liveness (round 11). A per-run session epoch
    # rides every params reply: a restarted learner's epoch differs,
    # so reattaching clients are tellable from fresh ones (and from
    # clients of a DIFFERENT learner incarnation — the stale-epoch
    # unroll guard). Wall-clock microseconds + pid: unique across
    # restarts of the same port without any on-disk state.
    self.session_epoch = ((int(time.time() * 1e6) << 10)
                          ^ (os.getpid() & 0x3ff))
    self._t_start = time.monotonic()
    self._heartbeat_secs = float(heartbeat_secs)
    self._idle_timeout = float(idle_timeout_secs)
    self._liveness_on = (self._heartbeat_secs > 0
                         or self._idle_timeout > 0)
    # Mid-frame/send stall deadline: the idle window when set, else a
    # heartbeat-derived floor (a frame should never trickle longer
    # than a few missed heartbeats).
    self._stall_secs = (self._idle_timeout if self._idle_timeout > 0
                        else max(3 * self._heartbeat_secs, 10.0))
    # Reader/reaper poll interval: short enough that fast test windows
    # (idle 0.5 s) resolve, bounded below so we never spin.
    polls = [1.0]
    if self._idle_timeout > 0:
      polls.append(self._idle_timeout / 4)
    if self._heartbeat_secs > 0:
      polls.append(self._heartbeat_secs / 2)
    self._poll_secs = max(min(polls), 0.05)
    self._watchdog = ThreadWatchdog()
    self._params_lock = make_lock('remote.IngestServer._params_lock')
    self._version = 1
    self._blob_version = 1
    # One pickle per version (VERDICT r2 W2): handler threads send
    # these cached bytes instead of re-serializing the tree per
    # get_params — at the advertised 150+-actor-host topology every
    # version bump otherwise costs O(hosts × tree) pickles.
    self._serializations = 0
    self._params_frame = self._make_blob(self._version, params)
    self._params_frame_compat = (
        self._make_blob(self._version, params, compat=True)
        if self._wire_int8 else None)
    # Routed inference (v10): the learner attaches a serving fn
    # (InferenceServer.serve_remote) via attach_serving; 'infer'
    # requests answer ('error', ...) until then. set_draining flips
    # the notice routers drain on.
    self._serving_fn = None
    self._draining = False
    self._stats_lock = make_lock('remote.IngestServer._stats_lock')
    # Round 13: the scattered per-module ints moved into the unified
    # metrics registry (telemetry.Counter — each has its own lock;
    # cross-counter atomicity was never relied on). stats() keeps its
    # exact key surface by reading .value; the drain manifest, halt
    # bundle, flight recorder, and the remote 'stats' request read the
    # same objects through registry.snapshot().
    self._unrolls = telemetry.counter('ingest/unrolls')
    self._rejected = telemetry.counter('ingest/rejected')
    self._stale_rejected = telemetry.counter('ingest/stale_rejected')
    self._quarantined = telemetry.counter('ingest/quarantined')
    # Integrity ledger (round 12): unrolls refused because their v7
    # CRC trailer mismatched (verified before the put — the buffer
    # never saw them), and the discard accounting of thrown-away
    # partial/unparseable frames (the round-12 fix: the quarantine
    # path used to count the CONN but drop how much data died with
    # it).
    self._wire_crc_rejected = telemetry.counter(
        'ingest/wire_crc_rejected')
    self._discarded_frames = telemetry.counter(
        'ingest/discarded_frames')
    self._discarded_bytes = telemetry.counter(
        'ingest/discarded_bytes')
    self._connections = 0
    self._param_subscribers = 0  # cumulative hello_params adoptions
    # Liveness/restart counters (round 11).
    self._conns_reaped = telemetry.counter('ingest/conns_reaped')
    self._heartbeat_misses = telemetry.counter(
        'ingest/heartbeat_misses')
    self._stale_epoch_rejected = telemetry.counter(
        'ingest/stale_epoch_rejected')
    self._reattached = 0         # hellos carrying a FOREIGN prior epoch
    self._reconnected = 0        # hellos carrying OUR epoch (same run)
    self._reattach_latency = 0.0  # last reattach: secs since start
    self._unjoined_threads = 0   # close()-time join-deadline misses
    # Ack service-time percentiles read straight from the registry
    # histogram (round 13: telemetry.Histogram IS the
    # LatencyReservoir design promoted to a registry citizen — a
    # second reservoir would be the same samples bookkept twice).
    self._ack_hist = telemetry.histogram('ingest/ack_ms')
    self._closed = threading.Event()
    # Threads/conns are appended by the accept loop, pruned as peers
    # disconnect, snapshotted by close() — all under one lock (flapping
    # actor hosts over a long run must not accumulate dead entries).
    self._threads: List[threading.Thread] = []
    self._conns: List[_Conn] = []
    # Elastic membership ledger (round 20): host identity -> the conn
    # currently carrying it, plus the pending join/leave events the
    # driver drains into durable incidents. Keyed on the v9 hello's
    # 'host' string, so a RECONNECT of a known host (new conn, same
    # identity) is a non-event while a fresh host records host_joined
    # and a dead conn still owning its identity records host_left.
    self._members: Dict[str, _Conn] = {}
    self._member_events: List[Dict] = []
    self._hosts_joined = telemetry.counter('ingest/hosts_joined')
    self._hosts_left = telemetry.counter('ingest/hosts_left')
    self._conns_lock = make_lock('remote.IngestServer._conns_lock')
    # Trajectory-lane handoff: readers push (conn, unroll, t_recv,
    # client_version); the worker pool validates, commits
    # (backpressure lives in the blocking put) and acks. BOUNDED
    # (see _INGEST_QUEUE_DEPTH): a reader blocked in put is socket-
    # level backpressure on its peer, not unbounded server memory.
    self._ingest_q: 'queue.Queue' = queue.Queue(
        maxsize=_INGEST_QUEUE_DEPTH)
    if ingest_workers <= 0:
      ingest_workers = max(1, min(4, os.cpu_count() or 1))
    self._workers = [
        threading.Thread(target=self._ingest_worker,
                         args=(f'ingest-worker-{i}',),
                         name=f'ingest-worker-{i}', daemon=True)
        for i in range(ingest_workers)]
    for w in self._workers:
      w.start()
    self._param_lane = _ParamLane(self._snapshot_frame,
                                  idle_timeout_secs=self._idle_timeout,
                                  watchdog=self._watchdog)
    self._listener = socket.create_server((host, port))
    self.port = self._listener.getsockname()[1]
    self._accept_thread = threading.Thread(
        target=self._accept_loop, name='ingest-accept', daemon=True)
    self._accept_thread.start()
    # Idle/half-open reaper (round 11): the one thread that owns the
    # between-frames idle budget — it closes a silent peer's socket,
    # which wakes the blocked reader with an OSError and runs the
    # normal disconnect cleanup. Mid-frame stalls abort faster on the
    # reader itself (_ConnLiveness).
    self._reaper_thread = None
    if self._idle_timeout > 0:
      self._reaper_thread = threading.Thread(
          target=self._reap_loop, name='ingest-reaper', daemon=True)
      self._reaper_thread.start()

  def _make_blob(self, version, params,
                 compat: bool = False) -> Tuple[List[bytes], bytes]:
    """One published version as (wire frame segments, CRC trailer):
    [head (length prefix + OOB tag + skeleton + buffer table), raw
    buffer, raw buffer, ...] plus the 4 trailer bytes v7 subscribers
    get appended (cached WITH the blob — one CRC per publish, not per
    fetch).

    Out-of-band framing in the params direction too (round 6 — the
    same lesson the r4 unroll framing measured at +90%): the frame IS
    the arrays, so neither the server (per send) nor the client (per
    fetch) copies them through the pickler — the client's
    `_recv_msg` reconstructs zero-copy views, which matters doubly on
    the param lane where 8 polling fetchers' unpickles used to share
    the core with the unroll pump's acks.

    Integrity (round 12): the info dict carries 'params_digest' — a
    content CRC of the WIRE-form tree (post-bf16-cast, pre-upcast:
    the client verifies the exact bytes it received) computed HERE,
    at publish time, before serialization. The 'publish_corrupt'
    fault site fires between the digest and the pickle: the shipped
    frame is then self-consistent (its CRC trailer matches its bytes)
    and only the client's digest check can catch the damage — the
    host-memory-rot shape.

    v10 (round 21): with wire_dtype='int8' the primary blob is the
    absmax-quantized tree (kind 'params_int8'; runtime/codec.py —
    the digest covers the WIRE form, q arrays and scales, exactly
    like the bf16 digest covers the cast tree). `compat=True` builds
    the bf16 blob served to v<=9 subscribers instead — each publish
    builds both ONCE; `compat` builds don't advance the
    serializations clock (its contract is one count per VERSION, the
    per-version cost the test hook watches)."""
    if not compat:
      with self._params_lock:
        self._serializations += 1  # test hook: once per version
    wire_int8 = self._wire_int8 and not compat
    wire_bf16 = self._wire_bf16 or (self._wire_int8 and compat)
    if wire_int8:
      from scalable_agent_tpu.runtime import codec as codec_lib
      params = codec_lib.quantize_np(params)
    elif wire_bf16:
      import jax
      import ml_dtypes
      params = jax.tree_util.tree_map(
          lambda x: x.astype(ml_dtypes.bfloat16)
          if getattr(x, 'dtype', None) == np.float32 else x, params)
    digest = integrity.tree_digest(params)
    plan = faults_lib.active()
    fault = faults_lib.fire('publish_corrupt')
    if fault is not None:
      params = faults_lib.corrupt_params_tree(
          fault, params, seed=plan.seed if plan else 0)
    # v6: server info rides every params reply as a 4th element (old
    # clients index [0..2] and never see it). The hello reply IS a
    # params reply, so this is also how a client learns the session
    # epoch and the negotiated heartbeat cadence — no extra frame, no
    # extra version field on the wire.
    # 'wire_crc'/'crc_algo' are SERVER-WIDE facts (the blob is cached
    # per version, not per connection): each side derives the same
    # per-conn negotiation from (peer protocol >= 7) AND (server
    # wire_crc) AND (client offered crc) AND (algorithms match), so
    # no per-connection state needs to ride the cached frame.
    info = {'protocol': PROTOCOL_VERSION,
            'session_epoch': self.session_epoch,
            'heartbeat_secs': self._heartbeat_secs,
            'idle_timeout_secs': self._idle_timeout,
            'wire_crc': self._wire_crc,
            'crc_algo': integrity.CRC_ALGO,
            # v8: a server-wide fact like wire_crc — a v8 client
            # seeing it stamps trace contexts on its unroll frames.
            'trace': self._trace,
            'params_digest': integrity.digest_record(digest)}
    if wire_int8:
      kind = 'params_int8'
    elif wire_bf16:
      kind = 'params_bf16'
    else:
      kind = 'params'
    segments = _oob_frame_segments((kind, version, params, info))
    return segments, _CRC.pack(_segments_crc(segments))

  def publish_params(self, params) -> int:
    """Swap in a new host param snapshot; returns the new version.
    Call with numpy trees (device_get first). Serializes ONCE, here
    on the caller (learner-loop) thread — handler threads only ship
    the cached bytes. The pickle runs OUTSIDE the lock (handlers'
    acks/get_params must not stall behind it); a handler reading the
    previous blob between the version bump and the swap just triggers
    one redundant client refetch. Safe under concurrent publishers:
    the swap is version-guarded, so a slow pickle of version N can
    never overwrite version N+1's blob (ADVICE r3)."""
    with self._params_lock:
      self._version += 1
      version = self._version
    blob = self._make_blob(version, params)
    compat = (self._make_blob(version, params, compat=True)
              if self._wire_int8 else None)
    with self._params_lock:
      if version > self._blob_version:
        self._params_frame = blob
        self._params_frame_compat = compat
        self._blob_version = version
    return version

  @property
  def serializations(self) -> int:
    """How many times a param snapshot was pickled (== versions
    published, independent of client count)."""
    with self._params_lock:
      return self._serializations

  def live_hosts(self) -> int:
    """Hosts currently in the membership ledger (v9 peers only —
    pre-v9 connections never name a host identity and so never
    count here; use stats()['live'] for raw connection counts)."""
    with self._conns_lock:
      return len(self._members)

  def membership(self) -> List[str]:
    """Sorted host identities currently attached."""
    with self._conns_lock:
      return sorted(self._members)

  def drain_membership_events(self) -> List[Dict]:
    """Pop-all of the pending join/leave events, oldest first. The
    driver turns these into durable host_joined/host_left incidents
    at the summary cadence; each event is delivered exactly once."""
    with self._conns_lock:
      events, self._member_events = self._member_events, []
    return events

  def stats(self):
    with self._conns_lock:
      live = len(self._conns)
      live_hosts = len(self._members)
      per_conn = {f'{c.addr}': c.unrolls for c in self._conns}
      per_conn_stale = {f'{c.addr}': c.stale_rejected
                        for c in self._conns if c.stale_rejected}
    lane = self._param_lane.stats()
    wedged = self._wedged_threads()
    p50, p99 = self._ack_hist.percentiles(0.5, 0.99)
    ack_p50_ms, ack_p99_ms = round(p50, 3), round(p99, 3)
    with self._stats_lock:
      return {'unrolls': self._unrolls.value,
              'rejected': self._rejected.value,
              # Staleness-window rejections (round 9): unrolls refused
              # because the client's params version fell behind the
              # admission window — benign for the client (it refetches
              # and keeps its connection), but a host whose EVERY
              # unroll is stale is starving; the per-conn map names it.
              'stale_rejected': self._stale_rejected.value,
              'per_conn_stale_rejected': per_conn_stale,
              # Connections dropped after an unparseable/garbage frame
              # (protocol error path): the wire-level quarantine — a
              # corrupting peer loses its connection, the server and
              # every other connection keep going.
              'quarantined': self._quarantined.value,
              # v7 payload integrity (round 12): unrolls refused for a
              # mismatched CRC trailer (verified before the put — the
              # buffer provably never saw them), the param-lane ledger
              # of digest-refused publishes, and the discard
              # accounting of thrown-away partial/unparseable frames.
              'wire_crc_rejected': self._wire_crc_rejected.value,
              'publish_digest_rejected': lane['digest_rejected'],
              'discarded_frames': self._discarded_frames.value,
              'discarded_bytes': self._discarded_bytes.value,
              'connections': self._connections,  # cumulative
              'live': live,
              # Elastic membership (round 20): hosts currently in the
              # v9 ledger and the cumulative join/leave traffic — the
              # pod-size ground truth the driver gauges and the
              # controller's pod_size actuator read.
              'live_hosts': live_hosts,
              'hosts_joined': self._hosts_joined.value,
              'hosts_left': self._hosts_left.value,
              # Per-lane transport counters (round 6): the driver
              # turns these into summary-interval rates/latencies.
              'per_conn_unrolls': per_conn,
              'ack_p50_ms': ack_p50_ms,
              'ack_p99_ms': ack_p99_ms,
              'param_blobs': lane['blobs'],
              'param_bytes': lane['bytes'],
              'param_subscribers': self._param_subscribers,
              # Fan-out shrinkage (round 11 satellite): EVERY dropped
              # param-lane subscriber — disconnects, protocol errors,
              # idle reaps — so a quietly shrinking fleet is visible
              # in the driver summaries, not just in missing hosts.
              'param_subs_dropped': lane['subs_dropped'],
              'param_subs_reaped': lane['subs_reaped'],
              # Liveness/restart counters (round 11): reaped
              # idle/half-open connections, v6 peers silent past 2x
              # their heartbeat (the leading indicator before a
              # reap), unrolls refused for carrying a dead
              # incarnation's epoch (asserted ZERO by the partition
              # storm), and the fleet re-attach ledger a restarted
              # learner reports (count + seconds from server start to
              # the latest cross-epoch hello).
              'conns_reaped': self._conns_reaped.value,
              'heartbeat_misses': self._heartbeat_misses.value,
              'stale_epoch_rejected': self._stale_epoch_rejected.value,
              'reattached': self._reattached,
              'reconnected': self._reconnected,
              'reattach_latency_secs': round(self._reattach_latency, 3),
              'session_epoch': self.session_epoch,
              # Wedged-thread watchdog: service threads (readers,
              # workers, param lane, reaper) that made no progress
              # past the stall deadline — the silent-leak failure the
              # round-11 deadlines exist to prevent, surfaced instead
              # of assumed away.
              'ingest_threads_wedged': len(wedged),
              'wedged_thread_names': wedged,
              'unjoined_threads': self._unjoined_threads}

  def _wedged_threads(self) -> List[str]:
    """Service threads with no watchdog beat past the stall deadline.
    Liveness mode only: without poll timeouts an idle reader
    legitimately never beats, so the watchdog would cry wolf."""
    if not self._liveness_on:
      return []
    return self._watchdog.wedged(max(3 * self._stall_secs, 15.0))

  def _reap_loop(self):
    """Close connections (either lane handles its own sockets — this
    covers the trajectory lane) that received nothing inside the idle
    window; count heartbeat misses on v6 conns as the leading
    indicator. The close wakes the connection's blocked reader with an
    OSError; its normal unwind prunes the conn list."""
    while not self._closed.wait(max(self._poll_secs / 2, 0.05)):
      self._watchdog.beat('ingest-reaper')
      now = time.monotonic()
      with self._conns_lock:
        conns = list(self._conns)
      for conn in conns:
        if conn.is_waiting_on_us():
          # An unroll is in flight on this conn: the peer is parked
          # awaiting OUR ack (lockstep) — its silence is the protocol
          # working, not a half-open link. Backpressure can hold the
          # ack far past any idle window; reaping here would kill a
          # protocol-obedient peer and duplicate its unroll on
          # reconnect (the 'slow learner != dead learner' contract).
          continue
        # v5 peers CANNOT ping (no heartbeat machinery), so a
        # live-but-slow v5 actor (long episodes, mixed-version fleet
        # mid-upgrade) would be indistinguishable from half-open at
        # the v6 window — give them a generous multiple: half-open v5
        # conns still reap (bounded leak, not forever), slow live
        # ones survive any sane unroll cadence.
        idle_window = (self._idle_timeout if conn.heartbeat
                       else 5 * self._idle_timeout)
        silent = now - conn.last_recv
        if (conn.heartbeat and not conn.hb_missed
            and silent > 2 * self._heartbeat_secs):
          conn.hb_missed = True
          self._heartbeat_misses.inc()
          log.warning('remote actor %s missed its heartbeat window '
                      '(silent %.1fs, cadence %.1fs)', conn.addr,
                      silent, self._heartbeat_secs)
        if silent > idle_window and not conn.reaped:
          conn.reaped = True
          self._conns_reaped.inc()
          log.warning('reaping idle/half-open connection %s (silent '
                      '%.1fs > %.1fs window)', conn.addr, silent,
                      self._idle_timeout)
          try:
            conn.sock.shutdown(socket.SHUT_RDWR)
          except OSError:
            pass
          try:
            conn.sock.close()
          except OSError:
            pass
    self._watchdog.unregister('ingest-reaper')

  def _ingest_worker(self, name: str = 'ingest-worker'):
    """Validate/commit/ack loop — the trajectory lane's half that must
    not run on the reader thread (r5: recv + validate + put + ack
    serialized per connection made 4 connections slower than 1)."""
    try:
      self._ingest_worker_loop(name)
    finally:
      # EVERY exit path (sentinel, closed flag, Closed mid-put) must
      # retire the watchdog entry, or a cleanly-exited worker reads
      # as wedged forever in post-close stats.
      self._watchdog.unregister(name)

  def _ingest_worker_loop(self, name: str):
    while True:
      self._watchdog.beat(name)
      try:
        job = self._ingest_q.get(timeout=1.0)
      except queue.Empty:
        if self._closed.is_set():
          return
        continue
      if job is None:
        return
      (conn, unroll, t_recv, client_version, client_epoch, crc_pair,
       trace) = job
      try:
        if crc_pair is not None and crc_pair[0] != crc_pair[1]:
          # v7 payload integrity: the frame's bytes are not the bytes
          # the client sent — refuse BEFORE the staleness/epoch/
          # validation checks (every field parsed from a corrupt
          # frame is untrustworthy) and before the buffer put. The
          # benign ('corrupt', computed) reply keeps the connection:
          # the client re-sends once, then quarantines itself.
          computed, wire = crc_pair
          self._wire_crc_rejected.inc()
          conn.crc_rejected += 1
          log.warning(
              'unroll from %s failed its CRC trailer (computed '
              '%08x, wire %08x) — refused before the buffer put',
              conn.addr, computed, wire)
          conn.send(('corrupt', computed))
          continue
        if (client_epoch is not None
            and client_epoch != self.session_epoch):
          # A dead incarnation's unroll (v6 epoch stamp): refuse it
          # WITHOUT touching the buffer. Structurally unreachable over
          # plain TCP — the counter is the partition storm's proof
          # that zero stale-epoch unrolls crossed a restart, and the
          # guard that keeps that true if a proxy/load-balancer ever
          # sits in front of the port.
          self._stale_epoch_rejected.inc()
          conn.send(('stale_epoch', self.session_epoch))
          continue
        if self._max_staleness and client_version is not None:
          with self._params_lock:
            current = self._version
          if current - int(client_version) > self._max_staleness:
            # Version-windowed admission: refuse the unroll BEFORE
            # validation or the buffer put, but keep the connection —
            # the 'stale' reply carries the current version, so the
            # client's refetch-on-newer-version path fires and the
            # next unroll arrives fresh.
            self._stale_rejected.inc()
            conn.stale_rejected += 1
            conn.send(('stale', current))
            continue
        if self._validate is not None:
          problems = self._validate(unroll)
          if problems:
            # Reject WITHOUT touching the buffer (a malformed unroll
            # must not poison training) but keep the connection: the
            # actor decides whether this is fatal.
            self._rejected.inc()
            conn.send(('error', 'unroll rejected: '
                       + '; '.join(problems)))
            continue
        # Trace span (round 13, v8): this unroll passed every check —
        # stamp COMMIT (admitted; the buffer put below may still wait
        # on backpressure, which the commit→staged hop then shows as
        # queue time) and tag the unroll BEFORE the put so the
        # prefetcher can never consume it ahead of its tag. The
        # piggybacked params-install notice ('pi') becomes its own
        # trace record here — the publish→installed-at-actor hop.
        tracer = telemetry.get_tracer()
        if trace is not None and tracer is not None:
          telemetry.stamp(trace, telemetry.HOP_COMMIT)
          # Commit-time publish counter in the INGEST clock ('cv'):
          # policy lag for this unroll is cv - bv, a publish-count
          # delta judged within the clock its behaviour version was
          # stamped in (the tracer's local clock counts driver
          # publishes — a different sequence).
          with self._params_lock:
            trace['cv'] = self._version
          install = trace.pop('pi', None)
          if install is not None:
            try:
              tracer.on_install(trace.get('a', conn.addr),
                                install[0], install[1])
            except (TypeError, IndexError):
              pass  # malformed notice from a buggy peer: drop it
          tracer.tag(unroll, trace)
        # Blocking put IS the backpressure: the delayed ack holds the
        # remote pump exactly like the reference's remote enqueue
        # into the capacity-1 queue. Poll so close() can interrupt.
        # The ('busy',) keepalive that tells a v6 peer "slow, not
        # dead" meanwhile is the READER's job (_ConnLiveness.idle) —
        # it covers this wait AND a job still parked in the handoff
        # queue behind other connections (workers < connections under
        # load), which no worker-side emission could.
        while True:
          try:
            self._buffer.put(unroll, timeout=1.0)
            break
          except TimeoutError:
            if self._closed.is_set():
              return
            self._watchdog.beat(name)
        self._unrolls.inc()
        conn.unrolls += 1
        with self._params_lock:
          version = self._version
        conn.send(('ack', version))
        self._ack_hist.observe((time.monotonic() - t_recv) * 1e3)
      except ring_buffer.Closed:
        return  # learner shut down; readers see their conns drop
      except (ConnectionError, OSError):
        pass  # peer gone mid-ack; its reader notices and cleans up
      except Exception:
        log.exception('ingest worker failed on an unroll')
      finally:
        # The reply (ack/stale/reject/busy-abandon) is out, or the
        # conn is dead either way: this unroll is no longer in flight,
        # so the conn's silence becomes a liveness signal again.
        conn.job_finished()

  def _accept_loop(self):
    while not self._closed.is_set():
      try:
        conn, addr = self._listener.accept()
      except OSError:
        return  # listener closed
      conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      if self._liveness_on:
        # Timeout mode: the reader polls (so stalls are detectable and
        # the watchdog sees beats) and every send is progress-bounded.
        conn.settimeout(self._poll_secs)
        wrapped = _Conn(conn, addr=addr,
                        send_stall_secs=self._stall_secs,
                        base_timeout=self._poll_secs)
      else:
        wrapped = _Conn(conn, addr=addr)
      t = threading.Thread(target=self._serve, args=(wrapped, addr),
                           name=f'ingest-{addr}', daemon=True)
      with self._conns_lock:
        if self._closed.is_set():
          conn.close()
          return
        self._conns.append(wrapped)
        self._threads = [x for x in self._threads if x.is_alive()]
        self._threads.append(t)
      with self._stats_lock:
        self._connections += 1
      t.start()

  def _snapshot_frame(
      self, proto: int = PROTOCOL_VERSION) -> Tuple[List[bytes], bytes]:
    """(cached frame segments, cached CRC trailer) of the current
    published version — the trailer ships only to v7 CRC peers.
    `proto` selects the encoding (v10 codec negotiation): a v<=9
    peer of an int8 publisher gets the cached bf16 compat blob."""
    with self._params_lock:
      if self._wire_int8 and proto < 10:
        return self._params_frame_compat
      return self._params_frame

  def snapshot_nbytes(self, proto: int = PROTOCOL_VERSION) -> int:
    """Wire size of the current cached snapshot frame (bench +
    egress-arithmetic hook; the 4 trailer bytes are noise)."""
    return sum(len(s) for s in self._snapshot_frame(proto)[0])

  def attach_serving(self, fn) -> None:
    """Attach the routed-inference seam (v10): `fn(payload dict) ->
    result dict`, normally InferenceServer.serve_remote. 'infer'
    requests answer ('error', 'serving not attached') until this is
    called; None detaches."""
    with self._params_lock:
      self._serving_fn = fn

  def set_draining(self, draining: bool = True) -> None:
    """Flip the drain notice 'infer' replies carry — routers
    (runtime/routing.py) shift a replica's share away BEFORE its
    connections die (the PR 17 leave convention, serving-plane
    edition)."""
    with self._params_lock:
      self._draining = bool(draining)

  def _serve(self, conn: _Conn, addr):
    log.info('remote actor connected from %s', addr)
    # Handshake is per-connection: with a contract set, no unroll is
    # accepted until this client's hello matched (a reconnecting
    # client re-handshakes — cheap, and it re-verifies after learner
    # restarts that may have changed the config).
    handshaken = self._contract is None
    adopted = False
    leave_to_close = False  # close() owns the socket/list teardown
    thread_name = f'ingest-reader-{addr}'
    # The liveness ledger exists on EVERY connection now (round 12):
    # besides the round-11 stall/keepalive machinery (armed only in
    # liveness mode — on a blocking legacy socket its timeout paths
    # simply never fire), it carries the per-frame byte count the
    # discard accounting reports when a frame is thrown away.
    liveness = _ConnLiveness(
        conn, self._closed, self._stall_secs,
        watchdog=self._watchdog if self._liveness_on else None,
        name=thread_name, heartbeat_secs=self._heartbeat_secs)
    liveness.beat()
    crc_ctx = None  # armed once the hello negotiates v7 CRC
    try:
      while not self._closed.is_set():
        msg = _recv_msg(conn.sock, liveness, crc_ctx)
        if msg is None:
          return  # client went away
        kind = msg[0]
        if kind == 'hello':
          offered = msg[1] if len(msg) > 1 else None
          if self._contract is not None:
            problem = contract_mismatch_message(self._contract, offered)
            if problem is not None:
              log.warning('rejecting actor %s: %s', addr, problem)
              conn.send(('reject', problem))
              return
            handshaken = True
          # v6 negotiation (contract or not — protocol tests handshake
          # against contract-less servers too): the offered protocol
          # decides whether this conn gets busy keepalives and
          # heartbeat-miss accounting; the client-info dict's prior
          # epoch tells a reattaching client (cross-epoch — a learner
          # RESTART behind it) from a same-run reconnect.
          if isinstance(offered, dict):
            conn.protocol = int(offered.get('protocol') or 5)
          conn.heartbeat = (conn.protocol >= 6
                            and self._heartbeat_secs > 0)
          client_info = msg[2] if len(msg) > 2 else None
          # v7 CRC negotiation: peer protocol, server knob, client
          # offer, and algorithm must ALL agree (a zlib-fallback host
          # paired with a crc32c host negotiates OFF — phantom
          # corruption would be worse than no check). Takes effect
          # AFTER the hello reply below: the reply ships per the
          # conn's PRIOR crc state, because the client cannot know
          # the outcome until it has parsed this very frame.
          crc_next = (conn.protocol >= 7 and self._wire_crc
                      and isinstance(client_info, dict)
                      and bool(client_info.get('crc'))
                      and client_info.get('crc_algo') ==
                      integrity.CRC_ALGO)
          prior_epoch = (client_info or {}).get('epoch') \
              if isinstance(client_info, dict) else None
          try:
            prior_epoch = (None if prior_epoch is None
                           else int(prior_epoch))
          except (TypeError, ValueError):
            prior_epoch = None  # garbage epoch: treat as a fresh hello
          if prior_epoch is not None:
            with self._stats_lock:
              if prior_epoch != self.session_epoch:
                self._reattached += 1
                self._reattach_latency = (time.monotonic()
                                          - self._t_start)
                log.info(
                    'remote actor %s REATTACHED across a learner '
                    'restart (prior epoch %d -> %d) %.2fs after '
                    'server start', addr, prior_epoch,
                    self.session_epoch, self._reattach_latency)
              else:
                self._reconnected += 1
          # v9 membership: a hello naming a host identity enters the
          # ledger. Only a NEW identity is a join event — a reconnect
          # of a known host just re-points its entry at this conn
          # (the old conn's unwind sees it no longer owns the
          # identity and stays silent).
          host_id = (client_info.get('host')
                     if isinstance(client_info, dict) else None)
          if isinstance(host_id, str) and host_id:
            conn.host_id = host_id
            with self._conns_lock:
              fresh = host_id not in self._members
              self._members[host_id] = conn
              if fresh:
                self._member_events.append(
                    {'kind': 'host_joined', 'host': host_id,
                     'reattach': prior_epoch is not None})
            if fresh:
              self._hosts_joined.inc()
              log.info('host %s JOINED the pod (%s)', host_id, addr)
          segments, trailer = self._snapshot_frame(conn.protocol)
          conn.send_segments(segments,
                             trailer if conn.crc else None)
          conn.crc = crc_next
          crc_ctx = _CrcContext() if conn.crc else None
        elif kind == 'ping':
          # Application-level heartbeat (v6): refreshes last_recv by
          # arriving; the pong carries the current params version so
          # an idle fleet still notices publishes without traffic.
          with self._params_lock:
            version = self._version
          conn.send(('pong', version))
        elif kind == 'hello_params':
          # Re-route this whole connection to the param lane: the
          # reader thread hands the raw socket over and exits — blob
          # traffic must never share a thread (or a socket) with the
          # trajectory lane's acks. Re-categorize the connection count
          # ('connections' means ACTOR connections; subscribers get
          # their own counter).
          with self._stats_lock:
            self._connections -= 1
            self._param_subscribers += 1
          # v7: the hello_params MAY carry the client-info dict — the
          # lane then appends the cached trailer to its replies and
          # verifies trailers on requests from this subscriber.
          sub_info = msg[1] if len(msg) > 1 else None
          sub_crc = (self._wire_crc and isinstance(sub_info, dict)
                     and bool(sub_info.get('crc'))
                     and sub_info.get('crc_algo') ==
                     integrity.CRC_ALGO)
          # v10: the subscriber's offered protocol picks its blob
          # encoding; absent (v<=9 hello_params, or the bare legacy
          # tuple), fall back to the trajectory-lane handshake's
          # protocol, else to the conservative bf16/f32 blob.
          sub_proto = conn.protocol
          if isinstance(sub_info, dict) and sub_info.get('protocol'):
            sub_proto = int(sub_info['protocol'])
          adopted = self._param_lane.adopt(conn.sock, crc=sub_crc,
                                           proto=sub_proto)
          return
        elif kind == 'get_params':
          # Legacy/in-band path (pre-v5 peers, protocol tests): served,
          # but production clients fetch over the param lane.
          segments, trailer = self._snapshot_frame(conn.protocol)
          conn.send_segments(segments,
                             trailer if conn.crc else None)
        elif kind == 'unroll':
          if not handshaken:
            # 'error', not 'reject': legacy (protocol-1) clients only
            # special-case 'bye'/'error' — a 'reject' here would parse
            # as a successful ack and they would silently drop every
            # unroll forever instead of failing loudly.
            conn.send(('error',
                       'unroll before a successful hello handshake — '
                       'upgrade/fix the actor host'))
            continue
          # Reader half of the trajectory lane ends here: validation,
          # the backpressure put and the ack all happen on the worker
          # pool, so this thread is back inside recv for the next
          # frame immediately. msg[2] (when present) is the client's
          # params version for the staleness window (v5 extension);
          # msg[3] (v6) is the session epoch the client handshook
          # under — the stale-incarnation guard.
          # Mark the unroll in flight BEFORE the enqueue: from here
          # until the worker's reply, this conn's silence is lockstep
          # protocol (reaper-exempt), not a liveness signal. On a v7
          # CRC conn the (computed, wire) pair rides the job: the
          # WORKER compares just before the put, so a corrupt frame
          # earns its benign reply without ever touching the buffer.
          conn.job_started()
          # msg[4] (v8) is the unroll's trace context: stamp WIRE here
          # (frame fully received) — the worker stamps COMMIT and the
          # rest of the pipeline completes the span.
          trace = msg[4] if len(msg) > 4 else None
          if isinstance(trace, dict):
            telemetry.stamp(trace, telemetry.HOP_WIRE)
          else:
            trace = None
          self._ingest_q.put((conn, msg[1], time.monotonic(),
                              msg[2] if len(msg) > 2 else None,
                              msg[3] if len(msg) > 3 else None,
                              (crc_ctx.computed, crc_ctx.wire)
                              if crc_ctx is not None else None,
                              trace))
        elif kind == 'leave':
          # v9 drain announcement: the host is exiting DELIBERATELY
          # (SIGTERM quiesce), so its unwind records
          # host_left(reason='drain') — survivors tell a planned
          # departure from a crash without any out-of-band channel.
          conn.draining = True
          conn.send(('bye_ack',))
          log.info('host %s announced drain from %s',
                   conn.host_id or '<unnamed>', addr)
          return  # the finally block runs the membership unwind
        elif kind == 'stats':
          # On-demand fleet telemetry (round 13): the unified
          # metrics-registry snapshot + this server's ingest stats,
          # served over the existing control lane — operators, tests,
          # and fleet tooling read the SAME source of truth the drain
          # manifest and flight recorder use, remotely.
          conn.send(('stats', {
              'registry': telemetry.registry().snapshot(),
              'ingest': self.stats(),
          }))
        elif kind == 'infer':
          # v10 routed inference: one carry-passing batch served from
          # the learner's resident version table (attach_serving).
          # Runs ON the reader thread — the request→reply lockstep
          # means one in-flight infer per connection, and routers open
          # a dedicated connection per replica, so the trajectory
          # lane's acks never queue behind a forward pass here. The
          # notice dict's 'draining' flag is how a replica's share
          # drains BEFORE its socket dies.
          with self._params_lock:
            serving_fn = self._serving_fn
            draining = self._draining
          if serving_fn is None:
            conn.send(('error', 'serving not attached'))
          else:
            try:
              result = serving_fn(msg[1])
            except Exception as e:
              log.exception('routed inference request failed')
              conn.send(('error',
                         f'infer failed: {type(e).__name__}: {e}'))
            else:
              conn.send_oob(('infer_ok', result,
                             {'draining': draining}))
        else:
          conn.send(('error', f'unknown message kind {kind!r}'))
      # Loop-condition exit on a closing server: same contract as
      # _ServerClosing below — close() owns the bye/teardown.
      leave_to_close = True
    except ring_buffer.Closed:
      pass  # learner shut down; dropping the conn tells the actor
    except _ServerClosing:
      # close() owns this connection's shutdown from here: leave the
      # socket open and the conn listed so the 'bye' sequence finds
      # it (closing here would race the bye into an RST).
      leave_to_close = True
    except _FrameStall as e:
      # Half-open peer caught MID-frame by the reader's own stall
      # deadline (faster than the reaper's idle window): reap it here
      # — the partial frame never reached the handoff queue, so the
      # buffer cannot be corrupted by it; it is simply discarded with
      # the connection.
      conn.reaped = True
      self._conns_reaped.inc()
      self._discarded_frames.inc()
      self._discarded_bytes.inc(liveness.frame_bytes)
      log.warning('reaping half-open connection %s: %s (partial '
                  'frame discarded: %d byte(s))', addr, e,
                  liveness.frame_bytes)
    except (ValueError, struct.error, pickle.UnpicklingError,
            EOFError) as e:
      # Unparseable frame — a version-skewed peer (a pre-v4 client's
      # untagged pickle starts with opcode 0x80 = "frame kind 128") or
      # garbage on the wire. Must not kill the handler thread
      # silently: log the likely cause and QUARANTINE just this
      # connection (counted — chaos.py's SLO asserts corrupt peers
      # get dropped while the learner keeps training). The discarded
      # frame's size rides the ledger too (round-12 fix: the conn was
      # counted but the thrown-away data never was — an operator
      # could not tell a dropped 40-byte hello from a dropped 2 MB
      # unroll burst).
      self._quarantined.inc()
      self._discarded_frames.inc()
      self._discarded_bytes.inc(liveness.frame_bytes)
      log.warning(
          'protocol/frame error from %s — connection quarantined '
          '(version-skewed peer? this learner speaks v%d; %d byte(s) '
          'discarded): %s', addr, PROTOCOL_VERSION,
          liveness.frame_bytes, e)
    except (ConnectionError, OSError) as e:
      if conn.reaped:
        log.info('remote actor %s reader unwound after reap', addr)
      elif not self._closed.is_set():
        log.warning('remote actor %s dropped: %s', addr, e)
    finally:
      if liveness is not None:
        self._watchdog.unregister(thread_name)
      if not adopted and not leave_to_close:
        conn.sock.close()
      if not leave_to_close:
        left_as = None
        with self._conns_lock:
          if conn in self._conns:
            self._conns.remove(conn)
          # Membership unwind: only the conn CURRENTLY owning the
          # identity records the departure — a reconnect re-pointed
          # the entry before the old reader unwound, so the old
          # conn's exit is a non-event.
          if (conn.host_id is not None
              and self._members.get(conn.host_id) is conn):
            del self._members[conn.host_id]
            reason = ('drain' if conn.draining
                      else 'reaped' if conn.reaped else 'lost')
            left_as = reason
            self._member_events.append(
                {'kind': 'host_left', 'host': conn.host_id,
                 'reason': reason})
        if left_as is not None:
          self._hosts_left.inc()
          log.warning('host %s LEFT the pod (%s)', conn.host_id,
                      left_as)
      if not adopted and not leave_to_close:
        log.info('remote actor %s disconnected', addr)

  def close(self, graceful: bool = True):
    """Shut the server down.

    graceful=True announces a CLEAN end ('bye' frame) so actors exit
    immediately instead of burning their reconnect window against a
    port that will never come back. Pass graceful=False when the
    learner intends to RESTART (exception unwind before a supervisor
    respawn) — actors then keep retrying and resume feeding.

    Graceful shutdown half-closes (SHUT_WR) before the hard close so
    the 'bye' is not discarded by an RST when the client's next
    request races the close; every step is time-bounded (a stuck peer
    cannot hang the learner's teardown).
    """
    self._closed.set()
    # shutdown() BEFORE close(): a thread blocked in accept() holds
    # the open file description, so close() alone leaves the port
    # LISTENing (owner-less) until some stray connection completes
    # the accept — shutdown wakes the blocked accept immediately and
    # releases the port deterministically.
    try:
      self._listener.shutdown(socket.SHUT_RDWR)
    except OSError:
      pass
    try:
      self._listener.close()
    except OSError:
      pass
    # Drain the worker pool (one sentinel per worker) and the param
    # lane before touching the trajectory conns: a worker mid-commit
    # may still send one last ack, which try_send below tolerates.
    # The handoff queue is bounded now: a full queue must not hang
    # close() — workers that miss their sentinel still exit with the
    # closed flag on their next buffer-put poll, or leak as daemons.
    for _ in self._workers:
      try:
        self._ingest_q.put(None, timeout=2.0)
      except queue.Full:
        log.warning('ingest close: handoff queue full; worker will '
                    'exit via the closed flag or leak as a daemon')
        break
    unjoined: List[str] = []
    if self._param_lane.close(graceful=graceful):
      unjoined.append('param-lane')
    with self._conns_lock:
      conns = list(self._conns)
      threads = list(self._threads)
    for conn in conns:
      if graceful:
        conn.try_send(('bye',))
        try:
          # FIN only: the client still reads the buffered 'bye' even
          # if it was mid-send; a full RDWR shutdown + close here can
          # turn into an RST that discards it.
          conn.sock.shutdown(socket.SHUT_WR)
        except OSError:
          pass
      else:
        try:
          conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
          pass
        conn.sock.close()
    for t in threads:
      t.join(timeout=2.0)
      if t.is_alive():
        unjoined.append(t.name)
    if graceful:
      for conn in conns:
        conn.sock.close()
    for w in self._workers:
      w.join(timeout=2.0)
      if w.is_alive():
        unjoined.append(w.name)
    self._accept_thread.join(timeout=2.0)
    if self._accept_thread.is_alive():
      unjoined.append('ingest-accept')
    if self._reaper_thread is not None:
      self._reaper_thread.join(timeout=2.0)
      if self._reaper_thread.is_alive():
        unjoined.append('ingest-reaper')
    # Join-deadline misses used to vanish silently (the InferenceServer
    # close parity, round 11 satellite): a leaked reader/worker pins
    # its buffers and a socket for the rest of the process lifetime —
    # count it and NAME it.
    with self._stats_lock:
      self._unjoined_threads = len(unjoined)
    if unjoined:
      log.warning(
          'TrajectoryIngestServer.close(): %d thread(s) missed the '
          'join deadline and leak as daemons: %s', len(unjoined),
          ', '.join(unjoined))


class RemoteActorClient:
  """Actor-side connection to the learner's ingest server.

  Two sockets, one per lane: unrolls/acks ride the trajectory
  connection opened here; `fetch_params` lazily opens a second
  connection onto the server's param lane (`hello_params`) so blob
  transfers never queue behind — or in front of — unroll acks.

  Strict request→reply per socket; NOT thread-safe — one pump thread
  owns it.

  Liveness (round 11): `io_timeout_secs` > 0 arms a recv/send deadline
  on both sockets — a silent learner (partition, hard crash behind a
  live NAT entry) surfaces as a ConnectionError within the window
  instead of pinning the pump forever. The deadline composes with the
  server's ('busy',) keepalives: a slow-but-alive learner emits busy
  frames at the heartbeat cadence while backpressure holds an ack, so
  `_rpc` keeps waiting (each frame is progress); only true silence
  trips the deadline. `session_epoch`/`server_info` are learned at
  handshake; the epoch stamps every unroll so a restarted learner can
  prove zero stale-incarnation unrolls crossed its restart.
  """

  def __init__(self, address: str, connect_timeout_secs: float = 60.0,
               io_timeout_secs: float = 0.0, wire_crc: bool = True):
    host, port = address.rsplit(':', 1)
    self._addr = (host, int(port))
    self._io_timeout = (float(io_timeout_secs)
                        if io_timeout_secs and io_timeout_secs > 0
                        else None)
    self._param_sock: Optional[socket.socket] = None
    # Unrolls the learner's staleness window refused (benign: dropped
    # + refetch; the pump reads this for its logs).
    self.stale_rejections = 0
    # v6 liveness/restart state: the server-info dict from the last
    # params reply, the session epoch this connection handshook under,
    # and how many ('busy',) backpressure keepalives were absorbed.
    self.server_info: Dict = {}
    self.session_epoch: Optional[int] = None
    self.busy_frames = 0
    # v7 payload integrity: offer CRC at hello (`wire_crc`); `_crc`
    # flips on when the handshake reply's server-info confirms the
    # negotiation — from then on every frame both ways carries the
    # trailer. `crc_rejected` counts ('corrupt', crc) refusals of OUR
    # unrolls (a climbing count implicates THIS host's NIC/RAM);
    # `digest_rejected` counts param snapshots refused before install.
    self._wire_crc = bool(wire_crc)
    self._crc = False
    self._param_sock_crc = False  # the cached sub's pinned CRC state
    self.crc_rejected = 0
    self.digest_rejected = 0
    self._digest_nack: Optional[int] = None  # rides the retry fetch
    # v8 trace spans: `trace_ok` flips on when the handshake reply's
    # server-info advertises a tracing learner — unroll frames then
    # carry their trace context as a 5th element, and the most recent
    # params-install event piggybacks on the next one ('pi' notice —
    # the publish→installed-at-actor hop, same pattern as the digest
    # nack).
    self.trace_ok = False
    self._pending_install: Optional[List] = None
    deadline = time.monotonic() + connect_timeout_secs
    last_err = None
    # Capped exponential backoff + full jitter: after a learner
    # restart, hundreds of actor hosts all lose their connection at
    # the same instant — fixed-interval retries would hammer the new
    # listener in lockstep (thundering herd).
    backoff = Backoff(base=0.2, cap=5.0)
    while True:
      try:
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=10.0)
        if self._sock.getsockname() == self._sock.getpeername():
          # Localhost self-connect: while the learner's port is down,
          # the kernel can hand our outbound socket that very port as
          # its ephemeral source, and TCP simultaneous-open "succeeds"
          # against ourselves — a phantom learner that both occupies
          # the port and never replies. Drop it and retry.
          self._sock.close()
          raise OSError('self-connect while learner port is down')
        break
      except OSError as e:  # learner may not be up yet: retry
        last_err = e
        if time.monotonic() > deadline:
          raise ConnectionError(
              f'could not reach learner at {address}: {e}') from e
        backoff.sleep()
    self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    self._sock.settimeout(self._io_timeout)
    log.info('connected to learner at %s (after %s)', address, last_err)

  def _rpc(self, msg, oob: bool = False):
    # Scripted partition/latency (runtime/faults.py round 11): delay
    # sleeps before the send; blackhole goes COMPLETELY silent for its
    # window without closing — the learner-side idle reaper must see
    # half-open silence, and this client then discovers the reaped
    # socket when the partition "heals".
    plan = faults_lib.active()
    delay = faults_lib.fire('conn_delay')
    if delay is not None:
      faults_lib.apply_conn_delay(delay, seed=plan.seed if plan else 0)
    partition = faults_lib.fire('conn_partition')
    if partition is not None:
      faults_lib.apply_conn_partition(partition)
    fault = faults_lib.fire('transport_send')
    if fault is not None:
      # Scripted transport damage (runtime/faults.py): ship garbage/
      # truncated bytes the learner must survive, then surface the
      # OSError this client's reconnect path expects.
      faults_lib.apply_transport_fault(
          fault, self._sock, seed=plan.seed if plan else 0)
    if oob:
      _send_oob(self._sock, msg, crc=self._crc)
    else:
      _send_msg(self._sock, msg, crc=self._crc)
    crc_ctx = _CrcContext() if self._crc else None
    while True:
      try:
        reply = _recv_msg(self._sock, crc_ctx=crc_ctx)
      except socket.timeout as e:
        raise ConnectionError(
            f'learner silent past the {self._io_timeout}s I/O '
            'deadline (no ack, no busy keepalive) — treating the '
            'connection as dead') from e
      except (ValueError, struct.error, pickle.UnpicklingError,
              EOFError) as e:
        raise ProtocolError(
            f'unparseable reply from the learner ({e!r}) — likely a '
            f'protocol-version skew (this client speaks '
            f'v{PROTOCOL_VERSION}); upgrade both roles together') from e
      if reply is None:
        raise ConnectionError('learner closed the connection')
      if crc_ctx is not None and not crc_ctx.ok:
        # A reply failing ITS trailer means the learner→actor
        # direction corrupts: nothing parsed from it can be trusted.
        # ConnectionError on purpose — a fresh connection (and a
        # re-handshake) is the recovery; persistent failures land in
        # the reconnect window where the operator can see them.
        raise ConnectionError(
            f'learner reply failed its CRC trailer (computed '
            f'{crc_ctx.computed:08x}, wire {crc_ctx.wire:08x})')
      if reply[0] == 'busy':
        # Backpressure keepalive (v6): the ack is held back by a full
        # learner buffer, not a dead learner — keep waiting (each
        # frame refreshes the per-recv deadline by arriving).
        self.busy_frames += 1
        continue
      break
    if reply[0] == 'bye':
      raise LearnerShutdown('learner finished training')
    if reply[0] == 'reject':
      raise ContractMismatch(reply[1])
    if reply[0] == 'corrupt':
      # v7: the learner's CRC check refused our unroll — the frame
      # was damaged AFTER we computed its trailer (wire, NIC, or this
      # host's own memory). The connection itself is fine.
      self.crc_rejected += 1
      raise UnrollCorrupt(
          f'learner refused the unroll: payload CRC mismatch (its '
          f'computed crc {reply[1]:08x}) — re-send once, then treat '
          'this host as suspect', crc=reply[1])
    if reply[0] == 'stale_epoch':
      raise SessionEpochMismatch(
          f'learner refused this client\'s session epoch '
          f'{self.session_epoch} (its current epoch: {reply[1]}) — '
          'the learner restarted; re-handshake required')
    if reply[0] == 'error':
      raise RuntimeError(f'learner rejected request: {reply[1]}')
    return reply

  def _decode_params(self, reply, negotiate: bool = False,
                     offered_protocol: Optional[int] = None
                     ) -> Tuple[int, object]:
    """(version, tree) from a params reply; 'params_bf16' blobs
    (learner running remote_params_dtype=bfloat16) upcast back to
    float32 here — the actor's agent/contract only ever sees f32.
    v6 replies carry a 4th element, the server-info dict (protocol,
    session epoch, heartbeat cadence) — recorded here; absent from v5
    servers, in which case the liveness state stays empty.

    v7: the server-info's 'params_digest' is verified against the
    WIRE-form tree (before the upcast — the exact bytes received)
    BEFORE this snapshot can reach update_params. A mismatch raises
    ParamsCorrupt: the caller must NOT install, keeps its prior
    params, and refetches on backoff — a corrupt publish is rejected
    fleet-wide without a version bump. The v7 CRC negotiation resolves
    here ONLY for handshake replies (`negotiate=True`): the server
    pins its side at the hello, so flipping on a mid-stream params
    reply (a lane fetch without a handshake) would desynchronize the
    framing."""
    version, tree = reply[1], reply[2]
    if len(reply) > 3 and isinstance(reply[3], dict):
      self.server_info = reply[3]
      epoch = reply[3].get('session_epoch')
      if epoch is not None:
        self.session_epoch = epoch
      if negotiate:
        self._crc = (self._wire_crc
                     and int(self.server_info.get('protocol') or 0)
                     >= 7
                     and bool(self.server_info.get('wire_crc'))
                     and self.server_info.get('crc_algo') ==
                     integrity.CRC_ALGO)
      if offered_protocol is not None:
        # v8: stamp traces only when BOTH sides speak v8 — keyed on
        # the protocol this client OFFERED (like the CRC negotiation:
        # a forged older contract must land the same negotiation on
        # both sides) AND the server's advertised tracing fact.
        self.trace_ok = (int(offered_protocol) >= 8
                         and int(self.server_info.get('protocol')
                                 or 0) >= 8
                         and bool(self.server_info.get('trace')))
      record = self.server_info.get('params_digest')
      if record is not None:
        verdict = integrity.verify_record(
            record, integrity.tree_digest(tree))
        if verdict is False:
          self.digest_rejected += 1
          self._digest_nack = int(version)
          raise ParamsCorrupt(
              f'params v{version} failed its content digest '
              f'(recorded {record}) — snapshot NOT installed; keep '
              'the prior params and refetch on backoff',
              version=int(version))
        if verdict is None:
          log.warning(
              'params digest not comparable (recorded %r, local algo '
              '%s) — content verification skipped', record,
              integrity.CRC_ALGO)
    if reply[0] == 'params_bf16':
      import jax
      import ml_dtypes
      tree = jax.tree_util.tree_map(
          lambda x: x.astype(np.float32)
          if getattr(x, 'dtype', None) == ml_dtypes.bfloat16 else x,
          tree)
    elif reply[0] == 'params_int8':
      # v10 int8 blobs (runtime/codec.py): the digest above covered
      # the WIRE form (q arrays + scales); the host decode to f32
      # happens only after it verified.
      from scalable_agent_tpu.runtime import codec as codec_lib
      tree = codec_lib.dequantize_np(tree)
    return version, tree

  def handshake(self, contract, prior_epoch: Optional[int] = None,
                host: Optional[str] = None) -> Tuple[int, object]:
    """Offer this host's trajectory contract; returns (version,
    params) on agreement, raises ContractMismatch (naming the
    offending fields) when the learner refuses. The handshake blob
    rides the trajectory connection (once per connect — before any
    unroll is in flight, so there is no ack to starve).

    `prior_epoch` (v6): the session epoch of the learner this host was
    attached to before the drop, if any — a RESTARTED learner sees a
    foreign epoch and counts/times the fleet re-attach; old servers
    ignore the extra hello element. The same client-info dict carries
    the v7 CRC offer (algorithm included — mixed-fallback pairs must
    negotiate the check OFF, not miscompare) and the v9 `host`
    identity for the learner's elastic membership ledger."""
    # Offer CRC only when the CONTRACT itself speaks v7: tests (and
    # mixed fleets mid-upgrade) legitimately offer an older protocol
    # through a forged contract, and the negotiation must then land
    # identically on both sides — the server keys on the offered
    # protocol, so the client must too.
    offered_protocol = (contract.get('protocol')
                        if isinstance(contract, dict) else None)
    # A non-dict contract reaches the server as a legacy hello (its
    # reader keys protocol 5) — never offer CRC there.
    offer_crc = (self._wire_crc and offered_protocol is not None
                 and int(offered_protocol) >= 7)
    info: Dict = {}
    if prior_epoch is not None:
      info['epoch'] = int(prior_epoch)
    if offer_crc:
      info['crc'] = True
      info['crc_algo'] = integrity.CRC_ALGO
    if host is not None:
      # v9 membership: a stable host identity enters the learner's
      # ledger (join/leave events, live-host gauge). Old servers
      # ignore the extra key — offering it costs nothing.
      info['host'] = str(host)
    msg = ('hello', contract, info) if info else ('hello', contract)
    if not offer_crc:
      self._crc = False
    self.trace_ok = False  # re-negotiated per handshake below
    return self._decode_params(
        self._rpc(msg), negotiate=offer_crc,
        offered_protocol=(int(offered_protocol)
                          if offered_protocol is not None else None))

  def ping(self) -> int:
    """Application-level heartbeat on the trajectory lane (v6): keeps
    an idle connection inside the learner's reaping window and returns
    the learner's CURRENT params version from the pong — so an idle
    fleet still notices publishes. Raises like any rpc on a dead
    learner (the pump's reconnect path runs)."""
    reply = self._rpc(('ping',))
    if reply[0] != 'pong':
      raise ProtocolError(f'expected pong, got {reply[0]!r}')
    return reply[1]

  def fetch_params(self) -> Tuple[int, object]:
    """(version, host param pytree) — the current learner snapshot,
    fetched over the dedicated param lane. A lane failure closes just
    the param socket and surfaces as ConnectionError/OSError; the
    caller's reconnect path rebuilds both lanes. A CACHED lane that
    died between fetches (the learner's idle reaper legitimately reaps
    a long-quiet subscriber) retries ONCE on a fresh param socket
    before surfacing — a reaped sub must not cost the whole
    trajectory connection a reconnect cycle."""
    had_cached_lane = self._param_sock is not None
    try:
      return self._fetch_params_once()
    except (ConnectionError, OSError) as e:
      if not had_cached_lane:
        raise
      log.info('param lane died between fetches (%s); retrying once '
               'on a fresh subscriber connection', e)
      return self._fetch_params_once()

  def _fetch_params_once(self) -> Tuple[int, object]:
    if self._param_sock is None:
      try:
        sock = socket.create_connection(self._addr, timeout=10.0)
      except OSError:
        raise ConnectionError(
            f'could not open the param lane to {self._addr}')
      sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      sock.settimeout(self._io_timeout)
      # The hello_params itself is pre-negotiation (no trailer); with
      # CRC already negotiated on the trajectory lane (handshake),
      # the info dict turns the same machinery on for this subscriber
      # — every subsequent frame on the lane carries trailers both
      # ways. The lane's state is PINNED at open: a later handshake
      # flipping self._crc must not desynchronize a cached sub.
      # v10: the info dict ALWAYS carries 'protocol' — the lane picks
      # this subscriber's blob encoding from it (an int8 publisher
      # hands v<=9 subscribers the bf16 compat blob); a v<=9 server
      # reads only the crc keys and ignores the rest.
      if self._crc:
        _send_msg(sock, ('hello_params',
                         {'protocol': PROTOCOL_VERSION, 'crc': True,
                          'crc_algo': integrity.CRC_ALGO}))
      else:
        _send_msg(sock, ('hello_params',
                         {'protocol': PROTOCOL_VERSION}))
      self._param_sock = sock
      self._param_sock_crc = self._crc
    lane_crc = self._param_sock_crc
    try:
      # A digest-rejected notice from a prior corrupt fetch rides the
      # retry, so the learner's publish_digest_rejected ledger sees
      # the fleet-side refusal without a dedicated side channel.
      # Independent of lane CRC: digests ship (and verify) whenever
      # the server is v7 — which is the only way _digest_nack gets
      # set — and the lane's parser reads the notice regardless of
      # its own trailer negotiation (a wire_crc=False server must not
      # be blind to fleet-side refusals).
      if self._digest_nack is not None:
        req = ('get_params', {'digest_rejected': self._digest_nack})
      else:
        req = ('get_params',)
      self._digest_nack = None
      _send_msg(self._param_sock, req, crc=lane_crc)
      crc_ctx = _CrcContext() if lane_crc else None
      reply = _recv_msg(self._param_sock, crc_ctx=crc_ctx)
      if reply is not None and crc_ctx is not None and not crc_ctx.ok:
        self._close_param_sock()
        raise ConnectionError(
            f'param blob failed its CRC trailer (computed '
            f'{crc_ctx.computed:08x}, wire {crc_ctx.wire:08x}) — '
            'wire corruption; refetching on a fresh subscriber')
    except socket.timeout as e:
      self._close_param_sock()
      raise ConnectionError(
          f'param lane silent past the {self._io_timeout}s I/O '
          'deadline') from e
    except (ValueError, struct.error, pickle.UnpicklingError,
            EOFError) as e:
      self._close_param_sock()
      raise ProtocolError(
          f'unparseable param-lane reply ({e!r}) — likely a '
          f'protocol-version skew (this client speaks '
          f'v{PROTOCOL_VERSION}); upgrade both roles together') from e
    except OSError:
      self._close_param_sock()
      raise
    if reply is None:
      self._close_param_sock()
      raise ConnectionError('learner closed the param lane')
    if reply[0] == 'bye':
      # Graceful lane shutdown (round 11): a clean end-of-training
      # answer instead of a raw EOF the client must diagnose.
      self._close_param_sock()
      raise LearnerShutdown('learner finished training (param lane)')
    if reply[0] == 'error':
      raise RuntimeError(f'learner rejected param fetch: {reply[1]}')
    return self._decode_params(reply)

  def _close_param_sock(self):
    if self._param_sock is not None:
      try:
        self._param_sock.close()
      except OSError:
        pass
      self._param_sock = None

  def note_install(self, version: int):
    """Record a params install (update_params completed actor-side);
    the event piggybacks on the NEXT traced unroll frame ('pi'
    notice) so the learner's traces.jsonl carries the
    publish→installed-at-actor hop without a dedicated side channel.
    Only the latest install is kept — the hop of interest is the
    freshest version's propagation."""
    self._pending_install = [int(version), round(time.time(), 6)]

  def send_unroll(self, unroll,
                  params_version: Optional[int] = None,
                  trace: Optional[Dict] = None) -> int:
    """Ship one ActorOutput; returns the learner's params version.
    Uses the out-of-band frame: the unroll's frame stacks ARE the
    message, so they go raw instead of through the pickler.

    `params_version` (when known) rides the frame so a learner running
    a staleness window (--max_unroll_staleness) can judge admission. A
    ('stale', current) reply means the unroll was REFUSED benignly:
    counted on `stale_rejections`, and the returned (newer) version
    makes the caller's refetch-on-newer path fire — the same contract
    as an ack, minus the landed unroll.

    When this client handshook with a v6 learner, the SESSION EPOCH
    stamps the frame too (4th element, ignored by old servers): a
    learner incarnation this unroll does not belong to refuses it
    with 'stale_epoch' → SessionEpochMismatch (ConnectionError — the
    reconnect/re-handshake path is the response).

    `trace` (v8, when tracing negotiated): the unroll's trace context
    — stamped HOP_SEND here and shipped as the 5th frame element so
    the learner completes the span. A pending params-install notice
    rides it ('pi'); on a refusal/resend the SAME context ships again
    (the duplicate hop stamps tell the report a resend happened)."""
    if trace is not None and self.trace_ok:
      telemetry.stamp(trace, telemetry.HOP_SEND)
      if self._pending_install is not None:
        trace['pi'] = self._pending_install
        self._pending_install = None
      msg = ('unroll', unroll,
             None if params_version is None else int(params_version),
             None if self.session_epoch is None
             else int(self.session_epoch),
             trace)
    elif self.session_epoch is not None:
      msg = ('unroll', unroll,
             None if params_version is None else int(params_version),
             int(self.session_epoch))
    elif params_version is None:
      msg = ('unroll', unroll)
    else:
      msg = ('unroll', unroll, int(params_version))
    reply = self._rpc(msg, oob=True)
    if reply[0] == 'stale':
      self.stale_rejections += 1
    return reply[1]

  def fetch_stats(self) -> Dict:
    """The learner's on-demand telemetry snapshot (v8 'stats' request
    on the trajectory lane): {'registry': <unified metrics-registry
    snapshot>, 'ingest': <ingest server stats>}. Raises like any rpc
    against a dead/old learner (old servers answer 'error' → the
    RuntimeError path)."""
    reply = self._rpc(('stats',))
    if reply[0] != 'stats':
      raise ProtocolError(f'expected stats, got {reply[0]!r}')
    return reply[1]

  def supports_infer(self) -> bool:
    """True when the handshaken server advertised protocol >= 10 —
    the routed-inference capability gate (routing.py skips pre-v10
    replicas instead of burning a request on the 'error' reply)."""
    return int(self.server_info.get('protocol') or 0) >= 10

  def remote_infer(self, payload: dict) -> Tuple[dict, dict]:
    """One routed inference batch (v10): ship `payload` (the
    InferenceServer.serve_remote dict — batch-leading numpy arrays)
    out-of-band, return (result dict, notice dict). The notice
    carries 'draining' — routing.py drains this replica's share when
    it flips. Raises RuntimeError against a server with no serving
    attached (or a pre-v10 server: 'error', unknown kind)."""
    reply = self._rpc(('infer', payload), oob=True)
    if reply[0] == 'error':
      raise RuntimeError(f'routed inference refused: {reply[1]}')
    if reply[0] != 'infer_ok':
      raise ProtocolError(f'expected infer_ok, got {reply[0]!r}')
    notice = reply[2] if len(reply) > 2 and isinstance(reply[2], dict) \
        else {}
    return reply[1], notice

  def send_leave(self) -> bool:
    """Announce a DELIBERATE exit (v9 drain): the learner records
    host_left(reason='drain') instead of 'lost' when this connection
    unwinds. Best-effort by design — True when the learner
    acknowledged, False against an old server (('error', unknown
    kind) → RuntimeError) or a dead connection; the caller closes
    and exits either way, never gated on the announcement."""
    try:
      reply = self._rpc(('leave', {}))
    except (RuntimeError, OSError, LearnerShutdown):
      return False
    return reply[0] == 'bye_ack'

  def close(self):
    self._close_param_sock()
    try:
      self._sock.close()
    except OSError:
      pass


def run_remote_actor(config, learner_address: str, task: int = 0,
                     stop_after_unrolls: Optional[int] = None,
                     platform: Optional[str] = 'cpu',
                     connect_timeout_secs: float = 120.0,
                     reconnect_secs: Optional[float] = None) -> int:
  """Actor-only host main loop (reference --job_name=actor --task=N).

  Builds a CPU inference server + actor fleet against params fetched
  from the learner, pumps unrolls to the learner's ingest server, and
  refreshes params whenever an ack reports a newer version. Returns the
  number of unrolls shipped. Runs until the learner closes the
  connection (normal end of training) or `stop_after_unrolls`.

  Args:
    config: the SAME Config the learner runs with (env/model knobs must
      agree — the reference shares one flag set across jobs too).
    learner_address: host:port of the learner's ingest server.
    task: this actor host's index; offsets env seeds so hosts explore
      independently (reference --task).
    stop_after_unrolls: optional unroll budget (tests).
    platform: force this jax platform BEFORE first jax use ('cpu' for
      actor hosts — they have no accelerator; None = leave as-is).
    reconnect_secs: elasticity (defaults to
      config.actor_reconnect_secs): when > 0 and the connection drops,
      keep retrying the learner for this many seconds — the fleet
      pauses on buffer backpressure meanwhile — then resume feeding
      with freshly fetched params. This is how actor hosts survive a
      learner restart-from-checkpoint (SURVEY §5.3 is greenfield; the
      reference's actors just die). 0 = exit on disconnect.
      Delivery is at-least-once: an unroll whose ack was lost in the
      drop is resent on the new connection — a duplicate trajectory at
      the learner, harmless to the off-policy math (same class as any
      stale in-flight unroll).
  """
  if platform:
    import jax
    jax.config.update('jax_platforms', platform)

  from scalable_agent_tpu import config as config_lib
  from scalable_agent_tpu import driver as driver_lib
  from scalable_agent_tpu.envs import factory
  from scalable_agent_tpu.runtime.inference import InferenceServer

  if reconnect_secs is None:
    reconnect_secs = getattr(config, 'actor_reconnect_secs', 0.0)
  for warning in config_lib.validate_transport(config):
    log.warning('%s', warning)
  for warning in config_lib.validate_integrity(config):
    log.warning('%s', warning)
  # Round 15: the probation cool-down vs idle-reaping cross-link (the
  # CRC probation sleep happens on THIS host's pump).
  for warning in config_lib.validate_controller(config):
    log.warning('%s', warning)
  # Client-side I/O deadline: the idle window doubles as "how long do
  # I wait on a silent learner" — symmetric with the server's reaping
  # of silent clients. Busy keepalives keep a backpressured-but-alive
  # learner inside it.
  io_timeout = getattr(config, 'remote_conn_idle_timeout_secs', 0.0)
  wire_crc = bool(getattr(config, 'wire_crc', True))
  levels = factory.level_names(config)
  spec0 = factory.make_env_spec(config, levels[0], seed=1)
  agent = driver_lib.build_agent(config, spec0.num_actions,
                                 num_tasks=len(levels))

  contract = trajectory_contract(config, agent, spec0.num_actions)
  # v9 membership identity: stable for THIS host process's lifetime
  # (reconnects keep it — a reconnect is a non-event in the learner's
  # ledger), unique across hosts and across restarts of the same task
  # slot (the pid) — a replacement host for the same task is a fresh
  # join, which is exactly what the elastic storm asserts.
  host_id = f'{socket.gethostname()}:{os.getpid()}:task{task}'
  client = RemoteActorClient(learner_address,
                             connect_timeout_secs=connect_timeout_secs,
                             io_timeout_secs=io_timeout,
                             wire_crc=wire_crc)
  unrolls_sent = 0
  # SIGTERM drain (round 20, riding the PR 6 quiesce idiom): the
  # handler only flips an event — the pump notices at its next wake,
  # quiesces the fleet, ANNOUNCES the departure ('leave' → the
  # learner records host_left(reason='drain') instead of 'lost') and
  # exits cleanly. Registered best-effort: under a non-main thread
  # (tests drive this function directly) signal.signal raises
  # ValueError and the drain stays externally triggerable only.
  drain = threading.Event()

  def _on_sigterm(signum, frame):
    del signum, frame
    log.warning('remote actor task=%d received SIGTERM — draining '
                '(quiesce fleet, announce leave, exit)', task)
    drain.set()

  try:
    signal.signal(signal.SIGTERM, _on_sigterm)
  except ValueError:
    pass  # not the main thread: no signal-driven drain
  # Integrity ledger across reconnects (client objects are replaced):
  # CRC refusals of our unrolls (with the round-15 probation rung),
  # digest-refused publishes, and whether this host took itself out
  # of the fleet.
  probation = CrcProbation(
      cooldown_secs=getattr(config, 'fleet_probation_secs', 30.0))
  digest_rejections = 0
  self_quarantined = False
  try:
    # The hello reply IS a cached params frame, so the STARTUP
    # handshake can meet a corrupt publish exactly like a mid-run
    # refetch — and must get the same bounded-backoff retries (the
    # corrupt blob is superseded at the next publish cadence), not a
    # fleet-shrinking crash.
    backoff = Backoff(base=0.3, cap=3.0)
    for attempt in range(5):
      try:
        version, params = client.handshake(contract, host=host_id)
        break
      except LearnerShutdown:
        # Connected just as training ended: a clean no-op, not a
        # crash.
        log.info('learner already finished training; remote actor '
                 'exiting')
        return 0
      except ParamsCorrupt as e:
        digest_rejections += 1
        log.error('remote actor task=%d: handshake params failed '
                  'their digest (%s) — attempt %d/5', task, e,
                  attempt + 1)
        if attempt == 4:
          raise
        backoff.sleep()
    known_epoch = client.session_epoch  # None against a v5 learner
    # Heartbeat cadence is the SERVER's call (negotiated via its
    # hello-reply info dict): 0 / absent (v5 learner) = no pings.
    heartbeat_secs = float(
        client.server_info.get('heartbeat_secs') or 0.0)
    log.info('remote actor task=%d got params v%d (epoch=%s, '
             'heartbeat=%.1fs)', task, version, known_epoch,
             heartbeat_secs)

    # Seed space DISJOINT from the learner hosts' (driver.train uses
    # process_index * max(num_actors, 1000) for env streams and
    # config.seed + 1000/2000 + base for sampling): a mixed topology
    # (local fleet + remote hosts) must not run bit-identical RNG
    # streams in the same training batch.
    seed_base = _REMOTE_SEED_SPACE + task * max(config.num_actors, 1000)
    server = InferenceServer(agent, params, config,
                             seed=config.seed + seed_base,
                             fleet_size=config.num_actors)
    server.warmup(spec0.obs_spec, max_size=config.num_actors)
    buffer = ring_buffer.TrajectoryBuffer(
        max(2 * config.num_actors, 2))
    # Trace-span stamping (round 13, v8): this host stamps HOP_DONE on
    # each completed unroll with the behaviour params version it acted
    # with (`version` is the pump's live binding — reads see every
    # refresh); the pump ships the context on the wire and the learner
    # completes the span. Negotiated: against a non-tracing/older
    # learner the pump pops the tags and drops them.
    if getattr(config, 'telemetry_trace', True):
      telemetry.configure_actor_tracing(version_fn=lambda: version,
                                        epoch=known_epoch)
    client.note_install(version)  # the handshake install IS the first
    fleet = driver_lib.make_fleet(
        config, agent, server.policy, buffer, levels,
        seed_base=seed_base, level_offset=task * config.num_actors,
        initial_state_fn=server.initial_core_state)
    fleet.start()

    def reconnect():
      """New client + fresh params after a drop; False = gave up.

      Retries the WHOLE connect+fetch cycle until the deadline: a
      connection that resets right after connecting (learner mid-
      restart, listener backlog races) must not end the actor."""
      nonlocal client, version, known_epoch, heartbeat_secs
      client.close()
      deadline = time.monotonic() + reconnect_secs
      # Jittered backoff between whole connect+handshake cycles: the
      # fleet must not re-handshake against a restarting learner in
      # lockstep (the constructor's connect loop jitters its own
      # retries; this covers handshake-level failures).
      backoff = Backoff(base=0.2, cap=5.0)
      while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          log.info('remote actor task=%d gave up reconnecting', task)
          return False
        try:
          new_client = RemoteActorClient(learner_address,
                                         connect_timeout_secs=remaining,
                                         io_timeout_secs=io_timeout,
                                         wire_crc=wire_crc)
        except ConnectionError:
          continue  # connect window exhausted → loop exits above
        try:
          # The prior epoch rides the hello: a RESTARTED learner (new
          # epoch) counts this as a fleet re-attach and times it.
          v, new_params = new_client.handshake(contract,
                                               prior_epoch=known_epoch,
                                               host=host_id)
        except ContractMismatch:
          # The restarted learner runs an INCOMPATIBLE config: retrying
          # cannot succeed — surface it instead of burning the window.
          new_client.close()
          raise
        except (OSError, RuntimeError):
          new_client.close()
          backoff.sleep()
          continue
        client = new_client
        version = v
        if (known_epoch is not None
            and new_client.session_epoch != known_epoch):
          log.warning(
              'remote actor task=%d RE-ATTACHED to a restarted '
              'learner (epoch %s -> %s); params refreshed to v%d',
              task, known_epoch, new_client.session_epoch, version)
        known_epoch = new_client.session_epoch
        heartbeat_secs = float(
            new_client.server_info.get('heartbeat_secs') or 0.0)
        server.update_params(new_params)
        new_client.note_install(v)
        if getattr(config, 'telemetry_trace', True):
          # Fresh epoch on every (re)handshake: spans must name the
          # learner incarnation their unrolls actually fed.
          telemetry.configure_actor_tracing(
              version_fn=lambda: version, epoch=known_epoch)
        log.info('remote actor task=%d reconnected, params v%d',
                 task, version)
        return True

    elastic = bool(reconnect_secs) and reconnect_secs > 0

    def resume_after_drop():
      """True to keep going after a dropped connection (crash path);
      False = give up and exit."""
      if elastic and reconnect():
        return True
      log.info('learner connection closed; remote actor exiting')
      return False

    def refresh_params():
      """Fetch + install the current snapshot (version-gated on the
      server side against redundant copies).

      v7 integrity: a snapshot failing its content digest is NOT
      installed — the inference arena keeps the prior params. Retried
      on backoff a bounded number of times (the corrupt blob is
      CACHED learner-side, so it stays corrupt until the next
      publish); giving up keeps training on the old snapshot and the
      next ack's newer version triggers the refetch of a clean one.
      The rejection itself is reported to the learner on the retry's
      get_params (publish_digest_rejected)."""
      nonlocal version, params, digest_rejections
      backoff = Backoff(base=0.2, cap=2.0)
      for attempt in range(3):
        try:
          v, p = client.fetch_params()
        except ParamsCorrupt as e:
          digest_rejections += 1
          log.error('remote actor task=%d: %s (attempt %d/3)', task,
                    e, attempt + 1)
          if attempt == 2:
            log.error(
                'remote actor task=%d: giving up on params v%s — '
                'keeping v%d; the next publish will be refetched',
                task, e.version, version)
            return
          backoff.sleep()
          continue
        version, params = v, p
        server.update_params(params, version=version)
        # The install event (the publish→installed-at-actor hop)
        # piggybacks on the next traced unroll frame.
        client.note_install(version)
        log.info('remote actor task=%d refreshed params to v%d',
                 task, version)
        return

    try:
      unroll = None  # a drop mid-send must not lose the unroll
      unroll_trace = None  # its trace context rides every (re)send
      last_io = time.monotonic()
      while (not drain.is_set() and
             (stop_after_unrolls is None or
              unrolls_sent < stop_after_unrolls)):
        if unroll is None:
          probation.next_unroll()
          try:
            # With heartbeats negotiated, wake often enough to ping an
            # idle trajectory lane inside the learner's reaping window.
            get_timeout = (min(10.0, heartbeat_secs)
                           if heartbeat_secs > 0 else 10.0)
            unroll = buffer.get(timeout=get_timeout)
            unroll_trace = telemetry.pop_unroll(unroll)
          except TimeoutError:
            fleet.check_health(stall_timeout_secs=300.0)
            errors = fleet.errors()
            if errors:
              raise errors[0]
            if (heartbeat_secs > 0 and
                time.monotonic() - last_io >= heartbeat_secs):
              # Idle heartbeat: keeps the conn out of the reaper's
              # window AND learns about publishes while quiet (the
              # pong carries the current version).
              try:
                pong_version = client.ping()
                last_io = time.monotonic()
                if pong_version > version:
                  refresh_params()
              except OSError:
                if not resume_after_drop():
                  break
                last_io = time.monotonic()
            continue
        try:
          # The current params version rides along so a staleness-
          # windowed learner can judge admission; a 'stale' refusal
          # still returns the newer version, so the refetch below
          # fires and the NEXT unroll ships fresh.
          ack_version = client.send_unroll(unroll,
                                           params_version=version,
                                           trace=unroll_trace)
        except UnrollCorrupt as e:
          # The learner's CRC refused our frame. Once is wire noise:
          # re-send the SAME unroll (at-least-once, like any lost
          # ack). Twice for the same unroll means the corruption is
          # on THIS host's path (NIC/RAM — the learner verified
          # against the trailer WE computed). Round 15: before the
          # terminal self-quarantine, ONE probation rung — cool down,
          # re-send the same unroll as a single probe, and only
          # quarantine on repeat failure (docs/RUNBOOK.md §9) — so a
          # transient (an overheated NIC, a since-replaced DIMM)
          # doesn't cost the fleet this host forever.
          last_io = time.monotonic()
          verdict = probation.on_refusal()
          if verdict == CrcProbation.QUARANTINE:
            self_quarantined = True
            log.error(
                'remote actor task=%d SELF-QUARANTINED: the same '
                'unroll failed the learner CRC twice (%s) — suspect '
                'NIC/memory on this host; exiting the fleet', task, e)
            break
          if verdict == CrcProbation.PROBE:
            log.error(
                'remote actor task=%d: CRC PROBATION — the same '
                'unroll failed the learner CRC twice (%s); cooling '
                'down %.1fs then sending ONE probe (repeat failure '
                'quarantines this host)', task, e,
                probation.cooldown_secs)
            # Cool down WITHOUT going silent: a cool-down longer than
            # the learner's idle window would otherwise get this conn
            # reaped as half-open mid-probation — ping at the
            # heartbeat cadence (best-effort; a reap/drop surfaces on
            # the probe send, which owns the reconnect path).
            cool_end = time.monotonic() + probation.cooldown_secs
            while True:
              remaining = cool_end - time.monotonic()
              if remaining <= 0:
                break
              time.sleep(min(remaining, heartbeat_secs)
                         if heartbeat_secs > 0 else remaining)
              if heartbeat_secs > 0 and \
                 time.monotonic() < cool_end:
                try:
                  client.ping()
                except OSError:
                  break  # dropped mid-cool-down: probe send handles it
            last_io = time.monotonic()
            continue
          log.warning('remote actor task=%d: unroll failed the '
                      'learner CRC (%s); re-sending once', task, e)
          continue
        except OSError:
          # OSError, not just ConnectionError: a blackholed learner
          # host surfaces as ETIMEDOUT — or the round-11 client-side
          # I/O deadline fired on pure silence — and both must
          # trigger the reconnect window. SessionEpochMismatch (the
          # learner restarted under us) rides the same path: the
          # reconnect IS the re-handshake.
          if resume_after_drop():
            last_io = time.monotonic()
            continue  # resend the SAME unroll on the new connection
          break
        last_io = time.monotonic()
        if probation.on_ack():
          log.warning(
              'remote actor task=%d: CRC probation probe ACCEPTED — '
              'host recovered; staying in the fleet', task)
        unroll = None
        unroll_trace = None
        unrolls_sent += 1
        if ack_version > version:
          try:
            # Version-gated on the server side: a refetch racing the
            # publish cadence can hand back the version already being
            # served — the whole-tree copy is skipped for it (stats:
            # publishes_skipped).
            refresh_params()
            last_io = time.monotonic()
          except OSError:
            # Dropped between ack and refresh; reconnect() refetches.
            if not resume_after_drop():
              break
            last_io = time.monotonic()
      if drain.is_set():
        # Quiesce first (no more unrolls can be produced against the
        # announced-gone connection), then tell the learner this is a
        # DELIBERATE exit — best-effort: an old/dead learner just
        # records 'lost' when the socket closes below.
        fleet.stop()
        acked = client.send_leave()
        log.warning('remote actor task=%d drained cleanly after %d '
                    'unroll(s) (leave %s)', task, unrolls_sent,
                    'acked' if acked else 'not acked — old learner?')
    except LearnerShutdown:
      # Clean end of training ('bye'): no reconnect window to burn.
      log.info('learner finished training; remote actor exiting')
    except ring_buffer.Closed:
      log.info('local buffer closed; remote actor exiting')
    finally:
      telemetry.clear_actor_tracing()
      fleet.stop()
      server.close()
  finally:
    client.close()
  log.info('remote actor task=%d shipped %d unrolls', task,
           unrolls_sent)
  if (probation.crc_resends or probation.probations
      or digest_rejections or self_quarantined):
    # Greppable one-liner for harnesses (chaos.py) and operators: the
    # client-side half of the integrity ledger (the learner's stats
    # carry the server-side half).
    log.warning(
        'INTEGRITY_REPORT task=%d crc_resends=%d digest_rejections=%d '
        'crc_probations=%d crc_probation_recoveries=%d '
        'self_quarantined=%s', task, probation.crc_resends,
        digest_rejections, probation.probations, probation.recoveries,
        self_quarantined)
  return unrolls_sent
